"""Kernel tests: flash attention (interpret mode) + ring attention on the
virtual device mesh, both against the XLA oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k_llms_tpu.ops.attention import attention_xla, flash_attention
from k_llms_tpu.ops.ring_attention import ring_attention
from k_llms_tpu.parallel.mesh import make_mesh


def _qkv(seed, B=2, QH=4, KVH=2, S=64, D=16, dtype=jnp.float32):
    rng = jax.random.key(seed)
    q = jax.random.normal(jax.random.fold_in(rng, 1), (B, QH, S, D), dtype)
    k = jax.random.normal(jax.random.fold_in(rng, 2), (B, KVH, S, D), dtype)
    v = jax.random.normal(jax.random.fold_in(rng, 3), (B, KVH, S, D), dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
def test_flash_matches_xla(causal):
    q, k, v = _qkv(0)
    lens = jnp.array([64, 40], jnp.int32)
    mask = (jnp.arange(64)[None, :] < lens[:, None]).astype(jnp.int32)
    ref = attention_xla(q, k, v, causal=causal, key_mask=mask)
    out = flash_attention(
        q, k, v, causal=causal, key_lengths=lens, block_q=16, block_k=16, interpret=True
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_flash_ragged_q_padding():
    q, k, v = _qkv(1)
    out = flash_attention(
        q[:, :, :37], k, v, causal=False, block_q=16, block_k=16, interpret=True
    )
    ref = attention_xla(q[:, :, :37], k, v, causal=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_flash_gqa_head_mapping():
    # QH=8 sharing KVH=2: wrong head mapping would blow the error up
    q, k, v = _qkv(2, QH=8, KVH=2)
    ref = attention_xla(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True, block_q=16, block_k=16, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("ring_size", [2, 4, 8])
def test_ring_attention_exact(causal, ring_size):
    mesh = make_mesh(ring_size, 1)
    # S sharded over the ring: each device holds S/ring_size positions
    q, k, v = _qkv(3, S=64)
    ref = attention_xla(q, k, v, causal=causal)
    out = ring_attention(mesh, q, k, v, seq_axis="data", causal=causal)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def test_ring_attention_memory_layout():
    # one more shape: GQA + batch 1
    mesh = make_mesh(4, 1)
    q, k, v = _qkv(4, B=1, QH=8, KVH=4, S=32, D=8)
    ref = attention_xla(q, k, v, causal=True)
    out = ring_attention(mesh, q, k, v, seq_axis="data", causal=True)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref), rtol=2e-5, atol=2e-5
    )
