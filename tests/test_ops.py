"""Kernel tests: flash attention (interpret mode) + ring attention on the
virtual device mesh, both against the XLA oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k_llms_tpu.ops.attention import attention_xla, flash_attention
from k_llms_tpu.ops.ring_attention import ring_attention
from k_llms_tpu.parallel.mesh import make_mesh


def _qkv(seed, B=2, QH=4, KVH=2, S=64, D=16, dtype=jnp.float32):
    rng = jax.random.key(seed)
    q = jax.random.normal(jax.random.fold_in(rng, 1), (B, QH, S, D), dtype)
    k = jax.random.normal(jax.random.fold_in(rng, 2), (B, KVH, S, D), dtype)
    v = jax.random.normal(jax.random.fold_in(rng, 3), (B, KVH, S, D), dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
def test_flash_matches_xla(causal):
    q, k, v = _qkv(0)
    lens = jnp.array([64, 40], jnp.int32)
    mask = (jnp.arange(64)[None, :] < lens[:, None]).astype(jnp.int32)
    ref = attention_xla(q, k, v, causal=causal, key_mask=mask)
    out = flash_attention(
        q, k, v, causal=causal, key_lengths=lens, block_q=16, block_k=16, interpret=True
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_flash_ragged_q_padding():
    q, k, v = _qkv(1)
    out = flash_attention(
        q[:, :, :37], k, v, causal=False, block_q=16, block_k=16, interpret=True
    )
    ref = attention_xla(q[:, :, :37], k, v, causal=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_flash_gqa_head_mapping():
    # QH=8 sharing KVH=2: wrong head mapping would blow the error up
    q, k, v = _qkv(2, QH=8, KVH=2)
    ref = attention_xla(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True, block_q=16, block_k=16, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("ring_size", [2, 4, 8])
def test_ring_attention_exact(causal, ring_size):
    mesh = make_mesh(ring_size, 1)
    # S sharded over the ring: each device holds S/ring_size positions
    q, k, v = _qkv(3, S=64)
    ref = attention_xla(q, k, v, causal=causal)
    out = ring_attention(mesh, q, k, v, seq_axis="data", causal=causal)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def test_ring_attention_memory_layout():
    # one more shape: GQA + batch 1
    mesh = make_mesh(4, 1)
    q, k, v = _qkv(4, B=1, QH=8, KVH=4, S=32, D=8)
    ref = attention_xla(q, k, v, causal=True)
    out = ring_attention(mesh, q, k, v, seq_axis="data", causal=True)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


# ---------------------------------------------------------------------------
# Pallas shared-prefix decode attention
# ---------------------------------------------------------------------------

def _decode_oracle(q, pk, pv, prompt_lens, scale):
    """Two-phase reference: full softmax over each row's valid prefix keys."""
    B, QH, D = q.shape
    R, P, KVH, _ = pk.shape
    G = QH // KVH
    n_per = B // R
    out = np.zeros((B, QH, D), np.float32)
    m = np.zeros((B, QH), np.float32)
    l = np.zeros((B, QH), np.float32)
    for b in range(B):
        r = b // n_per
        valid = int(prompt_lens[r])
        for h in range(QH):
            kv = h // G
            s = (
                np.asarray(q[b, h], np.float32)
                @ np.asarray(pk[r, :valid, kv], np.float32).T
            ) * scale
            mx = s.max()
            e = np.exp(s - mx)
            m[b, h] = mx
            l[b, h] = e.sum()
            out[b, h] = (e / e.sum()) @ np.asarray(pv[r, :valid, kv], np.float32)
    return out, m, l


@pytest.mark.parametrize("R,n_per,QH,KVH,P", [(1, 8, 4, 2, 32), (4, 2, 8, 2, 64), (2, 4, 4, 4, 160)])
def test_decode_prefix_attention_matches_oracle(R, n_per, QH, KVH, P):
    from k_llms_tpu.ops.attention import decode_prefix_attention

    D = 16
    B = R * n_per
    key = jax.random.key(0)
    kq, kk, kv_, kl = jax.random.split(key, 4)
    q = jax.random.normal(kq, (B, QH, D), jnp.float32)
    pk = jax.random.normal(kk, (R, P, KVH, D), jnp.float32)
    pv = jax.random.normal(kv_, (R, P, KVH, D), jnp.float32)
    # Ragged valid lengths per request (>=1, <= P), not block-aligned.
    lens = jax.random.randint(kl, (R,), 1, P + 1)

    out, m, l = decode_prefix_attention(
        q, pk, pv, lens, sm_scale=0.25, block_k=32, interpret=True
    )
    ref_out, ref_m, ref_l = _decode_oracle(
        np.asarray(q), np.asarray(pk), np.asarray(pv), np.asarray(lens), 0.25
    )
    np.testing.assert_allclose(np.asarray(out), ref_out, rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(m), ref_m, rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(l), ref_l, rtol=2e-5, atol=2e-5)


def test_flash_softcap_and_window_matches_xla():
    """Kernel softcap + sliding-window support against a manually-masked
    XLA reference."""
    from k_llms_tpu.ops.attention import flash_attention

    B, QH, KVH, S, D = 2, 4, 2, 64, 16
    key = jax.random.key(3)
    kq, kk, kv_ = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, QH, S, D), jnp.float32)
    k = jax.random.normal(kk, (B, KVH, S, D), jnp.float32)
    v = jax.random.normal(kv_, (B, KVH, S, D), jnp.float32)
    lens = jnp.array([S, 37], jnp.int32)
    W, CAP, scale = 9, 12.0, 0.3

    def oracle():
        G = QH // KVH
        qg = q.reshape(B, KVH, G, S, D)
        s = jnp.einsum("bhgqd,bhkd->bhgqk", qg, k) * scale
        s = CAP * jnp.tanh(s / CAP)
        rows = jnp.arange(S)[:, None]
        cols = jnp.arange(S)[None, :]
        mask = (cols <= rows) & (cols > rows - W)
        mask = mask[None, None, None] & (cols[None] < lens[:, None, None])[:, None, None]
        s = jnp.where(mask, s, jnp.finfo(jnp.float32).min)
        w = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhgqk,bhkd->bhgqd", w, v).reshape(B, QH, S, D)

    out = flash_attention(
        q, k, v, causal=True, key_lengths=lens, sm_scale=scale,
        softcap=CAP, window=W, block_q=32, block_k=32, interpret=True,
    )
    # Compare only query rows with >=1 valid key (row <= len+W-2): rows whose
    # window misses the valid key range entirely have no defined output (the
    # kernel zeroes them; the XLA oracle spreads a uniform softmax).
    for b in range(B):
        r_valid = min(S, int(lens[b]) + W - 1)
        np.testing.assert_allclose(
            np.asarray(out)[b, :, :r_valid],
            np.asarray(oracle())[b, :, :r_valid],
            rtol=2e-5,
            atol=2e-5,
        )


def test_flash_dynamic_window_traced():
    """The window can be a TRACED scalar (alternating-layer configs pick W per
    scanned layer) without recompiling per value."""
    from k_llms_tpu.ops.attention import NO_WINDOW, flash_attention

    B, QH, KVH, S, D = 1, 2, 2, 32, 8
    key = jax.random.key(5)
    kq, kk, kv_ = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, QH, S, D), jnp.float32)
    k = jax.random.normal(kk, (B, KVH, S, D), jnp.float32)
    v = jax.random.normal(kv_, (B, KVH, S, D), jnp.float32)

    @jax.jit
    def run(w):
        return flash_attention(
            q, k, v, causal=True, window=w, block_q=16, block_k=16, interpret=True
        )

    windowed = run(jnp.int32(4))
    full = run(jnp.int32(NO_WINDOW))
    ref_full = flash_attention(q, k, v, causal=True, block_q=16, block_k=16, interpret=True)
    np.testing.assert_allclose(np.asarray(full), np.asarray(ref_full), rtol=2e-5, atol=2e-5)
    assert not np.allclose(np.asarray(windowed), np.asarray(full))


def test_flash_q_offset_continuation_matches_full():
    """q_offset mode (continuation prefill): the suffix queries of a full
    causal attention must equal running flash on only those queries with
    q_offset = prefix length, against the full key space."""
    q, k, v = _qkv(7)
    P = 24  # prefix length; suffix queries are rows P..S
    full = flash_attention(q, k, v, causal=True, block_q=16, block_k=16, interpret=True)
    suffix = flash_attention(
        q[:, :, P:], k, v, causal=True, q_offset=P, block_q=16, block_k=16,
        interpret=True,
    )
    np.testing.assert_allclose(
        np.asarray(suffix), np.asarray(full[:, :, P:]), rtol=2e-5, atol=2e-5
    )


def test_flash_q_offset_traced_scalar():
    """q_offset as a traced scalar (the engine passes prefix_len dynamically)."""
    q, k, v = _qkv(8)
    P = 17

    @jax.jit
    def run(qs, k, v, off):
        return flash_attention(
            qs, k, v, causal=True, q_offset=off, block_q=16, block_k=16,
            interpret=True,
        )

    suffix = run(q[:, :, P:], k, v, jnp.int32(P))
    full = flash_attention(q, k, v, causal=True, block_q=16, block_k=16, interpret=True)
    np.testing.assert_allclose(
        np.asarray(suffix), np.asarray(full[:, :, P:]), rtol=2e-5, atol=2e-5
    )


def test_flash_q_offset_with_window():
    """Sliding windows are evaluated at absolute (offset) positions."""
    q, k, v = _qkv(9)
    W, P = 20, 16
    full = flash_attention(
        q, k, v, causal=True, window=W, block_q=16, block_k=16, interpret=True
    )
    suffix = flash_attention(
        q[:, :, P:], k, v, causal=True, window=W, q_offset=P, block_q=16,
        block_k=16, interpret=True,
    )
    np.testing.assert_allclose(
        np.asarray(suffix), np.asarray(full[:, :, P:]), rtol=2e-5, atol=2e-5
    )
