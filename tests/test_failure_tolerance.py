"""Graceful-degradation ladder (SURVEY.md §5): failed samples drop out of the
vote instead of failing the request; parse failures degrade to None; support
thresholds relax rather than explode."""

import jax.numpy as jnp
import numpy as np

from k_llms_tpu import KLLMs
from k_llms_tpu.consensus.consolidation import _safe_parse_content
from k_llms_tpu.consensus.similarity import SimilarityScorer
from k_llms_tpu.consensus.consolidation import consolidate_chat_completions
from k_llms_tpu.ops.sampling import sample_logits
from k_llms_tpu.types import ChatCompletion


def _completion(contents):
    return ChatCompletion.model_validate(
        {
            "id": "c",
            "created": 0,
            "model": "m",
            "object": "chat.completion",
            "choices": [
                {
                    "finish_reason": "stop",
                    "index": i,
                    "message": {"role": "assistant", "content": content},
                }
                for i, content in enumerate(contents)
            ],
        }
    )


def test_empty_sample_drops_out_of_vote():
    # sample 3 produced nothing -> it is excluded from consensus but still
    # listed among the original choices
    comp = _completion(["yes", "yes", ""])
    result = consolidate_chat_completions(comp, SimilarityScorer.levenshtein())
    assert result.choices[0].message.content == "yes"
    assert result.likelihoods == {"text": 1.0}  # 2/2 of the valid samples
    assert len(result.choices) == 4


def test_malformed_json_degrades_to_text_wrap():
    assert _safe_parse_content("{broken json") == {"text": "{broken json"}
    assert _safe_parse_content(None) == {"text": None}


def test_mixed_json_and_garbage_still_consolidates():
    comp = _completion(['{"a": 1}', '{"a": 1}', "total garbage"])
    result = consolidate_chat_completions(comp, SimilarityScorer.levenshtein())
    # structures disagree ({"a":...} vs {"text":...}) but the request succeeds
    assert result.choices[0].message.content is not None
    assert result.likelihoods is not None


def test_nonfinite_logits_sanitized():
    logits = jnp.array(
        [[1.0, 2.0, 3.0, 4.0], [jnp.nan, jnp.nan, jnp.nan, jnp.nan], [1.0, jnp.inf, 0.0, 0.0]],
        jnp.float32,
    )
    import jax

    toks, lps = sample_logits(logits, jax.random.key(0), temperature=1.0)
    assert toks.shape == (3,)
    assert np.isfinite(np.asarray(lps)).all()
    # greedy on the row with a single +inf picks it deterministically... +inf is
    # masked to -inf, so argmax falls to the best finite value
    toks0, _ = sample_logits(logits, jax.random.key(0), temperature=0.0)
    assert int(toks0[0]) == 3
    assert int(toks0[2]) == 0


def test_single_sample_failure_does_not_fail_request():
    # a responder that errors for one sample: model empty text for it
    client = KLLMs(backend="fake", responses=[["ok answer", "", "ok answer"]])
    resp = client.chat.completions.create(
        messages=[{"role": "user", "content": "q"}], model="m", n=3
    )
    assert resp.choices[0].message.content == "ok answer"


def test_list_form_preserves_original_sample_indexes():
    """List-of-completions form: a sample with EMPTY choices is skipped, but
    the surviving samples keep their ORIGINAL positions in choice.index —
    compacting would silently misattribute outputs to the wrong sample."""

    def one(content):
        return ChatCompletion.model_validate(
            {
                "id": "c",
                "created": 0,
                "model": "m",
                "object": "chat.completion",
                "choices": [] if content is None else [
                    {
                        "finish_reason": "stop",
                        "index": 0,
                        "message": {"role": "assistant", "content": content},
                    }
                ],
            }
        )

    comps = [one('{"a": 1}'), one(None), one('{"a": 1}')]
    result = consolidate_chat_completions(comps, SimilarityScorer.levenshtein())
    assert [c.index for c in result.choices] == [0, 1, 3]
