"""kllms-check: per-rule fixture tests, CLI contract, and the tier-1 gate.

Every rule is pinned twice: a ``bad`` fixture that must produce the rule's
findings and a ``good`` fixture that must stay silent (a rule that cannot
fire protects nothing; a rule that fires on idiomatic code gets suppressed
into noise). The package-wide run is the tentpole gate: the real serving
stack must be lint-clean on every PR, via the same ``--check`` entry point CI
uses.
"""

import json
import subprocess
import sys
from pathlib import Path

import pytest

from k_llms_tpu.analysis.framework import (
    DEFAULT_CONFIG,
    RULES,
    _ensure_rules_loaded,
    load_project,
    run_rules,
    unsuppressed,
)

REPO = Path(__file__).resolve().parent.parent
FIXTURES = Path(__file__).resolve().parent / "fixtures" / "lint"

EXPECTED_RULES = {
    "lock-order",
    "dispatch-under-lock",
    "host-sync-hot-path",
    "jit-recompile-hygiene",
    "failpoint-coverage",
    "counter-hygiene",
    "wire-error-contract",
    "guarded-by",
    "guarded-by-unguarded",
    "guarded-by-escape",
    "guarded-by-annotation",
}

GUARDED_BY_FAMILY = (
    "guarded-by",
    "guarded-by-unguarded",
    "guarded-by-escape",
    "guarded-by-annotation",
)


def run_fixture(rule_id, rel, config=None, readme=None, test_sources=None):
    """Run one rule over one fixture subtree as a standalone project."""
    cfg = dict(DEFAULT_CONFIG)
    cfg.update(config or {})
    project = load_project(
        FIXTURES, paths=[FIXTURES / rel], config=cfg, with_context=False
    )
    assert project.files, f"fixture {rel} matched no files"
    assert all(f.parse_error is None for f in project.files)
    project.readme = readme
    project.test_sources = dict(test_sources or {})
    return run_rules(project, [rule_id])


def messages(findings):
    return [f.message for f in findings if not f.suppressed]


# ---------------------------------------------------------------------------
# rule registry
# ---------------------------------------------------------------------------


def test_registry_has_all_project_rules_with_metadata():
    _ensure_rules_loaded()
    assert EXPECTED_RULES <= set(RULES)
    assert len(RULES) >= 6
    for rid, cls in RULES.items():
        rule = cls()
        assert rule.id == rid
        assert rule.summary and rule.invariant and rule.subsystem, rid


# ---------------------------------------------------------------------------
# per-rule fixtures
# ---------------------------------------------------------------------------


def test_lock_order_bad_fixture_finds_cycle_and_raw_lock():
    msgs = messages(run_fixture("lock-order", "lock-order/bad.py"))
    assert len(msgs) == 2
    cycle = [m for m in msgs if "lock-order cycle" in m]
    assert len(cycle) == 1
    assert "fix.a" in cycle[0] and "fix.b" in cycle[0]
    raw = [m for m in msgs if "threading.Lock()" in m]
    assert len(raw) == 1 and "bad.RAW" in raw[0]


def test_lock_order_good_fixture_is_clean():
    assert messages(run_fixture("lock-order", "lock-order/good.py")) == []


def test_dispatch_under_lock_bad_fixture():
    msgs = messages(
        run_fixture("dispatch-under-lock", "dispatch-under-lock/bad.py")
    )
    assert len(msgs) == 2
    assert all("fix.guard" in m and "allow_dispatch" in m for m in msgs)


def test_dispatch_under_lock_good_fixture_is_clean():
    assert (
        messages(run_fixture("dispatch-under-lock", "dispatch-under-lock/good.py"))
        == []
    )


HOT_CFG = {
    "host-sync-hot-path": {
        "hot_functions": [
            "decode_step", "paged_*", "grammar_mask_logits", "grammar_advance",
        ]
    }
}


def test_host_sync_bad_fixture_flags_jitted_and_hot_syncs():
    msgs = messages(
        run_fixture("host-sync-hot-path", "host-sync-hot-path/bad.py", HOT_CFG)
    )
    assert len(msgs) == 5
    assert sum("a jitted body" in m for m in msgs) == 1
    assert sum("a configured hot function" in m for m in msgs) == 4
    assert any("*.item" in m for m in msgs)
    assert any("np.asarray" in m for m in msgs)
    assert any("jax.device_get" in m for m in msgs)
    # The glob-matched paged function is flagged, pinning the pattern
    # matching that the real `paged_decode_attention_*` config relies on.
    assert any("*.tolist" in m and "paged_decode_attention_ref" in m for m in msgs)


def test_host_sync_good_fixture_is_clean():
    assert (
        messages(
            run_fixture(
                "host-sync-hot-path", "host-sync-hot-path/good.py", HOT_CFG
            )
        )
        == []
    )


def test_jit_recompile_bad_fixture():
    msgs = messages(
        run_fixture("jit-recompile-hygiene", "jit-recompile-hygiene/bad.py")
    )
    assert len(msgs) == 1
    assert "recompiles on every call" in msgs[0]


JIT_CFG = {
    "jit-recompile-hygiene": {
        "builder_functions": ["_get_decode_loop", "_grammar_programs"]
    }
}


def test_jit_recompile_good_fixture_sanctions_every_memoized_pattern():
    assert (
        messages(
            run_fixture(
                "jit-recompile-hygiene", "jit-recompile-hygiene/good.py", JIT_CFG
            )
        )
        == []
    )


def test_jit_recompile_builder_config_is_load_bearing():
    # Without the configured builder_functions entries the same fixture must
    # fire on every config-sanctioned builder — proving the pyproject
    # `_get_decode_loop` / `_grammar_programs` entries suppress real findings.
    msgs = messages(
        run_fixture("jit-recompile-hygiene", "jit-recompile-hygiene/good.py")
    )
    assert len(msgs) == 2
    assert any("_get_decode_loop" in m for m in msgs)
    assert any("_grammar_programs" in m for m in msgs)


BAD_FP_TESTS = {
    "tests/test_x.py": 'spec = FailSpec(action="error")\nfire("engine.launch")\n'
}
BAD_FP_README = "| `engine.launch` | engine | batched launch |\n"


def test_failpoint_coverage_bad_fixture():
    msgs = messages(
        run_fixture(
            "failpoint-coverage",
            "failpoint-coverage/bad",
            readme=BAD_FP_README,
            test_sources=BAD_FP_TESTS,
        )
    )
    assert len(msgs) == 6
    assert sum("string literal" in m for m in msgs) == 1
    assert sum("'engine.typo' is not registered" in m for m in msgs) == 1
    assert sum("dead registry entry" in m for m in msgs) == 1
    assert sum("exercised by no test" in m for m in msgs) == 1
    assert sum("README registry-table" in m for m in msgs) == 1
    assert sum("'hang' is never" in m for m in msgs) == 1


GOOD_FP_TESTS = {
    "tests/test_x.py": (
        'FailSpec(action="error")\nFailSpec(action="hang")\n'
        'fire("engine.launch")\nfire("engine.pages")\n'
    )
}
GOOD_FP_README = (
    "| `engine.launch` | engine | batched launch |\n"
    "| `engine.pages` | engine | slot page release |\n"
)


def test_failpoint_coverage_good_fixture_is_clean():
    assert (
        messages(
            run_fixture(
                "failpoint-coverage",
                "failpoint-coverage/good",
                readme=GOOD_FP_README,
                test_sources=GOOD_FP_TESTS,
            )
        )
        == []
    )


def test_counter_hygiene_bad_fixture():
    msgs = messages(run_fixture("counter-hygiene", "counter-hygiene/bad"))
    assert len(msgs) == 8
    # Counter group findings.
    assert sum("counter group" in m and "without declared=" in m for m in msgs) == 1
    assert sum("'a.typo'" in m for m in msgs) == 1
    assert sum("'stale.name'" in m and "never" in m for m in msgs) == 1
    assert sum("not surfaced" in m and "ALPHA_EVENTS" in m for m in msgs) == 1
    # Histogram group findings mirror the counter contract.
    assert sum("histogram group" in m and "without declared=" in m for m in msgs) == 1
    assert sum("'h.typo'" in m for m in msgs) == 1
    assert sum("'stale.hist'" in m and "never observed" in m for m in msgs) == 1
    assert sum("not surfaced" in m and "GAMMA_HIST" in m for m in msgs) == 1


def test_counter_hygiene_good_fixture_is_clean():
    assert messages(run_fixture("counter-hygiene", "counter-hygiene/good")) == []


def test_wire_error_contract_bad_fixture():
    msgs = messages(
        run_fixture("wire-error-contract", "wire-error-contract/bad.py")
    )
    assert len(msgs) == 3
    assert sum("BadError" in m and "type, status_code" in m for m in msgs) == 1
    assert sum("PartialError" in m and "status_code" in m for m in msgs) == 1
    assert sum("WorseError.as_wire" in m for m in msgs) == 1


def test_wire_error_contract_good_fixture_is_clean():
    assert (
        messages(run_fixture("wire-error-contract", "wire-error-contract/good.py"))
        == []
    )


def test_guarded_by_good_fixtures_are_clean():
    for rid in GUARDED_BY_FAMILY:
        assert messages(run_fixture(rid, "guarded-by/good")) == [], rid


def test_guarded_by_bad_fixture_flags_minority_declared_and_tie():
    msgs = messages(run_fixture("guarded-by", "guarded-by/bad"))
    assert len(msgs) == 3
    declared = [m for m in msgs if "declared via # kllms: guarded-by" in m]
    assert len(declared) == 1
    assert "Annotated._items" in declared[0] and "Annotated.add" in declared[0]
    inferred = [m for m in msgs if "inferred: held at 2 of 3 access sites" in m]
    assert len(inferred) == 1
    assert "Stats._counts" in inferred[0] and "read in Stats.peek" in inferred[0]
    tie = [m for m in msgs if "cannot infer a guard" in m]
    assert len(tie) == 1
    assert "'fix.torn_a'" in tie[0] and "'fix.torn_b'" in tie[0]
    assert "guarded-by[<lock>]" in tie[0]


def test_guarded_by_unguarded_bad_fixture_names_every_writer():
    msgs = messages(run_fixture("guarded-by-unguarded", "guarded-by/bad"))
    assert len(msgs) == 1
    assert "Gauge.level is written from 2 methods" in msgs[0]
    assert "Gauge.down, Gauge.up" in msgs[0]
    assert "kllms: unguarded" in msgs[0]


def test_guarded_by_unguarded_min_writers_config_is_load_bearing():
    cfg = {"guarded-by": {"min_write_methods": 3}}
    assert messages(run_fixture("guarded-by-unguarded", "guarded-by/bad", cfg)) == []


def test_guarded_by_ignore_pattern_exempts_attribute():
    cfg = {"guarded-by": {"ignore": ["Stats._*"]}}
    assert (
        messages(run_fixture("guarded-by", "guarded-by/bad/inferred.py", cfg)) == []
    )


def test_guarded_by_escape_bad_fixture():
    msgs = messages(run_fixture("guarded-by-escape", "guarded-by/bad"))
    assert len(msgs) == 2
    assert sum("returned raw from Leaky.raw" in m for m in msgs) == 1
    assert (
        sum("passed raw into self._executor.submit" in m for m in msgs) == 1
    )
    assert all("Leaky._ring" in m and "'fix.leaky'" in m for m in msgs)


def test_guarded_by_annotation_bad_fixture_cross_checks_lock_names():
    msgs = messages(run_fixture("guarded-by-annotation", "guarded-by/bad"))
    assert len(msgs) == 2
    unknown = [m for m in msgs if "names no known lock" in m]
    assert len(unknown) == 1
    # The cross-check vocabulary comes from the lock-order extraction: the
    # typo'd name is rejected and the class's canonical names are offered.
    assert "fix.nosuch" in unknown[0]
    assert "canonical names for Annotated: fix.annotated" in unknown[0]
    assert sum("needs a reason" in m for m in msgs) == 1


# ---------------------------------------------------------------------------
# suppression machinery + parse errors
# ---------------------------------------------------------------------------


def test_inline_suppressions_cover_same_line_and_line_above():
    findings = run_fixture("lock-order", "suppression/bad.py")
    assert len(findings) == 3
    silenced = [f for f in findings if f.suppressed]
    loud = [f for f in findings if not f.suppressed]
    assert len(silenced) == 2 and len(loud) == 1
    assert all(f.suppress_reason for f in silenced)
    assert "LOUD" in loud[0].message


def test_syntax_error_becomes_parse_error_finding(tmp_path):
    bad = tmp_path / "broken.py"
    bad.write_text("def nope(:\n", encoding="utf-8")
    project = load_project(
        tmp_path, paths=[bad], config=dict(DEFAULT_CONFIG), with_context=False
    )
    findings = run_rules(project, ["lock-order"])
    assert [f.rule for f in findings] == ["parse-error"]
    assert not findings[0].suppressed


def test_unknown_rule_id_raises():
    project = load_project(
        FIXTURES,
        paths=[FIXTURES / "lock-order" / "good.py"],
        config=dict(DEFAULT_CONFIG),
        with_context=False,
    )
    with pytest.raises(ValueError, match="unknown rule"):
        run_rules(project, ["no-such-rule"])


# ---------------------------------------------------------------------------
# CLI contract + the tier-1 package gate
# ---------------------------------------------------------------------------


def _cli(*args, cwd=REPO):
    return subprocess.run(
        [sys.executable, "-m", "k_llms_tpu.analysis", *args],
        cwd=cwd,
        capture_output=True,
        text=True,
        timeout=120,
    )


@pytest.mark.duration_budget(10)
def test_package_is_lint_clean_via_check_cli():
    """The tentpole gate: `python -m k_llms_tpu.analysis --check` exits 0
    over the real package, with the full rule set enabled."""
    proc = _cli("--check", "--json")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert set(doc) == {"root", "files", "rules", "findings", "ok"}
    assert doc["ok"] is True
    assert doc["findings"] == []
    assert doc["files"] > 50
    assert EXPECTED_RULES <= set(doc["rules"])


def test_cli_exits_one_with_findings_on_bad_fixture():
    proc = _cli(
        "--root",
        str(FIXTURES),
        str(FIXTURES / "lock-order" / "bad.py"),
        "--rule",
        "lock-order",
        "--json",
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["ok"] is False
    assert doc["rules"] == ["lock-order"]
    for f in doc["findings"]:
        assert set(f) == {
            "rule", "file", "line", "message", "suppressed", "suppress_reason",
        }
        assert f["rule"] == "lock-order" and f["line"] > 0


def test_cli_list_rules_and_usage_error():
    proc = _cli("--list-rules")
    assert proc.returncode == 0
    for rid in EXPECTED_RULES:
        assert rid in proc.stdout
    proc = _cli("--rule", "no-such-rule")
    assert proc.returncode == 2
    assert "unknown rule" in proc.stderr


def test_package_lint_in_process_matches_cli():
    """Same gate without the subprocess, so failures show findings inline."""
    project = load_project(REPO)
    findings = unsuppressed(run_rules(project))
    assert not findings, "\n".join(f.format() for f in findings)


# ---------------------------------------------------------------------------
# SARIF output + baseline suppression
# ---------------------------------------------------------------------------


def test_sarif_output_matches_2_1_0_shape():
    """Pin the SARIF 2.1.0 shape CI consumes: schema/version headers, the
    rule metadata as driver rule descriptors, and per-result locations."""
    proc = _cli(
        "--root",
        str(FIXTURES),
        str(FIXTURES / "guarded-by" / "bad"),
        "--rule",
        "guarded-by",
        "--sarif",
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["version"] == "2.1.0"
    assert doc["$schema"].endswith("sarif-schema-2.1.0.json")
    (run,) = doc["runs"]
    driver = run["tool"]["driver"]
    assert driver["name"] == "kllms-check"
    assert [r["id"] for r in driver["rules"]] == ["guarded-by"]
    for r in driver["rules"]:
        assert r["shortDescription"]["text"]
        assert r["fullDescription"]["text"]
    assert run["originalUriBaseIds"]["SRCROOT"]["uri"].startswith("file://")
    assert len(run["results"]) == 3
    for res in run["results"]:
        assert res["ruleId"] == "guarded-by"
        assert res["ruleIndex"] == 0
        assert res["level"] == "error"
        assert res["message"]["text"]
        loc = res["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"].endswith(".py")
        assert loc["artifactLocation"]["uriBaseId"] == "SRCROOT"
        assert loc["region"]["startLine"] >= 1
        assert res["partialFingerprints"]["kllmsFingerprint/v1"]


def test_sarif_and_json_are_mutually_exclusive():
    proc = _cli("--sarif", "--json")
    assert proc.returncode == 2
    assert "mutually exclusive" in proc.stderr


def test_baseline_makes_dirty_tree_pass_but_new_finding_fails(tmp_path):
    bad = str(FIXTURES / "guarded-by" / "bad")
    base = tmp_path / "baseline.json"
    proc = _cli(
        "--root", str(FIXTURES), bad,
        "--rule", "guarded-by",
        "--write-baseline", str(base),
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(base.read_text(encoding="utf-8"))
    assert doc["version"] == 1
    assert len(doc["fingerprints"]) == 3
    # The dirty tree passes against its recorded baseline...
    proc = _cli(
        "--root", str(FIXTURES), bad,
        "--rule", "guarded-by",
        "--check", "--baseline", str(base),
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    # ...while findings NOT in the baseline (here: another family rule over
    # the same tree) still fail the run.
    proc = _cli(
        "--root", str(FIXTURES), bad,
        "--rule", "guarded-by", "--rule", "guarded-by-escape",
        "--check", "--baseline", str(base),
    )
    assert proc.returncode == 1
    assert "guarded-by-escape" in proc.stdout
    assert "declared via # kllms: guarded-by" not in proc.stdout


def test_baseline_usage_error_on_malformed_file(tmp_path):
    broken = tmp_path / "broken.json"
    broken.write_text("not json", encoding="utf-8")
    proc = _cli("--baseline", str(broken))
    assert proc.returncode == 2
    assert "--baseline" in proc.stderr
