"""Runtime lock-order sanitizer (KLLMS_LOCKCHECK=1) unit tests.

The sanitizer must (a) stay a zero-overhead pass-through when the env var is
unset, (b) fold per-thread acquisition stacks into a global order graph and
flag a real A->B / B->A inversion built by two threads, (c) flag device
dispatch under a lock not declared ``allow_dispatch=True``, and (d) keep
Condition.wait bookkeeping honest (wait releases the lock; no phantom holds).
"""

import threading
import time

import pytest

from k_llms_tpu.analysis import lockcheck


@pytest.fixture
def checked(monkeypatch):
    """Enable the sanitizer and isolate its process-wide state."""
    monkeypatch.setenv("KLLMS_LOCKCHECK", "1")
    lockcheck.reset_state()
    yield
    lockcheck.reset_state()


def _in_thread(fn):
    t = threading.Thread(target=fn)
    t.start()
    t.join(timeout=5.0)
    assert not t.is_alive()


def test_factories_return_plain_primitives_when_disabled(monkeypatch):
    monkeypatch.delenv("KLLMS_LOCKCHECK", raising=False)
    assert not lockcheck.lockcheck_enabled()
    lock = lockcheck.make_lock("t.plain")
    rlock = lockcheck.make_rlock("t.plain_r")
    cv = lockcheck.make_condition("t.plain_cv")
    for obj in (lock, rlock, cv):
        assert not isinstance(obj, lockcheck._CheckedBase)
    with lock, rlock, cv:
        pass


def test_enabled_values(monkeypatch):
    for val, expect in [("1", True), ("true", True), ("ON", True),
                        ("0", False), ("off", False), ("", False)]:
        monkeypatch.setenv("KLLMS_LOCKCHECK", val)
        assert lockcheck.lockcheck_enabled() is expect


def test_two_thread_inversion_is_reported_as_cycle(checked):
    a = lockcheck.make_lock("t.a")
    b = lockcheck.make_lock("t.b")

    def forward():
        with a:
            with b:
                pass

    def backward():
        with b:
            with a:
                pass

    # Sequenced via join so the test never actually deadlocks; the graph
    # still records a->b from thread 1 and b->a from thread 2.
    _in_thread(forward)
    _in_thread(backward)

    found = lockcheck.violations()
    assert len(found) == 1
    assert "lock-order cycle" in found[0]
    assert "t.a" in found[0] and "t.b" in found[0]
    assert "test_lockcheck.py" in found[0]  # closing site is actionable
    with pytest.raises(lockcheck.LockCheckError, match="lock-order cycle"):
        lockcheck.assert_clean()


def test_consistent_order_is_clean(checked):
    a = lockcheck.make_lock("t.a")
    b = lockcheck.make_lock("t.b")

    def forward():
        with a:
            with b:
                pass

    _in_thread(forward)
    _in_thread(forward)
    with a:
        with b:
            pass
    assert set(lockcheck.graph()) == {("t.a", "t.b")}
    lockcheck.assert_clean()


def test_rlock_reentrancy_is_not_a_violation(checked):
    r = lockcheck.make_rlock("t.r")
    with r:
        with r:
            pass
    lockcheck.assert_clean()
    assert ("t.r", "t.r") not in lockcheck.graph()


def test_same_name_instances_are_orderless_peers(checked):
    # Per-member locks (reliability.replica.{id}) share a canonical name;
    # nesting two distinct instances must not fabricate a self-cycle.
    m1 = lockcheck.make_lock("t.member")
    m2 = lockcheck.make_lock("t.member")
    with m1:
        with m2:
            pass
    with m2:
        with m1:
            pass
    lockcheck.assert_clean()


def test_dispatch_under_plain_lock_is_a_violation(checked):
    guard = lockcheck.make_lock("t.guard")
    with guard:
        lockcheck.note_device_dispatch("unit step")
    found = lockcheck.violations()
    assert len(found) == 1
    assert "unit step" in found[0] and "t.guard" in found[0]
    assert "allow_dispatch" in found[0]


def test_dispatch_under_allow_dispatch_lock_is_clean(checked):
    gate = lockcheck.make_lock("t.gate", allow_dispatch=True)
    with gate:
        lockcheck.note_device_dispatch("unit step")
    lockcheck.assert_clean()


def test_dispatch_with_nothing_held_is_clean(checked):
    lockcheck.note_device_dispatch("free step")
    lockcheck.assert_clean()


def test_condition_wait_releases_and_notify_wakes(checked):
    cv = lockcheck.make_condition("t.cv")
    woke = []
    flag = []

    def waiter():
        with cv:
            cv.wait_for(lambda: flag, timeout=5.0)
            woke.append(True)

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.05)
    # If wait() failed to release the underlying lock this acquire would
    # block until the waiter's timeout; the join below would then fail.
    with cv:
        flag.append(1)
        cv.notify_all()
    t.join(timeout=5.0)
    assert not t.is_alive()
    assert woke == [True]
    lockcheck.assert_clean()


def test_condition_hold_still_counts_for_ordering(checked):
    cv = lockcheck.make_condition("t.cv")
    inner = lockcheck.make_lock("t.inner")
    with cv:
        with inner:
            pass
    assert ("t.cv", "t.inner") in lockcheck.graph()


def test_reset_state_clears_violations_and_graph(checked):
    guard = lockcheck.make_lock("t.guard")
    with guard:
        lockcheck.note_device_dispatch("unit step")
    assert lockcheck.violations()
    lockcheck.reset_state()
    assert lockcheck.violations() == []
    assert lockcheck.graph() == {}
    lockcheck.assert_clean()


def test_violations_deduplicate(checked):
    guard = lockcheck.make_lock("t.guard")
    for _ in range(3):
        with guard:
            lockcheck.note_device_dispatch("unit step")
    assert len(lockcheck.violations()) == 1


# ---------------------------------------------------------------------------
# racecheck: the Eraser-style lockset sanitizer (KLLMS_RACECHECK=1)
# ---------------------------------------------------------------------------


@pytest.fixture
def racecheck(monkeypatch):
    """Enable the lockset sanitizer (without lockcheck, proving it carries
    its own instrumentation) and isolate process-wide state."""
    monkeypatch.setenv("KLLMS_RACECHECK", "1")
    monkeypatch.delenv("KLLMS_LOCKCHECK", raising=False)
    lockcheck.reset_state()
    yield
    lockcheck.reset_state()


def test_two_thread_unguarded_write_race_reports_both_stacks(racecheck):
    class Loop:
        def __init__(self):
            self._lock = lockcheck.make_lock("t.race_loop")

        def first_writer(self):
            self.gauge = 1

        def second_writer(self):
            self.gauge = 2

    loop = Loop()
    # The factory saw ``self`` in its caller's frame and auto-registered it.
    assert getattr(type(loop), "_kllms_is_tracked", False)
    t1 = threading.Thread(target=loop.first_writer, name="racecheck-w1")
    t1.start()
    t1.join(timeout=5.0)
    t2 = threading.Thread(target=loop.second_writer, name="racecheck-w2")
    t2.start()
    t2.join(timeout=5.0)
    found = lockcheck.violations()
    assert len(found) == 1, found
    msg = found[0]
    assert "racecheck" in msg and "Loop.gauge" in msg
    assert "'t.race_loop'" in msg
    # BOTH access stacks, each attributed to its thread and call site.
    assert "access A [write by racecheck-w1]" in msg
    assert "access B [write by racecheck-w2]" in msg
    assert "first_writer" in msg and "second_writer" in msg
    with pytest.raises(lockcheck.LockCheckError, match="racecheck"):
        lockcheck.assert_clean()
    lockcheck.reset_state()
    lockcheck.assert_clean()


def test_correctly_guarded_field_stays_clean(racecheck):
    class Box:
        def __init__(self):
            self._lock = lockcheck.make_lock("t.race_box")
            self.total = 0

        def bump(self):
            for _ in range(200):
                with self._lock:
                    self.total += 1

    box = Box()
    threads = [threading.Thread(target=box.bump) for _ in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10.0)
        assert not t.is_alive()
    with box._lock:
        assert box.total == 600
    lockcheck.assert_clean()


def test_init_phase_single_thread_writes_are_exempt(racecheck):
    class Cfg:
        def __init__(self):
            self._lock = lockcheck.make_lock("t.race_cfg")
            self.width = 0

    cfg = Cfg()
    for i in range(50):
        cfg.width = i  # still the first thread: Eraser's exclusive state
    assert lockcheck.violations() == []
    # A second thread that only READS moves the field to shared — reported
    # only if it later goes shared-modified, which a pure reader never does.
    _in_thread(lambda: [cfg.width for _ in range(10)])
    lockcheck.assert_clean()


def test_racecheck_off_allocates_no_instrumentation(monkeypatch):
    monkeypatch.delenv("KLLMS_RACECHECK", raising=False)
    monkeypatch.delenv("KLLMS_LOCKCHECK", raising=False)
    before = dict(lockcheck._tracked_classes)

    class Plain:
        def __init__(self):
            self._lock = lockcheck.make_lock("t.race_plain")
            self.value = 0

    p = Plain()
    assert type(p) is Plain  # class never swapped
    assert not isinstance(p._lock, lockcheck._CheckedBase)
    assert "_kllms_race_fields" not in p.__dict__
    assert lockcheck._tracked_classes == before
    # The public registration surface is equally a no-op when disabled.
    lockcheck.shared_state(p, "t.race_plain")
    lockcheck.race_exempt(p, "value")
    assert type(p) is Plain
    assert "_kllms_race_fields" not in p.__dict__
    assert "_kllms_race_exempt" not in p.__dict__


def test_race_exempt_mirrors_unguarded_annotation(racecheck):
    class Latch:
        def __init__(self):
            self._lock = lockcheck.make_lock("t.race_latch")
            self.closed = False
            lockcheck.race_exempt(self, "closed")

        def close(self):
            self.closed = True

    latch = Latch()
    _in_thread(latch.close)
    _in_thread(latch.close)
    assert latch.closed is True
    lockcheck.assert_clean()


def test_shared_state_explicit_registration_without_a_factory(racecheck):
    class Bare:
        pass

    bare = Bare()
    lockcheck.shared_state(bare, "t.race_bare")

    def w1():
        bare.x = 1

    def w2():
        bare.x = 2

    _in_thread(w1)
    _in_thread(w2)
    assert any("Bare.x" in m for m in lockcheck.violations())
