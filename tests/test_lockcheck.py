"""Runtime lock-order sanitizer (KLLMS_LOCKCHECK=1) unit tests.

The sanitizer must (a) stay a zero-overhead pass-through when the env var is
unset, (b) fold per-thread acquisition stacks into a global order graph and
flag a real A->B / B->A inversion built by two threads, (c) flag device
dispatch under a lock not declared ``allow_dispatch=True``, and (d) keep
Condition.wait bookkeeping honest (wait releases the lock; no phantom holds).
"""

import threading
import time

import pytest

from k_llms_tpu.analysis import lockcheck


@pytest.fixture
def checked(monkeypatch):
    """Enable the sanitizer and isolate its process-wide state."""
    monkeypatch.setenv("KLLMS_LOCKCHECK", "1")
    lockcheck.reset_state()
    yield
    lockcheck.reset_state()


def _in_thread(fn):
    t = threading.Thread(target=fn)
    t.start()
    t.join(timeout=5.0)
    assert not t.is_alive()


def test_factories_return_plain_primitives_when_disabled(monkeypatch):
    monkeypatch.delenv("KLLMS_LOCKCHECK", raising=False)
    assert not lockcheck.lockcheck_enabled()
    lock = lockcheck.make_lock("t.plain")
    rlock = lockcheck.make_rlock("t.plain_r")
    cv = lockcheck.make_condition("t.plain_cv")
    for obj in (lock, rlock, cv):
        assert not isinstance(obj, lockcheck._CheckedBase)
    with lock, rlock, cv:
        pass


def test_enabled_values(monkeypatch):
    for val, expect in [("1", True), ("true", True), ("ON", True),
                        ("0", False), ("off", False), ("", False)]:
        monkeypatch.setenv("KLLMS_LOCKCHECK", val)
        assert lockcheck.lockcheck_enabled() is expect


def test_two_thread_inversion_is_reported_as_cycle(checked):
    a = lockcheck.make_lock("t.a")
    b = lockcheck.make_lock("t.b")

    def forward():
        with a:
            with b:
                pass

    def backward():
        with b:
            with a:
                pass

    # Sequenced via join so the test never actually deadlocks; the graph
    # still records a->b from thread 1 and b->a from thread 2.
    _in_thread(forward)
    _in_thread(backward)

    found = lockcheck.violations()
    assert len(found) == 1
    assert "lock-order cycle" in found[0]
    assert "t.a" in found[0] and "t.b" in found[0]
    assert "test_lockcheck.py" in found[0]  # closing site is actionable
    with pytest.raises(lockcheck.LockCheckError, match="lock-order cycle"):
        lockcheck.assert_clean()


def test_consistent_order_is_clean(checked):
    a = lockcheck.make_lock("t.a")
    b = lockcheck.make_lock("t.b")

    def forward():
        with a:
            with b:
                pass

    _in_thread(forward)
    _in_thread(forward)
    with a:
        with b:
            pass
    assert set(lockcheck.graph()) == {("t.a", "t.b")}
    lockcheck.assert_clean()


def test_rlock_reentrancy_is_not_a_violation(checked):
    r = lockcheck.make_rlock("t.r")
    with r:
        with r:
            pass
    lockcheck.assert_clean()
    assert ("t.r", "t.r") not in lockcheck.graph()


def test_same_name_instances_are_orderless_peers(checked):
    # Per-member locks (reliability.replica.{id}) share a canonical name;
    # nesting two distinct instances must not fabricate a self-cycle.
    m1 = lockcheck.make_lock("t.member")
    m2 = lockcheck.make_lock("t.member")
    with m1:
        with m2:
            pass
    with m2:
        with m1:
            pass
    lockcheck.assert_clean()


def test_dispatch_under_plain_lock_is_a_violation(checked):
    guard = lockcheck.make_lock("t.guard")
    with guard:
        lockcheck.note_device_dispatch("unit step")
    found = lockcheck.violations()
    assert len(found) == 1
    assert "unit step" in found[0] and "t.guard" in found[0]
    assert "allow_dispatch" in found[0]


def test_dispatch_under_allow_dispatch_lock_is_clean(checked):
    gate = lockcheck.make_lock("t.gate", allow_dispatch=True)
    with gate:
        lockcheck.note_device_dispatch("unit step")
    lockcheck.assert_clean()


def test_dispatch_with_nothing_held_is_clean(checked):
    lockcheck.note_device_dispatch("free step")
    lockcheck.assert_clean()


def test_condition_wait_releases_and_notify_wakes(checked):
    cv = lockcheck.make_condition("t.cv")
    woke = []
    flag = []

    def waiter():
        with cv:
            cv.wait_for(lambda: flag, timeout=5.0)
            woke.append(True)

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.05)
    # If wait() failed to release the underlying lock this acquire would
    # block until the waiter's timeout; the join below would then fail.
    with cv:
        flag.append(1)
        cv.notify_all()
    t.join(timeout=5.0)
    assert not t.is_alive()
    assert woke == [True]
    lockcheck.assert_clean()


def test_condition_hold_still_counts_for_ordering(checked):
    cv = lockcheck.make_condition("t.cv")
    inner = lockcheck.make_lock("t.inner")
    with cv:
        with inner:
            pass
    assert ("t.cv", "t.inner") in lockcheck.graph()


def test_reset_state_clears_violations_and_graph(checked):
    guard = lockcheck.make_lock("t.guard")
    with guard:
        lockcheck.note_device_dispatch("unit step")
    assert lockcheck.violations()
    lockcheck.reset_state()
    assert lockcheck.violations() == []
    assert lockcheck.graph() == {}
    lockcheck.assert_clean()


def test_violations_deduplicate(checked):
    guard = lockcheck.make_lock("t.guard")
    for _ in range(3):
        with guard:
            lockcheck.note_device_dispatch("unit step")
    assert len(lockcheck.violations()) == 1
