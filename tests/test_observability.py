"""Observability + config + multihost-init plumbing."""

import os

import pytest

from k_llms_tpu import KLLMs
from k_llms_tpu.backends.tpu import BackendConfig, TpuBackend
from k_llms_tpu.parallel.distributed import initialize_multihost
from k_llms_tpu.utils.observability import Trace, confidence_histogram


def test_trace_phases():
    t = Trace()
    with t.phase("a"):
        pass
    with t.phase("b"):
        with t.phase("a"):
            pass
    d = t.as_dict()
    assert set(d) == {"a", "b"}
    assert d["a"] >= 0


def test_confidence_histogram():
    lik = {"a": 0.9, "b": [0.1, 0.5], "c": {"d": 1.0, "reason": True}}
    h = confidence_histogram(lik)
    assert h["count"] == 4  # bool excluded
    assert sum(h["histogram"]) == 4
    assert h["min"] == 0.1
    empty = confidence_histogram({})
    assert empty["count"] == 0


def test_timings_attached_when_traced(monkeypatch):
    monkeypatch.setenv("KLLMS_TRACE", "1")
    client = KLLMs(backend="fake", responses=[["a", "a"]])
    resp = client.chat.completions.create(
        messages=[{"role": "user", "content": "q"}], model="m", n=2
    )
    assert resp.timings["sample"] >= 0
    assert "consolidate" in resp.timings


def test_engine_stats_attached_when_traced(monkeypatch):
    """KLLMS_TRACE=1 on a local backend also surfaces the engine serving
    stats operators tune speculative/prefix/batch knobs against."""
    monkeypatch.setenv("KLLMS_TRACE", "1")
    backend = TpuBackend(model="tiny", max_new_tokens=4)
    client = KLLMs(backend=backend)
    resp = client.chat.completions.create(
        messages=[{"role": "user", "content": "q"}], model="tiny", n=2, seed=1
    )
    stats = resp.engine_stats
    assert set(stats) == {"spec", "prefix_cache", "scheduler"}
    assert stats["prefix_cache"] == {"hits": 0, "partial_hits": 0, "misses": 0}
    assert stats["scheduler"]["served"] >= 1

    # fake backend has no engine: timings only, no engine_stats
    fake = KLLMs(backend="fake", responses=[["a", "a"]])
    r2 = fake.chat.completions.create(
        messages=[{"role": "user", "content": "q"}], model="m", n=2
    )
    assert getattr(r2, "engine_stats", None) is None
    assert r2.timings["sample"] >= 0


def test_timings_absent_by_default(monkeypatch):
    monkeypatch.delenv("KLLMS_TRACE", raising=False)
    client = KLLMs(backend="fake", responses=[["a", "a"]])
    resp = client.chat.completions.create(
        messages=[{"role": "user", "content": "q"}], model="m", n=2
    )
    assert getattr(resp, "timings", None) is None


def test_backend_config_overrides():
    backend = TpuBackend(
        config=BackendConfig(model="tiny", dtype="float32", max_new_tokens=4, attention_impl="xla")
    )
    assert backend.engine.config.dtype == "float32"
    assert backend.default_max_new_tokens == 4


def test_backend_kwargs_still_work():
    backend = TpuBackend(model="tiny", max_new_tokens=8)
    assert backend.backend_config.max_new_tokens == 8


def test_initialize_multihost_noop_single_host(monkeypatch):
    monkeypatch.delenv("KLLMS_COORDINATOR", raising=False)
    monkeypatch.delenv("KLLMS_NUM_PROCESSES", raising=False)
    assert initialize_multihost() is False


def test_engine_stats_captured_at_generation_time(monkeypatch):
    """Traced responses carry the spec stats captured for THIS request (via
    GenerationResult), so a concurrent request mutating engine.spec_stats
    after generation cannot contaminate the trace."""
    monkeypatch.setenv("KLLMS_TRACE", "1")
    backend = TpuBackend(model="tiny", max_new_tokens=4, speculative="prompt_lookup")
    client = KLLMs(backend=backend)
    resp = client.chat.completions.create(
        messages=[{"role": "user", "content": "q q q q"}], model="tiny", n=2, seed=1
    )
    captured = dict(resp.engine_stats["spec"])
    # the spec loop serves on the mesh too (r3 #4); the capture must reflect
    # THIS request's actual generation-time stats
    assert "verify_iterations" in captured, captured
    # simulate a concurrent request overwriting the shared engine field
    backend.engine.spec_stats = {"verify_iterations": 999}
    assert resp.engine_stats["spec"] == captured  # trace unaffected
