"""Sequence-parallel forward must match the dense forward exactly."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k_llms_tpu.engine.long_context import forward_sequence_parallel
from k_llms_tpu.models import get_config, init_params
from k_llms_tpu.models.llama import forward
from k_llms_tpu.parallel.mesh import make_mesh


def test_sequence_parallel_matches_dense():
    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.key(0))
    mesh = make_mesh(8, 1)
    B, S = 2, 64
    tokens = jax.random.randint(jax.random.key(1), (B, S), 0, cfg.vocab_size)

    logits_sp, hidden_sp, kv_sp = jax.jit(
        lambda p, t: forward_sequence_parallel(cfg, p, t, mesh, seq_axis="data")
    )(params, tokens)
    logits_ref, hidden_ref = forward(cfg, params, tokens, jnp.ones((B, S), jnp.int32))

    np.testing.assert_allclose(
        np.asarray(logits_sp), np.asarray(logits_ref), rtol=2e-4, atol=2e-4
    )
    np.testing.assert_allclose(
        np.asarray(hidden_sp), np.asarray(hidden_ref), rtol=2e-4, atol=2e-4
    )
    # The returned prefix cache has the dense prefill layout.
    assert kv_sp.k.shape == (cfg.num_layers, B, S, cfg.num_kv_heads, cfg.head_dim)
    assert kv_sp.k.dtype == cfg.jax_dtype


VARIANTS = {
    "qwen2-bias": dict(qkv_bias=True),
    "gemma2-norms": dict(
        act="gelu",
        norm_offset=True,
        embed_scale=True,
        post_block_norms=True,
        logit_softcap=30.0,
        query_scale=0.125,
    ),
    "moe": dict(num_experts=4, num_experts_per_tok=2),
}


@pytest.mark.parametrize("variant", sorted(VARIANTS))
def test_sequence_parallel_matches_dense_variants(variant):
    """Every architecture branch the dense _block supports (QKV bias, Gemma-2
    norms/GeGLU/softcap, MoE routing) must agree between ring and dense paths."""
    cfg = get_config("tiny").with_(**VARIANTS[variant])
    params = init_params(cfg, jax.random.key(2))
    mesh = make_mesh(8, 1)
    B, S = 2, 32
    tokens = jax.random.randint(jax.random.key(3), (B, S), 0, cfg.vocab_size)

    logits_sp, _, _ = jax.jit(
        lambda p, t: forward_sequence_parallel(cfg, p, t, mesh, seq_axis="data")
    )(params, tokens)
    logits_ref, _ = forward(cfg, params, tokens, jnp.ones((B, S), jnp.int32))
    np.testing.assert_allclose(
        np.asarray(logits_sp), np.asarray(logits_ref), rtol=2e-4, atol=2e-4
    )


def test_sequence_parallel_rejects_softcap_and_window():
    mesh = make_mesh(8, 1)
    for over in (dict(attn_softcap=50.0), dict(sliding_window=16)):
        cfg = get_config("tiny").with_(**over)
        params = init_params(cfg, jax.random.key(0))
        with pytest.raises(NotImplementedError):
            forward_sequence_parallel(cfg, params, jnp.zeros((1, 64), jnp.int32), mesh)


def test_sequence_parallel_rejects_indivisible():
    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.key(0))
    mesh = make_mesh(8, 1)
    tokens = jnp.zeros((1, 60), jnp.int32)
    import pytest

    with pytest.raises(ValueError):
        forward_sequence_parallel(cfg, params, tokens, mesh)


def test_engine_routes_long_prompts_through_sp_prefill():
    """End-to-end: an engine with sp_prefill_min_tokens set must produce the
    SAME generation for a long prompt as the dense engine (identical seeds),
    and must actually take the SP route (jit cache populated)."""
    from k_llms_tpu.engine.engine import LocalEngine

    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.key(0))
    mesh = make_mesh(4, 2)
    prompt = [int(x) for x in
              jax.random.randint(jax.random.key(9), (70,), 5, 200)]

    dense = LocalEngine(cfg, params=params, mesh=mesh)
    sp = LocalEngine(cfg, params=params, mesh=mesh, sp_prefill_min_tokens=64)

    r_dense = dense.generate(prompt, n=4, max_new_tokens=6, temperature=0.7, seed=3)
    r_sp = sp.generate(prompt, n=4, max_new_tokens=6, temperature=0.7, seed=3)

    assert sp._sp_prefill_cache and not sp._prefill_cache  # SP route taken
    assert dense._prefill_cache and not dense._sp_prefill_cache
    np.testing.assert_array_equal(r_sp.tokens, r_dense.tokens)
    np.testing.assert_allclose(r_sp.logprobs, r_dense.logprobs, rtol=1e-4, atol=1e-4)

    # Short prompts stay on the dense path even when the threshold is set.
    sp.generate(prompt[:10], n=2, max_new_tokens=2, temperature=0.7, seed=3)
    assert sp._prefill_cache


def test_engine_sp_threshold_respects_unsupported_configs():
    """Softcap/sliding-window configs must silently keep the dense path —
    never crash on the ring kernel's NotImplementedError."""
    from k_llms_tpu.engine.engine import LocalEngine

    cfg = get_config("tiny").with_(sliding_window=16)
    params = init_params(cfg, jax.random.key(0))
    mesh = make_mesh(8, 1)
    eng = LocalEngine(cfg, params=params, mesh=mesh, sp_prefill_min_tokens=32)
    res = eng.generate(list(range(5, 70)), n=2, max_new_tokens=3, temperature=0.5, seed=1)
    assert res.tokens.shape == (2, 3)
    assert eng._prefill_cache and not eng._sp_prefill_cache


def test_generate_many_routes_sp_per_request():
    """Coalesced batches must route each long-prompt prefill through the SP
    path and match the solo (generate) results bit-for-bit."""
    from k_llms_tpu.engine.engine import GenRequestSpec, LocalEngine

    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.key(0))
    mesh = make_mesh(4, 2)
    long_prompt = [int(x) for x in jax.random.randint(jax.random.key(4), (70,), 5, 200)]
    short_prompt = list(range(5, 15))

    eng = LocalEngine(cfg, params=params, mesh=mesh, sp_prefill_min_tokens=64)
    solo = [
        eng.generate(p, n=2, max_new_tokens=4, temperature=0.6, seed=s)
        for p, s in ((long_prompt, 11), (short_prompt, 12))
    ]
    batched = eng.generate_many(
        [GenRequestSpec(long_prompt, 2, 11), GenRequestSpec(short_prompt, 2, 12)],
        max_new_tokens=4,
        temperature=0.6,
    )
    assert eng._sp_prefill_cache  # long request took the SP route
    assert eng._prefill_cache  # short request stayed dense
    for s, b in zip(solo, batched):
        np.testing.assert_array_equal(s.tokens, b.tokens)


def test_ulysses_matches_dense_and_ring():
    """All-to-all (Ulysses) context parallelism must agree with both the dense
    forward and the ring path — all three are exact algorithms."""
    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.key(5))
    mesh = make_mesh(8, 1)
    B, S = 2, 64
    tokens = jax.random.randint(jax.random.key(6), (B, S), 0, cfg.vocab_size)

    logits_u, _, kv_u = jax.jit(
        lambda p, t: forward_sequence_parallel(
            cfg, p, t, mesh, seq_axis="data", attention="ulysses"
        )
    )(params, tokens)
    logits_r, _, kv_r = jax.jit(
        lambda p, t: forward_sequence_parallel(cfg, p, t, mesh, seq_axis="data")
    )(params, tokens)
    logits_ref, _ = forward(cfg, params, tokens, jnp.ones((B, S), jnp.int32))

    np.testing.assert_allclose(
        np.asarray(logits_u), np.asarray(logits_ref), rtol=2e-4, atol=2e-4
    )
    np.testing.assert_allclose(
        np.asarray(logits_u), np.asarray(logits_r), rtol=2e-4, atol=2e-4
    )
    np.testing.assert_allclose(
        np.asarray(kv_u.k, np.float32), np.asarray(kv_r.k, np.float32),
        rtol=2e-4, atol=2e-4,
    )


def test_ulysses_rejects_unknown_strategy():
    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.key(0))
    mesh = make_mesh(8, 1)
    with pytest.raises(ValueError, match="Unknown sequence-parallel"):
        forward_sequence_parallel(
            cfg, params, jnp.zeros((1, 64), jnp.int32), mesh, attention="zigzag"
        )


def test_engine_sp_ulysses_route_matches_dense():
    """The engine's SP route with attention="ulysses" generates identically
    to the dense engine."""
    from k_llms_tpu.engine.engine import LocalEngine

    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.key(0))
    mesh = make_mesh(4, 2)
    prompt = [int(x) for x in jax.random.randint(jax.random.key(9), (70,), 5, 200)]
    dense = LocalEngine(cfg, params=params, mesh=mesh)
    uly = LocalEngine(
        cfg, params=params, mesh=mesh,
        sp_prefill_min_tokens=64, sp_attention="ulysses",
    )
    r_d = dense.generate(prompt, n=4, max_new_tokens=5, temperature=0.7, seed=3)
    r_u = uly.generate(prompt, n=4, max_new_tokens=5, temperature=0.7, seed=3)
    assert uly._sp_prefill_cache
    np.testing.assert_array_equal(r_u.tokens, r_d.tokens)


def test_sp_attention_validated_eagerly():
    from k_llms_tpu.engine.engine import LocalEngine

    cfg = get_config("tiny")
    with pytest.raises(ValueError, match="Unknown sp_attention"):
        LocalEngine(cfg, params=init_params(cfg, jax.random.key(0)),
                    use_mesh=False, sp_attention="ulyses")
