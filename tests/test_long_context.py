"""Sequence-parallel forward must match the dense forward exactly."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k_llms_tpu.engine.long_context import forward_sequence_parallel
from k_llms_tpu.models import get_config, init_params
from k_llms_tpu.models.llama import forward
from k_llms_tpu.parallel.mesh import make_mesh


def test_sequence_parallel_matches_dense():
    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.key(0))
    mesh = make_mesh(8, 1)
    B, S = 2, 64
    tokens = jax.random.randint(jax.random.key(1), (B, S), 0, cfg.vocab_size)

    logits_sp, hidden_sp = jax.jit(
        lambda p, t: forward_sequence_parallel(cfg, p, t, mesh, seq_axis="data")
    )(params, tokens)
    logits_ref, hidden_ref = forward(cfg, params, tokens, jnp.ones((B, S), jnp.int32))

    np.testing.assert_allclose(
        np.asarray(logits_sp), np.asarray(logits_ref), rtol=2e-4, atol=2e-4
    )
    np.testing.assert_allclose(
        np.asarray(hidden_sp), np.asarray(hidden_ref), rtol=2e-4, atol=2e-4
    )


VARIANTS = {
    "qwen2-bias": dict(qkv_bias=True),
    "gemma2-norms": dict(
        act="gelu",
        norm_offset=True,
        embed_scale=True,
        post_block_norms=True,
        logit_softcap=30.0,
        query_scale=0.125,
    ),
    "moe": dict(num_experts=4, num_experts_per_tok=2),
}


@pytest.mark.parametrize("variant", sorted(VARIANTS))
def test_sequence_parallel_matches_dense_variants(variant):
    """Every architecture branch the dense _block supports (QKV bias, Gemma-2
    norms/GeGLU/softcap, MoE routing) must agree between ring and dense paths."""
    cfg = get_config("tiny").with_(**VARIANTS[variant])
    params = init_params(cfg, jax.random.key(2))
    mesh = make_mesh(8, 1)
    B, S = 2, 32
    tokens = jax.random.randint(jax.random.key(3), (B, S), 0, cfg.vocab_size)

    logits_sp, _ = jax.jit(
        lambda p, t: forward_sequence_parallel(cfg, p, t, mesh, seq_axis="data")
    )(params, tokens)
    logits_ref, _ = forward(cfg, params, tokens, jnp.ones((B, S), jnp.int32))
    np.testing.assert_allclose(
        np.asarray(logits_sp), np.asarray(logits_ref), rtol=2e-4, atol=2e-4
    )


def test_sequence_parallel_rejects_softcap_and_window():
    mesh = make_mesh(8, 1)
    for over in (dict(attn_softcap=50.0), dict(sliding_window=16)):
        cfg = get_config("tiny").with_(**over)
        params = init_params(cfg, jax.random.key(0))
        with pytest.raises(NotImplementedError):
            forward_sequence_parallel(cfg, params, jnp.zeros((1, 64), jnp.int32), mesh)


def test_sequence_parallel_rejects_indivisible():
    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.key(0))
    mesh = make_mesh(8, 1)
    tokens = jnp.zeros((1, 60), jnp.int32)
    import pytest

    with pytest.raises(ValueError):
        forward_sequence_parallel(cfg, params, tokens, mesh)
