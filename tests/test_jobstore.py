"""JobStore durability: the exactly-once contract under kills at ANY point.

Three layers of pinning:

- Unit behavior: round-trip, reopen, requeue/late-commit convergence, cancel
  with partial output, missing-input and stray-``.tmp`` reconciliation.
- The ``batch.store`` failpoint's ``torn`` action: a prefix of a journal
  frame reaches the file and the append raises — the exact disk state a kill
  mid-write leaves. Recovery must truncate the tail and land the item on the
  safe side (pending when "started" tore; done when the segment committed).
- A byte-offset truncation sweep: replay a full job's journal, truncate a
  COPY at every few bytes, reopen, and assert the invariants hold at every
  single prefix — segments are authoritative, no duplicate output records,
  no crash. This is the "kill anywhere" claim as an exhaustive loop rather
  than a sampled race.
"""

import json
import os
import shutil
from pathlib import Path

import pytest

from k_llms_tpu.reliability import failpoints as fp
from k_llms_tpu.reliability.failpoints import FailSpec
from k_llms_tpu.reliability.jobstore import JobStore, TERMINAL_STATUSES


def _items(n):
    return [
        {
            "custom_id": f"c{i}",
            "rid": f"batch_req_{i:024d}",
            "body": {
                "messages": [{"role": "user", "content": f"q{i}"}],
                "seed": i,
            },
        }
        for i in range(n)
    ]


def _record(item, idx, error=False):
    if error:
        return {
            "id": item["rid"], "custom_id": item["custom_id"],
            "response": None,
            "error": {
                "status_code": 400, "message": "boom",
                "type": "invalid_request_error", "param": None, "code": None,
            },
        }
    return {
        "id": item["rid"], "custom_id": item["custom_id"],
        "response": {"status_code": 200, "body": {"idx": idx}},
        "error": None,
    }


def _complete_job(store, items, job_id=None):
    job = store.create_job(items, tenant="default", job_id=job_id)
    for idx, item in enumerate(items):
        assert store.note_item_started(job.id, idx)
        assert store.commit_item(job.id, idx, _record(item, idx))
    assert store.finish_job(job.id) == "completed"
    return job.id


def _output_ids(store, job_id):
    out = store.read_output(job_id)
    assert out is not None
    return [json.loads(line)["id"] for line in out.splitlines()]


def test_round_trip_and_reopen(tmp_path):
    items = _items(4)
    store = JobStore(tmp_path)
    jid = _complete_job(store, items)
    out = store.read_output(jid)
    assert len(out.splitlines()) == 4
    store.close()

    store2 = JobStore(tmp_path)
    job = store2.job(jid)
    assert job.status == "completed"
    assert job.counts() == {"total": 4, "completed": 4, "failed": 0}
    assert store2.read_output(jid) == out
    assert store2.unfinished_jobs() == []
    store2.close()


def test_error_items_complete_with_errors(tmp_path):
    items = _items(3)
    store = JobStore(tmp_path)
    job = store.create_job(items, tenant="default")
    for idx, item in enumerate(items):
        store.note_item_started(job.id, idx)
        store.commit_item(
            job.id, idx, _record(item, idx, error=(idx == 1)), error=(idx == 1)
        )
    assert store.finish_job(job.id) == "completed_with_errors"
    records = [
        json.loads(line) for line in store.read_output(job.id).splitlines()
    ]
    assert [r["error"] is not None for r in records] == [False, True, False]
    assert store.job(job.id).counts()["failed"] == 1
    store.close()


def test_torn_failpoint_on_started_append_rolls_back_to_pending(tmp_path):
    """A torn 'started' record is invisible after recovery: the item is
    pending again and executes normally."""
    items = _items(2)
    store = JobStore(tmp_path)
    job = store.create_job(items, tenant="default")
    with fp.failpoints({"batch.store": FailSpec(action="torn", times=1)}):
        with pytest.raises(RuntimeError, match="torn journal append"):
            store.note_item_started(job.id, 0)
    store.close()

    store2 = JobStore(tmp_path)
    recovered = store2.job(job.id)
    assert recovered.items == ["pending", "pending"]
    # The torn tail is gone from disk: the journal replays cleanly now.
    jid = job.id
    for idx, item in enumerate(items):
        assert store2.note_item_started(jid, idx)
        assert store2.commit_item(jid, idx, _record(item, idx))
    assert store2.finish_job(jid) == "completed"
    assert len(_output_ids(store2, jid)) == 2
    store2.close()


def test_torn_failpoint_on_commit_append_segment_wins(tmp_path):
    """Kill between segment rename and journal append: the segment is the
    commit point, so recovery classifies the item done — exactly once."""
    items = _items(2)
    store = JobStore(tmp_path)
    job = store.create_job(items, tenant="default")
    store.note_item_started(job.id, 0)
    with fp.failpoints({"batch.store": FailSpec(action="torn", times=1)}):
        with pytest.raises(RuntimeError, match="batch.store"):
            store.commit_item(job.id, 0, _record(items[0], 0))
    store.close()

    store2 = JobStore(tmp_path)
    recovered = store2.job(job.id)
    assert recovered.items[0] == "done"  # segment authoritative
    assert recovered.items[1] == "pending"
    store2.note_item_started(job.id, 1)
    store2.commit_item(job.id, 1, _record(items[1], 1))
    assert store2.finish_job(job.id) == "completed"
    ids = _output_ids(store2, job.id)
    assert len(ids) == 2 and len(set(ids)) == 2
    store2.close()


def test_manual_garbage_tail_truncated(tmp_path):
    items = _items(2)
    store = JobStore(tmp_path)
    jid = _complete_job(store, items)
    store.close()
    journal = tmp_path / "journal.log"
    intact = journal.read_bytes()
    with open(journal, "ab") as fh:
        fh.write(b"\x07garbage-partial-frame")
    store2 = JobStore(tmp_path)
    assert store2.job(jid).status == "completed"
    assert journal.read_bytes() == intact  # tail truncated in place
    store2.close()


def test_kill_anywhere_truncation_sweep(tmp_path):
    """Truncate a complete run's journal at every few byte offsets; every
    prefix must recover to a consistent state with no duplicate outputs."""
    src = tmp_path / "src"
    src.mkdir()
    items = _items(3)
    store = JobStore(src)
    jid = _complete_job(store, items, job_id="batch_sweep")
    store.close()
    journal_bytes = (src / "journal.log").read_bytes()

    for cut in range(0, len(journal_bytes) + 1, 3):
        trial = tmp_path / f"cut{cut}"
        shutil.copytree(src, trial)
        with open(trial / "journal.log", "ab") as fh:
            fh.truncate(cut)
        store2 = JobStore(trial)
        jobs = store2.jobs()
        if jid in jobs:
            job = jobs[jid]
            # Segments are authoritative: every committed segment must be
            # reflected as done regardless of where the journal was cut.
            for idx in range(job.n_items):
                seg = trial / "jobs" / jid / "out" / f"{idx:05d}.json"
                assert seg.exists(), "commit sweep wrote all segments"
                assert job.items[idx] == "done", (cut, idx, job.items)
            assert job.status == "completed"
            ids = _output_ids(store2, jid)
            assert len(ids) == 3 and len(set(ids)) == 3, (cut, ids)
        # else: the cut removed the creation record itself — "job never
        # submitted" is the other legal pole of the contract.
        store2.close()
        shutil.rmtree(trial)


def test_requeue_then_late_commit_converges(tmp_path):
    """Drain checkpoints an in-flight item to pending; the straggler thread
    commits anyway. Both writers target the same segment with identical
    bytes, so the output holds exactly one record."""
    items = _items(1)
    store = JobStore(tmp_path)
    job = store.create_job(items, tenant="default")
    store.note_item_started(job.id, 0)
    assert store.requeue_item(job.id, 0)
    assert store.job(job.id).items[0] == "pending"
    # The straggler's late commit lands after the checkpoint:
    assert store.commit_item(job.id, 0, _record(items[0], 0))
    assert store.finish_job(job.id) == "completed"
    assert len(_output_ids(store, job.id)) == 1
    store.close()
    # And the journal's pending->done sequence replays to the same state.
    store2 = JobStore(tmp_path)
    assert store2.job(job.id).status == "completed"
    assert len(_output_ids(store2, job.id)) == 1
    store2.close()


def test_requeue_refuses_non_started(tmp_path):
    items = _items(1)
    store = JobStore(tmp_path)
    job = store.create_job(items, tenant="default")
    assert not store.requeue_item(job.id, 0)  # pending, not started
    store.note_item_started(job.id, 0)
    store.commit_item(job.id, 0, _record(items[0], 0))
    assert not store.requeue_item(job.id, 0)  # done is final
    store.close()


def test_cancel_keeps_partial_output(tmp_path):
    items = _items(3)
    store = JobStore(tmp_path)
    job = store.create_job(items, tenant="default")
    store.note_item_started(job.id, 0)
    store.commit_item(job.id, 0, _record(items[0], 0))
    assert store.cancel_job(job.id) == "cancelled"
    assert len(_output_ids(store, job.id)) == 1
    # Cancelled is terminal: no new work may start, cancel is idempotent.
    assert not store.note_item_started(job.id, 1)
    assert store.cancel_job(job.id) == "cancelled"
    store.close()
    store2 = JobStore(tmp_path)
    assert store2.job(job.id).status == "cancelled"
    assert store2.unfinished_jobs() == []
    store2.close()


def test_stray_tmp_segment_discarded(tmp_path):
    items = _items(1)
    store = JobStore(tmp_path)
    job = store.create_job(items, tenant="default")
    store.close()
    stray = tmp_path / "jobs" / job.id / "out" / "00000.json.tmp"
    stray.write_bytes(b'{"half-written":')
    store2 = JobStore(tmp_path)
    assert not stray.exists()
    assert store2.job(job.id).items == ["pending"]
    store2.close()


def test_unparsable_segment_reexecutes(tmp_path):
    items = _items(1)
    store = JobStore(tmp_path)
    job = store.create_job(items, tenant="default")
    store.note_item_started(job.id, 0)
    store.close()
    seg = tmp_path / "jobs" / job.id / "out" / "00000.json"
    seg.write_bytes(b"\x00\xff not json")
    store2 = JobStore(tmp_path)
    assert not seg.exists()  # unlinked: re-execution is the safe direction
    assert store2.job(job.id).items == ["pending"]
    store2.close()


def test_missing_input_marks_cancelled(tmp_path):
    items = _items(1)
    store = JobStore(tmp_path)
    job = store.create_job(items, tenant="default")
    store.close()
    os.unlink(tmp_path / "jobs" / job.id / "input.jsonl")
    store2 = JobStore(tmp_path)
    assert store2.job(job.id).status == "cancelled"
    store2.close()


def test_terminal_statuses_are_frozen():
    assert set(TERMINAL_STATUSES) == {
        "completed", "completed_with_errors", "cancelled"
    }


# -- TTL sweep (ISSUE 18) ----------------------------------------------------

def test_ttl_sweeps_only_expired_terminal_jobs(tmp_path):
    """On open, terminal jobs older than ttl_s are GC'd — journal gc record,
    directory gone, no resurrection on later reopens. Fresh terminal jobs and
    unfinished jobs (however old) survive."""
    from k_llms_tpu.utils.observability import BATCH_EVENTS

    store = JobStore(tmp_path)
    old_done = _complete_job(store, _items(2))
    fresh_done = _complete_job(store, _items(2))
    stale_open = store.create_job(_items(2), tenant="default").id
    store.close()

    # created_at is journal-borne: a short real wait with a shorter ttl ages
    # every job already written without touching the store's internals.
    import time as _time

    _time.sleep(0.12)
    before = BATCH_EVENTS.snapshot()
    store2 = JobStore(tmp_path, ttl_s=0.05)
    after = BATCH_EVENTS.snapshot()
    # Both terminal jobs are older than 50ms -> swept; the open job survives.
    assert store2.job(old_done) is None
    assert store2.job(fresh_done) is None
    assert store2.job(stale_open) is not None
    assert not (tmp_path / "jobs" / old_done).exists()
    assert after.get("batch.job_swept", 0) - before.get("batch.job_swept", 0) == 2
    store2.close()

    # Swept jobs must NOT resurrect (as cancelled ghosts or otherwise) on a
    # later TTL-free reopen: the gc journal record wins over the job record.
    store3 = JobStore(tmp_path)
    assert store3.job(old_done) is None
    assert store3.job(fresh_done) is None
    assert store3.job(stale_open).status in ("queued", "in_progress")
    store3.close()


def test_ttl_zero_or_none_never_sweeps(tmp_path):
    store = JobStore(tmp_path)
    jid = _complete_job(store, _items(1))
    store.close()
    for ttl in (None, 0, 0.0):
        s = JobStore(tmp_path, ttl_s=ttl)
        assert s.job(jid) is not None
        s.close()


def test_ttl_sweep_removes_orphan_dirs(tmp_path):
    """A job directory with no journal row (create killed before its journal
    append, or an interrupted sweep rmtree) is deleted by the orphan pass."""
    store = JobStore(tmp_path)
    jid = _complete_job(store, _items(1))
    store.close()
    orphan = tmp_path / "jobs" / "batch_orphan"
    (orphan / "out").mkdir(parents=True)
    (orphan / "input.jsonl").write_bytes(b"{}\n")
    store2 = JobStore(tmp_path, ttl_s=3600.0)
    assert not orphan.exists()
    assert store2.job(jid) is not None  # fresh terminal job: kept
    assert store2.read_output(jid) is not None
    store2.close()
