"""Consensus-quality eval: k-way consensus must beat a single sample on the
scripted noise model (the hermetic stand-in for the reference's quality
benchmark, README_TESTS.md:205-214)."""

import json

from k_llms_tpu.utils.quality import (
    DEFAULT_TRUTH,
    consensus_quality_eval,
    field_accuracy,
    make_noisy_samples,
)

from reference_oracle import load_reference_engine, reference_available

import pytest


def test_field_accuracy_exact():
    assert field_accuracy(DEFAULT_TRUTH, DEFAULT_TRUTH) == 1.0


def test_field_accuracy_partial():
    pred = dict(DEFAULT_TRUTH)
    pred["vendor"] = "wrong"
    acc = field_accuracy(pred, DEFAULT_TRUTH)
    assert 0 < acc < 1


def test_field_accuracy_float_tolerance():
    pred = json.loads(json.dumps(DEFAULT_TRUTH))
    pred["total"] = DEFAULT_TRUTH["total"] * 1.001  # within 0.5%
    assert field_accuracy(pred, DEFAULT_TRUTH) == 1.0
    pred["total"] = DEFAULT_TRUTH["total"] * 1.2
    assert field_accuracy(pred, DEFAULT_TRUTH) < 1.0


def test_field_accuracy_missing_rows_penalized():
    pred = json.loads(json.dumps(DEFAULT_TRUTH))
    pred["line_items"] = pred["line_items"][:1]
    assert field_accuracy(pred, DEFAULT_TRUTH) < 1.0


def test_noise_model_deterministic():
    a = make_noisy_samples(DEFAULT_TRUTH, 4, 0.3, 42)
    b = make_noisy_samples(DEFAULT_TRUTH, 4, 0.3, 42)
    assert a == b
    assert a != make_noisy_samples(DEFAULT_TRUTH, 4, 0.3, 43)
    # Every sample stays valid JSON.
    for s in a:
        json.loads(s)


def test_noise_zero_is_identity():
    for s in make_noisy_samples(DEFAULT_TRUTH, 3, 0.0, 7):
        # list-drop/shuffle are noise-gated too, so noise=0 must be lossless
        assert json.loads(s) == DEFAULT_TRUTH


def test_consensus_beats_single_sample():
    """The headline claim: consensus over n noisy samples is more accurate
    than one sample — the whole point of the framework."""
    r = consensus_quality_eval(n_values=(3, 8), trials=8, seed=1)
    assert r["consensus_n3"] >= r["single_sample"]
    assert r["consensus_n8"] > r["single_sample"] + 0.05
    assert r["consensus_n8"] >= 0.85  # the reference's comparable quality bar


@pytest.mark.skipif(not reference_available(), reason="reference tree not present")
def test_quality_noise_model_matches_reference_consensus():
    """The consensus outcome on this noise model is BIT-IDENTICAL to the
    reference engine's (levenshtein mode), so quality numbers measured here
    transfer to the reference algorithm."""
    from k_llms_tpu.consensus.recursion import (
        consensus_values,
        recursive_list_alignments,
    )
    from k_llms_tpu.consensus.settings import ConsensusSettings
    from k_llms_tpu.consensus.similarity import SimilarityScorer

    ref = load_reference_engine()

    def _boom(*a, **kw):  # embeddings must not be consulted in levenshtein mode
        raise RuntimeError("no embeddings in levenshtein mode")

    for trial in range(3):
        samples = [
            json.loads(s) for s in make_noisy_samples(DEFAULT_TRUTH, 8, 0.25, 500 + trial)
        ]
        scorer = SimilarityScorer(method="levenshtein")
        settings = ConsensusSettings(
            reference_exact=True, string_similarity_method="levenshtein"
        )
        aligned, _ = recursive_list_alignments(samples, scorer, settings.min_support_ratio)
        ours, _ = consensus_values(aligned, settings, scorer)

        rsettings = ref.ConsensusSettings(string_similarity_method="levenshtein")
        raligned, _ = ref.recursive_list_alignments(
            samples, "levenshtein", _boom, None, rsettings.min_support_ratio
        )
        theirs, _ = ref.consensus_values(raligned, rsettings, _boom, None)
        assert ours == theirs
