"""List alignment pipeline + Condorcet ordering
(reference consensus_utils :109-430, majority_sorting.py)."""

import pytest

from k_llms_tpu.consensus.alignment import (
    _compute_dynamic_threshold,
    _prune_low_support_elements,
    SimilarityCache,
    lists_alignment,
    low_cutoff_bound,
    remove_outliers,
)
from k_llms_tpu.consensus.majority import sort_by_original_majority
from k_llms_tpu.consensus.recursion import recursive_list_alignments
from k_llms_tpu.consensus.similarity import SimilarityScorer


@pytest.fixture
def scorer():
    return SimilarityScorer(method="levenshtein")


def test_prune_low_support():
    aligned = [["a", None], ["a", None], ["a", "b"]]
    pruned = _prune_low_support_elements(aligned, 0.51)
    assert pruned == [["a"], ["a"], ["a"]]


def test_prune_relaxes_when_all_below():
    aligned = [["a", None], [None, "b"], [None, None]]
    pruned = _prune_low_support_elements(aligned, 0.9)
    # max support is 1/3; threshold relaxes to that, both columns kept
    assert pruned == aligned


def test_low_cutoff_bound_empty():
    assert low_cutoff_bound([]) == 0.0


def test_remove_outliers_no_jump():
    data = [0.5, 0.51, 0.52, 0.53, 0.9]
    assert remove_outliers(data) == data


def test_dynamic_threshold_single_list(scorer):
    cache = SimilarityCache(scorer.generic, [["a"]])
    assert _compute_dynamic_threshold(cache) == 0.5


def test_alignment_identical_lists(scorer):
    lists = [["apple", "banana"], ["apple", "banana"], ["apple", "banana"]]
    aligned, idx = lists_alignment(lists, scorer.generic, min_support_ratio=0.51)
    assert aligned == [["apple", "banana"]] * 3
    assert idx == [[0, 1]] * 3


def test_alignment_permuted_lists(scorer):
    lists = [["apple pie", "banana bread"], ["banana bread", "apple pie"]]
    aligned, idx = lists_alignment(lists, scorer.generic, min_support_ratio=0.5)
    # Same column contents across rows after alignment
    for col in range(2):
        vals = {row[col] for row in aligned}
        assert len(vals) == 1
    # Condorcet order follows majority original order: tie 1-1, broken by avg pos
    flat = aligned[0]
    assert set(flat) == {"apple pie", "banana bread"}


def test_alignment_missing_element_gives_none(scorer):
    lists = [["apple pie", "banana bread"], ["apple pie"], ["apple pie", "banana bread"]]
    aligned, _ = lists_alignment(lists, scorer.generic, min_support_ratio=0.5)
    assert aligned[1] == ["apple pie", None]


def test_alignment_empty_lists(scorer):
    aligned, idx = lists_alignment([[], []], scorer.generic)
    assert aligned == [[], []]


def test_alignment_with_known_reference(scorer):
    lists = [["x1", "y1"], ["y1", "x1"]]
    aligned, idx = lists_alignment(lists, scorer.generic, reference_list_idx=0)
    assert aligned[0] == ["x1", "y1"]
    assert aligned[1] == ["x1", "y1"]
    assert idx[1] == [1, 0]


def test_sort_by_original_majority_reorders():
    originals = [["b", "a"], ["b", "a"], ["a", "b"]]
    # aligned columns: col0 = a's, col1 = b's (same objects)
    aligned = [[row[1], row[0]] for row in originals[:2]] + [[originals[2][0], originals[2][1]]]
    sorted_lists, pos = sort_by_original_majority(aligned, originals)
    # b precedes a in 2 of 3 rows => b's column first
    assert sorted_lists[0] == ["b", "a"]
    assert pos[0] == [0, 1]


def test_recursive_alignment_dicts_of_lists(scorer):
    values = [
        {"items": [{"name": "alpha beta"}, {"name": "gamma delta"}]},
        {"items": [{"name": "gamma delta"}, {"name": "alpha beta"}]},
    ]
    aligned, mappings = recursive_list_alignments(values, scorer, 0.51)
    names0 = [d["name"] for d in aligned[0]["items"]]
    names1 = [d["name"] for d in aligned[1]["items"]]
    assert names0 == names1
    assert any(k.startswith("items.") for k in mappings)


def test_recursive_alignment_preserves_all_none():
    values = [None, None]
    aligned, mappings = recursive_list_alignments(values, SimilarityScorer.levenshtein(), 0.51)
    assert aligned == [None, None]
    assert mappings == {"": ["", ""]}


def test_recursive_alignment_mixed_types_passthrough(scorer):
    values = [{"a": 1}, "string", 5]
    aligned, mappings = recursive_list_alignments(values, scorer, 0.51, current_path="root")
    assert aligned == values
    assert mappings == {"root": ["root", "root", "root"]}
