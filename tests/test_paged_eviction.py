"""Prefix-cache eviction vs in-flight readers of shared pages.

A paged cache entry owns its pages through one refcount; every in-flight
reader (a continuous-loop row mid-decode, a continuation prefill pinning its
matched run) holds its own. Evicting the entry — LRU pressure or explicit —
may therefore only drop the ENTRY's reference: the pages must survive, still
serving bit-exact gathers, until the last reader retires, and only then
return to the free stack.
"""

import numpy as np
import pytest

from k_llms_tpu.engine.continuous import ContinuousDecodeLoop
from k_llms_tpu.engine.engine import LocalEngine
from k_llms_tpu.models import get_config

PAGE = 8


@pytest.fixture()
def paged_engine():
    from conftest import shared_params

    cfg = get_config("tiny")
    return LocalEngine(
        cfg, params=shared_params(cfg, 0), use_mesh=False,
        kv_layout="paged", kv_page_size=PAGE,
        prefix_cache_size=2, prefix_cache_min_reuse=8,
    )


def test_evicted_entry_pages_survive_until_reader_retires(paged_engine):
    """Pin an entry's run like an in-flight reader, evict everything, and
    check the pages stay owned (and readable, bit-exact) until the pin
    drops."""
    eng = paged_engine
    prompt = [(i * 31) % 150 + 3 for i in range(20)]
    eng.generate(prompt, n=1, max_new_tokens=2, temperature=0.0, seed=1)
    alloc = eng._kv_pool.allocator
    with eng._paged_mutex:
        (entry,) = eng._prefix_entries.values()
        run = entry[1]
        pages = list(run.pages)
        before = run.materialize()
        run.retain()  # the in-flight reader's pin
    try:
        with eng._paged_mutex:
            eng._evict_paged_entries(10**9)  # evict ALL entries
        assert not eng._prefix_entries
        # Entry's reference dropped, reader's survives: still owned...
        assert all(alloc.refcount(p) == 1 for p in pages)
        # ...and gathers still return the exact prefill bytes.
        after = run.materialize()
        np.testing.assert_array_equal(
            np.asarray(before.k), np.asarray(after.k)
        )
        np.testing.assert_array_equal(
            np.asarray(before.v), np.asarray(after.v)
        )
    finally:
        alloc.decref(pages)  # reader retires — NOW the pages free
    assert all(alloc.refcount(p) == 0 for p in pages)
    alloc.verify()
    assert alloc.snapshot()["in_use"] == 0


def test_loop_rows_survive_lru_eviction_midflight(paged_engine):
    """End to end: rows decode from a cached run while cache-churning batch
    requests evict that entry mid-flight. The rows' gathers must stay bound
    to live pages (refcounted by the rows), and the final tokens must equal a
    dense engine's."""
    from conftest import shared_engine, shared_params

    eng = paged_engine
    loop = ContinuousDecodeLoop(eng, width=2, max_prompt=64, max_new=24)
    prompt = [(i * 17) % 140 + 5 for i in range(12)]
    churn = [
        [(i * 19) % 130 + 6 for i in range(16)],
        [(i * 23) % 120 + 7 for i in range(18)],
    ]
    evicted = {"done": False}

    def sink(step, _toks):
        if step == 1 and not evicted["done"]:
            evicted["done"] = True
            # prefix_cache_size=2: two distinct stores evict the loop
            # request's entry while its rows are still decoding from it.
            for c in churn:
                eng.generate(c, n=1, max_new_tokens=2, temperature=0.0, seed=3)

    try:
        got = loop.submit(
            prompt, n=2, max_new=16, temperature=0.0, top_p=None, seed=4,
            token_sink=sink,
        ).result(timeout=180)
        assert evicted["done"]
        dense = shared_engine(model="tiny")
        dense_loop = ContinuousDecodeLoop(dense, width=2, max_prompt=64, max_new=24)
        try:
            want = dense_loop.submit(
                prompt, n=2, max_new=16, temperature=0.0, top_p=None, seed=4,
            ).result(timeout=180)
        finally:
            dense_loop.stop()
        np.testing.assert_array_equal(got.tokens, want.tokens)
        np.testing.assert_array_equal(got.logprobs, want.logprobs)
        assert loop.drain(timeout=60)
        assert loop.stats["pages"]["loop_refs"] == 0
    finally:
        loop.stop()
