"""Paged generate_many vs dense: the coalesced-batch differential.

PR 7 made the continuous loop decode against the page pool; the coalesced
``generate_many`` batch still round-tripped every prompt through dense
caches. ``_generate_many_paged`` closes that gap: admission by refcounted
page runs, fresh gen pages per live row, the fused paged-attention step, and
dispatch-and-swap of the donated pool buffers. Every test here pins the same
bar as tests/test_paged_differential.py does for the loop: byte-identical
tokens, logprobs, lengths, and finish reasons against a dense engine on
equal inputs — including prefix-cache hits, shared/extended runs, and both
fallback paths (the ``paged_generate_many=False`` knob and a pool too small
to admit, which must unwind cleanly and retry dense).

Engines come from the session-scoped conftest factories with the SAME keys
tests/test_paged_differential.py uses, so the compile caches are shared.
"""

import numpy as np
import pytest

from k_llms_tpu.engine.engine import GenRequestSpec, LocalEngine
from k_llms_tpu.models import get_config
from k_llms_tpu.utils.observability import KERNEL_EVENTS

PAGE = 8


@pytest.fixture(scope="module")
def engines():
    from conftest import shared_engine

    dense = shared_engine(model="tiny")
    # Explicit pool sizing: the pool is built once, on the first paged
    # launch, so without it the module's first test would fix the capacity
    # every later launch gets. Admissions here are TRANSIENT
    # (prefix_cache_size defaults 0): the launch pin is each run's only
    # reference, exercising the retain-then-release branch.
    paged = shared_engine(
        model="tiny", kv_layout="paged", kv_page_size=PAGE, kv_pool_pages=256
    )
    assert paged.paged_generate_many  # default on
    return dense, paged


PROMPT_A = list(range(3, 20))  # 17 tokens: spans 3 pages, partial tail
PROMPT_B = list(range(5, 16))  # 11 tokens: different bucket occupancy
PROMPT_C = PROMPT_A[:9]  # strict prefix of A: admission shares its pages


def _items(seed0=7):
    return [
        GenRequestSpec(prompt_ids=PROMPT_A, n=2, seed=seed0),
        GenRequestSpec(prompt_ids=PROMPT_B, n=3, seed=seed0 + 4),
        GenRequestSpec(prompt_ids=PROMPT_C, n=1, seed=seed0 + 6),
    ]


def _assert_identical(rd, rp, top_logprobs=False):
    assert len(rd) == len(rp)
    for a, b in zip(rd, rp):
        assert not isinstance(a, Exception) and not isinstance(b, Exception)
        np.testing.assert_array_equal(a.tokens, b.tokens)
        np.testing.assert_array_equal(a.logprobs, b.logprobs)
        np.testing.assert_array_equal(a.lengths, b.lengths)
        assert a.finish_reasons == b.finish_reasons
        if top_logprobs:
            np.testing.assert_array_equal(a.top_tokens, b.top_tokens)
            np.testing.assert_array_equal(a.top_logprobs, b.top_logprobs)


def _xla_dispatches():
    return KERNEL_EVENTS.snapshot().get("kernel.paged_attn_xla_dispatch", 0)


def test_greedy_coalesced_identical(engines):
    """Mixed fan-outs (n=2/3/1 -> row-group padding), a shared-prefix
    admission, greedy decode: byte-identical, and the launch must actually
    have dispatched the paged step (counted per launch)."""
    dense, paged = engines
    kw = dict(max_new_tokens=10, temperature=0.0, top_p=None, top_logprobs=2)
    before = _xla_dispatches()
    rd = dense.generate_many(_items(), **kw)
    rp = paged.generate_many(_items(), **kw)
    _assert_identical(rd, rp, top_logprobs=True)
    assert _xla_dispatches() == before + 1  # one paged launch, CPU -> xla


def test_sampled_coalesced_identical(engines):
    """Sampling keys derive from (seed, step, sample_idx) only — the paged
    batch must replay the dense sampled stream exactly."""
    dense, paged = engines
    kw = dict(max_new_tokens=12, temperature=0.7, top_p=0.9, top_logprobs=2)
    rd = dense.generate_many(_items(seed0=21), **kw)
    rp = paged.generate_many(_items(seed0=21), **kw)
    _assert_identical(rd, rp, top_logprobs=True)


def test_prefix_cache_hit_identical(engines):
    """Second identical launch admits every prompt through the paged prefix
    cache (zero prefill device work) — outputs must not move."""
    dense, _ = engines
    from conftest import shared_engine

    cached = shared_engine(
        model="tiny", kv_layout="paged", kv_page_size=PAGE,
        kv_pool_pages=256, prefix_cache_size=4,
    )
    kw = dict(max_new_tokens=8, temperature=0.6, top_p=0.95)
    items = [
        GenRequestSpec(prompt_ids=PROMPT_A, n=2, seed=31),
        GenRequestSpec(prompt_ids=PROMPT_B, n=2, seed=33),
    ]
    rd = dense.generate_many(items, **kw)
    rp1 = cached.generate_many(items, **kw)
    assert cached._prefix_entries  # the admissions were cached
    rp2 = cached.generate_many(items, **kw)  # pure cache-hit admission
    _assert_identical(rd, rp1)
    _assert_identical(rd, rp2)
    # Launch pins fully unwound: only cache entries keep references.
    cached._kv_pool.allocator.verify()


def test_streamed_tokens_match(engines):
    """The io_callback token tap runs inside the paged loop too: the sink
    must observe the same (step, tokens) stream on both layouts."""
    dense, paged = engines
    streams = {}
    for name, eng in (("dense", dense), ("paged", paged)):
        got = []
        sink = lambda step, toks, got=got: got.append(
            (int(step), np.asarray(toks).copy())
        )
        items = [
            GenRequestSpec(prompt_ids=PROMPT_B, n=2, seed=41, token_sink=sink)
        ]
        res = eng.generate_many(
            items, max_new_tokens=6, temperature=0.5, top_p=0.9
        )
        assert not isinstance(res[0], Exception)
        # io_callback is unordered/at-least-once: dedup + sort by step.
        streams[name] = {s: t for s, t in sorted(got)}
    assert streams["dense"].keys() == streams["paged"].keys()
    for s in streams["dense"]:
        np.testing.assert_array_equal(streams["dense"][s], streams["paged"][s])


def test_config_knob_falls_back_dense(engines):
    """paged_generate_many=False: the paged-layout engine keeps the legacy
    dense-transient batch path and outputs stay identical."""
    dense, _ = engines
    from conftest import shared_engine

    off = shared_engine(
        model="tiny", kv_layout="paged", kv_page_size=PAGE,
        paged_generate_many=False,
    )
    kw = dict(max_new_tokens=8, temperature=0.0, top_p=None)
    before = _xla_dispatches()
    rd = dense.generate_many(_items(seed0=51), **kw)
    ro = off.generate_many(_items(seed0=51), **kw)
    _assert_identical(rd, ro)
    assert _xla_dispatches() == before  # the paged step never dispatched


def test_pool_exhausted_unwinds_and_falls_back():
    """A pool too small for the launch's gen reserve: the paged attempt must
    raise internally, return every reference it took, and the dense fallback
    must still serve the batch — byte-identical to a dense engine."""
    cfg = get_config("tiny")
    from conftest import shared_engine, shared_params

    # Private engine: an 8-page pool (the floor) holds the 1-page prompts but
    # not the 4 rows x pages_for(16) = 8 gen pages the launch reserves on top
    # of them. Two items, because a 1-item batch routes to the solo path
    # before the coalesced paged gate ever runs.
    eng = LocalEngine(
        cfg, params=shared_params(cfg), use_mesh=False, param_seed=0,
        kv_layout="paged", kv_page_size=PAGE, kv_pool_pages=8,
        prefix_cache_size=0,
    )
    pool = eng._ensure_kv_pool()
    assert pool.allocator.total_pages == 8
    free0 = pool.allocator.free_pages

    dense = shared_engine(model="tiny")
    items = [
        GenRequestSpec(prompt_ids=list(range(2, 8)), n=2, seed=61),
        GenRequestSpec(prompt_ids=list(range(3, 9)), n=2, seed=63),
    ]
    kw = dict(max_new_tokens=16, temperature=0.0, top_p=None)
    before = _xla_dispatches()
    rd = dense.generate_many(items, **kw)
    rp = eng.generate_many(items, **kw)
    _assert_identical(rd, rp)
    # The paged step never dispatched (exhaustion precedes kernel selection)
    # and the unwind returned every page the attempt allocated.
    assert _xla_dispatches() == before
    assert pool.allocator.free_pages == free0
    pool.allocator.verify()
