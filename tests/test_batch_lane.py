"""Batch lane: HTTP surface, typed-error capture, crash/drain containment,
and the SIGKILL recovery differential.

The differential is the tentpole pin: a child process runs a real
BatchLane + JobStore over a FakeBackend with a frozen wall clock, SIGKILLs
ITSELF after N committed output segments, and a second child recovers and
finishes the job. The recovered output must be byte-identical to an
uninterrupted run — same record ids (submission-pinned seeds, content-derived
ids), same order, zero duplicates. That is the exactly-once contract measured
at the only place it matters: the output file.
"""

import asyncio
import json
import os
import signal
import subprocess
import sys
import threading
import time
from pathlib import Path

import httpx
import pytest

from k_llms_tpu import KLLMs
from k_llms_tpu.backends.fake import FakeBackend
from k_llms_tpu.reliability import failpoints as fp
from k_llms_tpu.reliability.failpoints import FailSpec
from k_llms_tpu.reliability.jobstore import JobStore
from k_llms_tpu.serving import ServingApp
from k_llms_tpu.serving.batch import BatchLane
from k_llms_tpu.types.wire import InvalidRequestError
from k_llms_tpu.utils.observability import BATCH_EVENTS

REPO = Path(__file__).resolve().parent.parent


def _fake_client():
    return KLLMs(backend=FakeBackend(), model="fake-model")


def _jsonl(n, seed_base=100):
    return "\n".join(
        json.dumps({
            "custom_id": f"c{i}",
            "method": "POST",
            "url": "/v1/chat/completions",
            "body": {
                "messages": [{"role": "user", "content": f"question {i}"}],
                "n": 1,
                "seed": seed_base + i,
            },
        })
        for i in range(n)
    ).encode()


def _asgi(app):
    return httpx.AsyncClient(
        transport=httpx.ASGITransport(app=app), base_url="http://testserver"
    )


def _run(coro):
    return asyncio.run(coro)


async def _poll_terminal(client, jid, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        r = await client.get(f"/v1/batches/{jid}")
        assert r.status_code == 200
        if r.json()["status"] in ("completed", "completed_with_errors",
                                  "cancelled"):
            return r.json()
        await asyncio.sleep(0.02)
    raise AssertionError(f"job {jid} never reached a terminal status")


# ---------------------------------------------------------------------------
# HTTP surface
# ---------------------------------------------------------------------------


def test_http_submit_poll_output(tmp_path):
    app = ServingApp(_fake_client(), batch_dir=str(tmp_path))

    async def scenario():
        async with _asgi(app) as c:
            r = await c.post("/v1/batches", content=_jsonl(4))
            assert r.status_code == 200
            wire = r.json()
            assert wire["object"] == "batch"
            assert wire["status"] in ("queued", "in_progress")
            assert wire["request_counts"]["total"] == 4
            final = await _poll_terminal(c, wire["id"])
            assert final["status"] == "completed"
            assert final["request_counts"] == {
                "total": 4, "completed": 4, "failed": 0,
            }
            out = await c.get(f"/v1/batches/{wire['id']}/output")
            assert out.status_code == 200
            assert out.headers["content-type"] == "application/jsonl"
            records = [json.loads(l) for l in out.content.splitlines()]
            assert [r["custom_id"] for r in records] == [
                "c0", "c1", "c2", "c3",
            ]
            assert all(r["response"]["status_code"] == 200 for r in records)
            assert all(r["id"].startswith("batch_req_") for r in records)
            # health carries the per-job section
            h = await c.get("/healthz")
            assert wire["id"] in h.json()["batch"]["jobs"]

    _run(scenario())
    app.drain()


def test_http_unknown_job_404_and_wrong_method_405(tmp_path):
    app = ServingApp(_fake_client(), batch_dir=str(tmp_path))

    async def scenario():
        async with _asgi(app) as c:
            r = await c.get("/v1/batches/batch_nope")
            assert r.status_code == 404
            assert r.json()["error"]["code"] == "not_found"
            # Known path, wrong method: 405 with the Allow header derived
            # from the route table, not a bare 404.
            r = await c.get("/v1/batches")
            assert r.status_code == 405
            assert r.headers["allow"] == "POST"
            r = await c.post("/healthz")
            assert r.status_code == 405
            assert r.headers["allow"] == "GET"
            # Truly unknown path is still 404.
            r = await c.get("/v1/nope")
            assert r.status_code == 404

    _run(scenario())
    app.drain()


def test_http_output_conflict_before_terminal(tmp_path):
    """GET output on a known, unfinished job is 409 — never a partial file."""
    client = _fake_client()
    gate = threading.Event()
    inner = client.chat.completions.create

    def gated(**kwargs):
        assert gate.wait(30)
        return inner(**kwargs)

    client.chat.completions.create = gated
    app = ServingApp(client, batch_dir=str(tmp_path))

    async def scenario():
        async with _asgi(app) as c:
            r = await c.post("/v1/batches", content=_jsonl(2))
            jid = r.json()["id"]
            out = await c.get(f"/v1/batches/{jid}/output")
            assert out.status_code == 409
            assert out.json()["error"]["code"] == "batch_not_finished"
            gate.set()
            await _poll_terminal(c, jid)
            out = await c.get(f"/v1/batches/{jid}/output")
            assert out.status_code == 200

    _run(scenario())
    app.drain()


def test_http_cancel(tmp_path):
    client = _fake_client()
    gate = threading.Event()
    inner = client.chat.completions.create

    def gated(**kwargs):
        assert gate.wait(30)
        return inner(**kwargs)

    client.chat.completions.create = gated
    app = ServingApp(client, batch_dir=str(tmp_path))

    async def scenario():
        async with _asgi(app) as c:
            r = await c.post("/v1/batches", content=_jsonl(3))
            jid = r.json()["id"]
            r = await c.post(f"/v1/batches/{jid}/cancel")
            assert r.status_code == 200
            assert r.json()["status"] == "cancelled"
            gate.set()
            # Cancelled is terminal; the (possibly partial) output exists.
            out = await c.get(f"/v1/batches/{jid}/output")
            assert out.status_code == 200

    _run(scenario())
    app.drain()


def test_http_submit_rejects_bad_jsonl(tmp_path):
    app = ServingApp(_fake_client(), batch_dir=str(tmp_path))

    async def scenario():
        async with _asgi(app) as c:
            r = await c.post("/v1/batches", content=b"not json\n")
            assert r.status_code == 400
            assert "line 1" in r.json()["error"]["message"]
            r = await c.post("/v1/batches", content=b"")
            assert r.status_code == 400
            r = await c.post("/v1/batches", content=json.dumps({
                "custom_id": "x", "method": "GET", "url": "/v1/embeddings",
                "body": {"messages": [{"role": "user", "content": "hi"}]},
            }).encode())
            assert r.status_code == 400
            r = await c.post("/v1/batches", content=json.dumps({
                "body": {"messages": []},
            }).encode())
            assert r.status_code == 400
            assert r.json()["error"]["param"] == "messages"

    _run(scenario())
    app.drain()


# ---------------------------------------------------------------------------
# Error capture, crash containment, drain/recover
# ---------------------------------------------------------------------------


def test_typed_error_captured_into_output(tmp_path):
    """A poisoned item fails alone: its typed wire error becomes an output
    record and the job completes with errors."""
    client = _fake_client()
    inner = client.chat.completions.create

    def flaky(**kwargs):
        if "poison" in kwargs["messages"][-1]["content"]:
            raise InvalidRequestError("poisoned item", param="messages")
        return inner(**kwargs)

    client.chat.completions.create = flaky
    store = JobStore(tmp_path)
    lane = BatchLane(client, store, max_in_flight=2)
    body = b"\n".join([
        json.dumps({"body": {
            "messages": [{"role": "user", "content": "fine"}], "seed": 1,
        }}).encode(),
        json.dumps({"body": {
            "messages": [{"role": "user", "content": "poison"}], "seed": 2,
        }}).encode(),
        json.dumps({"body": {
            "messages": [{"role": "user", "content": "also fine"}], "seed": 3,
        }}).encode(),
    ])
    wire = lane.submit(body, tenant="default")
    assert lane.wait_idle(30), lane.health()
    final = lane.job_wire(wire["id"])
    assert final["status"] == "completed_with_errors"
    assert final["request_counts"] == {"total": 3, "completed": 2, "failed": 1}
    records = [
        json.loads(l) for l in lane.output_bytes(wire["id"]).splitlines()
    ]
    assert records[1]["response"] is None
    assert records[1]["error"]["status_code"] == 400
    assert records[1]["error"]["type"] == "invalid_request_error"
    assert records[0]["error"] is None and records[2]["error"] is None
    lane.close()


def test_worker_crash_contained_and_job_completes(tmp_path):
    """The batch.worker crash failpoint kills a worker thread after dequeue,
    BEFORE mark-started: the item is checkpointed back to pending, a
    replacement worker spawns, and the job still completes exactly once."""
    before = BATCH_EVENTS.snapshot().get("batch.worker_crashes", 0)
    store = JobStore(tmp_path)
    lane = BatchLane(_fake_client(), store, max_in_flight=2)
    with fp.failpoints({"batch.worker": FailSpec(action="crash", times=1)}):
        wire = lane.submit(_jsonl(5), tenant="default")
        assert lane.wait_idle(30), lane.health()
    assert lane.job_wire(wire["id"])["status"] == "completed"
    assert BATCH_EVENTS.snapshot()["batch.worker_crashes"] == before + 1
    assert lane.health()["worker_respawns"] >= 1
    ids = [
        json.loads(l)["id"]
        for l in lane.output_bytes(wire["id"]).splitlines()
    ]
    assert len(ids) == 5 and len(set(ids)) == 5
    lane.close()


def test_drain_requeues_then_recovery_completes_exactly_once(tmp_path):
    """drain() checkpoints in-flight + pending items back to pending; the
    straggler's late commit converges (same segment path, same bytes); a
    fresh lane over the same store recovers and finishes the job with zero
    duplicate records."""
    client = _fake_client()
    gate = threading.Event()
    entered = threading.Event()
    inner = client.chat.completions.create

    def gated(**kwargs):
        entered.set()
        assert gate.wait(30)
        return inner(**kwargs)

    client.chat.completions.create = gated
    store = JobStore(tmp_path)
    lane = BatchLane(client, store, max_in_flight=1)
    wire = lane.submit(_jsonl(3), tenant="default")
    assert entered.wait(10)  # item 0 is in flight, blocked in create()
    lane.drain(timeout=0.3)  # too short for the blocked item: requeued
    job = store.job(wire["id"])
    assert job.items.count("pending") == 3  # all checkpointed
    # Release the straggler; its late commit lands in the segment anyway.
    gate.set()
    seg0 = tmp_path / "jobs" / wire["id"] / "out" / "00000.json"
    deadline = time.monotonic() + 10
    while not seg0.exists() and time.monotonic() < deadline:
        time.sleep(0.02)
    assert seg0.exists()
    lane.close()

    # Restart: a fresh store + lane recover the journal and finish the rest.
    client.chat.completions.create = inner
    store2 = JobStore(tmp_path)
    lane2 = BatchLane(client, store2, max_in_flight=2)
    assert lane2.recover() == 1
    assert lane2.wait_idle(30), lane2.health()
    assert lane2.job_wire(wire["id"])["status"] == "completed"
    ids = [
        json.loads(l)["id"]
        for l in lane2.output_bytes(wire["id"]).splitlines()
    ]
    assert len(ids) == 3 and len(set(ids)) == 3
    lane2.close()


def test_batch_lane_runs_under_owner_batch_slo(tmp_path):
    """Items dispatch under the owning tenant's #batch lane context (batch
    SLO, shared quota buckets) when the backend carries a tenancy config."""
    seen = {}
    client = _fake_client()
    inner = client.chat.completions.create

    def spy(**kwargs):
        seen["tenant"] = kwargs.get("tenant")
        return inner(**kwargs)

    client.chat.completions.create = spy

    class _Tenancy:
        def batch_lane(self, owner):
            class _Ctx:
                name = f"{owner}#batch"
            return _Ctx()

    client.backend.tenancy = _Tenancy()
    lane = BatchLane(client, JobStore(tmp_path), max_in_flight=1)
    wire = lane.submit(_jsonl(1), tenant="acme")
    assert lane.wait_idle(30)
    assert seen["tenant"] == "acme#batch"
    assert lane.job_wire(wire["id"])["status"] == "completed"
    lane.close()


# ---------------------------------------------------------------------------
# SIGKILL recovery differential
# ---------------------------------------------------------------------------

_CHILD = r"""
import json, os, signal, sys, time

time.time = lambda: 1_700_000_000.0  # frozen wall clock: byte-parity outputs

root, mode = sys.argv[1], sys.argv[2]

from k_llms_tpu import KLLMs
from k_llms_tpu.backends.fake import FakeBackend
from k_llms_tpu.reliability.jobstore import JobStore
from k_llms_tpu.serving.batch import BatchLane

client = KLLMs(backend=FakeBackend(), model="fake-model")
store = JobStore(root)
lane = BatchLane(client, store, max_in_flight=1)
jid_file = os.path.join(root, "jid.txt")
if os.path.exists(jid_file):
    jid = open(jid_file).read().strip()
    lane.recover()
else:
    body = "\n".join(
        json.dumps({"custom_id": "c%d" % i, "body": {
            "messages": [{"role": "user", "content": "question %d" % i}],
            "n": 1, "seed": 1000 + i}})
        for i in range(6)
    ).encode()
    jid = lane.submit(body, tenant="default")["id"]
    with open(jid_file, "w") as fh:
        fh.write(jid)

if mode == "run":
    ok = lane.wait_idle(90)
    status = store.job(jid).status
    lane.close()
    sys.exit(0 if ok and status == "completed" else 3)

kill_after = int(mode)
outdir = os.path.join(root, "jobs", jid, "out")
deadline = time.monotonic() + 90
while time.monotonic() < deadline:
    done = len([f for f in os.listdir(outdir) if f.endswith(".json")])
    if done >= kill_after:
        os.kill(os.getpid(), signal.SIGKILL)  # no cleanup, no atexit
    time.sleep(0.005)
sys.exit(4)
"""


def _child(script, root, mode):
    return subprocess.run(
        [sys.executable, str(script), str(root), mode],
        cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": str(REPO)},
        capture_output=True,
        text=True,
        timeout=180,
    )


@pytest.mark.duration_budget(30)
def test_sigkill_recovery_output_byte_identical(tmp_path):
    script = tmp_path / "child.py"
    script.write_text(_CHILD)

    baseline_root = tmp_path / "baseline"
    baseline_root.mkdir()
    proc = _child(script, baseline_root, "run")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    jid = (baseline_root / "jid.txt").read_text().strip()
    baseline = (baseline_root / "jobs" / jid / "output.jsonl").read_bytes()
    assert len(baseline.splitlines()) == 6

    for kill_after in (0, 2):
        root = tmp_path / f"kill{kill_after}"
        root.mkdir()
        proc = _child(script, root, str(kill_after))
        # The child SIGKILLed itself mid-job: no flush, no atexit, the
        # hardest crash shape the OS offers.
        assert proc.returncode == -signal.SIGKILL, (
            proc.returncode, proc.stdout, proc.stderr,
        )
        proc = _child(script, root, "run")
        assert proc.returncode == 0, proc.stdout + proc.stderr
        jid2 = (root / "jid.txt").read_text().strip()
        recovered = (root / "jobs" / jid2 / "output.jsonl").read_bytes()
        assert recovered == baseline, f"kill_after={kill_after}"
        ids = [json.loads(l)["id"] for l in recovered.splitlines()]
        assert len(ids) == len(set(ids)) == 6


# ---------------------------------------------------------------------------
# Lint gate over the new modules
# ---------------------------------------------------------------------------


@pytest.mark.duration_budget(30)
def test_batch_modules_lint_clean():
    """kllms-check stays at zero findings over the batch modules: counter
    hygiene (BATCH_EVENTS literals), failpoint coverage (batch.store /
    batch.worker), and guarded-by on the new locks."""
    from k_llms_tpu.analysis.framework import (
        load_project, run_rules, unsuppressed,
    )

    project = load_project(REPO)
    findings = unsuppressed(run_rules(project))
    mine = [
        f for f in findings
        if "serving/batch.py" in f.file
        or "serving/app.py" in f.file
        or "reliability/jobstore.py" in f.file
        or "reliability/failpoints.py" in f.file
    ]
    assert not mine, "\n".join(f.format() for f in mine)
