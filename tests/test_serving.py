"""Wire-surface tests for the HTTP front door (k_llms_tpu/serving/).

The in-process tier runs the ASGI app under httpx.ASGITransport — no sockets,
byte-level assertions against the same client library the server wraps. The
socket tier stands up the stdlib HTTP/1.1 runner (ServerThread) on loopback.
No pytest-asyncio in the image: async test bodies run via asyncio.run().
"""

import asyncio
import json
import time

import httpx
import pytest

from k_llms_tpu import KLLMs
from k_llms_tpu.backends.fake import FakeBackend
from k_llms_tpu.reliability import failpoints as fp
from k_llms_tpu.reliability.failpoints import FailSpec
from k_llms_tpu.serving import ServerThread, ServingApp
from k_llms_tpu.serving.sse import parse_stream
from k_llms_tpu.types.wire import (
    BackendUnavailableError,
    RateLimitError,
    RequestTimeoutError,
    ServerDrainingError,
)
from k_llms_tpu.utils.observability import FAILURE_EVENTS, SERVE_EVENTS, STREAM_EVENTS


def _fake_client(responses=None):
    return KLLMs(
        backend=FakeBackend(responses or ["alpha beta gamma", "alpha beta", "delta"]),
        model="fake-model",
    )


def _asgi(app):
    return httpx.AsyncClient(
        transport=httpx.ASGITransport(app=app), base_url="http://testserver"
    )


def _run(coro):
    return asyncio.run(coro)


BODY = {
    "messages": [{"role": "user", "content": "say something"}],
    "model": "fake-model",
    "n": 3,
    "seed": 11,
}


# -- in-process: non-stream ------------------------------------------------
def test_nonstream_byte_parity_with_inprocess_create(monkeypatch):
    """The wire bytes of stream=false must be exactly the client library's
    model_dump of the same call — the HTTP layer adds nothing and loses
    nothing. `created` is frozen so both paths see one clock."""
    client = _fake_client()
    app = ServingApp(client)
    frozen = int(time.time())
    monkeypatch.setattr(time, "time", lambda: frozen)

    async def go():
        async with _asgi(app) as c:
            return await c.post("/v1/chat/completions", json=BODY)

    wire = _run(go())
    assert wire.status_code == 200
    direct = _fake_client().chat.completions.create(**BODY)
    assert wire.content == json.dumps(
        direct.model_dump(mode="json"), separators=(",", ":")
    ).encode()


def test_nonstream_consensus_shape():
    app = ServingApp(_fake_client())

    async def go():
        async with _asgi(app) as c:
            return await c.post("/v1/chat/completions", json=BODY)

    payload = _run(go()).json()
    assert payload["object"] == "chat.completion"
    assert len(payload["choices"]) == BODY["n"] + 1  # consensus + samples
    assert payload["choices"][0]["index"] == 0
    assert payload["likelihoods"]


# -- in-process: SSE -------------------------------------------------------
def test_sse_event_ordering_and_final_consensus():
    app = ServingApp(_fake_client())

    async def go():
        async with _asgi(app) as c:
            return await c.post(
                "/v1/chat/completions", json={**BODY, "stream": True}
            )

    resp = _run(go())
    assert resp.status_code == 200
    assert resp.headers["content-type"].startswith("text/event-stream")
    events = list(parse_stream(resp.content))
    assert events[-1] == ("done", None)
    datas = [d for kind, d in events if kind == "data"]
    chunks = [d for d in datas if d["object"] == "chat.completion.chunk"]
    finals = [d for d in datas if d["object"] == "chat.completion"]
    assert len(finals) == 1
    # Ordering: every chunk precedes the single final consensus event.
    assert datas.index(finals[0]) == len(datas) - 1
    # Per-sample streams: wire choice indices 1..n, each with >=1 content
    # delta and role on the FIRST delta only.
    per_sample = {}
    for ch in chunks:
        c = ch["choices"][0]
        per_sample.setdefault(c["index"], []).append(c["delta"])
    assert set(per_sample) >= {1, 2, 3}
    for idx in (1, 2, 3):
        deltas = per_sample[idx]
        assert deltas[0].get("role") == "assistant"
        assert all("role" not in d for d in deltas[1:])
    # Streamed text reassembles to the final per-sample choices.
    final = finals[0]
    for idx in (1, 2, 3):
        text = "".join(d.get("content") or "" for d in per_sample[idx])
        assert text == final["choices"][idx]["message"]["content"]
    # Final consensus event is consolidated: choices[0] + likelihoods.
    assert final["choices"][0]["index"] == 0
    assert final["likelihoods"]


def test_stream_counters_move():
    app = ServingApp(_fake_client())
    before = STREAM_EVENTS.snapshot()

    async def go():
        async with _asgi(app) as c:
            await c.post("/v1/chat/completions", json={**BODY, "stream": True})

    _run(go())
    after = STREAM_EVENTS.snapshot()
    assert after.get("streams.opened", 0) > before.get("streams.opened", 0)
    assert after.get("streams.completed", 0) > before.get("streams.completed", 0)
    assert after.get("tokens.streamed", 0) > before.get("tokens.streamed", 0)


# -- error mapping ---------------------------------------------------------
class _ErrorBackend(FakeBackend):
    def __init__(self, exc):
        super().__init__(["x"])
        self._exc = exc

    def chat_completion(self, request):
        raise self._exc


@pytest.mark.parametrize(
    "exc,status",
    [
        (RateLimitError("queue full", retry_after=7.0), 429),
        (ServerDrainingError("draining"), 503),
        (BackendUnavailableError("engine down"), 503),
        (RequestTimeoutError("deadline exceeded"), 408),
    ],
)
def test_typed_wire_errors_map_to_http(exc, status):
    app = ServingApp(KLLMs(backend=_ErrorBackend(exc), model="m"))

    async def go():
        async with _asgi(app) as c:
            return await c.post("/v1/chat/completions", json=BODY)

    resp = _run(go())
    assert resp.status_code == status
    err = resp.json()["error"]
    assert err["message"]
    assert err["type"] == exc.as_wire()["error"]["type"]
    if isinstance(exc, RateLimitError):
        assert resp.headers["retry-after"] == "7"


def test_bad_json_and_missing_messages_are_400():
    app = ServingApp(_fake_client())

    async def go():
        async with _asgi(app) as c:
            r1 = await c.post("/v1/chat/completions", content=b"{nope")
            r2 = await c.post("/v1/chat/completions", json={"messages": []})
            r3 = await c.get("/unknown/route")
            return r1, r2, r3

    r1, r2, r3 = _run(go())
    assert r1.status_code == 400
    assert r1.json()["error"]["type"] == "invalid_request_error"
    assert r2.status_code == 400
    assert r2.json()["error"]["param"] == "messages"
    assert r3.status_code == 404


def test_stream_unsupported_backend_is_typed_400():
    """A non-streaming backend yields the OpenAI-shaped invalid_request_error
    with param=stream — in-process (raise) and over the wire (400)."""

    class NoStream(FakeBackend):
        supports_streaming = False

    client = KLLMs(backend=NoStream(["x"]), model="m")
    from k_llms_tpu.types.wire import InvalidRequestError

    with pytest.raises(InvalidRequestError) as ei:
        client.chat.completions.create(**BODY, stream=True)
    assert ei.value.param == "stream"
    assert ei.value.status_code == 400

    app = ServingApp(client)

    async def go():
        async with _asgi(app) as c:
            return await c.post(
                "/v1/chat/completions", json={**BODY, "stream": True}
            )

    resp = _run(go())
    assert resp.status_code == 400
    assert resp.json()["error"]["param"] == "stream"


def test_parse_rejects_stream():
    from pydantic import BaseModel

    from k_llms_tpu.types.wire import InvalidRequestError

    class Out(BaseModel):
        x: int

    client = _fake_client()
    with pytest.raises(InvalidRequestError):
        client.chat.completions.parse(
            messages=BODY["messages"], response_format=Out, stream=True
        )


# -- healthz / metrics -----------------------------------------------------
def test_healthz_and_metrics_fake():
    app = ServingApp(_fake_client())

    async def go():
        async with _asgi(app) as c:
            h = await c.get("/healthz")
            m = await c.get("/metrics")
            return h, m

    h, m = _run(go())
    assert h.status_code == 200
    assert m.status_code == 200
    assert "kllms_serve_events_total" in m.text
    assert 'event="request.healthz.200"' in m.text


# -- serving.request failpoint --------------------------------------------
def test_serving_request_failpoint_raise_maps_to_500():
    app = ServingApp(_fake_client())

    async def go():
        async with _asgi(app) as c:
            return await c.post("/v1/chat/completions", json=BODY)

    with fp.failpoints({"serving.request": FailSpec(action="raise", times=1)}):
        resp = _run(go())
    assert resp.status_code == 500
    # Next request is clean (times=1 consumed).
    assert _run(go()).status_code == 200


def test_serving_request_disconnect_failpoint_truncates_stream():
    """KLLMS_FAILPOINTS='serving.request=disconnect:1' semantics: the server
    drops the response after the first delta exactly as if the client hung up,
    and the stream's budget is cancelled."""
    app = ServingApp(_fake_client())

    async def go():
        async with _asgi(app) as c:
            return await c.post(
                "/v1/chat/completions", json={**BODY, "stream": True}
            )

    before = SERVE_EVENTS.snapshot().get("request.disconnect", 0)
    with fp.failpoints({"serving.request": FailSpec(action="disconnect", times=1)}):
        resp = _run(go())
    events = list(parse_stream(resp.content))
    datas = [d for kind, d in events if kind == "data"]
    # Truncated: deltas only — no final consensus event, no [DONE].
    assert all(d["object"] == "chat.completion.chunk" for d in datas)
    assert ("done", None) not in events
    assert SERVE_EVENTS.snapshot()["request.disconnect"] == before + 1


def test_serving_request_disconnect_parses_from_env():
    from k_llms_tpu.reliability import failpoints as _fpmod

    _fpmod.configure_from_env("serving.request=disconnect:2")
    try:
        spec = _fpmod._registry["serving.request"]
        assert spec.action == "disconnect"
        assert spec.times == 2
    finally:
        _fpmod.clear()


# -- TPU backend over the wire --------------------------------------------
def _tpu_client(**cfg):
    import jax
    from conftest import shared_engine

    from k_llms_tpu.backends.tpu import TpuBackend

    engine = (
        shared_engine("tiny", mesh_shape=(8, 1)) if len(jax.devices()) == 8 else None
    )
    backend = TpuBackend(model="tiny", max_new_tokens=12, engine=engine, **cfg)
    return KLLMs(backend=backend, model="tiny")


@pytest.fixture(scope="module")
def tpu_app():
    client = _tpu_client()
    yield ServingApp(client), client
    client.close()


def test_tpu_nonstream_byte_parity(tpu_app, monkeypatch):
    """Acceptance: non-stream JSON over the wire is byte-identical to the
    in-process client result for a pinned seed (deterministic ids + frozen
    clock; ASGITransport shares the process, so the same engine serves both)."""
    app, client = tpu_app
    body = {**BODY, "model": "tiny", "max_tokens": 8}
    frozen = int(time.time())
    monkeypatch.setattr(time, "time", lambda: frozen)

    async def go():
        async with _asgi(app) as c:
            return await c.post("/v1/chat/completions", json=body)

    wire = _run(go())
    assert wire.status_code == 200
    direct = client.chat.completions.create(**body)
    assert wire.content == json.dumps(
        direct.model_dump(mode="json"), separators=(",", ":")
    ).encode()


def test_tpu_stream_deltas_before_final(tpu_app):
    """Acceptance: stream=true over the TPU-CPU backend produces >=1 content
    delta for every live sample before the final consensus event."""
    app, _ = tpu_app
    body = {**BODY, "model": "tiny", "n": 2, "max_tokens": 8, "stream": True}

    async def go():
        async with _asgi(app) as c:
            return await c.post("/v1/chat/completions", json=body)

    resp = _run(go())
    assert resp.status_code == 200
    datas = [d for kind, d in parse_stream(resp.content) if kind == "data"]
    finals = [d for d in datas if d["object"] == "chat.completion"]
    assert len(finals) == 1 and datas[-1] is finals[0]
    seen = set()
    for d in datas[:-1]:
        c = d["choices"][0]
        if c["delta"].get("content"):
            seen.add(c["index"])
    assert seen >= {1, 2}


def test_tpu_healthz_lifecycle(tpu_app):
    """healthz follows the scheduler lifecycle: 200 while READY, 503 after
    drain(). Runs last-ish in this module's fixture lifetime — it kills the
    module-scoped backend, so it builds its own."""
    client = _tpu_client()
    app = ServingApp(client)

    async def go(path="/healthz"):
        async with _asgi(app) as c:
            return await c.get(path)

    r = _run(go())
    assert r.status_code == 200
    assert r.json()["state"] == "ready"
    client.backend.drain(timeout=30)
    r = _run(go())
    assert r.status_code == 503
    assert r.json()["state"] in ("draining", "stopped")
    # Post-drain chat requests get the typed 503, not a hang.
    async def chat():
        async with _asgi(app) as c:
            return await c.post(
                "/v1/chat/completions", json={**BODY, "model": "tiny"}
            )

    resp = _run(chat())
    assert resp.status_code == 503
    client.close()


# -- real socket -----------------------------------------------------------
def test_real_socket_smoke():
    client = _fake_client()
    with ServerThread(ServingApp(client)) as srv:
        h = httpx.get(srv.base_url + "/healthz", timeout=10)
        assert h.status_code == 200
        r = httpx.post(
            srv.base_url + "/v1/chat/completions", json=BODY, timeout=30
        )
        assert r.status_code == 200
        assert len(r.json()["choices"]) == BODY["n"] + 1
        with httpx.stream(
            "POST",
            srv.base_url + "/v1/chat/completions",
            json={**BODY, "stream": True},
            timeout=30,
        ) as resp:
            assert resp.status_code == 200
            raw = b"".join(resp.iter_raw())
        events = list(parse_stream(raw))
        assert events[-1] == ("done", None)
        assert any(
            d["object"] == "chat.completion" for kind, d in events if kind == "data"
        )


def test_real_socket_sse_keepalive_pings_during_idle_gap():
    """Keep-alive contract (PR 13): when the backend goes quiet longer than
    ``sse_ping_interval_s``, the stream emits ``: ping`` comment frames so
    idle-timeout proxies don't sever a healthy long decode — and the SSE
    parser treats them as invisible (comment lines, not events)."""
    from types import SimpleNamespace

    client = _fake_client()
    backend = client.backend
    backend.backend_config = SimpleNamespace(sse_ping_interval_s=0.15)
    orig = backend.chat_completion_stream

    def slow_stream(request, emit):
        time.sleep(0.7)  # idle gap before the first delta: ~4 ping windows
        return orig(request, emit)

    backend.chat_completion_stream = slow_stream
    pings_before = STREAM_EVENTS.snapshot().get("streams.pings", 0)
    with ServerThread(ServingApp(client)) as srv:
        with httpx.stream(
            "POST",
            srv.base_url + "/v1/chat/completions",
            json={**BODY, "stream": True},
            timeout=30,
        ) as resp:
            assert resp.status_code == 200
            raw = b"".join(resp.iter_raw())
    assert raw.count(b": ping\n\n") >= 2
    assert STREAM_EVENTS.snapshot()["streams.pings"] >= pings_before + 2
    # Comment frames are transparent to consumers: the event stream parses
    # exactly as if they were never sent.
    events = list(parse_stream(raw))
    assert events[-1] == ("done", None)
    assert any(
        d["object"] == "chat.completion" for kind, d in events if kind == "data"
    )
    client.close()


@pytest.mark.slow
def test_real_socket_tpu_stream_and_disconnect_soak():
    """Acceptance soak: a real-socket client that drops the TCP connection
    mid-stream cancels the decode (engine.decode_abort moves), the scheduler
    ends READY, and no futures are left hung — repeated to shake out races."""
    client = _tpu_client(continuous_batching=True, continuous_width=4,
                         continuous_max_prompt=128, continuous_max_new=64)
    backend = client.backend
    with ServerThread(ServingApp(client)) as srv:
        # Clean stream first: >=1 delta per live sample before the final.
        body = {**BODY, "model": "tiny", "n": 2, "max_tokens": 12, "stream": True}
        with httpx.stream(
            "POST", srv.base_url + "/v1/chat/completions", json=body, timeout=120
        ) as resp:
            raw = b"".join(resp.iter_raw())
        datas = [d for kind, d in parse_stream(raw) if kind == "data"]
        assert datas[-1]["object"] == "chat.completion"
        streamed = {
            d["choices"][0]["index"]
            for d in datas[:-1]
            if d["choices"][0]["delta"].get("content")
        }
        assert streamed >= {1, 2}

        aborts_before = FAILURE_EVENTS.snapshot().get("engine.decode_abort", 0)
        for trial in range(5):
            body = {
                **BODY, "model": "tiny", "n": 2, "max_tokens": 48,
                "seed": 100 + trial, "stream": True,
            }
            try:
                with httpx.stream(
                    "POST",
                    srv.base_url + "/v1/chat/completions",
                    json=body,
                    timeout=120,
                ) as resp:
                    # Read just the first frame, then slam the connection shut.
                    for _chunk in resp.iter_raw():
                        break
            except httpx.HTTPError:
                pass
            # Give the server's EOF watcher + abort poller time to land.
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                if (
                    FAILURE_EVENTS.snapshot().get("engine.decode_abort", 0)
                    > aborts_before + trial
                ):
                    break
                time.sleep(0.1)
        aborts_after = FAILURE_EVENTS.snapshot().get("engine.decode_abort", 0)
        assert aborts_after > aborts_before, (
            "mid-stream disconnects never aborted the decode "
            f"({aborts_before} -> {aborts_after})"
        )
        # The loop and scheduler both quiesce: no hung slot rows, no queued
        # futures, lifecycle back to READY.
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            cont = backend._continuous
            idle = cont is None or (
                not cont._queue and all(r is None for r in cont._active)
            )
            snap = backend.scheduler.health()
            if idle and snap["queue_depth"] == 0 and snap["in_flight"] == 0:
                break
            time.sleep(0.1)
        snap = backend.scheduler.health()
        assert snap["state"] == "ready"
        assert snap["queue_depth"] == 0 and snap["in_flight"] == 0
        cont = backend._continuous
        assert not cont._queue and all(r is None for r in cont._active)
    # ServerThread.stop drains the backend on exit; a follow-up request now
    # gets the typed 503 rather than hanging.
    with pytest.raises((ServerDrainingError, BackendUnavailableError)):
        client.chat.completions.create(**{**BODY, "model": "tiny"})
