"""Architecture variants beyond Llama: Qwen2 (QKV biases) and Mistral
(sliding-window attention). The reference supports every model the OpenAI API
hosts; the local engine covers the open-weight families the same way — one
transformer program parameterized by ModelConfig."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k_llms_tpu.engine.engine import LocalEngine
from k_llms_tpu.engine.tokenizer import ByteTokenizer
from k_llms_tpu.models import get_config, init_params
from k_llms_tpu.models.llama import decode_step, forward, init_cache, prefill

TINY_QWEN = get_config("tiny").with_(name="tiny-qwen", qkv_bias=True)
TINY_MISTRAL = get_config("tiny").with_(name="tiny-mistral", sliding_window=6)


def test_registry_has_new_families():
    for name in ("qwen2-7b", "qwen2.5-0.5b", "mistral-7b"):
        cfg = get_config(name)
        assert cfg.vocab_size > 0
    assert get_config("qwen2-7b").qkv_bias
    assert get_config("mistral-7b").sliding_window == 4096


def test_qkv_bias_params_and_effect():
    params = init_params(TINY_QWEN, jax.random.key(0))
    assert params["layers"]["bq"].shape == (TINY_QWEN.num_layers, TINY_QWEN.q_dim)

    tokens = jnp.array([[3, 4, 5, 6]], jnp.int32)
    mask = jnp.ones_like(tokens)
    base, _ = forward(TINY_QWEN, params, tokens, mask)

    # Nonzero biases must change the logits (they flow through attention).
    bumped = dict(params)
    bumped["layers"] = dict(params["layers"])
    bumped["layers"]["bq"] = params["layers"]["bq"] + 0.5
    moved, _ = forward(TINY_QWEN, bumped, tokens, mask)
    assert not np.allclose(np.asarray(base), np.asarray(moved))


def test_qwen_decode_matches_forward():
    params = init_params(TINY_QWEN, jax.random.key(1))
    S = 12
    tokens = jax.random.randint(jax.random.key(2), (1, S), 0, TINY_QWEN.vocab_size)
    prompt_len = jnp.int32(8)

    pl_logits, prefix = prefill(TINY_QWEN, params, tokens, prompt_len)
    full, _ = forward(
        TINY_QWEN, params, tokens, (jnp.arange(S)[None, :] < prompt_len).astype(jnp.int32)
    )
    np.testing.assert_allclose(pl_logits[0], full[0, 7], rtol=1e-5, atol=1e-5)

    # Step-by-step decode must carry the biases through the cached path too.
    n = 2
    gen_cache = init_cache(TINY_QWEN, n, 4)
    for step in range(3):
        tk = jnp.broadcast_to(tokens[0, 8 + step], (n,))
        logits, gen_cache = decode_step(
            TINY_QWEN, params, tk, jnp.int32(step), prompt_len, gen_cache, prefix
        )
        full_s, _ = forward(
            TINY_QWEN, params, tokens, (jnp.arange(S)[None, :] < 9 + step).astype(jnp.int32)
        )
        np.testing.assert_allclose(logits[0], full_s[0, 8 + step], rtol=1e-4, atol=1e-4)


def test_sliding_window_equals_dense_when_window_covers_seq():
    cfg_wide = get_config("tiny").with_(sliding_window=64)
    cfg_dense = get_config("tiny")
    params = init_params(cfg_dense, jax.random.key(3))
    tokens = jax.random.randint(jax.random.key(4), (1, 10), 0, cfg_dense.vocab_size)
    mask = jnp.ones_like(tokens)
    a, _ = forward(cfg_wide, params, tokens, mask)
    b, _ = forward(cfg_dense, params, tokens, mask)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5)


def test_sliding_window_restricts_attention():
    cfg = get_config("tiny").with_(sliding_window=3)
    dense = get_config("tiny")
    params = init_params(dense, jax.random.key(5))
    S = 16
    tokens = jax.random.randint(jax.random.key(6), (1, S), 0, dense.vocab_size)
    mask = jnp.ones_like(tokens)
    win, _ = forward(cfg, params, tokens, mask)
    full, _ = forward(dense, params, tokens, mask)
    # Early positions (inside the window) agree; late positions diverge.
    np.testing.assert_allclose(np.asarray(win[0, 1]), np.asarray(full[0, 1]), rtol=1e-5, atol=1e-5)
    assert not np.allclose(np.asarray(win[0, -1]), np.asarray(full[0, -1]))


def test_mistral_decode_matches_forward():
    """Windowed decode over the shared prefix must reproduce the windowed full
    forward — masks on the gen-cache and prefix sides line up with positions."""
    cfg = TINY_MISTRAL  # window 6 < prompt_len+steps: exercises both boundaries
    params = init_params(cfg, jax.random.key(7))
    S = 16
    tokens = jax.random.randint(jax.random.key(8), (1, S), 0, cfg.vocab_size)
    prompt_len = jnp.int32(10)

    pl_logits, prefix = prefill(cfg, params, tokens, prompt_len)
    full, _ = forward(
        cfg, params, tokens, (jnp.arange(S)[None, :] < prompt_len).astype(jnp.int32)
    )
    np.testing.assert_allclose(pl_logits[0], full[0, 9], rtol=1e-5, atol=1e-5)

    n = 2
    gen_cache = init_cache(cfg, n, 4)
    for step in range(3):
        tk = jnp.broadcast_to(tokens[0, 10 + step], (n,))
        logits, gen_cache = decode_step(
            cfg, params, tk, jnp.int32(step), prompt_len, gen_cache, prefix
        )
        full_s, _ = forward(
            cfg, params, tokens, (jnp.arange(S)[None, :] < 11 + step).astype(jnp.int32)
        )
        np.testing.assert_allclose(
            logits[0], full_s[0, 10 + step], rtol=1e-4, atol=1e-4
        )


def test_engine_generate_qwen_and_mistral():
    tok = ByteTokenizer()
    ids = tok.apply_chat_template([{"role": "user", "content": "family check"}])
    for cfg in (TINY_QWEN, TINY_MISTRAL):
        engine = LocalEngine(cfg, use_mesh=False)
        r = engine.generate(ids, n=3, max_new_tokens=6, temperature=1.0, seed=0)
        assert r.tokens.shape == (3, 6)


def test_engine_generate_qwen_sharded_and_quantized():
    if len(jax.devices()) < 4:
        pytest.skip("needs the 8-device CPU mesh")
    from k_llms_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(2, 2, jax.devices()[:4])
    engine = LocalEngine(TINY_QWEN, mesh=mesh, quantize=True)
    tok = ByteTokenizer()
    ids = tok.apply_chat_template([{"role": "user", "content": "sharded qwen"}])
    r = engine.generate(ids, n=4, max_new_tokens=6, seed=2)
    assert r.tokens.shape == (4, 6)


def test_config_from_hf_families(tmp_path):
    import json

    from k_llms_tpu.models.loader import config_from_hf

    qwen = {
        "model_type": "qwen2",
        "vocab_size": 151936,
        "hidden_size": 896,
        "intermediate_size": 4864,
        "num_hidden_layers": 24,
        "num_attention_heads": 14,
        "num_key_value_heads": 2,
        "rope_theta": 1000000.0,
        "rms_norm_eps": 1e-6,
        "max_position_embeddings": 32768,
        "sliding_window": 131072,
        "use_sliding_window": False,
        "bos_token_id": 151643,
        "eos_token_id": 151645,
    }
    d = tmp_path / "qwen"
    d.mkdir()
    (d / "config.json").write_text(json.dumps(qwen))
    cfg = config_from_hf(str(d))
    assert cfg.qkv_bias and cfg.sliding_window is None
    assert cfg.rope_theta == 1000000.0

    mistral = {
        "model_type": "mistral",
        "vocab_size": 32000,
        "hidden_size": 4096,
        "intermediate_size": 14336,
        "num_hidden_layers": 32,
        "num_attention_heads": 32,
        "num_key_value_heads": 8,
        "rope_theta": 10000.0,
        "rms_norm_eps": 1e-5,
        "max_position_embeddings": 32768,
        "sliding_window": 4096,
        "bos_token_id": 1,
        "eos_token_id": 2,
    }
    d2 = tmp_path / "mistral"
    d2.mkdir()
    (d2 / "config.json").write_text(json.dumps(mistral))
    cfg2 = config_from_hf(str(d2))
    assert not cfg2.qkv_bias and cfg2.sliding_window == 4096


def test_safetensors_import_with_bias(tmp_path):
    from safetensors.numpy import save_file

    from k_llms_tpu.models.loader import load_safetensors

    cfg = TINY_QWEN.with_(dtype="float32")
    params = init_params(cfg, jax.random.key(9))
    # Give the biases real values so the round-trip is meaningful.
    params["layers"]["bq"] = jax.random.normal(
        jax.random.key(10), params["layers"]["bq"].shape, jnp.float32
    )

    tensors = {
        "model.embed_tokens.weight": np.asarray(params["embed"]),
        "model.norm.weight": np.asarray(params["final_norm"]),
        "lm_head.weight": np.ascontiguousarray(np.asarray(params["lm_head"]).T),
    }
    hf_names = {
        "wq": "self_attn.q_proj",
        "wk": "self_attn.k_proj",
        "wv": "self_attn.v_proj",
        "wo": "self_attn.o_proj",
        "w_gate": "mlp.gate_proj",
        "w_up": "mlp.up_proj",
        "w_down": "mlp.down_proj",
    }
    for i in range(cfg.num_layers):
        for ours, hf in hf_names.items():
            tensors[f"model.layers.{i}.{hf}.weight"] = np.ascontiguousarray(
                np.asarray(params["layers"][ours][i]).T
            )
        for ours, hf in (("bq", "q_proj"), ("bk", "k_proj"), ("bv", "v_proj")):
            tensors[f"model.layers.{i}.self_attn.{hf}.bias"] = np.asarray(
                params["layers"][ours][i]
            )
        tensors[f"model.layers.{i}.input_layernorm.weight"] = np.asarray(
            params["layers"]["attn_norm"][i]
        )
        tensors[f"model.layers.{i}.post_attention_layernorm.weight"] = np.asarray(
            params["layers"]["mlp_norm"][i]
        )
    ckpt = tmp_path / "hf-qwen"
    ckpt.mkdir()
    save_file(tensors, str(ckpt / "model.safetensors"))

    loaded = load_safetensors(str(ckpt), cfg, dtype=jnp.float32)
    np.testing.assert_allclose(
        np.asarray(loaded["layers"]["bq"]), np.asarray(params["layers"]["bq"])
    )
    tokens = jax.random.randint(jax.random.key(11), (1, 8), 0, cfg.vocab_size)
    mask = jnp.ones_like(tokens)
    a, _ = forward(cfg, params, tokens, mask)
    b, _ = forward(cfg, loaded, tokens, mask)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5)
