"""Prompt-prefix KV cache: exact hits, suffix-only continuation prefill, LRU
eviction — all bit-equal to the uncached engine."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k_llms_tpu.engine.engine import LocalEngine
from k_llms_tpu.models import get_config, init_params

SYSTEM = [int(x) for x in jax.random.randint(jax.random.key(0), (48,), 5, 200)]
DOC_A = [int(x) for x in jax.random.randint(jax.random.key(1), (20,), 5, 200)]
DOC_B = [int(x) for x in jax.random.randint(jax.random.key(2), (25,), 5, 200)]


def _engines(cfg_overrides=None, **engine_kwargs):
    cfg = get_config("tiny")
    if cfg_overrides:
        cfg = cfg.with_(**cfg_overrides)
    params = init_params(cfg, jax.random.key(3))
    plain = LocalEngine(cfg, params=params, use_mesh=False)
    cached = LocalEngine(
        cfg, params=params, use_mesh=False,
        prefix_cache_size=4, prefix_cache_min_reuse=16, **engine_kwargs,
    )
    return plain, cached


def test_exact_hit_skips_device_prefill():
    plain, cached = _engines()
    prompt = SYSTEM + DOC_A
    r1 = cached.generate(prompt, n=2, max_new_tokens=4, temperature=0.7, seed=5)
    assert cached.prefix_cache_stats == {"hits": 0, "partial_hits": 0, "misses": 1}
    r2 = cached.generate(prompt, n=2, max_new_tokens=4, temperature=0.7, seed=5)
    assert cached.prefix_cache_stats["hits"] == 1
    np.testing.assert_array_equal(r1.tokens, r2.tokens)
    # And identical to the uncached engine.
    ref = plain.generate(prompt, n=2, max_new_tokens=4, temperature=0.7, seed=5)
    np.testing.assert_array_equal(r1.tokens, ref.tokens)


def test_shared_system_prefix_continuation_matches_dense():
    """Second document reuses the first prompt's system-prefix KV; the
    generation must match the uncached engine exactly."""
    plain, cached = _engines()
    cached.generate(SYSTEM + DOC_A, n=2, max_new_tokens=4, temperature=0.7, seed=7)
    r_cached = cached.generate(SYSTEM + DOC_B, n=2, max_new_tokens=4, temperature=0.7, seed=8)
    assert cached.prefix_cache_stats["partial_hits"] == 1
    r_plain = plain.generate(SYSTEM + DOC_B, n=2, max_new_tokens=4, temperature=0.7, seed=8)
    np.testing.assert_array_equal(r_cached.tokens, r_plain.tokens)
    np.testing.assert_allclose(
        r_cached.logprobs, r_plain.logprobs, rtol=1e-4, atol=1e-4
    )


def test_below_reuse_threshold_takes_full_prefill():
    _, cached = _engines()
    cached.generate(SYSTEM + DOC_A, n=1, max_new_tokens=2, temperature=0.5, seed=1)
    # Only 8 common tokens (< min_reuse 16): full prefill, counted as a miss.
    cached.generate(SYSTEM[:8] + DOC_B, n=1, max_new_tokens=2, temperature=0.5, seed=1)
    assert cached.prefix_cache_stats["misses"] == 2
    assert cached.prefix_cache_stats["partial_hits"] == 0


def test_growing_chain_hit_accounting():
    """The bench protocol's accounting, pinned: a growing prompt chain costs
    one miss then partial hits only; exact repeats of the longest prompt are
    full hits (zero prefill device work)."""
    _, cached = _engines()
    base = SYSTEM + DOC_A
    chain = [base, base + DOC_B, base + DOC_B + DOC_A]
    for p in chain:
        cached.generate(p, n=1, max_new_tokens=2, temperature=0.0, seed=1)
    assert cached.prefix_cache_stats == {"hits": 0, "partial_hits": 2, "misses": 1}
    for _ in range(2):
        cached.generate(chain[-1], n=1, max_new_tokens=2, temperature=0.0, seed=1)
    assert cached.prefix_cache_stats == {"hits": 2, "partial_hits": 2, "misses": 1}


def test_lru_eviction_caps_entries():
    _, cached = _engines()
    cached.prefix_cache_size = 2
    for s in range(4):
        prompt = [100 + s] * 40  # four disjoint prompts
        cached.generate(prompt, n=1, max_new_tokens=2, temperature=0.5, seed=s)
    assert len(cached._prefix_entries) == 2


def test_prompt_that_is_prefix_of_cached_prompt():
    """A new prompt fully contained in a cached one still gets a correct
    continuation (common length is capped so >=1 suffix token remains)."""
    plain, cached = _engines()
    cached.generate(SYSTEM + DOC_A, n=1, max_new_tokens=3, temperature=0.6, seed=9)
    short = SYSTEM + DOC_A[:5]
    r_cached = cached.generate(short, n=1, max_new_tokens=3, temperature=0.6, seed=10)
    r_plain = plain.generate(short, n=1, max_new_tokens=3, temperature=0.6, seed=10)
    np.testing.assert_array_equal(r_cached.tokens, r_plain.tokens)


@pytest.mark.parametrize("overrides", [
    dict(sliding_window=16, sliding_window_layers="all"),
    dict(sliding_window=16, sliding_window_layers="alternating"),
    dict(attn_softcap=50.0, query_scale=0.125),
    dict(attention_impl="flash", sliding_window=16, sliding_window_layers="all"),
    dict(attention_impl="flash", sliding_window=16, sliding_window_layers="alternating"),
    dict(attention_impl="flash", attn_softcap=50.0, query_scale=0.125),
])
def test_continuation_matches_dense_on_windowed_and_softcap_configs(overrides):
    """The continuation path builds masks over absolute positions, so sliding
    windows and softcaps must agree with the dense prefill bit-for-bit."""
    plain, cached = _engines(cfg_overrides=overrides)
    cached.generate(SYSTEM + DOC_A, n=2, max_new_tokens=3, temperature=0.7, seed=21)
    r_c = cached.generate(SYSTEM + DOC_B, n=2, max_new_tokens=3, temperature=0.7, seed=22)
    assert cached.prefix_cache_stats["partial_hits"] == 1
    r_p = plain.generate(SYSTEM + DOC_B, n=2, max_new_tokens=3, temperature=0.7, seed=22)
    np.testing.assert_array_equal(r_c.tokens, r_p.tokens)


def test_prefix_cache_on_mesh():
    """Continuation prefill under a (4, 2) mesh matches the uncached result."""
    from k_llms_tpu.parallel.mesh import make_mesh

    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.key(3))
    mesh = make_mesh(4, 2)
    plain = LocalEngine(cfg, params=params, mesh=mesh)
    cached = LocalEngine(
        cfg, params=params, mesh=mesh, prefix_cache_size=4, prefix_cache_min_reuse=16
    )
    cached.generate(SYSTEM + DOC_A, n=4, max_new_tokens=3, temperature=0.7, seed=31)
    r_c = cached.generate(SYSTEM + DOC_B, n=4, max_new_tokens=3, temperature=0.7, seed=32)
    assert cached.prefix_cache_stats["partial_hits"] == 1
    r_p = plain.generate(SYSTEM + DOC_B, n=4, max_new_tokens=3, temperature=0.7, seed=32)
    np.testing.assert_array_equal(r_c.tokens, r_p.tokens)


def test_backend_config_plumbs_prefix_cache():
    from k_llms_tpu.backends.tpu import TpuBackend

    backend = TpuBackend(model="tiny", prefix_cache_size=3, prefix_cache_min_reuse=8)
    assert backend.engine.prefix_cache_size == 3
    assert backend.engine.prefix_cache_min_reuse == 8


def test_generate_many_uses_prefix_cache():
    """Coalesced batches consult and populate the cache per request."""
    from k_llms_tpu.engine.engine import GenRequestSpec

    plain, cached = _engines()
    cached.generate(SYSTEM + DOC_A, n=2, max_new_tokens=3, temperature=0.6, seed=40)
    batched = cached.generate_many(
        [GenRequestSpec(SYSTEM + DOC_A, 2, 41), GenRequestSpec(SYSTEM + DOC_B, 2, 42)],
        max_new_tokens=3,
        temperature=0.6,
    )
    assert cached.prefix_cache_stats["hits"] == 1  # exact reuse of DOC_A KV
    assert cached.prefix_cache_stats["partial_hits"] == 1  # DOC_B continuation
    solo = [
        plain.generate(p, n=2, max_new_tokens=3, temperature=0.6, seed=s)
        for p, s in ((SYSTEM + DOC_A, 41), (SYSTEM + DOC_B, 42))
    ]
    for s, b in zip(solo, batched):
        np.testing.assert_array_equal(s.tokens, b.tokens)


def test_oversized_continuation_falls_back_to_full_prefill():
    """A partial hit whose score tensor would blow the cap must take the full
    prefill path (counted as a miss) instead of the quadratic continuation."""
    _, cached = _engines()
    cached.MAX_CONT_SCORE_BYTES = 1  # force every continuation over the cap
    cached.generate(SYSTEM + DOC_A, n=1, max_new_tokens=2, temperature=0.5, seed=50)
    cached.generate(SYSTEM + DOC_B, n=1, max_new_tokens=2, temperature=0.5, seed=51)
    assert cached.prefix_cache_stats == {"hits": 0, "partial_hits": 0, "misses": 2}


def test_flash_continuation_matches_dense():
    """attention_impl="flash": the continuation prefill runs the flash kernel
    in q_offset mode — output must still be bit-equal to the uncached dense
    engine (VERDICT r2 #5)."""
    plain, cached = _engines(cfg_overrides={"attention_impl": "flash"})
    cached.generate(SYSTEM + DOC_A, n=2, max_new_tokens=4, temperature=0.7, seed=7)
    r_cached = cached.generate(SYSTEM + DOC_B, n=2, max_new_tokens=4, temperature=0.7, seed=8)
    assert cached.prefix_cache_stats["partial_hits"] == 1
    r_plain = plain.generate(SYSTEM + DOC_B, n=2, max_new_tokens=4, temperature=0.7, seed=8)
    np.testing.assert_array_equal(r_cached.tokens, r_plain.tokens)
    np.testing.assert_allclose(r_cached.logprobs, r_plain.logprobs, rtol=1e-4, atol=1e-4)


def test_flash_continuation_ignores_score_cap():
    """The 1 GB masked-XLA score cap does not apply to the flash path: even
    with the cap forced to 1 byte, the partial hit still takes continuation
    instead of falling back to full prefill."""
    plain, cached = _engines(cfg_overrides={"attention_impl": "flash"})
    cached.MAX_CONT_SCORE_BYTES = 1
    cached.generate(SYSTEM + DOC_A, n=1, max_new_tokens=3, temperature=0.6, seed=50)
    r = cached.generate(SYSTEM + DOC_B, n=1, max_new_tokens=3, temperature=0.6, seed=51)
    assert cached.prefix_cache_stats["partial_hits"] == 1
    ref = plain.generate(SYSTEM + DOC_B, n=1, max_new_tokens=3, temperature=0.6, seed=51)
    np.testing.assert_array_equal(r.tokens, ref.tokens)
