"""Self-healing continuous serving (PR 13): fault domains for the W-slot loop.

The load-bearing pins, one per fault domain:

- ``continuous.worker=crash`` (the previously-silent worker-death path) fails
  every queued and in-flight future with a TYPED error and restarts the loop —
  the regression this PR exists to close is a future that hangs forever.
- ``continuous.step=hang`` under the loop's own watchdog budget epoch-fences
  the abandoned dispatch thread, rebuilds the engine through ``rebuild_fn``,
  and REPLAYS the journaled in-flight rows byte-identically (greedy, sampled,
  and grammar-constrained alike), with sink deltas de-duplicated up to the
  delivery watermark so streaming clients see one contiguous stream.
- A loop-scoped ``engine.logits=nan`` quarantines exactly the poisoned row
  (typed ``sample_error.code="numeric_poison"``) while its batch neighbors
  decode on untouched — in BOTH the dense and paged step programs.
- Faults on a bare loop (no rebuild path) and faults past ``max_rebuilds``
  go TERMINAL with a typed EngineHungError instead of a rebuild storm.
"""

import json
import time

import numpy as np
import pytest

from k_llms_tpu.engine.continuous import ContinuousDecodeLoop
from k_llms_tpu.reliability import failpoints as fp
from k_llms_tpu.reliability.failpoints import FailSpec
from k_llms_tpu.reliability.supervisor import LaunchBudgetModel
from k_llms_tpu.types.wire import BackendUnavailableError, EngineHungError
from k_llms_tpu.utils.observability import RECOVERY_EVENTS


def _step_budget(seconds: float) -> LaunchBudgetModel:
    """Pinned per-step watchdog budget for drills (min == max: the EWMA can
    neither loosen nor tighten it mid-test)."""
    return LaunchBudgetModel(
        base_s=0.1, per_token_s=0.01, multiplier=1.0,
        min_budget_s=seconds, max_budget_s=seconds,
    )


@pytest.fixture(scope="module")
def eng():
    from conftest import shared_engine

    return shared_engine(model="tiny")


# -- worker crash containment ----------------------------------------------


def test_worker_crash_fails_futures_typed_and_restarts(eng):
    """Regression for the silent worker-death path: the crashed worker must
    flush its futures with a typed error (not strand them forever) and the
    restarted loop must serve follow-up traffic on the same engine."""
    loop = ContinuousDecodeLoop(eng, width=2, max_prompt=64, max_new=32)
    try:
        crashes = RECOVERY_EVENTS.snapshot().get("continuous.worker_crashes", 0)
        with fp.failpoints(
            {"continuous.worker": FailSpec(action="crash", times=1)}
        ):
            fut = loop.submit(
                [1, 2, 3], n=1, max_new=8, temperature=0.0, top_p=None, seed=1
            )
            # The old code logged the crash and returned — this .result() hung
            # forever. The contract now: typed failure, promptly.
            with pytest.raises(BackendUnavailableError, match="worker crashed"):
                fut.result(timeout=30)
        assert (
            RECOVERY_EVENTS.snapshot()["continuous.worker_crashes"] > crashes
        )
        st = loop.stats
        assert st["restarts"] >= 1
        assert st["last_recovery_reason"] == "worker_crash"
        # The engine was never at fault: the restarted loop decodes cleanly.
        ok = loop.submit(
            [1, 2, 3], n=1, max_new=4, temperature=0.0, top_p=None, seed=1
        ).result(timeout=120)
        assert int(ok.lengths[0]) > 0
        assert loop._terminal_error is None
    finally:
        loop.stop()


# -- hung step: watchdog + rebuild + byte-identical replay -----------------


@pytest.mark.parametrize(
    "label,kw",
    [
        ("greedy", dict(temperature=0.0, top_p=None)),
        ("sampled", dict(temperature=0.8, top_p=0.9)),
    ],
)
def test_hung_step_rebuild_replay_differential(eng, label, kw):
    """The acceptance differential: a request interrupted by a hung step and
    healed through journal + rebuild + replay returns EXACTLY the bytes of an
    uninterrupted run (pinned seed + self-deterministic row keys), and its
    token sink sees each step once — no duplicates across the fault."""
    baseline = ContinuousDecodeLoop(eng, width=4, max_prompt=64, max_new=32)
    try:
        base = baseline.submit(
            [5, 6, 7, 8], n=2, max_new=8, seed=23, **kw
        ).result(timeout=120)
    finally:
        baseline.stop()

    sunk = []
    loop = ContinuousDecodeLoop(
        eng, width=4, max_prompt=64, max_new=32,
        budget_model=_step_budget(6.0), rebuild_fn=lambda: eng, max_rebuilds=3,
    )
    try:
        hangs = RECOVERY_EVENTS.snapshot().get("continuous.step_hangs", 0)
        with fp.failpoints(
            {"continuous.step": FailSpec(action="hang", times=1, delay=20.0)}
        ):
            got = loop.submit(
                [5, 6, 7, 8], n=2, max_new=8, seed=23,
                token_sink=lambda s, t: sunk.append((s, t.copy())), **kw
            ).result(timeout=120)
        assert RECOVERY_EVENTS.snapshot()["continuous.step_hangs"] > hangs
        st = loop.stats
        assert st["restarts"] >= 1, label
        assert st["replayed_rows"] >= 2
        assert st["last_recovery_reason"] == "hung_step"
        # Byte-identical recovery.
        assert np.array_equal(got.tokens, base.tokens), label
        assert np.allclose(got.logprobs, base.logprobs, atol=1e-5)
        assert list(got.lengths) == list(base.lengths)
        # Watermark de-dup: step indices delivered exactly once, in order,
        # and each delivered token matches the authoritative buffers.
        steps = [s for s, _ in sunk]
        assert steps == sorted(set(steps))
        for step, row in sunk:
            for j in range(2):
                if step < got.lengths[j]:
                    assert row[j] == got.tokens[j, step]
    finally:
        loop.stop()


@pytest.mark.slow
@pytest.mark.duration_budget(90)
def test_hung_step_grammar_row_resumes(eng):
    """A grammar-constrained row survives the rebuild too: automaton state is
    journaled as data (prompt + grammar handle), re-admission re-derives it,
    and the replayed output still validates under the schema byte-for-byte."""
    from pydantic import BaseModel

    from k_llms_tpu.engine.grammar import (
        grammar_for_schema,
        grammar_vocab,
        validate_grammar_tokens,
    )
    from k_llms_tpu.engine.tokenizer import ByteTokenizer

    class Rec(BaseModel):
        name: str
        count: int

    tok = ByteTokenizer()
    g = grammar_for_schema(
        Rec.model_json_schema(), grammar_vocab(tok), vocab_digest="bytetok-rec"
    )
    prompt = tok.apply_chat_template([{"role": "user", "content": "extract"}])

    baseline = ContinuousDecodeLoop(eng, width=2, max_prompt=64, max_new=96)
    try:
        base = baseline.submit(
            prompt, n=1, max_new=96, temperature=1.0, top_p=None, seed=23,
            grammar=g,
        ).result(timeout=120)
    finally:
        baseline.stop()

    loop = ContinuousDecodeLoop(
        eng, width=2, max_prompt=64, max_new=96,
        budget_model=_step_budget(8.0), rebuild_fn=lambda: eng, max_rebuilds=3,
    )
    try:
        with fp.failpoints(
            {"continuous.step": FailSpec(action="hang", times=1, delay=20.0)}
        ):
            got = loop.submit(
                prompt, n=1, max_new=96, temperature=1.0, top_p=None, seed=23,
                grammar=g,
            ).result(timeout=240)
        assert loop.stats["last_recovery_reason"] == "hung_step"
        assert np.array_equal(got.tokens, base.tokens)
        body = [int(t) for t in got.tokens[0][: int(got.lengths[0])] if t < 256]
        ok, _ = validate_grammar_tokens(g, body)
        assert ok, bytes(body)
        if got.finish_reasons[0] == "stop":
            Rec.model_validate(json.loads(bytes(body)))
    finally:
        loop.stop()


# -- bounded recovery / terminal states ------------------------------------


def test_fault_without_rebuild_path_goes_terminal(eng):
    """A bare loop (no rebuild_fn) cannot heal a wedged device: the hung step
    drives a typed terminal state instead of an unbounded restart spin, and
    submit() re-raises it."""
    loop = ContinuousDecodeLoop(
        eng, width=2, max_prompt=64, max_new=32,
        budget_model=_step_budget(1.0),
    )
    try:
        with fp.failpoints(
            {"continuous.step": FailSpec(action="hang", times=1, delay=15.0)}
        ):
            fut = loop.submit(
                [1, 2, 3], n=1, max_new=8, temperature=0.0, top_p=None, seed=2
            )
            with pytest.raises(EngineHungError, match="without an engine rebuild"):
                fut.result(timeout=60)
        assert isinstance(loop._terminal_error, EngineHungError)
        with pytest.raises(EngineHungError):
            loop.submit(
                [1, 2], n=1, max_new=2, temperature=0.0, top_p=None, seed=2
            )
    finally:
        loop.stop()


def test_repeated_hangs_exhaust_rebuilds_then_terminal(eng):
    """Every replay's first step hangs again: fault credits never refill
    (no step completes), so after max_rebuilds attempts the loop goes
    terminal with the bounded-recovery error instead of rebuilding forever."""
    rebuilds = {"n": 0}

    def rebuild():
        rebuilds["n"] += 1
        return eng

    loop = ContinuousDecodeLoop(
        eng, width=2, max_prompt=64, max_new=32,
        budget_model=_step_budget(1.0), rebuild_fn=rebuild, max_rebuilds=1,
    )
    try:
        with fp.failpoints(
            {"continuous.step": FailSpec(action="hang", times=10, delay=15.0)}
        ):
            fut = loop.submit(
                [1, 2, 3], n=1, max_new=8, temperature=0.0, top_p=None, seed=3
            )
            with pytest.raises(EngineHungError, match="did not recover"):
                fut.result(timeout=60)
        assert rebuilds["n"] <= loop.max_rebuilds
        assert isinstance(loop._terminal_error, EngineHungError)
    finally:
        loop.stop()


# -- per-row numeric quarantine --------------------------------------------


def test_numeric_poison_quarantines_only_the_poisoned_row(eng):
    """Loop-scoped engine.logits=nan: the poisoned row freezes with a typed
    ``numeric_poison`` sample_error (its garbage token never reaches the
    accumulators) while the healthy neighbor decodes to completion."""
    loop = ContinuousDecodeLoop(eng, width=4, max_prompt=64, max_new=32)
    try:
        with fp.failpoints(
            {"engine.logits": FailSpec(action="nan", kill=1, seed=5, times=1)}
        ):
            res = loop.submit(
                [2, 3, 4], n=2, max_new=6, temperature=0.7, top_p=0.9, seed=9
            ).result(timeout=120)
        errs = res.sample_errors
        assert errs is not None
        assert sum(e is not None for e in errs) == 1
        j = next(i for i, e in enumerate(errs) if e is not None)
        assert errs[j]["code"] == "numeric_poison"
        assert int(res.lengths[j]) == 0
        k = 1 - j
        assert int(res.lengths[k]) > 0 and errs[k] is None
        assert loop.stats["quarantined_rows"] == 1
        # Quarantine is not a fault: no restart, no terminal, loop healthy.
        assert loop.stats["restarts"] == 0
        ok = loop.submit(
            [2, 3], n=1, max_new=4, temperature=0.0, top_p=None, seed=9
        ).result(timeout=120)
        assert int(ok.lengths[0]) > 0
    finally:
        loop.stop()


def test_numeric_poison_quarantine_paged_returns_pages():
    """Same contract through the PAGED step program, plus the pool side: the
    quarantined row's pages are decref'd on retirement, so the allocator
    stays conserved (loop_refs drains to 0, no pool quarantine)."""
    from conftest import shared_params

    from k_llms_tpu.engine.engine import LocalEngine
    from k_llms_tpu.models import get_config

    cfg = get_config("tiny")
    eng = LocalEngine(
        cfg, params=shared_params(cfg, 0), use_mesh=False,
        kv_layout="paged", kv_page_size=8,
    )
    loop = ContinuousDecodeLoop(eng, width=2, max_prompt=32, max_new=8)
    try:
        with fp.failpoints(
            {"engine.logits": FailSpec(action="nan", kill=1, seed=3, times=1)}
        ):
            res = loop.submit(
                [3, 1, 4, 1, 5], n=2, max_new=4, temperature=0.6, top_p=0.9,
                seed=4,
            ).result(timeout=120)
        errs = res.sample_errors
        assert errs is not None and sum(e is not None for e in errs) == 1
        assert loop.stats["quarantined_rows"] == 1
        pages = loop.stats["pages"]
        assert "quarantined" not in pages  # conservation held: full snapshot
        assert pages["loop_refs"] == 0
    finally:
        loop.stop()


# -- backend integration: adopt_engine + health + /metrics -----------------


def _cont_backend(**cfg):
    import jax
    from conftest import shared_engine

    from k_llms_tpu.backends.tpu import TpuBackend

    engine = (
        shared_engine("tiny", mesh_shape=(8, 1)) if len(jax.devices()) == 8 else None
    )
    return TpuBackend(
        model="tiny", max_new_tokens=8, engine=engine,
        continuous_batching=True, continuous_width=4,
        continuous_max_prompt=128, continuous_max_new=64, **cfg,
    )


@pytest.mark.slow
@pytest.mark.duration_budget(60)
def test_supervisor_rebuild_adopts_engine_into_loop():
    """The coalesced path's rebuild no longer kills the loop: the rebuilt
    engine is ADOPTED (same loop object, fresh device state) and an identical
    follow-up request reproduces the pre-rebuild bytes — _build_engine lands
    on exactly the weights a cold start would."""
    from k_llms_tpu import KLLMs

    backend = _cont_backend()
    client = KLLMs(backend=backend, model="tiny")
    try:
        msgs = [{"role": "user", "content": "adopt"}]
        before = client.chat.completions.create(
            messages=msgs, model="tiny", n=2, seed=41, temperature=0.8
        )
        loop = backend._continuous
        backend._rebuild_engine()
        assert backend._continuous is loop  # same loop, not a replacement
        assert loop.engine is backend.engine
        after = client.chat.completions.create(
            messages=msgs, model="tiny", n=2, seed=41, temperature=0.8
        )
        assert [c.message.content for c in before.choices] == [
            c.message.content for c in after.choices
        ]
    finally:
        client.close()


def test_health_and_metrics_surface_continuous_recovery_state():
    """health()['continuous'] carries the self-healing gauges and /metrics
    exports only the NUMERIC ones (strings/None/dicts in the stats snapshot
    must not become malformed Prometheus lines)."""
    import asyncio

    import httpx

    from k_llms_tpu import KLLMs
    from k_llms_tpu.serving import ServingApp

    backend = _cont_backend()
    client = KLLMs(backend=backend, model="tiny")
    try:
        client.chat.completions.create(
            messages=[{"role": "user", "content": "gauge"}], model="tiny",
            n=2, seed=7,
        )
        cont = backend.health()["continuous"]
        for key in (
            "width", "free_slots", "active_rows", "occupancy", "queue_depth",
            "restarts", "replayed_rows", "quarantined_rows",
            "last_recovery_reason",
        ):
            assert key in cont, key
        assert cont["last_recovery_reason"] is None  # healthy so far

        app = ServingApp(client)

        async def go():
            transport = httpx.ASGITransport(app=app)
            async with httpx.AsyncClient(
                transport=transport, base_url="http://testserver"
            ) as c:
                return await c.get("/metrics")

        body = asyncio.run(go()).text
        assert "kllms_continuous_restarts 0" in body
        assert "kllms_continuous_quarantined_rows 0" in body
        assert "kllms_continuous_width" in body
        assert "kllms_continuous_last_recovery_reason" not in body
        assert "kllms_continuous_pages" not in body  # nested dict skipped
        for line in body.splitlines():
            if line.startswith("kllms_continuous_"):
                float(line.split()[-1])  # every exported sample is numeric
    finally:
        client.close()


@pytest.mark.slow
@pytest.mark.duration_budget(90)
def test_streamed_request_rebuild_replay_differential():
    """The streaming half of the acceptance differential: a create(stream=True)
    interrupted by a hung step mid-decode delivers the SAME deltas and final
    response as an uninterrupted stream — the watermark suppresses replayed
    steps, so the client never sees a duplicate or a gap."""
    from k_llms_tpu import KLLMs

    backend = _cont_backend(
        watchdog_base_s=0.5, watchdog_per_token_s=0.01,
        watchdog_multiplier=1.0, watchdog_min_budget_s=8.0,
        watchdog_max_budget_s=8.0, max_rebuilds=3,
    )
    client = KLLMs(backend=backend, model="tiny")
    try:
        msgs = [{"role": "user", "content": "stream heal"}]

        def run_stream():
            deltas = []
            with client.chat.completions.create(
                messages=msgs, model="tiny", n=2, seed=37, temperature=0.8,
                stream=True,
            ) as stream:
                for chunk in stream:
                    for ch in chunk.get("choices", []):
                        c = ch.get("delta", {}).get("content")
                        if c:
                            deltas.append((ch["index"], c))
                return deltas, stream.response

        base_deltas, base = run_stream()
        restarts = backend.health()["continuous"]["restarts"]
        with fp.failpoints(
            {"continuous.step": FailSpec(action="hang", times=1, delay=30.0)}
        ):
            healed_deltas, healed = run_stream()
        assert backend.health()["continuous"]["restarts"] > restarts
        assert healed_deltas == base_deltas
        assert [c.message.content for c in base.choices] == [
            c.message.content for c in healed.choices
        ]
    finally:
        client.close()


@pytest.mark.slow
@pytest.mark.duration_budget(90)
def test_backend_hung_step_recovers_through_scheduler_lifecycle():
    """End to end through the backend: a hung loop step mid-request drives
    READY -> RECOVERING -> READY via the scheduler hooks, the request still
    succeeds (replayed on the rebuilt engine), and restart gauges move."""
    from k_llms_tpu import KLLMs

    backend = _cont_backend(
        watchdog_base_s=0.5, watchdog_per_token_s=0.01,
        watchdog_multiplier=1.0, watchdog_min_budget_s=8.0,
        watchdog_max_budget_s=8.0, max_rebuilds=3,
    )
    client = KLLMs(backend=backend, model="tiny")
    try:
        msgs = [{"role": "user", "content": "hang drill"}]
        base = client.chat.completions.create(
            messages=msgs, model="tiny", n=2, seed=19, temperature=0.8
        )
        with fp.failpoints(
            {"continuous.step": FailSpec(action="hang", times=1, delay=30.0)}
        ):
            healed = client.chat.completions.create(
                messages=msgs, model="tiny", n=2, seed=19, temperature=0.8
            )
        assert [c.message.content for c in base.choices] == [
            c.message.content for c in healed.choices
        ]
        h = backend.health()
        assert h["continuous"]["restarts"] >= 1
        assert h["continuous"]["last_recovery_reason"] == "hung_step"
        assert h["state"] in ("ready", "degraded")
    finally:
        client.close()
