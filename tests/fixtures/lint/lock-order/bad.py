"""lock-order fixture: an A->B / B->A cycle plus a raw threading lock."""

import threading

from k_llms_tpu.analysis.lockcheck import make_lock

RAW = threading.Lock()  # raw primitive: invisible to KLLMS_LOCKCHECK

A = make_lock("fix.a")
B = make_lock("fix.b")


def forward():
    with A:
        with B:
            return 1


def backward():
    with B:
        with A:
            return 2
