"""lock-order fixture: both call sites agree on A-before-B; factories only."""

from k_llms_tpu.analysis.lockcheck import make_lock

A = make_lock("fix.a")
B = make_lock("fix.b")


def forward():
    with A:
        with B:
            return 1


def also_forward():
    with A:
        with B:
            return 2
