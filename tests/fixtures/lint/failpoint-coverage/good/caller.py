"""failpoint-coverage fixture call sites: every registered site fires."""

from .reliability import failpoints as _failpoints


def launch():
    _failpoints.fire("engine.launch")


def release_pages():
    _failpoints.fire_keyed("engine.pages", key="slot0")
