"""failpoint-coverage fixture registry: every site live and documented."""

SITES = (
    "engine.launch",
    "engine.pages",
)


class FailSpec:
    def __post_init__(self):
        if self.action not in ("error", "hang"):
            raise ValueError(f"unknown failpoint action {self.action!r}")
