"""failpoint-coverage fixture registry: one live site, one dead entry."""

SITES = (
    "engine.launch",
    "engine.ghost",  # registered but never fired/tested/documented
)


class FailSpec:
    def __post_init__(self):
        if self.action not in ("error", "hang"):
            raise ValueError(f"unknown failpoint action {self.action!r}")
