"""failpoint-coverage fixture call sites: a typo'd site and a dynamic one."""

from .reliability import failpoints as _failpoints


def _site_name():
    return "engine." + "dynamic"


def launch():
    _failpoints.fire("engine.launch")
    _failpoints.fire("engine.typo")  # not registered in SITES
    _failpoints.fire(_site_name())  # non-literal: statically uncheckable
