"""counter-hygiene fixture groups: declared vocabulary covers every record."""


class EventCounters:
    def __init__(self, declared=None):
        self.declared = tuple(declared or ())

    def record(self, event, n=1):
        pass


EVENTS = EventCounters(declared=(
    "a.b",
    "keyed.*",  # f-string family: keyed.<route>
))
