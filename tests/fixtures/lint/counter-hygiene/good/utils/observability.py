"""counter-hygiene fixture groups: declared vocabularies cover every site."""


class EventCounters:
    def __init__(self, declared=None):
        self.declared = tuple(declared or ())

    def record(self, event, n=1):
        pass


class LatencyHistograms:
    def __init__(self, declared=None, buckets=()):
        self.declared = tuple(declared or ())

    def observe(self, name, seconds):
        pass


EVENTS = EventCounters(declared=(
    "a.b",
    "keyed.*",  # f-string family: keyed.<route>
))

HIST = LatencyHistograms(declared=(
    "h.a",
    "hkeyed.*",  # f-string family: hkeyed.<route>
))
