"""counter-hygiene fixture metrics surface: every group exported."""

from ..utils.observability import EVENTS


def metrics():
    return {"events": EVENTS.declared}
