"""counter-hygiene fixture metrics surface: every group exported."""

from ..utils.observability import EVENTS, HIST


def metrics():
    return {"events": EVENTS.declared, "latency": HIST.declared}
