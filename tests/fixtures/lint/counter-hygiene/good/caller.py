"""counter-hygiene fixture call sites: literals and an f-string family."""

from .utils.observability import EVENTS


def work(route):
    EVENTS.record("a.b")
    EVENTS.record(f"keyed.{route}")
