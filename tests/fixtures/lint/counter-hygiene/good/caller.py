"""counter-hygiene fixture call sites: literals and f-string families."""

from .utils.observability import EVENTS, HIST


def work(route):
    EVENTS.record("a.b")
    EVENTS.record(f"keyed.{route}")
    HIST.observe("h.a", 0.1)
    HIST.observe(f"hkeyed.{route}", 0.1)
