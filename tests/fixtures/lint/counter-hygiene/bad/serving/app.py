"""counter-hygiene fixture metrics surface: one group of each missing."""

from ..utils.observability import BETA_EVENTS, DELTA_HIST


def metrics():
    return {"beta": BETA_EVENTS.declared, "delta": DELTA_HIST.declared}
