"""counter-hygiene fixture metrics surface: one group missing on purpose."""

from ..utils.observability import BETA_EVENTS


def metrics():
    return {"beta": BETA_EVENTS.declared}
