"""counter-hygiene fixture groups: one undeclared, one with a stale name."""


class EventCounters:
    def __init__(self, declared=None):
        self.declared = tuple(declared or ())

    def record(self, event, n=1):
        pass


class LatencyHistograms:
    def __init__(self, declared=None, buckets=()):
        self.declared = tuple(declared or ())

    def observe(self, name, seconds):
        pass


ALPHA_EVENTS = EventCounters()  # no declared= vocabulary

BETA_EVENTS = EventCounters(declared=(
    "a.b",
    "stale.name",  # declared but never recorded anywhere
))

GAMMA_HIST = LatencyHistograms()  # no declared= vocabulary

DELTA_HIST = LatencyHistograms(declared=(
    "h.a",
    "stale.hist",  # declared but never observed anywhere
))
