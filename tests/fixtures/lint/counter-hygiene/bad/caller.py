"""counter-hygiene fixture call sites: covered and typo'd, counters + hists."""

from .utils.observability import BETA_EVENTS, DELTA_HIST


def work():
    BETA_EVENTS.record("a.b")
    BETA_EVENTS.record("a.typo")  # not covered by declared= patterns
    DELTA_HIST.observe("h.a", 0.1)
    DELTA_HIST.observe("h.typo", 0.1)  # not covered by declared= patterns
