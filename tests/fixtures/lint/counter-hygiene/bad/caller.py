"""counter-hygiene fixture call sites: one covered record, one typo."""

from .utils.observability import BETA_EVENTS


def work():
    BETA_EVENTS.record("a.b")
    BETA_EVENTS.record("a.typo")  # not covered by declared= patterns
