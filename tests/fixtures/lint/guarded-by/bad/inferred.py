"""guarded-by fixture: a minority access site that skips the inferred
majority guard."""

from k_llms_tpu.analysis.lockcheck import make_lock


class Stats:
    def __init__(self):
        self._lock = make_lock("fix.stats")
        self._counts = {}

    def bump(self, key):
        with self._lock:
            self._counts[key] = self._counts.get(key, 0) + 1

    def also_bump(self, key):
        with self._lock:
            self._counts[key] = 1

    def peek(self, key):
        return self._counts.get(key)  # BAD: unlocked minority read
