"""guarded-by fixture: annotation failures — a declared guard that is not
held, a guard naming no known lock, and a reasonless unguarded marker."""

from k_llms_tpu.analysis.lockcheck import make_lock


class Annotated:
    def __init__(self):
        self._lock = make_lock("fix.annotated")
        self._items = []  # kllms: guarded-by[fix.annotated]
        self._ghost = []  # kllms: guarded-by[fix.nosuch]
        self._bare = 0  # kllms: unguarded

    def add(self, x):
        self._items.append(x)  # BAD: declared guard not held

    def haunt(self, x):
        with self._lock:
            self._ghost.append(x)

    def bump(self):
        self._bare += 1
        return self._bare
