"""guarded-by fixture: two locks held at every access site — majority
inference ties and demands an explicit annotation."""

from k_llms_tpu.analysis.lockcheck import make_lock


class Torn:
    def __init__(self):
        self._a = make_lock("fix.torn_a")
        self._b = make_lock("fix.torn_b")
        self._val = 0

    def left(self):
        with self._a, self._b:
            self._val += 1

    def right(self):
        with self._a, self._b:
            self._val -= 1
