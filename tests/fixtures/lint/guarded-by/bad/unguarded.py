"""guarded-by fixture: an attribute written from two methods with an empty
inferred lockset — the classic multi-writer race shape."""

from k_llms_tpu.analysis.lockcheck import make_lock


class Gauge:
    def __init__(self):
        self._lock = make_lock("fix.gauge")
        self._guarded = 0
        self.level = 0

    def up(self):
        self.level += 1  # BAD: no lock, and down() also writes it
        with self._lock:
            self._guarded += 1

    def down(self):
        self.level -= 1  # BAD: no lock, and up() also writes it
        with self._lock:
            self._guarded -= 1
