"""guarded-by fixture: a guarded mutable container escaping its critical
section — returned raw and handed raw to an executor."""

from k_llms_tpu.analysis.lockcheck import make_lock


class Leaky:
    def __init__(self, executor):
        self._lock = make_lock("fix.leaky")
        self._ring = []
        self._executor = executor

    def push(self, item):
        with self._lock:
            self._ring.append(item)

    def raw(self):
        with self._lock:
            return self._ring  # BAD: reference outlives the lock

    def hand_off(self):
        with self._lock:
            self._executor.submit(sorted, self._ring)  # BAD: escapes to pool
