"""guarded-by fixture: explicit annotations — a declared guard that is
honored, and an unguarded-by-design field with a reason."""

from k_llms_tpu.analysis.lockcheck import make_lock


class Recorder:
    def __init__(self):
        self._lock = make_lock("fix.recorder")
        self._aux = make_lock("fix.recorder_aux")
        self._ring = []  # kllms: guarded-by[fix.recorder]
        self._hint = 0  # kllms: unguarded — monotonic hint; torn reads benign

    def record(self, item):
        with self._lock:
            self._ring.append(item)
        self._hint += 1

    def hint(self):
        return self._hint

    def flush(self):
        with self._lock:
            out = list(self._ring)
            self._ring.clear()
        return out
