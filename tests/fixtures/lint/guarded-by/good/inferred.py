"""guarded-by fixture: every access to guarded state holds the inferred
lock, and snapshots hand out copies instead of the raw container."""

from k_llms_tpu.analysis.lockcheck import make_lock


class Journal:
    def __init__(self):
        self._lock = make_lock("fix.journal")
        self._entries = []
        self._count = 0

    def add(self, item):
        with self._lock:
            self._entries.append(item)
            self._count += 1

    def snapshot(self):
        with self._lock:
            return list(self._entries)

    def total(self):
        with self._lock:
            return self._count
