"""guarded-by fixture: helpers reached only from locked regions (the
interprocedural entry-lockset fixpoint) and the *_locked naming convention
both count as holding the guard."""

from k_llms_tpu.analysis.lockcheck import make_lock


class Pool:
    def __init__(self):
        self._lock = make_lock("fix.pool")
        self._free = []

    def put(self, page):
        with self._lock:
            self._push(page)

    def take(self):
        with self._lock:
            return self._pop_locked()

    def _push(self, page):
        # Private and only ever called with the lock held: the fixpoint
        # assigns it entry lockset {fix.pool}.
        self._free.append(page)

    def _pop_locked(self):
        # The *_locked suffix floors the entry lockset at the class primary.
        return self._free.pop() if self._free else None
