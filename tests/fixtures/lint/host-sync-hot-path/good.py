"""host-sync-hot-path fixture: jitted body stays on device; syncs live in
ordinary (non-hot) functions where they are legitimate."""

import jax
import numpy as np


def _kernel(x):
    return x * 2


run = jax.jit(_kernel)


def decode_step(params, tok):
    return run(params), tok


def collect_results(arrays):
    # Not jitted, not configured hot: syncing here is fine.
    return [np.asarray(a) for a in map(jax.device_get, arrays)]
