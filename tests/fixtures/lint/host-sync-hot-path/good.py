"""host-sync-hot-path fixture: jitted body stays on device; syncs live in
ordinary (non-hot) functions where they are legitimate."""

import jax
import numpy as np


def _kernel(x):
    return x * 2


run = jax.jit(_kernel)


def decode_step(params, tok):
    return run(params), tok


def paged_decode_attention_ref(q, pool_k, tables):
    # Matched by the ``paged_*`` glob pattern; pure device code is clean.
    cols = pool_k[tables]
    return q @ cols.T


def collect_results(arrays):
    # Not jitted, not configured hot: syncing here is fine.
    return [np.asarray(a) for a in map(jax.device_get, arrays)]


def grammar_mask_logits(masks, state, logits):
    # Configured hot (PR 12 grammar op); the row gather + unpack is pure
    # device math, no readback.
    rows = masks[state]
    return logits + rows


def grammar_advance(trans, token, state):
    return trans[state, token]  # configured hot: pure device gather
