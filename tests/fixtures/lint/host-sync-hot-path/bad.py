"""host-sync-hot-path fixture: syncs inside a jitted body and a hot function.

The test runs this with ``hot_functions = ["decode_step", "paged_*"]`` —
the second entry pins the glob-pattern matching the real config relies on
for the paged-attention op family.
"""

import jax
import numpy as np


def _kernel(x):
    return x.item()  # sync inside a function that becomes a jitted body


run = jax.jit(_kernel)


def decode_step(arrays, tok):
    host = list(map(np.asarray, arrays))  # sync callable handed to map()
    return host, jax.device_get(tok)  # direct sync


def paged_decode_attention_ref(q, tables):
    pages = tables.tolist()  # glob-matched hot function: sync flagged
    return q, pages


def grammar_mask_logits(masks, state):
    rows = masks[state]
    return np.asarray(rows)  # configured hot (PR 12 grammar op): sync flagged
