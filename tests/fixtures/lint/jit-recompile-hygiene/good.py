"""jit-recompile-hygiene fixture: every sanctioned creation pattern."""

import functools

import jax


def _double(x):
    return x * 2


STEP = jax.jit(_double)  # module level: compiled once per import


def _build_step(f):
    return jax.jit(f)  # builder-named function


@functools.lru_cache(maxsize=8)
def step_for(width):
    return jax.jit(lambda x: x * width)  # memoized factory


class Engine:
    def __init__(self, f):
        self._fn = jax.jit(f)  # memoized store in __init__
        self._cache = {}

    def get(self, key, f):
        fn = self._cache.get(key)
        if fn is None:
            fn = jax.jit(f)
            self._cache[key] = fn  # memoized-getter idiom: store then reuse
        return fn

    def _get_decode_loop(self, f):
        # Sanctioned only via the configured ``builder_functions`` list:
        # the test pins that the config entry is load-bearing.
        return jax.jit(f)


class Loop:
    def _grammar_programs(self, f):
        # Sanctioned only via ``builder_functions`` (like _get_decode_loop):
        # the real loop memoizes by grammar table shapes before jitting.
        return jax.jit(f)
