"""jit-recompile-hygiene fixture: a wrapper built per call and thrown away."""

import jax


def per_request(f, x):
    g = jax.jit(f)  # new wrapper object every call -> recompile every call
    return g(x)
