"""Suppression-machinery fixture: one finding silenced inline, one not."""

import threading

SILENCED = threading.Lock()  # kllms: ignore[lock-order] — fixture: proves same-line suppression works

# kllms: ignore[lock-order] — fixture: proves comment-above suppression works
ALSO_SILENCED = threading.Lock()

LOUD = threading.Lock()
