"""dispatch-under-lock fixture: device work under a plain lock."""

import jax

from k_llms_tpu.analysis.lockcheck import make_lock

G = make_lock("fix.guard")


def run(step_fn, x):
    with G:
        return step_fn(x)


def read(x):
    with G:
        return jax.device_get(x)
