"""dispatch-under-lock fixture: the hold is declared at the creation site,
or the dispatch happens outside the critical section."""

import jax

from k_llms_tpu.analysis.lockcheck import make_lock

G = make_lock("fix.guard", allow_dispatch=True)
H = make_lock("fix.other")


def run(step_fn, x):
    with G:
        return step_fn(x)


def read(step_fn, x):
    with H:
        y = x + 1
    return jax.device_get(step_fn(y))
