"""wire-error-contract fixture: unpinned mappings and a rebuilt envelope."""


class KLLMsError(Exception):
    type = "api_error"
    status_code = 500

    def as_wire(self):
        return {"error": {"message": str(self), "type": self.type}}


class BadError(KLLMsError):
    # Direct subclass with neither `type` nor `status_code`: falls back to
    # the base 500 silently.
    pass


class PartialError(KLLMsError):
    type = "partial"  # status_code still missing


class WorseError(KLLMsError):
    type = "worse"
    status_code = 400

    def as_wire(self):
        # Rebuilds the envelope instead of extending super().as_wire().
        return {"message": str(self)}
