"""wire-error-contract fixture: pinned mappings, envelope preserved."""


class KLLMsError(Exception):
    type = "api_error"
    status_code = 500

    def as_wire(self):
        return {"error": {"message": str(self), "type": self.type}}


class InvalidRequestError(KLLMsError):
    type = "invalid_request_error"
    status_code = 400

    def as_wire(self):
        wire = super().as_wire()
        wire["error"]["param"] = "messages"
        return wire


class BackendUnavailableError(KLLMsError):
    type = "backend_unavailable"
    status_code = 503


class EngineHungError(BackendUnavailableError):
    # Indirect subclass: inherits the 503 mapping, nothing to pin.
    pass
