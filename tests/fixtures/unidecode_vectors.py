"""Real ``unidecode`` input/output vectors, hand-encoded from the wheel's
documented mapping set (the wheel itself is not installed in this image).

``PARITY_VECTORS`` are pairs our ``transliterate`` must reproduce exactly —
Latin specials, Cyrillic, Greek.  ``DIVERGENT_VECTORS`` are pairs where the
real unidecode romanizes (CJK pinyin) but our transliterator intentionally
emits per-codepoint ``u<hex>`` tokens instead; tests assert the documented
divergence (distinctness preserved, romanization not attempted).

Used by ``tests/reference_oracle.py`` to stub the reference's ``unidecode``
import faithfully: fixture hits return the REAL unidecode output, so parity
tests against the oracle exercise genuine reference behavior instead of being
circular.
"""

# (input, real unidecode output)
PARITY_VECTORS: list[tuple[str, str]] = [
    # Latin accents / specials
    ("café", "cafe"),
    ("naïve", "naive"),
    ("kožušček", "kozuscek"),  # unidecode README example
    ("straße", "strasse"),
    ("Øresund", "Oresund"),
    ("Łódź", "Lodz"),
    ("Ærø", "AEro"),
    ("smörgåsbord", "smorgasbord"),
    # Cyrillic (ALA-LC-like)
    ("Москва", "Moskva"),
    ("москва", "moskva"),
    ("Санкт-Петербург", "Sankt-Peterburg"),
    ("Хрущёв", "Khrushchiov"),
    ("Пётр", "Piotr"),
    ("Юлия", "Iuliia"),
    ("Ярославль", "Iaroslavl'"),
    ("объект", 'ob"ekt'),
    ("Крым", "Krym"),
    ("Київ", "Kiiv"),
    ("Чебоксары", "Cheboksary"),
    ("Железногорск", "Zheleznogorsk"),
    ("Цюрих", "Tsiurikh"),
    # Greek
    ("Αθήνα", "Athena"),
    ("Ελλάδα", "Ellada"),
    ("Θεσσαλονίκη", "Thessalonike"),
    ("φιλοσοφία", "philosophia"),
    ("ψυχή", "psukhe"),
    ("Ξάνθη", "Xanthe"),
    ("χάος", "khaos"),
    ("σοφός", "sophos"),
]

# (input, real unidecode output, our transliterate output = per-codepoint tokens)
DIVERGENT_VECTORS: list[tuple[str, str, str]] = [
    (inp, real, "".join(f"u{ord(c):04x}" for c in inp))
    for inp, real in [
        ("北京", "Bei Jing "),
        ("東京", "Dong Jing "),
    ]
]

UNIDECODE_TABLE: dict[str, str] = {}
for _inp, _out in PARITY_VECTORS + [(i, r) for i, r, _ in DIVERGENT_VECTORS]:
    UNIDECODE_TABLE[_inp] = _out
    # The reference calls unidecode on str(v).lower().replace(" ", "")
    # (consensus_utils.py:927-931); key those forms too so oracle runs hit the
    # real vector instead of the fallback.  lower/despace commutes with
    # unidecode for every script in this table.
    UNIDECODE_TABLE.setdefault(_inp.lower(), _out.lower())
    UNIDECODE_TABLE.setdefault(_inp.lower().replace(" ", ""), _out.lower().replace(" ", ""))
