"""Real ``unidecode`` input/output vectors, hand-encoded from the wheel's
documented mapping set (the wheel itself is not installed in this image).

``PARITY_VECTORS`` are pairs our ``transliterate`` must reproduce exactly —
Latin specials, Cyrillic, Greek, and (since round 5) CJK: hanzi pinyin, kana
romaji, and Hangul.  ``DIVERGENT_VECTORS`` are pairs where the real unidecode
romanizes but our transliterator intentionally emits per-codepoint ``u<hex>``
tokens instead; since round 5 that remainder is only the long tail of rare
ideographs outside the ~1,700-codepoint frequency table in
``k_llms_tpu/consensus/_cjk_data.py``.  Tests assert the documented divergence
(distinctness preserved, romanization not attempted).

Used by ``tests/reference_oracle.py`` to stub the reference's ``unidecode``
import faithfully: fixture hits return the REAL unidecode output, so parity
tests against the oracle exercise genuine reference behavior instead of being
circular.
"""

# (input, real unidecode output)
PARITY_VECTORS: list[tuple[str, str]] = [
    # Latin accents / specials
    ("café", "cafe"),
    ("naïve", "naive"),
    ("kožušček", "kozuscek"),  # unidecode README example
    ("straße", "strasse"),
    ("Øresund", "Oresund"),
    ("Łódź", "Lodz"),
    ("Ærø", "AEro"),
    ("smörgåsbord", "smorgasbord"),
    # Cyrillic (ALA-LC-like)
    ("Москва", "Moskva"),
    ("москва", "moskva"),
    ("Санкт-Петербург", "Sankt-Peterburg"),
    ("Хрущёв", "Khrushchiov"),
    ("Пётр", "Piotr"),
    ("Юлия", "Iuliia"),
    ("Ярославль", "Iaroslavl'"),
    ("объект", 'ob"ekt'),
    ("Крым", "Krym"),
    ("Київ", "Kiiv"),
    ("Чебоксары", "Cheboksary"),
    ("Железногорск", "Zheleznogorsk"),
    ("Цюрих", "Tsiurikh"),
    # Greek
    ("Αθήνα", "Athena"),
    ("Ελλάδα", "Ellada"),
    ("Θεσσαλονίκη", "Thessalonike"),
    ("φιλοσοφία", "philosophia"),
    ("ψυχή", "psukhe"),
    ("Ξάνθη", "Xanthe"),
    ("χάος", "khaos"),
    ("σοφός", "sophos"),
    # Han ideographs (unidecode emits "Syllable " per character)
    ("北京", "Bei Jing "),
    ("東京", "Dong Jing "),
    ("上海", "Shang Hai "),
    ("中国", "Zhong Guo "),
    ("日本", "Ri Ben "),
    ("你好", "Ni Hao "),
    ("汉字", "Han Zi "),
    ("漢字", "Han Zi "),
    ("日本語", "Ri Ben Yu "),
    # Polyphonic hanzi — characters with several Mandarin readings where
    # unidecode's Unihan tables pin ONE canonical choice; these guard the
    # hand-pinned frequency table against picking a different (valid but
    # non-parity) reading.  le/liao → "Liao ", zhe/zhao/zhuo → "Zhao ",
    # shei/shui → "Shui ", dou/du → "Du ", zhong/chong → "Zhong ",
    # xing/hang → "Xing ".
    ("了", "Liao "),
    ("着", "Zhao "),
    ("谁", "Shui "),
    ("都", "Du "),
    ("重", "Zhong "),
    ("行", "Xing "),
    ("了不起", "Liao Bu Qi "),
    ("重行", "Zhong Xing "),
    # Kana (lowercase romaji, no separators; unidecode's famous quirks kept:
    # は stays "ha" even as a particle, small っ is "tsu", ー is "-")
    ("こんにちは", "konnichiha"),
    ("ひらがな", "hiragana"),
    ("カタカナ", "katakana"),
    ("カード", "ka-do"),
    ("サッカー", "satsuka-"),
    # Hangul (algorithmic jamo decomposition, RR letter values)
    ("서울", "seoul"),
    ("안녕", "annyeong"),
    # NFD form of 서울 — conjoining jamo U+1109 U+1165 U+110B U+116E U+11AF
    # (macOS-filename / NFD-pipeline normalization).  Real unidecode romanizes
    # the x011 jamo block directly to the same letters; our transliterator
    # NFC-composes jamo runs back into syllables first, so both agree.
    ("\u1109\u1165\u110b\u116e\u11af", "seoul"),
]

# (input, real unidecode output, our transliterate output = per-codepoint
# tokens).  Long-tail ideographs outside the frequency table: real unidecode
# carries full Unihan tables and still romanizes these; we keep them distinct
# via u<hex> tokens instead.
#
# Provenance: the "real" outputs below are hand-encoded from unidecode 1.3.8's
# published data tables (x09e.py / x07f.py), NOT verified against an installed
# wheel in this image.  Tests only assert got != real (documented divergence),
# so a wrong hand-encoded value here cannot fail a test — if you bump the
# pinned version or gain access to the wheel, re-verify these two entries.
DIVERGENT_VECTORS: list[tuple[str, str, str]] = [
    (inp, real, "".join(f"u{ord(c):04x}" for c in inp))
    for inp, real in [
        ("麤", "Cu "),   # U+9EA4 'coarse' (triple deer) — rare tail
        ("羴", "Shan "),  # U+7FB4 'rank odor of sheep' — rare tail
    ]
]

UNIDECODE_TABLE: dict[str, str] = {}
for _inp, _out in PARITY_VECTORS + [(i, r) for i, r, _ in DIVERGENT_VECTORS]:
    UNIDECODE_TABLE[_inp] = _out
    # The reference calls unidecode on str(v).lower().replace(" ", "")
    # (consensus_utils.py:927-931); key those forms too so oracle runs hit the
    # real vector instead of the fallback.  lower/despace commutes with
    # unidecode for every script in this table.
    UNIDECODE_TABLE.setdefault(_inp.lower(), _out.lower())
    UNIDECODE_TABLE.setdefault(_inp.lower().replace(" ", ""), _out.lower().replace(" ", ""))
