"""Real ``unidecode`` input/output vectors, hand-encoded from the wheel's
documented mapping set (the wheel itself is not installed in this image).

``PARITY_VECTORS`` are pairs our ``transliterate`` must reproduce exactly —
Latin specials, Cyrillic, Greek, and (since round 5) CJK: hanzi pinyin, kana
romaji, and Hangul.  ``DIVERGENT_VECTORS`` are pairs where the real unidecode
romanizes but our transliterator intentionally emits per-codepoint ``u<hex>``
tokens instead; since round 5 that remainder is only the long tail of rare
ideographs outside the ~1,700-codepoint frequency table in
``k_llms_tpu/consensus/_cjk_data.py``.  Tests assert the documented divergence
(distinctness preserved, romanization not attempted).

Used by ``tests/reference_oracle.py`` to stub the reference's ``unidecode``
import faithfully: fixture hits return the REAL unidecode output, so parity
tests against the oracle exercise genuine reference behavior instead of being
circular.
"""

# (input, real unidecode output)
PARITY_VECTORS: list[tuple[str, str]] = [
    # Latin accents / specials
    ("café", "cafe"),
    ("naïve", "naive"),
    ("kožušček", "kozuscek"),  # unidecode README example
    ("straße", "strasse"),
    ("Øresund", "Oresund"),
    ("Łódź", "Lodz"),
    ("Ærø", "AEro"),
    ("smörgåsbord", "smorgasbord"),
    # Cyrillic (ALA-LC-like)
    ("Москва", "Moskva"),
    ("москва", "moskva"),
    ("Санкт-Петербург", "Sankt-Peterburg"),
    ("Хрущёв", "Khrushchiov"),
    ("Пётр", "Piotr"),
    ("Юлия", "Iuliia"),
    ("Ярославль", "Iaroslavl'"),
    ("объект", 'ob"ekt'),
    ("Крым", "Krym"),
    ("Київ", "Kiiv"),
    ("Чебоксары", "Cheboksary"),
    ("Железногорск", "Zheleznogorsk"),
    ("Цюрих", "Tsiurikh"),
    # Greek
    ("Αθήνα", "Athena"),
    ("Ελλάδα", "Ellada"),
    ("Θεσσαλονίκη", "Thessalonike"),
    ("φιλοσοφία", "philosophia"),
    ("ψυχή", "psukhe"),
    ("Ξάνθη", "Xanthe"),
    ("χάος", "khaos"),
    ("σοφός", "sophos"),
    # Han ideographs (unidecode emits "Syllable " per character)
    ("北京", "Bei Jing "),
    ("東京", "Dong Jing "),
    ("上海", "Shang Hai "),
    ("中国", "Zhong Guo "),
    ("日本", "Ri Ben "),
    ("你好", "Ni Hao "),
    ("汉字", "Han Zi "),
    ("漢字", "Han Zi "),
    ("日本語", "Ri Ben Yu "),
    # Kana (lowercase romaji, no separators; unidecode's famous quirks kept:
    # は stays "ha" even as a particle, small っ is "tsu", ー is "-")
    ("こんにちは", "konnichiha"),
    ("ひらがな", "hiragana"),
    ("カタカナ", "katakana"),
    ("カード", "ka-do"),
    ("サッカー", "satsuka-"),
    # Hangul (algorithmic jamo decomposition, RR letter values)
    ("서울", "seoul"),
    ("안녕", "annyeong"),
]

# (input, real unidecode output, our transliterate output = per-codepoint
# tokens).  Long-tail ideographs outside the frequency table: real unidecode
# carries full Unihan tables and still romanizes these; we keep them distinct
# via u<hex> tokens instead.
DIVERGENT_VECTORS: list[tuple[str, str, str]] = [
    (inp, real, "".join(f"u{ord(c):04x}" for c in inp))
    for inp, real in [
        ("麤", "Cu "),   # U+9EA4 'coarse' (triple deer) — rare tail
        ("羴", "Shan "),  # U+7FB4 'rank odor of sheep' — rare tail
    ]
]

UNIDECODE_TABLE: dict[str, str] = {}
for _inp, _out in PARITY_VECTORS + [(i, r) for i, r, _ in DIVERGENT_VECTORS]:
    UNIDECODE_TABLE[_inp] = _out
    # The reference calls unidecode on str(v).lower().replace(" ", "")
    # (consensus_utils.py:927-931); key those forms too so oracle runs hit the
    # real vector instead of the fallback.  lower/despace commutes with
    # unidecode for every script in this table.
    UNIDECODE_TABLE.setdefault(_inp.lower(), _out.lower())
    UNIDECODE_TABLE.setdefault(_inp.lower().replace(" ", ""), _out.lower().replace(" ", ""))
