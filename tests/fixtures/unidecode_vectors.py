"""Real ``unidecode`` input/output vectors, hand-encoded from the wheel's
documented mapping set (the wheel itself is not installed in this image).

``PARITY_VECTORS`` are pairs our ``transliterate`` must reproduce exactly —
Latin specials, Cyrillic, Greek, and (since round 5) CJK: hanzi pinyin, kana
romaji, and Hangul.  ``DIVERGENT_VECTORS`` are pairs where the real unidecode
romanizes but our transliterator intentionally emits per-codepoint ``u<hex>``
tokens instead; since round 5 that remainder is only the long tail of rare
ideographs outside the ~1,700-codepoint frequency table in
``k_llms_tpu/consensus/_cjk_data.py``.  Tests assert the documented divergence
(distinctness preserved, romanization not attempted).

Used by ``tests/reference_oracle.py`` to stub the reference's ``unidecode``
import faithfully: fixture hits return the REAL unidecode output, so parity
tests against the oracle exercise genuine reference behavior instead of being
circular.
"""

# (input, real unidecode output)
PARITY_VECTORS: list[tuple[str, str]] = [
    # Latin accents / specials
    ("café", "cafe"),
    ("naïve", "naive"),
    ("kožušček", "kozuscek"),  # unidecode README example
    ("straße", "strasse"),
    ("Øresund", "Oresund"),
    ("Łódź", "Lodz"),
    ("Ærø", "AEro"),
    ("smörgåsbord", "smorgasbord"),
    # Cyrillic (ALA-LC-like)
    ("Москва", "Moskva"),
    ("москва", "moskva"),
    ("Санкт-Петербург", "Sankt-Peterburg"),
    ("Хрущёв", "Khrushchiov"),
    ("Пётр", "Piotr"),
    ("Юлия", "Iuliia"),
    ("Ярославль", "Iaroslavl'"),
    ("объект", 'ob"ekt'),
    ("Крым", "Krym"),
    ("Київ", "Kiiv"),
    ("Чебоксары", "Cheboksary"),
    ("Железногорск", "Zheleznogorsk"),
    ("Цюрих", "Tsiurikh"),
    # Greek
    ("Αθήνα", "Athena"),
    ("Ελλάδα", "Ellada"),
    ("Θεσσαλονίκη", "Thessalonike"),
    ("φιλοσοφία", "philosophia"),
    ("ψυχή", "psukhe"),
    ("Ξάνθη", "Xanthe"),
    ("χάος", "khaos"),
    ("σοφός", "sophos"),
    # Han ideographs (unidecode emits "Syllable " per character)
    ("北京", "Bei Jing "),
    ("東京", "Dong Jing "),
    ("上海", "Shang Hai "),
    ("中国", "Zhong Guo "),
    ("日本", "Ri Ben "),
    ("你好", "Ni Hao "),
    ("汉字", "Han Zi "),
    ("漢字", "Han Zi "),
    ("日本語", "Ri Ben Yu "),
    # Polyphonic hanzi — characters with several Mandarin readings where
    # unidecode's Unihan tables pin ONE canonical choice; these guard the
    # hand-pinned frequency table against picking a different (valid but
    # non-parity) reading.  le/liao → "Liao ", zhe/zhao/zhuo → "Zhao ",
    # shei/shui → "Shui ", dou/du → "Du ", zhong/chong → "Zhong ",
    # xing/hang → "Xing ".
    ("了", "Liao "),
    ("着", "Zhao "),
    ("谁", "Shui "),
    ("都", "Du "),
    ("重", "Zhong "),
    ("行", "Xing "),
    ("了不起", "Liao Bu Qi "),
    ("重行", "Zhong Xing "),
    # Common toponyms/institution words the round-5 probe found missing from
    # the frequency table (not rare-tail: 中華/經濟/歷史/廣州 are everyday
    # vocabulary), traditional and simplified forms both.
    ("華", "Hua "),
    ("中華", "Zhong Hua "),
    ("中华", "Zhong Hua "),
    ("經濟", "Jing Ji "),
    ("经济", "Jing Ji "),
    ("歷史", "Li Shi "),
    ("历史", "Li Shi "),
    ("廣州", "Guang Zhou "),
    ("广州", "Guang Zhou "),
    ("深圳", "Shen Zhen "),
    ("大阪", "Da Ban "),
    ("株式会社", "Zhu Shi Hui She "),
    ("關係", "Guan Xi "),
    ("中華人民共和国", "Zhong Hua Ren Min Gong He Guo "),
    # Kana (lowercase romaji, no separators; unidecode's famous quirks kept:
    # は stays "ha" even as a particle, small っ is "tsu", ー is "-")
    ("こんにちは", "konnichiha"),
    ("ひらがな", "hiragana"),
    ("カタカナ", "katakana"),
    ("カード", "ka-do"),
    ("サッカー", "satsuka-"),
    # Hangul (algorithmic jamo decomposition, RR letter values)
    ("서울", "seoul"),
    ("안녕", "annyeong"),
    # NFD form of 서울 — conjoining jamo U+1109 U+1165 U+110B U+116E U+11AF
    # (macOS-filename / NFD-pipeline normalization).  Real unidecode romanizes
    # the x011 jamo block directly to the same letters; our transliterator
    # NFC-composes jamo runs back into syllables first, so both agree.
    ("\u1109\u1165\u110b\u116e\u11af", "seoul"),
]

# Long-tail ideographs outside the frequency table: real unidecode carries
# full Unihan tables and still romanizes these; we keep them distinct via
# u<hex> tokens instead.  Entries are (input, real unidecode output, our
# transliterate output = per-codepoint tokens).
#
# Provenance (ADVICE.md #3): the divergence test's ``got != real`` assertion
# can never fail on a WRONG "real" pin, so a pin only belongs in
# ``DIVERGENT_VECTORS`` once verified against an installed unidecode wheel
# (pinned version: 1.3.8).  This image does not ship the wheel
# (``import unidecode`` raises ModuleNotFoundError), so the two hand-encoded
# entries — transcribed from unidecode 1.3.8's published data tables
# (x09e.py / x07f.py) but never checked against a running wheel — live in
# ``UNVERIFIED_DIVERGENT_VECTORS``.  Their "real" values are documentation,
# NOT oracle data: they are excluded from ``UNIDECODE_TABLE`` so an incorrect
# transcription can't leak into reference-parity tests as ground truth.
# ``tests/test_translit.py::test_pins_match_installed_unidecode_wheel`` runs
# whenever the wheel IS importable and promotes/corrects these automatically
# flagging any drift; until then only the ``got == ours`` half is asserted.
UNIDECODE_PINNED_VERSION = "1.3.8"

_DIVERGENT = lambda pairs: [  # noqa: E731 - tiny local helper
    (inp, real, "".join(f"u{ord(c):04x}" for c in inp)) for inp, real in pairs
]

# Wheel-verified divergent pins (empty until a wheel is available to verify
# against; see provenance note above).
DIVERGENT_VECTORS: list[tuple[str, str, str]] = _DIVERGENT([])

# Hand-encoded, explicitly UNVERIFIED divergent pins.
UNVERIFIED_DIVERGENT_VECTORS: list[tuple[str, str, str]] = _DIVERGENT(
    [
        ("麤", "Cu "),   # U+9EA4 'coarse' (triple deer) — rare tail
        ("羴", "Shan "),  # U+7FB4 'rank odor of sheep' — rare tail
    ]
)

# The reference-oracle stub table is built ONLY from parity vectors and
# wheel-verified divergent pins — unverified "real" values must not become
# the oracle's ground truth.
UNIDECODE_TABLE: dict[str, str] = {}
for _inp, _out in PARITY_VECTORS + [(i, r) for i, r, _ in DIVERGENT_VECTORS]:
    UNIDECODE_TABLE[_inp] = _out
    # The reference calls unidecode on str(v).lower().replace(" ", "")
    # (consensus_utils.py:927-931); key those forms too so oracle runs hit the
    # real vector instead of the fallback.  lower/despace commutes with
    # unidecode for every script in this table.
    UNIDECODE_TABLE.setdefault(_inp.lower(), _out.lower())
    UNIDECODE_TABLE.setdefault(_inp.lower().replace(" ", ""), _out.lower().replace(" ", ""))
