"""Page-pool accounting: the pure-Python property sweep and the fail-fast
invariant wiring.

The property test drives PageAllocator through seeded random admit / fork /
copy-on-write / retire schedules against an independent reference allocator
(a dozen lines of dict-and-list bookkeeping) and checks BLOCK-TABLE
equivalence — not just counters — after every operation; both sides are
deterministic (LIFO free stack, in-order frees), so any divergence is a real
accounting bug, not test noise. The failpoint tests pin that a simulated lost
decref (``engine.pages=leak:N``) trips :meth:`PageAllocator.verify` through
the continuous loop's ``stats`` property — the serving health read IS the
leak detector. CPU-only, no device work except the tiny leak-loop test.
"""

import random
import time

import numpy as np
import pytest

from k_llms_tpu.engine.paging import (
    TRASH_PAGE,
    PageAccountingError,
    PageAllocator,
    PagePoolExhausted,
    flat_slots,
    pages_for,
)
from k_llms_tpu.reliability.failpoints import FailSpec, failpoints
from k_llms_tpu.types.wire import EngineHungError


class _RefAllocator:
    """Independent reference: same contract, naive bookkeeping."""

    def __init__(self, total):
        self.total = total
        self.free = list(range(total - 1, 0, -1))
        self.ref = {}

    def alloc(self, count):
        if len(self.free) < count:
            raise MemoryError
        pages = [self.free.pop() for _ in range(count)]
        for p in pages:
            self.ref[p] = 1
        return pages

    def incref(self, pages):
        for p in pages:
            self.ref[p] += 1

    def decref(self, pages):
        for p in pages:
            self.ref[p] -= 1
            if self.ref[p] == 0:
                del self.ref[p]
                self.free.append(p)


def _check_equivalent(alloc, ref, rows_real, rows_ref):
    alloc.verify()
    assert alloc.free_pages == len(ref.free)
    ref_arr = np.zeros(alloc.total_pages, np.int64)
    ref_arr[TRASH_PAGE] = 1
    for p, c in ref.ref.items():
        ref_arr[p] = c
    np.testing.assert_array_equal(alloc._ref, ref_arr)
    assert rows_real == rows_ref  # block tables match page for page
    # flat_slots agrees with a hand computation for every live table.
    for table in rows_real:
        pos = np.arange(len(table) * alloc.page_size + 3)
        got = flat_slots(table, pos, alloc.page_size)
        for i in range(len(table) * alloc.page_size):
            assert got[i] == table[i // alloc.page_size] * alloc.page_size + (
                i % alloc.page_size
            )


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_allocator_matches_reference_under_random_schedule(seed):
    rng = random.Random(seed)
    ps = 4
    alloc = PageAllocator(48, ps)
    ref = _RefAllocator(48)
    rows_real, rows_ref = [], []  # parallel lists of block tables

    for _ in range(600):
        op = rng.random()
        if op < 0.40:  # admit: a fresh shared prompt run + one private page
            plen = rng.randint(1, 20)
            npages = pages_for(plen, ps)
            try:
                shared = alloc.alloc(npages)
            except PagePoolExhausted:
                with pytest.raises(MemoryError):
                    ref.alloc(npages)
                continue
            rows_real.append(list(shared))
            rows_ref.append(list(ref.alloc(npages)))
        elif op < 0.65 and rows_real:  # fork: new reader of an existing table
            i = rng.randrange(len(rows_real))
            alloc.incref(rows_real[i])
            ref.incref(rows_ref[i])
            rows_real.append(list(rows_real[i]))
            rows_ref.append(list(rows_ref[i]))
        elif op < 0.80 and rows_real:  # CoW: replace one shared page
            i = rng.randrange(len(rows_real))
            j = rng.randrange(len(rows_real[i]))
            if alloc.refcount(rows_real[i][j]) > 1:
                try:
                    new = alloc.alloc(1)[0]
                except PagePoolExhausted:
                    continue
                new_ref = ref.alloc(1)[0]
                alloc.decref([rows_real[i][j]])
                ref.decref([rows_ref[i][j]])
                rows_real[i][j] = new
                rows_ref[i][j] = new_ref
                alloc.note_cow()
        elif rows_real:  # retire
            i = rng.randrange(len(rows_real))
            alloc.decref(rows_real.pop(i))
            ref.decref(rows_ref.pop(i))
        _check_equivalent(alloc, ref, rows_real, rows_ref)

    while rows_real:  # drain: everything must come back
        alloc.decref(rows_real.pop())
        ref.decref(rows_ref.pop())
    _check_equivalent(alloc, ref, rows_real, rows_ref)
    assert alloc.free_pages == alloc.total_pages - 1
    assert alloc.snapshot()["in_use"] == 0


def test_misuse_raises_accounting_errors():
    alloc = PageAllocator(8, 4)
    pages = alloc.alloc(2)
    with pytest.raises(PageAccountingError):
        alloc.incref([TRASH_PAGE])
    with pytest.raises(PageAccountingError):
        alloc.decref([5])  # never allocated
    alloc.decref(pages)
    with pytest.raises(PageAccountingError):
        alloc.decref(pages)  # double free
    with pytest.raises(PagePoolExhausted):
        alloc.alloc(99)


def test_leak_detection_via_verify():
    alloc = PageAllocator(8, 4)
    alloc.verify()
    alloc.leak(2)
    with pytest.raises(PageAccountingError, match="leak"):
        alloc.verify()


def test_leak_failpoint_trips_loop_stats():
    """``engine.pages=leak:N`` fires on slot retirement in the continuous
    loop; the next ``stats`` read (what backend ``health()`` polls) must
    QUARANTINE the pool — report the accounting fault as data and flag the
    worker for rebuild — rather than raise into (and keep poisoning) every
    subsequent health poll. Uses a private engine: the poisoned pool must
    not leak into the shared fixtures."""
    from k_llms_tpu.engine.continuous import ContinuousDecodeLoop
    from k_llms_tpu.engine.engine import LocalEngine
    from k_llms_tpu.models import get_config

    from conftest import shared_params

    cfg = get_config("tiny")
    eng = LocalEngine(
        cfg, params=shared_params(cfg, 0), use_mesh=False,
        kv_layout="paged", kv_page_size=8,
    )
    loop = ContinuousDecodeLoop(eng, width=2, max_prompt=32, max_new=8)
    try:
        with failpoints({"engine.pages": FailSpec(action="leak", kill=2, times=1)}):
            loop.submit(
                [3, 1, 4, 1, 5], n=1, max_new=4, temperature=0.0, top_p=None,
                seed=2,
            ).result(timeout=120)
        pages = loop.stats["pages"]
        assert pages["quarantined"] is True
        assert "leak" in pages["error"]
        # Polling stays safe (no raise), and — with no rebuild path on this
        # bare loop — the worker drives the fault to a typed terminal state
        # instead of serving from the corrupt pool.
        deadline = time.monotonic() + 10.0
        while loop._terminal_error is None and time.monotonic() < deadline:
            _ = loop.stats
            time.sleep(0.01)
        assert isinstance(loop._terminal_error, EngineHungError)
        assert loop.stats["pages"]["quarantined"] is True
        with pytest.raises(EngineHungError):
            loop.submit(
                [3, 1, 4], n=1, max_new=2, temperature=0.0, top_p=None, seed=2
            )
    finally:
        loop.stop()


def test_leak_env_syntax_parses():
    from k_llms_tpu.reliability import failpoints as fp

    fp.configure_from_env("engine.pages=leak:3")
    try:
        spec = fp._registry["engine.pages"]
        assert spec.action == "leak" and spec.kill == 3
    finally:
        fp.clear()
