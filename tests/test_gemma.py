"""Gemma-2 family: GeGLU, offset RMSNorm, post-block norms, embed scaling,
softcaps, and alternating local/global attention — the most divergent
architecture the one-program transformer covers."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k_llms_tpu.engine.engine import LocalEngine
from k_llms_tpu.engine.tokenizer import ByteTokenizer
from k_llms_tpu.models import get_config, init_params
from k_llms_tpu.models.llama import decode_step, forward, init_cache, prefill, rms_norm

TINY_GEMMA = get_config("tiny").with_(
    name="tiny-gemma",
    sliding_window=5,
    sliding_window_layers="alternating",
    act="gelu",
    norm_offset=True,
    embed_scale=True,
    post_block_norms=True,
    attn_softcap=50.0,
    logit_softcap=30.0,
    query_scale=16.0**-0.5,
    num_layers=4,  # even count: two local, two global layers
)


def test_registry_gemma_configs():
    for name in ("gemma-2-2b", "gemma-2-9b"):
        cfg = get_config(name)
        assert cfg.post_block_norms and cfg.attn_softcap == 50.0
        assert cfg.sliding_window_layers == "alternating"


def test_offset_rms_norm():
    x = jnp.ones((1, 4), jnp.float32) * 2.0
    w = jnp.zeros((4,), jnp.float32)
    # offset: weight 0 means identity scale (1 + 0).
    np.testing.assert_allclose(
        np.asarray(rms_norm(x, w, 1e-6, offset=True)),
        np.asarray(rms_norm(x, jnp.ones((4,)), 1e-6, offset=False)),
        rtol=1e-6,
    )


def test_gemma_param_tree():
    params = init_params(TINY_GEMMA, jax.random.key(0))
    layers = params["layers"]
    assert "post_attn_norm" in layers and "post_mlp_norm" in layers
    # Offset norms initialize at 0 (effective scale 1).
    assert float(jnp.abs(layers["attn_norm"]).max()) == 0.0
    assert float(jnp.abs(params["final_norm"]).max()) == 0.0


def test_gemma_forward_shapes_and_softcap():
    params = init_params(TINY_GEMMA, jax.random.key(1))
    tokens = jax.random.randint(jax.random.key(2), (2, 12), 0, TINY_GEMMA.vocab_size)
    mask = jnp.ones_like(tokens)
    logits, hidden = forward(TINY_GEMMA, params, tokens, mask)
    assert logits.shape == (2, 12, TINY_GEMMA.vocab_size)
    # Final softcap bounds every logit strictly below the cap.
    assert float(jnp.abs(logits).max()) < 30.0


def test_gemma_decode_matches_forward():
    """Alternating local/global masks + shared-prefix decode must reproduce the
    full forward — this pins the per-layer jnp.where mask selection in the scan
    AND the windowed decode arithmetic simultaneously."""
    cfg = TINY_GEMMA
    params = init_params(cfg, jax.random.key(3))
    S = 16
    tokens = jax.random.randint(jax.random.key(4), (1, S), 0, cfg.vocab_size)
    prompt_len = jnp.int32(9)  # window 5 < prompt: both mask regimes exercised

    pl_logits, prefix = prefill(cfg, params, tokens, prompt_len)
    full, _ = forward(
        cfg, params, tokens, (jnp.arange(S)[None, :] < prompt_len).astype(jnp.int32)
    )
    np.testing.assert_allclose(pl_logits[0], full[0, 8], rtol=1e-4, atol=1e-4)

    n = 2
    gen_cache = init_cache(cfg, n, 5)
    for step in range(4):
        tk = jnp.broadcast_to(tokens[0, 9 + step], (n,))
        logits, gen_cache = decode_step(
            cfg, params, tk, jnp.int32(step), prompt_len, gen_cache, prefix
        )
        full_s, _ = forward(
            cfg, params, tokens, (jnp.arange(S)[None, :] < 10 + step).astype(jnp.int32)
        )
        np.testing.assert_allclose(logits[0], full_s[0, 9 + step], rtol=1e-4, atol=1e-4)


def test_alternating_differs_from_all_windowed():
    params = init_params(TINY_GEMMA, jax.random.key(5))
    all_local = TINY_GEMMA.with_(sliding_window_layers="all")
    S = 14
    tokens = jax.random.randint(jax.random.key(6), (1, S), 0, TINY_GEMMA.vocab_size)
    mask = jnp.ones_like(tokens)
    a, _ = forward(TINY_GEMMA, params, tokens, mask)
    b, _ = forward(all_local, params, tokens, mask)
    # Global layers see past the window; all-windowed layers cannot.
    assert not np.allclose(np.asarray(a[0, -1]), np.asarray(b[0, -1]))


def test_gemma_engine_generate():
    engine = LocalEngine(TINY_GEMMA, use_mesh=False)
    tok = ByteTokenizer()
    ids = tok.apply_chat_template([{"role": "user", "content": "gemma check"}])
    r = engine.generate(ids, n=3, max_new_tokens=6, temperature=1.0, seed=0)
    assert r.tokens.shape == (3, 6)
    again = engine.generate(ids, n=3, max_new_tokens=6, temperature=1.0, seed=0)
    np.testing.assert_array_equal(r.tokens, again.tokens)


def test_gemma_engine_sharded():
    if len(jax.devices()) < 4:
        pytest.skip("needs the 8-device CPU mesh")
    from k_llms_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(2, 2, jax.devices()[:4])
    engine = LocalEngine(TINY_GEMMA, mesh=mesh)
    tok = ByteTokenizer()
    ids = tok.apply_chat_template([{"role": "user", "content": "sharded gemma"}])
    r = engine.generate(ids, n=4, max_new_tokens=6, seed=1)
    assert r.tokens.shape == (4, 6)


def test_config_from_hf_gemma2(tmp_path):
    from k_llms_tpu.models.loader import config_from_hf

    hf = {
        "model_type": "gemma2",
        "vocab_size": 256128,
        "hidden_size": 2304,
        "intermediate_size": 9216,
        "num_hidden_layers": 26,
        "num_attention_heads": 8,
        "num_key_value_heads": 4,
        "head_dim": 256,
        "rope_theta": 10000.0,
        "rms_norm_eps": 1e-6,
        "max_position_embeddings": 8192,
        "sliding_window": 4096,
        "query_pre_attn_scalar": 256,
        "attn_logit_softcapping": 50.0,
        "final_logit_softcapping": 30.0,
        "bos_token_id": 2,
        "eos_token_id": 1,
        "pad_token_id": 0,
    }
    d = tmp_path / "gemma2"
    d.mkdir()
    (d / "config.json").write_text(json.dumps(hf))
    cfg = config_from_hf(str(d))
    assert cfg.act == "gelu" and cfg.norm_offset and cfg.embed_scale
    assert cfg.post_block_norms and cfg.sliding_window_layers == "alternating"
    assert cfg.attn_softcap == 50.0 and cfg.logit_softcap == 30.0
    assert cfg.query_scale == pytest.approx(256.0**-0.5)
    assert cfg.head_dim == 256  # from hf, NOT hidden/heads (2304/8=288)


def test_safetensors_import_gemma_norms(tmp_path):
    from safetensors.numpy import save_file

    from k_llms_tpu.models.loader import load_safetensors

    cfg = TINY_GEMMA.with_(dtype="float32")
    params = init_params(cfg, jax.random.key(7))
    rng = np.random.default_rng(0)
    for key in ("attn_norm", "mlp_norm", "post_attn_norm", "post_mlp_norm"):
        params["layers"][key] = jnp.asarray(
            rng.standard_normal(params["layers"][key].shape), jnp.float32
        )

    tensors = {
        "model.embed_tokens.weight": np.asarray(params["embed"]),
        "model.norm.weight": np.asarray(params["final_norm"]),
        # Tied embeddings: no lm_head.weight in the file (Gemma).
    }
    hf_weights = {
        "wq": "self_attn.q_proj",
        "wk": "self_attn.k_proj",
        "wv": "self_attn.v_proj",
        "wo": "self_attn.o_proj",
        "w_gate": "mlp.gate_proj",
        "w_up": "mlp.up_proj",
        "w_down": "mlp.down_proj",
    }
    hf_norms = {
        "attn_norm": "input_layernorm",
        "post_attn_norm": "post_attention_layernorm",
        "mlp_norm": "pre_feedforward_layernorm",
        "post_mlp_norm": "post_feedforward_layernorm",
    }
    for i in range(cfg.num_layers):
        for ours, hf in hf_weights.items():
            tensors[f"model.layers.{i}.{hf}.weight"] = np.ascontiguousarray(
                np.asarray(params["layers"][ours][i]).T
            )
        for ours, hf in hf_norms.items():
            tensors[f"model.layers.{i}.{hf}.weight"] = np.asarray(params["layers"][ours][i])
    ckpt = tmp_path / "hf-gemma"
    ckpt.mkdir()
    save_file(tensors, str(ckpt / "model.safetensors"))

    loaded = load_safetensors(str(ckpt), cfg, dtype=jnp.float32)
    # Norms land in the right slots (the post_attention_layernorm name trap).
    np.testing.assert_allclose(
        np.asarray(loaded["layers"]["post_attn_norm"]),
        np.asarray(params["layers"]["post_attn_norm"]),
    )
    np.testing.assert_allclose(
        np.asarray(loaded["layers"]["mlp_norm"]), np.asarray(params["layers"]["mlp_norm"])
    )
    # Tied embeddings: lm_head = embed.T
    np.testing.assert_allclose(
        np.asarray(loaded["lm_head"]), np.asarray(params["embed"]).T
    )


def test_gemma_flash_forward_matches_xla():
    """Flash prefill now covers softcap + alternating windows: the full Gemma-2
    style forward must match the XLA path."""
    import numpy as np

    cfg_xla = TINY_GEMMA.with_(attention_impl="xla")
    cfg_flash = TINY_GEMMA.with_(attention_impl="flash")
    params = init_params(cfg_xla, jax.random.key(2))
    S = 24
    tokens = jax.random.randint(jax.random.key(3), (2, S), 0, cfg_xla.vocab_size)
    mask = (jnp.arange(S)[None, :] < jnp.array([[S], [17]])).astype(jnp.int32)
    a, _ = forward(cfg_xla, params, tokens, mask)
    b, _ = forward(cfg_flash, params, tokens, mask)
    # Padded query rows whose sliding window misses the valid range entirely
    # have no defined output (kernel zeroes them, XLA spreads uniform) — only
    # the valid rows carry semantics.
    np.testing.assert_allclose(
        np.asarray(a)[0], np.asarray(b)[0], rtol=2e-3, atol=2e-3
    )
    np.testing.assert_allclose(
        np.asarray(a)[1, :17], np.asarray(b)[1, :17], rtol=2e-3, atol=2e-3
    )
