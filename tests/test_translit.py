"""First-party transliteration vs hand-encoded real-unidecode vectors.

Pins `k_llms_tpu/consensus/translit.py` to the reference's sanitization
behavior (`/root/reference/k_llms/utils/consensus_utils.py:15,925-933`) on
Latin/Cyrillic/Greek and (since round 5) CJK — hanzi pinyin, kana romaji,
Hangul — and documents the remaining intentional divergence on rare
long-tail ideographs.
"""

import pytest

from fixtures.unidecode_vectors import (
    DIVERGENT_VECTORS,
    PARITY_VECTORS,
    UNIDECODE_PINNED_VERSION,
    UNVERIFIED_DIVERGENT_VECTORS,
)
from k_llms_tpu.consensus.settings import ConsensusSettings
from k_llms_tpu.consensus.text import ascii_fold, sanitize_value
from k_llms_tpu.consensus.translit import transliterate
from k_llms_tpu.consensus.voting import voting_consensus


@pytest.mark.parametrize("inp,expected", PARITY_VECTORS, ids=[v[0] for v in PARITY_VECTORS])
def test_parity_with_real_unidecode(inp, expected):
    assert transliterate(inp) == expected


_ALL_DIVERGENT = DIVERGENT_VECTORS + UNVERIFIED_DIVERGENT_VECTORS


@pytest.mark.parametrize(
    "inp,real,ours", _ALL_DIVERGENT, ids=[v[0] for v in _ALL_DIVERGENT]
)
def test_documented_long_tail_divergence(inp, real, ours):
    # real unidecode romanizes even rare tail ideographs (full Unihan tables);
    # we emit per-codepoint tokens for them (distinctness only).  The strong
    # claim is ``got == ours`` (exact per-codepoint token form); ``got != real``
    # is asserted only for wheel-VERIFIED pins — on unverified ones it could
    # never fail against a wrong pin (ADVICE.md #3), so it proves nothing.
    got = transliterate(inp)
    assert got == ours
    if (inp, real, ours) in DIVERGENT_VECTORS:
        assert got != real  # the divergence is intentional and documented


def test_pins_match_installed_unidecode_wheel():
    """Verify every hand-encoded "real unidecode" pin against the actual
    wheel.  The CI image doesn't ship unidecode, so this skips there — but any
    environment that has it (a dev box, a future image bump) validates the
    whole fixture and flags entries that can be promoted out of
    UNVERIFIED_DIVERGENT_VECTORS."""
    unidecode = pytest.importorskip("unidecode")
    version = getattr(unidecode, "__version__", None) or pytest.importorskip(
        "importlib.metadata"
    ).version("Unidecode")
    assert version == UNIDECODE_PINNED_VERSION, (
        f"installed unidecode {version} != pinned {UNIDECODE_PINNED_VERSION}; "
        "re-verify the fixture vectors before bumping the pin"
    )
    for inp, real in PARITY_VECTORS:
        assert unidecode.unidecode(inp) == real, f"parity pin wrong for {inp!r}"
    for inp, real, _ in _ALL_DIVERGENT:
        assert unidecode.unidecode(inp) == real, (
            f"divergent 'real' pin wrong for {inp!r}"
        )


def test_cjk_vote_keys_match_reference_pipeline():
    # The reference sanitizes str(v).lower().replace(" ","") -> unidecode ->
    # strip non-alnum (consensus_utils.py:925-933).  lower() precedes the
    # romanization, so pinyin capitals survive into the vote key.
    assert sanitize_value("北京") == "BeiJing"
    assert sanitize_value("東京") == "DongJing"
    assert sanitize_value("こんにちは") == "konnichiha"
    assert sanitize_value("서울") == "seoul"
    # Native-script and romanized spellings of the same name now produce
    # vote keys with identical letters (case differs exactly as it would
    # under the reference's pipeline, which also lowercases *before* folding).
    assert sanitize_value("北京").lower() == sanitize_value("Beijing")


def test_ascii_fold_is_transliterate():
    assert ascii_fold("Μοσχάτο Москва") == transliterate("Μοσχάτο Москва")


def test_distinct_nonlatin_vote_keys():
    # VERDICT r2 acceptance: "Москва" vs "Berlin" must be distinct vote keys.
    assert sanitize_value("Москва") != sanitize_value("Berlin")
    assert sanitize_value("Москва") == "moskva"
    # Same value spelled with/without accents still collapses (desired).
    assert sanitize_value("café") == sanitize_value("cafe")
    # Distinct CJK strings stay distinct even without romanization.
    assert sanitize_value("北京") != sanitize_value("東京")
    assert sanitize_value("北京") != ""
    # Arbitrary unmapped scripts (Hebrew, Arabic, Hangul) never collapse to "".
    for a, b in [("מוסקבה", "ברלין"), ("مدينة", "قرية"), ("서울", "부산")]:
        ka, kb = sanitize_value(a), sanitize_value(b)
        assert ka and kb and ka != kb


def test_voting_no_longer_collapses_nonlatin():
    # 3 distinct Cyrillic city votes: majority must win on its own merits,
    # not because all three folded to "" and shared one bucket.
    settings = ConsensusSettings()
    winner, conf = voting_consensus(
        ["Москва", "Москва", "Берлин"], settings, parent_valid_frac=1.0
    )
    assert winner == "Москва"
    assert conf == pytest.approx(round(2 / 3, 5), abs=1e-9)  # reference rounds to 5 dp


def test_capitalization_style_matches_unidecode():
    # unidecode capitalizes only the first romanized letter: Щ -> "Shch".
    assert transliterate("Щ") == "Shch"
    assert transliterate("Ж") == "Zh"
    assert transliterate("Θ") == "Th"
    assert transliterate("Ψ") == "Ps"


def test_hard_soft_signs_match_unidecode():
    # unidecode maps ъ -> '"' and ь -> "'" (stripped later by the vote-key
    # regex, but string-level parity keeps the oracle honest).
    assert transliterate("объект") == 'ob"ekt'
    assert transliterate("Ярославль") == "Iaroslavl'"
