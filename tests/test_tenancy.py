"""Multi-tenant isolation (ISSUE 16 tentpole).

Fast deterministic coverage of the tenancy subsystem and its scheduler
integration: token buckets under an injected clock, registry resolution
(configured / dynamic / API-key), quota 429s whose ``retry_after`` is the
tenant's OWN bucket refill (not the global drain estimate), the keyed
``scheduler.tenant=exhaust`` failpoint, weighted-fair dequeue across tenant
queues, interactive-before-batch ordering, brownout shedding of batch-class
work, tiered eviction (batch first, then over-quota tenants, then priority),
and the drained-rate fix (shed work never inflates the drain estimate).
"""

import threading
import time

import pytest

from k_llms_tpu.engine.scheduler import EngineScheduler
from k_llms_tpu.reliability.deadline import RequestBudget
from k_llms_tpu.reliability.failpoints import FailSpec, failpoints
from k_llms_tpu.reliability.tenancy import (
    DEFAULT_TENANT,
    TenancyConfig,
    TenantContext,
    TenantSpec,
    TokenBucket,
)
from k_llms_tpu.types import RateLimitError


class _Clock:
    """Injectable monotonic clock."""

    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _echo(payloads):
    return list(payloads)


def _blocked_scheduler(**kwargs):
    """A scheduler whose worker is parked on an Event, so queued items stay
    queued until the test releases the gate."""
    sched = EngineScheduler(name="test", batch_window=0.0, **kwargs)
    gate = threading.Event()
    blocker = sched.submit(gate.wait)
    for _ in range(200):
        if sched.stats["queued"] == 0 and blocker.running():
            break
        time.sleep(0.005)
    return sched, gate, blocker


# ---------------------------------------------------------------------------
# token buckets
# ---------------------------------------------------------------------------


def test_token_bucket_burst_and_refill():
    clock = _Clock()
    b = TokenBucket(rate=2.0, burst=4.0, clock=clock)
    assert b.level() == 4.0
    assert b.try_take(4.0)
    assert not b.try_take(1.0)  # empty; level untouched by the failed take
    assert b.time_until(1.0) == pytest.approx(0.5)  # 1 token / 2 per s
    clock.advance(0.5)
    assert b.try_take(1.0)
    clock.advance(100.0)
    assert b.level() == 4.0  # refill clamps at burst


def test_token_bucket_over_burst_cost_reports_finite_horizon():
    clock = _Clock()
    b = TokenBucket(rate=1.0, burst=2.0, clock=clock)
    b.try_take(2.0)
    # A cost that can never fit still gets the full-burst horizon, not inf.
    assert b.time_until(100.0) == pytest.approx(2.0)


def test_token_bucket_validation():
    with pytest.raises(ValueError):
        TokenBucket(rate=0.0, burst=1.0)
    with pytest.raises(ValueError):
        TokenBucket(rate=1.0, burst=-1.0)


# ---------------------------------------------------------------------------
# specs + registry resolution
# ---------------------------------------------------------------------------


def test_tenant_spec_validation():
    with pytest.raises(ValueError):
        TenantSpec(name="x", slo="gold")
    with pytest.raises(ValueError):
        TenantSpec(name="x", weight=0.0)
    with pytest.raises(ValueError):
        TenantSpec(name="x", requests_per_s=-1.0)
    with pytest.raises(ValueError):
        TenantSpec(name="")


def test_registry_resolution_and_overrides():
    cfg = TenancyConfig.from_options(
        default_weight=1.0,
        default_requests_per_s=10.0,
        tenants={"bulk": {"slo": "batch", "weight": 2.0, "rows_per_s": 8.0}},
        api_keys={"sk-abc": "bulk"},
    )
    default = cfg.resolve(None)
    assert default.name == DEFAULT_TENANT
    assert default.interactive and default.limited
    bulk = cfg.resolve("bulk")
    assert not bulk.interactive
    assert bulk.weight == 2.0
    # Overrides inherit unset fields from the default spec.
    assert bulk.spec.requests_per_s == 10.0
    # Same name resolves to the SAME live context (shared bucket state).
    assert cfg.resolve("bulk") is bulk
    # A context passes straight through.
    assert cfg.resolve(bulk) is bulk
    # API-key mapping; unmapped keys become their own tenant name.
    assert cfg.tenant_for_key("sk-abc") == "bulk"
    assert cfg.tenant_for_key(None) == DEFAULT_TENANT
    assert cfg.tenant_for_key("") == DEFAULT_TENANT
    assert cfg.tenant_for_key("sk-unknown") == "sk-unknown"
    # Dynamic tenants materialize under default policy with their OWN buckets.
    dyn = cfg.resolve("sk-unknown")
    assert dyn.name == "sk-unknown"
    assert dyn.spec.requests_per_s == 10.0
    assert dyn is not default
    assert "bulk" in cfg.known_tenants()


def test_try_admit_charges_both_buckets_atomically():
    clock = _Clock()
    ctx = TenantContext(
        TenantSpec(
            name="m", requests_per_s=100.0, rows_per_s=4.0, rows_burst=4.0
        ),
        clock=clock,
    )
    assert ctx.try_admit(rows=4) is None
    # Row bucket is empty; request bucket must NOT have been charged for the
    # rejected attempt (atomicity): horizon reflects rows only.
    wait = ctx.try_admit(rows=4)
    assert wait == pytest.approx(1.0)
    assert ctx.over_quota()
    snap = ctx.quota_snapshot()
    assert snap["request_tokens"] == pytest.approx(99.0)
    assert snap["row_tokens"] == 0.0
    clock.advance(1.0)
    assert ctx.refill_horizon(rows=4) == 0.0


# ---------------------------------------------------------------------------
# scheduler quota charging: tenant-owned retry_after
# ---------------------------------------------------------------------------


def test_quota_429_retry_after_is_tenants_own_refill():
    clock = _Clock()
    tenancy = TenancyConfig.from_options(
        tenants={"meter": {"requests_per_s": 0.5, "request_burst": 1.0}},
        clock=clock,
    )
    sched = EngineScheduler(name="t", batch_window=0.0, tenancy=tenancy)
    try:
        ctx = sched.charge_tenant_quota("meter")
        assert isinstance(ctx, TenantContext) and ctx.name == "meter"
        with pytest.raises(RateLimitError) as ei:
            sched.charge_tenant_quota("meter")
        # The hint is THIS tenant's bucket refill (1 token / 0.5 per s = 2 s),
        # not the global drain-rate estimate.
        assert ei.value.retry_after == pytest.approx(2.0)
        # Other tenants are untouched by meter's exhaustion.
        sched.charge_tenant_quota("other")
        health = sched.health()
        assert health["shed_quota"] == 1
        assert health["tenants"]["meter"]["shed_quota"] == 1
        clock.advance(2.0)
        sched.charge_tenant_quota("meter")  # refilled
    finally:
        sched.shutdown()


def test_scheduler_tenant_exhaust_failpoint_is_keyed():
    sched = EngineScheduler(name="t", batch_window=0.0)
    try:
        with failpoints(
            {"scheduler.tenant": FailSpec(action="exhaust", member="bulk", times=1)}
        ):
            # Non-matching tenant: the keyed spec neither fires nor burns times.
            sched.charge_tenant_quota("chat")
            with pytest.raises(RateLimitError) as ei:
                sched.charge_tenant_quota("bulk")
            assert "forced by failpoint" in str(ei.value)
            # Unlimited tenant: horizon 0 floors at the 0.1 s minimum hint.
            assert ei.value.retry_after == pytest.approx(0.1)
            sched.charge_tenant_quota("bulk")  # times=1 consumed
    finally:
        sched.shutdown()


# ---------------------------------------------------------------------------
# weighted-fair dequeue
# ---------------------------------------------------------------------------


def test_wfq_serves_tenants_by_weight():
    tenancy = TenancyConfig.from_options(
        tenants={"gold": {"weight": 3.0}, "bronze": {"weight": 1.0}}
    )
    sched, gate, blocker = _blocked_scheduler(tenancy=tenancy)
    try:
        order = []
        futures = []
        # Bronze enqueues FIRST — under FIFO it would drain first; under WFQ
        # gold's 3x weight earns ~3 of every 4 early slots.
        for name in ("bronze", "gold"):
            for i in range(12):
                key = (name, i)  # distinct keys: no coalescing across items

                def fn(payloads, _name=name):
                    order.extend(_name for _ in payloads)
                    return list(payloads)

                futures.append(
                    sched.submit_batched(key, i, fn, weight=1, tenant=name)
                )
        gate.set()
        for f in futures:
            f.result(timeout=30)
        assert len(order) == 24
        first12 = order[:12]
        assert first12.count("gold") >= 8, first12
        assert first12.count("bronze") >= 1, first12  # no starvation either
    finally:
        gate.set()
        sched.shutdown()


def test_interactive_class_drains_before_batch():
    tenancy = TenancyConfig.from_options(
        tenants={"bulk": {"slo": "batch"}, "chat": {"slo": "interactive"}}
    )
    sched, gate, blocker = _blocked_scheduler(tenancy=tenancy)
    try:
        order = []
        futures = []
        # Bulk enqueues first; chat must still be served strictly first.
        for name in ("bulk", "chat"):
            for i in range(6):
                def fn(payloads, _name=name):
                    order.extend(_name for _ in payloads)
                    return list(payloads)

                futures.append(
                    sched.submit_batched((name, i), i, fn, weight=1, tenant=name)
                )
        gate.set()
        for f in futures:
            f.result(timeout=30)
        assert order[:6] == ["chat"] * 6, order
        health = sched.health()
        assert health["tenants"]["chat"]["served"] == 6
        assert health["tenants"]["bulk"]["served"] == 6
    finally:
        gate.set()
        sched.shutdown()


# ---------------------------------------------------------------------------
# brownout + tiered eviction
# ---------------------------------------------------------------------------


def test_brownout_sheds_batch_class_with_typed_429():
    tenancy = TenancyConfig.from_options(tenants={"bulk": {"slo": "batch"}})
    sched, gate, blocker = _blocked_scheduler(
        tenancy=tenancy, max_queue_weight=10
    )
    try:
        fillers = [
            sched.submit_batched(("f", i), i, _echo, weight=3, tenant="chat")
            for i in range(3)
        ]  # queued weight 9 >= 0.9 * 10 -> brownout
        assert sched.health()["brownout"] is True
        shed = sched.submit_batched(("b", 0), 0, _echo, weight=1, tenant="bulk")
        with pytest.raises(RateLimitError) as ei:
            shed.result(timeout=5)
        assert "brownout" in str(ei.value)
        assert ei.value.retry_after >= 0.1
        # In-SLO interactive work still fits under the hard cap.
        ok = sched.submit_batched(("c", 0), 0, _echo, weight=1, tenant="chat")
        health = sched.health()
        assert health["shed_brownout"] == 1
        assert health["tenants"]["bulk"]["shed_brownout"] == 1
        gate.set()
        for f in fillers + [ok]:
            f.result(timeout=30)
    finally:
        gate.set()
        sched.shutdown()


def test_eviction_prefers_batch_class_over_equal_priority():
    tenancy = TenancyConfig.from_options(tenants={"bulk": {"slo": "batch"}})
    sched, gate, blocker = _blocked_scheduler(
        tenancy=tenancy, max_queue_weight=4, brownout_high_water=2.0
    )
    try:
        # brownout_high_water=2.0 keeps the brownout gate closed so this
        # exercises the capacity/eviction path in isolation.
        bulk = [
            sched.submit_batched(("b", i), i, _echo, weight=2, tenant="bulk")
            for i in range(2)
        ]
        # Queue full (weight 4/4). An INTERACTIVE arrival at the same
        # priority evicts batch-class work (tier 1) — pre-tenancy rules would
        # have shed the newcomer.
        chat = sched.submit_batched(("c", 0), 0, _echo, weight=2, tenant="chat")
        evicted = [f for f in bulk if f.done()]
        assert len(evicted) == 1
        with pytest.raises(RateLimitError):
            evicted[0].result()
        assert sched.health()["tenants"]["bulk"]["evicted"] == 1
        gate.set()
        assert chat.result(timeout=30) == 0
    finally:
        gate.set()
        sched.shutdown()


def test_no_cross_eviction_among_equal_interactive_tenants():
    tenancy = TenancyConfig.from_options(tenants={"a": {}, "b": {}})
    sched, gate, blocker = _blocked_scheduler(
        tenancy=tenancy, max_queue_weight=2, brownout_high_water=2.0
    )
    try:
        held = sched.submit_batched(("a", 0), 0, _echo, weight=2, tenant="a")
        # Equal class, equal priority, neither over quota: the newcomer is
        # shed, the queued item survives (the PR 2 contract, per tenant).
        shed = sched.submit_batched(("b", 0), 0, _echo, weight=2, tenant="b")
        with pytest.raises(RateLimitError):
            shed.result(timeout=5)
        assert not held.done()
        gate.set()
        assert held.result(timeout=30) == 0
    finally:
        gate.set()
        sched.shutdown()


def test_eviction_prefers_over_quota_tenant_second():
    clock = _Clock()
    tenancy = TenancyConfig.from_options(
        tenants={"greedy": {"requests_per_s": 1.0, "request_burst": 1.0}},
        clock=clock,
    )
    sched, gate, blocker = _blocked_scheduler(
        tenancy=tenancy, max_queue_weight=2, brownout_high_water=2.0
    )
    try:
        # Drain greedy's request bucket so it is over quota, then queue its
        # item (queued BEFORE the bucket check matters: eviction reads the
        # live bucket state at arrival time of the newcomer).
        assert tenancy.resolve("greedy").try_admit() is None
        held = sched.submit_batched(
            ("g", 0), 0, _echo, weight=2, tenant="greedy"
        )
        assert tenancy.resolve("greedy").over_quota()
        chat = sched.submit_batched(("c", 0), 0, _echo, weight=2, tenant="chat")
        assert held.done()  # evicted: over-quota tenant displaced (tier 2)
        with pytest.raises(RateLimitError):
            held.result()
        gate.set()
        assert chat.result(timeout=30) == 0
    finally:
        gate.set()
        sched.shutdown()


# ---------------------------------------------------------------------------
# drain-rate excludes shed work (satellite 1)
# ---------------------------------------------------------------------------


def test_drain_rate_excludes_shed_work():
    sched, gate, blocker = _blocked_scheduler()
    try:
        budget = RequestBudget.from_timeout(0.01)
        futures = [
            sched.submit_batched(("k", i), i, _echo, weight=4, budget=budget)
            for i in range(4)
        ]
        time.sleep(0.05)  # budgets expire while queued
        gate.set()
        for f in futures:
            with pytest.raises(Exception):
                f.result(timeout=30)
        for _ in range(200):
            if sched.health()["shed"] >= 4:
                break
            time.sleep(0.005)
        health = sched.health()
        assert health["shed"] >= 4
        # Every queued item was shed at dequeue: none of that weight reached
        # the runner, so the drain-rate estimate must not count it (a 429's
        # global retry hint would otherwise promise capacity that was never
        # actually served).
        assert health["drain_rate"] == 0.0
    finally:
        gate.set()
        sched.shutdown()


# ---------------------------------------------------------------------------
# health surface
# ---------------------------------------------------------------------------


def test_health_reports_per_tenant_queues_and_quota():
    tenancy = TenancyConfig.from_options(
        tenants={"bulk": {"slo": "batch", "weight": 2.0}}
    )
    sched, gate, blocker = _blocked_scheduler(tenancy=tenancy)
    try:
        f = sched.submit_batched(("b", 0), 0, _echo, weight=3, tenant="bulk")
        health = sched.health()
        entry = health["tenants"]["bulk"]
        assert entry["slo"] == "batch"
        assert entry["weight"] == 2.0
        assert entry["queued"] == 1
        assert entry["queued_weight"] == 3
        gate.set()
        f.result(timeout=30)
    finally:
        gate.set()
        sched.shutdown()
