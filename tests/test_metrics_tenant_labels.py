"""Prometheus label escaping for hostile tenant ids (ISSUE 16 satellite).

Unmapped API keys become their own dynamic tenant names, and tenant names
become ``tenant=\"...\"`` label VALUES on the per-tenant histogram and event
families — so an adversarial Authorization header (double quotes, backslashes,
newlines) flows straight toward the ``/metrics`` exposition. These tests pin
that such ids are escaped per the 0.0.4 text format and can never break a
sample line, inject fake samples, or smuggle a newline into the scrape.
"""

import asyncio

import httpx

from k_llms_tpu import KLLMs
from k_llms_tpu.backends.fake import FakeBackend
from k_llms_tpu.observability.prometheus import (
    escape_label_value,
    labeled_histogram_family,
    render_families,
)
from k_llms_tpu.serving import ServingApp

BODY = {
    "messages": [{"role": "user", "content": "say something"}],
    "model": "fake-model",
    "n": 2,
    "seed": 3,
}

#: Hostile tenant id: every character class the exposition format escapes,
#: plus an attempted sample-line injection after a newline.
HOSTILE = 'ten"ant\\evil\nkllms_fake_total{x="y"} 999'


def _run(coro):
    return asyncio.run(coro)


def _asgi(app):
    return httpx.AsyncClient(
        transport=httpx.ASGITransport(app=app), base_url="http://testserver"
    )


def test_escape_label_value_order_and_coverage():
    # Backslash first (or the quote escape would be double-escaped), then
    # quote, then newline.
    assert escape_label_value('a\\b"c\nd') == 'a\\\\b\\"c\\nd'
    assert escape_label_value(HOSTILE).count("\n") == 0


def test_labeled_family_renders_hostile_tenant_on_one_line():
    snap = {"buckets": [(0.1, 1), (1.0, 2)], "sum": 0.3, "count": 2}
    fam = labeled_histogram_family(
        "kllms_request_e2e_by_tenant_seconds", "per-tenant e2e", {HOSTILE: snap}
    )
    text = render_families([fam])
    lines = text.strip().split("\n")
    # 2 meta lines + 3 buckets (incl +Inf) + _sum + _count — the embedded
    # newline in the tenant id must NOT have minted extra lines.
    assert len(lines) == 7
    for line in lines[2:]:
        assert line.startswith("kllms_request_e2e_by_tenant_seconds")
        assert 'tenant="ten\\"ant\\\\evil\\nkllms_fake_total{x=\\"y\\"} 999"' in line
    # The injection payload never appears as its own sample.
    assert "\nkllms_fake_total" not in text


def test_hostile_api_key_cannot_corrupt_metrics_scrape():
    from k_llms_tpu.utils.observability import LATENCY, TENANT_EVENTS

    client = KLLMs(
        backend=FakeBackend(["alpha beta", "alpha"]), model="fake-model"
    )
    app = ServingApp(client)

    async def go():
        async with _asgi(app) as c:
            # httpx forbids raw newlines in header values, so exercise the
            # quote/backslash classes over HTTP...
            r = await c.post(
                "/v1/chat/completions",
                json=BODY,
                headers={"Authorization": 'Bearer k"ey\\with"quotes'},
            )
            assert r.status_code == 200
            return await c.get("/metrics")

    try:
        resp = _run(go())
        assert resp.status_code == 200
        text = resp.text
        assert 'tenant="k\\"ey\\\\with\\"quotes"' in text
        # ...and the newline class through the tracer/counter path directly:
        # observations carrying the fully hostile tenant id still render one
        # sample per line and every line parses as `name{labels} value`.
        LATENCY.observe(f"request.e2e.{HOSTILE}", 0.25)
        TENANT_EVENTS.record(f"tenant.requests.{HOSTILE}")
        resp2 = _run(_scrape(app))
        for line in resp2.text.strip().split("\n"):
            assert line, "blank line injected into exposition"
            if line.startswith("#"):
                continue
            name_and_labels, _, value = line.rpartition(" ")
            assert name_and_labels and not name_and_labels.startswith("{")
            float(value)  # every sample line ends in a parseable number
        assert 'kllms_fake_total{x="y"} 999' not in resp2.text
    finally:
        # Hostile ids live in process-global counters; don't leak them into
        # other tests' scrapes.
        LATENCY.reset()
        TENANT_EVENTS.reset()


async def _scrape(app):
    async with _asgi(app) as c:
        return await c.get("/metrics")
