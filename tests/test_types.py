"""Golden tests for the response contract (SURVEY.md §7 stage 1)."""

import json

from k_llms_tpu.types import (
    ChatCompletion,
    CompletionUsage,
    KLLMsChatCompletion,
    KLLMsParsedChatCompletion,
)


def make_completion(contents, model="llama-3-8b"):
    return ChatCompletion.model_validate(
        {
            "id": "chatcmpl-test",
            "created": 1735000000,
            "model": model,
            "object": "chat.completion",
            "choices": [
                {
                    "finish_reason": "stop",
                    "index": i,
                    "message": {"role": "assistant", "content": c},
                }
                for i, c in enumerate(contents)
            ],
            "usage": {"prompt_tokens": 10, "completion_tokens": 20, "total_tokens": 30},
        }
    )


def test_chat_completion_roundtrip():
    comp = make_completion(["hello", "world"])
    dumped = comp.model_dump()
    assert dumped["object"] == "chat.completion"
    assert dumped["choices"][0]["message"]["role"] == "assistant"
    assert dumped["choices"][1]["message"]["content"] == "world"
    re = ChatCompletion.model_validate(dumped)
    assert re == comp


def test_kllms_completion_adds_likelihoods():
    comp = make_completion(["x"])
    k = KLLMsChatCompletion.model_validate({**comp.model_dump(), "likelihoods": {"a": 0.5}})
    assert k.likelihoods == {"a": 0.5}
    # default None and survives serialization
    k2 = KLLMsChatCompletion.model_validate(comp.model_dump())
    assert k2.likelihoods is None
    payload = json.loads(k.model_dump_json())
    assert payload["likelihoods"] == {"a": 0.5}


def test_parsed_completion_carries_parsed_field():
    payload = make_completion([json.dumps({"a": 1})]).model_dump()
    payload["choices"][0]["message"]["parsed"] = {"a": 1}
    k = KLLMsParsedChatCompletion.model_validate(payload)
    assert k.choices[0].message.parsed == {"a": 1}


def test_usage_details_optional():
    u = CompletionUsage(prompt_tokens=1, completion_tokens=2, total_tokens=3)
    assert u.prompt_tokens_details is None
    d = u.model_dump()
    assert d["total_tokens"] == 3


def test_unknown_fields_tolerated():
    payload = make_completion(["x"]).model_dump()
    payload["some_future_field"] = {"y": 1}
    comp = ChatCompletion.model_validate(payload)
    assert comp.model_dump()["some_future_field"] == {"y": 1}
