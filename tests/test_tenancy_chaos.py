"""Starvation-freedom chaos soak for multi-tenant isolation (ISSUE 16).

The acceptance drill: a batch-class tenant floods the continuous-batching
backend with far more offered load than it can drain while an interactive
tenant keeps a steady trickle — with the lock-order graph and the
Eraser-style lockset sanitizer armed (KLLMS_LOCKCHECK=1 + KLLMS_RACECHECK=1)
and the keyed ``scheduler.tenant=exhaust`` failpoint firing against the
flooding tenant mid-soak. Invariants: the interactive tenant is NEVER
starved (every chat request completes, bounded p99), zero hung futures,
every failure is a typed KLLMsError, sheds land on the batch tenant only,
and both sanitizers come out clean at exit.
"""

import threading
import time

import pytest

from k_llms_tpu import KLLMs
from k_llms_tpu.analysis import lockcheck
from k_llms_tpu.reliability import failpoints as fp
from k_llms_tpu.reliability.failpoints import FailSpec
from k_llms_tpu.types.wire import KLLMsError, RateLimitError
from k_llms_tpu.utils.observability import LATENCY, TENANT_EVENTS

#: Interactive requests must clear the flooded queue well inside this bound —
#: generous against CPU-jit noise, tiny against the flood's total drain time.
CHAT_P99_BOUND_S = 90.0


def _backend():
    import jax
    from conftest import shared_engine

    from k_llms_tpu.backends.tpu import TpuBackend

    engine = (
        shared_engine("tiny", mesh_shape=(8, 1)) if len(jax.devices()) == 8 else None
    )
    return TpuBackend(
        model="tiny", max_new_tokens=8, engine=engine,
        continuous_batching=True, continuous_width=4,
        continuous_max_prompt=128, continuous_max_new=64,
        # Equal weights: isolation must come from the SLO class (interactive
        # before batch in WFQ selection), not from a weight thumb on the
        # scale.
        tenants={
            "bulk": {"slo": "batch", "weight": 1.0},
            "chat": {"slo": "interactive", "weight": 1.0},
        },
    )


@pytest.mark.slow
@pytest.mark.duration_budget(300)
def test_interactive_tenant_never_starves_under_batch_flood(monkeypatch):
    monkeypatch.setenv("KLLMS_LOCKCHECK", "1")
    monkeypatch.setenv("KLLMS_RACECHECK", "1")
    lockcheck.reset_state()
    LATENCY.reset()
    TENANT_EVENTS.reset()
    backend = _backend()
    client = KLLMs(backend=backend, model="tiny")
    results = {}
    chat_e2e = {}
    lock = threading.Lock()

    def worker(key, tenant, seed):
        msgs = [{"role": "user", "content": f"soak {key}"}]
        t0 = time.monotonic()
        try:
            cc = client.chat.completions.create(
                messages=msgs, model="tiny", n=2, seed=seed,
                temperature=0.8, tenant=tenant,
            )
            with lock:
                results[key] = ("ok", cc)
                if tenant == "chat":
                    chat_e2e[key] = time.monotonic() - t0
        except KLLMsError as e:
            # Typed errors only — anything else propagates and fails the test.
            with lock:
                results[key] = ("typed", e)

    n_bulk, n_chat = 18, 6
    # The flood: every bulk request submitted up front, far over what a
    # width-4 loop drains promptly. Mid-flood the keyed failpoint force-
    # exhausts bulk's buckets twice — those two requests must land as typed
    # 429s on bulk alone while chat rides through untouched.
    threads = []
    with fp.failpoints(
        {"scheduler.tenant": FailSpec(action="exhaust", member="bulk", times=2)}
    ):
        for i in range(n_bulk):
            t = threading.Thread(target=worker, args=(f"bulk{i}", "bulk", 400 + i))
            threads.append(t)
            t.start()
        # Steady interactive trickle while the flood is queued: each chat
        # request arrives AFTER bulk work is already piled up, so finishing
        # promptly proves class-first WFQ selection, not lucky ordering.
        for i in range(n_chat):
            time.sleep(0.5)
            t = threading.Thread(target=worker, args=(f"chat{i}", "chat", 600 + i))
            threads.append(t)
            t.start()
        for t in threads:
            t.join(timeout=240.0)
        # The headline invariant: zero hung futures / zero hung clients.
        assert not any(t.is_alive() for t in threads)

    assert len(results) == n_bulk + n_chat

    # Interactive starvation freedom: every chat request SUCCEEDED (no sheds,
    # no 429s) and its e2e latency stayed bounded despite the standing flood.
    chat_results = {k: r for k, r in results.items() if k.startswith("chat")}
    assert all(r[0] == "ok" for r in chat_results.values()), chat_results
    assert len(chat_e2e) == n_chat
    p99 = sorted(chat_e2e.values())[-1]
    assert p99 < CHAT_P99_BOUND_S, f"interactive p99 {p99:.1f}s — starved"

    # The forced exhausts hit bulk (typed RateLimitError with the tenant's
    # own refill horizon) and ONLY bulk.
    rate_limited = [
        r[1] for r in results.values() if r[0] == "typed"
    ]
    assert all(isinstance(e, RateLimitError) for e in rate_limited)
    assert len(rate_limited) == 2
    for e in rate_limited:
        assert "bulk" in str(e) and "forced by failpoint" in str(e)
        assert e.retry_after is not None and e.retry_after >= 0.1
    events = TENANT_EVENTS.snapshot()
    assert events.get("tenant.shed_quota.bulk", 0) == 2
    for shed in ("shed_quota", "shed_brownout", "shed_over_capacity", "evicted"):
        assert events.get(f"tenant.{shed}.chat", 0) == 0, (shed, events)

    # Every non-shed bulk request still completed: batch class is deprioritized,
    # never abandoned.
    bulk_ok = [k for k in results if k.startswith("bulk") and results[k][0] == "ok"]
    assert len(bulk_ok) == n_bulk - 2

    # Per-tenant observability came along for the ride: both tenants have
    # queue-wait attribution, and admissions were counted per tenant.
    lat = LATENCY.snapshot()
    chat_wait = lat.get("scheduler.queue_wait.chat", {})
    assert chat_wait.get("count", 0) >= n_chat
    # Bounded p99 queue wait for the interactive class, straight off the
    # histogram: EVERY chat observation landed inside the largest finite
    # bucket at or under the bound (cumulative count == total count).
    in_bound = max(
        (cum for bound, cum in chat_wait["buckets"] if bound <= CHAT_P99_BOUND_S),
        default=0,
    )
    assert in_bound == chat_wait["count"], chat_wait
    assert lat.get("scheduler.queue_wait.bulk", {}).get("count", 0) >= 1
    assert events.get("tenant.admitted.chat", 0) == n_chat
    assert events.get("tenant.admitted.bulk", 0) == n_bulk - 2

    assert backend.health()["state"] == "ready"
    client.close()
    lockcheck.assert_clean()
