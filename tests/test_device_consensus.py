"""On-device consensus (PR 8): the batched JAX kernels and the
``DeviceSimilarityScorer`` must be *bit-identical* to the host consensus path
— same winners, same likelihood trees — across nested structures, degenerate
n=1, degraded survivor inputs, and CJK/transliteration vectors. Fallback to
host (failpoint, unavailable device, unsupported shapes) must be automatic,
lossless, and observable through CONSENSUS_EVENTS, scheduler stats/health,
and the /metrics gauges.

On CI the "device" is the 8-way virtual CPU mesh (conftest) — the kernels and
dispatch plumbing are identical to chip deployments.
"""

import asyncio
import json
import random

import pytest

from k_llms_tpu import KLLMs
from k_llms_tpu.backends.tpu import TpuBackend
from k_llms_tpu.consensus.consolidation import consolidate_chat_completions
from k_llms_tpu.consensus.device import (
    DeviceSimilarityScorer,
    batched_levenshtein,
    batched_votes,
    device_available,
    device_best_match_scores,
    _encode_vote_column,
)
from k_llms_tpu.consensus.settings import ConsensusSettings
from k_llms_tpu.consensus.similarity import SimilarityScorer
from k_llms_tpu.consensus.voting import voting_consensus
from k_llms_tpu.native import levenshtein_distance
from k_llms_tpu.reliability import failpoints as fp
from k_llms_tpu.reliability.failpoints import FailSpec
from k_llms_tpu.types import ChatCompletion
from k_llms_tpu.utils.observability import CONSENSUS_EVENTS
from k_llms_tpu.utils.quality import TRUTH_DOCS, make_noisy_samples

pytestmark = pytest.mark.skipif(
    not device_available(), reason="JAX device unavailable for consensus kernels"
)


def _completion(samples):
    return ChatCompletion.model_validate(
        {
            "id": "c", "created": 0, "model": "m", "object": "chat.completion",
            "choices": [
                {
                    "finish_reason": "stop",
                    "index": i,
                    "message": {"role": "assistant", "content": s},
                }
                for i, s in enumerate(samples)
            ],
        }
    )


def _consolidate(samples, scorer, settings=ConsensusSettings()):
    r = consolidate_chat_completions(_completion(samples), scorer, settings)
    return r.choices[0].message.content, r.likelihoods


def _assert_device_matches_host(samples, settings=ConsensusSettings()):
    """The pinned contract: device output == host output, exactly — content
    AND the full likelihood tree (stronger than the 1e-6 the issue allows,
    because kernels return integers and floats are derived host-side)."""
    host = _consolidate(samples, SimilarityScorer.levenshtein(), settings)
    dev_scorer = DeviceSimilarityScorer(method="levenshtein")
    first = _consolidate(samples, dev_scorer, settings)
    warm = _consolidate(samples, dev_scorer, settings)  # cached-bucket replay
    assert first == host
    assert warm == host


# -- kernel unit tests ------------------------------------------------------

def test_batched_levenshtein_matches_native():
    rng = random.Random(3)
    alpha = "abcdefg012"
    pairs = [("", ""), ("", "abc"), ("same", "same"), ("kitten", "sitting")]
    for _ in range(200):
        a = "".join(rng.choice(alpha) for _ in range(rng.randrange(0, 40)))
        b = "".join(rng.choice(alpha) for _ in range(rng.randrange(0, 40)))
        pairs.append((a, b))
    # long bucket, up to the kernel's 128-char ceiling
    pairs.append(("x" * 128, "x" * 100 + "y" * 28))
    got = batched_levenshtein(pairs)
    want = [levenshtein_distance(a, b) for a, b in pairs]
    assert got == want


def test_batched_cosine_matches_host():
    """Host parity for the embeddings kernel (ISSUE 18). This is the one
    float-producing kernel, so the contract is tolerance-based — device f32
    dot/norms vs host float64 — with the zero-norm floor, the [-1,1]->[0,1]
    normalization, and the [1e-8, 1] clip mirrored exactly."""
    import numpy as np

    from k_llms_tpu.consensus.device import batched_cosine
    from k_llms_tpu.consensus.similarity import cosine_similarity

    rng = np.random.default_rng(5)
    pairs = [(rng.normal(size=64).tolist(), rng.normal(size=64).tolist()) for _ in range(130)]
    v = rng.normal(size=64).tolist()
    pairs.append((v, v))  # identical: clips to exactly 1.0
    pairs.append((v, (-np.asarray(v)).tolist()))  # antipodal: floors near 0
    pairs.append(([0.0] * 64, v))  # zero norm: exact lower bound
    pairs.append((rng.normal(size=16).tolist(), rng.normal(size=16).tolist()))  # 2nd dim group
    got = batched_cosine(pairs)
    want = [cosine_similarity(a, b) for a, b in pairs]
    assert np.allclose(got, want, atol=1e-5)
    assert got[-2] == 1e-8  # zero-norm floor is exact, not approximate
    with pytest.raises(ValueError):
        batched_cosine([([0.0] * 8, [0.0] * 4)])


def test_embeddings_scorer_routes_pairs_through_device_cosine():
    """End-to-end: an embeddings-method DeviceSimilarityScorer batches every
    eligible pair through the cosine kernel (counted in
    consensus.device_cosine), and consolidation output matches the host
    embeddings scorer."""
    import zlib

    import numpy as np

    def embed(texts):
        out = []
        for t in texts:
            rng = np.random.default_rng(zlib.crc32(t.encode("utf-8")))
            out.append(rng.normal(size=32).tolist())
        return out

    # Long enough to clear EMBEDDING_MIN_CHARS so the embeddings route (not
    # the Levenshtein degrade) scores the content field.
    base = "the quick brown fox jumps over the lazy dog near the river bank"
    samples = [
        json.dumps({"summary": base, "tag": "x"}),
        json.dumps({"summary": base + " again", "tag": "x"}),
        json.dumps({"summary": "a completely different sentence about tax law and accounting rules", "tag": "y"}),
    ]
    host = _consolidate(samples, SimilarityScorer(method="embeddings", embed_fn=embed))
    scorer = DeviceSimilarityScorer(method="embeddings", embed_fn=embed)
    before = CONSENSUS_EVENTS.snapshot()
    content, likelihoods = _consolidate(samples, scorer)
    after = CONSENSUS_EVENTS.snapshot()
    assert after.get("consensus.device_cosine", 0) > before.get("consensus.device_cosine", 0)
    assert content == host[0]

    def flatten(node, out):
        if isinstance(node, dict):
            for v in node.values():
                flatten(v, out)
        elif isinstance(node, (list, tuple)):
            for v in node:
                flatten(v, out)
        elif isinstance(node, (int, float)):
            out.append(float(node))
        return out

    assert np.allclose(flatten(likelihoods, []), flatten(host[1], []), atol=1e-5)


def test_batched_votes_match_voting_consensus():
    rng = random.Random(7)
    pools = [
        ["alpha", "Alpha", "ALPHA ", "beta", None],
        ["北京", "東京", "京都", None],
        [True, False, None],
    ]
    combos = [
        ConsensusSettings(),
        ConsensusSettings(allow_none_as_candidate=True),
        ConsensusSettings(canonical_spelling=False),
        ConsensusSettings(canonical_spelling=False, allow_none_as_candidate=True),
    ]
    checked = 0
    for _ in range(120):
        pool = rng.choice(pools)
        col = [rng.choice(pool) for _ in range(rng.randrange(1, 12))]
        for cs in combos:
            enc = _encode_vote_column(col, cs)
            if enc is None:
                continue
            (got_val, got_count) = batched_votes([enc])[0]
            want_val, want_conf = voting_consensus(list(col), cs)
            got_conf = round(got_count / len(col), 5)
            assert got_val == want_val and type(got_val) is type(want_val)
            assert abs(got_conf - want_conf) < 1e-12
            checked += 1
    assert checked > 100  # the encoder must actually cover these columns


def test_device_best_match_scores_matches_host_scan():
    import numpy as np

    from k_llms_tpu.consensus.alignment import ElementTable, _best_match_scores

    rng = random.Random(11)
    words = ["red", "green", "blue", "teal", "grey", "pink"]
    for _ in range(15):
        lists = [
            [rng.choice(words) for _ in range(rng.randrange(0, 5))]
            for _ in range(rng.randrange(2, 5))
        ]
        if not any(lists):
            continue
        scorer = SimilarityScorer.levenshtein()
        table = ElementTable(scorer.generic, lists)
        want = _best_match_scores(table)
        got = device_best_match_scores(
            np.asarray(table.sim, dtype=np.float32), table.owner.astype(np.int32)
        )
        assert len(got) == len(want)
        for g, w in zip(got, want):
            assert abs(g - w) < 1e-6


# -- differential suite: device ≡ host --------------------------------------

@pytest.mark.parametrize("doc", sorted(TRUTH_DOCS))
@pytest.mark.parametrize("n", [1, 2, 8, 32])
def test_device_equals_host_on_corpus(doc, n):
    samples = make_noisy_samples(TRUTH_DOCS[doc], n, 0.15, seed=7 + n)
    _assert_device_matches_host(samples)


@pytest.mark.parametrize(
    "settings",
    [
        ConsensusSettings(allow_none_as_candidate=True),
        ConsensusSettings(canonical_spelling=False),
    ],
    ids=["none-candidate", "no-canonical"],
)
def test_device_equals_host_settings_variants(settings):
    samples = make_noisy_samples(TRUTH_DOCS["invoice"], 8, 0.2, seed=5)
    _assert_device_matches_host(samples, settings)


def test_device_equals_host_nested_lists_and_dicts():
    truth = {
        "teams": [
            {"name": "core", "members": ["ada", "lin", "mae"], "active": True},
            {"name": "infra", "members": ["kai"], "active": False},
        ],
        "tags": [["a", "b"], ["c"]],
        "meta": {"depth": {"level": "three", "codes": ["x1", "x2"]}},
    }
    samples = make_noisy_samples(truth, 8, 0.25, seed=13)
    _assert_device_matches_host(samples)


def test_device_equals_host_on_degraded_survivors():
    """Broken samples (invalid JSON) force the survivor-consensus degrade
    path; the device scorer must agree with host on the survivors and keep
    the degraded metadata identical."""
    samples = make_noisy_samples(TRUTH_DOCS["invoice"], 8, 0.15, seed=9)
    samples[1] = '{"vendor": "Acme Corp", "total":'  # truncated JSON
    samples[5] = "not json at all"
    host = consolidate_chat_completions(
        _completion(samples), SimilarityScorer.levenshtein()
    )
    dev = consolidate_chat_completions(
        _completion(samples), DeviceSimilarityScorer(method="levenshtein")
    )
    assert dev.choices[0].message.content == host.choices[0].message.content
    assert dev.likelihoods == host.likelihoods
    assert dev.degraded == host.degraded
    # the malformed samples did reach consensus as degraded text entries
    assert "text" in (host.likelihoods or {})


def test_device_equals_host_cjk_translit_vectors():
    """CJK payloads: normalize_string strips non-ASCII before Levenshtein
    (maxlen-0 pairs score 1.0 on both paths) while vote keys go through the
    first-party transliterator — winners and spellings must match exactly."""
    truth = {
        "city": "北京",
        "greeting": "こんにちは",
        "office": {"name": "東京支社", "floor": "三階"},
        "stops": ["서울", "大阪", "京都"],
    }
    for n in (2, 8, 16):
        samples = make_noisy_samples(truth, n, 0.2, seed=21 + n)
        _assert_device_matches_host(samples)
        _assert_device_matches_host(
            samples, ConsensusSettings(canonical_spelling=False)
        )


@pytest.mark.slow
@pytest.mark.duration_budget(120)
def test_device_equals_host_n128_soak():
    """The n=128 column: vote kernel at its max sample width, pair batches in
    the >1k-pair regime — plus a second warm pass through the bucket cache."""
    samples = make_noisy_samples(TRUTH_DOCS["invoice"], 128, 0.15, seed=31)
    _assert_device_matches_host(samples)


# -- fallback + observability ----------------------------------------------

def test_failpoint_fallback_is_lossless_and_counted():
    samples = make_noisy_samples(TRUTH_DOCS["profile"], 8, 0.15, seed=17)
    host = _consolidate(samples, SimilarityScorer.levenshtein())
    scorer = DeviceSimilarityScorer(method="levenshtein")
    before = CONSENSUS_EVENTS.snapshot()
    with fp.failpoints({"consensus.device": FailSpec(action="fallback", times=2)}):
        assert _consolidate(samples, scorer) == host  # fallback #1
        assert _consolidate(samples, scorer) == host  # fallback #2
        assert _consolidate(samples, scorer) == host  # spec exhausted: device
    after = CONSENSUS_EVENTS.snapshot()

    def delta(k):
        return after.get(k, 0) - before.get(k, 0)

    assert delta("consensus.fallback_failpoint") == 2
    assert delta("consensus.host_dispatch") == 2
    assert delta("consensus.device_dispatch") == 1


def test_unsupported_payloads_fall_back_silently():
    # Strings beyond the kernel's 128-char normalized ceiling take the host
    # native kernel inside the device session — results still identical.
    long_a = "tok" * 60
    long_b = "tok" * 59 + "alt"
    samples = [
        json.dumps({"blob": long_a, "tag": "x"}),
        json.dumps({"blob": long_b, "tag": "x"}),
        json.dumps({"blob": long_a, "tag": "y"}),
    ]
    _assert_device_matches_host(samples)


def test_cache_stats_shape_and_counters():
    scorer = DeviceSimilarityScorer(method="levenshtein")
    samples = make_noisy_samples(TRUTH_DOCS["invoice"], 8, 0.15, seed=3)
    _consolidate(samples, scorer)
    _consolidate(samples, scorer)
    stats = scorer.cache_stats()
    for name in ("similarity", "embeddings", "vote", "medoid", "numeric", "align", "pairs"):
        assert name in stats, f"missing cache section {name!r}"
        for key in ("entries", "hits", "misses", "evictions", "expirations", "maxsize"):
            assert key in stats[name]
    # the warm repeat must be served by the caches, not recomputed
    assert stats["pairs"].get("hits", 0) >= 1
    assert stats["align"].get("hits", 0) >= 1


# -- backend integration: scheduler stats, health, /metrics ------------------

def _shared_tiny_engine():
    import jax
    from conftest import shared_engine

    if len(jax.devices()) == 8:
        return shared_engine("tiny", mesh_shape=(8, 1))
    return None


@pytest.fixture(scope="module")
def tpu_client():
    backend = TpuBackend(model="tiny", max_new_tokens=16, engine=_shared_tiny_engine())
    return KLLMs(backend=backend, model="tiny"), backend


@pytest.mark.duration_budget(30)
def test_backend_requests_survive_device_failpoint(tpu_client):
    """consensus.device=fallback:N through a real backend: zero request
    failures, dispatch counters record the degradation."""
    client, backend = tpu_client
    before = CONSENSUS_EVENTS.snapshot()
    with fp.failpoints({"consensus.device": FailSpec(action="fallback", times=1)}):
        resp = client.chat.completions.create(
            messages=[{"role": "user", "content": "count to three"}],
            model="tiny", n=3, temperature=1.0, seed=7,
        )
    assert len(resp.choices) == 4  # consensus + originals: nothing failed
    after = CONSENSUS_EVENTS.snapshot()
    assert after.get("consensus.fallback_failpoint", 0) > before.get(
        "consensus.fallback_failpoint", 0
    )
    assert after.get("consensus.host_dispatch", 0) > before.get(
        "consensus.host_dispatch", 0
    )


def test_scheduler_stats_and_health_carry_consensus(tpu_client):
    client, backend = tpu_client
    client.chat.completions.create(
        messages=[{"role": "user", "content": "hello there"}],
        model="tiny", n=3, temperature=1.0, seed=11,
    )
    for snap in (backend.scheduler.stats, backend.scheduler.health(), backend.health()):
        consensus = snap.get("consensus")
        assert consensus is not None, "consensus section missing from snapshot"
        assert consensus["device_consensus"] is True
        for key in ("hits", "misses", "entries", "evictions"):
            assert key in consensus["cache"]
        assert "caches" in consensus and "events" in consensus
    # a consolidation ran, so dispatch events must be nonzero overall
    events = backend.health()["consensus"]["events"]
    assert sum(events.values()) > 0


def test_metrics_exports_consensus_gauges(tpu_client):
    import httpx

    from k_llms_tpu.serving import ServingApp

    client, backend = tpu_client
    client.chat.completions.create(
        messages=[{"role": "user", "content": "one more"}],
        model="tiny", n=3, temperature=1.0, seed=13,
    )
    app = ServingApp(client)

    async def go():
        transport = httpx.ASGITransport(app=app)
        async with httpx.AsyncClient(
            transport=transport, base_url="http://testserver"
        ) as c:
            return await c.get("/metrics")

    body = asyncio.run(go()).text
    assert "kllms_consensus_cache_hits" in body
    assert "kllms_consensus_cache_misses" in body
    assert "kllms_consensus_cache_entries" in body
    assert "kllms_consensus_cache_evictions" in body
    assert "kllms_consensus_device_enabled 1" in body
    assert 'kllms_consensus_events_total{event="consensus.' in body


def test_device_consensus_config_off_uses_plain_scorer(tpu_client):
    _, backend = tpu_client
    assert isinstance(backend.similarity_scorer("levenshtein"), DeviceSimilarityScorer)
    off = TpuBackend(
        model="tiny", max_new_tokens=16, engine=_shared_tiny_engine(),
        device_consensus=False,
    )
    scorer = off.similarity_scorer("levenshtein")
    assert not isinstance(scorer, DeviceSimilarityScorer)
    assert isinstance(scorer, SimilarityScorer)
    assert off.health()["consensus"]["device_consensus"] is False
