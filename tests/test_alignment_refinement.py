"""The alignment knobs that fix high-n quality (VERDICT r2 #3, r3 #3):

``alignment_refinement_rounds`` — global re-assignment after the greedy
reference election, undoing the cluster fragmentation that silently drops
majority-supported list rows at n>=16;
``canonical_spelling`` — vote/medoid winners reported in the bucket's most
common exact spelling instead of the first-seen one.

Both default ON (the reference's own headline n=32 config scores below its
n=8 without them); ``ConsensusSettings(reference_exact=True)`` restores the
reference's bit-exact behavior, and the oracle differential suite pins that
mode.
"""

import json

import pytest

from k_llms_tpu.backends.fake import FakeBackend
from k_llms_tpu.client import KLLMs
from k_llms_tpu.consensus.settings import ConsensusSettings
from k_llms_tpu.consensus.voting import voting_consensus
from k_llms_tpu.utils.quality import (
    DEFAULT_TRUTH,
    PO_TRUTH,
    consensus_quality_eval,
    field_accuracy,
    make_noisy_samples,
)

FAITHFUL = ConsensusSettings(reference_exact=True)


def _consensus(samples, settings=None, n=None):
    client = KLLMs(backend=FakeBackend(responses=[samples]), model="m")
    resp = client.chat.completions.create(
        messages=[{"role": "user", "content": "x"}],
        model="m",
        n=n or len(samples),
        consensus_settings=settings,
    )
    return json.loads(resp.choices[0].message.content)


def test_refinement_recovers_dropped_row_at_n32():
    """Seed 32/trial 0 is a known fragmentation case: the greedy election
    splits the 'Express shipping' cluster into two sub-majority groups and the
    reference-exact path drops the row; the default (refined) path
    re-coalesces it."""
    samples = make_noisy_samples(DEFAULT_TRUTH, 32, 0.15, 32)

    faithful = _consensus(samples, FAITHFUL)
    assert len(faithful["line_items"]) == 2  # the reference-faithful row drop

    refined = _consensus(samples)  # default posture
    assert len(refined["line_items"]) == 3
    descs = {r["description"] for r in refined["line_items"]}
    assert "Express shipping and handling" in descs


def test_refinement_noop_when_groups_already_stable():
    """On clean low-n input refinement must not change the result."""
    samples = make_noisy_samples(DEFAULT_TRUTH, 4, 0.05, 9)
    assert _consensus(samples, FAITHFUL) == _consensus(
        samples, ConsensusSettings(alignment_refinement_rounds=3)
    )


def test_canonical_spelling_vote():
    values = ["USD", "usd", "usd", "usd"]
    first_seen, _ = voting_consensus(values, FAITHFUL)
    assert first_seen == "USD"  # reference-exact: first original in the bucket
    canonical, conf = voting_consensus(values, ConsensusSettings())
    assert canonical == "usd"  # default: the majority spelling wins
    assert conf == 1.0  # spelling choice must not change the confidence


def test_canonical_spelling_medoid_tiebreak():
    # >2-word strings route to the similarity medoid; case variants normalize
    # identically so the first index wins ties unless canonical_spelling is on.
    values = ["EXTENDED WARRANTY, 24 MONTHS"] + ["Extended warranty, 24 months"] * 3
    doc = lambda s: json.dumps({"note": s})
    faithful = _consensus([doc(v) for v in values], FAITHFUL)
    assert faithful["note"] == values[0]
    tuned = _consensus([doc(v) for v in values])  # default posture
    assert tuned["note"] == "Extended warranty, 24 months"


def test_tuned_quality_monotone_and_above_bar():
    """VERDICT r2/r3 acceptance: n=32 quality >= n=8 quality, both >= 0.85 —
    on the DEFAULT settings path."""
    r = consensus_quality_eval(n_values=(8, 32), trials=6)
    assert r["truth_docs"] == 3
    assert r["consensus_n32"] >= r["consensus_n8"] >= 0.85


def _full_row_trials(n, settings, trials=12):
    """How many of ``trials`` deterministic purchase-order trials keep ALL
    four truth item rows after consensus.  Seeds mirror
    ``consensus_quality_eval`` (doc index 1 = purchase_order) so the counts
    line up with the benchmarked quality numbers."""
    kept = 0
    for t in range(trials):
        samples = make_noisy_samples(PO_TRUTH, n, 0.15, 1000 * t + n + 77777 * 1)
        out = _consensus(samples, settings, n)
        kept += len(out.get("items", [])) == len(PO_TRUTH["items"])
    return kept


def test_reference_exact_n32_row_fragmentation_pinned():
    """Root cause of ROADMAP open item 5 (reference-exact 0.813 @ n=32 vs
    0.934 @ n=16): the reference's single greedy alignment scan is
    order-dependent, and at n=32 it fragments true row-clusters into
    sub-majority groups that fall below ``min_support_ratio`` and get pruned —
    entire majority-supported list rows vanish, taking every leaf field with
    them.  It is a property of the reference semantics, NOT an implementation
    bug (the oracle differential suite pins our reference-exact path to the
    reference bit for bit).

    This test pins the mechanism three ways on deterministic seeds:
    reference-exact row retention degrades sharply from n=16 to n=32;
    refinement rounds ALONE (everything else still reference-exact) restore
    n=16-level retention; canonical spelling alone does not touch row drops
    (it is a leaf-value knob, confirming the rows — not the spellings — are
    what fragment)."""
    exact16 = _full_row_trials(16, FAITHFUL)
    exact32 = _full_row_trials(32, FAITHFUL)
    assert exact16 >= 9  # n=16: fragmentation is rare (10/12 on these seeds)
    assert exact32 <= 5  # n=32: most trials lose at least one row (4/12)
    assert exact32 < exact16

    refined32 = _full_row_trials(
        32, ConsensusSettings(reference_exact=True, alignment_refinement_rounds=2)
    )
    assert refined32 >= exact16  # refinement alone restores n=16 retention

    spelled32 = _full_row_trials(
        32, ConsensusSettings(reference_exact=True, canonical_spelling=True)
    )
    assert spelled32 == exact32  # spelling does not affect row retention


def test_posture_resolution():
    """Auto knobs resolve by posture; explicit values always win."""
    default = ConsensusSettings()
    assert default.effective_refinement_rounds == 2
    assert default.effective_canonical_spelling is True

    exact = ConsensusSettings(reference_exact=True)
    assert exact.effective_refinement_rounds == 0
    assert exact.effective_canonical_spelling is False

    # Explicit overrides beat the posture in BOTH directions.
    mixed = ConsensusSettings(reference_exact=True, alignment_refinement_rounds=1)
    assert mixed.effective_refinement_rounds == 1
    assert mixed.effective_canonical_spelling is False
    off = ConsensusSettings(canonical_spelling=False)
    assert off.effective_canonical_spelling is False
    assert off.effective_refinement_rounds == 2
