"""The opt-in alignment knobs that fix high-n quality (VERDICT r2 #3):

``alignment_refinement_rounds`` — global re-assignment after the greedy
reference election, undoing the cluster fragmentation that silently drops
majority-supported list rows at n>=16;
``canonical_spelling`` — vote/medoid winners reported in the bucket's most
common exact spelling instead of the first-seen one.

Both default OFF; the defaults stay reference-exact (pinned by the oracle
differential suite, which runs with default settings).
"""

import json

import pytest

from k_llms_tpu.backends.fake import FakeBackend
from k_llms_tpu.client import KLLMs
from k_llms_tpu.consensus.settings import ConsensusSettings
from k_llms_tpu.consensus.voting import voting_consensus
from k_llms_tpu.utils.quality import (
    DEFAULT_TRUTH,
    consensus_quality_eval,
    field_accuracy,
    make_noisy_samples,
)

TUNED = ConsensusSettings(alignment_refinement_rounds=2, canonical_spelling=True)


def _consensus(samples, settings=None, n=None):
    client = KLLMs(backend=FakeBackend(responses=[samples]), model="m")
    resp = client.chat.completions.create(
        messages=[{"role": "user", "content": "x"}],
        model="m",
        n=n or len(samples),
        consensus_settings=settings,
    )
    return json.loads(resp.choices[0].message.content)


def test_refinement_recovers_dropped_row_at_n32():
    """Seed 32/trial 0 is a known fragmentation case: the greedy election
    splits the 'Express shipping' cluster into two sub-majority groups and the
    faithful path drops the row; refinement re-coalesces it."""
    samples = make_noisy_samples(DEFAULT_TRUTH, 32, 0.15, 32)

    faithful = _consensus(samples)
    assert len(faithful["line_items"]) == 2  # the reference-faithful row drop

    refined = _consensus(samples, ConsensusSettings(alignment_refinement_rounds=2))
    assert len(refined["line_items"]) == 3
    descs = {r["description"] for r in refined["line_items"]}
    assert "Express shipping and handling" in descs


def test_refinement_noop_when_groups_already_stable():
    """On clean low-n input refinement must not change the result."""
    samples = make_noisy_samples(DEFAULT_TRUTH, 4, 0.05, 9)
    assert _consensus(samples) == _consensus(
        samples, ConsensusSettings(alignment_refinement_rounds=3)
    )


def test_canonical_spelling_vote():
    values = ["USD", "usd", "usd", "usd"]
    first_seen, _ = voting_consensus(values, ConsensusSettings())
    assert first_seen == "USD"  # reference-exact: first original in the bucket
    canonical, conf = voting_consensus(
        values, ConsensusSettings(canonical_spelling=True)
    )
    assert canonical == "usd"
    assert conf == 1.0  # spelling choice must not change the confidence


def test_canonical_spelling_medoid_tiebreak():
    # >2-word strings route to the similarity medoid; case variants normalize
    # identically so the first index wins ties unless canonical_spelling is on.
    values = ["EXTENDED WARRANTY, 24 MONTHS"] + ["Extended warranty, 24 months"] * 3
    doc = lambda s: json.dumps({"note": s})
    faithful = _consensus([doc(v) for v in values])
    assert faithful["note"] == values[0]
    tuned = _consensus([doc(v) for v in values], TUNED)
    assert tuned["note"] == "Extended warranty, 24 months"


def test_tuned_quality_monotone_and_above_bar():
    """VERDICT r2 acceptance: n=32 quality >= n=8 quality, both >= 0.85."""
    r = consensus_quality_eval(n_values=(8, 32), trials=6, consensus_settings=TUNED)
    assert r["truth_docs"] == 3
    assert r["consensus_n32"] >= r["consensus_n8"] >= 0.85


def test_default_settings_unchanged_by_knobs():
    s = ConsensusSettings()
    assert s.alignment_refinement_rounds == 0
    assert s.canonical_spelling is False
