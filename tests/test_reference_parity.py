"""Differential tests: our consensus engine vs the reference engine (oracle).

Fuzzes randomized nested JSON-like sample sets through BOTH implementations of
recursive alignment + consensus and asserts identical consensus values and
likelihood structures. This is the bit-compatibility check SURVEY.md §7 stage 2
demands for the "full of tie-breaks and magic constants" numerics.
"""

import math
import random

import pytest

from reference_oracle import load_reference_engine, reference_available
from k_llms_tpu.backends.fake import deterministic_embedding
from k_llms_tpu.consensus.recursion import consensus_values, recursive_list_alignments
from k_llms_tpu.consensus.settings import ConsensusSettings
from k_llms_tpu.consensus.similarity import SimilarityScorer

pytestmark = pytest.mark.skipif(
    not reference_available(), reason="reference tree not mounted"
)

NAMES = ["Alice Smith", "Bob Jones", "Charlie Brown", "Dana White", "Eve Adams"]
CITIES = ["Paris", "London", "New York City", "San Francisco", "Berlin"]
SENTENCES = [
    "The quick brown fox jumps over the lazy dog near the river bank",
    "Machine learning models often disagree about ambiguous inputs entirely",
    "Invoices must be paid within thirty days of the delivery date",
    "The annual report shows strong growth in the European market segment",
    "Customer satisfaction remains the primary goal of the support team",
]
ENUMS = ["yes", "no", "maybe", "active", "inactive", "pending"]


def _perturb_string(rng, s, p=0.3):
    if rng.random() > p:
        return s
    chars = list(s)
    op = rng.choice(["swap", "drop", "dup", "case"])
    if not chars:
        return s
    i = rng.randrange(len(chars))
    if op == "swap" and len(chars) > 1:
        j = min(i + 1, len(chars) - 1)
        chars[i], chars[j] = chars[j], chars[i]
    elif op == "drop":
        chars.pop(i)
    elif op == "dup":
        chars.insert(i, chars[i])
    else:
        chars[i] = chars[i].upper()
    return "".join(chars)


def _perturb_number(rng, x, p=0.4):
    if rng.random() > p:
        return x
    kind = rng.choice(["jitter", "big", "sign", "pow10"])
    if kind == "jitter":
        return round(x * (1 + rng.uniform(-0.02, 0.02)), 4)
    if kind == "big":
        return round(x * rng.uniform(1.5, 3.0), 4)
    if kind == "sign":
        return -x
    return x * (10 ** rng.choice([-1, 1]))


def make_record(rng, depth=0):
    rec = {}
    rec["name"] = rng.choice(NAMES)
    rec["status"] = rng.choice(ENUMS)
    rec["amount"] = round(rng.uniform(1, 5000), 2)
    rec["active"] = rng.random() < 0.5
    rec["note"] = rng.choice(SENTENCES)
    if depth < 1 and rng.random() < 0.6:
        rec["items"] = [
            {"sku": rng.choice(CITIES) + " widget", "qty": rng.randint(1, 20)}
            for _ in range(rng.randint(0, 3))
        ]
    if rng.random() < 0.3:
        rec["reasoning___why"] = rng.choice(SENTENCES)
    return rec


def perturb_record(rng, rec, depth=0):
    out = {}
    for k, v in rec.items():
        if rng.random() < 0.1:
            continue  # drop field
        if isinstance(v, str):
            if k == "status":
                out[k] = rng.choice(ENUMS) if rng.random() < 0.25 else v
            else:
                out[k] = _perturb_string(rng, v)
        elif isinstance(v, bool):
            out[k] = (not v) if rng.random() < 0.2 else v
        elif isinstance(v, (int, float)):
            out[k] = _perturb_number(rng, v)
        elif isinstance(v, list):
            lst = [perturb_record(rng, item, depth + 1) for item in v]
            if rng.random() < 0.3 and lst:
                lst.pop(rng.randrange(len(lst)))
            if rng.random() < 0.3:
                lst.append({"sku": rng.choice(CITIES) + " gadget", "qty": rng.randint(1, 9)})
            rng.shuffle(lst)
            out[k] = lst
        elif isinstance(v, dict):
            out[k] = perturb_record(rng, v, depth + 1)
        else:
            out[k] = v
    if rng.random() < 0.1:
        out["extra_field"] = rng.choice(ENUMS)
    return out


def make_samples(seed):
    rng = random.Random(seed)
    base = make_record(rng)
    n = rng.randint(2, 6)
    return [perturb_record(rng, base) for _ in range(n)]


def _normalize(obj):
    """Make floats comparable (both engines round to 5 where they round)."""
    if isinstance(obj, float):
        return round(obj, 9)
    if isinstance(obj, dict):
        return {k: _normalize(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_normalize(v) for v in obj]
    return obj


def run_reference(samples, method):
    ref = load_reference_engine()
    settings = ref.ConsensusSettings(string_similarity_method=method)

    def embed(texts):
        return [deterministic_embedding(t) for t in texts]

    aligned, mappings = ref.recursive_list_alignments(
        samples, method, embed, None, settings.min_support_ratio
    )
    aligned = [(d if isinstance(d, dict) else {}) for d in aligned]
    value, conf = ref.consensus_values(aligned, settings, embed, client=None)
    return _normalize(aligned), _normalize(value), _normalize(conf), mappings


def run_ours(samples, method):
    # The oracle pins the reference-exact posture (VERDICT r3 #3): the
    # DEFAULT posture intentionally diverges (refinement + canonical spelling).
    settings = ConsensusSettings(reference_exact=True, string_similarity_method=method)
    scorer = SimilarityScorer(
        method=method, embed_fn=lambda ts: [deterministic_embedding(t) for t in ts]
    )
    aligned, mappings = recursive_list_alignments(samples, scorer, settings.min_support_ratio)
    aligned = [(d if isinstance(d, dict) else {}) for d in aligned]
    value, conf = consensus_values(aligned, settings, scorer)
    return _normalize(aligned), _normalize(value), _normalize(conf), mappings


# Full 25-seed budget for the default method; 10 seeds apiece for the rest
# (structural, so a healthy run reports ZERO skips — a skip in the summary
# always means something environmental went wrong).
PARITY_CASES = [
    (seed, method)
    for method in ("levenshtein", "embeddings", "jaccard", "hamming")
    for seed in range(25 if method == "levenshtein" else 10)
]


@pytest.mark.parametrize("seed,method", PARITY_CASES)
def test_parity_random_structures(seed, method):
    samples = make_samples(seed)
    ref_aligned, ref_value, ref_conf, ref_map = run_reference(samples, method)
    our_aligned, our_value, our_conf, our_map = run_ours(samples, method)
    assert our_aligned == ref_aligned, f"alignment diverged (seed={seed})"
    assert our_value == ref_value, f"consensus value diverged (seed={seed})"
    assert our_conf == ref_conf, f"likelihoods diverged (seed={seed})"
    assert our_map == ref_map, f"key mappings diverged (seed={seed})"


@pytest.mark.parametrize("seed", range(10))
def test_parity_primitive_numeric(seed):
    ref = load_reference_engine()
    rng = random.Random(1000 + seed)
    base = rng.uniform(-100, 100)
    values = [
        _perturb_number(rng, base, p=0.8) if rng.random() > 0.2 else None
        for _ in range(rng.randint(2, 8))
    ]

    def embed(texts):
        return [deterministic_embedding(t) for t in texts]

    ref_val, ref_conf = ref.consensus_as_primitive(
        values, ref.ConsensusSettings(), embed, client=None
    )
    scorer = SimilarityScorer(method="embeddings", embed_fn=lambda ts: embed(ts))
    our_val, our_conf = __import__(
        "k_llms_tpu.consensus.primitive", fromlist=["consensus_as_primitive"]
    ).consensus_as_primitive(values, ConsensusSettings(reference_exact=True), scorer)
    if ref_val is None:
        assert our_val is None
    else:
        assert our_val == pytest.approx(ref_val, abs=1e-12)
    assert our_conf == pytest.approx(ref_conf, abs=1e-12)


@pytest.mark.parametrize("seed", range(10))
def test_parity_voting(seed):
    ref = load_reference_engine()
    rng = random.Random(2000 + seed)
    pool = ENUMS + [None, "São Paulo", "sao paulo"]
    values = [rng.choice(pool) for _ in range(rng.randint(2, 9))]
    ref_out = ref.voting_consensus(values, ref.ConsensusSettings())
    from k_llms_tpu.consensus.voting import voting_consensus

    our_out = voting_consensus(values, ConsensusSettings(reference_exact=True))
    assert our_out == ref_out


# ---------------------------------------------------------------------------
# Gnarly fuzz: unicode, None/empty values, mixed types, scalar lists, large n
# ---------------------------------------------------------------------------

UNICODE = [
    "café résumé naïve",
    "déjà vu — touché",
    "Ångström Σigma ñandú",
    "日本語テキストの抽出フィールド",
    "zażółć gęślą jaźń",
    # fixture-covered Cyrillic/Greek/CJK: the oracle's unidecode stub returns
    # REAL unidecode output for these (fixtures/unidecode_vectors.py), so
    # parity here is against genuine reference sanitization, not our own fold.
    "Москва",
    "Санкт-Петербург",
    "объект",
    "Αθήνα",
    "Θεσσαλονίκη",
    "北京",
    "東京",
    "上海",
    "你好",
    "こんにちは",
    "カタカナ",
    "서울",
]
GNARLY_SCALARS = [
    "", None, 0, 0.0, False, True, "42", 42, -0.0, 1e-9, 1e12,
    "   spaced   out   ", "UPPER lower MiXeD",
]


def make_gnarly_record(rng):
    rec = {
        "title": rng.choice(UNICODE),
        "tags": [rng.choice(ENUMS) for _ in range(rng.randint(0, 4))],
        "scores": [round(rng.uniform(-10, 10), 3) for _ in range(rng.randint(0, 5))],
        "misc": rng.choice(GNARLY_SCALARS),
        "maybe": None if rng.random() < 0.4 else rng.choice(SENTENCES),
        "count": rng.choice([0, 1, 7, 1000000, -3]),
    }
    if rng.random() < 0.5:
        rec["nested"] = {
            "inner": [
                {"k": rng.choice(UNICODE), "v": rng.choice(GNARLY_SCALARS)}
                for _ in range(rng.randint(0, 3))
            ]
        }
    return rec


def perturb_gnarly(rng, rec):
    out = {}
    for k, v in rec.items():
        r = rng.random()
        if r < 0.08:
            continue  # drop field
        if r < 0.16:
            out[k] = rng.choice(GNARLY_SCALARS)  # type flip
            continue
        if isinstance(v, str):
            out[k] = _perturb_string(rng, v, p=0.5)
        elif isinstance(v, bool):
            out[k] = (not v) if rng.random() < 0.3 else v
        elif isinstance(v, (int, float)):
            out[k] = _perturb_number(rng, v)  # ints stay ints when unperturbed
        elif isinstance(v, list):
            lst = [
                perturb_gnarly(rng, x) if isinstance(x, dict)
                else (_perturb_string(rng, x, p=0.4) if isinstance(x, str) else x)
                for x in v
            ]
            if rng.random() < 0.4:
                rng.shuffle(lst)
            out[k] = lst
        elif isinstance(v, dict):
            out[k] = perturb_gnarly(rng, v)
        else:
            out[k] = v
    return out


@pytest.mark.parametrize("method", ["levenshtein", "jaccard"])
@pytest.mark.parametrize("seed", range(15))
def test_parity_gnarly_structures(seed, method):
    """Unicode sanitization, None/empty-falsy similarity rules, mixed-type
    fields, scalar-list alignment, and large n must all stay bit-compatible."""
    rng = random.Random(10_000 + seed)
    base = make_gnarly_record(rng)
    n = rng.randint(2, 16)
    samples = [perturb_gnarly(rng, base) for _ in range(n)]
    our_aligned, our_value, our_conf, our_map = run_ours(samples, method)
    ref_aligned, ref_value, ref_conf, ref_map = run_reference(samples, method)
    assert our_aligned == ref_aligned, f"alignment diverged (seed={seed})"
    assert our_value == ref_value, f"consensus value diverged (seed={seed})"
    assert our_conf == ref_conf, f"likelihoods diverged (seed={seed})"
    assert our_map == ref_map, f"key mappings diverged (seed={seed})"


@pytest.mark.parametrize("seed", range(6))
def test_parity_headline_n32(seed):
    """The reference_exact posture at the headline consensus size
    (n in 24..32): exactly the regime where the greedy election fragments
    clusters and support pruning drops rows — whatever the reference does
    there (including the row drop) must be reproduced bit-for-bit under
    reference_exact=True (the DEFAULT posture fixes the drop instead —
    test_alignment_refinement.py pins that side)."""
    rng = random.Random(31_000 + seed)
    base = make_gnarly_record(rng)
    n = rng.randint(24, 32)
    samples = [perturb_gnarly(rng, base) for _ in range(n)]
    our_aligned, our_value, our_conf, our_map = run_ours(samples, "levenshtein")
    ref_aligned, ref_value, ref_conf, ref_map = run_reference(samples, "levenshtein")
    assert our_aligned == ref_aligned, f"alignment diverged (seed={seed})"
    assert our_value == ref_value, f"consensus value diverged (seed={seed})"
    assert our_conf == ref_conf, f"likelihoods diverged (seed={seed})"
    assert our_map == ref_map, f"key mappings diverged (seed={seed})"
