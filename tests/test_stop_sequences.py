"""On-device stop sequences (VERDICT r2 #8): rows halt in the decode loop when
their recent-token window matches a tokenized stop sequence, and usage bills
only the tokens behind the visible (truncated) text — no decode steps or
billing past the stop."""

import numpy as np
import pytest

from k_llms_tpu import KLLMs
from k_llms_tpu.backends.tpu import TpuBackend
from k_llms_tpu.engine.engine import MAX_STOP_LEN


@pytest.fixture(scope="module")
def backend():
    return TpuBackend(model="tiny", max_new_tokens=24)


def test_device_halt_on_forced_stop_token(backend):
    """logit_bias forces every step to emit one token; a stop sequence of two
    of those tokens must halt the row at exactly 2 generated tokens instead of
    decoding to max_new."""
    engine = backend.engine
    tok_id = 65  # 'A' in the byte tokenizer
    bias = {tok_id: 100.0}
    prompt = backend.tokenizer.encode("hello")

    free = engine.generate(
        prompt, n=2, max_new_tokens=16, temperature=1.0, seed=5, logit_bias=bias
    )
    assert all(length == 16 for length in free.lengths)  # runs to the cap

    stopped = engine.generate(
        prompt,
        n=2,
        max_new_tokens=16,
        temperature=1.0,
        seed=5,
        logit_bias=bias,
        stop_sequences=[[tok_id, tok_id]],
    )
    assert all(length == 2 for length in stopped.lengths)
    assert stopped.finish_reasons == ["stop", "stop"]


def test_single_token_stop_on_first_emission(backend):
    engine = backend.engine
    tok_id = 66
    out = engine.generate(
        backend.tokenizer.encode("x"),
        n=1,
        max_new_tokens=16,
        temperature=1.0,
        seed=1,
        logit_bias={tok_id: 100.0},
        stop_sequences=[[tok_id]],
    )
    assert out.lengths[0] == 1
    assert out.finish_reasons == ["stop"]


def test_overlong_stop_sequence_skipped_on_device(backend):
    """Sequences longer than MAX_STOP_LEN fall back to host truncation; the
    device loop must ignore them (and not halt spuriously)."""
    engine = backend.engine
    tok_id = 67
    out = engine.generate(
        backend.tokenizer.encode("x"),
        n=1,
        max_new_tokens=12,
        temperature=1.0,
        seed=1,
        logit_bias={tok_id: 100.0},
        stop_sequences=[[tok_id] * (MAX_STOP_LEN + 1)],
    )
    assert out.lengths[0] == 12


def test_usage_zero_when_stop_opens_the_text(backend):
    """Billing contract, exact case: logit_bias forces every token to 'A', so
    stop='AAA' truncates the text to "" — zero visible tokens, zero billed."""
    client = KLLMs(backend=backend)
    resp = client.chat.completions.create(
        messages=[{"role": "user", "content": "y"}],
        model="tiny",
        n=2,
        seed=3,
        logit_bias={"65": 100},
        stop="AAA",
    )
    for choice in resp.choices[1:]:
        assert choice.message.content == ""
        assert choice.finish_reason == "stop"
    assert resp.usage.completion_tokens == 0


def test_usage_trimmed_to_visible_text(backend):
    """Generic case: billed tokens shrink to the truncation point — bounded
    below by the visible char count (a byte token yields at most one char)
    and strictly below the unstopped billing."""
    client = KLLMs(backend=backend)
    resp = client.chat.completions.create(
        messages=[{"role": "user", "content": "y"}], model="tiny", n=2, seed=3
    )
    full = resp.choices[1].message.content
    assert len(full) > 2
    stop_char = full[2]

    resp2 = client.chat.completions.create(
        messages=[{"role": "user", "content": "y"}],
        model="tiny",
        n=2,
        seed=3,
        stop=stop_char,
    )
    total_chars = 0
    for choice in resp2.choices[1:]:
        text = choice.message.content or ""
        assert stop_char not in text
        total_chars += len(text)
    assert total_chars <= resp2.usage.completion_tokens < resp.usage.completion_tokens


def test_earliest_stop_in_text_wins(backend):
    """OpenAI semantics: with several stop strings the cut happens at the
    EARLIEST occurrence in the text, not at the first match in list order."""
    client = KLLMs(backend=backend)
    resp = client.chat.completions.create(
        messages=[{"role": "user", "content": "q"}], model="tiny", n=2, seed=21
    )
    full = resp.choices[1].message.content
    assert len(full) >= 8
    late, early = full[5:7], full[2:4]  # list order: later-in-text first
    expected_cut = min(full.find(late), full.find(early))

    resp2 = client.chat.completions.create(
        messages=[{"role": "user", "content": "q"}],
        model="tiny",
        n=2,
        seed=21,
        stop=[late, early],
    )
    assert resp2.choices[1].message.content == full[:expected_cut]


def test_stop_rows_halt_independently(backend):
    """One row hitting its stop must not halt sibling rows (per-row done)."""
    engine = backend.engine
    # Without bias the byte model generates pseudo-random bytes; a stop on a
    # rare 2-token sequence will trigger for some seeds/rows only. Force
    # divergence instead: bias two tokens equally and stop on one of them.
    out = engine.generate(
        backend.tokenizer.encode("z"),
        n=4,
        max_new_tokens=12,
        temperature=1.0,
        seed=11,
        stop_sequences=[[250]],  # a byte the random model rarely emits
    )
    assert (np.asarray(out.lengths) > 0).all()


def test_stop_window_match_properties():
    """Direct properties of the shared matcher: padding wildcards, dead rows,
    multi-sequence OR, and exact right-alignment."""
    import jax.numpy as jnp

    from k_llms_tpu.engine.engine import stop_window_match

    stops = jnp.array(
        [
            [-1, -1, -1, -1, -1, -1, 7, 9],   # 2-token stop [7, 9]
            [-1, -1, -1, -1, -1, -1, -1, 4],  # 1-token stop [4]
            [-1, -1, -1, -1, -1, -1, -1, -1], # inactive row
            [-1, -1, -1, -1, -1, -1, -1, -1],
        ],
        jnp.int32,
    )
    win = jnp.array(
        [
            [1, 2, 3, 4, 5, 6, 7, 9],   # ends with [7, 9] -> hit
            [1, 2, 3, 4, 5, 6, 9, 7],   # wrong order -> miss
            [1, 2, 3, 4, 5, 6, 7, 4],   # ends with 4 -> hit (second stop)
            [7, 9, 3, 4, 5, 6, 1, 2],   # stop NOT at the suffix -> miss
            [-1, -1, -1, -1, -1, -1, -1, -1],  # fresh row: all -1 sentinel
        ],
        jnp.int32,
    )
    got = [bool(x) for x in stop_window_match(win, stops)]
    assert got == [True, False, True, False, False]


def test_visible_token_count_multibyte_boundaries():
    """The billing scan must count every token contributing to the visible
    text (ADVICE r3): partial UTF-8 decodes to replacement chars whose length
    already covers the cut while later bytes still shape those chars, so a
    length-only search (binary OR linear) under-bills. Hand-computed pins."""
    from k_llms_tpu.backends.tpu import _visible_token_count
    from k_llms_tpu.engine.tokenizer import ByteTokenizer

    tok = ByteTokenizer()
    cases = [
        # (bytes, visible char count pos, expected token count)
        (list("abc😀STOP".encode()), 4, 7),   # 'abc😀' = 3 + 4 emoji bytes
        (list("abc😀STOP".encode()), 3, 3),   # 'abc' alone
        (list("中文X".encode()), 1, 3),        # one 3-byte char
        (list("中文X".encode()), 2, 6),        # both 3-byte chars
        (list("aéb".encode()), 2, 3),          # 'aé' = 1 + 2 bytes
        (list(b"a\xc3X"), 2, 2),               # lone truncated lead -> real U+FFFD
        (list(b"\x9f\x9fa"), 2, 2),            # lone continuations, one char each
    ]
    for ids, pos, want in cases:
        text = tok.decode(ids)
        got = _visible_token_count(tok, ids, pos, text)
        assert got == want, (bytes(ids), pos, got, want)
        # The accepted prefix must reproduce the visible text exactly.
        assert tok.decode(ids[:got])[:pos] == text[:pos]


def test_visible_token_count_survives_nonmonotone_decode():
    """Decoded length is NOT guaranteed non-decreasing in the token count:
    HF-style decode cleanup (clean_up_tokenization_spaces collapsing spaces
    before punctuation) can SHRINK the decode when a token is appended. The
    old binary search assumed monotonicity and could land past the true
    boundary, silently over-billing. Counterexample pinned with a cleanup
    tokenizer: piece lengths go 3 -> 2 -> 3."""
    from k_llms_tpu.backends.tpu import _visible_token_count

    class CleanupTok:
        _pieces = {1: "a  ", 2: ",", 3: "z"}

        def decode(self, ids):
            return "".join(self._pieces[i] for i in ids).replace("  ,", ",")

    tok = CleanupTok()
    ids = [1, 2, 3]
    assert [len(tok.decode(ids[:k])) for k in range(4)] == [0, 3, 2, 3]
    text = tok.decode(ids)  # "a,z"
    pos = 2  # visible: "a,"
    got = _visible_token_count(tok, ids, pos, text)
    assert got == 2, got
    assert tok.decode(ids[:got])[:pos] == text[:pos]


def test_stop_billing_covers_multibyte_visible_text(backend):
    """End-to-end: force emoji bytes via logit_bias so the text is a soup of
    replacement chars (partial UTF-8) — exactly the boundary the length-only
    scan got wrong. The billed tokens are pinned through the logprobs payload:
    their concatenated BYTES must decode back to the visible text, and usage
    must equal their count — the old under-billing predicate produced a byte
    prefix whose decode fell short of the returned text."""
    client = KLLMs(backend=backend)
    emoji = "😀".encode()  # f0 9f 98 80
    resp = client.chat.completions.create(
        messages=[{"role": "user", "content": "m"}],
        model="tiny",
        n=2,
        seed=17,
        logprobs=True,
        logit_bias={str(b): 100 for b in emoji},
        stop="\N{GRINNING FACE}",
    )
    billed_total = 0
    saw_billed_bytes = False
    for choice in resp.choices[1:]:
        text = choice.message.content or ""
        assert "😀" not in text
        entries = choice.logprobs.content if choice.logprobs else []
        billed_total += len(entries)
        if entries:
            saw_billed_bytes = True
        billed_bytes = b"".join(bytes(e.bytes) for e in entries)
        decoded = billed_bytes.decode("utf-8", errors="replace")
        assert decoded[: len(text)] == text, (billed_bytes, text)
    assert resp.usage.completion_tokens == billed_total
    assert saw_billed_bytes  # the soup must actually bill something
