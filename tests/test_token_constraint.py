"""Token-level (BPE) grammar constraints: product automaton, vocabulary
compilation, device mask/advance, and end-to-end guaranteed-valid JSON from a
random model through an HF fast tokenizer."""

import json
import random

import numpy as np
import pytest
from pydantic import BaseModel

from k_llms_tpu.engine.engine import LocalEngine
from k_llms_tpu.engine.json_constraint import validate_prefix
from k_llms_tpu.engine.schema_constraint import compile_schema
from k_llms_tpu.engine.token_constraint import (
    TokenConstraint,
    json_product_automaton,
    json_token_constraint,
    schema_token_constraint,
    validate_tokens,
    vocab_byte_strings,
)
from k_llms_tpu.models import get_config


# A BPE-flavored synthetic vocabulary: all single bytes, common JSON fragments
# as multi-byte merges, and two specials (eos=V-2, pad=V-1) mapped to None.
def make_vocab():
    vocab = [bytes([b]) for b in range(256)]
    vocab += [
        b'{"',
        b'":',
        b'",',
        b'"}',
        b'"a"',
        b'"name"',
        b'"qty"',
        b": ",
        b", ",
        b"true",
        b"false",
        b"null",
        b"12",
        b"3.14",
        b'{"k": ',
        b"[1, 2]",
        b"}}",
        b"]]",
        b'{"x": [',
    ]
    vocab += [None, None]  # specials: eos, pad
    return vocab


EOS_ID = len(make_vocab()) - 2


# --- product automaton ----------------------------------------------------


def product_validate(trans, terminal, start, data: bytes):
    state = start
    for b in data:
        state = int(trans[state, b])
        if state < 0:
            return False, False
    return True, bool(terminal[state])


@pytest.mark.parametrize(
    "doc,ok",
    [
        (b'{"a": 1}', True),
        (b'[{"k": [true, {}]}]', True),
        (b"[[[[1]]]]", True),
        (b"[[[[[1]]]]]", False),  # depth 5 > bound 4
        (b"{,}", False),
        (b"01", False),
        (b'{"a": 1,}', False),
    ],
)
def test_product_automaton_matches_pda(doc, ok):
    trans, terminal, start = json_product_automaton(max_depth=4)
    valid, complete = product_validate(trans, terminal, start, doc)
    assert (valid and complete) == ok
    if ok:
        # agree with the byte-level PDA oracle
        v2, c2 = validate_prefix(doc)
        assert v2 and c2


def test_product_rejects_mismatched_closers():
    trans, _, start = json_product_automaton(max_depth=4)
    assert product_validate(trans, np.zeros(1, bool), start, b"[}")[0] is False
    assert product_validate(trans, np.zeros(1, bool), start, b"{]")[0] is False


# --- vocabulary compilation ------------------------------------------------


@pytest.fixture(scope="module")
def tc() -> TokenConstraint:
    return json_token_constraint(make_vocab(), max_depth=4)


def tok_ids(vocab, *pieces):
    return [vocab.index(p) for p in pieces]


def test_multibyte_tokens_allowed_where_walkable(tc):
    vocab = make_vocab()
    ok, complete = validate_tokens(tc, tok_ids(vocab, b'{"k": ', b"12"))
    assert ok and not complete  # {"k": 12  — object still open
    ok, complete = validate_tokens(tc, tok_ids(vocab, b'{"k": ', b"12") + [vocab.index(b"}")])
    assert ok and complete


def test_structurally_invalid_tokens_masked(tc):
    vocab = make_vocab()
    start_mask = np.unpackbits(tc.packed[tc.start], count=tc.vocab_size).astype(bool)
    assert start_mask[vocab.index(b'{"')]  # object opener legal at start
    assert start_mask[vocab.index(b"[1, 2]")]  # full array literal legal
    assert not start_mask[vocab.index(b"}")]  # closer before any opener
    assert not start_mask[vocab.index(b"}}")]
    assert not start_mask[vocab.index(b",")]  # separator outside any container
    # "}}" is a double-pop: legal only under two open objects
    two_deep, _ = b'{"k": {"x": 1', b""
    state = tc.start
    for b in two_deep:
        state = int(tc.trans[state, b])
    deep_mask = np.unpackbits(tc.packed[state], count=tc.vocab_size).astype(bool)
    assert deep_mask[vocab.index(b"}}")]
    start_after_one = tc.start
    for b in b'{"k": 1':
        start_after_one = int(tc.trans[start_after_one, b])
    one_mask = np.unpackbits(tc.packed[start_after_one], count=tc.vocab_size).astype(bool)
    assert not one_mask[vocab.index(b"}}")]


def test_specials_never_masked_in(tc):
    assert tc.token_len[EOS_ID] == 0
    assert not np.unpackbits(tc.packed, axis=1)[:, EOS_ID].any()


def test_random_mask_walks_always_valid_json_prefix(tc):
    """Greedy random walks under the mask only ever produce valid prefixes."""
    vocab = make_vocab()
    rng = random.Random(0)
    for _ in range(50):
        state, out = tc.start, b""
        for _step in range(30):
            mask = np.unpackbits(tc.packed[state], count=tc.vocab_size).astype(bool)
            choices = np.flatnonzero(mask)
            if not len(choices):
                break
            pick = int(rng.choice(choices))
            out += vocab[pick]
            for b in vocab[pick]:
                state = int(tc.trans[state, b])
            ok, _complete = validate_prefix(out)
            assert ok, out
        if tc.terminal[state]:
            json.loads(out.decode())


# --- schema-derived token masks --------------------------------------------


class Item(BaseModel):
    name: str
    qty: int


def test_schema_token_constraint_enforces_keys():
    dfa = compile_schema(Item.model_json_schema())
    tc = schema_token_constraint(dfa, make_vocab())
    vocab = make_vocab()
    mask0 = np.unpackbits(tc.packed[tc.start], count=tc.vocab_size).astype(bool)
    assert mask0[vocab.index(b'{"')]  # the object must open
    assert not mask0[vocab.index(b"[")]  # an array cannot
    # after '{"' only the first key can continue: "name"
    state = tc.start
    for b in b'{"':
        state = int(tc.trans[state, b])
    mask = np.unpackbits(tc.packed[state], count=tc.vocab_size).astype(bool)
    assert mask[vocab.index(b"n")]
    assert not mask[vocab.index(b"q")]


# --- HF tokenizer extraction -----------------------------------------------


def make_hf_bpe(tmp_path):
    """A real byte-level BPE fast tokenizer built in-process (no assets)."""
    from tokenizers import Tokenizer, models, pre_tokenizers, decoders
    from tokenizers.trainers import BpeTrainer
    from transformers import PreTrainedTokenizerFast

    tok = Tokenizer(models.BPE(unk_token=None))
    tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
    tok.decoder = decoders.ByteLevel()
    trainer = BpeTrainer(
        vocab_size=400,
        special_tokens=["<|eos|>", "<|pad|>"],
        initial_alphabet=pre_tokenizers.ByteLevel.alphabet(),
    )
    corpus = [
        json.dumps({"name": "widget", "qty": 3, "tags": ["a", "b"], "price": 4.5}),
        json.dumps({"name": "gadget", "qty": 7, "nested": {"k": True}}),
        "hello world this is filler text for merges",
    ] * 50
    tok.train_from_iterator(corpus, trainer)
    fast = PreTrainedTokenizerFast(
        tokenizer_object=tok, eos_token="<|eos|>", pad_token="<|pad|>"
    )
    return fast


def test_vocab_byte_strings_byte_level_bpe(tmp_path):
    fast = make_hf_bpe(tmp_path)
    vocab = vocab_byte_strings(fast)
    assert len(vocab) == len(fast)
    # specials are None; real tokens round-trip through the tokenizer
    assert vocab[fast.eos_token_id] is None
    text = '{"name": "widget"}'
    ids = fast.encode(text, add_special_tokens=False)
    assert b"".join(vocab[i] for i in ids).decode() == text


def test_vocab_byte_strings_sentencepiece_style():
    class FakeSP:
        all_special_ids = [0]

        def __len__(self):
            return 5

        def convert_ids_to_tokens(self, ids):
            return ["<s>", "▁hello", "▁", "<0x0A>", "x"][: len(ids)]

    vocab = vocab_byte_strings(FakeSP())
    assert vocab == [None, b" hello", b" ", b"\n", b"x"]


# --- end-to-end: random model, HF BPE tokenizer, guaranteed-valid JSON -----


class HFAdapter:
    """Duck-typed tokenizer wrapper over a PreTrainedTokenizerFast (the same
    interface HFTokenizer exposes), for driving TpuBackend without assets."""

    is_byte_level = False

    def __init__(self, fast):
        self._tok = fast
        self.vocab_size = len(fast)
        self.eos_id = fast.eos_token_id
        self.pad_id = fast.pad_token_id

    def encode(self, text, add_bos=False):
        return self._tok.encode(text, add_special_tokens=False)

    def decode(self, ids):
        return self._tok.decode(list(ids), skip_special_tokens=True)

    def apply_chat_template(self, messages, add_generation_prompt=True):
        text = "\n".join(f"<{m['role']}> {m['content']}" for m in messages)
        return self.encode(text + "\n<assistant> ")

    @property
    def stop_ids(self):
        return [self.eos_id]


def test_backend_parse_bpe_end_to_end(tmp_path):
    """client.parse() on a BPE tokenizer: every sample is schema-valid JSON —
    the guarantee VERDICT r1 flagged as missing for real checkpoints."""
    from k_llms_tpu import KLLMs
    from k_llms_tpu.backends.tpu import TpuBackend

    fast = make_hf_bpe(tmp_path)
    adapter = HFAdapter(fast)
    backend = TpuBackend(model="tiny")
    backend.tokenizer = adapter
    backend._vocab_bytes_cache = None
    backend.engine.config = backend.engine.config.with_(
        eos_token_id=fast.eos_token_id, pad_token_id=fast.pad_token_id
    )
    client = KLLMs(backend=backend, model="tiny")
    resp = client.chat.completions.parse(
        messages=[{"role": "user", "content": "emit an item"}],
        model="tiny",
        n=3,
        seed=11,
        temperature=1.2,
        max_tokens=48,
        response_format=Item,
    )
    stopped = [c for c in resp.choices[1:] if c.finish_reason == "stop"]
    for choice in stopped:
        Item.model_validate(json.loads(choice.message.content))
        assert isinstance(choice.message.parsed, Item)


@pytest.mark.parametrize("kind", ["json", "schema"])
def test_generate_bpe_grammar_guaranteed(tmp_path, kind):
    fast = make_hf_bpe(tmp_path)
    vocab = vocab_byte_strings(fast)
    if kind == "json":
        tc = json_token_constraint(vocab, max_depth=4)
    else:
        tc = schema_token_constraint(compile_schema(Item.model_json_schema()), vocab)

    config = get_config("tiny").with_(
        eos_token_id=fast.eos_token_id, pad_token_id=fast.pad_token_id
    )
    engine = LocalEngine(config, use_mesh=False)
    result = engine.generate(
        fast.encode("emit json", add_special_tokens=False),
        n=4,
        max_new_tokens=48,
        temperature=1.5,
        seed=5,
        eos_ids=[fast.eos_token_id],
        constraint=tc,
    )
    for i in range(4):
        ids = [int(t) for t in result.tokens[i][: int(result.lengths[i])]]
        ids = [t for t in ids if t != fast.eos_token_id]
        data = b"".join(vocab[t] for t in ids)
        ok, complete = validate_prefix(data)
        assert ok, data
        if result.finish_reasons[i] == "stop":
            obj = json.loads(data.decode())
            if kind == "schema":
                Item.model_validate(obj)
