"""Compiled grammar masks (PR 12): schema-constrained n-way decoding.

The load-bearing pins: the packed uint32 token masks agree with the byte-DFA
oracle bit for bit (host and device), the process-wide cache makes one compile
per (schema, vocab) fleet-wide, the ``engine.grammar`` failpoint and compile
errors degrade to unconstrained decode WITHOUT erroring the request, output is
byte-identical to the pre-grammar path whenever no constraint is attached, and
constrained greedy decode parses under the schema for every TRUTH_DOCS shape.
"""

import json

import jax.numpy as jnp
import numpy as np
import pytest
from pydantic import BaseModel

from k_llms_tpu.engine.grammar import (
    CompiledGrammar,
    clear_grammar_cache,
    device_grammar,
    grammar_advance,
    grammar_cache_stats,
    grammar_for_schema,
    grammar_initial_state,
    grammar_mask_logits,
    grammar_vocab,
    validate_grammar_tokens,
)
from k_llms_tpu.engine.schema_constraint import compile_schema, validate_bytes
from k_llms_tpu.engine.tokenizer import ByteTokenizer
from k_llms_tpu.reliability import failpoints as fp
from k_llms_tpu.reliability.failpoints import FailSpec
from k_llms_tpu.utils.observability import GRAMMAR_EVENTS

TOK = ByteTokenizer()
VOCAB = grammar_vocab(TOK)


class Record(BaseModel):
    name: str
    count: int


def _events():
    return dict(GRAMMAR_EVENTS.snapshot())


def _delta(before, after, key):
    return after.get(key, 0) - before.get(key, 0)


def _grammar(schema):
    return grammar_for_schema(schema, VOCAB, vocab_digest="bytetok-test")


# ---------------------------------------------------------------------------
# mask packing + host/device parity
# ---------------------------------------------------------------------------


def test_packed_masks_match_dfa_oracle_per_token():
    """Every bit of the uint32-packed mask equals "this token's bytes survive
    the byte DFA from this state" — checked exhaustively over the byte vocab
    for a sample of states."""
    clear_grammar_cache()
    dfa = compile_schema(Record.model_json_schema())
    g = _grammar(Record.model_json_schema())
    assert isinstance(g, CompiledGrammar)
    n_states = g.trans.shape[0]
    for state in range(0, n_states, max(1, n_states // 12)):
        for token in range(TOK.vocab_size):
            bit = bool((g.masks[state, token // 32] >> (token % 32)) & 1)
            bs = VOCAB[token]
            if bs is None:
                assert not bit  # specials/pad never mask-allowed
                continue
            st = state
            for b in bs:
                st = int(g.trans[st, b]) if st >= 0 else -1
            assert bit == (st >= 0), (state, token)


def test_device_mask_and_advance_match_host_oracle():
    g = _grammar(Record.model_json_schema())
    d = device_grammar(g)
    doc = b'{"name":"ok","count":3}'
    state = grammar_initial_state(d, 1)
    eos = jnp.asarray([TOK.eos_id], jnp.int32)
    for i, byte in enumerate(doc):
        masked = grammar_mask_logits(d, jnp.zeros((1, TOK.vocab_size)), state, eos)
        allowed = np.asarray(masked[0] > jnp.finfo(jnp.float32).min / 2)
        host_state = int(np.asarray(state)[0])
        for token in range(0, TOK.vocab_size, 7):
            host_bit = bool((g.masks[host_state, token // 32] >> (token % 32)) & 1)
            if token == TOK.eos_id:
                host_bit = host_bit or bool(g.terminal[host_state])
            assert bool(allowed[token]) == host_bit, (i, token)
        assert allowed[byte], (i, chr(byte))
        state = grammar_advance(d, jnp.asarray([byte], jnp.int32), state)
    # Complete document: terminal, so EOS opens.
    masked = grammar_mask_logits(d, jnp.zeros((1, TOK.vocab_size)), state, eos)
    assert bool(np.asarray(masked[0] > jnp.finfo(jnp.float32).min / 2)[TOK.eos_id])
    ok, terminal = validate_grammar_tokens(g, list(doc))
    assert ok and terminal


def test_state_padding_is_inert():
    """pad_states rounds the state axis to a power of two (shared XLA program
    across schemas) without changing any mask or transition."""
    g = _grammar(Record.model_json_schema())
    plain, padded = device_grammar(g), device_grammar(g, pad_states=64)
    assert padded.trans.shape[0] >= 64
    assert padded.trans.shape[0] & (padded.trans.shape[0] - 1) == 0
    doc = b'{"name":"a","count":1}'
    for d in (plain, padded):
        state = grammar_initial_state(d, 1)
        for byte in doc:
            state = grammar_advance(d, jnp.asarray([byte], jnp.int32), state)
        eos = jnp.asarray([TOK.eos_id], jnp.int32)
        masked = grammar_mask_logits(d, jnp.zeros((1, TOK.vocab_size)), state, eos)
        assert bool(np.asarray(masked[0])[TOK.eos_id] == 0.0)


def test_specials_freeze_and_padded_rows_are_dead():
    g = _grammar(Record.model_json_schema())
    d = device_grammar(g, pad_states=64)
    state = grammar_initial_state(d, 2)
    # EOS/pad have token_len 0: advancing on them must not move the state.
    frozen = grammar_advance(
        d, jnp.asarray([TOK.eos_id, TOK.pad_id], jnp.int32), state
    )
    assert np.array_equal(np.asarray(frozen), np.asarray(state))
    # A padded (dead) state row allows nothing and EOS stays shut.
    dead = jnp.asarray([d.trans.shape[0] - 1], jnp.int32)
    masked = grammar_mask_logits(d, jnp.zeros((1, TOK.vocab_size)), dead,
                                 jnp.asarray([TOK.eos_id], jnp.int32))
    assert not np.any(np.asarray(masked[0]) > jnp.finfo(jnp.float32).min / 2)


# ---------------------------------------------------------------------------
# cache: one compile per (schema, vocab) per process
# ---------------------------------------------------------------------------


def test_cache_hit_returns_same_object_and_counts():
    clear_grammar_cache()
    before = _events()
    a = _grammar(Record.model_json_schema())
    mid = _events()
    b = _grammar(Record.model_json_schema())
    after = _events()
    assert a is b  # fleet members share one compiled table set
    assert _delta(before, mid, "grammar.miss") == 1
    assert _delta(before, mid, "grammar.compile") == 1
    assert _delta(mid, after, "grammar.hit") == 1
    assert _delta(mid, after, "grammar.compile") == 0
    stats = grammar_cache_stats()
    assert stats["entries"] >= 1 and stats["maxsize"] == 64


def test_cache_keys_split_on_schema_and_vocab():
    clear_grammar_cache()
    a = _grammar(Record.model_json_schema())
    other = grammar_for_schema(
        Record.model_json_schema(), VOCAB, vocab_digest="other-vocab"
    )
    generic = _grammar(None)
    assert a is not other  # same schema, different tokenizer -> distinct
    assert generic.digest.startswith("grammar-json-")
    assert grammar_cache_stats()["entries"] == 3


def test_unsupported_schema_degrades_to_generic_json():
    clear_grammar_cache()
    before = _events()
    g = _grammar({"type": "object"})  # free-form: SchemaUnsupported
    after = _events()
    assert isinstance(g, CompiledGrammar)
    assert g.digest.startswith("grammar-json-")
    assert _delta(before, after, "grammar.fallback_unsupported") == 1
    # Cached under the schema's own key: the second call is a pure hit.
    assert _grammar({"type": "object"}) is g
    # The generic grammar still accepts any JSON document.
    ok, terminal = validate_grammar_tokens(g, list(b'[1,{"k":null}]'))
    assert ok and terminal


# ---------------------------------------------------------------------------
# engine.grammar failpoint: degrade, never error
# ---------------------------------------------------------------------------


def test_engine_grammar_failpoint_fallback_degrades_to_unconstrained():
    """engine.grammar=fallback:2 — the registry drill: the next two compiles
    return None (unconstrained decode + post-hoc validation), counted, then
    the spec exhausts and compilation resumes."""
    clear_grammar_cache()
    before = _events()
    with fp.failpoints({"engine.grammar": FailSpec(action="fallback", times=2)}):
        assert _grammar(Record.model_json_schema()) is None  # fired (1)
        assert _grammar(Record.model_json_schema()) is None  # fired (2)
        assert isinstance(_grammar(Record.model_json_schema()), CompiledGrammar)
    after = _events()
    assert _delta(before, after, "grammar.fallback_failpoint") == 2


def test_engine_grammar_failpoint_raise_is_swallowed_and_counted():
    """The raise variant simulates a compile crash: grammar_for_schema still
    returns None — a constrained request NEVER errors on grammar failure."""
    clear_grammar_cache()
    before = _events()
    with fp.failpoints({"engine.grammar": FailSpec(action="raise", times=1)}):
        assert _grammar(Record.model_json_schema()) is None
    assert _delta(before, _events(), "grammar.fallback_error") == 1


def test_engine_grammar_env_syntax_parses():
    fp.configure_from_env("engine.grammar=fallback:1")
    try:
        clear_grammar_cache()
        before = _events()
        assert _grammar(Record.model_json_schema()) is None
        assert _delta(before, _events(), "grammar.fallback_failpoint") == 1
    finally:
        fp.clear()


def test_failpoint_request_degrades_but_succeeds():
    """End to end: with the failpoint armed, parse() still serves — decode is
    unconstrained, post-hoc validation stays authoritative."""
    from k_llms_tpu import KLLMs

    clear_grammar_cache()
    client = KLLMs(backend="tpu", model="tiny", max_new_tokens=32)
    with fp.failpoints({"engine.grammar": FailSpec(action="fallback", times=1)}):
        r = client.chat.completions.parse(
            messages=[{"role": "user", "content": "extract"}],
            response_format=Record, model="tiny", n=2, seed=5,
        )
    assert len(r.choices) == 3  # consensus + 2 samples: request served
    client.close()


# ---------------------------------------------------------------------------
# byte-identity: no constraint attached == pre-grammar output
# ---------------------------------------------------------------------------


def test_constrained_decoding_off_is_byte_identical_to_no_response_format():
    """BackendConfig(constrained_decoding=False) + response_format produces
    EXACTLY the tokens of a plain request: the grammar path adds nothing when
    no mask is attached."""
    from k_llms_tpu.backends.base import ChatRequest
    from k_llms_tpu.backends.tpu import BackendConfig, TpuBackend

    msgs = [{"role": "user", "content": "say something"}]

    def run(config_kwargs, req_kwargs):
        backend = TpuBackend(
            model="tiny",
            config=BackendConfig(model="tiny", max_new_tokens=24, **config_kwargs),
        )
        req = ChatRequest(messages=msgs, model="tiny", n=3, seed=17,
                          temperature=0.9, **req_kwargs)
        r = backend.chat_completion(req)
        texts = [c.message.content for c in r.choices[1:]]
        backend.drain()
        return texts

    plain = run({}, {})
    off = run({"constrained_decoding": False},
              {"response_format": {"type": "json_object"}})
    assert off == plain


def test_engine_generate_without_constraint_unchanged_by_grammar_import():
    """Direct engine check: generate() with constraint=None is deterministic
    and unaffected by grammar compilation happening in the same process."""
    from conftest import shared_engine

    eng = shared_engine(model="tiny")
    a = eng.generate([1, 2, 3], n=2, max_new_tokens=8, temperature=0.8, seed=9)
    clear_grammar_cache()
    _grammar(Record.model_json_schema())  # compile something in between
    b = eng.generate([1, 2, 3], n=2, max_new_tokens=8, temperature=0.8, seed=9)
    assert np.array_equal(a.tokens, b.tokens)
    assert np.allclose(a.logprobs, b.logprobs)


# ---------------------------------------------------------------------------
# the continuous loop decodes under the mask
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def loop():
    from conftest import shared_engine

    from k_llms_tpu.engine.continuous import ContinuousDecodeLoop

    eng = shared_engine(model="tiny")
    lp = ContinuousDecodeLoop(eng, width=4, max_prompt=64, max_new=96)
    yield lp
    lp.stop()


def _prompt():
    return TOK.apply_chat_template([{"role": "user", "content": "extract"}])


def test_continuous_loop_constrained_rows_obey_grammar(loop):
    clear_grammar_cache()
    g = _grammar(Record.model_json_schema())
    r = loop.submit(
        _prompt(), n=3, max_new=96, temperature=1.0, top_p=None, seed=23,
        grammar=g,
    ).result(timeout=120)
    for i in range(3):
        ids = [int(t) for t in r.tokens[i][: int(r.lengths[i])]]
        body = [t for t in ids if t < 256]
        ok, _ = validate_grammar_tokens(g, body)
        assert ok, bytes(body)
        if r.finish_reasons[i] == "stop":
            Record.model_validate(json.loads(bytes(body)))


def test_continuous_loop_mixed_batch_leaves_plain_rows_byte_identical(loop):
    """A grammar row decoding beside a plain row must not perturb the plain
    row's tokens: masking is jnp.where-gated per row, and row keys are
    position-independent."""
    alone = loop.submit(
        [1, 2, 3, 4, 5], n=2, max_new=8, temperature=0.7, top_p=0.9, seed=31
    ).result(timeout=120)
    g = _grammar(Record.model_json_schema())
    noisy = loop.submit(
        _prompt(), n=1, max_new=64, temperature=1.0, top_p=None, seed=1, grammar=g
    )
    beside = loop.submit(
        [1, 2, 3, 4, 5], n=2, max_new=8, temperature=0.7, top_p=0.9, seed=31
    ).result(timeout=120)
    noisy.result(timeout=120)
    assert np.array_equal(alone.tokens, beside.tokens)
    assert np.allclose(alone.logprobs, beside.logprobs, atol=1e-5)


def test_continuous_loop_rejects_second_grammar_while_busy(loop):
    """The loop holds ONE resident grammar; a different schema mid-flight is
    bounced to the coalescing path via ValueError (the backend catches it)."""
    g1 = _grammar(Record.model_json_schema())

    class Other(BaseModel):
        flag: bool

    g2 = _grammar(Other.model_json_schema())
    assert g1.digest != g2.digest
    holder = {}

    def sink(step, _toks):
        if step == 0 and "err" not in holder:
            try:
                loop.submit(_prompt(), n=1, max_new=8, temperature=0.0,
                            top_p=None, seed=2, grammar=g2)
                holder["err"] = None
            except ValueError as e:
                holder["err"] = e

    fut = loop.submit(
        _prompt(), n=1, max_new=48, temperature=1.0, top_p=None, seed=3,
        grammar=g1, token_sink=sink,
    )
    fut.result(timeout=120)
    assert isinstance(holder.get("err"), ValueError)
    # Once drained, the other grammar is admissible (resident swap).
    r = loop.submit(_prompt(), n=1, max_new=48, temperature=0.0, top_p=None,
                    seed=2, grammar=g2).result(timeout=120)
    body = [int(t) for t in r.tokens[0][: int(r.lengths[0])] if int(t) < 256]
    assert validate_grammar_tokens(g2, body)[0]


# ---------------------------------------------------------------------------
# TRUTH_DOCS differential: constrained greedy parses under every schema shape
# ---------------------------------------------------------------------------


def _schema_of(value):
    """Structural JSON schema of a truth document (objects closed, arrays
    typed from their first element) — the schemas bench_constrained uses."""
    if isinstance(value, bool):
        return {"type": "boolean"}
    if isinstance(value, int):
        return {"type": "integer"}
    if isinstance(value, float):
        return {"type": "number"}
    if isinstance(value, str):
        return {"type": "string"}
    if isinstance(value, list):
        return {"type": "array", "items": _schema_of(value[0])}
    if isinstance(value, dict):
        return {
            "type": "object",
            "properties": {k: _schema_of(v) for k, v in value.items()},
            "required": list(value),
            "additionalProperties": False,
        }
    raise TypeError(type(value))


@pytest.mark.parametrize("doc", ["invoice", "purchase_order", "profile"])
def test_constrained_greedy_parses_under_every_truth_schema(doc):
    """For each TRUTH_DOCS shape: greedy decode under the compiled grammar
    yields a mask-legal token stream, and a completed stream is a full JSON
    document valid under the byte DFA (the differential the bench reports)."""
    from conftest import shared_engine

    from k_llms_tpu.utils.quality import TRUTH_DOCS

    schema = _schema_of(TRUTH_DOCS[doc])
    dfa = compile_schema(schema)
    g = _grammar(schema)
    assert isinstance(g, CompiledGrammar)
    eng = shared_engine(model="tiny")
    r = eng.generate(
        _prompt(), n=2, max_new_tokens=160, temperature=0.0, seed=1,
        eos_ids=TOK.stop_ids, constraint=g,
    )
    for i in range(2):
        ids = [int(t) for t in r.tokens[i][: int(r.lengths[i])]]
        body = [t for t in ids if t < 256]
        ok, _ = validate_grammar_tokens(g, body)
        assert ok, bytes(body)
        assert validate_bytes(dfa, bytes(body))[0]
        if r.finish_reasons[i] == "stop":
            assert validate_bytes(dfa, bytes(body))[1]
            json.loads(bytes(body))
