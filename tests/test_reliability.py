"""Request-lifecycle hardening: deadlines, cancellation, retry/circuit,
failpoint injection, and partial-failure consensus — all CPU-only.

The reference SDK inherits timeout/retry machinery from the OpenAI client
(PAPER.md §0); this suite pins the locally-built replacement end to end:
unit behavior of the reliability primitives, typed-error wire shapes, and the
ISSUE acceptance scenarios (seeded mid-decode sample kills degrade to a
survivor consensus; zero survivors / pre-admission expiry raise typed errors
within the deadline plus one scheduler window).
"""

import math
import threading
import time

import pytest

from k_llms_tpu import KLLMs
from k_llms_tpu.reliability import failpoints as fp
from k_llms_tpu.reliability.deadline import Deadline, RequestBudget
from k_llms_tpu.reliability.failpoints import FailSpec
from k_llms_tpu.reliability.retry import CircuitBreaker, RetryPolicy, is_retryable
from k_llms_tpu.types.wire import (
    BackendUnavailableError,
    KLLMsError,
    RequestCancelledError,
    RequestTimeoutError,
)
from k_llms_tpu.utils.observability import EventCounters


# -- Deadline / RequestBudget ---------------------------------------------


def test_deadline_infinite_by_default():
    d = Deadline()
    assert not d.finite
    assert d.remaining() == math.inf
    assert not d.expired()
    assert not Deadline.from_timeout(None).finite


def test_deadline_from_timeout_counts_down():
    d = Deadline.from_timeout(30.0)
    assert d.finite
    assert 29.0 < d.remaining() <= 30.0
    assert not d.expired()
    assert Deadline.from_timeout(0.0).expired()


def test_deadline_negative_timeout_rejected():
    with pytest.raises(ValueError, match="timeout must be >= 0"):
        Deadline.from_timeout(-1.0)


def test_budget_cancel_token():
    b = RequestBudget.from_timeout(None)
    assert not b.should_abort()
    b.check("anywhere")  # no-op while healthy
    b.cancel()
    assert b.cancelled and b.should_abort()
    with pytest.raises(RequestCancelledError, match="at stage-x"):
        b.check("stage-x")


def test_budget_expiry_raises_timeout():
    b = RequestBudget.from_timeout(0.0)
    assert b.should_abort()
    with pytest.raises(RequestTimeoutError, match="deadline exceeded"):
        b.check("queue")


def test_budget_cancel_verdict_wins_over_expiry():
    """Cancel is the caller's explicit signal; expiry is incidental."""
    b = RequestBudget.from_timeout(0.0)
    b.cancel()
    assert isinstance(b.error(), RequestCancelledError)


# -- typed error wire shapes ----------------------------------------------


def test_error_wire_shapes_match_openai_contract():
    cases = [
        (RequestTimeoutError("t"), "timeout", "request_timeout", 408),
        (RequestCancelledError("c"), "cancelled", "request_cancelled", 499),
        (BackendUnavailableError("b"), "server_error", "backend_unavailable", 503),
    ]
    for err, etype, code, status in cases:
        assert isinstance(err, KLLMsError)
        assert err.status_code == status
        wire = err.as_wire()
        assert wire["error"]["type"] == etype
        assert wire["error"]["code"] == code
        assert wire["error"]["message"]


# -- RetryPolicy ----------------------------------------------------------


def test_retry_succeeds_after_transient_failures():
    policy = RetryPolicy(max_attempts=3, base_delay=0.01, seed=7)
    sleeps = []
    attempts = []

    def flaky():
        attempts.append(1)
        if len(attempts) < 3:
            raise OSError("transient")
        return "ok"

    assert policy.call(flaky, sleep=sleeps.append) == "ok"
    assert len(attempts) == 3
    assert len(sleeps) == 2
    assert all(s >= 0 for s in sleeps)


def test_retry_exhaustion_raises_last_error():
    policy = RetryPolicy(max_attempts=2, base_delay=0.0, jitter=False)
    calls = []

    def always_down():
        calls.append(1)
        raise OSError("still down")

    with pytest.raises(OSError, match="still down"):
        policy.call(always_down, sleep=lambda _s: None)
    assert len(calls) == 2


def test_retry_skips_non_retryable():
    policy = RetryPolicy(max_attempts=5)
    calls = []

    def param_bug():
        calls.append(1)
        raise ValueError("caller bug")

    with pytest.raises(ValueError):
        policy.call(param_bug)
    assert len(calls) == 1  # parameter errors fail identically every attempt
    assert not is_retryable(ValueError("x"))
    assert not is_retryable(RequestTimeoutError("final verdict"))
    assert is_retryable(OSError("transient"))


def test_retry_deterministic_schedule_with_seed():
    a = RetryPolicy(max_attempts=4, base_delay=0.05, seed=123)
    b = RetryPolicy(max_attempts=4, base_delay=0.05, seed=123)
    assert [a.delay_for(k) for k in (1, 2, 3)] == [b.delay_for(k) for k in (1, 2, 3)]
    nj = RetryPolicy(base_delay=0.05, max_delay=2.0, jitter=False)
    assert [nj.delay_for(k) for k in (1, 2, 3)] == [0.05, 0.1, 0.2]
    assert nj.delay_for(20) == 2.0  # capped


def test_retry_respects_spent_budget():
    policy = RetryPolicy(max_attempts=5, base_delay=0.0)
    budget = RequestBudget.from_timeout(0.0)
    with pytest.raises(RequestTimeoutError):
        policy.call(lambda: "never", budget=budget)


def test_retry_sleep_bounded_by_remaining_budget():
    policy = RetryPolicy(max_attempts=3, base_delay=10.0, jitter=False)
    budget = RequestBudget.from_timeout(0.2)
    sleeps = []

    def flaky():
        if not sleeps:
            raise OSError("once")
        return "ok"

    assert policy.call(flaky, budget=budget, sleep=sleeps.append) == "ok"
    assert len(sleeps) == 1
    assert sleeps[0] <= 0.2  # a retry never outlives the deadline


# -- CircuitBreaker -------------------------------------------------------


def make_breaker(**kw):
    clock = [0.0]
    kw.setdefault("failure_threshold", 3)
    kw.setdefault("reset_timeout", 10.0)
    return CircuitBreaker(clock=lambda: clock[0], **kw), clock


def test_circuit_opens_after_threshold_and_sheds_fast():
    br, _clock = make_breaker()
    for _ in range(3):
        br.allow()
        br.record_failure()
    assert br.state == "open"
    with pytest.raises(BackendUnavailableError, match="circuit open"):
        br.allow()


def test_circuit_half_open_probe_then_close():
    br, clock = make_breaker()
    for _ in range(3):
        br.record_failure()
    clock[0] = 10.0  # reset_timeout elapsed: one probe admitted
    br.allow()
    assert br.state == "half_open"
    with pytest.raises(BackendUnavailableError, match="probe in flight"):
        br.allow()  # concurrent callers shed while the probe runs
    br.record_success()
    assert br.state == "closed"
    br.allow()


def test_circuit_half_open_probe_failure_reopens():
    br, clock = make_breaker()
    for _ in range(3):
        br.record_failure()
    clock[0] = 10.0
    br.allow()  # probe admitted
    br.record_failure()
    assert br.state == "open"
    clock[0] = 15.0  # opened_at moved to 10.0; not yet due again
    with pytest.raises(BackendUnavailableError):
        br.allow()


def test_circuit_success_resets_failure_streak():
    br, _clock = make_breaker()
    br.record_failure()
    br.record_failure()
    br.record_success()  # streak broken
    br.record_failure()
    br.record_failure()
    assert br.state == "closed"  # threshold counts CONSECUTIVE failures


# -- failpoints -----------------------------------------------------------


def test_failpoint_raise_bounded_by_times():
    with fp.failpoints({"backend.dispatch": FailSpec(action="raise", times=2)}):
        for _ in range(2):
            with pytest.raises(RuntimeError, match="injected failpoint"):
                fp.fire("backend.dispatch")
        assert fp.fire("backend.dispatch") is None  # reverted to no-op
        assert fp.fire("engine.decode") is None  # other sites untouched
    assert not fp.active()


def test_failpoint_kill_samples_returns_spec():
    spec = FailSpec(action="kill_samples", kill=3, seed=9)
    with fp.failpoints({"engine.decode": spec}):
        got = fp.fire("engine.decode")
        assert got is spec and got.kill == 3 and got.seed == 9


def test_failpoint_unknown_site_fails_loudly():
    with pytest.raises(ValueError, match="unknown failpoint site"):
        with fp.failpoints({"scheduler.typo": FailSpec()}):
            pass  # pragma: no cover
    with pytest.raises(ValueError, match="unknown failpoint action"):
        FailSpec(action="explode")


def test_failpoint_scopes_nest_and_restore():
    outer = FailSpec(action="kill_samples", kill=1)
    inner = FailSpec(action="kill_samples", kill=2)
    with fp.failpoints({"engine.decode": outer}):
        with fp.failpoints({"engine.decode": inner}):
            assert fp.fire("engine.decode").kill == 2
        assert fp.fire("engine.decode").kill == 1
    assert fp.fire("engine.decode") is None


def test_failpoint_env_parsing():
    fp.configure_from_env("backend.dispatch=raise:2,engine.decode=kill_samples:3:7")
    try:
        assert fp._registry["backend.dispatch"].times == 2
        spec = fp._registry["engine.decode"]
        assert spec.action == "kill_samples" and spec.kill == 3 and spec.seed == 7
    finally:
        fp.clear()
    with pytest.raises(ValueError, match="unknown site"):
        fp.configure_from_env("nonsense.site=raise")
    fp.clear()
    fp.configure_from_env("")  # empty env is a no-op
    assert not fp.active()


def test_failpoint_hang_env_parsing_defaults():
    fp.configure_from_env("engine.launch=hang:1:30,engine.logits=nan:2:7,loader.params=corrupt:1")
    try:
        hang = fp._registry["engine.launch"]
        assert hang.action == "hang" and hang.times == 1 and hang.delay == 30.0
        nan = fp._registry["engine.logits"]
        assert nan.action == "nan" and nan.kill == 2 and nan.seed == 7
        assert fp._registry["loader.params"].action == "corrupt"
    finally:
        fp.clear()
    # A bare hang spec defaults to "effectively forever" — the watchdog, not
    # the spec, must be what unwedges the launch.
    fp.configure_from_env("engine.launch=hang")
    try:
        assert fp._registry["engine.launch"].delay == fp.HANG_DELAY
    finally:
        fp.clear()


def test_failpoint_scheduler_admit_raises_at_submission():
    """The scheduler.admit site fires at submit time, BEFORE any queueing —
    the injected fault reaches the caller synchronously."""
    from k_llms_tpu.engine.scheduler import EngineScheduler

    s = EngineScheduler(name="admit-fp")
    try:
        with fp.failpoints({"scheduler.admit": FailSpec(action="raise", times=1)}):
            with pytest.raises(RuntimeError, match="injected failpoint fault"):
                s.call(lambda: 1)
        assert s.call(lambda: 2) == 2  # spec consumed; admission healthy again
    finally:
        s.drain(timeout=5.0)


def test_failpoint_consensus_consolidate_raises():
    """The consensus.consolidate site fires at consolidation entry, after
    generation — a consolidation fault must not be mistaken for a backend
    fault (no breaker/retry involvement)."""
    from k_llms_tpu.consensus.consolidation import consolidate_chat_completions
    from k_llms_tpu.consensus.similarity import SimilarityScorer
    from k_llms_tpu.types import ChatCompletion

    completion = ChatCompletion.model_validate(
        {
            "id": "cc-1",
            "object": "chat.completion",
            "created": 0,
            "model": "tiny",
            "choices": [
                {
                    "index": 0,
                    "finish_reason": "stop",
                    "message": {"role": "assistant", "content": "hi"},
                }
            ],
        }
    )
    scorer = SimilarityScorer.levenshtein()
    with fp.failpoints({"consensus.consolidate": FailSpec(action="raise", times=1)}):
        with pytest.raises(RuntimeError, match="injected failpoint fault"):
            consolidate_chat_completions([completion], scorer)
    consolidate_chat_completions([completion], scorer)  # healthy after the scope


# -- failure-event counters -----------------------------------------------


def test_event_counters():
    c = EventCounters()
    assert c.get("x") == 0
    c.record("x")
    c.record("x", 2)
    c.record("y")
    assert c.get("x") == 3
    snap = c.snapshot()
    assert snap == {"x": 3, "y": 1}
    c.record("x")
    assert snap["x"] == 3  # snapshot is a copy, not a view
    c.reset()
    assert c.snapshot() == {}


# -- client plumbing (fake backend: hermetic, no device work) -------------


def make_fake_client(contents, **kw):
    return KLLMs(backend="fake", responses=[contents], **kw)


def test_create_rejects_negative_timeout():
    client = make_fake_client(["a"])
    with pytest.raises(ValueError, match="timeout must be >= 0"):
        client.chat.completions.create(
            messages=[{"role": "user", "content": "q"}], model="m", timeout=-1
        )


def test_create_rejects_bad_budget_type():
    client = make_fake_client(["a"])
    with pytest.raises(ValueError, match="budget must be a RequestBudget"):
        client.chat.completions.create(
            messages=[{"role": "user", "content": "q"}], model="m", budget=3.0
        )


def test_expired_timeout_raises_typed_error_fast():
    client = make_fake_client(["a", "b"])
    t0 = time.monotonic()
    with pytest.raises(RequestTimeoutError):
        client.chat.completions.create(
            messages=[{"role": "user", "content": "q"}], model="m", n=2, timeout=0.0
        )
    assert time.monotonic() - t0 < 1.0  # shed, not served


def test_client_level_default_timeout_applies():
    client = make_fake_client(["a"], timeout=0.0)
    with pytest.raises(RequestTimeoutError):
        client.chat.completions.create(
            messages=[{"role": "user", "content": "q"}], model="m"
        )
    # per-call timeout overrides the client default
    resp = client.chat.completions.create(
        messages=[{"role": "user", "content": "q"}], model="m", timeout=30.0
    )
    assert resp.choices[0].message.content == "a"


def test_pre_cancelled_budget_raises_cancelled():
    client = make_fake_client(["a"])
    budget = RequestBudget.from_timeout(None)
    budget.cancel()
    with pytest.raises(RequestCancelledError):
        client.chat.completions.create(
            messages=[{"role": "user", "content": "q"}], model="m", budget=budget
        )


def test_dispatch_retries_transient_backend_fault():
    """backend.dispatch raise:2 with max_attempts=3: two injected faults are
    absorbed by the retry policy and the request still succeeds."""
    client = make_fake_client(["hello"])
    client.backend.retry_policy = RetryPolicy(max_attempts=3, base_delay=0.0, seed=1)
    with fp.failpoints({"backend.dispatch": FailSpec(action="raise", times=2)}):
        resp = client.chat.completions.create(
            messages=[{"role": "user", "content": "q"}], model="m"
        )
    assert resp.choices[0].message.content == "hello"


def test_dispatch_circuit_opens_on_persistent_fault():
    """A backend that fails every dispatch trips its circuit breaker; the
    breaker then sheds subsequent calls with the typed unavailable error."""
    client = make_fake_client(["hello"])
    client.backend.retry_policy = RetryPolicy(max_attempts=1)
    breaker = client.backend.circuit_breaker
    assert breaker is client.backend.circuit_breaker  # lazily cached per backend
    with fp.failpoints({"backend.dispatch": FailSpec(action="raise")}):
        for _ in range(breaker.failure_threshold):
            with pytest.raises(RuntimeError):
                client.chat.completions.create(
                    messages=[{"role": "user", "content": "q"}], model="m"
                )
        assert breaker.state == "open"
        with pytest.raises(BackendUnavailableError):
            client.chat.completions.create(
                messages=[{"role": "user", "content": "q"}], model="m"
            )
    breaker.record_success()  # close it again for other tests


# -- acceptance: partial-failure consensus on the real engine -------------


@pytest.fixture(scope="module")
def tpu_client():
    return KLLMs(backend="tpu", model="tiny", max_new_tokens=16)


def test_kill_3_of_8_degrades_to_survivor_consensus(tpu_client):
    """ISSUE acceptance: a seeded failpoint kills 3 of n=8 samples mid-decode;
    create() still returns a consensus built from the 5 survivors, with a
    structured degraded marker and survival-scaled likelihoods."""
    with fp.failpoints(
        {"engine.decode": FailSpec(action="kill_samples", kill=3, seed=4)}
    ):
        resp = tpu_client.chat.completions.create(
            messages=[{"role": "user", "content": "report"}],
            model="tiny",
            n=8,
            temperature=0.0,
            seed=11,
        )
    assert len(resp.choices) == 9  # consensus + 8 originals
    killed = [c for c in resp.choices[1:] if getattr(c, "sample_error", None)]
    survivors = [c for c in resp.choices[1:] if not getattr(c, "sample_error", None)]
    assert len(killed) == 3 and len(survivors) == 5
    assert all(c.message.content == "" for c in killed)
    assert all(k.sample_error["code"] == "decode_fault" for k in killed)
    # consensus comes from the survivors (greedy: all five agree)
    assert resp.choices[0].message.content == survivors[0].message.content
    assert resp.choices[0].message.content != ""
    # structured degraded marker
    assert resp.degraded["requested"] == 8
    assert resp.degraded["survived"] == 5
    assert resp.degraded["survival_fraction"] == pytest.approx(5 / 8)
    assert len(resp.degraded["sample_errors"]) == 3
    # survival-scaled likelihoods: unanimous survivors would score 1.0; the
    # loss of 3/8 samples scales that to 0.625
    assert resp.likelihoods == {"text": pytest.approx(5 / 8)}


def test_kill_all_samples_raises_typed_error(tpu_client):
    """Zero survivors is not a consensus: the typed backend error surfaces."""
    with fp.failpoints(
        {"engine.decode": FailSpec(action="kill_samples", kill=8, seed=0)}
    ):
        with pytest.raises(BackendUnavailableError, match="all 8 samples failed"):
            tpu_client.chat.completions.create(
                messages=[{"role": "user", "content": "report"}],
                model="tiny",
                n=8,
                temperature=0.0,
                seed=11,
            )


def test_healthy_request_has_no_degraded_marker(tpu_client):
    resp = tpu_client.chat.completions.create(
        messages=[{"role": "user", "content": "ok"}], model="tiny", n=3, seed=2
    )
    assert resp.degraded is None
    assert len(resp.choices) == 4


def test_deadline_expired_pre_admission_bounded(tpu_client):
    """ISSUE acceptance: an already-expired deadline raises the typed error
    within timeout + one scheduler window — never reaching the device."""
    served_before = tpu_client.backend.scheduler.stats["served"]
    t0 = time.monotonic()
    with pytest.raises(RequestTimeoutError):
        tpu_client.chat.completions.create(
            messages=[{"role": "user", "content": "late"}],
            model="tiny",
            n=8,
            timeout=0.0,
        )
    elapsed = time.monotonic() - t0
    assert elapsed < 0.0 + tpu_client.backend.scheduler.batch_window + 1.0
    assert tpu_client.backend.scheduler.stats["served"] == served_before


def test_mid_decode_cancellation_stops_at_token_granularity(tpu_client):
    """An in-flight request cancelled from another thread stops between decode
    steps and surfaces the typed cancellation error."""
    msgs = [{"role": "user", "content": "long story"}]
    # Warm the compile caches (with and without the cancel poller) so the
    # cancel below lands during DECODE, not during XLA compilation.
    warm = RequestBudget.from_timeout(None)
    tpu_client.chat.completions.create(
        messages=msgs, model="tiny", n=2, max_tokens=512, seed=3, budget=warm,
        stop="\x00",  # unmatchable: forces the full 512-token decode shape
    )
    budget = RequestBudget.from_timeout(None)
    box = {}

    def run():
        t0 = time.monotonic()
        try:
            tpu_client.chat.completions.create(
                messages=msgs, model="tiny", n=2, max_tokens=512, seed=3,
                budget=budget, stop="\x00",
            )
            box["outcome"] = "completed"
        except RequestCancelledError:
            box["outcome"] = "cancelled"
        except Exception as e:  # pragma: no cover - diagnostic
            box["outcome"] = repr(e)
        box["elapsed"] = time.monotonic() - t0

    t = threading.Thread(target=run)
    t.start()
    time.sleep(0.3)  # let decode start (warm path: prefill is milliseconds)
    budget.cancel()
    t.join(timeout=60)
    assert not t.is_alive()
    assert box["outcome"] == "cancelled", box


def test_timeout_expiring_mid_decode_raises_timeout(tpu_client):
    """A finite deadline shorter than the decode aborts between steps with the
    timeout error (same poller as cancellation, different verdict)."""
    msgs = [{"role": "user", "content": "long story"}]
    warm = RequestBudget.from_timeout(None)
    tpu_client.chat.completions.create(
        messages=msgs, model="tiny", n=2, max_tokens=512, seed=3, budget=warm,
        stop="\x00",
    )
    with pytest.raises(RequestTimeoutError):
        tpu_client.chat.completions.create(
            messages=msgs, model="tiny", n=2, max_tokens=512, seed=3,
            timeout=0.25, stop="\x00",
        )


@pytest.mark.slow
def test_chaos_soak_mixed_failpoints(tpu_client):
    """Long-running chaos soak (excluded from the tier-1 budget run via the
    registered ``slow`` marker): alternate healthy, degraded, dispatch-fault,
    and shed requests for many rounds and assert the serving stack never
    wedges — every request either returns a well-formed response or raises a
    typed lifecycle error, and a healthy request still succeeds at the end."""
    msgs = [{"role": "user", "content": "soak"}]
    outcomes = {"ok": 0, "degraded": 0, "typed": 0}
    for round_ in range(12):
        mode = round_ % 4
        try:
            if mode == 0:
                resp = tpu_client.chat.completions.create(
                    messages=msgs, model="tiny", n=4, seed=round_
                )
            elif mode == 1:
                with fp.failpoints(
                    {"engine.decode": FailSpec(action="kill_samples", kill=2, seed=round_)}
                ):
                    resp = tpu_client.chat.completions.create(
                        messages=msgs, model="tiny", n=4, temperature=0.0, seed=round_
                    )
            elif mode == 2:
                tpu_client.backend.retry_policy = RetryPolicy(
                    max_attempts=3, base_delay=0.0, seed=round_
                )
                with fp.failpoints(
                    {"backend.dispatch": FailSpec(action="raise", times=1)}
                ):
                    resp = tpu_client.chat.completions.create(
                        messages=msgs, model="tiny", n=4, seed=round_
                    )
            else:
                with pytest.raises(RequestTimeoutError):
                    tpu_client.chat.completions.create(
                        messages=msgs, model="tiny", n=4, timeout=0.0
                    )
                outcomes["typed"] += 1
                continue
        except KLLMsError:
            outcomes["typed"] += 1
            continue
        assert len(resp.choices) == 5
        if resp.degraded is not None:
            assert resp.degraded["survived"] == 2
            outcomes["degraded"] += 1
        else:
            outcomes["ok"] += 1
    assert outcomes["ok"] >= 3 and outcomes["degraded"] >= 3 and outcomes["typed"] >= 3
    # the stack is still healthy after the chaos
    resp = tpu_client.chat.completions.create(messages=msgs, model="tiny", n=3, seed=99)
    assert resp.degraded is None and len(resp.choices) == 4
