"""Continuous in-flight batching (engine/continuous.py): the persistent
W-slot decode loop behind the serving path's streaming mode.

The load-bearing pins: a late request JOINS a decode already in flight (the
whole point — no waiting behind coalesced groups), sampling is
self-deterministic regardless of batch composition, budget cancellation
retires slot rows through the same ``engine.decode_abort`` accounting as the
batch path, and the TpuBackend routes only qualifying requests to the loop.
"""

import time

import numpy as np
import pytest

from k_llms_tpu.engine.continuous import ContinuousDecodeLoop
from k_llms_tpu.reliability.deadline import RequestBudget
from k_llms_tpu.types.wire import RequestCancelledError
from k_llms_tpu.utils.observability import FAILURE_EVENTS


@pytest.fixture(scope="module")
def loop():
    from conftest import shared_engine

    eng = shared_engine(model="tiny")
    lp = ContinuousDecodeLoop(eng, width=4, max_prompt=64, max_new=32)
    yield lp
    lp.stop()


def test_basic_generation_and_sink_order(loop):
    sunk = []
    fut = loop.submit(
        [1, 2, 3, 4, 5], n=2, max_new=8, temperature=0.7, top_p=0.9, seed=7,
        token_sink=lambda step, toks: sunk.append((step, toks.copy())),
    )
    result = fut.result(timeout=120)
    assert result.tokens.shape == (2, 8)
    assert list(result.lengths) == [8, 8] or all(
        fin in ("stop", "length") for fin in result.finish_reasons
    )
    # Sink delivery is strictly in step order and bit-identical to the final
    # buffers (the host drives the loop, so there is no reorder window).
    assert [s for s, _ in sunk] == list(range(len(sunk)))
    for step, row in sunk:
        for j in range(2):
            if step < result.lengths[j]:
                assert row[j] == result.tokens[j, step]


def test_self_deterministic_across_batch_composition(loop):
    """Same seed → same tokens, whether the request ran alone or beside
    others — row keys derive from (seed, step, sample), not slot position."""
    a = loop.submit(
        [1, 2, 3, 4, 5], n=2, max_new=8, temperature=0.7, top_p=0.9, seed=21
    ).result(timeout=120)
    # Re-run with a neighbor occupying other slots.
    noise = loop.submit(
        [9, 8, 7], n=2, max_new=16, temperature=1.0, top_p=0.95, seed=4
    )
    b = loop.submit(
        [1, 2, 3, 4, 5], n=2, max_new=8, temperature=0.7, top_p=0.9, seed=21
    ).result(timeout=120)
    noise.result(timeout=120)
    assert np.array_equal(a.tokens, b.tokens)
    assert np.allclose(a.logprobs, b.logprobs, atol=1e-5)


def test_greedy_matches_batch_engine(loop):
    """temperature=0 through the slot loop reproduces the batch decode loop's
    greedy tokens — same model, same argmax, different orchestration."""
    cont = loop.submit(
        [1, 2, 3, 4, 5], n=1, max_new=8, temperature=0.0, top_p=None, seed=3
    ).result(timeout=120)
    batch = loop.engine.generate(
        [1, 2, 3, 4, 5], n=1, max_new_tokens=8, temperature=0.0, seed=3
    )
    nc, nb = int(cont.lengths[0]), int(batch.lengths[0])
    assert np.array_equal(cont.tokens[0][:nc], batch.tokens[0][:nb])


def test_late_request_joins_in_flight_decode(loop):
    """Acceptance pin: a request submitted while another is mid-decode starts
    decoding before the first finishes (joined_in_flight increments and the
    active row count covers both requests at once)."""
    base_joined = loop.stats["joined_in_flight"]
    holder = {}

    def sink(step, _toks):
        # Deterministic mid-flight arrival: B is submitted the moment A's
        # first token lands, long before A's 32 steps finish.
        if step == 0 and "b" not in holder:
            holder["b"] = loop.submit(
                [4, 5, 6], n=1, max_new=4, temperature=0.8, top_p=0.95, seed=12
            )

    a = loop.submit(
        [1, 2, 3], n=2, max_new=32, temperature=0.8, top_p=0.95, seed=11,
        token_sink=sink,
    ).result(timeout=120)
    b = holder["b"].result(timeout=120)
    assert a.tokens.shape[0] == 2 and b.tokens.shape[0] == 1
    assert loop.stats["joined_in_flight"] > base_joined
    assert loop.stats["max_active_rows"] >= 3
    # Occupancy accounting is coherent: row_steps never exceeds steps * W.
    assert 0 < loop.stats["row_steps"] <= loop.stats["steps"] * loop.width


def test_budget_abort_retires_rows(loop):
    budget = RequestBudget()
    before = FAILURE_EVENTS.snapshot().get("engine.decode_abort", 0)
    fut = loop.submit(
        [1, 2, 3, 4], n=1, max_new=32, temperature=0.9, top_p=0.9, seed=5,
        budget=budget,
    )
    time.sleep(0.02)
    budget.cancel()
    with pytest.raises(RequestCancelledError):
        fut.result(timeout=120)
    assert FAILURE_EVENTS.snapshot().get("engine.decode_abort", 0) > before
    # Slots freed: a follow-up request still runs.
    ok = loop.submit(
        [1, 2], n=1, max_new=4, temperature=0.0, top_p=None, seed=1
    ).result(timeout=120)
    assert int(ok.lengths[0]) > 0


def test_qualification_bounds(loop):
    assert loop.qualifies(10, 2, 16)
    assert not loop.qualifies(10, loop.width + 1, 16)  # too many samples
    assert not loop.qualifies(loop.max_prompt + 1, 1, 16)  # prompt too long
    assert not loop.qualifies(10, 1, loop.max_new + 1)  # too many new tokens


def test_backend_routes_qualifying_requests_to_loop():
    """TpuBackend with continuous_batching=True serves plain sampling through
    the slot loop (stats move); since PR 12 grammar-constrained requests ride
    the same loop under the fused mask instead of dropping to coalescing."""
    import jax
    from conftest import shared_engine

    from k_llms_tpu import KLLMs
    from k_llms_tpu.backends.tpu import TpuBackend
    from k_llms_tpu.utils.observability import GRAMMAR_EVENTS

    engine = (
        shared_engine("tiny", mesh_shape=(8, 1)) if len(jax.devices()) == 8 else None
    )
    backend = TpuBackend(
        model="tiny", max_new_tokens=8, engine=engine,
        continuous_batching=True, continuous_width=4,
        continuous_max_prompt=128, continuous_max_new=64,
    )
    client = KLLMs(backend=backend, model="tiny")
    msgs = [{"role": "user", "content": "hello"}]

    r = client.chat.completions.create(messages=msgs, model="tiny", n=2, seed=9)
    assert len(r.choices) == 3
    assert backend._continuous.stats["admitted"] == 1

    # json_object response_format compiles to the generic-JSON grammar and
    # rides the loop as a masked request: admission count moves, and every
    # generated token is a counted masked step.
    masked_before = GRAMMAR_EVENTS.snapshot().get("grammar.masked_steps", 0)
    r2 = client.chat.completions.create(
        messages=msgs, model="tiny", n=1, seed=9, max_tokens=4,
        response_format={"type": "json_object"},
    )
    assert r2.choices
    assert backend._continuous.stats["admitted"] == 2
    assert GRAMMAR_EVENTS.snapshot().get("grammar.masked_steps", 0) > masked_before

    # health() surfaces the loop; drain() quiesces it and closes admission.
    assert backend.health()["continuous"]["completed"] >= 1
    assert backend.drain(timeout=30)
    from k_llms_tpu.types.wire import BackendUnavailableError, ServerDrainingError

    with pytest.raises((ServerDrainingError, BackendUnavailableError)):
        client.chat.completions.create(messages=msgs, model="tiny")
    client.close()


def test_continuous_determinism_matches_nonstream_through_backend():
    """The SAME request through the continuous loop with and without a token
    sink yields identical choices — streaming must not perturb sampling."""
    import jax
    from conftest import shared_engine

    from k_llms_tpu import KLLMs
    from k_llms_tpu.backends.tpu import TpuBackend

    engine = (
        shared_engine("tiny", mesh_shape=(8, 1)) if len(jax.devices()) == 8 else None
    )
    backend = TpuBackend(
        model="tiny", max_new_tokens=8, engine=engine,
        continuous_batching=True, continuous_width=4,
        continuous_max_prompt=128, continuous_max_new=64,
    )
    client = KLLMs(backend=backend, model="tiny")
    msgs = [{"role": "user", "content": "stream parity"}]
    plain = client.chat.completions.create(
        messages=msgs, model="tiny", n=2, seed=33, temperature=0.8
    )
    with client.chat.completions.create(
        messages=msgs, model="tiny", n=2, seed=33, temperature=0.8, stream=True
    ) as stream:
        for _ in stream:
            pass
        streamed = stream.response
    assert [c.message.content for c in plain.choices] == [
        c.message.content for c in streamed.choices
    ]
    client.close()
