"""Idle-slot chaos soak for the offline batch lane (ISSUE 17).

The acceptance drill: a steady interactive trickle runs against the
continuous-batching backend twice — once with the batch lane quiet (the
baseline) and once with a large durable batch job grinding through the same
scheduler — with the lock-order graph and the Eraser-style lockset sanitizer
armed (KLLMS_LOCKCHECK=1 + KLLMS_RACECHECK=1). Invariants: the interactive
p99 queue wait stays within 2x of the lane-off baseline (batch work fills
idle slots, it never displaces interactive admissions — WFQ selects the
interactive class first), the batch job completes with exactly one output
record per item, zero hung futures or worker threads, the backend is READY
at exit, and both sanitizers come out clean.
"""

import json
import math
import threading
import time

import pytest

from k_llms_tpu import KLLMs
from k_llms_tpu.analysis import lockcheck
from k_llms_tpu.reliability.jobstore import JobStore
from k_llms_tpu.serving.batch import BatchLane
from k_llms_tpu.utils.observability import LATENCY

#: Interactive queue waits on a CPU-jit tiny model are noisy at the low end;
#: the 2x isolation ratio is enforced above this floor, not below it.
QUEUE_WAIT_FLOOR_S = 2.5

N_TRICKLE = 6
N_BATCH_ITEMS = 16


def _backend():
    import jax
    from conftest import shared_engine

    from k_llms_tpu.backends.tpu import TpuBackend

    engine = (
        shared_engine("tiny", mesh_shape=(8, 1)) if len(jax.devices()) == 8 else None
    )
    return TpuBackend(
        model="tiny", max_new_tokens=8, engine=engine,
        continuous_batching=True, continuous_width=4,
        continuous_max_prompt=128, continuous_max_new=64,
        tenants={
            "acme": {"slo": "interactive", "weight": 1.0},
            "chat": {"slo": "interactive", "weight": 1.0},
        },
    )


def _hist_p99(name):
    """p99 upper bound straight off the cumulative histogram buckets."""
    snap = LATENCY.snapshot().get(name)
    assert snap is not None and snap["count"] > 0, f"no {name} observations"
    want = math.ceil(0.99 * snap["count"])
    for bound, cum in snap["buckets"]:
        if cum >= want:
            return bound
    return float("inf")


def _trickle(client, tag, seed_base):
    """Sequential interactive requests, each submitted while whatever else
    is in the system is already queued; returns nothing — the measurement
    is the scheduler.queue_wait.chat histogram."""
    for i in range(N_TRICKLE):
        cc = client.chat.completions.create(
            messages=[{"role": "user", "content": f"trickle {tag} {i}"}],
            model="tiny", n=1, seed=seed_base + i, tenant="chat",
        )
        assert cc.choices, f"{tag} request {i} returned no choices"
        time.sleep(0.2)


@pytest.mark.slow
@pytest.mark.duration_budget(300)
def test_interactive_p99_bounded_while_batch_job_drains(monkeypatch, tmp_path):
    monkeypatch.setenv("KLLMS_LOCKCHECK", "1")
    monkeypatch.setenv("KLLMS_RACECHECK", "1")
    lockcheck.reset_state()
    LATENCY.reset()
    backend = _backend()
    client = KLLMs(backend=backend, model="tiny")

    # -- phase 1: lane off. Warm caches, then measure the baseline p99. ----
    _trickle(client, "warm", 100)
    LATENCY.reset()
    _trickle(client, "base", 200)
    p99_base = _hist_p99("scheduler.queue_wait.chat")

    # -- phase 2: lane on. One large durable job grinds at batch SLO under
    # acme's quota while the identical trickle repeats. ---------------------
    LATENCY.reset()
    lane = BatchLane(client, JobStore(tmp_path), max_in_flight=3)
    body = "\n".join(
        json.dumps({"custom_id": f"item{i}", "body": {
            "messages": [{"role": "user", "content": f"offline work {i}"}],
            "n": 1, "seed": 500 + i,
        }})
        for i in range(N_BATCH_ITEMS)
    ).encode()
    wire = lane.submit(body, tenant="acme")
    _trickle(client, "loaded", 300)
    p99_loaded = _hist_p99("scheduler.queue_wait.chat")

    # The batch job itself must finish — deprioritized is not abandoned.
    assert lane.wait_idle(180), lane.health()
    final = lane.job_wire(wire["id"])
    assert final["status"] == "completed", final
    records = [
        json.loads(l) for l in lane.output_bytes(wire["id"]).splitlines()
    ]
    assert len(records) == N_BATCH_ITEMS
    ids = [r["id"] for r in records]
    assert len(set(ids)) == N_BATCH_ITEMS, "duplicate output records"
    assert all(r["response"]["status_code"] == 200 for r in records)

    # Items ran under acme's derived #batch lane, visible in the per-tenant
    # queue-wait attribution (batch SLO, owner's quota — PR 16 plumbing).
    lane_wait = LATENCY.snapshot().get("scheduler.queue_wait.acme#batch", {})
    assert lane_wait.get("count", 0) >= N_BATCH_ITEMS

    # The isolation headline: the loaded trickle's p99 queue wait is within
    # 2x of the lane-off baseline (floored against CPU-jit noise).
    bound = 2.0 * max(p99_base, QUEUE_WAIT_FLOOR_S)
    assert p99_loaded <= bound, (
        f"interactive p99 queue wait {p99_loaded:.2f}s with the lane on "
        f"vs {p99_base:.2f}s baseline — batch work displaced interactive"
    )

    # Zero hung worker threads; clean shutdown; sanitizers clean.
    lane.drain(timeout=30.0)
    health = lane.health()
    assert health["in_flight_items"] == 0, health
    lane.close()
    assert not any(
        t.name.startswith("kllms-batch") and t.is_alive()
        for t in threading.enumerate()
    )
    assert backend.health()["state"] == "ready"
    client.close()
    lockcheck.assert_clean()
