"""Primitive consensus: hybrid numeric clustering + similarity medoid
(reference consensus_utils :1075-1237)."""

import pytest

from k_llms_tpu.consensus.primitive import consensus_as_primitive
from k_llms_tpu.consensus.settings import ConsensusSettings
from k_llms_tpu.consensus.similarity import SimilarityScorer


@pytest.fixture
def scorer():
    return SimilarityScorer(method="levenshtein")


def run(values, scorer, settings=None, **kw):
    return consensus_as_primitive(values, settings or ConsensusSettings(), scorer, **kw)


def test_empty_and_single(scorer):
    assert run([None, None], scorer) == (None, 1.0)
    val, conf = run([5, None], scorer)
    assert val == 5
    assert conf == 0.5  # parent_valid_frac * non_none/total


def test_numeric_majority_cluster(scorer):
    # 100, 101 cluster together at 3% rel_eps; 200 is alone
    val, conf = run([100, 101, 200], scorer)
    assert val == pytest.approx(100.5)
    assert conf == round(2 / 3, 5)


def test_numeric_exact_majority(scorer):
    val, conf = run([7, 7, 7, 9999], scorer)
    assert val == pytest.approx(7.0)
    assert conf == 0.75


def test_numeric_tie_power10_support(scorer):
    # Two singleton clusters: 1000 and 100000... power-of-10 closeness breaks tie
    # via support absorption; deterministic outcome matters more than which wins.
    val, conf = run([1000.0, 10.0], scorer)
    assert val in (1000.0, 10.0)


def test_all_bools_go_numeric_branch_and_return_none(scorer):
    # Quirk parity: type(True)() == False isinstance int => numeric branch,
    # xs skips bools => (None, parent_valid_frac)
    val, conf = run([True, False, True], scorer)
    assert val is None
    assert conf == 1.0


def test_string_medoid(scorer):
    vals = ["the cat sat on the mat", "the cat sat on a mat", "dogs everywhere"]
    val, conf = run(vals, scorer)
    assert val in vals[:2]
    assert 0 < conf <= 1.0


def test_medoid_confidence_rounding(scorer):
    val, conf = run(["aaaa", "aaab"], scorer)
    assert conf == round(conf, 5)


def test_index_tuple_medoid(scorer):
    # The reference re-elects alignment group representatives by running
    # consensus_as_primitive on (list_idx, pos) tuples (:308-318)
    vals = [(0, 1), (1, 1), (2, 5)]
    val, conf = run(vals, scorer)
    assert val == (0, 1) or val == (1, 1)


def test_llm_consensus_mode(scorer):
    settings = ConsensusSettings(
        string_consensus_method="llm-consensus", string_similarity_method="embeddings"
    )
    s = SimilarityScorer(method="embeddings", embed_fn=None)
    val, conf = consensus_as_primitive(
        ["The sky is blue", "The sky is blue today", "El cielo es azul"],
        settings,
        s,
        llm_consensus_fn=lambda vs: "The sky is blue",
    )
    assert val == "The sky is blue"
    assert 0 < conf <= 1.0


def test_llm_consensus_requires_fn(scorer):
    settings = ConsensusSettings(
        string_consensus_method="llm-consensus", string_similarity_method="embeddings"
    )
    with pytest.raises(ValueError):
        consensus_as_primitive(["a b c d", "e f g h"], settings, scorer)


def test_none_majority_returns_none(scorer):
    # single non-None short-circuits earlier (:1085), so use two spread values
    val, conf = run([None, None, 5.0, 6.0], scorer)
    assert val is None
    assert conf == 0.5
