"""End-to-end KLLMs(backend="tpu") on the virtual CPU mesh: the BASELINE.md
acceptance path — n-way consensus with zero OpenAI calls."""

import numpy as np
import pytest

from k_llms_tpu import KLLMs
from k_llms_tpu.backends.tpu import TpuBackend


def _shared_tiny_engine():
    """The session-shared tiny engine on the default (8, 1) auto mesh — same
    construction KLLMs(backend="tpu") would do, minus the duplicate compiles."""
    import jax
    from conftest import shared_engine

    if len(jax.devices()) == 8:
        return shared_engine("tiny", mesh_shape=(8, 1))
    return None  # odd device counts: let the backend pick its own auto mesh


@pytest.fixture(scope="module")
def client():
    backend = TpuBackend(model="tiny", max_new_tokens=16, engine=_shared_tiny_engine())
    return KLLMs(backend=backend, model="tiny")


def test_create_consensus_contract(client):
    resp = client.chat.completions.create(
        messages=[{"role": "user", "content": "Tell me something"}],
        model="tiny",
        n=4,
        temperature=1.0,
        seed=11,
    )
    assert len(resp.choices) == 5  # consensus + 4 samples
    assert resp.choices[0].index == 0
    assert resp.likelihoods is not None
    assert resp.usage.prompt_tokens > 0
    assert resp.usage.completion_tokens > 0
    assert resp.system_fingerprint.startswith("k-llms-tpu/")


def test_create_seed_reproducible(client):
    kwargs = dict(
        messages=[{"role": "user", "content": "again"}], model="tiny", n=3, seed=5
    )
    a = client.chat.completions.create(**kwargs)
    b = client.chat.completions.create(**kwargs)
    assert [c.message.content for c in a.choices] == [c.message.content for c in b.choices]


def test_greedy_unanimous_consensus(client):
    resp = client.chat.completions.create(
        messages=[{"role": "user", "content": "x"}], model="tiny", n=3, temperature=0.0, seed=1
    )
    originals = [c.message.content for c in resp.choices[1:]]
    assert originals[0] == originals[1] == originals[2]
    assert resp.choices[0].message.content == originals[0]
    assert resp.likelihoods == {"text": 1.0}


def test_logprobs_surface(client):
    resp = client.chat.completions.create(
        messages=[{"role": "user", "content": "lp"}],
        model="tiny",
        n=2,
        seed=2,
        logprobs=True,
    )
    sample = resp.choices[1]
    assert sample.logprobs is not None
    assert len(sample.logprobs.content) > 0
    assert sample.logprobs.content[0].logprob <= 0.0


@pytest.mark.slow  # 17s e2e spanning embeddings + llm-consensus; each half
@pytest.mark.duration_budget(45)  # has dedicated tier-1 coverage
def test_backend_embeddings_and_llm_consensus():
    backend = TpuBackend(model="tiny", max_new_tokens=8, engine=_shared_tiny_engine())
    embs = backend.embeddings(["alpha beta", "alpha beta", "gamma"])
    assert len(embs) == 3
    np.testing.assert_allclose(embs[0], embs[1], rtol=1e-5)
    out = backend.llm_consensus(["a", "b", "a"])
    assert isinstance(out, str) and len(out) >= 0


def test_stop_string_truncates():
    backend = TpuBackend(model="tiny", max_new_tokens=12, engine=_shared_tiny_engine())
    client = KLLMs(backend=backend)
    resp = client.chat.completions.create(
        messages=[{"role": "user", "content": "y"}], model="tiny", n=1, seed=3
    )
    full = resp.choices[0].message.content
    if len(full) > 1:
        stop_char = full[1]
        resp2 = client.chat.completions.create(
            messages=[{"role": "user", "content": "y"}],
            model="tiny",
            n=1,
            seed=3,
            stop=stop_char,
        )
        # single-sample passthrough keeps the full text; multi-sample path truncates.
        # Use n=2 to exercise the truncation path deterministically.
        resp3 = client.chat.completions.create(
            messages=[{"role": "user", "content": "y"}],
            model="tiny",
            n=2,
            seed=3,
            stop=stop_char,
        )
        for choice in resp3.choices[1:]:
            assert stop_char not in (choice.message.content or "")


def test_concurrent_requests_coalesce(client):
    """Five concurrent clients with the same sampling config decode as one
    coalesced batch (the local answer to the reference's 5-worker concurrency
    baseline, README_TESTS.md:214), each still getting its own seed stream."""
    import threading

    backend = client.backend
    # Warm the compile caches (solo + coalesced-shape programs compile lazily).
    client.chat.completions.create(
        messages=[{"role": "user", "content": "warm"}], model="tiny", n=2, seed=0,
        temperature=0.7,
    )

    # Solo references for each prompt (serial, no coalescing possible).
    prompts = [f"question number {i}" for i in range(5)]
    solo = [
        client.chat.completions.create(
            messages=[{"role": "user", "content": p}], model="tiny", n=2,
            seed=100 + i, temperature=0.7,
        )
        for i, p in enumerate(prompts)
    ]

    coalesced_before = backend.scheduler.stats["coalesced"]
    gate = threading.Event()
    blocker = backend.scheduler.submit(gate.wait)  # hold the worker
    results = [None] * 5
    errors = []

    def call(i):
        try:
            results[i] = client.chat.completions.create(
                messages=[{"role": "user", "content": prompts[i]}], model="tiny",
                n=2, seed=100 + i, temperature=0.7,
            )
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=call, args=(i,)) for i in range(5)]
    for t in threads:
        t.start()
    # Wait until all five generation requests are queued behind the blocker.
    for _ in range(500):
        if backend.scheduler.stats["queued"] >= 5:
            break
        import time

        time.sleep(0.01)
    gate.set()
    for t in threads:
        t.join(timeout=120)
    blocker.result(timeout=5)

    assert errors == []
    assert backend.scheduler.stats["coalesced"] > coalesced_before
    for i, (r, s) in enumerate(zip(results, solo)):
        assert r is not None
        assert len(r.choices) == 3  # consensus + 2 samples
        # Per-request seed streams survive coalescing: same results as solo.
        assert [c.message.content for c in r.choices] == [
            c.message.content for c in s.choices
        ]


def test_top_logprobs_surface(client):
    """OpenAI parity: logprobs=True + top_logprobs=k returns k ranked
    alternatives per emitted token, containing real model logprobs."""
    resp = client.chat.completions.create(
        messages=[{"role": "user", "content": "tlp"}],
        model="tiny",
        n=2,
        seed=4,
        logprobs=True,
        top_logprobs=3,
    )
    sample = resp.choices[1]
    assert sample.logprobs is not None
    for entry in sample.logprobs.content:
        tops = entry.top_logprobs
        assert len(tops) == 3
        lps = [t.logprob for t in tops]
        assert lps == sorted(lps, reverse=True)  # ranked desc
        assert all(lp <= 0.0 for lp in lps)
        # The best alternative is at least as likely as the emitted token.
        assert lps[0] >= entry.logprob - 1e-5


def test_top_logprobs_requires_logprobs(client):
    # OpenAI semantics: top_logprobs without logprobs=True is ignored.
    resp = client.chat.completions.create(
        messages=[{"role": "user", "content": "x"}],
        model="tiny",
        n=2,
        seed=4,
        top_logprobs=3,
    )
    assert resp.choices[1].logprobs is None


def test_top_logprobs_range_validated(client):
    with pytest.raises(ValueError, match="top_logprobs"):
        client.chat.completions.create(
            messages=[{"role": "user", "content": "x"}], model="tiny", n=1,
            logprobs=True, top_logprobs=21,
        )
