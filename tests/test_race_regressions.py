"""Regression tests (two real threads each) for the races the guarded-by
rule surfaced and this change fixed.

Each test pins the fix with a deterministic mutual-exclusion oracle instead
of a probabilistic hammer: the main thread HOLDS the guarding lock while a
second real thread calls the fixed method. Before the fix the method touched
the shared state without the lock and completed (or snapshotted stale state)
immediately; after the fix it must block until the lock is released and then
observe the mutation made while the lock was held. A scheduling delay can
only make the pre-fix failure *less* likely to be missed, never fail the
fixed code.
"""

import threading
import time

import pytest

from k_llms_tpu.backends.fake import FakeBackend
from k_llms_tpu.reliability.replicas import ReplicaSet

# The blocked-reader probe window: long enough for the worker thread to hit
# the contended section, short enough to keep tier-1 fast.
_WINDOW_S = 0.15


def _start(fn):
    out = {}

    def run():
        out["value"] = fn()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    return t, out


def _finish(t, out):
    t.join(timeout=5.0)
    assert not t.is_alive()
    return out["value"]


# ---------------------------------------------------------------------------
# ContinuousDecodeLoop.stats: the counter snapshot must happen under the
# loop lock (it used to dict() the stats BEFORE acquiring it).
# ---------------------------------------------------------------------------


@pytest.mark.duration_budget(30)
def test_continuous_stats_snapshot_is_taken_under_the_loop_lock():
    from conftest import shared_engine
    from k_llms_tpu.engine.continuous import ContinuousDecodeLoop

    eng = shared_engine(model="tiny")
    loop = ContinuousDecodeLoop(eng, width=2, max_prompt=64, max_new=32)
    try:
        with loop._lock:
            t, out = _start(lambda: loop.stats)
            time.sleep(_WINDOW_S)
            # Mutate while still holding the lock: a snapshot taken outside
            # the lock (the old bug) has already run dict(self._stats) and
            # cannot see this key.
            loop._stats["race_probe"] = "set-under-lock"
        snap = _finish(t, out)
        assert snap["race_probe"] == "set-under-lock"
    finally:
        loop.stop()


# ---------------------------------------------------------------------------
# PagedKVPool.pool_bytes: reads self.kv (atomically swapped under self.lock)
# and must wait for the pool lock.
# ---------------------------------------------------------------------------


def test_pool_bytes_waits_for_the_pool_lock():
    from k_llms_tpu.engine.paging import PagedKVPool
    from k_llms_tpu.models import get_config

    pool = PagedKVPool(get_config("tiny"), total_pages=4, page_size=8)
    with pool.lock:
        t, out = _start(pool.pool_bytes)
        time.sleep(_WINDOW_S)
        # The old unlocked read has already returned by now.
        assert not out, "pool_bytes completed while the pool lock was held"
    size = _finish(t, out)
    assert size == pool.pool_bytes() > 0


# ---------------------------------------------------------------------------
# ReplicaSet: in_rotation / out_reason / last_probe_at are ReplicaHandle.lock
# state; _eligible, crop_texts and _probe must synchronize on it.
# ---------------------------------------------------------------------------


def _replica_set():
    return ReplicaSet(
        members=[FakeBackend(["ok"])], model="fake", hedge=False
    )


def test_eligible_reads_rotation_state_under_the_handle_lock():
    rs = _replica_set()
    handle = rs._handles[0]
    with handle.lock:
        t, out = _start(lambda: rs._eligible(frozenset()))
        time.sleep(_WINDOW_S)
        assert not out, "_eligible read in_rotation without the handle lock"
    eligible, reasons = _finish(t, out)
    assert len(eligible) == 1 and reasons == {}


def test_crop_texts_reads_rotation_state_under_the_handle_lock():
    rs = _replica_set()
    handle = rs._handles[0]
    with handle.lock:
        t, out = _start(lambda: rs.crop_texts(["hello world"], 1))
        time.sleep(_WINDOW_S)
        assert not out, "crop_texts read in_rotation without the handle lock"
    assert _finish(t, out)


def test_probe_stamps_last_probe_at_under_the_handle_lock():
    rs = _replica_set()
    handle = rs._handles[0]
    before = handle.last_probe_at
    with handle.lock:
        t, out = _start(lambda: rs._probe(handle))
        time.sleep(_WINDOW_S)
        # The very first statement of _probe is the stamp: if it ran without
        # the lock the timestamp has already moved.
        assert handle.last_probe_at == before, (
            "_probe wrote last_probe_at without the handle lock"
        )
    assert _finish(t, out) is True
    assert handle.last_probe_at > before


# ---------------------------------------------------------------------------
# LocalEngine prefix cache: the longest-common-prefix scan races the
# continuous loop's admission/store path unless it runs under _paged_mutex.
# ---------------------------------------------------------------------------


@pytest.mark.duration_budget(30)
def test_prefix_match_scan_runs_under_the_paged_mutex():
    from conftest import shared_engine

    eng = shared_engine(model="tiny")
    with eng._paged_mutex:
        t, out = _start(lambda: eng._match_prefix_entries([1, 2, 3], False))
        time.sleep(_WINDOW_S)
        assert not out, (
            "_match_prefix_entries scanned the cache without _paged_mutex"
        )
    assert _finish(t, out) == (None, 0)
