"""Fused paged-attention differentials: pallas-interpret == xla == dense.

The fused op (ops/paged_attention.py) has one contract and two
implementations. These tests pin the equivalence chain at both levels:

* op level — ``paged_decode_attention_pallas(interpret=True)`` against the
  XLA reference on synthetic pools with ragged lengths, phase-shifted
  (continuous-layout) gen tables, and trash-page garbage, across page sizes;
* step level — ``paged_verify_step`` against the dense ``verify_step`` on
  identical KV contents: BITWISE for the "xla" impl (the serving CPU path),
  greedy-token-exact + allclose for "pallas_interpret" (online softmax
  reorders float accumulation by design);

plus the selection contract: ``resolve_paged_attention_impl``'s CPU posture
("auto" -> xla, uncounted), the COUNTED fallback for an unsatisfiable
explicit "pallas", and the ``ops.paged_attn`` failpoint forcing the counted
fallback — the observability drill the README registry documents.

Widest page-size grids carry the ``slow`` tag; one mid-size representative
per class stays in tier-1.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k_llms_tpu.models import get_config
from k_llms_tpu.models.llama import KVCache, paged_verify_step, verify_step
from k_llms_tpu.ops.paged_attention import (
    PAGED_ATTENTION_IMPLS,
    note_paged_attn_dispatch,
    paged_attention_page_tables,
    paged_decode_attention_pallas,
    paged_decode_attention_xla,
    resolve_paged_attention_impl,
)
from k_llms_tpu.reliability import failpoints as fp
from k_llms_tpu.reliability.failpoints import FailSpec
from k_llms_tpu.utils.observability import KERNEL_EVENTS

CONFIG = get_config("tiny")
TRASH_PAGE = 0

# One fast mid-size representative; the widest/narrowest grids are slow.
PAGE_SIZES = [
    pytest.param(4, marks=pytest.mark.slow),
    8,
    pytest.param(16, marks=pytest.mark.slow),
]


def _params():
    from conftest import shared_params

    return shared_params(CONFIG, param_key=0)


# ---------------------------------------------------------------------------
# op level: synthetic pools, ragged tables, both layouts
# ---------------------------------------------------------------------------


def _build_tables(plens, G, ps, *, continuous):
    """Per-row block tables the way the engine lays them out.

    ``continuous=False`` is the coalesced-batch layout (gen rows start on
    fresh pages, phase 0); ``continuous=True`` is the continuous-loop layout
    where generated tokens continue the prompt's last partial page (phase =
    plen % ps). Unmapped positions point into the trash page, exactly like
    ``flat_slots`` does. Returns (prefix_idx [B, P], gen_idx [B, G],
    total_pages)."""
    B = len(plens)
    P = (max(int(p) for p in plens) + ps - 1) // ps * ps  # bucket width
    next_page = TRASH_PAGE + 1
    prefix_idx = np.empty((B, P), np.int32)
    gen_idx = np.empty((B, G), np.int32)
    for b, plen in enumerate(int(p) for p in plens):
        n_pp = -(-plen // ps)
        ppages = list(range(next_page, next_page + n_pp))
        next_page += n_pp
        for p in range(P):
            if p < plen:
                prefix_idx[b, p] = ppages[p // ps] * ps + p % ps
            else:
                prefix_idx[b, p] = TRASH_PAGE * ps + p % ps
        phase = plen % ps if continuous else 0
        n_gp = -(-(phase + G) // ps)
        if continuous and phase:
            gpages = [ppages[-1]] + list(range(next_page, next_page + n_gp - 1))
            next_page += n_gp - 1
        else:
            gpages = list(range(next_page, next_page + n_gp))
            next_page += n_gp
        for g in range(G):
            pos = phase + g
            gen_idx[b, g] = gpages[pos // ps] * ps + pos % ps
    return prefix_idx, gen_idx, next_page


@pytest.mark.parametrize("page_size", PAGE_SIZES)
@pytest.mark.parametrize("continuous", [False, True])
def test_op_pallas_interpret_matches_xla(page_size, continuous):
    """Ragged prompt/gen lengths, every page-boundary alignment class
    (mid-page, exact multiple, single-slot), trash garbage in the pool:
    the fused kernel must agree with the reference on both the coalesced
    (phase 0) and continuous (phase-shifted) gen layouts."""
    ps = page_size
    B, G = 4, 12
    QH, KVH, D = 4, 2, 16
    plens = np.array([1, ps, 2 * ps + 3, 2 * ps - 1], np.int32)
    wis = np.array([0, 3, G - 1, 7], np.int32)  # per-row generated counts

    prefix_idx, gen_idx, npages = _build_tables(
        plens, G, ps, continuous=continuous
    )
    if continuous:
        expect_phase = plens % ps
        _, _, phase = paged_attention_page_tables(
            jnp.asarray(prefix_idx), jnp.asarray(gen_idx), ps
        )
        np.testing.assert_array_equal(np.asarray(phase), expect_phase)

    keys = jax.random.split(jax.random.key(ps + int(continuous)), 5)
    pool_k = jax.random.normal(keys[0], (npages * ps, KVH, D), jnp.float32)
    pool_v = jax.random.normal(keys[1], (npages * ps, KVH, D), jnp.float32)
    q = jax.random.normal(keys[2], (B, 1, QH, D), jnp.float32)
    nk = jax.random.normal(keys[3], (B, 1, KVH, D), jnp.float32)
    nv = jax.random.normal(keys[4], (B, 1, KVH, D), jnp.float32)
    sm_scale = 1.0 / math.sqrt(D)

    s = np.arange(G)[None, None, :]
    key_mask = jnp.asarray(s <= wis[:, None, None])  # fresh column included
    c = np.arange(prefix_idx.shape[1])[None, None, :]
    prefix_mask = jnp.asarray(c < plens[:, None, None])

    out_x = paged_decode_attention_xla(
        q, pool_k, pool_v,
        jnp.asarray(prefix_idx), jnp.asarray(gen_idx),
        nk, nv, jnp.asarray(wis), key_mask, prefix_mask,
        sm_scale=sm_scale,
    )
    tables = paged_attention_page_tables(
        jnp.asarray(prefix_idx), jnp.asarray(gen_idx), ps
    )
    out_p = paged_decode_attention_pallas(
        q[:, 0], pool_k, pool_v, *tables, nk[:, 0], nv[:, 0],
        jnp.asarray(plens), jnp.asarray(wis),
        page_size=ps, sm_scale=sm_scale, interpret=True,
    )
    np.testing.assert_allclose(
        np.asarray(out_p), np.asarray(out_x[:, 0]), rtol=2e-5, atol=2e-6
    )


def test_op_shared_prefix_table_broadcasts():
    """An [R, P] request-major prefix table (the engine's shared-prefix
    layout) must produce the same kernel output as the explicitly repeated
    [B, P] per-row table."""
    ps = 8
    B, R, G = 4, 2, 8
    QH, KVH, D = 4, 2, 16
    plens_req = np.array([ps + 3, 2 * ps], np.int32)
    plens_row = np.repeat(plens_req, B // R)
    wis = np.array([0, 2, 5, 7], np.int32)

    prefix_req, _, npages0 = _build_tables(plens_req, 1, ps, continuous=False)
    prefix_row = np.repeat(prefix_req, B // R, axis=0)
    # Fresh gen pages per row, past the prompt pages.
    gen_idx = np.empty((B, G), np.int32)
    next_page = npages0
    for b in range(B):
        gpages = list(range(next_page, next_page + -(-G // ps)))
        next_page += len(gpages)
        for g in range(G):
            gen_idx[b, g] = gpages[g // ps] * ps + g % ps

    keys = jax.random.split(jax.random.key(42), 5)
    pool_k = jax.random.normal(keys[0], (next_page * ps, KVH, D), jnp.float32)
    pool_v = jax.random.normal(keys[1], (next_page * ps, KVH, D), jnp.float32)
    q = jax.random.normal(keys[2], (B, QH, D), jnp.float32)
    nk = jax.random.normal(keys[3], (B, KVH, D), jnp.float32)
    nv = jax.random.normal(keys[4], (B, KVH, D), jnp.float32)
    sm_scale = 1.0 / math.sqrt(D)

    outs = []
    for table in (prefix_req, prefix_row):
        tables = paged_attention_page_tables(
            jnp.asarray(table), jnp.asarray(gen_idx), ps
        )
        outs.append(
            np.asarray(
                paged_decode_attention_pallas(
                    q, pool_k, pool_v, *tables, nk, nv,
                    jnp.asarray(plens_row), jnp.asarray(wis),
                    page_size=ps, sm_scale=sm_scale, interpret=True,
                )
            )
        )
    np.testing.assert_array_equal(outs[0], outs[1])


# ---------------------------------------------------------------------------
# step level: paged_verify_step vs the dense verify_step oracle
# ---------------------------------------------------------------------------


def _step_case(ps, *, fork_gen_page=False, seed=0):
    """Build a dense world and a paged world holding IDENTICAL KV values.

    R=2 coalesced requests, 2 rows each, ragged prompt and generated
    lengths. Invalid dense slots and the paged trash/unused pages hold
    DIFFERENT garbage, so agreement proves the masking contract, not shared
    zeros. ``fork_gen_page``: duplicate one live row's gen page to a fresh
    physical page with identical contents and retarget the table — the CoW
    layout; physical placement must be invisible."""
    cfg = CONFIG
    L, KVH, D = cfg.num_layers, cfg.num_kv_heads, cfg.head_dim
    R, per, G = 2, 2, 10
    B = R * per
    plens_req = np.array([2 * ps + 3, ps], np.int32)
    P = 3 * ps
    lengths = np.array([0, 3, 5, G - 1], np.int32)

    rng = np.random.default_rng(seed)

    def randn(*shape):
        return rng.standard_normal(shape).astype(np.float32)

    # Dense caches: valid values shared with the pool, garbage elsewhere.
    pref_k, pref_v = randn(L, R, P, KVH, D), randn(L, R, P, KVH, D)
    gen_k, gen_v = randn(L, B, G, KVH, D), randn(L, B, G, KVH, D)

    # Paged pool: prompt pages per request, fresh gen pages per row.
    n_pp = [-(-int(p) // ps) for p in plens_req]
    gp = -(-G // ps)
    npages = 1 + sum(n_pp) + B * gp + 1  # trash + prompts + gens + fork spare
    flat = npages * ps
    pool_k, pool_v = randn(L, flat, KVH, D), randn(L, flat, KVH, D)

    next_page = TRASH_PAGE + 1
    prefix_idx = np.empty((R, P), np.int32)
    for r in range(R):
        ppages = list(range(next_page, next_page + n_pp[r]))
        next_page += n_pp[r]
        plen = int(plens_req[r])
        for p in range(P):
            if p < plen:
                slot = ppages[p // ps] * ps + p % ps
                pool_k[:, slot] = pref_k[:, r, p]
                pool_v[:, slot] = pref_v[:, r, p]
                prefix_idx[r, p] = slot
            else:
                prefix_idx[r, p] = TRASH_PAGE * ps + p % ps
    gen_idx = np.empty((B, G), np.int32)
    for b in range(B):
        gpages = list(range(next_page, next_page + gp))
        next_page += gp
        for g in range(G):
            slot = gpages[g // ps] * ps + g % ps
            gen_idx[b, g] = slot
            if g < lengths[b]:
                pool_k[:, slot] = gen_k[:, b, g]
                pool_v[:, slot] = gen_v[:, b, g]

    if fork_gen_page:
        # Copy row 3's first gen page to the spare physical page and retarget
        # its table — byte-for-byte the pool state after a CoW copy.
        src = int(gen_idx[3, 0]) // ps
        dst = next_page
        pool_k[:, dst * ps:(dst + 1) * ps] = pool_k[:, src * ps:(src + 1) * ps]
        pool_v[:, dst * ps:(dst + 1) * ps] = pool_v[:, src * ps:(src + 1) * ps]
        for g in range(min(ps, G)):
            gen_idx[3, g] = dst * ps + g

    tokens = rng.integers(0, cfg.vocab_size, (B, 1)).astype(np.int32)
    dense = dict(
        gen_cache=KVCache(k=jnp.asarray(gen_k), v=jnp.asarray(gen_v)),
        prefix=KVCache(k=jnp.asarray(pref_k), v=jnp.asarray(pref_v)),
    )
    paged = dict(
        pool_kv=KVCache(k=jnp.asarray(pool_k), v=jnp.asarray(pool_v)),
        prefix_idx=jnp.asarray(prefix_idx),
        gen_idx=jnp.asarray(gen_idx),
    )
    return (
        jnp.asarray(tokens),
        jnp.asarray(lengths),
        jnp.asarray(plens_req),
        dense,
        paged,
    )


@pytest.mark.parametrize("page_size", PAGE_SIZES)
def test_step_xla_bitwise_dense_pallas_greedy(page_size):
    params = _params()
    tokens, lengths, plens, dense, paged = _step_case(page_size)

    logits_d, cache_d = verify_step(
        CONFIG, params, tokens, lengths, plens,
        dense["gen_cache"], dense["prefix"],
    )
    logits_x, k_cols, v_cols = paged_verify_step(
        CONFIG, params, tokens, lengths, plens,
        paged["pool_kv"], paged["prefix_idx"], paged["gen_idx"],
        attn_impl="xla", page_size=page_size,
    )
    # The XLA impl IS the dense math over gathered pages: bitwise.
    np.testing.assert_array_equal(np.asarray(logits_x), np.asarray(logits_d))
    # The returned fresh columns must equal what dense wrote into its cache.
    wi = np.asarray(lengths)
    for b in range(tokens.shape[0]):
        np.testing.assert_array_equal(
            np.asarray(k_cols[:, b]), np.asarray(cache_d.k[:, b, wi[b]])
        )
        np.testing.assert_array_equal(
            np.asarray(v_cols[:, b]), np.asarray(cache_d.v[:, b, wi[b]])
        )

    logits_p, _, _ = paged_verify_step(
        CONFIG, params, tokens, lengths, plens,
        paged["pool_kv"], paged["prefix_idx"], paged["gen_idx"],
        attn_impl="pallas_interpret", page_size=page_size,
    )
    # Online softmax reorders float accumulation: greedy-token-exact is the
    # kernel's bar, with a tight numeric band behind it.
    np.testing.assert_array_equal(
        np.asarray(jnp.argmax(logits_p, -1)), np.asarray(jnp.argmax(logits_d, -1))
    )
    np.testing.assert_allclose(
        np.asarray(logits_p), np.asarray(logits_d), rtol=3e-5, atol=3e-5
    )


def test_step_cow_forked_table_is_invisible():
    """A gen page forked CoW-style (same bytes, different physical page) must
    leave both impls' outputs unchanged: bitwise for xla vs dense, bitwise
    for pallas forked-vs-shared (identical shapes and op order)."""
    ps = 8
    params = _params()
    tokens, lengths, plens, dense, shared = _step_case(ps)
    _, _, _, _, forked = _step_case(ps, fork_gen_page=True)

    logits_d, _ = verify_step(
        CONFIG, params, tokens, lengths, plens,
        dense["gen_cache"], dense["prefix"],
    )
    logits_f, _, _ = paged_verify_step(
        CONFIG, params, tokens, lengths, plens,
        forked["pool_kv"], forked["prefix_idx"], forked["gen_idx"],
        attn_impl="xla", page_size=ps,
    )
    np.testing.assert_array_equal(np.asarray(logits_f), np.asarray(logits_d))

    outs = []
    for world in (shared, forked):
        logits_p, _, _ = paged_verify_step(
            CONFIG, params, tokens, lengths, plens,
            world["pool_kv"], world["prefix_idx"], world["gen_idx"],
            attn_impl="pallas_interpret", page_size=ps,
        )
        outs.append(np.asarray(logits_p))
    np.testing.assert_array_equal(outs[0], outs[1])


# ---------------------------------------------------------------------------
# selection, counters, and the ops.paged_attn failpoint
# ---------------------------------------------------------------------------


def _snap():
    return dict(KERNEL_EVENTS.snapshot())


def _delta(before, after, key):
    return after.get(key, 0) - before.get(key, 0)


def test_resolve_cpu_posture_counts_only_unsatisfied_pallas():
    assert jax.default_backend() != "tpu"
    before = _snap()
    assert resolve_paged_attention_impl("auto") == "xla"
    assert resolve_paged_attention_impl("xla") == "xla"
    mid = _snap()
    # "auto" -> xla off-TPU is the documented CPU posture, NOT a fallback.
    assert all(_delta(before, mid, k) == 0 for k in mid if "fallback" in k)
    # An explicit "pallas" that cannot run is a COUNTED degradation, keyed
    # by reason: off-TPU with a supported config, the reason is the platform.
    assert resolve_paged_attention_impl("pallas") == "xla"
    assert _delta(mid, _snap(), "kernel.paged_attn_fallback.platform") == 1
    with pytest.raises(ValueError):
        resolve_paged_attention_impl("flash")
    assert set(PAGED_ATTENTION_IMPLS) == {"auto", "pallas", "xla"}


def test_resolve_names_the_unsupported_feature_in_the_fallback_key():
    """Config-driven fallbacks are distinguishable from platform ones on
    /metrics: softcap and sliding-window models record their own reason
    suffix, and the config reason wins over the platform reason."""
    import dataclasses

    before = _snap()
    softcap = dataclasses.replace(CONFIG, attn_softcap=30.0)
    assert resolve_paged_attention_impl("pallas", config=softcap) == "xla"
    sliding = dataclasses.replace(CONFIG, sliding_window=128)
    assert resolve_paged_attention_impl("pallas", config=sliding) == "xla"
    after = _snap()
    assert _delta(before, after, "kernel.paged_attn_fallback.softcap") == 1
    assert _delta(before, after, "kernel.paged_attn_fallback.sliding_window") == 1
    assert _delta(before, after, "kernel.paged_attn_fallback.platform") == 0


def test_ops_paged_attn_failpoint_forces_counted_fallback():
    """ops.paged_attn=fallback:2 — the registry drill: the next two launch
    resolutions take the counted XLA fallback regardless of the request, then
    the spec exhausts and resolution reverts to the normal posture."""
    before = _snap()
    with fp.failpoints({"ops.paged_attn": FailSpec(action="fallback", times=2)}):
        assert resolve_paged_attention_impl("auto") == "xla"  # fired (1)
        assert resolve_paged_attention_impl("auto") == "xla"  # fired (2)
        assert resolve_paged_attention_impl("auto") == "xla"  # exhausted
    after = _snap()
    assert _delta(before, after, "kernel.paged_attn_fallback.failpoint") == 2


def test_ops_paged_attn_env_syntax_parses():
    fp.configure_from_env("ops.paged_attn=fallback:1")
    try:
        before = _snap()
        assert resolve_paged_attention_impl("auto") == "xla"
        assert _delta(before, _snap(), "kernel.paged_attn_fallback.failpoint") == 1
    finally:
        fp.clear()


def test_dispatch_counters_and_metrics_group():
    before = _snap()
    note_paged_attn_dispatch("pallas")
    note_paged_attn_dispatch("pallas_interpret")  # counts as the kernel path
    note_paged_attn_dispatch("xla", 3)
    after = _snap()
    assert _delta(before, after, "kernel.paged_attn_pallas_dispatch") == 2
    assert _delta(before, after, "kernel.paged_attn_xla_dispatch") == 3

    # The group is wired into /metrics exporting (kllms_kernel_events_total).
    from k_llms_tpu.serving.app import _COUNTER_GROUPS

    assert ("kernel", "KERNEL_EVENTS") in _COUNTER_GROUPS
