"""Int8 weight-only quantization: numerics, end-to-end decode, sharded mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k_llms_tpu.engine.engine import LocalEngine
from k_llms_tpu.engine.tokenizer import ByteTokenizer
from k_llms_tpu.models import get_config, init_params
from k_llms_tpu.models.llama import forward
from k_llms_tpu.models.quant import (
    QTensor,
    qdot,
    quantize_params,
    quantize_weight,
    quantized_param_specs,
)
from k_llms_tpu.parallel.mesh import make_mesh
from k_llms_tpu.parallel.sharding import param_specs


def test_quantize_weight_roundtrip_error():
    w = jax.random.normal(jax.random.key(0), (64, 32), jnp.float32)
    qt = quantize_weight(w)
    assert qt.q.dtype == jnp.int8
    assert qt.scale.shape == (1, 32)
    deq = qt.q.astype(jnp.float32) * qt.scale
    # Per-channel symmetric int8: max error is half a quantization step.
    err = jnp.max(jnp.abs(deq - w) / qt.scale[0])
    assert float(err) <= 0.5 + 1e-6


def test_qdot_matches_dense_within_tolerance():
    key = jax.random.key(1)
    x = jax.random.normal(key, (4, 64), jnp.float32)
    w = jax.random.normal(jax.random.key(2), (64, 32), jnp.float32)
    exact = x @ w
    approx = qdot(x, quantize_weight(w))
    rel = jnp.linalg.norm(approx - exact) / jnp.linalg.norm(exact)
    assert float(rel) < 0.01  # int8 per-channel keeps ~2 decimal digits
    # Plain arrays pass through unchanged.
    np.testing.assert_allclose(np.asarray(qdot(x, w)), np.asarray(exact))


def test_stacked_weight_quantization_shapes():
    w = jax.random.normal(jax.random.key(3), (4, 16, 8), jnp.float32)  # [L, in, out]
    qt = quantize_weight(w)
    assert qt.q.shape == (4, 16, 8)
    assert qt.scale.shape == (4, 1, 8)


def test_quantized_forward_close_to_dense():
    config = get_config("tiny")
    params = init_params(config, jax.random.key(0))
    qparams = quantize_params(params)
    # Quantized tree: matmuls are QTensor, embed/norms untouched.
    assert isinstance(qparams["layers"]["wq"], QTensor)
    assert isinstance(qparams["lm_head"], QTensor)
    assert not isinstance(qparams["embed"], QTensor)

    tokens = jnp.array([[5, 6, 7, 8, 9, 10, 11, 12]], jnp.int32)
    mask = jnp.ones_like(tokens)
    logits_dense, _ = forward(config, params, tokens, mask)
    logits_q, _ = forward(config, qparams, tokens, mask)
    # Logits drift but argmax ranking stays overwhelmingly stable on random init.
    probs_dense = jax.nn.softmax(logits_dense, -1)
    probs_q = jax.nn.softmax(logits_q, -1)
    tv = 0.5 * jnp.abs(probs_dense - probs_q).sum(-1).mean()
    assert float(tv) < 0.05


def test_engine_generate_int8():
    engine = LocalEngine("tiny", use_mesh=False, quantize=True)
    tok = ByteTokenizer()
    ids = tok.apply_chat_template([{"role": "user", "content": "quantized decode"}])
    result = engine.generate(ids, n=4, max_new_tokens=8, temperature=1.0, seed=0)
    assert result.tokens.shape == (4, 8)
    assert result.logprobs.shape == (4, 8)
    # Reproducible under the same seed.
    again = engine.generate(ids, n=4, max_new_tokens=8, temperature=1.0, seed=0)
    np.testing.assert_array_equal(result.tokens, again.tokens)


def test_engine_generate_int8_sharded():
    if len(jax.devices()) < 4:
        pytest.skip("needs the 8-device CPU mesh")
    mesh = make_mesh(2, 2, jax.devices()[:4])
    engine = LocalEngine("tiny", mesh=mesh, quantize=True)
    tok = ByteTokenizer()
    ids = tok.apply_chat_template([{"role": "user", "content": "sharded int8"}])
    result = engine.generate(ids, n=4, max_new_tokens=6, temperature=0.7, seed=3)
    assert result.tokens.shape == (4, 6)


def test_quantized_param_specs_structure():
    config = get_config("tiny")
    specs = param_specs(config)
    qspecs = quantized_param_specs(specs)
    assert isinstance(qspecs["layers"]["wq"], QTensor)
    # Payload keeps the weight spec; scale drops the (size-1) contraction axis.
    assert qspecs["layers"]["wq"].q == specs["layers"]["wq"]
    assert qspecs["layers"]["wo"].scale[-2] is None
    assert qspecs["final_norm"] == specs["final_norm"]


def test_backend_config_quantization():
    from k_llms_tpu.backends.tpu import TpuBackend

    backend = TpuBackend(model="tiny", quantization="int8")
    assert backend.engine.quantized
    r = backend.chat_completion(
        __import__("k_llms_tpu.backends.base", fromlist=["ChatRequest"]).ChatRequest(
            messages=[{"role": "user", "content": "hi"}], model="tiny", n=2, seed=1
        )
    )
    assert len(r.choices) == 2

    with pytest.raises(ValueError, match="Unsupported quantization"):
        TpuBackend(model="tiny", quantization="fp8")


def test_prequantized_checkpoint_with_quantize_unset_on_mesh():
    """A PRE-quantized params tree passed with quantize=False must be detected
    and routed through the quantized spec machinery (ADVICE r3): the bf16
    pspecs tree doesn't match QTensor leaves, so the naive device_put would
    die in an opaque pytree error."""
    if len(jax.devices()) < 4:
        pytest.skip("needs the 8-device CPU mesh")
    config = get_config("tiny")
    params = init_params(config, jax.random.key(0))
    qparams = quantize_params(params)
    mesh = make_mesh(2, 2, jax.devices()[:4])
    engine = LocalEngine(config, params=qparams, mesh=mesh)  # quantize unset
    assert engine.quantized == "int8"
    tok = ByteTokenizer()
    ids = tok.apply_chat_template([{"role": "user", "content": "prequantized"}])
    result = engine.generate(ids, n=4, max_new_tokens=4, temperature=0.5, seed=2)
    assert result.tokens.shape == (4, 4)
    # Same tree served single-chip with quantize unset must agree with the
    # explicit-flag construction (both route through the same machinery).
    explicit = LocalEngine(config, params=qparams, mesh=mesh, quantize="int8")
    r2 = explicit.generate(ids, n=4, max_new_tokens=4, temperature=0.5, seed=2)
    np.testing.assert_array_equal(result.tokens, r2.tokens)
