"""Engine tests: decode correctness, reproducibility, sharding, embeddings."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k_llms_tpu.engine import ByteTokenizer, LocalEngine
from k_llms_tpu.models import get_config, init_params
from k_llms_tpu.models.llama import decode_step, forward, init_cache, prefill
from k_llms_tpu.ops.sampling import sample_logits
from k_llms_tpu.parallel.mesh import auto_mesh, make_mesh


@pytest.fixture(scope="module")
def engine():
    return LocalEngine("tiny")


@pytest.fixture(scope="module")
def tok():
    return ByteTokenizer()


def test_mesh_shape():
    mesh = auto_mesh()
    assert mesh.shape["data"] == 8
    mesh2 = auto_mesh(model_parallel=2)
    assert mesh2.shape == {"data": 4, "model": 2}
    with pytest.raises(ValueError):
        make_mesh(4, 4)


def test_decode_matches_forward():
    """Step-by-step decode over the shared prefix must reproduce the full
    causal forward — the core correctness property of the KV-cache path."""
    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.key(0))
    S = 16
    tokens = jax.random.randint(jax.random.key(1), (1, S), 0, cfg.vocab_size)
    prompt_len = jnp.int32(10)

    pl_logits, prefix = prefill(cfg, params, tokens, prompt_len)
    full_logits, _ = forward(
        cfg, params, tokens, (jnp.arange(S)[None, :] < prompt_len).astype(jnp.int32)
    )
    np.testing.assert_allclose(pl_logits[0], full_logits[0, 9], rtol=1e-5, atol=1e-5)

    n = 3
    gen_cache = init_cache(cfg, n, 4)
    for step in (0, 1):
        tk = jnp.broadcast_to(tokens[0, 10 + step], (n,))
        logits, gen_cache = decode_step(
            cfg, params, tk, jnp.int32(step), prompt_len, gen_cache, prefix
        )
        full, _ = forward(
            cfg,
            params,
            tokens,
            (jnp.arange(S)[None, :] < 11 + step).astype(jnp.int32),
        )
        np.testing.assert_allclose(logits[0], full[0, 10 + step], rtol=1e-5, atol=1e-5)


def test_generate_contract(engine, tok):
    ids = tok.apply_chat_template([{"role": "user", "content": "hello"}])
    r = engine.generate(ids, n=4, max_new_tokens=12, temperature=1.0, seed=7, eos_ids=tok.stop_ids)
    assert r.tokens.shape == (4, 12)
    assert r.logprobs.shape == (4, 12)
    assert all(f in ("stop", "length") for f in r.finish_reasons)
    assert (r.lengths >= 1).all() and (r.lengths <= 12).all()
    # logprobs are real log-probabilities
    active = r.logprobs[r.tokens != engine.config.pad_token_id]
    assert (active <= 0).all()


def test_generate_seed_reproducible(engine, tok):
    ids = tok.encode("The answer is")
    a = engine.generate(ids, n=3, max_new_tokens=8, seed=123, temperature=0.9)
    b = engine.generate(ids, n=3, max_new_tokens=8, seed=123, temperature=0.9)
    c = engine.generate(ids, n=3, max_new_tokens=8, seed=124, temperature=0.9)
    assert (a.tokens == b.tokens).all()
    assert not (a.tokens == c.tokens).all()


def test_generate_greedy_samples_identical(engine, tok):
    ids = tok.encode("abc")
    r = engine.generate(ids, n=3, max_new_tokens=6, temperature=0.0, seed=1)
    assert (r.tokens[0] == r.tokens[1]).all()
    assert (r.tokens[1] == r.tokens[2]).all()


def test_generate_n_not_divisible_by_mesh(engine, tok):
    # data axis is 8; n=5 must round-trip correctly
    r = engine.generate(tok.encode("xy"), n=5, max_new_tokens=4, seed=3)
    assert r.tokens.shape[0] == 5


def test_embed_tokens(engine, tok):
    embs = engine.embed_tokens([tok.encode("hello"), tok.encode("hello"), tok.encode("bye")])
    assert embs.shape == (3, engine.config.hidden_size)
    np.testing.assert_allclose(embs[0], embs[1], rtol=1e-5)
    assert not np.allclose(embs[0], embs[2])


def test_sampling_top_p_masks_tail():
    logits = jnp.log(jnp.array([[0.6, 0.3, 0.05, 0.05]], jnp.float32))
    toks = set()
    for s in range(40):
        t, _ = sample_logits(logits, jax.random.key(s), temperature=1.0, top_p=0.7)
        toks.add(int(t[0]))
    assert toks <= {0, 1}


def test_sampling_top_k():
    logits = jnp.log(jnp.array([[0.4, 0.3, 0.2, 0.1]], jnp.float32))
    toks = set()
    for s in range(40):
        t, _ = sample_logits(logits, jax.random.key(s), temperature=1.0, top_k=2)
        toks.add(int(t[0]))
    assert toks <= {0, 1}


def test_sampling_logprob_is_model_distribution():
    logits = jnp.array([[1.0, 2.0, 0.5, -1.0]], jnp.float32)
    t, lp = sample_logits(logits, jax.random.key(0), temperature=0.0)
    expected = jax.nn.log_softmax(logits)[0, t[0]]
    np.testing.assert_allclose(lp[0], expected, rtol=1e-6)


# ---------------------------------------------------------------------------
# Coalesced multi-request decode (generate_many)
# ---------------------------------------------------------------------------

def test_generate_many_matches_solo(engine, tok):
    """R coalesced requests must reproduce each request's SOLO results: same
    tokens (per-request seed streams are batch-composition-independent) across
    different prompt lengths/buckets and different n."""
    from k_llms_tpu.engine.engine import GenRequestSpec

    prompts = [
        tok.encode("The answer is"),
        tok.encode("A much longer prompt that lands in a different compile bucket: " * 3),
        tok.encode("xy"),
    ]
    ns = [3, 2, 5]
    solo = [
        engine.generate(p, n=n, max_new_tokens=8, seed=40 + i, temperature=0.9)
        for i, (p, n) in enumerate(zip(prompts, ns))
    ]
    many = engine.generate_many(
        [GenRequestSpec(p, n, 40 + i) for i, (p, n) in enumerate(zip(prompts, ns))],
        max_new_tokens=8,
        temperature=0.9,
    )
    assert len(many) == 3
    for s, m in zip(solo, many):
        assert m.tokens.shape == s.tokens.shape
        assert (s.tokens == m.tokens).all()
        np.testing.assert_allclose(s.logprobs, m.logprobs, rtol=1e-4, atol=1e-5)
        assert s.finish_reasons == m.finish_reasons
        assert s.prompt_len == m.prompt_len


def test_generate_many_greedy(engine, tok):
    from k_llms_tpu.engine.engine import GenRequestSpec

    prompts = [tok.encode("abc"), tok.encode("wxyz")]
    many = engine.generate_many(
        [GenRequestSpec(p, 2, None) for p in prompts],
        max_new_tokens=6,
        temperature=0.0,
    )
    solo = [engine.generate(p, n=1, max_new_tokens=6, temperature=0.0) for p in prompts]
    for s, m in zip(solo, many):
        # Greedy: every sample of the coalesced request equals the solo sample.
        assert (m.tokens[0] == s.tokens[0]).all()
        assert (m.tokens[1] == s.tokens[0]).all()


def test_generate_many_single_item_delegates(engine, tok):
    from k_llms_tpu.engine.engine import GenRequestSpec

    ids = tok.encode("The answer is")
    solo = engine.generate(ids, n=3, max_new_tokens=8, seed=123, temperature=0.9)
    [many] = engine.generate_many(
        [GenRequestSpec(ids, 3, 123)], max_new_tokens=8, temperature=0.9
    )
    assert (solo.tokens == many.tokens).all()


def test_flash_decode_matches_xla(tok):
    """The Pallas shared-prefix decode path reproduces the XLA decode path
    (greedy, same params)."""
    from k_llms_tpu.models import get_config, init_params

    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.key(0))
    e_xla = LocalEngine(
        cfg.with_(decode_attention_impl="xla"), params=params, use_mesh=False
    )
    e_flash = LocalEngine(
        cfg.with_(decode_attention_impl="flash"), params=params, use_mesh=False
    )
    ids = tok.encode("hello flash decode path")
    a = e_xla.generate(ids, n=8, max_new_tokens=8, temperature=0.0)
    b = e_flash.generate(ids, n=8, max_new_tokens=8, temperature=0.0)
    assert (a.tokens == b.tokens).all()
    np.testing.assert_allclose(a.logprobs, b.logprobs, rtol=5e-4, atol=5e-4)


def test_generate_top_logprobs(engine, tok):
    """Top-k capture: correct shapes, ranked order, and the chosen token's
    logprob appears among the top-k when k is large enough."""
    ids = tok.encode("top logprob capture")
    r = engine.generate(ids, n=2, max_new_tokens=6, temperature=0.9, seed=5, top_logprobs=4)
    assert r.top_tokens.shape == (2, 6, 4)
    assert r.top_logprobs.shape == (2, 6, 4)
    assert (np.diff(r.top_logprobs, axis=-1) <= 1e-6).all()  # desc per step
    # chosen-token logprob never exceeds the step's best alternative
    for i in range(2):
        for j in range(int(r.lengths[i])):
            assert r.logprobs[i, j] <= r.top_logprobs[i, j, 0] + 1e-5

    r2 = engine.generate(ids, n=2, max_new_tokens=6, temperature=0.9, seed=5)
    assert r2.top_tokens is None
    # capture must not perturb sampling
    assert (r2.tokens == r.tokens).all()


def test_top_p_bisection_matches_sort_reference():
    """The bisection top-p mask is EXACTLY the sort-based reference's kept set
    (smallest prefix with cumulative mass >= top_p, boundary + ties in)."""
    from k_llms_tpu.ops.sampling import sample_logits

    def sort_reference_kept(x, top_p):
        sorted_logits = jnp.sort(x, axis=-1)[:, ::-1]
        sorted_probs = jax.nn.softmax(sorted_logits, axis=-1)
        cumulative = jnp.cumsum(sorted_probs, axis=-1)
        keep_sorted = (cumulative - sorted_probs) < top_p
        threshold = jnp.min(
            jnp.where(keep_sorted, sorted_logits, jnp.inf), axis=-1, keepdims=True
        )
        return np.asarray(x >= threshold)

    rng = np.random.default_rng(11)
    for kind in ("normal", "peaked", "flat", "ties"):
        x = rng.standard_normal((4, 512)).astype(np.float32)
        if kind == "peaked":
            x[:, 0] += 20
        if kind == "flat":
            x = x * 1e-3
        if kind == "ties":
            x = np.round(x * 2) / 2
        for tp in (0.5, 0.9, 0.95):
            # Recover the kept set by sampling many draws can't prove equality;
            # instead compare masked supports via the sampler's internals:
            # temperature=1 so sampling_logits == x.
            tokens = jax.vmap(
                lambda key: sample_logits(jnp.asarray(x), key, temperature=1.0, top_p=tp)[0]
            )(jax.random.split(jax.random.key(0), 64))
            kept = sort_reference_kept(jnp.asarray(x), tp)
            # every sampled token must come from the reference kept set
            for row in range(x.shape[0]):
                assert set(np.asarray(tokens)[:, row].tolist()) <= set(
                    np.flatnonzero(kept[row]).tolist()
                )


def test_frequency_penalty_blocks_repeats(engine, tok):
    """An extreme frequency penalty makes greedy decode never repeat a token
    within a sample (the defining property of the OpenAI formula)."""
    ids = tok.encode("aaa")
    r = engine.generate(
        ids, n=2, max_new_tokens=10, temperature=0.0, frequency_penalty=1000.0
    )
    for i in range(2):
        emitted = r.tokens[i][: int(r.lengths[i])].tolist()
        assert len(emitted) == len(set(emitted))  # no repeats

    # Without the penalty, greedy output differs (and is allowed to repeat).
    r0 = engine.generate(ids, n=2, max_new_tokens=10, temperature=0.0)
    assert not (r0.tokens == r.tokens).all()


def test_presence_penalty_blocks_repeats(engine, tok):
    ids = tok.encode("xyz")
    b = engine.generate(
        ids, n=2, max_new_tokens=8, temperature=0.0, presence_penalty=1000.0
    )
    for i in range(2):
        emitted = b.tokens[i][: int(b.lengths[i])].tolist()
        assert len(emitted) == len(set(emitted))
    # Reported logprobs stay the MODEL distribution's (penalty shapes sampling
    # only): every reported logprob is a valid log-probability.
    assert (b.logprobs[b.tokens != engine.config.pad_token_id] <= 0).all()


@pytest.mark.parametrize("plen", [31, 32, 33, 63, 64, 65, 1])
def test_generate_at_bucket_boundaries(plen):
    """Prompt lengths straddling the power-of-two compile buckets must all
    decode correctly (off-by-one in bucket padding/masking is the classic
    failure here), and results must be invariant to the bucket chosen."""
    from k_llms_tpu.engine.engine import LocalEngine
    from k_llms_tpu.models import get_config, init_params

    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.key(0))
    eng = LocalEngine(cfg, params=params, use_mesh=False)
    prompt = [5 + (i % 90) for i in range(plen)]
    r = eng.generate(prompt, n=2, max_new_tokens=3, temperature=0.0, seed=2)
    assert r.tokens.shape == (2, 3)
    assert r.prompt_len == plen
    # Greedy output must not depend on the padding amount: re-run with the
    # same prompt embedded in a LARGER bucket by extending max_seq_len rules
    # via an explicit longer prompt prefix trim — i.e., the same tokens must
    # give the same result when generated twice (determinism across calls).
    r2 = eng.generate(prompt, n=2, max_new_tokens=3, temperature=0.0, seed=2)
    np.testing.assert_array_equal(r.tokens, r2.tokens)
