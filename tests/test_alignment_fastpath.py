"""The vectorized flat-dict similarity-matrix fast path must be bit-equal to
the generic pairwise loop it replaces (ElementTable.__init__), across scalar
types, missing keys, skip-pattern keys, NaN, and bool/int aliasing."""

import random

import numpy as np

from k_llms_tpu.consensus.alignment import ElementTable, _flat_dict_sim_matrix
from k_llms_tpu.consensus.similarity import SimilarityScorer

WORDS = [
    "alpha", "beta widget", "Industrial widget, stainless", "", "x",
    "Express shipping and handling",
]


def _rand_dict(rng):
    d = {}
    for k in ("description", "qty", "price", "ok", "reasoning___w", "extra"):
        if rng.random() < 0.75:
            d[k] = rng.choice(
                [
                    rng.choice(WORDS),
                    rng.randint(0, 20),
                    round(rng.uniform(0, 100), 2),
                    rng.random() < 0.5,
                    None,
                    float("nan"),
                ]
            )
    return d or {"description": "fallback"}


def test_fast_matrix_bit_equals_pairwise_loop():
    rng = random.Random(0)
    checked = 0
    for trial in range(60):
        lists = [
            [_rand_dict(rng) for _ in range(rng.randint(0, 4))]
            for _ in range(rng.randint(2, 8))
        ]
        flat = [x for lst in lists for x in lst]
        if len(flat) < 3:
            continue
        fast = _flat_dict_sim_matrix(flat, SimilarityScorer.levenshtein().generic)
        scorer = SimilarityScorer.levenshtein()
        n = len(flat)
        slow = np.ones((n, n))
        for a in range(n):
            for b in range(a + 1, n):
                slow[a, b] = slow[b, a] = scorer.generic(flat[a], flat[b])
        if fast is None:
            continue  # a guard fired (e.g. empty dict) — the loop serves it
        assert np.array_equal(fast, slow), f"trial {trial}"
        checked += 1
    assert checked >= 30  # the fast path must actually engage


def test_fast_path_falls_back_on_nested_and_foreign():
    scorer = SimilarityScorer.levenshtein()
    nested = [{"a": [1, 2]}, {"a": [1]}, {"a": [2]}]
    assert _flat_dict_sim_matrix(nested, scorer.generic) is None
    scalars = ["x", "y", "z"]
    assert _flat_dict_sim_matrix(scalars, scorer.generic) is None
    flat = [{"a": 1}, {"a": 2}, {"a": 3}]
    assert _flat_dict_sim_matrix(flat, lambda a, b: 0.5) is None  # foreign fn

    # and the table still produces the right matrix through the fallback
    table = ElementTable(scorer.generic, [nested])
    assert table.sim.shape == (3, 3)


def test_fast_path_engages_inside_element_table():
    scorer = SimilarityScorer.levenshtein()
    rows = [{"a": "x", "q": i} for i in range(4)]
    table = ElementTable(scorer.generic, [rows[:2], rows[2:]])
    ref = _flat_dict_sim_matrix(rows, SimilarityScorer.levenshtein().generic)
    assert ref is not None
    np.testing.assert_array_equal(table.sim, ref)
