"""Mixed-traffic chaos soak for the self-healing continuous loop (PR 13).

The ISSUE's acceptance drill: crash the worker thread, hang a decode step
AND a prefill chunk, poison logits, and leak KV pages — under concurrent
streaming, grammar-constrained, and plain n-way traffic on the
continuous-batching backend (chunked prefill on, so long admissions ingest
between decode steps while the faults land).
Every request must resolve (success or typed error, never a hung future),
rebuilds must stay bounded, the page pool must end conserved, the scheduler
must end READY, and both the lock-order graph and the Eraser-style lockset
sanitizer must come out clean under KLLMS_LOCKCHECK=1 + KLLMS_RACECHECK=1.
"""

import threading
import time

import pytest
from pydantic import BaseModel

from k_llms_tpu import KLLMs
from k_llms_tpu.analysis import lockcheck
from k_llms_tpu.reliability import failpoints as fp
from k_llms_tpu.reliability.failpoints import FailSpec
from k_llms_tpu.types.wire import KLLMsError
from k_llms_tpu.utils.observability import RECOVERY_EVENTS


class Record(BaseModel):
    name: str
    count: int


def _backend():
    import jax
    from conftest import shared_engine

    from k_llms_tpu.backends.tpu import TpuBackend

    engine = (
        shared_engine("tiny", mesh_shape=(8, 1)) if len(jax.devices()) == 8 else None
    )
    # Step budget 8 s: far under the 30 s injected hang (the loop watchdog
    # MUST fire) but roomy enough that the post-rebuild replay's first step —
    # a full recompile of the loop's jit closures — completes inside it.
    return TpuBackend(
        model="tiny", max_new_tokens=8, engine=engine,
        continuous_batching=True, continuous_width=4,
        continuous_max_prompt=128, continuous_max_new=64,
        watchdog_base_s=0.5, watchdog_per_token_s=0.01,
        watchdog_multiplier=1.0, watchdog_min_budget_s=8.0,
        watchdog_max_budget_s=8.0, max_rebuilds=4,
        # Chunked prefill ON (PR 18): prompts past 32 tokens ingest chunk by
        # chunk, so the soak also drills the PREFILLING fault domain.
        prefill_chunk_tokens=32,
    )


@pytest.mark.slow
@pytest.mark.duration_budget(240)
def test_continuous_chaos_soak_mixed_traffic(monkeypatch):
    """continuous.worker=crash, then continuous.step=hang + engine.logits=nan
    under mixed stream/grammar/non-stream concurrency, then engine.pages=leak
    — the full fault-domain tour on one live backend."""
    monkeypatch.setenv("KLLMS_LOCKCHECK", "1")
    monkeypatch.setenv("KLLMS_RACECHECK", "1")
    lockcheck.reset_state()
    backend = _backend()
    client = KLLMs(backend=backend, model="tiny")
    results = {}
    lock = threading.Lock()

    def worker(i):
        # One wave-2 lane carries a long prompt so a multi-chunk PREFILLING
        # admission is in flight while the faults land.
        content = ("chaos prefill " * 8) if i == 4 else f"chaos {i}"
        msgs = [{"role": "user", "content": content}]
        try:
            if i % 3 == 0:
                # Streaming lane: drain every chunk; a quarantined sample
                # surfaces as a terminal typed sample_error chunk, not a hang.
                with client.chat.completions.create(
                    messages=msgs, model="tiny", n=2, seed=200 + i,
                    temperature=0.8, stream=True,
                ) as stream:
                    chunks = list(stream)
                with lock:
                    results[i] = ("ok", chunks)
            elif i % 3 == 1:
                # Grammar lane: schema-constrained rows ride the same loop;
                # truncation or degraded samples leave parsed=None, never an
                # untyped error.
                pc = client.chat.completions.parse(
                    messages=msgs, response_format=Record, model="tiny",
                    n=2, seed=200 + i, temperature=0.8,
                )
                with lock:
                    results[i] = ("ok", pc)
            else:
                cc = client.chat.completions.create(
                    messages=msgs, model="tiny", n=2 if i % 2 else 4,
                    seed=200 + i, temperature=0.8,
                )
                with lock:
                    results[i] = ("ok", cc)
        except KLLMsError as e:
            with lock:
                results[i] = ("typed", e)

    # Wave 1 — worker crash under traffic. The crash kills the loop thread
    # while both requests are queued/in flight: each must resolve promptly
    # (typed BackendUnavailableError, or ok if the dispatch retry lands on
    # the restarted loop), never hang. Kept to two requests so the typed
    # failures cannot trip the circuit breaker (threshold 5).
    crashes = RECOVERY_EVENTS.snapshot().get("continuous.worker_crashes", 0)
    with fp.failpoints(
        {"continuous.worker": FailSpec(action="crash", times=1)}
    ):
        wave1 = [threading.Thread(target=worker, args=(i,)) for i in (0, 1)]
        for t in wave1:
            t.start()
        for t in wave1:
            t.join(timeout=120.0)
        assert not any(t.is_alive() for t in wave1)
    assert RECOVERY_EVENTS.snapshot()["continuous.worker_crashes"] > crashes

    # Wave 2 — hung step + hung prefill chunk + NaN poison while seven mixed
    # requests ride the restarted loop: the watchdog rebuilds and replays
    # through both hangs (the chunked admission re-ingests from cursor 0),
    # quarantine absorbs the poisoned rows, and traffic keeps flowing.
    with fp.failpoints(
        {
            "continuous.step": FailSpec(action="hang", times=1, delay=30.0),
            "continuous.prefill": FailSpec(action="hang", times=1, delay=30.0),
            "engine.logits": FailSpec(action="nan", kill=1, seed=13, times=2),
        }
    ):
        wave2 = [threading.Thread(target=worker, args=(i,)) for i in range(2, 9)]
        for t in wave2:
            t.start()
        for t in wave2:
            t.join(timeout=180.0)
        # The headline invariant: zero hung futures / zero hung clients.
        assert not any(t.is_alive() for t in wave2)
    assert sorted(results) == list(range(9))
    oks = [k for k, r in results.items() if r[0] == "ok" and k >= 2]
    assert oks, "wave-2 requests must ride through the recovery"

    # Wave 3 — page leak (paged loop only): a retiring slot drops a page from
    # the free list. The next stats audit QUARANTINES the pool (reported as
    # data, not a raise), the worker rebuilds + replays, and subsequent
    # audits come back conserved.
    if "pages" in backend.health()["continuous"]:
        with fp.failpoints(
            {"engine.pages": FailSpec(action="leak", kill=1, times=1)}
        ):
            client.chat.completions.create(
                messages=[{"role": "user", "content": "leak"}], model="tiny",
                n=2, seed=303, temperature=0.8,
            )
        healed = False
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            pages = backend.health()["continuous"].get("pages", {})
            if "quarantined" not in pages and pages.get("loop_refs") == 0:
                healed = True
                break
            time.sleep(0.2)
        assert healed, "page pool must heal back to a conserved snapshot"

    cont = backend.health()["continuous"]
    # Bounded recovery: the loop healed within its fault budget each time and
    # never went terminal (clean traffic below proves it).
    # crash + step hang + prefill hang + (leak on paged loops)
    assert 1 <= cont["restarts"] <= 5
    if "pages" in cont:
        assert "quarantined" not in cont["pages"]
        assert cont["pages"]["loop_refs"] == 0

    # Clean traffic after the chaos: scheduler healed back to READY.
    cc = client.chat.completions.create(
        messages=[{"role": "user", "content": "after"}], model="tiny",
        n=2, seed=5,
    )
    assert len(cc.choices) == 3  # consensus + both samples
    assert backend.health()["state"] == "ready"
    client.close()
    lockcheck.assert_clean()
