"""Llama-3.1/3.2-style RoPE scaling (HF rope_type="llama3"): frequency
adjustment differentially pinned against transformers' implementation, and
end-to-end logits parity on a checkpoint that ships rope_scaling."""

import json

import jax
import numpy as np
import pytest

from k_llms_tpu.models import get_config
from k_llms_tpu.models.llama import _rope_inv_freq
from k_llms_tpu.models.loader import _rope_scaling_from_hf

SCALING = {
    "rope_type": "llama3",
    "factor": 8.0,
    "low_freq_factor": 1.0,
    "high_freq_factor": 4.0,
    "original_max_position_embeddings": 64,
}


def test_inv_freq_matches_transformers():
    from transformers import LlamaConfig
    from transformers.modeling_rope_utils import ROPE_INIT_FUNCTIONS

    hf_cfg = LlamaConfig(
        hidden_size=64,
        num_attention_heads=4,
        head_dim=16,
        rope_theta=10000.0,
        rope_scaling=dict(SCALING),
        max_position_embeddings=512,
    )
    ref_inv_freq, _ = ROPE_INIT_FUNCTIONS["llama3"](hf_cfg, device="cpu")
    ours = _rope_inv_freq(16, 10000.0, _rope_scaling_from_hf(SCALING))
    np.testing.assert_allclose(
        np.asarray(ours), ref_inv_freq.numpy(), rtol=1e-6, atol=1e-8
    )


def test_hf_rope_scaling_parsing():
    assert _rope_scaling_from_hf(None) is None
    assert _rope_scaling_from_hf({"rope_type": "default"}) is None
    assert _rope_scaling_from_hf(SCALING) == (8.0, 1.0, 4.0, 64)
    with pytest.raises(ValueError):
        _rope_scaling_from_hf({"rope_type": "yarn", "factor": 4.0})


def test_registered_llama32_config_carries_scaling():
    cfg = get_config("llama-3.2-1b")
    assert cfg.rope_scaling == (32.0, 1.0, 4.0, 8192)
    assert get_config("llama-3-8b").rope_scaling is None


def test_logits_match_transformers_with_scaling(tmp_path):
    """Full parity: a checkpoint whose config.json ships llama3 rope_scaling
    must reproduce transformers' logits at positions PAST the original
    context window (where the scaling actually changes the frequencies)."""
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    from k_llms_tpu.models.llama import forward
    from k_llms_tpu.models.loader import config_from_hf, load_checkpoint

    d = tmp_path / "scaled"
    hf_config = LlamaConfig(
        vocab_size=320,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        head_dim=16,
        rope_theta=10000.0,
        rope_scaling=dict(SCALING),
        rms_norm_eps=1e-5,
        max_position_embeddings=512,
        bos_token_id=0,
        eos_token_id=1,
        tie_word_embeddings=False,
        attention_bias=False,
    )
    torch.manual_seed(1)
    model = LlamaForCausalLM(hf_config).eval()
    model.save_pretrained(str(d), safe_serialization=True)
    assert json.load(open(d / "config.json"))["rope_scaling"]["rope_type"] == "llama3"

    cfg = config_from_hf(str(d)).with_(dtype="float32")
    assert cfg.rope_scaling == (8.0, 1.0, 4.0, 64)
    params = load_checkpoint(str(d), cfg)

    rng = np.random.default_rng(7)
    ids = rng.integers(2, 320, size=(1, 100), dtype=np.int64)  # past orig ctx 64
    with torch.no_grad():
        ref = model(torch.tensor(ids)).logits.numpy()

    import jax.numpy as jnp

    ours, _ = forward(cfg, params, jnp.asarray(ids), jnp.ones((1, 100), jnp.int32))
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=2e-3, atol=2e-3)

    # Sanity: scaling OFF must NOT match at long positions — the parity above
    # is really exercising the scaled frequencies.
    cfg_off = cfg.with_(rope_scaling=None)
    off, _ = forward(cfg_off, params, jnp.asarray(ids), jnp.ones((1, 100), jnp.int32))
    assert not np.allclose(np.asarray(off), ref, rtol=2e-3, atol=2e-3)
