"""Self-healing engine supervision (PR 4): hung-launch watchdog, crash
recovery with in-flight replay, and the numeric-integrity quarantine.

Unit coverage of the supervisor primitives (budget model, epoch fencing,
bounded rebuilds, poison escalation), loader integrity (param summary,
manifest round-trip, corrupt-checkpoint fail-fast), engine-level quarantine
(NaN rows excluded from the consensus vote, healthy rows untouched), and the
ISSUE acceptance scenarios end to end on the real CPU engine: a hung launch
heals transparently (request resolves, scheduler returns READY), a replayed
request is byte-identical to an uninterrupted run, and the slow-tagged chaos
soak proves the stack never wedges under hang + NaN faults mid-traffic.
"""

import re
import threading
import time

import pytest

from k_llms_tpu import KLLMs
from k_llms_tpu.analysis import lockcheck
from k_llms_tpu.reliability import failpoints as fp
from k_llms_tpu.reliability.failpoints import FailSpec
from k_llms_tpu.reliability.supervisor import EngineSupervisor, LaunchBudgetModel
from k_llms_tpu.types.wire import (
    BackendUnavailableError,
    CheckpointCorruptError,
    EngineHungError,
    KLLMsError,
)
from k_llms_tpu.utils.observability import QUARANTINE_EVENTS, RECOVERY_EVENTS


# -- LaunchBudgetModel ----------------------------------------------------


def test_launch_budget_model_clamps_and_learns():
    m = LaunchBudgetModel(
        base_s=1.0, per_token_s=0.5, multiplier=2.0, min_budget_s=5.0, max_budget_s=50.0
    )
    assert m.budget(4, 1) == 5.0  # floor absorbs compile time
    assert m.budget(4, 1000) == 50.0  # ceiling bounds worst-case wait
    # First observation replaces the prior outright (no slow warm-up from a
    # guessed per-token latency), later ones EWMA toward the new sample.
    m.observe(4, 100, 10.0)
    assert m.stats()["per_token_s"] == pytest.approx(0.1)
    m.observe(4, 100, 30.0)
    assert 0.1 < m.stats()["per_token_s"] < 0.3
    assert m.stats()["observed_launches"] == 2


def _tight_budget() -> LaunchBudgetModel:
    """Watchdog fires after 0.25 s — unit tests simulate a hang by sleeping
    past that on the launch thread."""
    return LaunchBudgetModel(
        base_s=0.05, per_token_s=0.01, multiplier=1.0,
        min_budget_s=0.25, max_budget_s=0.25,
    )


# -- EngineSupervisor (fake launch/rebuild fns) ---------------------------


def test_hang_is_healed_by_rebuild_and_replay():
    calls = {"launch": 0, "rebuild": 0}
    events = []

    def rebuild():
        calls["rebuild"] += 1

    sup = EngineSupervisor(
        rebuild_fn=rebuild,
        budget_model=_tight_budget(),
        max_rebuilds=2,
        on_recovering=lambda a, r: events.append(("recovering", a, r)),
        on_rebuilt=lambda: events.append(("rebuilt",)),
    )

    def launch():
        calls["launch"] += 1
        if calls["launch"] == 1:
            time.sleep(1.0)  # wedged first attempt
        return "ok"

    assert sup.supervised_launch(launch, rows=2, max_new_tokens=4) == "ok"
    assert calls == {"launch": 2, "rebuild": 1}
    assert events == [("recovering", 1, "hung_launch"), ("rebuilt",)]
    st = sup.stats()
    assert st["hung_launches"] == 1 and st["rebuilds"] == 1
    assert st["replayed"] == 2  # rows, not launches
    assert st["epoch"] == 1 and st["consecutive_rebuilds"] == 0
    assert st["last_rebuild_reason"] == "hung_launch" and not st["stopped"]


def test_stale_result_from_hung_launch_is_discarded():
    """Epoch fencing: the abandoned thread's late result is discarded, never
    raced against the replay — the idempotency half of replay semantics."""
    before = RECOVERY_EVENTS.snapshot().get("supervisor.stale_results_discarded", 0)
    calls = {"n": 0}
    sup = EngineSupervisor(rebuild_fn=lambda: None, budget_model=_tight_budget())

    def launch():
        calls["n"] += 1
        if calls["n"] == 1:
            time.sleep(0.6)
            return "stale"
        return "fresh"

    assert sup.supervised_launch(launch) == "fresh"
    time.sleep(0.8)  # let the abandoned thread complete and hit the fence
    after = RECOVERY_EVENTS.snapshot().get("supervisor.stale_results_discarded", 0)
    # >= not ==: abandoned threads leaked by NEIGHBORING tests may also land
    # their (correctly discarded) stale results inside this window.
    assert after >= before + 1
    assert sup.epoch == 1


def test_rebuild_exhaustion_is_terminal_and_sticky():
    failed = []
    sup = EngineSupervisor(
        rebuild_fn=lambda: None,
        budget_model=_tight_budget(),
        max_rebuilds=1,
        on_rebuild_failed=failed.append,
    )
    with pytest.raises(EngineHungError, match="did not recover after 1"):
        sup.supervised_launch(lambda: time.sleep(1.0))
    assert len(failed) == 1 and isinstance(failed[0], EngineHungError)
    assert sup.stats()["stopped"]
    # Sticky: later launches fail fast without touching the engine.
    with pytest.raises(EngineHungError, match="stopped"):
        sup.supervised_launch(lambda: "never reached")


def test_corrupt_reload_is_terminal_with_typed_error():
    """A corrupt checkpoint can never be healed by retrying the rebuild —
    the precise typed error surfaces instead of a generic hung error."""
    def bad_rebuild():
        raise CheckpointCorruptError("manifest checksum mismatch")

    sup = EngineSupervisor(rebuild_fn=bad_rebuild, budget_model=_tight_budget())
    with pytest.raises(CheckpointCorruptError, match="checksum"):
        sup.supervised_launch(lambda: time.sleep(1.0))
    assert sup.stats()["stopped"]


def test_poison_rate_escalates_to_rebuild():
    rebuilds = []
    sup = EngineSupervisor(
        rebuild_fn=lambda: rebuilds.append(1),
        budget_model=_tight_budget(),
        poison_threshold=0.5,
        poison_window=4,
    )
    sup.note_poison(0, 4)  # clean launch decays the window
    sup.note_poison(1, 4)  # aggregate 1/8 < 0.5: below threshold
    assert sup.supervised_launch(lambda: "ok") == "ok"
    assert not rebuilds
    sup.note_poison(4, 4)
    sup.note_poison(4, 4)  # aggregate 9/16 >= 0.5: escalate
    assert sup.supervised_launch(lambda: "ok") == "ok"
    assert len(rebuilds) == 1
    assert sup.stats()["last_rebuild_reason"] == "poison_rate"
    # The escalation consumed the poison history; no rebuild storm.
    assert sup.supervised_launch(lambda: "ok") == "ok"
    assert len(rebuilds) == 1


def test_launch_exception_propagates_without_rebuild():
    """A launch that FAILS (raises) is not a launch that HANGS — errors keep
    their existing typed paths (OOM guard, breaker) and must not trigger the
    supervisor."""
    rebuilds = []
    sup = EngineSupervisor(rebuild_fn=lambda: rebuilds.append(1), budget_model=_tight_budget())

    def launch():
        raise ValueError("boom")

    with pytest.raises(ValueError, match="boom"):
        sup.supervised_launch(launch)
    assert not rebuilds
    st = sup.stats()
    assert st["rebuilds"] == 0 and not st["stopped"]


# -- loader integrity ------------------------------------------------------


def test_param_summary_shape():
    import jax

    from k_llms_tpu.models import get_config, init_params
    from k_llms_tpu.models.loader import param_summary

    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.key(0))
    s = param_summary(params)
    assert s["total_bytes"] > 0 and s["num_leaves"] >= 1
    assert sum(s["dtype_histogram"].values()) == s["num_leaves"]
    assert re.fullmatch(r"[0-9a-f]{8}", s["checksum"])
    assert param_summary(params) == s  # deterministic


def test_checkpoint_manifest_roundtrip_and_tamper(tmp_path):
    import json

    import jax

    from k_llms_tpu.models import get_config, init_params
    from k_llms_tpu.models import loader

    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.key(0))
    path = str(tmp_path / "ckpt")
    loader.save_checkpoint(path, params)
    manifest_path = loader._manifest_path(path)
    assert manifest_path.endswith(".params.json")
    with open(manifest_path) as f:
        manifest = json.load(f)
    assert manifest["checksum"] == loader.param_summary(params)["checksum"]

    loader.load_checkpoint(path, cfg)  # clean load verifies against manifest
    assert loader.last_load_summary["checksum"] == manifest["checksum"]
    assert loader.last_load_summary["total_bytes"] == manifest["total_bytes"]

    manifest["checksum"] = "deadbeef"
    with open(manifest_path, "w") as f:
        json.dump(manifest, f)
    with pytest.raises(CheckpointCorruptError, match="checksum"):
        loader.load_checkpoint(path, cfg)


def test_corrupt_failpoint_fails_fast(tmp_path):
    import jax

    from k_llms_tpu.models import get_config, init_params
    from k_llms_tpu.models import loader

    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.key(0))
    path = str(tmp_path / "ckpt")
    loader.save_checkpoint(path, params)
    before = QUARANTINE_EVENTS.snapshot().get("quarantine.checksum_failures", 0)
    with fp.failpoints({"loader.params": FailSpec(action="corrupt", times=1)}):
        with pytest.raises(CheckpointCorruptError, match="non-finite"):
            loader.load_checkpoint(path, cfg)
    assert QUARANTINE_EVENTS.snapshot()["quarantine.checksum_failures"] == before + 1
    # The failpoint consumed its budget; the checkpoint itself is intact.
    loader.load_checkpoint(path, cfg)


def test_param_summary_surfaces_in_backend_health(tmp_path):
    """Satellite: operators can verify WHICH weights are serving — the
    loader's verified summary rides health()["params"] when a checkpoint is
    loaded, and is None for seeded params."""
    import jax

    from k_llms_tpu.backends.tpu import TpuBackend
    from k_llms_tpu.models import get_config, init_params
    from k_llms_tpu.models import loader

    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.key(0))
    path = str(tmp_path / "ckpt")
    loader.save_checkpoint(path, params)
    b = TpuBackend(model="tiny", checkpoint_path=path)
    try:
        summary = b.health()["params"]
        assert summary["checksum"] == loader.param_summary(params)["checksum"]
        assert summary["total_bytes"] > 0 and summary["dtype_histogram"]
    finally:
        b.close()
    b2 = TpuBackend(model="tiny")
    try:
        assert b2.health()["params"] is None
    finally:
        b2.close()


# -- numeric-integrity quarantine on the real engine ----------------------


@pytest.fixture(scope="module")
def tpu_client():
    return KLLMs(backend="tpu", model="tiny", max_new_tokens=16)


def test_nan_quarantine_degrades_to_survivor_consensus(tpu_client):
    """ISSUE acceptance: NaN logits on 2 of n=6 decode rows quarantine ONLY
    the poisoned samples — survivors still vote, the degraded marker breaks
    the losses down by code, and likelihoods scale by survival."""
    with fp.failpoints({"engine.logits": FailSpec(action="nan", kill=2, seed=3)}):
        resp = tpu_client.chat.completions.create(
            messages=[{"role": "user", "content": "report"}],
            model="tiny",
            n=6,
            temperature=0.0,
            seed=5,
        )
    assert len(resp.choices) == 7  # consensus + 6 originals
    quarantined = [c for c in resp.choices[1:] if getattr(c, "sample_error", None)]
    survivors = [c for c in resp.choices[1:] if not getattr(c, "sample_error", None)]
    assert len(quarantined) == 2 and len(survivors) == 4
    assert all(c.sample_error["code"] == "numeric_poison" for c in quarantined)
    assert all(c.message.content == "" for c in quarantined)
    # consensus from survivors (greedy: all four agree)
    assert resp.choices[0].message.content == survivors[0].message.content
    assert resp.choices[0].message.content != ""
    assert resp.degraded["requested"] == 6 and resp.degraded["survived"] == 4
    assert resp.degraded["error_codes"] == {"numeric_poison": 2}
    assert resp.likelihoods == {"text": pytest.approx(4 / 6)}
    # engine-level counters surfaced through health()
    h = tpu_client.health()
    assert h["quarantine"]["samples"] >= 2 and h["quarantine"]["launches"] >= 1
    assert h["quarantined"] >= 2


def test_clean_traffic_decays_poison_window(tpu_client):
    """Healthy launches report poisoned=0 so the escalation window decays —
    one bad launch among many clean ones never triggers a rebuild."""
    sup = tpu_client.backend.supervisor
    rebuilds_before = sup.stats()["rebuilds"]
    resp = tpu_client.chat.completions.create(
        messages=[{"role": "user", "content": "clean"}], model="tiny", n=2, seed=1
    )
    assert resp.degraded is None
    assert sup.stats()["rebuilds"] == rebuilds_before
    assert len(sup._poison_history) >= 1
    assert sup._poison_history[-1][0] == 0  # clean launch recorded as 0 poisoned


@pytest.fixture(scope="module")
def spec_client():
    """Speculative decoding enabled: the spec decode loop has its own
    quarantine path (poisoned rows get a zero verify budget and emit
    nothing)."""
    return KLLMs(
        backend="tpu", model="tiny", max_new_tokens=12, speculative="prompt_lookup"
    )


def test_nan_quarantine_speculative_path(spec_client):
    with fp.failpoints({"engine.logits": FailSpec(action="nan", kill=1, seed=0)}):
        resp = spec_client.chat.completions.create(
            messages=[{"role": "user", "content": "echo echo echo"}],
            model="tiny",
            n=3,
            temperature=0.0,
            seed=2,
        )
    quarantined = [c for c in resp.choices[1:] if getattr(c, "sample_error", None)]
    assert len(quarantined) == 1
    assert quarantined[0].sample_error["code"] == "numeric_poison"
    assert quarantined[0].message.content == ""
    assert resp.degraded["survived"] == 2


# -- watchdog + recovery end to end on the real engine --------------------


def _tight_backend(**kw):
    from k_llms_tpu.backends.tpu import TpuBackend

    kw.setdefault("watchdog_base_s", 0.5)
    kw.setdefault("watchdog_per_token_s", 0.01)
    kw.setdefault("watchdog_multiplier", 1.0)
    kw.setdefault("watchdog_min_budget_s", 2.0)
    return TpuBackend(model="tiny", **kw)


def _chat_req(n=1, max_tokens=4, seed=1, temperature=0.0, content="hi"):
    from k_llms_tpu.backends.base import ChatRequest

    return ChatRequest(
        model="tiny",
        messages=[{"role": "user", "content": content}],
        n=n,
        max_tokens=max_tokens,
        temperature=temperature,
        seed=seed,
    )


@pytest.mark.duration_budget(30)
def test_hung_launch_end_to_end_recovery():
    """ISSUE acceptance: with engine.launch=hang:1 the request still resolves
    (watchdog detaches, engine rebuilds, launch replays) and the scheduler
    returns to READY with the recovery visible in health()."""
    before = RECOVERY_EVENTS.snapshot().get("supervisor.hung_launches", 0)
    with fp.failpoints({"engine.launch": FailSpec(action="hang", times=1, delay=10.0)}):
        b = _tight_backend()
        try:
            cc = b.chat_completion(_chat_req())
            assert len(cc.choices) == 1
            assert cc.choices[0].finish_reason in ("length", "stop")
            h = b.health()
            assert h["state"] == "ready"
            assert h["supervisor"]["hung_launches"] == 1
            assert h["supervisor"]["rebuilds"] == 1
            assert h["supervisor"]["replayed"] >= 1
            assert h["supervisor"]["consecutive_rebuilds"] == 0
            assert h["recoveries"] == 1 and h["recovery_attempt"] == 0
            assert h["last_recovery_reason"] == "hung_launch"
        finally:
            b.close()
    assert RECOVERY_EVENTS.snapshot()["supervisor.hung_launches"] == before + 1


@pytest.mark.duration_budget(30)
def test_replay_is_byte_identical_to_uninterrupted_run():
    """ISSUE acceptance: seeds are pinned at submission, weights reload to
    the same values (same param_seed), so the replayed request's text is
    byte-identical to a run that never hung."""
    kwargs = dict(n=2, max_tokens=8, seed=123, temperature=1.0, content="determinism")
    b1 = _tight_backend()
    try:
        baseline = b1.chat_completion(_chat_req(**kwargs))
    finally:
        b1.close()
    with fp.failpoints({"engine.launch": FailSpec(action="hang", times=1, delay=10.0)}):
        b2 = _tight_backend()
        try:
            replayed = b2.chat_completion(_chat_req(**kwargs))
            assert b2.supervisor.stats()["hung_launches"] == 1  # the hang happened
        finally:
            b2.close()
    assert [c.message.content for c in replayed.choices] == [
        c.message.content for c in baseline.choices
    ]
    assert replayed.usage.completion_tokens == baseline.usage.completion_tokens


@pytest.mark.duration_budget(30)
def test_rebuild_exhaustion_stops_scheduler_with_typed_503():
    """Every launch hangs; bounded rebuilds exhaust; the scheduler goes
    STOPPED and subsequent requests fail fast with a typed 503."""
    with fp.failpoints({"engine.launch": FailSpec(action="hang", times=10, delay=10.0)}):
        b = _tight_backend(max_rebuilds=1, watchdog_min_budget_s=1.0)
        try:
            with pytest.raises(EngineHungError, match="did not recover"):
                b.chat_completion(_chat_req(max_tokens=2))
            h = b.health()
            assert h["state"] == "stopped"
            assert h["supervisor"]["stopped"]
            with pytest.raises(BackendUnavailableError) as ei:
                b.chat_completion(_chat_req(max_tokens=2))
            assert ei.value.status_code == 503
        finally:
            b.close()


@pytest.mark.slow
@pytest.mark.duration_budget(180)
def test_chaos_soak_hang_and_nan_mid_traffic(monkeypatch):
    """ISSUE acceptance chaos soak: a hung launch AND NaN poison injected
    under concurrent traffic. Every request resolves (success, degraded, or
    typed error), zero hung futures, rebuilds stay bounded, and the engine
    returns to READY for clean traffic afterwards.

    Runs under KLLMS_LOCKCHECK=1 + KLLMS_RACECHECK=1: rebuild/replay churn
    exercises the supervisor, scheduler, and engine locks together; the soak
    must end with a clean lock-order graph and zero empty-lockset findings."""
    monkeypatch.setenv("KLLMS_LOCKCHECK", "1")
    monkeypatch.setenv("KLLMS_RACECHECK", "1")
    lockcheck.reset_state()
    # Budget 8 s: far below the 30 s hang (the watchdog MUST fire) but roomy
    # enough that a post-rebuild replay — full recompile + a 32-row coalesced
    # decode — finishes inside it even on a loaded CI machine. A too-tight
    # budget would declare the legitimate replay hung and exhaust rebuilds.
    b = _tight_backend(poison_threshold=0.9, watchdog_min_budget_s=8.0)
    # poison_threshold=0.9: quarantine absorbs the NaNs; the hang is what
    # exercises rebuild here.
    results = {}
    lock = threading.Lock()

    def worker(i):
        try:
            cc = b.chat_completion(
                _chat_req(n=4, max_tokens=6, seed=100 + i, content=f"soak {i}")
            )
            with lock:
                results[i] = ("ok", cc)
        except KLLMsError as e:
            with lock:
                results[i] = ("typed", e)

    with fp.failpoints(
        {
            "engine.launch": FailSpec(action="hang", times=1, delay=30.0),
            "engine.logits": FailSpec(action="nan", kill=1, seed=9),
        }
    ):
        threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=90.0)
        # Zero hung futures: every worker thread completed.
        assert not any(t.is_alive() for t in threads)
    assert sorted(results) == list(range(8))
    oks = [r for r in results.values() if r[0] == "ok"]
    assert oks, "at least some requests must succeed through the recovery"
    for kind, payload in results.values():
        if kind == "ok":
            assert len(payload.choices) == 4
    h = b.health()
    assert h["supervisor"]["rebuilds"] <= b.backend_config.max_rebuilds + 1
    assert h["supervisor"]["hung_launches"] >= 1
    assert h["quarantine"]["samples"] >= 1  # NaNs were quarantined, not fatal
    # Clean traffic after the chaos: engine healed back to READY.
    cc = b.chat_completion(_chat_req(n=2, max_tokens=4, seed=7))
    assert len(cc.choices) == 2
    assert b.health()["state"] in ("ready", "degraded")
    b.close()
    lockcheck.assert_clean()
