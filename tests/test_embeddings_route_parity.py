"""Differential validation of the EMBEDDINGS similarity route (weak spot #4
from round 1): consensus *outcomes* — not just the Levenshtein fallback — must
match the reference engine when both sides use the same embedding provider.

Both engines get the identical deterministic embedder (the fake backend's
hash-based vectors), so any divergence is in the similarity plumbing: the
>50-char gate, cosine normalization, cache behavior, alignment thresholds fed
by embedding similarities, and medoid election over them."""

import json

import numpy as np
import pytest

from k_llms_tpu.backends.fake import deterministic_embedding
from k_llms_tpu.consensus.recursion import consensus_values, recursive_list_alignments
from k_llms_tpu.consensus.settings import ConsensusSettings
from k_llms_tpu.consensus.similarity import SimilarityScorer

from reference_oracle import load_reference_engine, reference_available

pytestmark = pytest.mark.skipif(
    not reference_available(), reason="reference tree not present"
)

LONG = {
    "a": "The shipment of industrial widgets departed the Rotterdam warehouse "
    "on Tuesday morning and is expected at the Hamburg depot within three days.",
    "a2": "The shipment of industrial widgets left the Rotterdam warehouse on "
    "Tuesday morning and should reach the Hamburg depot within three days.",
    "b": "Payment terms are net thirty days from the invoice issue date, with a "
    "two percent discount applied for settlement within ten calendar days.",
    "c": "All customer support inquiries should be directed to the billing "
    "department via email and will be answered within two business days.",
}


def embed_fn(texts):
    return [deterministic_embedding(t) for t in texts]


def _run_ours(samples):
    scorer = SimilarityScorer(method="embeddings", embed_fn=embed_fn)
    settings = ConsensusSettings(string_similarity_method="embeddings")
    aligned, _ = recursive_list_alignments(samples, scorer, settings.min_support_ratio)
    return consensus_values(aligned, settings, scorer)


def _run_reference(samples):
    ref = load_reference_engine()
    # The reference caches similarities in module-global TTL caches; clear them
    # so each case is computed fresh.
    ref.embeddings_cache.clear()
    ref.similarity_cache.clear()
    settings = ref.ConsensusSettings(string_similarity_method="embeddings")
    aligned, _ = ref.recursive_list_alignments(
        samples, "embeddings", embed_fn, None, settings.min_support_ratio
    )
    return ref.consensus_values(aligned, settings, embed_fn, None)


def _assert_deep_close(a, b, path=""):
    assert type(a) is type(b) or (
        isinstance(a, (int, float)) and isinstance(b, (int, float))
    ), f"type mismatch at {path}: {type(a)} vs {type(b)}"
    if isinstance(a, dict):
        assert set(a) == set(b), f"key mismatch at {path}: {set(a)} vs {set(b)}"
        for k in a:
            _assert_deep_close(a[k], b[k], f"{path}.{k}")
    elif isinstance(a, list):
        assert len(a) == len(b), f"length mismatch at {path}"
        for i, (x, y) in enumerate(zip(a, b)):
            _assert_deep_close(x, y, f"{path}[{i}]")
    elif isinstance(a, float) and not isinstance(a, bool):
        np.testing.assert_allclose(a, b, rtol=1e-9, atol=1e-12, err_msg=path)
    else:
        assert a == b, f"value mismatch at {path}: {a!r} vs {b!r}"


def _both(samples):
    ours_val, ours_conf = _run_ours(json.loads(json.dumps(samples)))
    ref_val, ref_conf = _run_reference(json.loads(json.dumps(samples)))
    _assert_deep_close(ours_val, ref_val, "value")
    _assert_deep_close(ours_conf, ref_conf, "confidence")
    return ours_val, ours_conf


def test_long_string_medoid_via_embeddings():
    samples = [
        {"summary": LONG["a"]},
        {"summary": LONG["a2"]},
        {"summary": LONG["b"]},
    ]
    val, conf = _both(samples)
    # The medoid should be one of the two near-duplicates, chosen by embedding
    # cosine (levenshtein would agree here, but conf comes from cosine means).
    assert val["summary"] in (LONG["a"], LONG["a2"])


def test_list_alignment_driven_by_embeddings():
    samples = [
        {"notes": [LONG["a"], LONG["b"], LONG["c"]]},
        {"notes": [LONG["b"], LONG["a2"], LONG["c"]]},  # shuffled + variant
        {"notes": [LONG["c"], LONG["b"], LONG["a"]]},
    ]
    val, conf = _both(samples)
    assert len(val["notes"]) == 3


def test_mixed_short_strings_use_fallback_identically():
    # Short strings stay under the 50-char gate: both sides must take the
    # Levenshtein fallback INSIDE the embeddings method.
    samples = [
        {"city": "Amsterdam", "summary": LONG["a"]},
        {"city": "Amsterdem", "summary": LONG["a2"]},
        {"city": "Amsterdam", "summary": LONG["a"]},
    ]
    val, conf = _both(samples)
    assert val["city"] == "Amsterdam"


def test_embedding_failure_degrades_identically():
    calls = {"n": 0}

    def flaky_embed(texts):
        raise RuntimeError("embedding backend down")

    ours_scorer = SimilarityScorer(method="embeddings", embed_fn=flaky_embed)
    settings = ConsensusSettings(string_similarity_method="embeddings")
    samples = [{"summary": LONG["a"]}, {"summary": LONG["a2"]}, {"summary": LONG["b"]}]
    aligned, _ = recursive_list_alignments(
        json.loads(json.dumps(samples)), ours_scorer, settings.min_support_ratio
    )
    ours_val, ours_conf = consensus_values(aligned, settings, ours_scorer)

    ref = load_reference_engine()
    ref.embeddings_cache.clear()
    ref.similarity_cache.clear()
    rsettings = ref.ConsensusSettings(string_similarity_method="embeddings")
    raligned, _ = ref.recursive_list_alignments(
        json.loads(json.dumps(samples)), "embeddings", flaky_embed, None,
        rsettings.min_support_ratio,
    )
    ref_val, ref_conf = ref.consensus_values(raligned, rsettings, flaky_embed, None)
    _assert_deep_close(ours_val, ref_val, "value")
    _assert_deep_close(ours_conf, ref_conf, "confidence")
