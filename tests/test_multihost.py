"""REAL two-process DCN test: jax.distributed over localhost TCP on CPU.

The dryrun and CPU-mesh tests exercise multi-DEVICE sharding inside one
process; this test exercises the multi-HOST path (SURVEY.md §2.3 "DCN for
multi-host fan-out"): two OS processes initialize through
``initialize_multihost``, build one global mesh spanning both, and run a
psum + a sharded matmul whose collectives cross the process boundary. That is
the same wire path a TPU pod's inter-host traffic takes (gRPC/DCN), scaled
down to localhost."""

import os
import socket
import subprocess
import sys

WORKER = r"""
import os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, os.getcwd())

from k_llms_tpu.parallel.distributed import initialize_multihost

ok = initialize_multihost()  # from KLLMS_* env vars
assert ok, "expected distributed initialization"
assert jax.process_count() == 2
assert len(jax.devices()) == 4  # 2 local per process, 4 global

import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

mesh = Mesh(jax.devices(), ("data",))
pid = jax.process_index()

# A data-sharded global array: each process contributes its local shard.
local = jnp.arange(2, dtype=jnp.float32) + 10 * pid  # [10*pid, 10*pid+1]
arrs = jax.make_array_from_single_device_arrays(
    (4,),
    NamedSharding(mesh, P("data")),
    [jax.device_put(local[i : i + 1], d) for i, d in enumerate(jax.local_devices())],
)

@jax.jit
def total(x):
    return jnp.sum(x)  # global reduction -> crosses DCN

t = float(total(arrs))
assert t == 0 + 1 + 10 + 11, t

# A sharded matmul with a psum over the data axis (the coalesced-decode
# collective pattern).
if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:  # 0.4.x: experimental module
    from jax.experimental.shard_map import shard_map

@jax.jit
def dotsum(x):
    def body(xs):
        return jax.lax.psum(jnp.sum(xs * 2.0), "data")
    return shard_map(body, mesh=mesh, in_specs=P("data"), out_specs=P())(x)

d = float(dotsum(arrs))
assert d == 2 * (0 + 1 + 10 + 11), d

# The REAL model across the process boundary: tiny-config forward with the
# batch data-sharded over the 2-process mesh (params replicated), loss
# reduced globally. Identical results on both processes proves the DCN
# collectives carried the cross-host rows.
from k_llms_tpu.models import get_config, init_params
from k_llms_tpu.models.llama import forward

cfg = get_config("tiny").with_(num_layers=2)
params = init_params(cfg, jax.random.key(0))  # same seed -> identical, replicated

import numpy as np

tokens_local = (np.arange(2 * 16, dtype=np.int32).reshape(2, 16) + 100 * pid) % cfg.vocab_size
global_tokens = jax.make_array_from_process_local_data(
    NamedSharding(mesh, P("data", None)), tokens_local, (4, 16)
)
mask = jax.make_array_from_process_local_data(
    NamedSharding(mesh, P("data", None)), np.ones((2, 16), np.int32), (4, 16)
)

@jax.jit
def loss_fn(params, tokens, mask):
    logits, _ = forward(cfg, params, tokens, mask)
    return jnp.mean(logits.astype(jnp.float32) ** 2)

loss = float(loss_fn(params, global_tokens, mask))
assert loss > 0
print(f"WORKER_{pid}_LOSS={loss:.6f}")
print(f"WORKER_{pid}_OK")
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _run_workers(port: int):
    procs = []
    try:
        for pid in range(2):
            env = dict(os.environ)
            env.update(
                KLLMS_COORDINATOR=f"127.0.0.1:{port}",
                KLLMS_NUM_PROCESSES="2",
                KLLMS_PROCESS_ID=str(pid),
                JAX_PLATFORMS="cpu",
            )
            # A fresh interpreter per process: jax.distributed must initialize
            # before any backend use, which pytest's own process already did.
            procs.append(
                subprocess.Popen(
                    [sys.executable, "-c", WORKER],
                    env=env,
                    cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                    stdout=subprocess.PIPE,
                    stderr=subprocess.STDOUT,
                    text=True,
                )
            )
        outs = []
        for p in procs:
            try:
                outs.append(p.communicate(timeout=150)[0])
            except subprocess.TimeoutExpired:
                # A lost coordinator-port race can leave a worker blocked on
                # connect rather than exiting; kill it, keep whatever it
                # printed, and surface the round as failed so the caller's
                # fresh-port retry applies to this mode too. Per-process
                # communicate keeps the healthy worker's output intact.
                p.kill()
                outs.append(p.communicate()[0] or "")
        return outs, procs
    finally:
        for p in procs:  # a hung coordinator must not leak past the test
            if p.poll() is None:
                p.kill()
                p.communicate()


def test_two_process_dcn_collectives():
    # _free_port has an unavoidable close-to-rebind window; retry once with a
    # fresh port if the coordinator lost the race (clean bind failure or a
    # worker left hanging on the stolen port — both count as a lost round).
    for attempt in range(2):
        outputs, procs = _run_workers(_free_port())
        if all(p.returncode == 0 for p in procs) or attempt == 1:
            break
    for pid, (p, out) in enumerate(zip(procs, outputs)):
        assert p.returncode == 0, f"process {pid} failed:\n{out}"
        assert f"WORKER_{pid}_OK" in out
    # The globally-reduced model loss must be identical on both processes.
    losses = [
        line.split("=")[1]
        for out in outputs
        for line in out.splitlines()
        if "_LOSS=" in line
    ]
    assert len(losses) == 2 and losses[0] == losses[1], losses
