"""Test configuration: force an 8-device virtual CPU platform before JAX initializes.

The reference (k-LLMs) has no hermetic test story (SURVEY.md §4); ours runs the whole
framework — including the "distributed" decode path — on a simulated 8-device CPU mesh
so no TPU hardware is needed for CI.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
