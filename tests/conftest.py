"""Test configuration: force an 8-device virtual CPU platform before JAX
backends initialize.

The reference (k-LLMs) has no hermetic test story (SURVEY.md §4); ours runs the
whole framework — including the "distributed" decode path — on a simulated
8-device CPU mesh so no TPU hardware is needed for CI.

NB: this environment pre-sets JAX_PLATFORMS=axon via sitecustomize, so a plain
env-var default is not enough — we must update jax.config before first device
use.
"""

import os
import sys

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


# -- shared engines ----------------------------------------------------------
# Engine construction dominates suite wall time: every LocalEngine owns its
# own jit caches, so two module fixtures building "the same" engine compile
# every prefill/decode program twice. This session-scoped factory hands out
# ONE engine per construction key — identical engines across test_w4 /
# test_sp_decode / test_tpu_backend / test_speculative share compiles.
#
# Engines are STATEFUL (prefix cache, spec_stats, jit caches): tests that
# assert on those counters must reset them or build a private engine.
_PARAMS_CACHE = {}
_ENGINE_CACHE = {}


def shared_params(config, param_key=0):
    """init_params once per (config, seed) — configs are hashable."""
    key = (config, param_key)
    params = _PARAMS_CACHE.get(key)
    if params is None:
        from k_llms_tpu.models import init_params

        params = init_params(config, jax.random.key(param_key))
        _PARAMS_CACHE[key] = params
    return params


def shared_engine(model="tiny", *, param_key=0, mesh_shape=None, **kwargs):
    """One LocalEngine per (model-or-config, params seed, mesh shape, engine
    knobs) for the whole session. ``model``: registered name or ModelConfig;
    ``mesh_shape``: (data, model) for make_mesh, None = use_mesh=False.
    Extra kwargs go to LocalEngine verbatim (and join the cache key)."""
    from k_llms_tpu.models import get_config

    config = get_config(model) if isinstance(model, str) else model
    key = (config, param_key, mesh_shape, tuple(sorted(kwargs.items())))
    eng = _ENGINE_CACHE.get(key)
    if eng is None:
        from k_llms_tpu.engine.engine import LocalEngine

        # Always hand the engine the shared full-precision tree (it quantizes
        # passed-in params itself): a meshed engine's own param_seed init is
        # sharded and draws DIFFERENT values than the host-side init, which
        # would break solo-vs-mesh bit-equality tests.
        params = shared_params(config, param_key)
        if mesh_shape is None:
            eng = LocalEngine(
                config, params=params, use_mesh=False, param_seed=param_key,
                **kwargs,
            )
        else:
            from k_llms_tpu.parallel.mesh import make_mesh

            eng = LocalEngine(
                config, params=params, mesh=make_mesh(*mesh_shape),
                param_seed=param_key, **kwargs,
            )
        _ENGINE_CACHE[key] = eng
    return eng


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "mesh: requires the 8-device virtual CPU mesh (conftest sets it up; "
        "a caller-preset XLA_FLAGS without the device-count flag breaks it)",
    )
    config.addinivalue_line(
        "markers",
        "duration_budget(seconds): declared expected runtime; budgets over "
        "30s require the `slow` tag (enforced at collection by "
        "tests/_duration_guard.py)",
    )


def pytest_collection_modifyitems(config, items):
    """Tag every mesh-environment-gated test with the explicit ``mesh`` marker
    (VERDICT r2 weak #7): `pytest -m mesh` runs exactly the multi-device
    suites, and test_environment.py fails loudly when they would all silently
    skip because the virtual mesh is missing."""
    import pytest

    for item in items:
        for m in item.iter_markers("skipif"):
            reason = str(m.kwargs.get("reason", "")) + "".join(
                str(a) for a in m.args if isinstance(a, str)
            )
            if "8-device CPU mesh" in reason or "mesh" in reason.lower():
                item.add_marker(pytest.mark.mesh)
                break

    # Duration-budget guard: a test declaring a budget over the tier-1
    # threshold without a `slow` tag fails COLLECTION (deterministic, instant)
    # instead of flaking the 870 s tier-1 timeout at runtime.
    from _duration_guard import enforce

    enforce(items)
