"""Test configuration: force an 8-device virtual CPU platform before JAX
backends initialize.

The reference (k-LLMs) has no hermetic test story (SURVEY.md §4); ours runs the
whole framework — including the "distributed" decode path — on a simulated
8-device CPU mesh so no TPU hardware is needed for CI.

NB: this environment pre-sets JAX_PLATFORMS=axon via sitecustomize, so a plain
env-var default is not enough — we must update jax.config before first device
use.
"""

import os
import sys

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "mesh: requires the 8-device virtual CPU mesh (conftest sets it up; "
        "a caller-preset XLA_FLAGS without the device-count flag breaks it)",
    )
    config.addinivalue_line(
        "markers",
        "duration_budget(seconds): declared expected runtime; budgets over "
        "30s require the `slow` tag (enforced at collection by "
        "tests/_duration_guard.py)",
    )


def pytest_collection_modifyitems(config, items):
    """Tag every mesh-environment-gated test with the explicit ``mesh`` marker
    (VERDICT r2 weak #7): `pytest -m mesh` runs exactly the multi-device
    suites, and test_environment.py fails loudly when they would all silently
    skip because the virtual mesh is missing."""
    import pytest

    for item in items:
        for m in item.iter_markers("skipif"):
            reason = str(m.kwargs.get("reason", "")) + "".join(
                str(a) for a in m.args if isinstance(a, str)
            )
            if "8-device CPU mesh" in reason or "mesh" in reason.lower():
                item.add_marker(pytest.mark.mesh)
                break

    # Duration-budget guard: a test declaring a budget over the tier-1
    # threshold without a `slow` tag fails COLLECTION (deterministic, instant)
    # instead of flaking the 870 s tier-1 timeout at runtime.
    from _duration_guard import enforce

    enforce(items)
