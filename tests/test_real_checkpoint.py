"""Real-checkpoint path: a miniature REAL HF Llama checkpoint (safetensors +
config.json + a trained byte-level-BPE tokenizer.json with a chat template)
goes through ``config_from_hf`` → ``load_safetensors`` → ``HFTokenizer`` →
generate, and our forward's logits match ``transformers``' LlamaForCausalLM on
CPU. Covers the loader claims (`k_llms_tpu/models/loader.py:45-51`) and the
HFTokenizer surface with zero network access."""

import json

import jax.numpy as jnp
import numpy as np
import pytest
from pydantic import BaseModel

from k_llms_tpu import KLLMs
from k_llms_tpu.engine.tokenizer import HFTokenizer, get_tokenizer
from k_llms_tpu.models.llama import forward
from k_llms_tpu.models.loader import config_from_hf, load_safetensors

CHAT_TEMPLATE = (
    "{{ bos_token }}{% for message in messages %}"
    "<|start_header_id|>{{ message['role'] }}<|end_header_id|>"
    "{{ message['content'] }}<|eot_id|>{% endfor %}"
    "{% if add_generation_prompt %}<|start_header_id|>assistant<|end_header_id|>{% endif %}"
)

CORPUS = [
    "Extract the invoice fields from this document.",
    '{"vendor": "Acme Corporation", "total": 4310.55, "paid": false}',
    "The quick brown fox jumps over the lazy dog.",
    "Invoice number INV-2024-00417 issued March 3rd, net 30 terms.",
    '{"name": "widget", "count": 12, "price": 149.5}',
] * 4


@pytest.fixture(scope="module")
def hf_dir(tmp_path_factory):
    """Build a miniature real HF checkpoint: trained BPE tokenizer + random
    2-layer Llama saved with save_pretrained (the exact on-disk layout a real
    Llama-3 checkpoint directory has)."""
    from tokenizers import Tokenizer, decoders, models, pre_tokenizers, trainers
    from transformers import LlamaConfig, LlamaForCausalLM, PreTrainedTokenizerFast
    import torch

    d = tmp_path_factory.mktemp("mini_llama_hf")

    tok = Tokenizer(models.BPE())
    tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
    tok.decoder = decoders.ByteLevel()
    trainer = trainers.BpeTrainer(
        vocab_size=384,
        special_tokens=[
            "<|begin_of_text|>",
            "<|end_of_text|>",
            "<|eot_id|>",
            "<|start_header_id|>",
            "<|end_header_id|>",
        ],
        initial_alphabet=pre_tokenizers.ByteLevel.alphabet(),
    )
    tok.train_from_iterator(CORPUS, trainer)
    fast = PreTrainedTokenizerFast(
        tokenizer_object=tok,
        bos_token="<|begin_of_text|>",
        eos_token="<|end_of_text|>",
        # Llama-3 marks the chat-control tokens special; the token-constraint
        # compiler relies on that to keep them out of the grammar vocabulary.
        additional_special_tokens=[
            "<|eot_id|>",
            "<|start_header_id|>",
            "<|end_header_id|>",
        ],
    )
    fast.chat_template = CHAT_TEMPLATE
    fast.save_pretrained(str(d))

    config = LlamaConfig(
        vocab_size=len(fast),
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        head_dim=16,
        rope_theta=10000.0,
        rms_norm_eps=1e-5,
        max_position_embeddings=512,
        bos_token_id=fast.bos_token_id,
        eos_token_id=fast.eos_token_id,
        tie_word_embeddings=False,
        attention_bias=False,
    )
    torch.manual_seed(0)
    model = LlamaForCausalLM(config).eval()
    model.save_pretrained(str(d), safe_serialization=True)
    return str(d)


def test_config_from_real_checkpoint(hf_dir):
    cfg = config_from_hf(hf_dir)
    assert cfg is not None
    assert cfg.hidden_size == 64
    assert cfg.num_layers == 2
    assert cfg.num_kv_heads == 2
    assert cfg.head_dim == 16
    assert cfg.rope_theta == 10000.0


def test_logits_match_transformers(hf_dir):
    """Our stacked-scan forward on the imported weights reproduces
    transformers' reference implementation (f32, CPU)."""
    import torch
    from transformers import AutoTokenizer, LlamaForCausalLM

    cfg = config_from_hf(hf_dir).with_(dtype="float32")
    params = load_safetensors(hf_dir, cfg, dtype=jnp.float32)

    hf_tok = AutoTokenizer.from_pretrained(hf_dir, local_files_only=True)
    ids = [hf_tok.bos_token_id] + hf_tok.encode(
        "The quick brown fox jumps over the lazy invoice.", add_special_tokens=False
    )

    tokens = jnp.asarray([ids], jnp.int32)
    ours, _ = forward(cfg, params, tokens, jnp.ones_like(tokens))

    model = LlamaForCausalLM.from_pretrained(hf_dir, torch_dtype=torch.float32).eval()
    with torch.no_grad():
        theirs = model(torch.tensor([ids])).logits.numpy()

    np.testing.assert_allclose(np.asarray(ours), theirs, rtol=2e-4, atol=2e-4)


def test_hf_tokenizer_surface(hf_dir):
    tok = get_tokenizer(hf_dir)
    assert isinstance(tok, HFTokenizer)
    assert tok.is_byte_level is False
    assert tok.bos_id is not None and tok.eos_id is not None

    # Round trip through the trained BPE merges.
    text = "Extract the invoice fields"
    ids = tok.encode(text)
    assert tok.decode(ids) == text
    # BPE actually merges: fewer tokens than bytes.
    assert len(ids) < len(text.encode("utf-8"))

    # Chat template produces header structure + generation prompt.
    ids = tok.apply_chat_template(
        [{"role": "user", "content": "hello"}], add_generation_prompt=True
    )
    assert ids[0] == tok.bos_id
    header = tok._tok.convert_tokens_to_ids("<|start_header_id|>")
    assert ids.count(header) == 2  # user turn + assistant header

    # Stop ids: eos plus the eot turn delimiter.
    eot = tok._tok.convert_tokens_to_ids("<|eot_id|>")
    assert tok.eos_id in tok.stop_ids
    assert eot in tok.stop_ids


def test_end_to_end_generate_real_checkpoint(hf_dir):
    """Full public path on the real checkpoint: unregistered model name falls
    back to the checkpoint's own config.json; HFTokenizer drives the chat
    template; n=3 consensus completes."""
    client = KLLMs(
        backend="tpu",
        model="mini-llama-hf",
        checkpoint_path=hf_dir,
        tokenizer_path=hf_dir,
        dtype="float32",
        max_new_tokens=12,
    )
    resp = client.chat.completions.create(
        messages=[{"role": "user", "content": "Say something."}],
        model="mini-llama-hf",
        n=3,
        temperature=0.9,
        seed=11,
    )
    assert len(resp.choices) == 4
    assert all(isinstance(c.message.content, str) for c in resp.choices)
    assert resp.usage.prompt_tokens > 0
    assert resp.usage.completion_tokens > 0


def test_parse_bpe_constraint_real_checkpoint(hf_dir):
    """Structured output on the real BPE vocabulary: the schema DFA lifts to
    token-level masks over the trained tokenizer, so every sample is valid
    JSON obeying the schema prefix (grammar-guaranteed even on a random
    model)."""

    class Item(BaseModel):
        name: str
        count: int

    client = KLLMs(
        backend="tpu",
        model="mini-llama-hf",
        checkpoint_path=hf_dir,
        tokenizer_path=hf_dir,
        dtype="float32",
        max_new_tokens=48,
    )
    resp = client.chat.completions.parse(
        messages=[{"role": "user", "content": "Extract the item."}],
        response_format=Item,
        model="mini-llama-hf",
        n=2,
        temperature=0.9,
        seed=3,
    )
    assert len(resp.choices) == 3
    for c in resp.choices[1:]:
        if c.finish_reason == "stop":  # completed samples must validate
            obj = json.loads(c.message.content)
            Item.model_validate(obj)


def test_token_bytes_sentencepiece_marker_keeps_spaces():
    """SentencePiece vocabularies carry '▁' (U+2581) word-boundary markers in
    the raw piece where the text has spaces. decode([id]) strips a lone
    piece's leading space, so the old fallback dropped every inter-word space
    from concatenated per-token bytes; the marker must map to b' ' directly."""

    class FakeSP:
        unk_token_id = 0

        def convert_ids_to_tokens(self, i):
            return {5: "▁hello", 6: "▁world", 7: "!"}.get(i)

        def decode(self, ids, skip_special_tokens=True):
            # What transformers does to a lone piece: leading space stripped.
            return "".join(
                self.convert_ids_to_tokens(i).replace("▁", " ") for i in ids
            ).lstrip(" ")

    tok = object.__new__(HFTokenizer)
    tok._tok = FakeSP()
    tok.bos_id, tok.eos_id, tok.pad_id = 1, 2, 2
    assert tok.token_bytes(5) == b" hello"
    joined = b"".join(tok.token_bytes(i) for i in [5, 6, 7])
    assert joined == b" hello world!"


def test_hf_tokenizer_without_chat_template(tmp_path, hf_dir):
    """Base-model checkpoints ship no chat template; the tokenizer falls back
    to a minimal llama-style layout instead of raising."""
    import shutil

    d = tmp_path / "no_template"
    shutil.copytree(hf_dir, d)
    # strip the template from the saved tokenizer config
    cfg_path = d / "tokenizer_config.json"
    cfg = json.loads(cfg_path.read_text())
    cfg.pop("chat_template", None)
    cfg_path.write_text(json.dumps(cfg))
    for extra in ("chat_template.jinja",):  # newer transformers sidecar file
        p = d / extra
        if p.exists():
            p.unlink()

    tok = get_tokenizer(str(d))
    assert getattr(tok._tok, "chat_template", None) is None
    ids = tok.apply_chat_template([{"role": "user", "content": "hello"}])
    assert ids[0] == tok.bos_id
    text = tok.decode(ids)
    assert "hello" in text and "<assistant>" in text
