"""bench.py relay-outage hardening (VERDICT r2 #1): a dead device relay must
produce the one-line JSON with an explicit "error" field — never a bare
traceback — and the hermetic quality section must still be present."""

import json

import pytest

import bench


def test_emit_includes_error_field(capsys):
    bench._emit(None, None, {"quality": {"ok": 1}}, error="RuntimeError: boom")
    line = json.loads(capsys.readouterr().out.strip())
    assert line["metric"] == "n32_consensus_p50_over_single_p50"
    assert line["value"] is None
    assert line["error"] == "RuntimeError: boom"
    assert line["detail"]["quality"] == {"ok": 1}


def test_main_emits_structured_json_when_relay_down(monkeypatch, capsys):
    monkeypatch.setattr(bench, "_device_probe_ok", lambda: False)
    monkeypatch.setattr(bench, "PROBE_ATTEMPTS", 2)
    monkeypatch.setattr(bench, "PROBE_BACKOFF_S", 0)
    monkeypatch.setattr(bench, "RUN_RETRIES", 0)
    # keep the test fast: stub the (hermetic but multi-second) quality eval
    monkeypatch.setattr(
        bench,
        "bench_quality",
        lambda: {"default": {"consensus_n32": 1.0}, "reference_exact": {}},
    )

    with pytest.raises(SystemExit) as exc_info:
        bench.main()
    assert exc_info.value.code == 1

    out = capsys.readouterr().out.strip().splitlines()
    line = json.loads(out[-1])  # exactly one JSON line on stdout
    assert len(out) == 1
    assert line["value"] is None and line["vs_baseline"] is None
    assert "device unavailable" in line["error"]
    assert line["detail"]["quality"]["default"]["consensus_n32"] == 1.0


def test_wait_for_device_returns_when_probe_passes(monkeypatch):
    monkeypatch.setattr(bench, "_device_probe_ok", lambda: True)
    bench.wait_for_device()  # must not raise or sleep


def test_flagship_retry_after_transient_unavailable(monkeypatch, capsys):
    """A mid-run UNAVAILABLE on the first attempt must retry and succeed."""
    monkeypatch.setattr(bench, "_device_probe_ok", lambda: True)
    monkeypatch.setattr(bench, "bench_quality", lambda: {})
    calls = {"n": 0}

    def flaky_flagship():
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("UNAVAILABLE: socket closed")
        return {"ratio": 1.25}, object(), object()

    monkeypatch.setattr(bench, "bench_flagship", flaky_flagship)
    monkeypatch.setattr(bench, "bench_concurrency", lambda b, c: {"speedup": 3.0})

    bench.main()
    line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert calls["n"] == 2
    assert line["value"] == 1.25
    assert "error" not in line
    assert line["detail"]["concurrency"]["speedup"] == 3.0
