"""health() snapshots across every ServerState transition (PR 4 satellite):
each reachable lifecycle state yields a well-typed snapshot — state string,
queue depth, recovery attempt counters, quarantine counters — so a /healthz
consumer never sees a missing or mistyped field mid-transition."""

import pytest

from k_llms_tpu.engine.scheduler import EngineScheduler, ServerState
from k_llms_tpu.types.wire import BackendUnavailableError

INT_FIELDS = (
    "queue_depth",
    "queue_weight",
    "in_flight",
    "effective_max_rows",
    "max_rows",
    "served",
    "errors",
    "shed",
    "shed_over_capacity",
    "evicted",
    "oom_splits",
    "recoveries",
    "recovery_attempt",
    "quarantined",
)


def _assert_snapshot_shape(h):
    assert h["state"] in {s.value for s in ServerState}
    for k in INT_FIELDS:
        assert isinstance(h[k], int), f"{k} must be an int, got {type(h[k])}"
    assert h["last_recovery_reason"] is None or isinstance(
        h["last_recovery_reason"], str
    )
    assert h["max_queue_weight"] is None or isinstance(h["max_queue_weight"], int)
    assert isinstance(h["drain_rate"], (int, float))


def test_health_through_full_lifecycle():
    """Walk READY -> DEGRADED -> RECOVERING -> DEGRADED -> READY ->
    DRAINING/STOPPED via the same hooks the engine and supervisor use,
    asserting snapshot shape and the recovery/quarantine fields at each
    step."""
    s = EngineScheduler(name="lifecycle", max_rows=8)
    try:
        h = s.health()
        # STARTING is transient (worker thread startup); both are legal here.
        assert h["state"] in ("starting", "ready")
        _assert_snapshot_shape(h)
        assert h["recoveries"] == 0 and h["recovery_attempt"] == 0
        assert h["last_recovery_reason"] is None and h["quarantined"] == 0

        # Device OOM: width backs off, DEGRADED.
        s.note_oom()
        h = s.health()
        assert h["state"] == "degraded"
        assert h["effective_max_rows"] == 4 and h["oom_splits"] == 1
        _assert_snapshot_shape(h)

        # Supervisor starts a rebuild: RECOVERING, attempt visible.
        s.note_recovering(1, "hung_launch")
        h = s.health()
        assert h["state"] == "recovering"
        assert h["recoveries"] == 1 and h["recovery_attempt"] == 1
        assert h["last_recovery_reason"] == "hung_launch"
        _assert_snapshot_shape(h)

        # Quarantined rows accumulate regardless of lifecycle state.
        s.note_quarantine(3)
        s.note_quarantine(0)  # no-op
        assert s.health()["quarantined"] == 3

        # Rebuild done: width backoff SURVIVES the rebuild, so the scheduler
        # lands back in DEGRADED, not READY.
        s.note_rebuilt()
        h = s.health()
        assert h["state"] == "degraded" and h["recovery_attempt"] == 0
        _assert_snapshot_shape(h)

        # Three clean launches restore the width and clear DEGRADED.
        for _ in range(3):
            s.note_recovered()
        h = s.health()
        assert h["state"] == "ready" and h["effective_max_rows"] == 8
        _assert_snapshot_shape(h)

        # A second recovery from READY also transitions.
        s.note_recovering(1, "poison_rate")
        h = s.health()
        assert h["state"] == "recovering" and h["recoveries"] == 2
        assert h["last_recovery_reason"] == "poison_rate"
        s.note_rebuilt()
        assert s.health()["state"] == "ready"  # no width backoff this time
    finally:
        assert s.drain(timeout=5.0)
    h = s.health()
    assert h["state"] == "stopped"
    _assert_snapshot_shape(h)


def test_health_during_draining_state():
    """DRAINING is observable mid-drain: admission closed, snapshot intact."""
    import threading
    import time

    s = EngineScheduler(name="drainer", batch_window=0.0)
    release = threading.Event()
    entered = threading.Event()

    def slow(_):
        entered.set()
        release.set()  # trivial work; drain() below must still join cleanly
        return 1

    s.call(lambda: slow(None))
    t = threading.Thread(target=lambda: s.drain(timeout=5.0))
    t.start()
    # Poll until the drain thread flips the state (scheduler may already have
    # finished the queued work, so accept stopped too).
    for _ in range(100):
        if s.health()["state"] in ("draining", "stopped"):
            break
        time.sleep(0.01)
    h = s.health()
    assert h["state"] in ("draining", "stopped")
    _assert_snapshot_shape(h)
    t.join(timeout=10.0)
    assert s.health()["state"] == "stopped"


def test_rebuild_failed_stops_and_flushes_queue_typed():
    """Terminal rebuild failure: STOPPED, queued futures flushed with a typed
    503, snapshot still well-formed, new work rejected."""
    s = EngineScheduler(name="terminal")
    s.note_rebuild_failed(RuntimeError("rebuild exploded"))
    h = s.health()
    assert h["state"] == "stopped"
    _assert_snapshot_shape(h)
    with pytest.raises(BackendUnavailableError) as ei:
        s.call(lambda: 1)
    assert ei.value.status_code == 503
