"""int4 (w4a16) quantization: packing, the Pallas kernel (interpret mode on
CPU), and the engine/backend plumbing."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k_llms_tpu.ops.w4matmul import (
    GROUP,
    Q4Tensor,
    pack_int4,
    supports_int4,
    unpack_int4,
    w4_matmul,
)


def test_pack_unpack_roundtrip_exact():
    # Values already on the int4 grid round-trip exactly through pack/unpack.
    key = jax.random.key(0)
    ints = jax.random.randint(key, (256, 128), -7, 8).astype(jnp.float32)
    w = ints * 0.01  # uniform scale per group -> amax/7 recovers the grid
    q4 = pack_int4(w)
    assert q4.q.shape == (128, 128)
    assert q4.scale.shape == (2, 128)
    deq = unpack_int4(q4)
    np.testing.assert_allclose(np.asarray(deq), np.asarray(w), rtol=1e-5, atol=1e-7)


def test_pack_quantization_error_bounded():
    w = jax.random.normal(jax.random.key(1), (512, 256), jnp.float32)
    q4 = pack_int4(w)
    deq = np.asarray(unpack_int4(q4))
    w_np = np.asarray(w)
    # Max error within a group is scale/2; scale = amax/7.
    scales = np.abs(w_np.reshape(-1, GROUP, 256)).max(axis=1) / 7.0
    err = np.abs(deq - w_np).reshape(-1, GROUP, 256).max(axis=1)
    assert (err <= scales / 2 + 1e-7).all()


def test_kernel_matches_xla_reference():
    # Real kernel blocking (K=512 -> one 512 block; N=512) in interpret mode.
    key = jax.random.key(2)
    w = jax.random.normal(key, (512, 512), jnp.float32)
    q4 = pack_int4(w)
    x = jax.random.normal(jax.random.key(3), (48, 512), jnp.float32)
    ref = x @ unpack_int4(q4).astype(x.dtype)
    out = w4_matmul(x, q4, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-2, atol=2e-2)


def test_kernel_multiblock_grid():
    # Multiple row/N/K blocks: K=1024 (one block of 8 groups), N=768 (128-col
    # blocks x6), rows spanning two row blocks when block_rows is small.
    w = jax.random.normal(jax.random.key(4), (1024, 768), jnp.float32)
    q4 = pack_int4(w)
    x = jax.random.normal(jax.random.key(5), (40, 1024), jnp.float32)
    ref = x @ unpack_int4(q4).astype(x.dtype)
    out = w4_matmul(x, q4, block_rows=16, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-2, atol=2e-2)


def test_kernel_bf16_activations():
    w = jax.random.normal(jax.random.key(6), (512, 512), jnp.float32)
    q4 = pack_int4(w)
    x = jax.random.normal(jax.random.key(7), (16, 512), jnp.bfloat16)
    ref = (x.astype(jnp.float32) @ unpack_int4(q4)).astype(jnp.bfloat16)
    out = w4_matmul(x, q4, interpret=True)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32), rtol=5e-2, atol=5e-2
    )


def test_qdot_dispatches_q4():
    from k_llms_tpu.models.quant import qdot

    w = jax.random.normal(jax.random.key(8), (256, 256), jnp.float32)
    q4 = pack_int4(w)
    x = jax.random.normal(jax.random.key(9), (2, 3, 256), jnp.float32)
    out = qdot(x, q4)
    assert out.shape == (2, 3, 256)
    ref = x @ unpack_int4(q4)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-2, atol=2e-2)


def test_supports_int4_gate():
    assert supports_int4(256) and supports_int4(4096)
    assert not supports_int4(128) and not supports_int4(320)


def test_quantize_params_bits4_mixed_tree():
    """bits=4 packs eligible weights Q4 and falls back int8 for the rest."""
    from k_llms_tpu.models import get_config, init_params
    from k_llms_tpu.models.quant import QTensor, quantize_params

    cfg = get_config("tiny").with_(
        hidden_size=256, intermediate_size=512, num_layers=2, vocab_size=384
    )
    params = init_params(cfg, jax.random.key(0))
    qp = quantize_params(params, bits=4)
    assert isinstance(qp["layers"]["w_gate"], Q4Tensor)  # 256 -> 512
    assert isinstance(qp["layers"]["w_down"], Q4Tensor)  # 512 -> 256
    assert isinstance(qp["lm_head"], Q4Tensor)  # 256 -> 384
    # wk: K=256 eligible, N=kv_dim may not be 128-divisible on tiny; just check
    # the tree is fully quantized one way or the other.
    for name in ("wq", "wk", "wv", "wo"):
        assert isinstance(qp["layers"][name], (Q4Tensor, QTensor))


def test_int4_generate_end_to_end():
    """A small-but-eligible model generates through the full engine with
    quantize="int4" (CPU: XLA fallback inside w4_matmul for tiny shapes,
    interpret-mode kernel for eligible ones)."""
    from k_llms_tpu.engine.engine import LocalEngine
    from k_llms_tpu.models import get_config

    cfg = get_config("tiny").with_(
        hidden_size=256,
        intermediate_size=512,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        head_dim=64,
        vocab_size=384,
        max_seq_len=128,
    )
    from conftest import shared_engine

    eng = shared_engine(cfg, quantize="int4")
    assert eng.quantized == "int4"
    res = eng.generate([5, 6, 7], n=2, max_new_tokens=4, temperature=0.7, seed=11)
    assert res.tokens.shape == (2, 4)
    assert (res.tokens < 384).all()


def test_int4_orbax_roundtrip(tmp_path):
    """Q4Tensor leaves survive an orbax save/restore (rebuilt by scale shape)."""
    from k_llms_tpu.models.loader import load_orbax, save_checkpoint

    w = jax.random.normal(jax.random.key(10), (256, 128), jnp.float32)
    q4 = pack_int4(w)
    tree = {"layers": {"w_up": q4}, "note": jnp.ones((2,))}
    path = str(tmp_path / "ckpt")
    save_checkpoint(path, tree)
    restored = load_orbax(path)
    assert isinstance(restored["layers"]["w_up"], Q4Tensor)
    np.testing.assert_array_equal(
        np.asarray(restored["layers"]["w_up"].q), np.asarray(q4.q)
    )


def test_quantize_params_passes_through_quantized_leaves():
    """Serving a quantized checkpoint with the quantization flag still set
    must keep the stored leaves, not crash or re-quantize the lossy payload."""
    from k_llms_tpu.models import get_config, init_params
    from k_llms_tpu.models.quant import QTensor, quantize_params

    cfg = get_config("tiny").with_(
        hidden_size=256, intermediate_size=512, num_layers=2, vocab_size=384
    )
    q4_tree = quantize_params(init_params(cfg, jax.random.key(0)), bits=4)
    for bits in (4, 8):
        again = quantize_params(q4_tree, bits=bits)
        assert again["layers"]["w_gate"] is q4_tree["layers"]["w_gate"]
        assert isinstance(again["lm_head"], Q4Tensor)
    q8_tree = quantize_params(init_params(cfg, jax.random.key(0)), bits=8)
    again8 = quantize_params(q8_tree, bits=4)
    assert isinstance(again8["layers"]["w_gate"], QTensor)


def test_init_params_quantized_bits4_shapes():
    from k_llms_tpu.models import get_config
    from k_llms_tpu.models.quant import init_params_quantized

    cfg = get_config("tiny").with_(
        hidden_size=256, intermediate_size=512, num_layers=2, vocab_size=384
    )
    params = init_params_quantized(cfg, jax.random.key(0), bits=4)
    gate = params["layers"]["w_gate"]
    assert isinstance(gate, Q4Tensor)
    assert gate.q.shape == (2, 128, 512)
    assert gate.scale.shape == (2, 2, 512)


# -- int4 under tensor parallelism (VERDICT r2 #7) ---------------------------

def _int4_cfg():
    from k_llms_tpu.models import get_config

    return get_config("tiny").with_(
        hidden_size=256,
        intermediate_size=512,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        head_dim=64,
        vocab_size=384,
        max_seq_len=128,
    )


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs the 8-device CPU mesh")
def test_int4_on_mesh_bitcompares_single_chip():
    """quantization="int4" survives a data=4 x model=2 mesh (shard_mapped
    w4a16) and produces the single-chip engine's exact tokens/logprobs."""
    from conftest import shared_engine

    cfg = _int4_cfg()
    solo = shared_engine(cfg, param_key=4, quantize="int4")
    tp = shared_engine(cfg, param_key=4, mesh_shape=(4, 2), quantize="int4")
    assert tp.quantized == "int4"  # no silent int8 downgrade any more
    assert tp.params["layers"]["wo"].part == "row"
    assert tp.params["layers"]["wq"].part == "col"

    prompt = [5, 6, 7, 8, 9]
    kw = dict(n=4, max_new_tokens=6, temperature=0.0, seed=3)
    r_solo = solo.generate(prompt, **kw)
    r_tp = tp.generate(prompt, **kw)
    np.testing.assert_array_equal(r_tp.tokens, r_solo.tokens)
    np.testing.assert_allclose(r_tp.logprobs, r_solo.logprobs, rtol=1e-4, atol=1e-4)

    # sampled path too (same seed stream on both engines)
    kw = dict(n=4, max_new_tokens=4, temperature=0.9, seed=17)
    np.testing.assert_array_equal(
        tp.generate(prompt, **kw).tokens, solo.generate(prompt, **kw).tokens
    )


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs the 8-device CPU mesh")
def test_int4_downgrades_when_groups_would_split():
    """tp=4 over a K=256 row-parallel weight would split a quantization group
    (needs K % (128*4) == 0) — the engine must fall back to int8, loudly."""
    from k_llms_tpu.engine.engine import LocalEngine
    from k_llms_tpu.models.quant import int4_mesh_compatible
    from k_llms_tpu.parallel.mesh import make_mesh

    cfg = _int4_cfg()
    assert int4_mesh_compatible(cfg, 2)
    assert not int4_mesh_compatible(cfg, 4)
    eng = LocalEngine(cfg, mesh=make_mesh(2, 4), quantize="int4")
    assert eng.quantized == "int8"


def test_int4_fmt_marker_roundtrip(tmp_path):
    """Checkpoints record the quantized layout explicitly (fmt leaf) instead
    of relying on the scale-shape heuristic (ADVICE r2)."""
    from k_llms_tpu.models import init_params
    from k_llms_tpu.models.loader import load_orbax, save_checkpoint
    from k_llms_tpu.models.quant import QTensor, quantize_params

    # intermediate_size=384: w_down has K=384 (not a 256 multiple), so the
    # tree is a GENUINE int4/int8 mix — both fmt branches get exercised.
    cfg = _int4_cfg().with_(intermediate_size=384)
    qp = quantize_params(init_params(cfg, jax.random.key(1)), bits=4)
    assert isinstance(qp["layers"]["w_down"], QTensor)
    path = str(tmp_path / "ckpt4")
    save_checkpoint(path, qp)
    restored = load_orbax(path)
    assert isinstance(restored["layers"]["w_gate"], Q4Tensor)
    assert isinstance(restored["lm_head"], Q4Tensor)
    assert isinstance(restored["layers"]["w_down"], QTensor)  # fmt=8 branch
    np.testing.assert_array_equal(
        np.asarray(restored["layers"]["w_gate"].q),
        np.asarray(qp["layers"]["w_gate"].q),
    )
    np.testing.assert_array_equal(
        np.asarray(restored["layers"]["w_down"].q),
        np.asarray(qp["layers"]["w_down"].q),
    )


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs the 8-device CPU mesh")
def test_prequantized_checkpoint_layout_survives_mesh_init():
    """A pre-quantized tree keeps its STORED layout through engine init on a
    mesh even when the requested bits differ (quantize_weight_bits documents
    layout preservation): int8 tree + quantize="int4" must not crash on a
    spec-structure mismatch, and generation still works."""
    from k_llms_tpu.engine.engine import LocalEngine
    from k_llms_tpu.models import init_params
    from k_llms_tpu.models.quant import QTensor, quantize_params
    from k_llms_tpu.parallel.mesh import make_mesh

    cfg = _int4_cfg()
    int8_tree = quantize_params(init_params(cfg, jax.random.key(6)), bits=8)
    mesh = make_mesh(4, 2)
    eng = LocalEngine(cfg, params=int8_tree, mesh=mesh, quantize="int4")
    assert isinstance(eng.params["layers"]["w_gate"], QTensor)  # stored layout kept
    r = eng.generate([5, 6, 7], n=4, max_new_tokens=3, temperature=0.5, seed=2)
    assert r.tokens.shape == (4, 3)

    # And the inverse: stored int4 + requested int8 on a COMPATIBLE mesh keeps
    # int4 leaves and marks them for the sharded kernel.
    int4_tree = quantize_params(init_params(cfg, jax.random.key(7)), bits=4)
    eng2 = LocalEngine(cfg, params=int4_tree, mesh=mesh, quantize="int8")
    assert eng2.params["layers"]["w_gate"].part == "col"
    r2 = eng2.generate([5, 6, 7], n=4, max_new_tokens=3, temperature=0.5, seed=2)
    assert r2.tokens.shape == (4, 3)


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs the 8-device CPU mesh")
def test_stored_int4_incompatible_mesh_raises_before_pjit():
    """A pre-quantized int4 tree whose groups cannot shard over the model axis
    must fail with the clear ValueError BEFORE the sharded quantize/put (which
    would otherwise die inside pjit with an opaque sharding error)."""
    from k_llms_tpu.engine.engine import LocalEngine
    from k_llms_tpu.models import init_params
    from k_llms_tpu.models.quant import quantize_params
    from k_llms_tpu.parallel.mesh import make_mesh

    cfg = _int4_cfg()  # K=256 row weights: groups split at tp=4
    int4_tree = quantize_params(init_params(cfg, jax.random.key(9)), bits=4)
    with pytest.raises(ValueError, match="re-quantize to int8 or change the mesh"):
        LocalEngine(cfg, params=int4_tree, mesh=make_mesh(2, 4), quantize="int4")
    with pytest.raises(ValueError, match="re-quantize to int8 or change the mesh"):
        LocalEngine(cfg, params=int4_tree, mesh=make_mesh(2, 4), quantize="int8")
