"""Paged KV cache vs dense: the greedy differential.

The paged layout (engine/paging.py + the paged twins in models/llama.py) is
an OPTIMIZATION, not a semantic change — every test here pins byte-identical
tokens and logprobs between a paged engine/loop and its dense twin on equal
inputs: batch-path prefix-cache continuations, the continuous loop's steady
decode, a request that JOINS mid-flight, and the post-abort survivors. The
page machinery (n-way prompt sharing, copy-on-write at the first divergent
token, reserve-at-admission) must be invisible in the outputs.
"""

import numpy as np
import pytest

from k_llms_tpu.engine.continuous import ContinuousDecodeLoop
from k_llms_tpu.engine.engine import LocalEngine
from k_llms_tpu.models import get_config
from k_llms_tpu.reliability.deadline import RequestBudget
from k_llms_tpu.types.wire import RequestCancelledError

PAGE = 8  # small pages so tiny prompts still span/split several


@pytest.fixture(scope="module")
def engines():
    from conftest import shared_engine

    dense = shared_engine(model="tiny")
    paged = shared_engine(model="tiny", kv_layout="paged", kv_page_size=PAGE)
    return dense, paged


@pytest.fixture(scope="module")
def loops(engines):
    dense_eng, paged_eng = engines
    kw = dict(width=4, max_prompt=64, max_new=16)
    dense = ContinuousDecodeLoop(dense_eng, **kw)
    paged = ContinuousDecodeLoop(paged_eng, **kw)
    assert not dense.paged and paged.paged
    yield dense, paged
    dense.stop()
    paged.stop()


def _both(loops, prompt, **kw):
    dense, paged = loops
    fd = dense.submit(prompt, **kw)
    fp = paged.submit(prompt, **kw)
    return fd.result(timeout=180), fp.result(timeout=180)


def _assert_identical(rd, rp):
    np.testing.assert_array_equal(rd.tokens, rp.tokens)
    np.testing.assert_array_equal(rd.logprobs, rp.logprobs)
    np.testing.assert_array_equal(rd.lengths, rp.lengths)
    assert rd.finish_reasons == rp.finish_reasons


def test_greedy_partial_page_fanout(loops):
    """n=3 fan-out from a prompt that ends MID-page: all three rows' first
    generated token lands in the shared partial page, forcing copy-on-write —
    and the outputs must still match dense bit for bit."""
    rd, rp = _both(
        loops, [5, 6, 7, 8, 9, 10, 11],  # 7 tokens: page 0 is partial
        n=3, max_new=12, temperature=0.0, top_p=None, seed=17,
    )
    _assert_identical(rd, rp)
    pool = loops[1]._pool
    assert pool.allocator.stats["cow_copies"] >= 2  # n-1 rows must copy


def test_greedy_page_boundary_fanout(loops):
    """Prompt length an exact page multiple: no partial page, first writes go
    to fresh extension pages (the no-CoW branch)."""
    rd, rp = _both(
        loops, list(range(5, 5 + 2 * PAGE)),  # exactly 2 pages
        n=2, max_new=10, temperature=0.0, top_p=None, seed=23,
    )
    _assert_identical(rd, rp)


def test_sampled_identical(loops):
    """Sampling keys derive from (seed, step, sample_idx) only, so the paged
    loop must reproduce the dense loop's sampled stream exactly."""
    rd, rp = _both(
        loops, [1, 2, 3, 4], n=2, max_new=10,
        temperature=0.8, top_p=0.9, seed=3,
    )
    _assert_identical(rd, rp)


def test_midflight_join_identical(loops):
    """A request joining a decode already in flight must come out identical
    on both layouts (and the paged join must not disturb the first request's
    pages — its rows keep decoding through the same block tables)."""
    results = {}
    for name, loop in zip(("dense", "paged"), loops):
        holder = {}

        def sink(step, _toks, loop=loop, holder=holder):
            if step == 0 and "b" not in holder:
                holder["b"] = loop.submit(
                    [4, 5, 6], n=2, max_new=6, temperature=0.7, top_p=0.95,
                    seed=12,
                )

        a = loop.submit(
            [1, 2, 3], n=2, max_new=14, temperature=0.7, top_p=0.95, seed=11,
            token_sink=sink,
        ).result(timeout=180)
        b = holder["b"].result(timeout=180)
        results[name] = (a, b)
        assert loop.stats["joined_in_flight"] >= 1
    _assert_identical(results["dense"][0], results["paged"][0])
    _assert_identical(results["dense"][1], results["paged"][1])


def test_budget_abort_releases_pages_and_survivors_match(loops):
    """Cancel a paged request mid-flight: its rows' pages must return to the
    pool (conservation checked by the stats property), and a follow-up
    request decodes identically to dense."""
    dense, paged = loops
    budget = RequestBudget()
    fut = paged.submit(
        [9, 8, 7, 6, 5], n=2, max_new=16, temperature=0.9, top_p=0.9, seed=5,
        budget=budget,
    )
    import time

    time.sleep(0.02)
    budget.cancel()
    with pytest.raises(RequestCancelledError):
        fut.result(timeout=180)
    rd, rp = _both(
        loops, [2, 4, 6, 8], n=2, max_new=8, temperature=0.0, top_p=None,
        seed=9,
    )
    _assert_identical(rd, rp)


def test_drain_leaves_zero_loop_refs(loops):
    """After quiescing, the loop holds no page references and the pool's
    accounting invariants verify clean (stats runs PageAllocator.verify)."""
    dense, paged = loops
    assert paged.drain(timeout=60)
    s = paged.stats
    assert s["pages"]["loop_refs"] == 0
    # The module's engines run without a prefix cache, so nothing else may
    # hold pages either: every page is back on the free stack.
    assert s["pages"]["in_use"] == 0
    paged._closing = False  # reopen for any later tests in this module


# -- batch path: prefix-cache entries as page runs --------------------------


@pytest.fixture(scope="module")
def cached_engines():
    from conftest import shared_params

    cfg = get_config("tiny")
    params = shared_params(cfg, 0)
    plain = LocalEngine(cfg, params=params, use_mesh=False)
    kw = dict(prefix_cache_size=4, prefix_cache_min_reuse=16)
    dense = LocalEngine(cfg, params=params, use_mesh=False, **kw)
    paged = LocalEngine(
        cfg, params=params, use_mesh=False,
        kv_layout="paged", kv_page_size=PAGE, **kw,
    )
    return plain, dense, paged


SYSTEM = [(i * 37) % 200 + 5 for i in range(40)]
DOC_A = [(i * 11) % 190 + 7 for i in range(20)]
DOC_B = [(i * 13) % 180 + 9 for i in range(25)]


def test_batch_exact_hit_serves_from_pages(cached_engines):
    plain, dense, paged = cached_engines
    prompt = SYSTEM + DOC_A
    kw = dict(n=2, max_new_tokens=4, temperature=0.7, seed=5)
    r1 = paged.generate(prompt, **kw)
    assert paged.prefix_cache_stats["misses"] == 1
    r2 = paged.generate(prompt, **kw)  # exact hit: materialized from pages
    assert paged.prefix_cache_stats["hits"] == 1
    np.testing.assert_array_equal(r1.tokens, r2.tokens)
    ref = plain.generate(prompt, **kw)
    np.testing.assert_array_equal(r1.tokens, ref.tokens)
    # The entry holds real pool pages.
    assert paged._kv_pool is not None
    assert paged._kv_pool.allocator.in_use_pages > 0


def test_batch_continuation_shares_prefix_pages(cached_engines):
    """Second document extends the cached system prefix: the paged entry for
    SYSTEM+DOC_B must SHARE the matched run's full pages (refcount > 1, no
    copy) and still generate byte-identically to dense and uncached."""
    plain, dense, paged = cached_engines
    kw1 = dict(n=2, max_new_tokens=4, temperature=0.7, seed=7)
    kw2 = dict(n=2, max_new_tokens=4, temperature=0.7, seed=8)
    for eng in (dense, paged):
        eng.generate(SYSTEM + DOC_A, **kw1)
    r_paged = paged.generate(SYSTEM + DOC_B, **kw2)
    assert paged.prefix_cache_stats["partial_hits"] >= 1
    r_dense = dense.generate(SYSTEM + DOC_B, **kw2)
    r_plain = plain.generate(SYSTEM + DOC_B, **kw2)
    np.testing.assert_array_equal(r_paged.tokens, r_dense.tokens)
    np.testing.assert_array_equal(r_paged.tokens, r_plain.tokens)
    np.testing.assert_array_equal(r_paged.logprobs, r_dense.logprobs)
    # Shared full pages of the common prefix: at least one page is held by
    # both entries.
    assert paged._kv_pool.allocator.shared_pages > 0
