"""Replica-set serving (PR 5): health-aware routing, mid-flight failover,
hedged dispatch, and honest degradation.

Hermetic units run over FakeBackend members (routing scores, probation +
probe rejoin, seed pinning across failover, bounded failover, 429 scaling,
hedging, the typed no-healthy-replicas 503, and the resolve_backend
satellite). The ISSUE acceptance scenarios run on real CPU engines: the
greedy differential proves a failed-over request is byte-identical to an
uninterrupted run on the healthy member, and the hedge-cancel test proves the
losing launch dies through the engine's abort poller without ever touching a
circuit breaker. The slow-tagged chaos soak flaps one of three members
(down + hang via the keyed ``replica.dispatch`` / ``replica.probe``
failpoints) under concurrent traffic."""

import dataclasses
import threading
import time

import pytest

from k_llms_tpu import KLLMs
from k_llms_tpu.analysis import lockcheck
from k_llms_tpu.backends.base import (
    Backend,
    ChatRequest,
    UnknownBackendError,
    resolve_backend,
)
from k_llms_tpu.backends.fake import FakeBackend
from k_llms_tpu.reliability import failpoints as fp
from k_llms_tpu.reliability.failpoints import FailSpec
from k_llms_tpu.reliability.replicas import ReplicaSet
from k_llms_tpu.types.wire import (
    EngineHungError,
    KLLMsError,
    NoHealthyReplicasError,
    RateLimitError,
)
from k_llms_tpu.utils.observability import (
    FAILOVER_EVENTS,
    FAILURE_EVENTS,
    HEDGE_EVENTS,
    ROUTE_EVENTS,
)


def _req(content="hi", n=1, seed=None, **kw):
    return ChatRequest(
        messages=[{"role": "user", "content": content}], model="fake", n=n, seed=seed, **kw
    )


def _shutdown(rs):
    """Release the set's executor without closing member backends (tests often
    share members across several ReplicaSets)."""
    rs._executor.shutdown(wait=False)


# -- resolve_backend satellite ---------------------------------------------


def test_resolve_backend_unknown_name_is_typed_and_actionable():
    with pytest.raises(UnknownBackendError) as ei:
        resolve_backend("warp-drive")
    assert isinstance(ei.value, ValueError)  # pre-existing callers catch this
    assert ei.value.backend == "warp-drive"
    assert "replicas" in ei.value.known and "tpu" in ei.value.known
    msg = str(ei.value)
    assert "warp-drive" in msg and "'fake'" in msg and "Backend instance" in msg


def test_resolve_backend_rejects_non_string_non_backend():
    with pytest.raises(UnknownBackendError):
        resolve_backend(42)  # type: ignore[arg-type]


def test_resolve_backend_normalizes_names():
    assert isinstance(resolve_backend("  FAKE "), FakeBackend)
    assert isinstance(resolve_backend("Fake"), FakeBackend)
    rs = resolve_backend("ReplicaSet", members=[FakeBackend(["a"])], model="fake")
    assert isinstance(rs, ReplicaSet)
    _shutdown(rs)
    rs = resolve_backend("replica_set", members=[FakeBackend(["a"])], model="fake")
    assert isinstance(rs, ReplicaSet)
    _shutdown(rs)


def test_resolve_backend_passes_instances_through():
    b = FakeBackend(["x"])
    assert resolve_backend(b) is b
    rs = ReplicaSet(members=[b], model="fake")
    assert resolve_backend(rs) is rs  # a ReplicaSet IS a Backend
    _shutdown(rs)


# -- construction ----------------------------------------------------------


def test_replicaset_requires_members_and_unique_ids():
    with pytest.raises(ValueError, match="at least one member"):
        ReplicaSet(members=[])
    with pytest.raises(ValueError, match="duplicate replica ids"):
        ReplicaSet(
            members=[
                {"backend": "fake", "id": "a"},
                {"backend": "fake", "id": "a"},
            ]
        )
    with pytest.raises(ValueError, match="route_policy"):
        ReplicaSet(members=[FakeBackend()], route_policy="random")
    with pytest.raises(TypeError, match="member 0"):
        ReplicaSet(members=[object()])  # type: ignore[list-item]


def test_replicaset_member_specs_and_ids():
    rs = ReplicaSet(
        members=["fake", {"backend": "fake", "id": "east"}, FakeBackend(["z"])],
        model="fake",
        hedge=False,
    )
    assert sorted(rs.stats()) == ["east", "r0", "r2"]
    assert rs.model_name == "fake"
    _shutdown(rs)


# -- routing ---------------------------------------------------------------


def test_routing_prefers_lower_latency_member():
    rs = ReplicaSet(
        members=[FakeBackend(["slow"]), FakeBackend(["fast"])], model="fake", hedge=False
    )
    # Seed the EWMAs directly: r0 is 10x slower than r1.
    rs._by_id["r0"].note_success(0.5)
    rs._by_id["r1"].note_success(0.05)
    out = rs.dispatch_chat_completion(_req())
    assert out.choices[0].message.content == "fast"
    assert rs.stats()["r1"]["dispatched"] == 2
    _shutdown(rs)


def test_routing_skips_open_breaker_and_rejoins_via_probe_success():
    rs = ReplicaSet(members=[FakeBackend(["a"]), FakeBackend(["b"])], model="fake", hedge=False)
    breaker = rs._by_id["r0"].backend.circuit_breaker
    for _ in range(breaker.failure_threshold):
        breaker.record_failure()
    assert breaker.state == "open"
    out = rs.dispatch_chat_completion(_req())
    assert out.choices[0].message.content == "b"
    # The probe is also the breaker's recovery path: a passing probe records
    # a breaker success, so the circuit closes off synthetic traffic.
    assert rs.probe("r0") is True
    assert breaker.state == "closed"
    out = rs.dispatch_chat_completion(_req())
    assert out.choices[0].message.content in ("a", "b")
    _shutdown(rs)


class _StatefulHealthBackend(FakeBackend):
    """FakeBackend whose health() state is test-controlled (simulates a
    member whose PR-4 supervisor is rebuilding it)."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.state = "ready"

    def health(self):
        snap = super().health()
        snap["state"] = self.state
        return snap


def test_recovering_member_leaves_rotation_and_rejoins_only_after_probe():
    sick = _StatefulHealthBackend(["sick"])
    rs = ReplicaSet(
        members=[sick, FakeBackend(["ok"])],
        model="fake",
        hedge=False,
        probe_interval_s=float("inf"),  # no background probes: rejoin is explicit
    )
    sick.state = "recovering"
    before = ROUTE_EVENTS.get("route.pulled")
    out = rs.dispatch_chat_completion(_req())
    assert out.choices[0].message.content == "ok"
    assert ROUTE_EVENTS.get("route.pulled") == before + 1
    snap = rs.health()
    assert snap["state"] == "degraded"
    assert snap["replicas"]["r0"]["state"] == "out_of_rotation"
    assert "recovering" in snap["replicas"]["r0"]["out_reason"]
    # Backend healthy again — but rotation membership is gated on the probe,
    # not on time passing.
    sick.state = "ready"
    assert rs.health()["replicas"]["r0"]["state"] == "out_of_rotation"
    # A probe against a still-recovering backend fails and keeps it out.
    sick.state = "recovering"
    assert rs.probe("r0") is False
    assert rs.health()["replicas"]["r0"]["in_rotation"] is False
    sick.state = "ready"
    assert rs.probe("r0") is True
    assert rs.health()["state"] == "ready"
    assert rs.health()["replicas"]["r0"]["in_rotation"] is True
    _shutdown(rs)


def test_probe_failpoint_keeps_member_out_of_rotation():
    rs = ReplicaSet(
        members=[FakeBackend(["a"]), FakeBackend(["b"])],
        model="fake",
        hedge=False,
        probe_interval_s=float("inf"),  # probes are explicit: the failpoint budget is ours
    )
    before_pf = ROUTE_EVENTS.get("route.probe_failures")
    with fp.failpoints(
        {
            "replica.dispatch": FailSpec(action="down", member="r0", times=1),
            "replica.probe": FailSpec(action="fail", member="r0", times=1),
        }
    ):
        out = rs.dispatch_chat_completion(_req())
        assert out.choices[0].message.content == "b"
        assert rs.probe("r0") is False  # consumes the probe failpoint
        assert rs.health()["replicas"]["r0"]["in_rotation"] is False
        assert rs.health()["replicas"]["r0"]["probe_failures"] >= 1
    assert ROUTE_EVENTS.get("route.probe_failures") >= before_pf + 1
    assert rs.probe("r0") is True  # spec exhausted: probe passes, member rejoins
    assert rs.health()["state"] == "ready"
    _shutdown(rs)


# -- failover --------------------------------------------------------------


def test_failover_pins_seed_so_retry_is_identical_input():
    seen = []

    def recorder(tag):
        def responder(request):
            seen.append((tag, request.seed))
            return ["resp"] * max(1, request.n)

        return responder

    rs = ReplicaSet(
        members=[FakeBackend(recorder("r0")), FakeBackend(recorder("r1"))],
        model="fake",
        hedge=False,
        probe_interval_s=float("inf"),
    )
    with fp.failpoints({"replica.dispatch": FailSpec(action="down", member="r0", times=1)}):
        rs.dispatch_chat_completion(_req(seed=None))
    # r0's attempt died at the failpoint (before its responder ran); the
    # failover attempt carries a pinned, non-None seed.
    assert len(seen) == 1 and seen[0][0] == "r1"
    assert seen[0][1] is not None
    # With the caller's own seed, the same seed reaches the survivor.
    seen.clear()
    rs._by_id["r0"].rejoin()
    with fp.failpoints({"replica.dispatch": FailSpec(action="down", member="r0", times=1)}):
        rs.dispatch_chat_completion(_req(seed=777))
    assert seen == [("r1", 777)]
    _shutdown(rs)


def test_failover_is_bounded_and_exhaustion_propagates():
    rs = ReplicaSet(
        members=[FakeBackend(["a"]), FakeBackend(["b"]), FakeBackend(["c"])],
        model="fake",
        hedge=False,
        max_failover_attempts=1,
        probe_interval_s=float("inf"),
    )
    before = FAILOVER_EVENTS.get("failover.exhausted")
    with fp.failpoints({"replica.dispatch": FailSpec(action="down")}):  # every member
        with pytest.raises(EngineHungError):
            rs.dispatch_chat_completion(_req())
    assert FAILOVER_EVENTS.get("failover.exhausted") == before + 1
    # Primary + exactly one failover attempt: only two members were tried.
    assert sum(1 for s in rs.stats().values() if not s["in_rotation"]) == 2
    _shutdown(rs)


def test_caller_errors_never_fail_over():
    def bad_request(request):
        raise ValueError("caller bug")

    rs = ReplicaSet(
        members=[FakeBackend(bad_request), FakeBackend(["never"])],
        model="fake",
        hedge=False,
    )
    with pytest.raises(ValueError, match="caller bug"):
        rs.dispatch_chat_completion(_req())
    # The member is NOT blamed for the caller's bug.
    assert rs.health()["replicas"]["r0"]["in_rotation"] is True
    assert rs.stats()["r1"]["dispatched"] == 0
    _shutdown(rs)


def test_all_members_shedding_scales_retry_after():
    def shed(request):
        raise RateLimitError("queue full", retry_after=2.0)

    rs = ReplicaSet(
        members=[FakeBackend(shed), FakeBackend(shed), FakeBackend(["ok"])],
        model="fake",
        hedge=False,
        probe_interval_s=float("inf"),
    )
    # One healthy member left: sheds from the other two route around them.
    rs._by_id["r2"].mark_down("test: simulate lost capacity")
    with pytest.raises(RateLimitError) as ei:
        rs.dispatch_chat_completion(_req())
    # 429s are load signals: nobody leaves rotation over them...
    assert rs.health()["replicas"]["r0"]["in_rotation"] is True
    assert rs.health()["replicas"]["r1"]["in_rotation"] is True
    # ...and retry_after is scaled by total/healthy (3/2 here) so callers back
    # off proportionally to the capacity actually lost.
    assert ei.value.retry_after == pytest.approx(2.0 * 3 / 2)
    _shutdown(rs)


def test_zero_healthy_members_is_typed_503_with_reasons():
    rs = ReplicaSet(
        members=[FakeBackend(["a"]), FakeBackend(["b"])],
        model="fake",
        hedge=False,
        max_failover_attempts=5,
        probe_interval_s=float("inf"),
    )
    before = ROUTE_EVENTS.get("route.no_healthy")
    with fp.failpoints(
        {
            "replica.dispatch": FailSpec(action="down", times=2),
            "replica.probe": FailSpec(action="fail"),
        }
    ):
        with pytest.raises(NoHealthyReplicasError) as ei:
            rs.dispatch_chat_completion(_req())
        assert rs.health()["state"] == "unavailable"
    err = ei.value
    assert err.status_code == 503
    assert sorted(err.reasons) == ["r0", "r1"]
    assert all("EngineHungError" in why for why in err.reasons.values())
    assert err.as_wire()["error"]["code"] == "no_healthy_replicas"
    assert err.as_wire()["error"]["replicas"] == err.reasons
    assert ROUTE_EVENTS.get("route.no_healthy") >= before + 1
    _shutdown(rs)


# -- hedging ---------------------------------------------------------------


def test_hedge_rescues_tail_and_cancels_loser():
    rs = ReplicaSet(
        members=[FakeBackend(["slowpoke"]), FakeBackend(["rescue"])],
        model="fake",
        hedge=True,
        hedge_delay_s=0.03,
        route_policy="round_robin",
        probe_interval_s=float("inf"),
    )
    before = HEDGE_EVENTS.snapshot()
    with fp.failpoints(
        {"replica.dispatch": FailSpec(action="sleep", member="r0", delay=0.5)}
    ):
        t0 = time.perf_counter()
        out = rs.dispatch_chat_completion(_req())
        elapsed = time.perf_counter() - t0
    assert out.choices[0].message.content == "rescue"
    assert elapsed < 0.4  # did not wait out the slow member
    after = HEDGE_EVENTS.snapshot()
    assert after.get("hedge.launched", 0) == before.get("hedge.launched", 0) + 1
    assert after.get("hedge.won_hedge", 0) == before.get("hedge.won_hedge", 0) + 1
    assert (
        after.get("hedge.cancelled_losers", 0)
        == before.get("hedge.cancelled_losers", 0) + 1
    )
    assert rs.stats()["r1"]["hedges_won"] == 1
    # Hedge losses are not health signals: the slow member keeps its place.
    assert rs.health()["replicas"]["r0"]["in_rotation"] is True
    assert rs._by_id["r0"].backend.circuit_breaker.state == "closed"
    _shutdown(rs)


def test_fast_primary_never_hedges():
    rs = ReplicaSet(
        members=[FakeBackend(["a"]), FakeBackend(["b"])],
        model="fake",
        hedge=True,
        hedge_delay_s=0.5,
    )
    before = HEDGE_EVENTS.get("hedge.launched")
    for _ in range(5):
        rs.dispatch_chat_completion(_req())
    assert HEDGE_EVENTS.get("hedge.launched") == before
    _shutdown(rs)


def test_hedge_failure_never_counts_against_breakers():
    """A hedge that FAILS (not just loses) must not touch the hedge member's
    circuit breaker — hedges call the raw chat_completion."""
    failures = {"n": 0}

    def flaky_hedge(request):
        failures["n"] += 1
        raise RuntimeError("hedge member exploded")

    rs2 = ReplicaSet(
        members=[FakeBackend(["primary"]), FakeBackend(flaky_hedge)],
        model="fake",
        hedge=True,
        hedge_delay_s=0.02,
        probe_interval_s=float("inf"),
    )
    with fp.failpoints(
        {"replica.dispatch": FailSpec(action="sleep", member="r0", delay=0.2)}
    ):
        out = rs2.dispatch_chat_completion(_req())
    assert out.choices[0].message.content == "primary"  # primary still won
    assert failures["n"] == 1  # the hedge really ran and really failed
    assert rs2._by_id["r1"].backend.circuit_breaker.state == "closed"
    # The failed hedge is not a rotation event either.
    assert rs2.health()["replicas"]["r1"]["in_rotation"] is True
    _shutdown(rs2)


def test_hedge_skipped_without_latency_history():
    """Adaptive mode (no fixed hedge_delay_s): no p95 history means no tail
    to hedge against — the dispatch stays single."""
    rs = ReplicaSet(members=[FakeBackend(["a"]), FakeBackend(["b"])], model="fake", hedge=True)
    before = HEDGE_EVENTS.get("hedge.launched")
    rs.dispatch_chat_completion(_req())
    assert HEDGE_EVENTS.get("hedge.launched") == before
    # After enough samples the p95-derived delay kicks in.
    for _ in range(6):
        rs.dispatch_chat_completion(_req())
    assert rs._hedge_delay(rs._by_id["r0"]) is not None
    _shutdown(rs)


# -- Backend surface / observability ---------------------------------------


def test_stats_and_health_shapes():
    rs = ReplicaSet(members=[FakeBackend(["a"]), FakeBackend(["b"])], model="fake", hedge=False)
    rs.dispatch_chat_completion(_req())
    stats = rs.stats()
    for rid in ("r0", "r1"):
        for key in ("dispatched", "failed", "hedges_won", "ewma_ms", "state"):
            assert key in stats[rid], f"stats[{rid}] missing {key}"
    h = rs.health()
    assert h["members"] == 2 and h["healthy_members"] == 2
    assert h["state"] == "ready" and h["hedge"] is False
    assert set(h["replicas"]) == {"r0", "r1"}
    assert h["replicas"]["r0"]["dispatched"] + h["replicas"]["r1"]["dispatched"] == 1
    _shutdown(rs)


def test_embeddings_and_consensus_fail_over():
    class DeadEmbed(FakeBackend):
        def embeddings(self, texts):
            raise RuntimeError("embedding engine gone")

        def llm_consensus(self, values):
            raise RuntimeError("consensus engine gone")

    rs = ReplicaSet(
        members=[DeadEmbed(), FakeBackend(["x"])],
        model="fake",
        hedge=False,
        probe_interval_s=float("inf"),
    )
    vecs = rs.embeddings(["alpha", "beta"])
    assert len(vecs) == 2 and len(vecs[0]) == 64
    rs._by_id["r0"].rejoin()
    assert rs.llm_consensus(["x", "y", "x"]) == "x"
    _shutdown(rs)


def test_client_integration_over_replicas():
    """KLLMs(backend="replicas", members=[...]) is a drop-in: consensus-first
    choice layout, likelihoods, and health()["replicas"] all flow through."""
    client = KLLMs(
        backend="replicas",
        members=[FakeBackend(["yes", "yes", "no"]), FakeBackend(["yes", "yes", "no"])],
        model="fake",
        hedge=False,
    )
    resp = client.chat.completions.create(
        messages=[{"role": "user", "content": "vote"}], model="fake", n=3
    )
    assert len(resp.choices) == 4  # consensus + 3
    assert resp.choices[0].message.content == "yes"
    assert resp.likelihoods["text"] == pytest.approx(2 / 3, abs=1e-4)
    assert set(client.health()["replicas"]) == {"r0", "r1"}
    client.close()


# -- real-engine acceptance (CPU mesh) -------------------------------------


@pytest.fixture(scope="module")
def tpu_members():
    """Two independent tiny engines with identical weights (same default
    param seed): what a dp-sliced replica deployment looks like in tests."""
    from k_llms_tpu.backends.tpu import TpuBackend

    b0 = TpuBackend(model="tiny")
    b1 = TpuBackend(model="tiny")
    yield b0, b1
    b0.close()
    b1.close()


@pytest.mark.duration_budget(30)
def test_greedy_differential_failover_is_byte_identical(tpu_members):
    """ISSUE acceptance: a request whose first attempt dies on r0 and fails
    over returns byte-identical output (consensus, choices, likelihoods) to
    an uninterrupted run on the healthy member — seeds are pinned at the set
    level before the first attempt."""
    b0, b1 = tpu_members
    kwargs = dict(
        messages=[{"role": "user", "content": "differential"}],
        model="tiny",
        n=3,
        temperature=0.0,
        seed=11,
        max_tokens=10,
    )
    baseline_client = KLLMs(backend=b1, model="tiny")
    baseline = baseline_client.chat.completions.create(**kwargs)

    rs = ReplicaSet(members=[b0, b1], model="tiny", hedge=False)
    client = KLLMs(backend=rs, model="tiny")
    before = FAILOVER_EVENTS.get("failover.attempts")
    with fp.failpoints({"replica.dispatch": FailSpec(action="down", member="r0", times=1)}):
        failed_over = client.chat.completions.create(**kwargs)
    assert FAILOVER_EVENTS.get("failover.attempts") == before + 1
    assert rs.stats()["r1"]["failovers"] == 1

    assert [c.message.content for c in failed_over.choices] == [
        c.message.content for c in baseline.choices
    ]
    assert failed_over.choices[0].message.content  # consensus is non-empty
    assert failed_over.likelihoods == baseline.likelihoods
    assert failed_over.usage.completion_tokens == baseline.usage.completion_tokens
    # The failover also shows up in the member scheduler's stats tallies.
    assert b1.scheduler.stats["failovers"] >= 1
    assert b1.scheduler.health()["routed"] >= 1
    _shutdown(rs)


@pytest.mark.duration_budget(30)
def test_hedged_dispatch_cancels_loser_through_abort_poller(tpu_members):
    """ISSUE acceptance: the hedge winner's result returns while the loser is
    cancelled mid-decode through the engine's io_callback abort poller
    (engine.decode_abort increments), and neither member's circuit breaker
    records anything."""
    b0, b1 = tpu_members
    req = ChatRequest(
        messages=[{"role": "user", "content": "hedge race"}],
        model="tiny",
        n=1,
        temperature=0.0,
        seed=3,
        max_tokens=48,
    )
    # Warm both engines so the race below measures decode, not compilation.
    b0.chat_completion(dataclasses.replace(req))
    t0 = time.perf_counter()
    b1.chat_completion(dataclasses.replace(req))
    decode_s = time.perf_counter() - t0

    rs = ReplicaSet(members=[b0, b1], model="tiny", hedge=True, hedge_delay_s=0.05)
    # Delay r0's (primary) launch so it is mid-decode — started, unfinished —
    # when r1's hedge result lands: hedge_delay < sleep < hedge_delay + decode.
    sleep_s = 0.05 + decode_s / 2
    aborts_before = FAILURE_EVENTS.get("engine.decode_abort")
    hedge_before = HEDGE_EVENTS.get("hedge.won_hedge")
    with fp.failpoints(
        {"replica.dispatch": FailSpec(action="sleep", member="r0", delay=sleep_s)}
    ):
        out = rs.dispatch_chat_completion(dataclasses.replace(req))
    assert out.choices and out.choices[0].message.content
    assert HEDGE_EVENTS.get("hedge.won_hedge") == hedge_before + 1
    assert rs.stats()["r1"]["hedges_won"] == 1
    # The loser aborts at its next token boundary; give it a moment to land.
    deadline = time.monotonic() + 5.0
    while (
        FAILURE_EVENTS.get("engine.decode_abort") <= aborts_before
        and time.monotonic() < deadline
    ):
        time.sleep(0.05)
    assert FAILURE_EVENTS.get("engine.decode_abort") > aborts_before, (
        "losing hedge was not cancelled through the engine abort poller"
    )
    # Hedge cancellation is not a failure anywhere: breakers stay closed and
    # the loser keeps its rotation slot.
    assert b0.circuit_breaker.state == "closed"
    assert b1.circuit_breaker.state == "closed"
    assert rs.health()["replicas"]["r0"]["in_rotation"] is True
    assert b1.scheduler.stats["hedges_won"] >= 1
    _shutdown(rs)


# -- chaos soak ------------------------------------------------------------


@pytest.mark.slow
@pytest.mark.duration_budget(120)
def test_chaos_soak_flapping_member_under_concurrent_traffic(monkeypatch):
    """ISSUE acceptance: a 3-member set where r1 repeatedly dies (down) and
    wedges (hang-style sleep) while concurrent traffic flows. Every request
    resolves with a typed result or typed error, zero hung futures, failovers
    stay bounded, and the flapping member rejoins after a probe passes.

    Runs under KLLMS_LOCKCHECK=1 + KLLMS_RACECHECK=1: router + per-replica +
    breaker locks are instrumented and handle/router fields go through the
    lockset sanitizer; the soak must end with a clean lock-order graph and
    zero empty-lockset findings."""
    monkeypatch.setenv("KLLMS_LOCKCHECK", "1")
    monkeypatch.setenv("KLLMS_RACECHECK", "1")
    lockcheck.reset_state()
    members = [FakeBackend(["m0"]), FakeBackend(["m1"]), FakeBackend(["m2"])]
    rs = ReplicaSet(
        members=members,
        model="fake",
        hedge=True,
        hedge_delay_s=0.05,
        probe_interval_s=0.05,
        max_failover_attempts=2,
    )
    stop = threading.Event()
    results = []
    lock = threading.Lock()

    def worker(i):
        k = 0
        while not stop.is_set():
            k += 1
            try:
                out = rs.dispatch_chat_completion(_req(content=f"soak {i}-{k}"))
                kind = ("ok", out.choices[0].message.content)
            except KLLMsError as e:
                kind = ("typed", type(e).__name__)
            with lock:
                results.append(kind)
            time.sleep(0.005)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(6)]
    for t in threads:
        t.start()
    try:
        for round_no in range(4):
            # r1 dies hard: every dispatch to it errors for a while, probes
            # fail too, so it sits in probation while survivors serve.
            with fp.failpoints(
                {
                    "replica.dispatch": FailSpec(action="down", member="r1"),
                    "replica.probe": FailSpec(action="fail", member="r1"),
                }
            ):
                time.sleep(0.4)
            # r1 wedges (slow, not dead): hedging rescues its primaries.
            with fp.failpoints(
                {"replica.dispatch": FailSpec(action="sleep", member="r1", delay=0.3)}
            ):
                time.sleep(0.3)
            # Faults lifted: the next probe must bring r1 back.
            rs.probe("r1")
            assert rs.health()["replicas"]["r1"]["in_rotation"] is True, (
                f"round {round_no}: r1 did not rejoin after probe success"
            )
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30.0)
    # Zero hung futures: every worker retired.
    assert not any(t.is_alive() for t in threads)
    assert len(results) >= 50
    oks = [r for r in results if r[0] == "ok"]
    typed = [r for r in results if r[0] == "typed"]
    assert len(oks) >= len(results) * 0.5, "most traffic must survive the flapping"
    # Anything that failed, failed with a TYPED error (KLLMsError), by
    # construction of the worker — nothing leaked an untyped exception.
    assert len(oks) + len(typed) == len(results)
    # Failovers happened but stayed bounded: no retry storm relative to the
    # traffic actually served.
    stats = rs.stats()
    total_failovers = sum(s["failovers"] for s in stats.values())
    assert total_failovers <= len(results) * (rs.max_failover_attempts + 1)
    # Full health restored after the last probe.
    h = rs.health()
    assert h["state"] == "ready" and h["healthy_members"] == 3
    _shutdown(rs)
    lockcheck.assert_clean()
