"""Overload protection & graceful degradation (ISSUE 2 tentpole).

Fast deterministic coverage of the new control paths — bounded admission
(weight cap, priority eviction, drain-rate retry_after), the HBM memory
model, the engine's OOM split-and-requeue guard (driven by the
``engine.launch`` failpoint, no real device faults needed), and the
health/drain lifecycle — plus a slow-tagged chaos soak proving the
acceptance criteria: 4x sustained over-capacity with bounded queue weight,
zero hung futures, only typed wire errors, an injected RESOURCE_EXHAUSTED
recovered by group splitting, and a clean drain.
"""

import threading
import time

import pytest

from _duration_guard import check_items, enforce
from k_llms_tpu.analysis import lockcheck
from k_llms_tpu.backends.base import ChatRequest
from k_llms_tpu.backends.tpu import BackendConfig, HbmMemoryModel, TpuBackend
from k_llms_tpu.engine.engine import is_resource_exhausted
from k_llms_tpu.engine.scheduler import EngineScheduler, ServerState
from k_llms_tpu.models.config import get_config
from k_llms_tpu.reliability.failpoints import FailSpec, failpoints, fire
from k_llms_tpu.types import (
    BackendUnavailableError,
    KLLMsError,
    RateLimitError,
    ServerDrainingError,
)


def _echo(payloads):
    return list(payloads)


def _blocked_scheduler(**kwargs):
    """A scheduler whose worker is parked on an Event, so queued items stay
    queued until the test releases the gate."""
    sched = EngineScheduler(name="test", batch_window=0.0, **kwargs)
    gate = threading.Event()
    blocker = sched.submit(gate.wait)
    # Wait until the worker has actually dequeued the blocker; otherwise it
    # still occupies queue weight and admission tests race.
    for _ in range(200):
        if sched.stats["queued"] == 0 and blocker.running():
            break
        time.sleep(0.005)
    return sched, gate, blocker


# ---------------------------------------------------------------------------
# typed wire errors
# ---------------------------------------------------------------------------


def test_rate_limit_error_wire_shape():
    e = RateLimitError("queue full", retry_after=2.5)
    assert e.status_code == 429
    assert e.retry_after == 2.5
    wire = e.as_wire()["error"]
    assert wire["type"] == "rate_limit_error"
    assert wire["code"] == "rate_limit_exceeded"
    assert isinstance(e, KLLMsError)


def test_server_draining_error_wire_shape():
    e = ServerDrainingError("draining")
    assert e.status_code == 503
    assert e.as_wire()["error"]["code"] == "server_draining"
    assert isinstance(e, KLLMsError)


# ---------------------------------------------------------------------------
# bounded admission
# ---------------------------------------------------------------------------


def test_queue_cap_sheds_with_typed_429():
    sched, gate, blocker = _blocked_scheduler(max_queue_weight=4)
    try:
        f1 = sched.submit_batched(("k",), 1, _echo, weight=2)
        f2 = sched.submit_batched(("k",), 2, _echo, weight=2)
        f3 = sched.submit_batched(("k",), 3, _echo, weight=2)  # 6 > 4: shed
        with pytest.raises(RateLimitError) as ei:
            f3.result(timeout=1)
        assert ei.value.status_code == 429
        assert 0.1 <= ei.value.retry_after <= 60.0
        h = sched.health()
        assert h["queue_weight"] == 4
        assert h["shed_over_capacity"] == 1
        gate.set()
        assert f1.result(timeout=5) == 1
        assert f2.result(timeout=5) == 2
    finally:
        gate.set()
        sched.shutdown()


def test_cap_is_by_weight_not_item_count():
    # cap 8 admits four weight-2 items but only one weight-8 item.
    sched, gate, _ = _blocked_scheduler(max_queue_weight=8)
    try:
        futs = [sched.submit_batched(("k",), i, _echo, weight=2) for i in range(4)]
        heavy = sched.submit_batched(("k",), 9, _echo, weight=2)
        with pytest.raises(RateLimitError):
            heavy.result(timeout=1)
        gate.set()
        assert [f.result(5) for f in futs] == [0, 1, 2, 3]
    finally:
        gate.set()
        sched.shutdown()


def test_unbounded_by_default_backcompat():
    sched, gate, _ = _blocked_scheduler()  # no max_queue_weight
    try:
        futs = [sched.submit_batched(("k",), i, _echo, weight=64) for i in range(20)]
        assert sched.health()["shed_over_capacity"] == 0
        gate.set()
        assert [f.result(10) for f in futs] == list(range(20))
    finally:
        gate.set()
        sched.shutdown()


def test_priority_eviction_prefers_important_work():
    sched, gate, _ = _blocked_scheduler(max_queue_weight=4)
    try:
        low = sched.submit_batched(("k",), "low", _echo, weight=4, priority=5)
        high = sched.submit_batched(("k",), "high", _echo, weight=2, priority=0)
        # The full queue evicted the strictly-lower-priority item.
        with pytest.raises(RateLimitError):
            low.result(timeout=1)
        gate.set()
        assert high.result(5) == "high"
        h = sched.health()
        assert h["evicted"] == 1
    finally:
        gate.set()
        sched.shutdown()


def test_no_eviction_among_equal_priority():
    sched, gate, _ = _blocked_scheduler(max_queue_weight=4)
    try:
        first = sched.submit_batched(("k",), "first", _echo, weight=4, priority=0)
        second = sched.submit_batched(("k",), "second", _echo, weight=4, priority=0)
        # Equal priority: FIFO holds, the NEWCOMER is shed.
        with pytest.raises(RateLimitError):
            second.result(timeout=1)
        gate.set()
        assert first.result(5) == "first"
    finally:
        gate.set()
        sched.shutdown()


def test_retry_after_tracks_drain_rate():
    sched = EngineScheduler(name="test", batch_window=0.0, max_queue_weight=4)
    try:
        # Build service history: ~40 weight/s drain rate.
        for i in range(10):
            sched.submit_batched(("k",), i, _echo, weight=4).result(5)
            time.sleep(0.01)
        gate = threading.Event()
        sched.submit(gate.wait)
        time.sleep(0.05)
        sched.submit_batched(("k",), 1, _echo, weight=4)
        with pytest.raises(RateLimitError) as ei:
            sched.submit_batched(("k",), 2, _echo, weight=4).result(1)
        # backlog(8) / measured-rate: well under the no-history 60 s clamp and
        # not the 1.0 s fallback pinned exactly.
        assert 0.1 <= ei.value.retry_after <= 10.0
        gate.set()
    finally:
        gate.set()
        sched.shutdown()


# ---------------------------------------------------------------------------
# health & lifecycle
# ---------------------------------------------------------------------------


def test_health_snapshot_fields_and_ready_state():
    sched = EngineScheduler(name="test", max_queue_weight=32)
    try:
        sched.submit(lambda: None).result(5)
        h = sched.health()
        assert h["state"] == "ready"
        for key in (
            "queue_depth", "queue_weight", "max_queue_weight", "in_flight",
            "effective_max_rows", "served", "shed", "shed_over_capacity",
            "evicted", "oom_splits", "drain_rate",
        ):
            assert key in h
        assert h["max_queue_weight"] == 32
        assert h["served"] >= 1
    finally:
        sched.shutdown()


def test_note_oom_backs_off_width_and_degrades():
    sched = EngineScheduler(name="test", max_rows=64)
    try:
        sched.submit(lambda: None).result(5)  # worker is READY
        sched.note_oom()
        h = sched.health()
        assert h["state"] == "degraded"
        assert h["effective_max_rows"] == 32
        sched.note_oom()
        assert sched.health()["effective_max_rows"] == 16
        # Three clean launches per step restore the width, then READY.
        for _ in range(3):
            sched.note_recovered()
        assert sched.health()["effective_max_rows"] == 32
        assert sched.health()["state"] == "degraded"
        for _ in range(3):
            sched.note_recovered()
        h = sched.health()
        assert h["effective_max_rows"] == 64
        assert h["state"] == "ready"
    finally:
        sched.shutdown()


def test_width_backoff_floors_at_one_row():
    sched = EngineScheduler(name="test", max_rows=2)
    try:
        for _ in range(5):
            sched.note_oom()
        assert sched.health()["effective_max_rows"] == 1
    finally:
        sched.shutdown()


def test_per_item_max_rows_hint_caps_group():
    sched = EngineScheduler(name="test", batch_window=0.05, max_rows=64)
    gate = threading.Event()
    sched.submit(gate.wait)
    time.sleep(0.05)
    seen = []

    def runner(payloads):
        seen.append(len(payloads))
        return list(payloads)

    try:
        futs = [
            sched.submit_batched(("k",), i, runner, weight=1, max_rows=2)
            for i in range(4)
        ]
        gate.set()
        assert sorted(f.result(5) for f in futs) == [0, 1, 2, 3]
        # cap 2 with pow2 projection admits at most 2 members per group.
        assert max(seen) <= 2
        assert len(seen) >= 2
    finally:
        gate.set()
        sched.shutdown()


def test_drain_while_busy_finishes_inflight_and_backlog():
    sched = EngineScheduler(name="test", batch_window=0.0)
    gate = threading.Event()
    started = threading.Event()

    def busy():
        started.set()
        gate.wait()
        return "done"

    inflight = sched.submit(busy)
    started.wait(5)
    queued = sched.submit_batched(("k",), "q", _echo, weight=1)

    res = {}
    def do_drain():
        res["clean"] = sched.drain(timeout=10)

    t = threading.Thread(target=do_drain)
    t.start()
    time.sleep(0.1)
    # Admission is closed while draining: typed 503.
    with pytest.raises(ServerDrainingError):
        sched.submit(lambda: 1).result(timeout=1)
    assert sched.state is ServerState.DRAINING
    gate.set()
    t.join(10)
    assert res["clean"] is True
    assert inflight.result(0) == "done"
    assert queued.result(0) == "q"  # backlog served before the worker retired
    assert sched.state is ServerState.STOPPED
    assert not sched._worker.is_alive()
    assert sched.health()["queue_depth"] == 0


def test_drain_timeout_fails_leftovers_with_503():
    sched = EngineScheduler(name="test", batch_window=0.0)
    gate = threading.Event()
    sched.submit(gate.wait)
    time.sleep(0.05)
    stuck = sched.submit_batched(("k",), "s", _echo, weight=1)
    assert sched.drain(timeout=0.3) is False
    with pytest.raises(ServerDrainingError):
        stuck.result(timeout=1)
    assert sched.state is ServerState.STOPPED
    gate.set()  # release the worker thread


def test_drain_is_idempotent_and_post_stop_submits_rejected():
    sched = EngineScheduler(name="test")
    assert sched.drain(timeout=5) is True
    assert sched.drain(timeout=5) is True
    with pytest.raises(BackendUnavailableError):
        sched.submit(lambda: 1).result(timeout=1)
    with pytest.raises(BackendUnavailableError):
        sched.submit_batched(("k",), 1, _echo).result(timeout=1)


def test_drain_refuses_worker_thread():
    sched = EngineScheduler(name="test")
    try:
        with pytest.raises(RuntimeError):
            sched.submit(lambda: sched.drain(1)).result(5)
    finally:
        sched.shutdown()


# ---------------------------------------------------------------------------
# failpoint "oom" action + RESOURCE_EXHAUSTED predicate
# ---------------------------------------------------------------------------


def test_oom_failpoint_raises_resource_exhausted_shape():
    with failpoints({"engine.launch": FailSpec(action="oom", times=1)}):
        with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
            fire("engine.launch")
        assert fire("engine.launch") is None  # times=1 exhausted


def test_is_resource_exhausted_predicate():
    assert is_resource_exhausted(RuntimeError("RESOURCE_EXHAUSTED: oom"))
    assert is_resource_exhausted(RuntimeError("Out of memory while allocating"))
    assert not is_resource_exhausted(RuntimeError("some other fault"))
    # Typed lifecycle errors never count, even with the marker in the text.
    assert not is_resource_exhausted(
        BackendUnavailableError("RESOURCE_EXHAUSTED downstream")
    )


def test_oom_env_syntax_parses():
    from k_llms_tpu.reliability import failpoints as fp

    fp.configure_from_env("engine.launch=oom:2")
    try:
        spec = fp._registry["engine.launch"]
        assert spec.action == "oom"
        assert spec.times == 2
    finally:
        fp.clear()


# ---------------------------------------------------------------------------
# HBM memory model
# ---------------------------------------------------------------------------


def test_memory_model_rows_shrink_with_seq_len():
    cfg = get_config("llama-3-8b")
    m = HbmMemoryModel(cfg, param_bytes=16 << 30, hbm_bytes=32 << 30, tp=1, dp=1)
    r_short, r_long = m.max_rows(256), m.max_rows(8192)
    assert r_short > r_long >= 1
    # 8B bf16 KV: 2 * 32 layers * 1024 kv_dim * 2 B = 128 KiB per token-row.
    assert m.kv_bytes_per_token == 2 * cfg.num_layers * cfg.kv_dim * 2


def test_memory_model_tp_and_dp_scaling():
    cfg = get_config("llama-3-8b")
    base = HbmMemoryModel(cfg, param_bytes=16 << 30, hbm_bytes=32 << 30, tp=1, dp=1)
    tp4 = HbmMemoryModel(cfg, param_bytes=16 << 30, hbm_bytes=32 << 30, tp=4, dp=1)
    dp4 = HbmMemoryModel(cfg, param_bytes=16 << 30, hbm_bytes=32 << 30, tp=1, dp=4)
    # TP shards both params and KV: strictly more rows fit per device.
    assert tp4.max_rows(4096) > base.max_rows(4096)
    # DP multiplies rows across replicas.
    assert dp4.max_rows(4096) >= 4 * base.max_rows(4096) - 4
    assert base.describe()["max_rows_at_max_seq"] >= 1


def test_memory_model_floors_at_one_row():
    cfg = get_config("llama-3-8b")
    # Params alone exceed planned HBM: cap must still be >= 1 (the OOM guard,
    # not admission, owns the doesn't-fit-at-all case).
    m = HbmMemoryModel(cfg, param_bytes=16 << 30, hbm_bytes=8 << 30)
    assert m.max_rows(8192) == 1
    assert m.budget_bytes() < 0


def test_memory_model_headroom_tightens_budget():
    cfg = get_config("tiny")
    loose = HbmMemoryModel(cfg, param_bytes=1 << 20, hbm_bytes=1 << 30, headroom=0.9)
    tight = HbmMemoryModel(cfg, param_bytes=1 << 20, hbm_bytes=1 << 30, headroom=0.5)
    assert loose.max_rows(1024) > tight.max_rows(1024)


# ---------------------------------------------------------------------------
# engine OOM guard (failpoint-driven fake OOM, real tiny engine)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def backend():
    b = TpuBackend(
        config=BackendConfig(model="tiny", max_new_tokens=4, batch_window=0.02)
    )
    # Warm the solo + 2-group compile caches outside the failpoint windows.
    b.chat_completion(_req(0))
    yield b


def _req(i, n=2):
    return ChatRequest(
        messages=[{"role": "user", "content": f"overload probe {i}"}],
        model="tiny",
        n=n,
        max_tokens=4,
        temperature=1.0,
        seed=i,
    )


def test_injected_oom_splits_group_and_all_members_complete(backend):
    results, errors = [], []

    def run(i):
        try:
            results.append(backend.chat_completion(_req(i)))
        except Exception as e:  # pragma: no cover - failure is the assertion
            errors.append(e)

    before = dict(backend.engine.oom_stats)
    with failpoints({"engine.launch": FailSpec(action="oom", times=1)}):
        threads = [threading.Thread(target=run, args=(i,)) for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(120)
    assert not errors, f"members failed instead of recovering: {errors!r}"
    assert len(results) == 4
    assert all(len(r.choices) == 2 for r in results)
    assert backend.engine.oom_stats["splits"] > before["splits"]
    assert backend.engine.oom_stats["unrecovered"] == before["unrecovered"]
    h = backend.health()
    assert h["oom_splits"] >= 1
    assert h["engine_oom"]["splits"] >= 1


def test_solo_oom_surfaces_typed_503(backend):
    # A single request that OOMs cannot be split: typed BackendUnavailable.
    with failpoints({"engine.launch": FailSpec(action="oom", times=2)}):
        with pytest.raises(BackendUnavailableError, match="out of memory"):
            backend.chat_completion(_req(99))
    assert backend.engine.oom_stats["unrecovered"] >= 1


def test_health_merges_breaker_and_memory_model(backend):
    h = backend.health()
    assert h["breaker"] in ("closed", "open", "half_open")
    assert h["memory_model"]["param_bytes"] > 0
    assert h["state"] in ("ready", "degraded")


def test_generate_many_passthrough_on_non_oom_errors(backend):
    # Non-OOM launch faults keep the PR 1 contract: delivered, not split.
    with failpoints({"engine.launch": FailSpec(action="raise", times=1)}):
        with pytest.raises(RuntimeError, match="injected failpoint fault"):
            backend.chat_completion(_req(7))
    before = backend.engine.oom_stats["splits"]
    backend.chat_completion(_req(8))
    assert backend.engine.oom_stats["splits"] == before


# ---------------------------------------------------------------------------
# dispatch layer: sheds are not backend-health failures
# ---------------------------------------------------------------------------


class _SheddingBackend(TpuBackend):
    def __init__(self, exc):
        # Bypass TpuBackend.__init__: no engine needed to test dispatch.
        self._exc = exc

    def chat_completion(self, request):
        raise self._exc


def test_shed_errors_do_not_trip_circuit_breaker():
    for exc in (RateLimitError("full", retry_after=1.0), ServerDrainingError("bye")):
        b = _SheddingBackend(exc)
        for _ in range(10):
            with pytest.raises(type(exc)):
                b.dispatch_chat_completion(_req(1))
        assert b.circuit_breaker.state == "closed"


def test_genuine_faults_still_trip_breaker():
    b = _SheddingBackend(RuntimeError("boom"))
    opened = False
    for _ in range(20):
        try:
            b.dispatch_chat_completion(_req(1))
        except Exception:
            pass
        if b.circuit_breaker.state == "open":
            opened = True
            break
    assert opened


# ---------------------------------------------------------------------------
# client lifecycle
# ---------------------------------------------------------------------------


def test_client_close_health_drain_fake_backend():
    from k_llms_tpu import KLLMs

    client = KLLMs(backend="fake")
    h = client.health()
    assert h["state"] == "ready"
    assert client.drain() is True
    client.close()  # idempotent


def test_client_context_manager_drains_tpu_backend():
    from k_llms_tpu import KLLMs

    with KLLMs(
        backend="tpu", model="tiny", max_new_tokens=4, max_queue_weight=32
    ) as client:
        out = client.chat.completions.create(
            messages=[{"role": "user", "content": "hi"}], model="tiny", n=1,
            max_tokens=4,
        )
        assert out.choices
        assert client.backend.scheduler.max_queue_weight == 32
    assert client.health()["state"] == "stopped"
    assert not client.backend.scheduler._worker.is_alive()


def test_async_client_context_manager():
    import asyncio

    from k_llms_tpu import AsyncKLLMs

    async def main():
        async with AsyncKLLMs(backend="fake") as client:
            return client.health()["state"]

    assert asyncio.run(main()) == "ready"


# ---------------------------------------------------------------------------
# duration-budget collection guard
# ---------------------------------------------------------------------------


class _FakeMarker:
    def __init__(self, args):
        self.args = args


class _FakeItem:
    def __init__(self, nodeid, budget=None, slow=False):
        self.nodeid = nodeid
        self._markers = {}
        if budget is not None:
            self._markers["duration_budget"] = _FakeMarker((budget,))
        if slow:
            self._markers["slow"] = _FakeMarker(())

    def get_closest_marker(self, name):
        return self._markers.get(name)


def test_duration_guard_flags_untagged_heavy_test():
    items = [
        _FakeItem("t::fast", budget=5),
        _FakeItem("t::heavy_untagged", budget=120),
        _FakeItem("t::heavy_slow", budget=120, slow=True),
        _FakeItem("t::undeclared"),
    ]
    violations = check_items(items, threshold=30.0)
    assert violations == [("t::heavy_untagged", 120.0)]
    with pytest.raises(pytest.UsageError, match="heavy_untagged"):
        enforce(items, threshold=30.0)


def test_duration_guard_passes_clean_suite():
    items = [_FakeItem("t::a", budget=29), _FakeItem("t::b", budget=600, slow=True)]
    assert check_items(items, threshold=30.0) == []
    enforce(items, threshold=30.0)  # must not raise


def test_duration_guard_rejects_argless_marker():
    item = _FakeItem("t::x")
    item._markers["duration_budget"] = _FakeMarker(())
    with pytest.raises(ValueError, match="seconds argument"):
        check_items([item])


# ---------------------------------------------------------------------------
# chaos soak (acceptance criteria) — slow-tagged, not part of tier-1
# ---------------------------------------------------------------------------


@pytest.mark.slow
@pytest.mark.duration_budget(300)
def test_overload_soak_4x_capacity_bounded_and_typed(monkeypatch):
    """ISSUE 2 acceptance: sustained >= 4x over-capacity for >= 30 s with
    queue weight never over the cap, zero hung futures, every rejection a
    typed 429/503/timeout wire error, >= 1 injected RESOURCE_EXHAUSTED
    recovered via group split with all survivors completing, and drain()
    returning with the queue empty and the worker joined.

    Runs under KLLMS_LOCKCHECK=1 + KLLMS_RACECHECK=1: every lock the backend
    creates below is instrumented and every factory-locked object's fields go
    through the lockset sanitizer; the soak must end with a clean lock-order
    graph and zero empty-lockset findings."""
    monkeypatch.setenv("KLLMS_LOCKCHECK", "1")
    monkeypatch.setenv("KLLMS_RACECHECK", "1")
    lockcheck.reset_state()
    cap = 32
    b = TpuBackend(
        config=BackendConfig(
            model="tiny", max_new_tokens=4, batch_window=0.01, max_queue_weight=cap
        )
    )
    b.chat_completion(_req(0))  # warm solo compile

    # -- deterministic OOM-split episode: park the worker, build a backlog so
    # the next group is guaranteed coalesced, inject one RESOURCE_EXHAUSTED.
    gate = threading.Event()
    b.scheduler.submit(gate.wait)
    time.sleep(0.05)
    split_results, split_errors = [], []

    def run_split(i):
        try:
            split_results.append(b.chat_completion(_req(i)))
        except Exception as e:
            split_errors.append(e)

    with failpoints({"engine.launch": FailSpec(action="oom", times=1)}):
        threads = [threading.Thread(target=run_split, args=(i,)) for i in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.3)  # let all four queue behind the gate
        gate.set()
        for t in threads:
            t.join(180)
    assert not split_errors, f"split survivors failed: {split_errors!r}"
    assert len(split_results) == 4
    assert b.engine.oom_stats["splits"] >= 1
    assert b.engine.oom_stats["unrecovered"] == 0

    # -- 30+ s sustained overload: 8 closed-loop clients against a queue cap
    # sized for ~2 queued requests (weight 8-16 each on the dp mesh) = well
    # over 4x the admissible backlog; a monitor samples queue weight.
    stop = threading.Event()
    outcomes = {"ok": 0, "shed": 0}
    bad_errors = []
    max_seen_weight = [0]
    lock = threading.Lock()

    def client(tid):
        i = 0
        while not stop.is_set():
            i += 1
            try:
                b.dispatch_chat_completion(_req(tid * 100000 + i))
                with lock:
                    outcomes["ok"] += 1
            except KLLMsError as e:
                with lock:
                    if e.status_code in (429, 503, 408):
                        outcomes["shed"] += 1
                    else:  # pragma: no cover - would fail the assertion below
                        bad_errors.append(e)
            except Exception as e:  # pragma: no cover
                with lock:
                    bad_errors.append(e)

    def monitor():
        while not stop.is_set():
            h = b.scheduler.health()
            with lock:
                max_seen_weight[0] = max(max_seen_weight[0], h["queue_weight"])
            assert h["queue_weight"] <= cap
            time.sleep(0.01)

    workers = [threading.Thread(target=client, args=(t,)) for t in range(8)]
    mon = threading.Thread(target=monitor)
    mon.start()
    for w in workers:
        w.start()
    time.sleep(31.0)
    stop.set()
    for w in workers:
        w.join(180)
        assert not w.is_alive(), "hung client thread = hung future"
    mon.join(10)

    assert not bad_errors, f"untyped/unexpected errors during soak: {bad_errors!r}"
    assert outcomes["ok"] > 0, "overloaded server must still serve"
    assert outcomes["shed"] > 0, "4x over-capacity must shed"
    assert max_seen_weight[0] <= cap

    # -- graceful drain: queue empties, worker joins.
    assert b.drain(timeout=60) is True
    assert b.scheduler.state is ServerState.STOPPED
    assert b.scheduler.health()["queue_depth"] == 0
    assert not b.scheduler._worker.is_alive()
    with pytest.raises((ServerDrainingError, BackendUnavailableError)):
        b.chat_completion(_req(1))

    # The whole soak ran under the lock sanitizer: no ordering inversions,
    # no device dispatch under an undeclared lock.
    lockcheck.assert_clean()
