"""Checkpoint I/O: orbax round-trip, HF safetensors import, TP-sharded decode
equivalence."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k_llms_tpu.engine import LocalEngine, ByteTokenizer
from k_llms_tpu.models import get_config, init_params
from k_llms_tpu.models.llama import forward
from k_llms_tpu.models.loader import (
    config_from_hf,
    load_checkpoint,
    load_orbax,
    load_safetensors,
    save_checkpoint,
)
from k_llms_tpu.parallel.mesh import make_mesh


def test_orbax_roundtrip(tmp_path):
    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.key(0))
    path = str(tmp_path / "ckpt")
    save_checkpoint(path, params)
    restored = load_checkpoint(path, cfg)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        params,
        restored,
    )


def test_safetensors_import(tmp_path):
    from safetensors.numpy import save_file

    cfg = get_config("tiny").with_(dtype="float32")
    params = init_params(cfg, jax.random.key(1))

    # Write an HF-layout checkpoint equivalent to `params`.
    tensors = {}
    tensors["model.embed_tokens.weight"] = np.asarray(params["embed"])
    tensors["model.norm.weight"] = np.asarray(params["final_norm"])
    # NB: safetensors.numpy writes the raw buffer, so transposed VIEWS must be
    # made contiguous or the file is silently corrupt.
    tensors["lm_head.weight"] = np.ascontiguousarray(np.asarray(params["lm_head"]).T)
    hf_names = {
        "wq": "self_attn.q_proj",
        "wk": "self_attn.k_proj",
        "wv": "self_attn.v_proj",
        "wo": "self_attn.o_proj",
        "w_gate": "mlp.gate_proj",
        "w_up": "mlp.up_proj",
        "w_down": "mlp.down_proj",
    }
    for i in range(cfg.num_layers):
        for ours, hf in hf_names.items():
            tensors[f"model.layers.{i}.{hf}.weight"] = np.ascontiguousarray(
                np.asarray(params["layers"][ours][i]).T
            )
        tensors[f"model.layers.{i}.input_layernorm.weight"] = np.asarray(
            params["layers"]["attn_norm"][i]
        )
        tensors[f"model.layers.{i}.post_attention_layernorm.weight"] = np.asarray(
            params["layers"]["mlp_norm"][i]
        )
    ckpt = tmp_path / "hf"
    ckpt.mkdir()
    save_file(tensors, str(ckpt / "model.safetensors"))

    loaded = load_safetensors(str(ckpt), cfg, dtype=jnp.float32)
    tokens = jax.random.randint(jax.random.key(2), (1, 8), 0, cfg.vocab_size)
    mask = jnp.ones((1, 8), jnp.int32)
    ref_logits, _ = forward(cfg, params, tokens, mask)
    got_logits, _ = forward(cfg, loaded, tokens, mask)
    np.testing.assert_allclose(
        np.asarray(got_logits), np.asarray(ref_logits), rtol=1e-5, atol=1e-5
    )


def test_config_from_hf(tmp_path):
    hf_cfg = {
        "vocab_size": 1000,
        "hidden_size": 64,
        "intermediate_size": 128,
        "num_hidden_layers": 2,
        "num_attention_heads": 4,
        "num_key_value_heads": 2,
        "rope_theta": 10000.0,
        "rms_norm_eps": 1e-6,
        "max_position_embeddings": 2048,
        "bos_token_id": 1,
        "eos_token_id": 2,
    }
    d = tmp_path / "model"
    d.mkdir()
    (d / "config.json").write_text(json.dumps(hf_cfg))
    cfg = config_from_hf(str(d))
    assert cfg.hidden_size == 64
    assert cfg.num_kv_heads == 2
    assert cfg.head_dim == 16
    assert cfg.eos_token_id == 2
    assert config_from_hf(str(tmp_path / "nope")) is None


def test_tensor_parallel_decode_matches_data_parallel():
    """The same weights must produce the same samples whether sharded
    (data=4, model=2) or (data=8, model=1) — sharding must not change results."""
    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.key(0))
    tok = ByteTokenizer()
    ids = tok.apply_chat_template([{"role": "user", "content": "tp check"}])

    eng_dp = LocalEngine(cfg, params=params, mesh=make_mesh(8, 1))
    eng_tp = LocalEngine(cfg, params=params, mesh=make_mesh(4, 2))
    r_dp = eng_dp.generate(ids, n=4, max_new_tokens=8, temperature=0.0, seed=9)
    r_tp = eng_tp.generate(ids, n=4, max_new_tokens=8, temperature=0.0, seed=9)
    np.testing.assert_array_equal(r_dp.tokens, r_tp.tokens)
    np.testing.assert_allclose(r_dp.logprobs, r_tp.logprobs, rtol=2e-4, atol=2e-4)


def test_orbax_roundtrip_quantized(tmp_path):
    """int8 QTensor trees survive orbax save/load (orbax restores NamedTuples
    as dicts without a target; the loader rebuilds them) and the restored
    params generate identically."""
    from k_llms_tpu.engine.engine import LocalEngine
    from k_llms_tpu.models.quant import QTensor, quantize_params

    cfg = get_config("tiny")
    params = quantize_params(init_params(cfg, jax.random.key(0)))
    path = str(tmp_path / "qckpt")
    save_checkpoint(path, params)
    loaded = load_orbax(path)

    assert isinstance(loaded["layers"]["wq"], QTensor)
    assert isinstance(loaded["lm_head"], QTensor)
    assert loaded["layers"]["wq"].q.dtype == jnp.int8

    e0 = LocalEngine(cfg, params=params, use_mesh=False)
    e1 = LocalEngine(cfg, params=loaded, use_mesh=False)
    ids = [72, 105]
    a = e0.generate(ids, n=2, max_new_tokens=6, temperature=0.0)
    b = e1.generate(ids, n=2, max_new_tokens=6, temperature=0.0)
    assert (a.tokens == b.tokens).all()
