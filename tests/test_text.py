"""String normalization + similarity primitives (reference consensus_utils :660-761)."""

import pytest

from k_llms_tpu.consensus.text import (
    ascii_fold,
    hamming_similarity,
    jaccard_similarity,
    key_normalization,
    levenshtein_similarity,
    normalize_string,
    sanitize_value,
)
from k_llms_tpu.consensus.settings import SIMILARITY_SCORE_LOWER_BOUND


def test_normalize_string():
    assert normalize_string("Hello, World! 42") == "helloworld42"
    assert normalize_string("") == ""
    assert normalize_string("___") == ""


def test_sanitize_value():
    assert sanitize_value("Crème Brûlée") == "cremebrulee"
    assert sanitize_value("Straße 12") == "strasse12"
    assert sanitize_value(True) == "true"
    assert sanitize_value("A  B") == "ab"


def test_ascii_fold_special_latin():
    assert ascii_fold("Løß œuf þing") == "Loss oeuf thing"


def test_key_normalization():
    assert key_normalization("items.3.name") == "items.*.name"
    assert key_normalization("a.b") == "a.b"


def test_levenshtein_similarity():
    assert levenshtein_similarity("kitten", "kitten") == 1.0
    assert levenshtein_similarity("", "") == 1.0
    # normalized: "kitten" vs "sitting" distance 3, max len 7
    assert levenshtein_similarity("kitten", "sitting") == pytest.approx(1 - 3 / 7)
    assert levenshtein_similarity("abc", "xyz") == SIMILARITY_SCORE_LOWER_BOUND


def test_hamming_similarity():
    assert hamming_similarity("abc", "abc") == 1.0
    assert hamming_similarity("abc", "abd") == pytest.approx(2 / 3)
    # padding with spaces counts as mismatch
    assert hamming_similarity("ab", "abcd") == pytest.approx(0.5)
    assert hamming_similarity("", "") == 1.0


def test_jaccard_similarity():
    assert jaccard_similarity("abc", "bcd") == pytest.approx(2 / 4)
    assert jaccard_similarity("", "") == 1.0
    assert jaccard_similarity("Hello!", "hello") == 1.0  # normalization first
