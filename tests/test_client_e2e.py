"""End-to-end client tests on the fake backend (the hermetic substitute for the
reference's live-API suite, README_TESTS.md:100-111)."""

import asyncio
import json

import pytest
from pydantic import BaseModel

from k_llms_tpu import AsyncKLLMs, KLLMs


def make_client(contents):
    return KLLMs(backend="fake", responses=[contents])


def test_create_n3_contract():
    client = make_client(["yes", "yes", "no"])
    resp = client.chat.completions.create(
        messages=[{"role": "user", "content": "q"}], model="m", n=3
    )
    # contract: choices[0]=consensus, 1..n originals, likelihoods present
    assert len(resp.choices) == 3 + 1
    assert resp.choices[0].index == 0
    assert [c.index for c in resp.choices[1:]] == [1, 2, 3]
    assert resp.choices[0].message.content == "yes"
    assert resp.likelihoods == {"text": round(2 / 3, 5)}
    assert [c.message.content for c in resp.choices[1:]] == ["yes", "yes", "no"]


def test_create_single_choice_passthrough():
    client = make_client(["hello"])
    resp = client.chat.completions.create(messages=[{"role": "user", "content": "q"}], model="m")
    assert len(resp.choices) == 1
    assert resp.likelihoods is None


def test_create_json_contents():
    payload = {"city": "Paris", "country": "France"}
    client = make_client([json.dumps(payload)] * 2 + [json.dumps({"city": "Paris", "country": "FR"})])
    resp = client.chat.completions.create(
        messages=[{"role": "user", "content": "q"}], model="m", n=3
    )
    consensus = json.loads(resp.choices[0].message.content)
    assert consensus["city"] == "Paris"
    assert consensus["country"] == "France"
    assert resp.likelihoods["city"] == 1.0
    assert resp.likelihoods["country"] == round(2 / 3, 5)


def test_parse_revalidates_into_model():
    class UserInfo(BaseModel):
        name: str
        age: int

    client = make_client(
        [json.dumps({"name": "Bob", "age": 44})] * 3 + [json.dumps({"name": "Rob", "age": 44})]
    )
    resp = client.chat.completions.parse(
        messages=[{"role": "user", "content": "q"}],
        model="m",
        n=4,
        response_format=UserInfo,
    )
    parsed = resp.choices[0].message.parsed
    assert isinstance(parsed, UserInfo)
    assert parsed.name == "Bob"
    assert parsed.age == 44
    assert resp.likelihoods["name"] == 0.75


def test_parse_populates_originals_and_single_sample():
    """Local samples are plain text, so parse() must fill ``parsed`` on the
    originals (the reference gets this from the server, completions.py:134) —
    including the n=1 single-choice passthrough."""

    class UserInfo(BaseModel):
        name: str
        age: int

    client = make_client([json.dumps({"name": "Bob", "age": 44})] * 4)
    resp = client.chat.completions.parse(
        messages=[{"role": "user", "content": "q"}], model="m", n=3, response_format=UserInfo
    )
    for choice in resp.choices:
        assert isinstance(choice.message.parsed, UserInfo)

    resp1 = client.chat.completions.parse(
        messages=[{"role": "user", "content": "q"}], model="m", n=1, response_format=UserInfo
    )
    assert isinstance(resp1.choices[0].message.parsed, UserInfo)
    assert resp1.choices[0].message.parsed.name == "Bob"


def test_parse_failure_gives_none_parsed():
    class Strict(BaseModel):
        count: int

    # close ints cluster together -> fractional cluster mean -> validation fails silently
    client = make_client(
        [json.dumps({"count": 100}), json.dumps({"count": 102}), json.dumps({"count": 103})]
    )
    resp = client.chat.completions.parse(
        messages=[{"role": "user", "content": "q"}], model="m", n=3, response_format=Strict
    )
    assert resp.choices[0].message.parsed is None
    assert json.loads(resp.choices[0].message.content)["count"] == pytest.approx(305 / 3)


def test_nested_list_consolidation():
    docs = [
        {"invoice": {"items": [{"sku": "widget large", "qty": 2}, {"sku": "gadget small", "qty": 1}]}},
        {"invoice": {"items": [{"sku": "gadget small", "qty": 1}, {"sku": "widget large", "qty": 2}]}},
        {"invoice": {"items": [{"sku": "widget large", "qty": 2}]}},
    ]
    client = make_client([json.dumps(d) for d in docs])
    resp = client.chat.completions.create(
        messages=[{"role": "user", "content": "q"}], model="m", n=3
    )
    consensus = json.loads(resp.choices[0].message.content)
    items = consensus["invoice"]["items"]
    skus = [i["sku"] for i in items]
    assert "widget large" in skus
    assert resp.likelihoods["invoice"]["items"][0]["sku"] >= 0.5


def test_async_client():
    async def main():
        client = AsyncKLLMs(backend="fake", responses=[["a", "a", "b"]])
        return await client.chat.completions.create(
            messages=[{"role": "user", "content": "q"}], model="m", n=3
        )

    resp = asyncio.run(main())
    assert resp.choices[0].message.content == "a"
    assert len(resp.choices) == 4


def test_usage_preserved():
    client = make_client(["x", "x"])
    resp = client.chat.completions.create(
        messages=[{"role": "user", "content": "hello world"}], model="m", n=2
    )
    assert resp.usage is not None
    assert resp.usage.total_tokens == resp.usage.prompt_tokens + resp.usage.completion_tokens


def test_get_embeddings_helper():
    client = make_client(["x"])
    embs = client.get_embeddings(["alpha", "beta"])
    assert len(embs) == 2
    assert len(embs[0]) == len(embs[1]) > 0
    # deterministic
    assert client.get_embeddings(["alpha"])[0] == embs[0]


def test_similarity_caches_shared_across_requests():
    """The backend owns one scorer per similarity method, so a second identical
    request hits the embedding/similarity TTL caches and issues ZERO embedding
    forwards (the reference amortizes via module-global caches,
    `consensus_utils.py:620-623`)."""
    from k_llms_tpu.backends.fake import FakeBackend

    long_a = (
        "The quick brown fox jumps over the extremely lazy dog near the "
        "riverbank just before dawn on a cold morning."
    )
    long_b = (
        "The quick brown fox leaps over the extremely lazy dog near the "
        "riverbank just before dawn on a cold morning."
    )

    class CountingBackend(FakeBackend):
        def __init__(self, *args, **kwargs):
            super().__init__(*args, **kwargs)
            self.embed_calls = 0

        def embeddings(self, texts):
            self.embed_calls += 1
            return super().embeddings(texts)

    contents = [
        json.dumps({"summary": long_a}),
        json.dumps({"summary": long_a}),
        json.dumps({"summary": long_b}),
    ]
    backend = CountingBackend(responses=[contents])
    client = KLLMs(backend=backend, model="m")
    msgs = [{"role": "user", "content": "q"}]

    first = client.chat.completions.create(messages=msgs, model="m", n=3)
    calls_after_first = backend.embed_calls
    assert calls_after_first > 0  # the >50-char strings went through embeddings

    second = client.chat.completions.create(messages=msgs, model="m", n=3)
    assert backend.embed_calls == calls_after_first
    assert second.choices[0].message.content == first.choices[0].message.content

    # A separate client over the SAME backend also shares the caches.
    other = KLLMs(backend=backend, model="m")
    other.chat.completions.create(messages=msgs, model="m", n=3)
    assert backend.embed_calls == calls_after_first


def test_bare_primitive_json_contents_degrade_to_text():
    """A model answering bare JSON primitives ("5", "[1, 2]") must not crash
    the likelihoods contract (the reference DOES crash here — its likelihoods
    field requires a dict): such contents degrade to free-text consensus."""
    client = make_client(["5", "5", "7"])
    resp = client.chat.completions.create(
        messages=[{"role": "user", "content": "q"}], model="m", n=3
    )
    assert resp.choices[0].message.content == "5"
    assert resp.likelihoods == {"text": round(2 / 3, 5)}

    client = make_client(["[1, 2]", "[1, 2]", "[1, 2]"])
    resp = client.chat.completions.create(
        messages=[{"role": "user", "content": "q"}], model="m", n=3
    )
    assert resp.choices[0].message.content == "[1, 2]"
    assert resp.likelihoods == {"text": 1.0}
