"""Threading stress for the locked TTL caches (the race-safety subsystem,
SURVEY.md §5): concurrent readers/writers across shared scorers must never
corrupt entries, lose updates to other keys, or deadlock."""

import threading

from k_llms_tpu.consensus.cache import TTLCache
from k_llms_tpu.consensus.similarity import SimilarityScorer


def test_ttl_cache_concurrent_hammer():
    cache = TTLCache(maxsize=64, ttl=300.0)
    errors = []

    def worker(tid):
        try:
            for i in range(2000):
                k = f"k{(tid * 7 + i) % 97}"
                cache.set(k, (tid, i))
                got = cache.get(k)
                # Entry may have been evicted/overwritten by another thread,
                # but a present value must be a well-formed tuple some thread
                # wrote — never a torn/partial state.
                if got is not None and not (
                    isinstance(got, tuple) and len(got) == 2
                ):
                    errors.append(("torn", k, got))
        except Exception as e:  # pragma: no cover
            errors.append(("exc", tid, repr(e)))

    threads = [threading.Thread(target=worker, args=(t,)) for t in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors[:5]


def test_shared_scorer_concurrent_scoring():
    """The per-backend shared scorer is hit by concurrent requests; scores
    must be deterministic regardless of interleaving (cache hit or miss)."""
    scorer = SimilarityScorer(method="levenshtein")
    pairs = [
        ("the quick brown fox", "the quick brown fix"),
        ("alpha beta gamma", "alpha beta gamma"),
        ("completely different", "nothing alike here"),
    ]
    expected = [scorer.string(a, b) for a, b in pairs]
    results = {}

    def worker(tid):
        out = []
        for _ in range(500):
            for (a, b), want in zip(pairs, expected):
                out.append(scorer.string(a, b) == want)
        results[tid] = all(out)

    threads = [threading.Thread(target=worker, args=(t,)) for t in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert all(results.values())
