"""Parameter validation at the public API boundary — the reference delegates
these 400s to the OpenAI server (README_TESTS.md error-scenario checklist:
invalid model, empty messages, bad parameters); a local engine must reject
them itself with clean errors instead of generating garbage or crashing
mid-trace."""

import pytest

from k_llms_tpu import KLLMs


@pytest.fixture(scope="module")
def client():
    return KLLMs(backend="tpu", model="tiny")


MSGS = [{"role": "user", "content": "hello"}]


def test_empty_messages_rejected(client):
    with pytest.raises(ValueError, match="messages"):
        client.chat.completions.create(messages=[], model="tiny", n=2)


def test_invalid_model_name_raises_at_construction():
    with pytest.raises(KeyError):
        KLLMs(backend="tpu", model="no-such-model")


def test_client_model_reaches_backend():
    """KLLMs(backend="tpu", model=X) must BUILD model X, not the default
    labeled as X."""
    c = KLLMs(backend="tpu", model="tiny")
    assert c.backend.model_name == "tiny"


def test_n_zero_rejected(client):
    with pytest.raises(ValueError, match="n must be"):
        client.chat.completions.create(messages=MSGS, model="tiny", n=0)


def test_negative_max_tokens_rejected(client):
    with pytest.raises(ValueError, match="max_tokens"):
        client.chat.completions.create(messages=MSGS, model="tiny", n=1, max_tokens=-5)


def test_temperature_out_of_range_rejected(client):
    for bad in (-1.0, 2.5):
        with pytest.raises(ValueError, match="temperature"):
            client.chat.completions.create(
                messages=MSGS, model="tiny", n=1, temperature=bad
            )


def test_top_p_out_of_range_rejected(client):
    for bad in (1.5, -0.2):
        with pytest.raises(ValueError, match="top_p"):
            client.chat.completions.create(messages=MSGS, model="tiny", n=1, top_p=bad)


def test_valid_edges_still_serve(client):
    r = client.chat.completions.create(
        messages=MSGS, model="tiny", n=1, temperature=0.0, top_p=1.0,
        max_tokens=1, seed=1,
    )
    # n=1 is the reference's single-choice passthrough (no consensus row).
    assert len(r.choices) == 1


def test_parse_validates_too(client):
    from pydantic import BaseModel

    class Out(BaseModel):
        x: int

    with pytest.raises(ValueError, match="messages"):
        client.chat.completions.parse(messages=[], model="tiny", response_format=Out)


def test_default_model_label_follows_backend_weights():
    """KLLMs(backend="tpu") with no model must label requests with the
    backend's ACTUAL model, not an unrelated default name."""
    c = KLLMs(backend="tpu")
    assert c.default_model == c.backend.model_name == "tiny"


def test_conflicting_config_and_model_rejected():
    from k_llms_tpu.backends.tpu import BackendConfig, TpuBackend

    with pytest.raises(ValueError, match="conflicts"):
        TpuBackend(model="llama-3-8b", config=BackendConfig(model="tiny"))
    # Agreeing values are fine.
    b = TpuBackend(model="tiny", config=BackendConfig(model="tiny"))
    assert b.model_name == "tiny"


# -- logit_bias (the reference forwards it to the server; here the decode
# loop applies it) -----------------------------------------------------------

def test_logit_bias_bans_a_token(client):
    """With +100 on both 'A' and 'B' greedy emits only those; additionally
    banning 'A' (-100) must leave pure 'B' output."""
    ab = client.chat.completions.create(
        messages=MSGS, model="tiny", n=1, temperature=0.0, seed=5, max_tokens=4,
        logit_bias={"65": 100, "66": 100},
    )
    assert set(ab.choices[0].message.content) <= {"A", "B"}
    only_b = client.chat.completions.create(
        messages=MSGS, model="tiny", n=1, temperature=0.0, seed=5, max_tokens=4,
        logit_bias={"65": -100, "66": 100},
    )
    assert only_b.choices[0].message.content == "BBBB"


def test_logit_bias_forces_a_token(client):
    """+100 on one ordinary token dominates every step of greedy decode."""
    target = 65  # 'A' in the byte tokenizer
    r = client.chat.completions.create(
        messages=MSGS, model="tiny", n=2, temperature=0.0, seed=6, max_tokens=4,
        logit_bias={str(target): 100},
    )
    for choice in r.choices[1:]:
        assert choice.message.content == "AAAA"


def test_logit_bias_value_range_validated(client):
    with pytest.raises(ValueError, match="logit_bias values"):
        client.chat.completions.create(
            messages=MSGS, model="tiny", n=1, logit_bias={"65": 500}
        )


def test_logit_bias_token_range_validated(client):
    with pytest.raises(ValueError, match="outside vocab"):
        client.chat.completions.create(
            messages=MSGS, model="tiny", n=1, logit_bias={"999999": 1.0}
        )


def test_top_p_zero_is_top1(client):
    # OpenAI accepts top_p=0 (degenerates to top-1); must serve, not 400.
    r = client.chat.completions.create(
        messages=MSGS, model="tiny", n=2, top_p=0.0, seed=8, max_tokens=2,
    )
    assert len(r.choices) == 3


def test_penalty_out_of_range_rejected(client):
    with pytest.raises(ValueError, match="frequency_penalty"):
        client.chat.completions.create(
            messages=MSGS, model="tiny", n=1, frequency_penalty=50.0
        )
    with pytest.raises(ValueError, match="presence_penalty"):
        client.chat.completions.create(
            messages=MSGS, model="tiny", n=1, presence_penalty=-3.0
        )
