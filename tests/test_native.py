"""Differential tests: native C++ kernels vs the wheels the reference used."""

import random
import string

import numpy as np
import pytest

from k_llms_tpu.native import (
    _levenshtein_py,
    _lsa_py,
    levenshtein_distance,
    linear_sum_assignment,
    native_available,
)


def test_native_built():
    assert native_available(), "C++ kernels should compile in this environment"


def test_levenshtein_basics():
    assert levenshtein_distance("", "") == 0
    assert levenshtein_distance("abc", "") == 3
    assert levenshtein_distance("kitten", "sitting") == 3
    assert levenshtein_distance("héllo", "hello") == 1


def test_levenshtein_vs_wheel():
    Levenshtein = pytest.importorskip("Levenshtein")
    rng = random.Random(42)
    alphabet = string.ascii_lowercase + "éß日本"
    for _ in range(200):
        a = "".join(rng.choices(alphabet, k=rng.randint(0, 30)))
        b = "".join(rng.choices(alphabet, k=rng.randint(0, 30)))
        assert levenshtein_distance(a, b) == Levenshtein.distance(a, b)
        assert _levenshtein_py(a, b) == Levenshtein.distance(a, b)


def test_lsa_square():
    cost = np.array([[4.0, 1.0, 3.0], [2.0, 0.0, 5.0], [3.0, 2.0, 2.0]])
    row, col = linear_sum_assignment(cost)
    assert cost[row, col].sum() == 5.0


def test_lsa_rectangular_both_ways():
    rng = np.random.default_rng(7)
    scipy_opt = pytest.importorskip("scipy.optimize")
    for _ in range(100):
        nr = rng.integers(1, 10)
        nc = rng.integers(1, 10)
        c = rng.random((nr, nc))
        r1, c1 = linear_sum_assignment(c)
        r2, c2 = scipy_opt.linear_sum_assignment(c)
        assert len(r1) == min(nr, nc)
        assert np.isclose(c[r1, c1].sum(), c[r2, c2].sum())
        # pure-python fallback agrees too
        r3, c3 = _lsa_py(np.asarray(c, dtype=np.float64))
        assert np.isclose(c[r3, c3].sum(), c[r2, c2].sum())


def test_lsa_empty():
    row, col = linear_sum_assignment(np.zeros((0, 3)))
    assert len(row) == 0
