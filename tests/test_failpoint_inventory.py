"""Static failpoint inventory (PR 4 satellite): every site registered in
``k_llms_tpu.reliability.failpoints.SITES`` must be exercised by at least one
test, by literal name, somewhere in the test tree. A registered-but-untested
site is dead injection surface — it suggests a hardened path that nothing
pins, which is exactly how fault-handling code rots."""

import pathlib

from k_llms_tpu.reliability.failpoints import SITES

TESTS_DIR = pathlib.Path(__file__).parent
THIS_FILE = pathlib.Path(__file__).name


def _test_tree_text():
    """Concatenated source of every test module except this one (which names
    every site by construction and must not self-satisfy the check)."""
    chunks = []
    for path in sorted(TESTS_DIR.rglob("test_*.py")):
        if path.name == THIS_FILE:
            continue
        chunks.append(path.read_text(encoding="utf-8"))
    return "\n".join(chunks)


def test_every_registered_failpoint_is_exercised():
    tree = _test_tree_text()
    unexercised = [site for site in SITES if site not in tree]
    assert not unexercised, (
        f"failpoint site(s) {unexercised} are registered in failpoints.SITES "
        "but no test names them — add coverage or retire the site"
    )


def test_inventory_is_nonempty_and_names_are_registered():
    """Guard the guard: SITES is the single source of truth and stays
    dot-namespaced (subsystem.site), so grep hits are unambiguous."""
    assert len(SITES) >= 12
    assert "replica.dispatch" in SITES and "replica.probe" in SITES
    assert "consensus.device" in SITES
    for site in SITES:
        sub, _, name = site.partition(".")
        assert sub and name, f"site {site!r} must be subsystem.name"
