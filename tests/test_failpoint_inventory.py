"""Failpoint inventory, rebuilt on the kllms-check AST scan (no hand lists).

The ``failpoint-coverage`` rule extracts the registry straight from the
``SITES`` tuple's AST and cross-checks four surfaces at once: every
``fire()``/``fire_keyed()`` call site uses a registered literal, every
registered site has a call site, a test that names it, and a README
registry-table row, and every ``FailSpec`` action variant is exercised. This
module pins that the rule (a) passes over the real repo and (b) sees exactly
the same registry the runtime does — so the lint gate can't drift from the
code it guards.
"""

import pathlib

from k_llms_tpu.analysis.framework import load_project, run_rules, unsuppressed
from k_llms_tpu.analysis.rules.contracts import FailpointCoverageRule
from k_llms_tpu.reliability.failpoints import SITES

REPO = pathlib.Path(__file__).resolve().parent.parent


def test_every_site_is_fired_tested_and_documented():
    """The full cross-surface sweep: registry <-> call sites <-> tests <->
    README. Any unsuppressed finding here is a rotten failpoint."""
    project = load_project(REPO)
    findings = unsuppressed(run_rules(project, ["failpoint-coverage"]))
    assert not findings, "\n".join(f.format() for f in findings)


def test_ast_registry_matches_runtime_registry():
    """Guard the guard: the rule's AST extraction of SITES must agree with
    the imported runtime tuple, and sites stay dot-namespaced so grep hits
    and README cells are unambiguous."""
    project = load_project(REPO, with_context=False)
    reg = project.find_file("reliability/failpoints.py")
    assert reg is not None
    sites = FailpointCoverageRule()._sites(reg)
    assert set(sites) == set(SITES)
    assert len(sites) >= 21
    assert "ops.paged_attn" in sites  # PR 11: paged-attention kernel drill
    assert "engine.grammar" in sites  # PR 12: constrained-decoding drill
    assert "continuous.step" in sites  # PR 13: decode-step hang drill
    assert "continuous.worker" in sites  # PR 13: worker-crash drill
    assert "serving.trace" in sites  # PR 14: tracer-degradation drill
    assert "scheduler.tenant" in sites  # PR 16: quota-exhaustion drill
    assert "batch.store" in sites  # PR 17: torn journal-append drill
    assert "batch.worker" in sites  # PR 17: batch-lane worker-crash drill
    assert "continuous.prefill" in sites  # PR 18: mid-chunk prefill-hang drill
    for site in sites:
        sub, _, name = site.partition(".")
        assert sub and name, f"site {site!r} must be subsystem.name"


def test_action_whitelist_is_extracted():
    """The FailSpec action vocabulary comes from the real membership check,
    not a copy — if extraction breaks, coverage of action variants silently
    stops, so pin it."""
    project = load_project(REPO, with_context=False)
    reg = project.find_file("reliability/failpoints.py")
    actions = FailpointCoverageRule()._actions(reg)
    assert len(actions) >= 4
    assert "raise" in actions and "hang" in actions
    assert "crash" in actions  # PR 13: worker-thread kill drill
    assert "drop" in actions  # PR 14: tracer degrades to no-op spans
    assert "exhaust" in actions  # PR 16: tenant-bucket exhaustion drill
    assert "torn" in actions  # PR 17: mid-append journal-tear drill
