"""Client embeddings-helper parity: crop/pricing/validation (`get_embeddings`,
reference `client.py:75-122`) and the async selective-crop + crop-all-retry
ladder (`async_get_embeddings`, reference `client.py:125-196`), plus the two
standalone consensus helpers (`consensus_utils.py:1243-1263`, :1355-1370).
"""

import asyncio
from typing import List

import pytest

from k_llms_tpu.backends.base import Backend, ChatRequest
from k_llms_tpu.client import MAX_TOKENS_PER_MODEL, PRICING, AsyncKLLMs, KLLMs
from k_llms_tpu.consensus import (
    compute_similarity_scores,
    intermediary_consensus_cleanup,
)
from k_llms_tpu.consensus.similarity import SimilarityScorer

from reference_oracle import load_reference_engine, reference_available


class RecordingBackend(Backend):
    """Backend that records embedding batches; crops at the character level so
    crop behavior is observable without a tokenizer; optionally fails the first
    embedding call (to exercise the async retry ladder)."""

    def __init__(self, fail_at_call: int = -1, tokens_per_batch: int = 100):
        self.batches: List[List[str]] = []
        self.crop_calls: List[int] = []
        self.models_seen: List[str] = []
        self.fail_at_call = fail_at_call
        self.call_count = 0
        self.tokens_per_batch = tokens_per_batch

    def chat_completion(self, request: ChatRequest):  # pragma: no cover - unused
        raise NotImplementedError

    def embeddings(self, texts: List[str]) -> List[List[float]]:
        self.call_count += 1
        if self.call_count - 1 == self.fail_at_call:
            raise RuntimeError("transient embedding failure")
        self.batches.append(list(texts))
        return [[float(len(t))] for t in texts]

    def embeddings_with_usage(self, texts: List[str], model=None):
        self.models_seen.append(model)
        return self.embeddings(texts), self.tokens_per_batch

    def crop_texts(self, texts: List[str], max_tokens: int, model=None) -> List[str]:
        self.crop_calls.append(len(texts))
        return [t[:max_tokens] for t in texts]


def test_get_embeddings_validates_model():
    client = KLLMs(backend=RecordingBackend())
    with pytest.raises(ValueError, match="not supported"):
        client.get_embeddings(["hello"], model="text-embedding-ada-002")


def test_get_embeddings_crops_and_batches():
    backend = RecordingBackend()
    client = KLLMs(backend=backend)
    texts = ["x" * 10000, "short", "y" * 9000]
    out = client.get_embeddings(texts, model="local", batch_size=2)
    # Character-level crop backend: every text capped at the model's max tokens.
    assert backend.batches[0][0] == "x" * MAX_TOKENS_PER_MODEL["local"]
    assert backend.batches[0][1] == "short"
    assert len(backend.batches) == 2  # 3 texts, batch_size=2
    assert out == [[8191.0], [5.0], [8191.0]]


def test_get_embeddings_pricing_accounting(capsys):
    backend = RecordingBackend(tokens_per_batch=1_000_000)
    client = KLLMs(backend=backend)
    client.get_embeddings(["a", "b"], model="text-embedding-3-small", verbose=True)
    captured = capsys.readouterr().out
    # 1M tokens at $0.020 / 1M == $0.02, printed exactly like the reference.
    assert "TOTAL PRICE: $0.020000" in captured
    assert PRICING["text-embedding-3-small"] == 0.020


def test_async_get_embeddings_selective_crop():
    backend = RecordingBackend()
    client = AsyncKLLMs(backend=backend)
    long_text = "z" * (MAX_TOKENS_PER_MODEL["local"] * 3 + 10)
    out = asyncio.run(client.async_get_embeddings([long_text, "tiny"], model="local"))
    # Selective crop: only the plausibly-over-cap text goes through crop_texts.
    assert backend.crop_calls == [1]
    assert out == [[float(MAX_TOKENS_PER_MODEL["local"])], [4.0]]


def test_async_get_embeddings_short_texts_skip_crop():
    backend = RecordingBackend()
    client = AsyncKLLMs(backend=backend)
    asyncio.run(client.async_get_embeddings(["a", "b", "c"], model="local"))
    assert backend.crop_calls == []


def test_async_get_embeddings_retries_with_crop_all():
    backend = RecordingBackend(fail_at_call=0)
    client = AsyncKLLMs(backend=backend)
    out = asyncio.run(client.async_get_embeddings(["hello", "world!"], model="local"))
    # First attempt failed; retry cropped ALL texts then succeeded.
    assert backend.crop_calls == [2]
    assert out == [[5.0], [6.0]]


def test_async_retry_accumulates_price_across_attempts(capsys):
    # 3 batches of 1; batch 2 (index 1) fails — the successful first batch's
    # tokens must still be billed in the final total (reference keeps one
    # running total_price across the failed try and the fallback loop).
    backend = RecordingBackend(fail_at_call=1, tokens_per_batch=1_000_000)
    client = AsyncKLLMs(backend=backend)
    out = asyncio.run(
        client.async_get_embeddings(
            ["aa", "bb", "cc"], model="text-embedding-3-small", batch_size=1, verbose=True
        )
    )
    assert len(out) == 3
    # 1 successful batch before the failure + 3 on retry = 4M tokens at $0.02/1M.
    assert "TOTAL PRICE: $0.080000" in capsys.readouterr().out


def test_model_passed_through_to_backend():
    backend = RecordingBackend()
    client = KLLMs(backend=backend)
    client.get_embeddings(["x"], model="text-embedding-3-large")
    assert backend.models_seen == ["text-embedding-3-large"]


def test_local_model_resolves_to_backend_default():
    backend = RecordingBackend()
    backend.embedding_model_name = "text-embedding-3-small"
    client = KLLMs(backend=backend)
    client.get_embeddings(["x"], model="local")
    # "local" maps to the model the backend will actually hit, so pricing and
    # crop caps follow it.
    assert backend.models_seen == ["text-embedding-3-small"]


def test_tpu_tokenizer_crop():
    from k_llms_tpu.backends.tpu import TpuBackend

    backend = TpuBackend(model="tiny")
    # Byte tokenizer: 1 token per byte; short texts skip the encode round-trip.
    assert backend.crop_texts(["abcdefgh", "xy"], max_tokens=4) == ["abcd", "xy"]
    # The internal cap in embeddings() agrees with the crop: same vectors.
    long = "q" * 20000
    short = long[:8191]
    assert backend.embeddings([long])[0] == backend.embeddings([short])[0]


def test_paid_backend_unknown_default_model_errors():
    backend = RecordingBackend()
    backend.embedding_model_name = "text-embedding-ada-002"
    backend.bills_usage = True  # paid backend: a $0 fallback would mis-bill
    client = KLLMs(backend=backend)
    with pytest.raises(ValueError, match="not supported"):
        client.get_embeddings(["x"], model="local")


def test_unknown_backend_default_model_is_tolerated():
    backend = RecordingBackend()
    backend.embedding_model_name = "custom-embedder-v2"
    client = KLLMs(backend=backend)
    # "local" resolves to an out-of-table backend default: default cap, $0 price.
    out = client.get_embeddings(["hello"], model="local")
    assert out == [[5.0]]
    assert backend.models_seen == ["custom-embedder-v2"]
    # A USER-named unknown model still errors (reference behavior).
    with pytest.raises(ValueError, match="not supported"):
        client.get_embeddings(["hello"], model="custom-embedder-v2")


# --- standalone consensus helpers -------------------------------------------


def test_compute_similarity_scores_basic():
    scorer = SimilarityScorer(method="levenshtein")
    assert compute_similarity_scores([], scorer) == []
    assert compute_similarity_scores(["solo"], scorer) == [1.0]
    scores = compute_similarity_scores(["alpha", "alpha", "omega"], scorer)
    assert scores[0] == scores[1] > scores[2]


def test_intermediary_consensus_cleanup():
    obj = {
        "keep": "  value  ",
        "empty": "",
        "blank": "   ",
        "nested": {"inner": "", "deep": {"x": "  "}},
        "items": ["", "a", {"b": ""}],
        "num": 0,
        "flag": False,
    }
    cleaned = intermediary_consensus_cleanup(obj)
    assert cleaned == {"keep": "value", "items": ["a"], "num": 0, "flag": False}
    assert intermediary_consensus_cleanup({"a": {"b": ""}}) is None
    assert intermediary_consensus_cleanup([""]) is None


@pytest.mark.skipif(not reference_available(), reason="reference tree not mounted")
def test_helpers_parity_vs_reference():
    ref = load_reference_engine()
    values_sets = [
        ["alpha beta", "alpha betta", "gamma delta"],
        [1.0, 1.01, 5.0, None and 0 or 2.0],
        [{"a": "x"}, {"a": "y"}, {"a": "x"}],
    ]
    settings = ref.ConsensusSettings(string_similarity_method="levenshtein")
    ours = SimilarityScorer(method="levenshtein")
    for values in values_sets:
        expected = ref.compute_similarity_scores(values, settings, None)
        got = compute_similarity_scores(values, ours)
        assert got == expected

    structures = [
        {"a": " x ", "b": "", "c": {"d": "  ", "e": [1, "", {"f": ""}]}},
        ["", "  ", {"g": ["", 0, False]}],
        "  trimmed  ",
        0,
        None,
    ]
    for obj in structures:
        assert intermediary_consensus_cleanup(obj) == ref.intermediary_consensus_cleanup(obj)
