"""Ring decode against the SEQUENCE-SHARDED prefix (VERDICT r2 #6): with
``sp_decode=True`` the SP prefill's KV never regathers to the replicated
layout — decode attends it in place via ring attention — and the outputs are
bit-equal to the dense single-engine path."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k_llms_tpu.engine.engine import LocalEngine
from conftest import shared_engine, shared_params
from k_llms_tpu.models import get_config
from k_llms_tpu.ops.ring_attention import ring_decode_prefix
from k_llms_tpu.parallel.mesh import make_mesh

PROMPT = [int(x) for x in jax.random.randint(jax.random.key(40), (64,), 5, 200)]


def _mesh_ok():
    return len(jax.devices()) >= 8


pytestmark = pytest.mark.skipif(
    not _mesh_ok(), reason="needs the 8-device CPU mesh"
)


# -- op level ----------------------------------------------------------------

def test_ring_decode_prefix_matches_dense_attention():
    """(out, m, l) from the ring decode op must reproduce plain softmax
    attention over the valid prefix keys."""
    mesh = make_mesh(8, 1)
    B, QH, KVH, D, S = 8, 4, 2, 16, 64
    plen = 50
    q = jax.random.normal(jax.random.key(1), (B, QH, D), jnp.float32)
    pk = jax.random.normal(jax.random.key(2), (1, S, KVH, D), jnp.float32)
    pv = jax.random.normal(jax.random.key(3), (1, S, KVH, D), jnp.float32)

    out, m, l = jax.jit(
        lambda q, pk, pv: ring_decode_prefix(mesh, q, pk, pv, jnp.int32(plen))
    )(q, pk, pv)

    G = QH // KVH
    qg = np.asarray(q).reshape(B, KVH, G, D)
    k = np.asarray(pk)[0]  # [S, KVH, D]
    v = np.asarray(pv)[0]
    scale = 1.0 / np.sqrt(D)
    s = np.einsum("bhgd,shd->bhgs", qg, k) * scale
    s[..., plen:] = -np.inf
    w = np.exp(s - s.max(-1, keepdims=True))
    w = w / w.sum(-1, keepdims=True)
    ref = np.einsum("bhgs,shd->bhgd", w, v).reshape(B, QH, D)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5, atol=2e-5)
    # m/l form a valid logsumexp decomposition of the same softmax
    lse = np.log(np.exp(s - s.max(-1, keepdims=True)).sum(-1)) + s.max(-1)
    np.testing.assert_allclose(
        (np.asarray(m) + np.log(np.asarray(l))).reshape(B, KVH, G), lse, rtol=1e-5, atol=1e-5
    )


# -- engine level ------------------------------------------------------------

@pytest.fixture(scope="module")
def engines():
    from conftest import shared_engine

    dense = shared_engine("tiny")
    ring = shared_engine(
        "tiny", mesh_shape=(4, 2), sp_prefill_min_tokens=48, sp_decode=True,
    )
    return dense, ring


def test_sp_decode_matches_dense(engines):
    dense, ring = engines
    kw = dict(n=4, max_new_tokens=6, temperature=0.0, seed=11)
    r_d = dense.generate(PROMPT, **kw)
    r_r = ring.generate(PROMPT, **kw)
    assert ring._sp_prefill_cache, "SP prefill route was not taken"
    np.testing.assert_array_equal(r_r.tokens, r_d.tokens)
    np.testing.assert_allclose(r_r.logprobs, r_d.logprobs, rtol=1e-4, atol=1e-4)
    assert r_r.finish_reasons == r_d.finish_reasons


def test_sp_decode_sampled_matches_dense(engines):
    """Sampling streams are seed-deterministic, so even at temperature>0 the
    ring-decode engine must reproduce the dense engine exactly."""
    dense, ring = engines
    kw = dict(n=4, max_new_tokens=5, temperature=0.9, seed=23)
    r_d = dense.generate(PROMPT, **kw)
    r_r = ring.generate(PROMPT, **kw)
    np.testing.assert_array_equal(r_r.tokens, r_d.tokens)


def test_sp_decode_prefix_stays_sequence_sharded(engines):
    """The decode path must consume the prefix WITHOUT regathering: the stored
    SP prefill output's sharding shards the sequence axis over 'data'."""
    _, ring = engines
    fl, prefix = ring._prefill_full(PROMPT, len(PROMPT), 64)
    spec = prefix.k.sharding.spec
    assert spec[2] == "data", spec  # [L, B, S, KVH, D] — S sharded over data


def test_short_prompts_keep_replicated_path(engines):
    """Below sp_prefill_min_tokens the normal dense prefill + replicated
    decode runs (no ring loop variant)."""
    dense, ring = engines
    short = PROMPT[:20]
    kw = dict(n=2, max_new_tokens=4, temperature=0.0, seed=5)
    np.testing.assert_array_equal(
        ring.generate(short, **kw).tokens, dense.generate(short, **kw).tokens
    )


def test_sp_decode_composes_with_prefix_cache_exact_hits():
    """Exact repeats of an SP-resident prompt reuse the cached seq-sharded KV
    (no re-prefill) and reproduce the same generation."""
    cfg = get_config("tiny")
    params = shared_params(cfg)
    mesh = make_mesh(4, 2)
    eng = LocalEngine(
        cfg, params=params, mesh=mesh,
        sp_prefill_min_tokens=48, sp_decode=True, prefix_cache_size=2,
    )
    kw = dict(n=4, max_new_tokens=4, temperature=0.7, seed=13)
    r1 = eng.generate(PROMPT, **kw)
    assert eng.prefix_cache_stats == {"hits": 0, "partial_hits": 0, "misses": 1}
    r2 = eng.generate(PROMPT, **kw)
    assert eng.prefix_cache_stats["hits"] == 1
    np.testing.assert_array_equal(r1.tokens, r2.tokens)


def test_sp_exact_hit_ignores_replicated_layout_entry():
    """Regression: _sp_prefill_routed's exact-hit path used to return ANY
    entry under the prompt key without checking its layout label — handing a
    REPLICATED prefix to ring decode, which gathers the whole O(S) prefix
    into every device's HBM (the exact spike sp_decode exists to avoid). A
    wrong-layout hit must be treated as a miss and overwritten with the
    sequence-sharded twin."""
    cfg = get_config("tiny")
    params = shared_params(cfg)
    dense = shared_engine("tiny")
    mesh = make_mesh(4, 2)
    eng = LocalEngine(
        cfg, params=params, mesh=mesh,
        sp_prefill_min_tokens=48, sp_decode=True, prefix_cache_size=2,
    )
    # Plant a replicated-layout entry under the exact prompt key (what a
    # replicated-path run sharing the cache would leave behind).
    bucket = 64
    tokens = jnp.array(
        [PROMPT + [cfg.pad_token_id] * (bucket - len(PROMPT))], jnp.int32
    )
    fl, pref = eng._get_prefill(bucket)(eng.params, tokens, jnp.int32(len(PROMPT)))
    assert not eng._kv_seq_sharded(pref)
    eng._prefix_store(PROMPT, fl, pref, seq_sharded=False)

    kw = dict(n=4, max_new_tokens=4, temperature=0.0, seed=3)
    r = eng.generate(PROMPT, **kw)
    assert eng.prefix_cache_stats["hits"] == 0  # wrong layout: NOT a hit
    assert eng.prefix_cache_stats["misses"] == 1
    entry = eng._prefix_entries[tuple(PROMPT)]
    assert entry[4] is True
    assert entry[1].k.sharding.spec[2] == "data"
    np.testing.assert_array_equal(r.tokens, dense.generate(PROMPT, **kw).tokens)
    # The overwritten (right-layout) entry now serves exact hits.
    eng.generate(PROMPT, **kw)
    assert eng.prefix_cache_stats["hits"] == 1


def test_seq_sharded_cache_entry_never_partial_matches():
    """A seq-sharded (sp_decode) cache entry must be exact-hit-only: a shorter
    prompt sharing its prefix takes a full prefill (miss), never the
    replicated continuation that would all-gather the O(S) prefix."""
    cfg = get_config("tiny")
    params = shared_params(cfg)
    mesh = make_mesh(4, 2)
    eng = LocalEngine(
        cfg, params=params, mesh=mesh,
        sp_prefill_min_tokens=48, sp_decode=True,
        prefix_cache_size=2, prefix_cache_min_reuse=16,
    )
    eng.generate(PROMPT, n=4, max_new_tokens=2, temperature=0.5, seed=1)
    assert eng.prefix_cache_stats["misses"] == 1
    # Shorter prompt sharing a >=16-token prefix: below the SP threshold, so
    # it routes through the replicated cache path — which must NOT partial-hit
    # the seq-sharded entry.
    short = PROMPT[:20]
    eng.generate(short, n=2, max_new_tokens=2, temperature=0.5, seed=2)
    assert eng.prefix_cache_stats["partial_hits"] == 0
    assert eng.prefix_cache_stats["misses"] == 2


def test_prefill_with_cache_labels_sp_entries_seq_sharded():
    """The prefix-cache MISS path (generate_many / _prefill_routed) must store
    SP-prefilled KV with the seq_sharded label (ADVICE r3): unlabeled, a later
    longer prompt would partial-hit it and the replicated continuation would
    all-gather the O(S) prefix."""
    cfg = get_config("tiny")
    params = shared_params(cfg)
    mesh = make_mesh(4, 2)
    eng = LocalEngine(
        cfg, params=params, mesh=mesh,
        sp_prefill_min_tokens=48, sp_decode=True,
        prefix_cache_size=2, prefix_cache_min_reuse=16,
    )
    eng._prefill_routed(PROMPT, len(PROMPT), 64)
    entry = eng._prefix_entries[tuple(PROMPT)]
    assert entry[4] is True, "SP-prefilled cache entry mislabeled as replicated"
    assert entry[1].k.sharding.spec[2] == "data"
    # A longer prompt sharing the whole prefix must NOT partial-hit it.
    longer = PROMPT + PROMPT[:32]
    eng._prefill_routed(longer, len(longer), 128)
    assert eng.prefix_cache_stats["partial_hits"] == 0
    assert eng.prefix_cache_stats["misses"] == 2


def test_generate_many_with_sp_decode_prefix_cache_bit_equal():
    """Coalesced requests through the sp_decode + prefix-cache engine must
    reproduce the dense engine exactly (the resharding of the seq-sharded
    entry to the replicated layout happens once, after _prefill_routed)."""
    from k_llms_tpu.engine.engine import GenRequestSpec

    cfg = get_config("tiny")
    params = shared_params(cfg)
    dense = shared_engine("tiny")
    mesh = make_mesh(4, 2)
    eng = LocalEngine(
        cfg, params=params, mesh=mesh,
        sp_prefill_min_tokens=48, sp_decode=True,
        prefix_cache_size=2, prefix_cache_min_reuse=16,
    )
    items = [
        GenRequestSpec(prompt_ids=PROMPT, n=2, seed=7),
        GenRequestSpec(prompt_ids=PROMPT[:20], n=2, seed=9),
    ]
    kw = dict(max_new_tokens=4, temperature=0.8)
    got = eng.generate_many(items, **kw)
    want = dense.generate_many(items, **kw)
    for g, w in zip(got, want):
        np.testing.assert_array_equal(g.tokens, w.tokens)


# -- ring-layout continuation prefill (VERDICT r3 #6) ------------------------

def test_sp_partial_hit_continues_in_ring_layout():
    """A growing prompt re-using a cached SP-resident prefix must take the
    ring-layout CONTINUATION (partial hit — no full re-prefill), produce a
    sequence-sharded entry, and generate tokens bit-equal to the dense
    engine's."""
    cfg = get_config("tiny")
    params = shared_params(cfg)
    dense = shared_engine("tiny")
    mesh = make_mesh(4, 2)
    eng = LocalEngine(
        cfg, params=params, mesh=mesh,
        sp_prefill_min_tokens=48, sp_decode=True,
        prefix_cache_size=4, prefix_cache_min_reuse=16,
    )
    kw = dict(n=4, max_new_tokens=4, temperature=0.7, seed=13)

    r1 = eng.generate(PROMPT, **kw)
    assert eng.prefix_cache_stats == {"hits": 0, "partial_hits": 0, "misses": 1}
    np.testing.assert_array_equal(r1.tokens, dense.generate(PROMPT, **kw).tokens)

    longer = PROMPT + [int(x) for x in jax.random.randint(jax.random.key(7), (30,), 5, 200)]
    r2 = eng.generate(longer, **kw)
    assert eng.prefix_cache_stats["partial_hits"] == 1
    assert eng.prefix_cache_stats["misses"] == 1  # no full re-prefill
    np.testing.assert_array_equal(r2.tokens, dense.generate(longer, **kw).tokens)

    # The continuation's entry is itself sequence-sharded and re-usable:
    # a third, even longer prompt continues from IT.
    entry = eng._prefix_entries[tuple(longer)]
    assert entry[4] is True
    assert entry[1].k.sharding.spec[2] == "data"
    longest = longer + [int(x) for x in jax.random.randint(jax.random.key(8), (20,), 5, 200)]
    r3 = eng.generate(longest, **kw)
    assert eng.prefix_cache_stats["partial_hits"] == 2
    assert eng.prefix_cache_stats["misses"] == 1
    np.testing.assert_array_equal(r3.tokens, dense.generate(longest, **kw).tokens)


def test_sp_continuation_crosses_bucket_boundary():
    """Continuation where the longer prompt lands in a BIGGER bucket: the
    stored prefix grows to the new bucket (sharded pad) and outputs stay
    bit-equal to dense."""
    cfg = get_config("tiny")
    params = shared_params(cfg)
    dense = shared_engine("tiny")
    mesh = make_mesh(4, 2)
    eng = LocalEngine(
        cfg, params=params, mesh=mesh,
        sp_prefill_min_tokens=48, sp_decode=True,
        prefix_cache_size=4, prefix_cache_min_reuse=16,
    )
    kw = dict(n=4, max_new_tokens=3, temperature=0.6, seed=29)
    eng.generate(PROMPT, **kw)  # bucket 64
    # 64 + 80 = 144 tokens -> bucket 256 > the entry's 64
    longer = PROMPT + [int(x) for x in jax.random.randint(jax.random.key(3), (80,), 5, 200)]
    r2 = eng.generate(longer, **kw)
    assert eng.prefix_cache_stats["partial_hits"] == 1
    np.testing.assert_array_equal(r2.tokens, dense.generate(longer, **kw).tokens)
    assert eng._prefix_entries[tuple(longer)][1].k.shape[2] == 256


def test_sp_continuation_logprobs_match_dense():
    """Float agreement, not just greedy tokens: continuation-path logprobs
    must match the dense engine's within tolerance."""
    cfg = get_config("tiny")
    params = shared_params(cfg)
    dense = shared_engine("tiny")
    mesh = make_mesh(4, 2)
    eng = LocalEngine(
        cfg, params=params, mesh=mesh,
        sp_prefill_min_tokens=48, sp_decode=True,
        prefix_cache_size=2, prefix_cache_min_reuse=16,
    )
    kw = dict(n=2, max_new_tokens=4, temperature=0.0, seed=5)
    eng.generate(PROMPT, **kw)
    longer = PROMPT + [int(x) for x in jax.random.randint(jax.random.key(11), (25,), 5, 200)]
    r = eng.generate(longer, **kw)
    assert eng.prefix_cache_stats["partial_hits"] == 1
    want = dense.generate(longer, **kw)
    np.testing.assert_array_equal(r.tokens, want.tokens)
    np.testing.assert_allclose(r.logprobs, want.logprobs, rtol=2e-4, atol=2e-4)


def test_suffix_prefix_attention_matches_dense():
    """(acc, m, l) from the one-psum suffix-vs-prefix op must reproduce plain
    softmax attention over the valid prefix keys, for every suffix query."""
    from k_llms_tpu.ops.ring_attention import suffix_prefix_attention

    mesh = make_mesh(8, 1)
    QH, KVH, D, S, Sq = 4, 2, 16, 64, 8
    plen = 41
    q = jax.random.normal(jax.random.key(1), (1, QH, Sq, D), jnp.float32)
    pk = jax.random.normal(jax.random.key(2), (1, S, KVH, D), jnp.float32)
    pv = jax.random.normal(jax.random.key(3), (1, S, KVH, D), jnp.float32)

    acc, m, l = jax.jit(
        lambda q, pk, pv: suffix_prefix_attention(mesh, q, pk, pv, jnp.int32(plen))
    )(q, pk, pv)

    G = QH // KVH
    qg = np.asarray(q).reshape(1, KVH, G, Sq, D)
    k = np.asarray(pk)[0]
    v = np.asarray(pv)[0]
    s = np.einsum("bhgqd,shd->bhgqs", qg, k) / np.sqrt(D)
    s[..., plen:] = -np.inf
    s = s.reshape(1, QH, Sq, S)
    w = np.exp(s - s.max(-1, keepdims=True))
    ref_out = np.einsum(
        "bhgqs,shd->bhgqd", (w / w.sum(-1, keepdims=True)).reshape(1, KVH, G, Sq, S), v
    ).reshape(1, QH, Sq, D)
    got = np.asarray(acc) / np.asarray(l)[..., None]
    np.testing.assert_allclose(got, ref_out, rtol=2e-5, atol=2e-5)
    # (m, l) is a valid logsumexp decomposition
    lse = np.log(np.exp(s - s.max(-1, keepdims=True)).sum(-1)) + s.max(-1)
    np.testing.assert_allclose(np.asarray(m) + np.log(np.asarray(l)), lse, rtol=1e-5, atol=1e-5)


def test_scatter_into_ring_writes_only_suffix_rows():
    from k_llms_tpu.ops.ring_attention import scatter_into_ring

    mesh = make_mesh(8, 1)
    S, Ssuf, KVH, D = 64, 16, 2, 4
    base = jax.random.normal(jax.random.key(1), (1, S, KVH, D), jnp.float32)
    suf = jax.random.normal(jax.random.key(2), (1, Ssuf, KVH, D), jnp.float32)
    start, total = 37, 48  # 11 real suffix rows; rows 48.. stay untouched
    out = jax.jit(
        lambda b, s: scatter_into_ring(mesh, b, s, jnp.int32(start), jnp.int32(total))
    )(base, suf)
    out = np.asarray(out)
    want = np.asarray(base).copy()
    want[0, start:total] = np.asarray(suf)[0, : total - start]
    np.testing.assert_array_equal(out, want)
