"""Mixtral-family mixture-of-experts: dense-einsum top-k routing, expert
parallelism over the model mesh axis, int8 expert weights, HF import."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k_llms_tpu.engine.engine import LocalEngine
from k_llms_tpu.engine.tokenizer import ByteTokenizer
from k_llms_tpu.models import get_config, init_params
from k_llms_tpu.models.llama import decode_step, forward, init_cache, prefill

TINY_MOE = get_config("tiny").with_(name="tiny-moe", num_experts=4, num_experts_per_tok=2)


def test_registry_mixtral():
    cfg = get_config("mixtral-8x7b")
    assert cfg.num_experts == 8 and cfg.num_experts_per_tok == 2
    assert cfg.rope_theta == 1000000.0


def test_moe_param_tree():
    params = init_params(TINY_MOE, jax.random.key(0))
    layers = params["layers"]
    L, E, H, I = 2, 4, 64, 160
    assert layers["w_router"].shape == (L, H, E)
    assert layers["w_gate"].shape == (L, E, H, I)
    assert layers["w_down"].shape == (L, E, I, H)


def test_top1_dominant_router_selects_expert():
    """With a router hugely preferring expert j, the MoE output must equal that
    expert's dense MLP output (softmax over top-k -> weight ~1 on j)."""
    from k_llms_tpu.models.llama import _moe_mlp

    params = init_params(TINY_MOE, jax.random.key(1))
    layer = {k: v[0] for k, v in params["layers"].items()}
    H = TINY_MOE.hidden_size
    j = 2
    router = jnp.full((H, TINY_MOE.num_experts), -1e4, jnp.float32).at[:, j].set(1e4)
    layer = dict(layer)
    layer["w_router"] = router.astype(layer["w_router"].dtype)

    # Positive activations keep h @ router[:, j] hugely positive for col j.
    h = jnp.abs(jax.random.normal(jax.random.key(2), (1, 3, H), jnp.float32)) + 0.1
    out = _moe_mlp(TINY_MOE, layer, h)

    gate = jax.nn.silu(h @ layer["w_gate"][j])
    up = h @ layer["w_up"][j]
    expected = (gate * up) @ layer["w_down"][j]
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), rtol=2e-2, atol=2e-2)


def test_moe_decode_matches_forward():
    cfg = TINY_MOE
    params = init_params(cfg, jax.random.key(3))
    S = 12
    tokens = jax.random.randint(jax.random.key(4), (1, S), 0, cfg.vocab_size)
    prompt_len = jnp.int32(8)

    pl_logits, prefix = prefill(cfg, params, tokens, prompt_len)
    full, _ = forward(
        cfg, params, tokens, (jnp.arange(S)[None, :] < prompt_len).astype(jnp.int32)
    )
    np.testing.assert_allclose(pl_logits[0], full[0, 7], rtol=1e-4, atol=1e-4)

    n = 2
    gen_cache = init_cache(cfg, n, 4)
    for step in range(3):
        tk = jnp.broadcast_to(tokens[0, 8 + step], (n,))
        logits, gen_cache = decode_step(
            cfg, params, tk, jnp.int32(step), prompt_len, gen_cache, prefix
        )
        full_s, _ = forward(
            cfg, params, tokens, (jnp.arange(S)[None, :] < 9 + step).astype(jnp.int32)
        )
        np.testing.assert_allclose(logits[0], full_s[0, 8 + step], rtol=1e-4, atol=1e-4)


def test_moe_engine_generate():
    engine = LocalEngine(TINY_MOE, use_mesh=False)
    tok = ByteTokenizer()
    ids = tok.apply_chat_template([{"role": "user", "content": "moe check"}])
    r = engine.generate(ids, n=3, max_new_tokens=6, temperature=1.0, seed=0)
    assert r.tokens.shape == (3, 6)


def test_moe_expert_parallel_matches_single_device():
    """EP sharding (experts over 'model') must be numerically identical to the
    unsharded program — GSPMD inserts the combine reduction."""
    if len(jax.devices()) < 4:
        pytest.skip("needs the 8-device CPU mesh")
    from k_llms_tpu.parallel.mesh import make_mesh

    cfg = TINY_MOE.with_(dtype="float32")
    params = init_params(cfg, jax.random.key(5))
    tok = ByteTokenizer()
    ids = tok.apply_chat_template([{"role": "user", "content": "expert parallel"}])

    single = LocalEngine(cfg, params=params, use_mesh=False)
    r1 = single.generate(ids, n=4, max_new_tokens=6, temperature=0.0, seed=1)

    mesh = make_mesh(2, 2, jax.devices()[:4])  # tp=2 shards 4 experts 2-way
    sharded = LocalEngine(cfg, params=params, mesh=mesh)
    r2 = sharded.generate(ids, n=4, max_new_tokens=6, temperature=0.0, seed=1)
    np.testing.assert_array_equal(r1.tokens, r2.tokens)


def test_moe_quantized_forward_close():
    from k_llms_tpu.models.quant import QTensor, quantize_params

    params = init_params(TINY_MOE, jax.random.key(6))
    qparams = quantize_params(params)
    assert isinstance(qparams["layers"]["w_gate"], QTensor)
    assert qparams["layers"]["w_gate"].scale.shape == (2, 4, 1, 160)
    assert not isinstance(qparams["layers"]["w_router"], QTensor)  # router stays dense

    tokens = jnp.array([[5, 6, 7, 8]], jnp.int32)
    mask = jnp.ones_like(tokens)
    a, _ = forward(TINY_MOE, params, tokens, mask)
    b, _ = forward(TINY_MOE, qparams, tokens, mask)
    tv = 0.5 * jnp.abs(jax.nn.softmax(a, -1) - jax.nn.softmax(b, -1)).sum(-1).mean()
    assert float(tv) < 0.05


def test_config_from_hf_mixtral(tmp_path):
    from k_llms_tpu.models.loader import config_from_hf

    hf = {
        "model_type": "mixtral",
        "vocab_size": 32000,
        "hidden_size": 4096,
        "intermediate_size": 14336,
        "num_hidden_layers": 32,
        "num_attention_heads": 32,
        "num_key_value_heads": 8,
        "rope_theta": 1000000.0,
        "rms_norm_eps": 1e-5,
        "max_position_embeddings": 32768,
        "num_local_experts": 8,
        "num_experts_per_tok": 2,
        "bos_token_id": 1,
        "eos_token_id": 2,
    }
    d = tmp_path / "mixtral"
    d.mkdir()
    (d / "config.json").write_text(json.dumps(hf))
    cfg = config_from_hf(str(d))
    assert cfg.num_experts == 8 and cfg.num_experts_per_tok == 2


def test_safetensors_import_mixtral(tmp_path):
    from safetensors.numpy import save_file

    from k_llms_tpu.models.loader import load_safetensors

    cfg = TINY_MOE.with_(dtype="float32")
    params = init_params(cfg, jax.random.key(7))

    tensors = {
        "model.embed_tokens.weight": np.asarray(params["embed"]),
        "model.norm.weight": np.asarray(params["final_norm"]),
        "lm_head.weight": np.ascontiguousarray(np.asarray(params["lm_head"]).T),
    }
    for i in range(cfg.num_layers):
        for ours, hf in (
            ("wq", "self_attn.q_proj"),
            ("wk", "self_attn.k_proj"),
            ("wv", "self_attn.v_proj"),
            ("wo", "self_attn.o_proj"),
        ):
            tensors[f"model.layers.{i}.{hf}.weight"] = np.ascontiguousarray(
                np.asarray(params["layers"][ours][i]).T
            )
        tensors[f"model.layers.{i}.input_layernorm.weight"] = np.asarray(
            params["layers"]["attn_norm"][i]
        )
        tensors[f"model.layers.{i}.post_attention_layernorm.weight"] = np.asarray(
            params["layers"]["mlp_norm"][i]
        )
        tensors[f"model.layers.{i}.block_sparse_moe.gate.weight"] = np.ascontiguousarray(
            np.asarray(params["layers"]["w_router"][i]).T
        )
        for e in range(cfg.num_experts):
            for ours, hf in (("w_gate", "w1"), ("w_up", "w3"), ("w_down", "w2")):
                tensors[
                    f"model.layers.{i}.block_sparse_moe.experts.{e}.{hf}.weight"
                ] = np.ascontiguousarray(np.asarray(params["layers"][ours][i, e]).T)
    ckpt = tmp_path / "hf-mixtral"
    ckpt.mkdir()
    save_file(tensors, str(ckpt / "model.safetensors"))

    loaded = load_safetensors(str(ckpt), cfg, dtype=jnp.float32)
    tokens = jax.random.randint(jax.random.key(8), (1, 8), 0, cfg.vocab_size)
    mask = jnp.ones_like(tokens)
    a, _ = forward(cfg, params, tokens, mask)
    b, _ = forward(cfg, loaded, tokens, mask)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5)
