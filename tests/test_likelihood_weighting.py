"""Likelihood-weighted consensus (BASELINE.json config 3): sample votes are
weighted by softmax of sequence logprobs; OFF by default (reference-exact)."""

import math

import pytest

from k_llms_tpu import KLLMs
from k_llms_tpu.consensus.settings import ConsensusSettings
from k_llms_tpu.consensus.voting import voting_consensus
from k_llms_tpu.consensus.primitive import consensus_as_primitive
from k_llms_tpu.consensus.similarity import SimilarityScorer
from k_llms_tpu.types import ChatCompletion
from k_llms_tpu.consensus.consolidation import consolidate_chat_completions


def test_weighted_voting_flips_majority():
    settings = ConsensusSettings()
    # unweighted: "no" wins 2-1
    val, conf = voting_consensus(["yes", "no", "no"], settings)
    assert val == "no"
    # one confident "yes" outweighs two unconfident "no"s
    val, conf = voting_consensus(["yes", "no", "no"], settings, weights=[0.8, 0.1, 0.1])
    assert val == "yes"
    assert conf == pytest.approx(0.8, abs=1e-4)


def test_weighted_numeric_cluster():
    scorer = SimilarityScorer.levenshtein()
    settings = ConsensusSettings()
    # 100 vs 200: the heavier sample wins even though counts tie
    val, conf = consensus_as_primitive(
        [100.0, 200.0], settings, scorer, weights=[0.9, 0.1]
    )
    assert val == pytest.approx(100.0)
    assert conf == pytest.approx(0.9, abs=1e-4)


def test_weights_none_is_reference_exact():
    scorer = SimilarityScorer.levenshtein()
    settings = ConsensusSettings()
    a = consensus_as_primitive([100, 101, 200], settings, scorer)
    b = consensus_as_primitive([100, 101, 200], settings, scorer, weights=None)
    assert a == b


def _completion_with_logprobs(contents_and_lps):
    return ChatCompletion.model_validate(
        {
            "id": "c",
            "created": 0,
            "model": "m",
            "object": "chat.completion",
            "choices": [
                {
                    "finish_reason": "stop",
                    "index": i,
                    "message": {"role": "assistant", "content": content},
                    "sample_logprob": lp,
                }
                for i, (content, lp) in enumerate(contents_and_lps)
            ],
        }
    )


def test_end_to_end_weighted_consolidation():
    comp = _completion_with_logprobs([("yes", -1.0), ("no", -8.0), ("no", -9.0)])
    scorer = SimilarityScorer.levenshtein()
    # default: agreement voting, "no" wins
    plain = consolidate_chat_completions(comp, scorer)
    assert plain.choices[0].message.content == "no"
    # weighted: the much-more-likely "yes" sample wins
    weighted = consolidate_chat_completions(
        comp, scorer, consensus_settings=ConsensusSettings(likelihood_weighting=True)
    )
    assert weighted.choices[0].message.content == "yes"


def test_tpu_backend_attaches_sample_logprob():
    client = KLLMs(backend="tpu", model="tiny", max_new_tokens=8)
    resp = client.chat.completions.create(
        messages=[{"role": "user", "content": "w"}], model="tiny", n=2, seed=4
    )
    for choice in resp.choices[1:]:
        lp = getattr(choice, "sample_logprob", None)
        assert lp is not None and lp <= 0.0
