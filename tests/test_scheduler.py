"""Request scheduler: serial device access, concurrent async clients."""

import asyncio
import threading
import time

import pytest

from k_llms_tpu import AsyncKLLMs
from k_llms_tpu.engine.scheduler import EngineScheduler


def test_scheduler_serializes():
    sched = EngineScheduler(name="t")
    active = []
    overlap = []

    def work(i):
        active.append(i)
        if len(active) > 1:
            overlap.append(tuple(active))
        time.sleep(0.01)
        active.remove(i)
        return i

    futures = [sched.submit(lambda i=i: work(i)) for i in range(8)]
    results = [f.result() for f in futures]
    assert results == list(range(8))
    assert overlap == []  # never two jobs at once
    assert sched.stats["served"] == 8
    sched.shutdown()


def test_scheduler_exception_propagates():
    sched = EngineScheduler(name="t2")

    def boom():
        raise RuntimeError("device on fire")

    with pytest.raises(RuntimeError, match="device on fire"):
        sched.submit(boom).result()
    # still serves after an error
    assert sched.call(lambda: 42) == 42
    assert sched.stats["errors"] == 1
    sched.shutdown()


def test_scheduler_reentrant_from_worker():
    sched = EngineScheduler(name="t3")

    def outer():
        return sched.call(lambda: "inner")  # would deadlock without reentrancy

    assert sched.call(outer) == "inner"
    sched.shutdown()


def test_concurrent_async_clients_share_engine():
    async def main():
        client = AsyncKLLMs(backend="tpu", model="tiny", max_new_tokens=6)
        reqs = [
            client.chat.completions.create(
                messages=[{"role": "user", "content": f"q{i}"}], model="tiny", n=2, seed=i
            )
            for i in range(4)
        ]
        return await asyncio.gather(*reqs)

    results = asyncio.run(main())
    assert len(results) == 4
    for r in results:
        assert len(r.choices) == 3
