"""Request scheduler: serial device access, concurrent async clients."""

import asyncio
import threading
import time

import pytest

from k_llms_tpu import AsyncKLLMs
from k_llms_tpu.engine.scheduler import EngineScheduler


def test_scheduler_serializes():
    sched = EngineScheduler(name="t")
    active = []
    overlap = []

    def work(i):
        active.append(i)
        if len(active) > 1:
            overlap.append(tuple(active))
        time.sleep(0.01)
        active.remove(i)
        return i

    futures = [sched.submit(lambda i=i: work(i)) for i in range(8)]
    results = [f.result() for f in futures]
    assert results == list(range(8))
    assert overlap == []  # never two jobs at once
    assert sched.stats["served"] == 8
    sched.shutdown()


def test_scheduler_exception_propagates():
    sched = EngineScheduler(name="t2")

    def boom():
        raise RuntimeError("device on fire")

    with pytest.raises(RuntimeError, match="device on fire"):
        sched.submit(boom).result()
    # still serves after an error
    assert sched.call(lambda: 42) == 42
    assert sched.stats["errors"] == 1
    sched.shutdown()


def test_scheduler_reentrant_from_worker():
    sched = EngineScheduler(name="t3")

    def outer():
        return sched.call(lambda: "inner")  # would deadlock without reentrancy

    assert sched.call(outer) == "inner"
    sched.shutdown()


def test_concurrent_async_clients_share_engine():
    async def main():
        client = AsyncKLLMs(backend="tpu", model="tiny", max_new_tokens=6)
        reqs = [
            client.chat.completions.create(
                messages=[{"role": "user", "content": f"q{i}"}], model="tiny", n=2, seed=i
            )
            for i in range(4)
        ]
        return await asyncio.gather(*reqs)

    results = asyncio.run(main())
    assert len(results) == 4
    for r in results:
        assert len(r.choices) == 3


# ---------------------------------------------------------------------------
# Cross-request coalescing (submit_batched)
# ---------------------------------------------------------------------------

def test_submit_batched_coalesces_same_key():
    sched = EngineScheduler(name="tb")
    gate = threading.Event()
    calls = []

    def runner(payloads):
        calls.append(list(payloads))
        return [p * 2 for p in payloads]

    # Occupy the worker so the batched items pile up in the queue.
    blocker = sched.submit(gate.wait)
    futs = [sched.submit_batched(("k",), i, runner) for i in range(5)]
    gate.set()
    assert [f.result(timeout=5) for f in futs] == [0, 2, 4, 6, 8]
    blocker.result(timeout=5)
    assert calls == [[0, 1, 2, 3, 4]]  # ONE runner call served all five
    stats = sched.stats
    assert stats["batches"] == 1
    assert stats["coalesced"] == 4
    sched.shutdown()


def test_submit_batched_respects_key_boundaries():
    sched = EngineScheduler(name="tb2")
    gate = threading.Event()
    calls = []

    def runner(payloads):
        calls.append(list(payloads))
        return list(payloads)

    blocker = sched.submit(gate.wait)
    futs = [
        sched.submit_batched(("a",), 1, runner),
        sched.submit_batched(("a",), 2, runner),
        sched.submit_batched(("b",), 3, runner),
        sched.submit_batched(("a",), 4, runner),
    ]
    gate.set()
    assert [f.result(timeout=5) for f in futs] == [1, 2, 3, 4]
    blocker.result(timeout=5)
    # Only the CONTIGUOUS head run coalesces: [1,2], then [3], then [4].
    assert calls == [[1, 2], [3], [4]]
    sched.shutdown()


def test_submit_batched_caps_batch_size():
    sched = EngineScheduler(name="tb3", max_batch=3)
    gate = threading.Event()
    calls = []

    def runner(payloads):
        calls.append(list(payloads))
        return list(payloads)

    blocker = sched.submit(gate.wait)
    futs = [sched.submit_batched(("k",), i, runner) for i in range(7)]
    gate.set()
    [f.result(timeout=5) for f in futs]
    blocker.result(timeout=5)
    assert [len(c) for c in calls] == [3, 3, 1]
    sched.shutdown()


def test_submit_batched_error_reaches_every_caller():
    sched = EngineScheduler(name="tb4")
    gate = threading.Event()

    def runner(payloads):
        raise RuntimeError("batch exploded")

    blocker = sched.submit(gate.wait)
    futs = [sched.submit_batched(("k",), i, runner) for i in range(3)]
    gate.set()
    blocker.result(timeout=5)
    for f in futs:
        with pytest.raises(RuntimeError, match="batch exploded"):
            f.result(timeout=5)
    assert sched.stats["errors"] == 3
    sched.shutdown()


def test_submit_batched_row_budget():
    """Groups stop growing when projected rows (len * max weight) would exceed
    max_rows — five n=32 requests must NOT fuse into one 160-row decode."""
    sched = EngineScheduler(name="tb5", max_rows=64)
    gate = threading.Event()
    calls = []

    def runner(payloads):
        calls.append(list(payloads))
        return list(payloads)

    blocker = sched.submit(gate.wait)
    futs = [sched.submit_batched(("k",), i, runner, weight=32) for i in range(5)]
    gate.set()
    [f.result(timeout=5) for f in futs]
    blocker.result(timeout=5)
    # 2 * 32 = 64 rows per group at most.
    assert [len(c) for c in calls] == [2, 2, 1]
    sched.shutdown()


def test_batch_window_fuses_concurrent_burst():
    """The admission window must fuse a concurrent burst into ONE runner call
    even though the first arrival finds an empty queue (without the window it
    would always decode solo)."""
    import threading

    from k_llms_tpu.engine.scheduler import EngineScheduler

    # max_batch == burst size: the group launches the instant the 5th client
    # is admitted, so the generous window bounds CI timing skew without ever
    # being waited in full.
    sched = EngineScheduler(name="t-window", max_batch=5, batch_window=10.0)
    calls = []

    def runner(payloads):
        calls.append(sorted(payloads))
        return [p * 2 for p in payloads]

    results = {}

    def client(i):
        results[i] = sched.call_batched(("k",), i, runner)

    threads = [threading.Thread(target=client, args=(i,)) for i in range(5)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert results == {i: i * 2 for i in range(5)}
    assert calls == [[0, 1, 2, 3, 4]]
    assert sched.stats["batches"] == 1 and sched.stats["coalesced"] == 4
    sched.shutdown()


def test_batch_window_does_not_delay_plain_submits():
    import time as _time

    from k_llms_tpu.engine.scheduler import EngineScheduler

    sched = EngineScheduler(name="t-window2", batch_window=0.5)
    t0 = _time.perf_counter()
    assert sched.call(lambda: 7) == 7
    assert _time.perf_counter() - t0 < 0.3  # no window applied to closures
    sched.shutdown()


def test_batch_window_breaks_at_key_boundary():
    """A different-key item at the queue head ends the window immediately (no
    5 s wait despite the huge window) — FIFO order is never violated to keep a
    window open, and the different-key item is never absorbed."""
    import time as _time

    from k_llms_tpu.engine.scheduler import EngineScheduler

    sched = EngineScheduler(name="t-window3", batch_window=5.0)
    calls = []

    def runner(payloads):
        calls.append(list(payloads))
        return payloads

    gate = sched.submit(lambda: __import__("time").sleep(0.05))
    fa = sched.submit_batched(("a",), 1, runner)
    # window=0 so "b" (which will head an empty queue after "a" runs) does not
    # sleep the scheduler-wide 5 s window and keep the test sub-second.
    fb = sched.submit_batched(("b",), 2, runner, window=0.0)
    gate.result(timeout=5)
    t0 = _time.perf_counter()
    assert fa.result(timeout=5) == 1  # "b" at the head closed "a"'s window
    assert _time.perf_counter() - t0 < 2.0
    assert fb.result(timeout=5) == 2
    assert calls == [[1], [2]]
    sched.shutdown()


def test_batch_window_skipped_when_budget_exhausted():
    """A head item that already exhausts the row budget cannot gain a partner,
    so the worker must not sleep the window at all (huge window + fast result
    proves the skip)."""
    import time as _time

    from k_llms_tpu.engine.scheduler import EngineScheduler

    sched = EngineScheduler(name="t-window4", max_rows=4, batch_window=10.0)
    t0 = _time.perf_counter()
    out = sched.call_batched(("k",), 5, lambda ps: [p + 1 for p in ps], weight=4)
    assert out == 6
    assert _time.perf_counter() - t0 < 2.0
    sched.shutdown()


@pytest.mark.slow  # 8s concurrency e2e; per-request spec_stats plumbing is
@pytest.mark.duration_budget(45)  # also covered by test_speculative
def test_concurrent_traced_requests_keep_their_own_spec_stats(monkeypatch):
    """Two concurrent traced requests must each carry their OWN generation-time
    engine stats even though they share one engine (the regression the
    GenerationResult.spec_stats threading exists to prevent)."""
    from k_llms_tpu import KLLMs
    from k_llms_tpu.backends.tpu import TpuBackend
    from k_llms_tpu.engine.engine import GenRequestSpec

    monkeypatch.setenv("KLLMS_TRACE", "1")
    backend = TpuBackend(
        model="tiny", max_new_tokens=4, speculative="prompt_lookup"
    )
    client = KLLMs(backend=backend)

    def one(i, out):
        out[i] = client.chat.completions.create(
            messages=[{"role": "user", "content": f"req {i}"}],
            model="tiny", n=2, seed=200 + i,
        )

    warm: dict = {}
    one(0, warm)  # compile the solo program shape
    tok = backend.tokenizer
    warm_ids = tok.apply_chat_template(
        [{"role": "user", "content": "req 0"}], add_generation_prompt=True
    )
    for r in (2, 4):  # compile the coalesced shapes a 3-thread race can hit
        backend.engine.generate_many(
            [GenRequestSpec(warm_ids, 2, i) for i in range(r)],
            max_new_tokens=backend.default_max_new_tokens,
            eos_ids=tok.stop_ids,
        )

    results: dict = {}
    threads = [threading.Thread(target=one, args=(i, results)) for i in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for i, resp in results.items():
        stats = resp.engine_stats
        assert set(stats) == {"spec", "prefix_cache", "scheduler"}
        spec = stats["spec"]
        # Each request's spec stats must be a VALID generation-time value for
        # that request: the spec loop's acceptance numbers (solo or
        # coalesced). A shared-state read racing another request's reset
        # would surface as {} here.
        assert "verify_iterations" in spec, spec


# -- request-lifecycle hardening: cancellation + shutdown -----------------


def test_cancelled_future_never_reaches_engine():
    """A queued request whose future is cancelled before admission to the
    worker must never run: set_running_or_notify_cancel filters it out."""
    sched = EngineScheduler(name="t-cancel")
    gate = threading.Event()
    ran = []

    blocker = sched.submit(lambda: gate.wait(5))
    victim = sched.submit(lambda: ran.append(1))
    assert victim.cancel()  # still queued behind the blocker
    gate.set()
    blocker.result(timeout=5)
    assert sched.call(lambda: "drain") == "drain"  # queue fully drained
    assert ran == []
    assert victim.cancelled()
    sched.shutdown()


def test_budget_cancelled_queued_request_shed_before_engine():
    """A budget cancelled while the item waits in the queue sheds at dequeue:
    the batch runner is never invoked for it and the caller gets the typed
    cancellation error."""
    from k_llms_tpu.reliability.deadline import RequestBudget
    from k_llms_tpu.types.wire import RequestCancelledError

    sched = EngineScheduler(name="t-shed")
    gate = threading.Event()
    runner_sizes = []

    def runner(payloads):
        runner_sizes.append(len(payloads))
        return list(payloads)

    blocker = sched.submit(lambda: gate.wait(5))
    budget = RequestBudget.from_timeout(None)
    fut = sched.submit_batched(("k",), "p", runner, budget=budget)
    budget.cancel()
    gate.set()
    blocker.result(timeout=5)
    with pytest.raises(RequestCancelledError):
        fut.result(timeout=5)
    assert sched.call(lambda: 1) == 1
    assert runner_sizes == []  # shed item never reached the runner
    assert sched.stats["shed"] == 1
    sched.shutdown()


def test_expired_budget_rejected_at_admission():
    """Work arriving with an already-spent budget is rejected at submit time
    (typed error on the future) instead of occupying queue space."""
    from k_llms_tpu.reliability.deadline import RequestBudget
    from k_llms_tpu.types.wire import RequestTimeoutError

    sched = EngineScheduler(name="t-adm")
    ran = []
    fut = sched.submit(lambda: ran.append(1), budget=RequestBudget.from_timeout(0.0))
    with pytest.raises(RequestTimeoutError):
        fut.result(timeout=1)
    assert sched.call(lambda: "after") == "after"
    assert ran == []
    assert sched.stats["shed"] == 1
    sched.shutdown()


def test_shutdown_joins_worker_with_work_in_flight():
    """shutdown() while the worker is mid-closure: the in-flight work
    completes, the sentinel drains, and the worker thread joins cleanly."""
    sched = EngineScheduler(name="t-down")
    started = threading.Event()

    def slow():
        started.set()
        time.sleep(0.2)
        return "done"

    fut = sched.submit(slow)
    assert started.wait(5)
    sched.shutdown()  # posted behind the in-flight item; join(timeout=5)
    assert not sched._worker.is_alive()
    assert fut.result(timeout=0) == "done"


def test_shutdown_with_queued_backlog_serves_backlog_first():
    """The shutdown sentinel is FIFO like everything else: items queued
    before shutdown() still run to completion before the worker exits."""
    sched = EngineScheduler(name="t-down2")
    gate = threading.Event()
    blocker = sched.submit(lambda: gate.wait(5))
    queued = [sched.submit(lambda i=i: i * i) for i in range(4)]
    gate.set()
    sched.shutdown()
    assert blocker.result(timeout=0) is True
    assert [f.result(timeout=0) for f in queued] == [0, 1, 4, 9]
    assert not sched._worker.is_alive()
