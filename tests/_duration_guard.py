"""Collection-time duration-budget guard (ISSUE 2 satellite).

Heavy tests declare their expected runtime with
``@pytest.mark.duration_budget(seconds)``.  Any test whose declared budget
exceeds ``TIER1_BUDGET_SECONDS`` must also be tagged ``slow`` — otherwise it
silently eats the tier-1 (``-m 'not slow'``) 870 s timeout (ROADMAP.md).  The
check runs at COLLECTION time so the violation fails the run immediately and
deterministically instead of surfacing as a flaky timeout twenty minutes in.

Kept as a plain module (not conftest-inline) so the rule itself is unit-tested
in ``tests/test_overload.py``.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

# A single tier-1 test declaring more than this many seconds must be `slow`.
TIER1_BUDGET_SECONDS = 30.0


def declared_budget(item) -> Optional[float]:
    """The test's declared duration budget in seconds, or None."""
    m = item.get_closest_marker("duration_budget")
    if m is None:
        return None
    if not m.args:
        raise ValueError(
            f"{item.nodeid}: duration_budget marker needs a seconds argument, "
            "e.g. @pytest.mark.duration_budget(45)"
        )
    return float(m.args[0])


def check_items(items, threshold: float = TIER1_BUDGET_SECONDS) -> List[Tuple[str, float]]:
    """Return (nodeid, budget) for every item whose declared budget exceeds
    ``threshold`` without a ``slow`` tag.  Empty list = collection may proceed."""
    violations: List[Tuple[str, float]] = []
    for item in items:
        budget = declared_budget(item)
        if budget is None:
            continue
        if budget > threshold and item.get_closest_marker("slow") is None:
            violations.append((item.nodeid, budget))
    return violations


def enforce(items, threshold: float = TIER1_BUDGET_SECONDS) -> None:
    """Raise ``pytest.UsageError`` (fails collection) on any violation."""
    violations = check_items(items, threshold)
    if violations:
        import pytest

        lines = "\n".join(f"  {nodeid} declares {budget:g}s" for nodeid, budget in violations)
        raise pytest.UsageError(
            f"test(s) declare a duration budget over {threshold:g}s without a "
            f"`slow` tag — tag them @pytest.mark.slow or shrink them:\n{lines}"
        )
