"""On-device embeddings as a SIMILARITY SIGNAL (round-1 weak spot #4).

`test_embeddings_route_parity.py` pins the embeddings-route *plumbing* to the
reference engine under a shared deterministic embedder. What it cannot show is
that the TPU backend's actual embedding vectors — mean-pooled final hidden
states of the local model (`engine.embed_tokens`, replacing the reference's
text-embedding-3 side-channel, `/root/reference/k_llms/client.py:75-122`) —
carry a usable semantic-overlap signal. These tests measure that directly:

- ordering: paraphrase pairs must score above unrelated pairs under the
  backend's own vectors + the engine's cosine normalization;
- outcome: on a realistic long-string consensus case, the embedding route
  through the REAL backend must elect the same majority medoid as the
  Levenshtein route (the degradation path the reference guarantees).

Even with random weights the transformer's pooled states are a strong
bag-of-context signal (inputs drive activations; shared spans share
activations), which is exactly the property consensus needs: corrupted copies
of one string must look closer to each other than to a different field's text.
"""

import numpy as np
import pytest

from k_llms_tpu.backends.tpu import TpuBackend
from k_llms_tpu.consensus.similarity import SimilarityScorer, cosine_similarity


@pytest.fixture(scope="module")
def backend():
    return TpuBackend(model="tiny")


PARAPHRASES = [
    "The shipment of industrial widgets departed the Rotterdam warehouse on "
    "Tuesday morning and is expected at the Hamburg depot within three days.",
    "The shipment of industrial widgets left the Rotterdam warehouse on "
    "Tuesday morning and should reach the Hamburg depot within three days.",
]
UNRELATED = [
    "Payment terms are net thirty days from the invoice issue date, with a "
    "two percent discount applied for settlement within ten calendar days.",
    "All customer support inquiries should be directed to the billing "
    "department via email and will be answered within two business days.",
]


def _cos(backend, a: str, b: str) -> float:
    va, vb = backend.embeddings([a, b])
    return cosine_similarity(np.asarray(va), np.asarray(vb))


def test_paraphrases_outscore_unrelated(backend):
    close = _cos(backend, PARAPHRASES[0], PARAPHRASES[1])
    far1 = _cos(backend, PARAPHRASES[0], UNRELATED[0])
    far2 = _cos(backend, PARAPHRASES[0], UNRELATED[1])
    assert close > far1 and close > far2, (close, far1, far2)


def test_small_corruptions_stay_close(backend):
    base = PARAPHRASES[0]
    corrupted = base.replace("Tuesday", "Tuesdya").replace("widgets", "widgtes")
    assert _cos(backend, base, corrupted) > _cos(backend, base, UNRELATED[0])


def test_identical_strings_score_near_one(backend):
    v = backend.embeddings([PARAPHRASES[0]] * 2)
    sim = cosine_similarity(np.asarray(v[0]), np.asarray(v[1]))
    assert sim > 0.999


def test_embedding_route_medoid_rejects_outlier(backend):
    """Majority medoid election on long strings: the backend's real on-device
    embedding route must land in the majority cluster — never the unrelated
    outlier — just like the Levenshtein fallback route does. (Which member of
    the near-tied majority cluster wins may differ between routes; the
    reference's contract is the cluster choice, not the tie-break.)"""
    majority = PARAPHRASES[0]
    cluster = [
        majority,
        majority.replace("Tuesday", "Wednesday"),
        majority.replace("three days", "four days"),
    ]
    variants = cluster + [UNRELATED[0]]

    def medoid(scorer: SimilarityScorer) -> str:
        sims = np.array(
            [[scorer.generic(a, b) for b in variants] for a in variants], np.float64
        )
        return variants[int(sims.mean(axis=1).argmax())]

    emb_scorer = SimilarityScorer(method="embeddings", embed_fn=backend.embeddings)
    lev_scorer = SimilarityScorer(method="levenshtein")
    assert medoid(emb_scorer) in cluster
    assert medoid(lev_scorer) in cluster
    # And the outlier's row mean must be strictly the lowest under embeddings.
    sims = np.array(
        [[emb_scorer.generic(a, b) for b in variants] for a in variants], np.float64
    )
    assert sims.mean(axis=1).argmin() == len(variants) - 1


def test_backend_scorer_uses_live_embeddings(backend):
    """The scorer the resources layer builds from this backend takes the
    embeddings route for >50-char strings (not the Levenshtein fallback):
    its scores must match hand-computed cosines of backend.embeddings."""
    scorer = backend.similarity_scorer(method="embeddings")
    got = scorer.generic(PARAPHRASES[0], UNRELATED[0])
    want = _cos(backend, PARAPHRASES[0], UNRELATED[0])
    assert got == pytest.approx(want, abs=1e-6)
