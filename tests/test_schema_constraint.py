"""Schema-guided decoding: DFA compiler, device parity, and the end-to-end
guarantee that parse() samples validate into the user's pydantic model."""

import json
from typing import List, Literal, Optional

import jax.numpy as jnp
import numpy as np
import pytest
from pydantic import BaseModel

from k_llms_tpu.engine.engine import LocalEngine
from k_llms_tpu.engine.schema_constraint import (
    SchemaUnsupported,
    compile_schema,
    device_dfa,
    dfa_advance,
    dfa_initial_state,
    dfa_mask_logits,
    validate_bytes,
)
from k_llms_tpu.engine.tokenizer import ByteTokenizer


class Item(BaseModel):
    sku: str
    qty: int


class Invoice(BaseModel):
    vendor: str
    total: float
    paid: bool
    priority: Literal["low", "high"]
    notes: Optional[str] = None
    items: List[Item] = []


GOOD = b'{"vendor":"ACME","total":4310.55,"paid":true,"priority":"high","notes":null,"items":[{"sku":"a","qty":2}]}'


def test_compile_and_accept():
    dfa = compile_schema(Invoice.model_json_schema())
    ok, complete = validate_bytes(dfa, GOOD)
    assert ok and complete
    Invoice.model_validate(json.loads(GOOD))
    for i in range(len(GOOD)):
        assert validate_bytes(dfa, GOOD[:i])[0]


@pytest.mark.parametrize(
    "doc",
    [
        b'{"vendor":"ACME"}',  # missing the remaining keys
        b'{"total":1',  # wrong key order
        b'{"vendor":"A","total":"x"',  # wrong type
        b'{"vendor":"A","total":1,"paid":true,"priority":"mid"',  # bad enum
        b'{"vendor":"A","extra":1',  # unknown key
    ],
)
def test_rejections(doc):
    dfa = compile_schema(Invoice.model_json_schema())
    ok, complete = validate_bytes(dfa, doc)
    assert not (ok and complete)


def test_enum_shared_prefix():
    class M(BaseModel):
        mode: Literal["auto", "autofix", "manual"]

    dfa = compile_schema(M.model_json_schema())
    for v in ("auto", "autofix", "manual"):
        doc = json.dumps({"mode": v}).replace(" ", "").encode()
        ok, complete = validate_bytes(dfa, doc)
        assert ok and complete, doc
    assert not validate_bytes(dfa, b'{"mode":"autom"}')[0] or not validate_bytes(dfa, b'{"mode":"autom"}')[1]


def test_array_of_scalars_and_empty():
    class M(BaseModel):
        tags: List[str]
        scores: List[float]

    dfa = compile_schema(M.model_json_schema())
    for doc in (b'{"tags":[],"scores":[]}', b'{"tags":["a","b"],"scores":[1,2.5,-3e2]}'):
        ok, complete = validate_bytes(dfa, doc)
        assert ok and complete, doc
        M.model_validate(json.loads(doc))


def test_unsupported_falls_through():
    with pytest.raises(SchemaUnsupported):
        compile_schema({"type": "object"})  # free-form object


def _one_field(**value_schema):
    return {
        "type": "object",
        "properties": {"v": value_schema},
        "required": ["v"],
        "additionalProperties": False,
    }


def _accepts(dfa, doc: bytes) -> bool:
    ok, complete = validate_bytes(dfa, doc)
    return ok and complete


def test_anyof_union_arms_with_distinct_first_bytes():
    dfa = compile_schema(
        _one_field(anyOf=[{"type": "integer"}, {"type": "string"},
                          {"type": "boolean"}, {"type": "null"}])
    )
    for doc in (b'{"v":12}', b'{"v":"x"}', b'{"v":true}', b'{"v":null}'):
        assert _accepts(dfa, doc), doc
    assert not _accepts(dfa, b'{"v":[1]}')
    # Arms that share a first byte are ambiguous -> unsupported, not wrong.
    with pytest.raises(SchemaUnsupported):
        compile_schema(_one_field(anyOf=[{"type": "integer"},
                                         {"type": "number"}]))


def test_nested_arrays_of_objects():
    inner = {
        "type": "object",
        "properties": {"id": {"type": "integer"},
                       "tags": {"type": "array", "items": {"type": "string"}}},
        "required": ["id", "tags"],
        "additionalProperties": False,
    }
    dfa = compile_schema(_one_field(type="array", items=inner))
    for doc in (
        b'{"v":[]}',
        b'{"v":[{"id":1,"tags":[]}]}',
        b'{"v":[{"id":1,"tags":["a","b"]},{"id":2,"tags":["c"]}]}',
    ):
        assert _accepts(dfa, doc), doc
        json.loads(doc)
    assert not _accepts(dfa, b'{"v":[{"tags":[],"id":1}]}')  # key order
    assert not _accepts(dfa, b'{"v":[{"id":1}]}')  # missing nested key


def test_integer_vs_number_token_boundaries():
    int_dfa = compile_schema(_one_field(type="integer"))
    num_dfa = compile_schema(_one_field(type="number"))
    for doc in (b'{"v":0}', b'{"v":-7}', b'{"v":123}'):
        assert _accepts(int_dfa, doc) and _accepts(num_dfa, doc), doc
    for doc in (b'{"v":1.5}', b'{"v":-0.25}', b'{"v":3e2}', b'{"v":1E-4}'):
        assert not validate_bytes(int_dfa, doc)[0], doc  # '.'/'e' dead for int
        assert _accepts(num_dfa, doc), doc
    for doc in (b'{"v":01}', b'{"v":.5}', b'{"v":1.}', b'{"v":-}'):
        assert not _accepts(int_dfa, doc) and not _accepts(num_dfa, doc), doc


def test_string_length_bounds_count_characters():
    dfa = compile_schema(_one_field(type="string", minLength=2, maxLength=4))
    for doc in (b'{"v":"ab"}', b'{"v":"abcd"}', b'{"v":"a\\nb"}',
                '{"v":"héj"}'.encode(), b'{"v":"a\\u00e9"}'):
        assert _accepts(dfa, doc), doc
        assert 2 <= len(json.loads(doc)["v"]) <= 4
    for doc in (b'{"v":""}', b'{"v":"a"}', b'{"v":"abcde"}'):
        assert not _accepts(dfa, doc), doc
    # min-only: the tail is unbounded.
    open_dfa = compile_schema(_one_field(type="string", minLength=3))
    assert _accepts(open_dfa, b'{"v":"abcdefghij"}')
    assert not _accepts(open_dfa, b'{"v":"ab"}')
    # Bounds past the unroll cap degrade rather than explode.
    with pytest.raises(SchemaUnsupported):
        compile_schema(_one_field(type="string", maxLength=4096))


def test_unicode_escape_surrogate_hygiene():
    """json.loads tolerates a lone \\uD8xx surrogate but the decoded string
    is unpaired UTF-16 that pydantic rejects — the DFA must ban lone
    surrogates and demand the full pair, or masked samples could complete
    without validating."""
    dfa = compile_schema(_one_field(type="string"))
    assert _accepts(dfa, b'{"v":"\\u00e9"}')        # plain BMP escape
    assert _accepts(dfa, b'{"v":"\\ud7ff"}')        # below the surrogate gap
    assert _accepts(dfa, b'{"v":"\\uD83D\\uDE00"}')  # full pair (one char)
    assert not _accepts(dfa, b'{"v":"\\uDcf7"}')     # lone low surrogate
    assert not _accepts(dfa, b'{"v":"\\uD83Dx"}')    # high without its pair
    assert not _accepts(dfa, b'{"v":"\\uD83D\\n"}')  # pair broken by escape
    # Character counting: the pair is ONE char against length bounds.
    one = compile_schema(_one_field(type="string", minLength=1, maxLength=1))
    assert _accepts(one, b'{"v":"\\uD83D\\uDE00"}')
    assert len(json.loads(b'{"v":"\\uD83D\\uDE00"}')["v"]) == 1


def test_string_formats_constrain_shape():
    date = compile_schema(_one_field(type="string", format="date"))
    assert _accepts(date, b'{"v":"2026-08-05"}')
    for doc in (b'{"v":"2026-13-01"}', b'{"v":"2026-00-01"}',
                b'{"v":"2026-01-32"}', b'{"v":"26-01-01"}'):
        assert not _accepts(date, doc), doc
    time_ = compile_schema(_one_field(type="string", format="time"))
    assert _accepts(time_, b'{"v":"23:59:59"}')
    assert not _accepts(time_, b'{"v":"24:00:00"}')
    uuid = compile_schema(_one_field(type="string", format="uuid"))
    assert _accepts(uuid, b'{"v":"123e4567-e89b-12d3-a456-426614174000"}')
    assert not _accepts(uuid, b'{"v":"123e4567-e89b-12d3-a456"}')
    with pytest.raises(SchemaUnsupported):
        compile_schema(_one_field(type="string", format="email"))


def test_device_matches_host_oracle():
    dfa = compile_schema(Invoice.model_json_schema())
    d = device_dfa(dfa)
    eos = jnp.array([257, -1, -1, -1], jnp.int32)
    rng = np.random.default_rng(1)
    for cut in sorted(rng.integers(0, len(GOOD), 12).tolist()) + [0, len(GOOD)]:
        prefix = GOOD[:cut]
        state = dfa_initial_state(d, 1)
        for byte in prefix:
            state = dfa_advance(d, jnp.array([byte], jnp.int32), state)
        masked = dfa_mask_logits(d, jnp.zeros((1, 512)), state, eos)
        allowed = np.asarray(masked[0] > jnp.finfo(jnp.float32).min)
        for byte in set(rng.integers(0, 256, 48).tolist()) | set(GOOD):
            expected = validate_bytes(dfa, prefix + bytes([byte]))[0]
            assert bool(allowed[byte]) == expected, (prefix, bytes([byte]))
        assert bool(allowed[257]) == validate_bytes(dfa, prefix)[1]


def test_constrained_generate_validates_into_model():
    """A RANDOM model under the schema DFA produces documents that parse AND
    validate into the pydantic model whenever generation completes."""
    dfa = compile_schema(Invoice.model_json_schema())
    engine = LocalEngine("tiny", use_mesh=False)
    tok = ByteTokenizer()
    ids = tok.apply_chat_template([{"role": "user", "content": "extract"}])
    completed = 0
    for seed in range(3):
        r = engine.generate(
            ids, n=8, max_new_tokens=160, temperature=1.0, seed=seed,
            eos_ids=tok.stop_ids, constraint=dfa,
        )
        for i in range(8):
            data = bytes(int(b) for b in r.tokens[i][: int(r.lengths[i])] if int(b) < 256)
            assert validate_bytes(dfa, data)[0], data
            if r.finish_reasons[i] == "stop":
                Invoice.model_validate(json.loads(data))
                completed += 1
    assert completed > 0  # at least some samples must complete at 160 tokens


def test_parse_end_to_end_all_samples_validate():
    """client.parse(): every completed sample now has a non-None .parsed —
    the full OpenAI structured-outputs guarantee, locally."""
    from k_llms_tpu import KLLMs

    class Compact(BaseModel):
        name: str
        count: int

    client = KLLMs(backend="tpu", model="tiny", max_new_tokens=96)
    r = client.chat.completions.parse(
        messages=[{"role": "user", "content": "extract the record"}],
        response_format=Compact,
        model="tiny",
        n=4,
        seed=11,
    )
    assert len(r.choices) == 5
    for choice in r.choices[1:]:
        if choice.finish_reason == "stop":
            assert choice.message.parsed is not None
            assert isinstance(choice.message.parsed.count, int)


def test_backend_constraint_for_compiles_grammars():
    from k_llms_tpu.backends.tpu import TpuBackend
    from k_llms_tpu.engine.grammar import CompiledGrammar, clear_grammar_cache

    clear_grammar_cache()
    backend = TpuBackend(model="tiny")
    # json_object (no schema) -> the generic-JSON product grammar.
    generic = backend._constraint_for({"type": "json_object"})
    assert isinstance(generic, CompiledGrammar)
    assert generic.digest.startswith("grammar-json-")
    assert backend._constraint_for(None) is None
    g = backend._constraint_for(Invoice)
    assert isinstance(g, CompiledGrammar)
    assert g.digest != generic.digest
    # The process-wide TTL cache makes the second call a hit (same object).
    assert backend._constraint_for(Invoice) is g


def test_backend_constraint_for_respects_config_switch():
    from k_llms_tpu.backends.tpu import BackendConfig, TpuBackend

    backend = TpuBackend(
        model="tiny", config=BackendConfig(model="tiny", constrained_decoding=False)
    )
    # Switch off: requests decode unconstrained, post-hoc validation only.
    assert backend._constraint_for(Invoice) is None
    assert backend._constraint_for({"type": "json_object"}) is None
