"""Schema-guided decoding: DFA compiler, device parity, and the end-to-end
guarantee that parse() samples validate into the user's pydantic model."""

import json
from typing import List, Literal, Optional

import jax.numpy as jnp
import numpy as np
import pytest
from pydantic import BaseModel

from k_llms_tpu.engine.engine import LocalEngine
from k_llms_tpu.engine.schema_constraint import (
    SchemaUnsupported,
    compile_schema,
    device_dfa,
    dfa_advance,
    dfa_initial_state,
    dfa_mask_logits,
    validate_bytes,
)
from k_llms_tpu.engine.tokenizer import ByteTokenizer


class Item(BaseModel):
    sku: str
    qty: int


class Invoice(BaseModel):
    vendor: str
    total: float
    paid: bool
    priority: Literal["low", "high"]
    notes: Optional[str] = None
    items: List[Item] = []


GOOD = b'{"vendor":"ACME","total":4310.55,"paid":true,"priority":"high","notes":null,"items":[{"sku":"a","qty":2}]}'


def test_compile_and_accept():
    dfa = compile_schema(Invoice.model_json_schema())
    ok, complete = validate_bytes(dfa, GOOD)
    assert ok and complete
    Invoice.model_validate(json.loads(GOOD))
    for i in range(len(GOOD)):
        assert validate_bytes(dfa, GOOD[:i])[0]


@pytest.mark.parametrize(
    "doc",
    [
        b'{"vendor":"ACME"}',  # missing the remaining keys
        b'{"total":1',  # wrong key order
        b'{"vendor":"A","total":"x"',  # wrong type
        b'{"vendor":"A","total":1,"paid":true,"priority":"mid"',  # bad enum
        b'{"vendor":"A","extra":1',  # unknown key
    ],
)
def test_rejections(doc):
    dfa = compile_schema(Invoice.model_json_schema())
    ok, complete = validate_bytes(dfa, doc)
    assert not (ok and complete)


def test_enum_shared_prefix():
    class M(BaseModel):
        mode: Literal["auto", "autofix", "manual"]

    dfa = compile_schema(M.model_json_schema())
    for v in ("auto", "autofix", "manual"):
        doc = json.dumps({"mode": v}).replace(" ", "").encode()
        ok, complete = validate_bytes(dfa, doc)
        assert ok and complete, doc
    assert not validate_bytes(dfa, b'{"mode":"autom"}')[0] or not validate_bytes(dfa, b'{"mode":"autom"}')[1]


def test_array_of_scalars_and_empty():
    class M(BaseModel):
        tags: List[str]
        scores: List[float]

    dfa = compile_schema(M.model_json_schema())
    for doc in (b'{"tags":[],"scores":[]}', b'{"tags":["a","b"],"scores":[1,2.5,-3e2]}'):
        ok, complete = validate_bytes(dfa, doc)
        assert ok and complete, doc
        M.model_validate(json.loads(doc))


def test_unsupported_falls_through():
    with pytest.raises(SchemaUnsupported):
        compile_schema({"type": "object"})  # free-form object


def test_device_matches_host_oracle():
    dfa = compile_schema(Invoice.model_json_schema())
    d = device_dfa(dfa)
    eos = jnp.array([257, -1, -1, -1], jnp.int32)
    rng = np.random.default_rng(1)
    for cut in sorted(rng.integers(0, len(GOOD), 12).tolist()) + [0, len(GOOD)]:
        prefix = GOOD[:cut]
        state = dfa_initial_state(d, 1)
        for byte in prefix:
            state = dfa_advance(d, jnp.array([byte], jnp.int32), state)
        masked = dfa_mask_logits(d, jnp.zeros((1, 512)), state, eos)
        allowed = np.asarray(masked[0] > jnp.finfo(jnp.float32).min)
        for byte in set(rng.integers(0, 256, 48).tolist()) | set(GOOD):
            expected = validate_bytes(dfa, prefix + bytes([byte]))[0]
            assert bool(allowed[byte]) == expected, (prefix, bytes([byte]))
        assert bool(allowed[257]) == validate_bytes(dfa, prefix)[1]


def test_constrained_generate_validates_into_model():
    """A RANDOM model under the schema DFA produces documents that parse AND
    validate into the pydantic model whenever generation completes."""
    dfa = compile_schema(Invoice.model_json_schema())
    engine = LocalEngine("tiny", use_mesh=False)
    tok = ByteTokenizer()
    ids = tok.apply_chat_template([{"role": "user", "content": "extract"}])
    completed = 0
    for seed in range(3):
        r = engine.generate(
            ids, n=8, max_new_tokens=160, temperature=1.0, seed=seed,
            eos_ids=tok.stop_ids, constraint=dfa,
        )
        for i in range(8):
            data = bytes(int(b) for b in r.tokens[i][: int(r.lengths[i])] if int(b) < 256)
            assert validate_bytes(dfa, data)[0], data
            if r.finish_reasons[i] == "stop":
                Invoice.model_validate(json.loads(data))
                completed += 1
    assert completed > 0  # at least some samples must complete at 160 tokens


def test_parse_end_to_end_all_samples_validate():
    """client.parse(): every completed sample now has a non-None .parsed —
    the full OpenAI structured-outputs guarantee, locally."""
    from k_llms_tpu import KLLMs

    class Compact(BaseModel):
        name: str
        count: int

    client = KLLMs(backend="tpu", model="tiny", max_new_tokens=96)
    r = client.chat.completions.parse(
        messages=[{"role": "user", "content": "extract the record"}],
        response_format=Compact,
        model="tiny",
        n=4,
        seed=11,
    )
    assert len(r.choices) == 5
    for choice in r.choices[1:]:
        if choice.finish_reason == "stop":
            assert choice.message.parsed is not None
            assert isinstance(choice.message.parsed.count, int)


def test_backend_falls_back_to_json_for_unsupported():
    from k_llms_tpu.backends.tpu import TpuBackend

    backend = TpuBackend(model="tiny")
    # dict/object response_format without properties -> generic JSON automaton.
    assert backend._constraint_for({"type": "json_object"}) == "json"
    assert backend._constraint_for(None) is None
    dfa = backend._constraint_for(Invoice)
    assert dfa is not None and dfa != "json"
    # Cached on second call (same object identity).
    assert backend._constraint_for(Invoice) is dfa
