"""Constrained JSON decoding: automaton tables, device mask/advance, and
end-to-end guaranteed-valid-JSON generation from a random model."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k_llms_tpu.engine.engine import LocalEngine
from k_llms_tpu.engine.json_constraint import (
    S,
    advance,
    build_tables,
    device_tables,
    initial_state,
    mask_logits,
    validate_prefix,
)
from k_llms_tpu.engine.tokenizer import ByteTokenizer


# --- host automaton -------------------------------------------------------


@pytest.mark.parametrize(
    "doc",
    [
        b'{"a": 1}',
        b'[1, 2.5e-3, true, null, "x"]',
        b'  {"k": {"nested": [false, {}]}, "s": "\\u00e9\\n"}  ',
        b"42",
        b"-0.5e+10",
        b"0",
        b"-0",
        b"1e07",  # exponent digits may lead with zero
        b'""',
        b"[]",
        b"{}",
        b'[[[{"deep": []}]]]',
    ],
)
def test_valid_documents_accepted(doc):
    ok, complete = validate_prefix(doc)
    assert ok and complete
    json.loads(doc)  # agree with Python's parser


@pytest.mark.parametrize(
    "doc",
    [
        b"{,}",
        b"[1,]",
        b'{"a" 1}',
        b"tru",  # valid prefix but incomplete
        b"trux",
        b"01",  # strict JSON: no leading zeros
        b"[050]",
        b"-01",
        b"true, 6",  # no top-level comma
        b"}",
        b"]",
        b'{"a": 1]',
        b'["\\x"]',
        b'{"a":}',
    ],
)
def test_invalid_or_incomplete_rejected(doc):
    ok, complete = validate_prefix(doc)
    assert not (ok and complete)


def test_prefix_validity_of_truncations():
    doc = b'{"name": "Jos\xc3\xa9", "tags": [1, -2.5, null], "ok": true}'
    for i in range(len(doc)):
        ok, _ = validate_prefix(doc[:i])
        assert ok, doc[:i]


# --- device mask vs host oracle ------------------------------------------


def test_device_mask_agrees_with_host_validator():
    """For random valid prefixes, a byte is allowed by the device mask iff the
    host validator accepts the extended prefix."""
    rng = np.random.default_rng(0)
    t = device_tables()
    eos = jnp.array([257, -1, -1, -1], jnp.int32)

    prefixes = [b"", b"{", b'{"a', b'{"a": ', b'{"a": [1, ', b'{"a": {"b": "c', b"-1", b'[true, "x\\']
    for prefix in prefixes:
        state, depth, stack = initial_state(1)
        for byte in prefix:
            state, depth, stack = advance(t, jnp.array([byte], jnp.int32), state, depth, stack)
        logits = jnp.zeros((1, 512), jnp.float32)
        masked = mask_logits(t, logits, state, depth, stack, eos)
        allowed = np.asarray(masked[0] > jnp.finfo(jnp.float32).min)
        # Sample 64 random bytes + all structural bytes, compare with the oracle.
        candidates = set(rng.integers(0, 256, 64).tolist()) | set(b'{}[]",:0 9at\\nf-.eE+')
        for byte in candidates:
            expected, _ = validate_prefix(prefix + bytes([byte]))
            assert bool(allowed[byte]) == expected, (prefix, chr(byte), expected)
        # EOS column agrees with completeness.
        _, complete = validate_prefix(prefix)
        assert bool(allowed[257]) == complete, prefix


def test_depth_guard_blocks_nesting():
    t = device_tables()
    state, depth, stack = initial_state(1, max_depth=2)
    for byte in b"[[":
        state, depth, stack = advance(t, jnp.array([byte], jnp.int32), state, depth, stack)
    masked = mask_logits(t, jnp.zeros((1, 512)), state, depth, stack, jnp.array([257], jnp.int32))
    allowed = np.asarray(masked[0] > jnp.finfo(jnp.float32).min)
    assert not allowed[ord("[")] and not allowed[ord("{")]
    assert allowed[ord("1")] and allowed[ord("]")]


# --- end-to-end -----------------------------------------------------------


def test_constrained_generate_yields_valid_json():
    """A RANDOM model under the JSON constraint must emit documents whose every
    prefix is valid JSON — the strongest guarantee the mask can make."""
    engine = LocalEngine("tiny", use_mesh=False)
    tok = ByteTokenizer()
    ids = tok.apply_chat_template([{"role": "user", "content": "emit json"}])
    for seed, temperature in ((0, 1.0), (7, 2.0), (13, 0.7)):
        r = engine.generate(
            ids, n=8, max_new_tokens=48, temperature=temperature, seed=seed,
            eos_ids=tok.stop_ids, constraint="json",
        )
        for i in range(8):
            data = bytes(int(b) for b in r.tokens[i][: int(r.lengths[i])] if int(b) < 256)
            ok, complete = validate_prefix(data)
            assert ok, data
            if r.finish_reasons[i] == "stop":
                assert complete, data
                json.loads(data)  # round-trips through a real parser


def test_constrained_generate_reproducible():
    engine = LocalEngine("tiny", use_mesh=False)
    tok = ByteTokenizer()
    ids = tok.apply_chat_template([{"role": "user", "content": "json please"}])
    a = engine.generate(ids, n=4, max_new_tokens=24, seed=5, constraint="json")
    b = engine.generate(ids, n=4, max_new_tokens=24, seed=5, constraint="json")
    np.testing.assert_array_equal(a.tokens, b.tokens)


def test_unknown_constraint_rejected():
    engine = LocalEngine("tiny", use_mesh=False)
    with pytest.raises(ValueError, match="Unknown constraint"):
        engine.generate([1, 2, 3], constraint="xml")


def test_non_byte_semantics_rejected():
    engine = LocalEngine("tiny", use_mesh=False)
    # eos id inside the byte range would alias the eos mask onto a byte column.
    with pytest.raises(ValueError, match="byte-level token semantics"):
        engine.generate([1, 2, 3], constraint="json", eos_ids=[2])


def test_depth_guard_allows_openers_inside_strings():
    """At the nesting limit, '{'/'[' must still be allowed as STRING CONTENT —
    the guard gates on the byte actually pushing."""
    t = device_tables()
    state, depth, stack = initial_state(1, max_depth=1)
    for byte in b'{"k':  # inside a key string at full depth
        state, depth, stack = advance(t, jnp.array([byte], jnp.int32), state, depth, stack)
    masked = mask_logits(t, jnp.zeros((1, 512)), state, depth, stack, jnp.array([257], jnp.int32))
    allowed = np.asarray(masked[0] > jnp.finfo(jnp.float32).min)
    assert allowed[ord("{")] and allowed[ord("[")]


def test_parse_uses_constraint_end_to_end():
    """client.parse() on the TPU backend produces syntactically-valid JSON in
    every sample's content (the reference gets this guarantee from OpenAI)."""
    from pydantic import BaseModel

    from k_llms_tpu import KLLMs

    class Extraction(BaseModel):
        name: str = ""
        total: float = 0.0

    client = KLLMs(backend="tpu", model="tiny", max_new_tokens=48)
    r = client.chat.completions.parse(
        messages=[{"role": "user", "content": "extract the invoice"}],
        response_format=Extraction,
        model="tiny",
        n=3,
        seed=2,
    )
    assert len(r.choices) == 4
    for choice in r.choices[1:]:
        content = choice.message.content or ""
        ok, _ = validate_prefix(content.encode("utf-8"))
        assert ok, content


def test_constrained_sharded():
    if len(jax.devices()) < 4:
        pytest.skip("needs the 8-device CPU mesh")
    from k_llms_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(2, 2, jax.devices()[:4])
    engine = LocalEngine("tiny", mesh=mesh)
    tok = ByteTokenizer()
    ids = tok.apply_chat_template([{"role": "user", "content": "sharded json"}])
    r = engine.generate(ids, n=4, max_new_tokens=16, seed=1, constraint="json")
    for i in range(4):
        data = bytes(int(b) for b in r.tokens[i][: int(r.lengths[i])] if int(b) < 256)
        ok, _ = validate_prefix(data)
        assert ok, data
