"""Loader that imports the REFERENCE consensus engine as a test oracle.

The reference at /root/reference imports packages absent from this environment
(openai, retab, cachetools, unidecode). We register tiny in-memory stubs for
those, then load `k_llms/utils/{majority_sorting,consensus_utils}.py` directly
from the reference tree under a synthetic package name (bypassing the package
__init__, which would drag in the full OpenAI client surface).

This gives differential tests a ground-truth implementation to fuzz against.
Nothing from the reference is copied into the repo; it is only executed at test
time, and tests skip cleanly when /root/reference is absent.
"""

from __future__ import annotations

import importlib.machinery
import importlib.util
import os
import sys
import types
import unicodedata

REFERENCE_ROOT = "/root/reference"

_cached = None


def reference_available() -> bool:
    return os.path.isdir(os.path.join(REFERENCE_ROOT, "k_llms", "utils"))


def _stub_module(name: str) -> types.ModuleType:
    """A ModuleType with a real ModuleSpec, so later
    ``importlib.util.find_spec(name)`` (e.g. transformers' optional-dependency
    probe) sees a well-formed module instead of raising on ``__spec__ is None``.
    """
    mod = types.ModuleType(name)
    mod.__spec__ = importlib.machinery.ModuleSpec(name, loader=None)
    return mod


def _install_stub_modules() -> None:
    # --- cachetools: only TTLCache is used ---
    if "cachetools" not in sys.modules:
        cachetools = _stub_module("cachetools")

        class TTLCache(dict):
            def __init__(self, maxsize=1024, ttl=300):
                super().__init__()
                self.maxsize = maxsize
                self.ttl = ttl

            def __setitem__(self, key, value):
                if len(self) >= self.maxsize:
                    self.clear()
                super().__setitem__(key, value)

        cachetools.TTLCache = TTLCache
        sys.modules["cachetools"] = cachetools

    # --- unidecode: fixture-backed stub. Known inputs return REAL unidecode
    # output (hand-encoded vectors), so oracle parity on those is genuine, not
    # circular. Off-fixture inputs fall back to our transliterator, which the
    # fixture tests (tests/test_translit.py) pin to unidecode behavior for
    # Latin/Cyrillic/Greek. ---
    if "unidecode" not in sys.modules:
        unidecode_mod = _stub_module("unidecode")

        from k_llms_tpu.consensus.translit import transliterate

        from fixtures.unidecode_vectors import UNIDECODE_TABLE

        def _unidecode(text: str) -> str:
            hit = UNIDECODE_TABLE.get(text)
            return hit if hit is not None else transliterate(text)

        unidecode_mod.unidecode = _unidecode
        sys.modules["unidecode"] = unidecode_mod

    # --- openai: classes + completion_usage types ---
    if "openai" not in sys.modules:
        from k_llms_tpu.types import wire

        openai_mod = _stub_module("openai")

        class OpenAI:  # pragma: no cover - never actually called by the oracle
            def __init__(self, *a, **kw):
                raise RuntimeError("oracle must not construct an OpenAI client")

        class AsyncOpenAI:
            def __init__(self, *a, **kw):
                raise RuntimeError("oracle must not construct an OpenAI client")

        openai_mod.OpenAI = OpenAI
        openai_mod.AsyncOpenAI = AsyncOpenAI

        openai_types = _stub_module("openai.types")
        completion_usage = _stub_module("openai.types.completion_usage")
        completion_usage.CompletionUsage = wire.CompletionUsage
        completion_usage.CompletionTokensDetails = wire.CompletionTokensDetails
        completion_usage.PromptTokensDetails = wire.PromptTokensDetails

        openai_mod.types = openai_types
        openai_types.completion_usage = completion_usage
        sys.modules["openai"] = openai_mod
        sys.modules["openai.types"] = openai_types
        sys.modules["openai.types.completion_usage"] = completion_usage

    # --- retab: one type import, never instantiated in the paths we exercise ---
    if "retab" not in sys.modules:
        retab = _stub_module("retab")
        retab_types = _stub_module("retab.types")
        retab_docs = _stub_module("retab.types.documents")
        retab_extract = _stub_module("retab.types.documents.extract")

        class RetabParsedChatCompletion:  # minimal placeholder
            pass

        retab_extract.RetabParsedChatCompletion = RetabParsedChatCompletion
        sys.modules["retab"] = retab
        sys.modules["retab.types"] = retab_types
        sys.modules["retab.types.documents"] = retab_docs
        sys.modules["retab.types.documents.extract"] = retab_extract


def load_reference_engine():
    """Returns the reference consensus_utils module (cached)."""
    global _cached
    if _cached is not None:
        return _cached
    if not reference_available():
        raise RuntimeError("reference tree not available")

    _install_stub_modules()

    utils_dir = os.path.join(REFERENCE_ROOT, "k_llms", "utils")
    pkg_name = "_reference_oracle_utils"
    if pkg_name not in sys.modules:
        pkg = types.ModuleType(pkg_name)
        pkg.__path__ = [utils_dir]
        sys.modules[pkg_name] = pkg

    def _load(mod_name: str):
        full = f"{pkg_name}.{mod_name}"
        if full in sys.modules:
            return sys.modules[full]
        spec = importlib.util.spec_from_file_location(
            full, os.path.join(utils_dir, f"{mod_name}.py")
        )
        module = importlib.util.module_from_spec(spec)
        sys.modules[full] = module
        spec.loader.exec_module(module)
        return module

    _load("majority_sorting")
    _cached = _load("consensus_utils")
    return _cached


_keyalign_cached = None


def load_reference_keyalign():
    """Returns (key_selection, fuzzy_key_selection, key_based_alignment) from
    the reference tree (they only need pydantic + each other)."""
    global _keyalign_cached
    if _keyalign_cached is not None:
        return _keyalign_cached
    if not reference_available():
        raise RuntimeError("reference tree not available")

    _install_stub_modules()

    utils_dir = os.path.join(REFERENCE_ROOT, "k_llms", "utils")
    pkg_name = "_reference_oracle_utils"
    if pkg_name not in sys.modules:
        pkg = types.ModuleType(pkg_name)
        pkg.__path__ = [utils_dir]
        sys.modules[pkg_name] = pkg

    def _load(mod_name: str):
        full = f"{pkg_name}.{mod_name}"
        if full in sys.modules:
            return sys.modules[full]
        spec = importlib.util.spec_from_file_location(
            full, os.path.join(utils_dir, f"{mod_name}.py")
        )
        module = importlib.util.module_from_spec(spec)
        sys.modules[full] = module
        spec.loader.exec_module(module)
        return module

    ks = _load("key_selection")
    fz = _load("fuzzy_key_selection")
    kb = _load("key_based_alignment")
    _keyalign_cached = (ks, fz, kb)
    return _keyalign_cached
