"""Chunked prefill (ISSUE 18): long prompt ingestion interleaved into the
continuous loop's decode steps instead of one monolithic prefill under the
loop lock.

The determinism contract pinned here:

- **Output tokens are byte-identical** between chunked-on and chunked-off
  loops — greedy, sampled, grammar-constrained, and streamed alike. The
  first token comes from the final chunk's logits with the submission-pinned
  seed, and decode proceeds over the chunk-written KV.
- **Logprobs are ULP-equivalent** (atol 1e-5) across on/off: a C-token chunk
  and a whole-bucket prefill compile to different XLA programs (query-axis
  shape), whose matmul reductions differ in the last float32 bits. Within
  the chunked path itself — replay after a mid-chunk watchdog rebuild, or a
  prefix-cache hit on a chunk-ingested prompt — results ARE bitwise
  identical, because the same compiled programs rerun on the same inputs.
- Fault domains carry over: a hung chunk epoch-fences + rebuilds + replays
  byte-identically from cursor 0; a budget abort retires the PREFILLING row
  through the decode-abort counters; paged page accounting stays balanced.
"""

import json
import time

import numpy as np
import pytest

from k_llms_tpu.engine.continuous import ContinuousDecodeLoop
from k_llms_tpu.reliability import failpoints as fp
from k_llms_tpu.reliability.deadline import RequestBudget
from k_llms_tpu.reliability.failpoints import FailSpec
from k_llms_tpu.reliability.supervisor import LaunchBudgetModel
from k_llms_tpu.types.wire import RequestCancelledError
from k_llms_tpu.utils.observability import FAILURE_EVENTS, RECOVERY_EVENTS

LONG_PROMPT = list(range(2, 100))  # 98 tokens: 4 chunks at C=32
CHUNK = 32


def _step_budget(seconds: float) -> LaunchBudgetModel:
    return LaunchBudgetModel(
        base_s=0.1, per_token_s=0.01, multiplier=1.0,
        min_budget_s=seconds, max_budget_s=seconds,
    )


@pytest.fixture(scope="module")
def eng():
    from conftest import shared_engine

    return shared_engine(model="tiny")


@pytest.fixture(scope="module")
def paged_eng():
    from conftest import shared_engine

    return shared_engine(model="tiny", kv_layout="paged", kv_page_size=16)


def _run(loop, prompt=LONG_PROMPT, **kw):
    kw.setdefault("n", 2)
    kw.setdefault("max_new", 8)
    kw.setdefault("temperature", 0.7)
    kw.setdefault("top_p", 0.9)
    kw.setdefault("seed", 11)
    return loop.submit(list(prompt), **kw).result(timeout=120)


def _assert_same_output(on, off, label=""):
    assert np.array_equal(on.tokens, off.tokens), label
    assert list(on.lengths) == list(off.lengths), label
    assert list(on.finish_reasons) == list(off.finish_reasons), label
    # ULP contract: see module docstring — on/off logprobs come from
    # different-shaped XLA programs, equal to within f32 noise.
    assert np.allclose(on.logprobs, off.logprobs, atol=1e-5), label


# -- on/off differentials ----------------------------------------------------

@pytest.mark.parametrize(
    "label,kw",
    [
        ("greedy", dict(temperature=0.0, top_p=None)),
        ("sampled", dict(temperature=0.7, top_p=0.9)),
    ],
)
def test_chunked_on_off_differential_dense(eng, label, kw):
    """The tentpole differential: a long admission ingested in C-token chunks
    produces byte-identical output tokens to whole-prompt prefill."""
    off = ContinuousDecodeLoop(eng, width=4, max_prompt=128, max_new=16)
    try:
        base = _run(off, **kw)
    finally:
        off.stop()
    on = ContinuousDecodeLoop(
        eng, width=4, max_prompt=128, max_new=16, prefill_chunk_tokens=CHUNK
    )
    try:
        got = _run(on, **kw)
        st = dict(on.stats)
    finally:
        on.stop()
    assert st["prefill_chunks"] == (len(LONG_PROMPT) + CHUNK - 1) // CHUNK
    _assert_same_output(got, base, label)


def test_chunked_on_off_differential_paged(paged_eng):
    """Same pin on the paged layout: chunk KV scattered into the row's page
    run at its current offset, and page accounting balanced after retire."""
    off = ContinuousDecodeLoop(paged_eng, width=4, max_prompt=128, max_new=16)
    try:
        base = _run(off)
        base_g = _run(off, temperature=0.0, top_p=None, seed=3)
    finally:
        off.stop()
    on = ContinuousDecodeLoop(
        paged_eng, width=4, max_prompt=128, max_new=16,
        prefill_chunk_tokens=CHUNK,
    )
    try:
        assert on.paged
        got = _run(on)
        got_g = _run(on, temperature=0.0, top_p=None, seed=3)
        alloc = on._pool.allocator
        alloc.verify()
        free_mid = alloc.free_pages
        _run(on, seed=29)
        assert alloc.free_pages == free_mid  # no leak per admission cycle
    finally:
        on.stop()
    alloc.verify()
    _assert_same_output(got, base, "paged sampled")
    _assert_same_output(got_g, base_g, "paged greedy")


def test_chunked_stream_sink_is_contiguous_and_identical(eng):
    """A streaming consumer over a chunked admission sees each step exactly
    once, in order, with tokens matching the authoritative buffers — and the
    stream equals the chunked-off stream byte-for-byte."""
    def collect(loop):
        sunk = []
        got = loop.submit(
            list(LONG_PROMPT), n=2, max_new=8, temperature=0.8, top_p=0.9,
            seed=17, token_sink=lambda s, t: sunk.append((s, t.copy())),
        ).result(timeout=120)
        return got, sunk

    off = ContinuousDecodeLoop(eng, width=4, max_prompt=128, max_new=16)
    try:
        base, base_sunk = collect(off)
    finally:
        off.stop()
    on = ContinuousDecodeLoop(
        eng, width=4, max_prompt=128, max_new=16, prefill_chunk_tokens=CHUNK
    )
    try:
        got, sunk = collect(on)
    finally:
        on.stop()
    assert np.array_equal(got.tokens, base.tokens)
    steps = [s for s, _ in sunk]
    assert steps == sorted(set(steps))
    for step, row in sunk:
        for j in range(2):
            if step < got.lengths[j]:
                assert row[j] == got.tokens[j, step]
    assert [(s, r.tolist()) for s, r in sunk] == [
        (s, r.tolist()) for s, r in base_sunk
    ]


def test_chunked_grammar_row_matches_off(eng):
    """A grammar-constrained long admission chunks like any other and still
    emits the identical, schema-valid stream."""
    from pydantic import BaseModel

    from k_llms_tpu.engine.grammar import (
        grammar_for_schema,
        grammar_vocab,
        validate_grammar_tokens,
    )
    from k_llms_tpu.engine.tokenizer import ByteTokenizer

    class Rec(BaseModel):
        name: str
        count: int

    tok = ByteTokenizer()
    g = grammar_for_schema(
        Rec.model_json_schema(), grammar_vocab(tok), vocab_digest="bytetok-rec"
    )
    # Long enough to span several chunks (ByteTokenizer: 1 token per byte).
    prompt = tok.apply_chat_template(
        [{"role": "user", "content": "extract the record " * 4}]
    )
    assert len(prompt) > 2 * CHUNK
    kw = dict(n=1, max_new=96, temperature=1.0, top_p=None, seed=23, grammar=g)

    off = ContinuousDecodeLoop(eng, width=2, max_prompt=128, max_new=96)
    try:
        base = off.submit(list(prompt), **kw).result(timeout=120)
    finally:
        off.stop()
    on = ContinuousDecodeLoop(
        eng, width=2, max_prompt=128, max_new=96, prefill_chunk_tokens=CHUNK
    )
    try:
        got = on.submit(list(prompt), **kw).result(timeout=120)
        st = dict(on.stats)
    finally:
        on.stop()
    assert st["prefill_chunks"] >= 2
    assert np.array_equal(got.tokens, base.tokens)
    body = [int(t) for t in got.tokens[0][: int(got.lengths[0])] if t < 256]
    ok, _ = validate_grammar_tokens(g, body)
    assert ok, bytes(body)
    if got.finish_reasons[0] == "stop":
        Rec.model_validate(json.loads(bytes(body)))


# -- interleaving ------------------------------------------------------------

def test_chunks_interleave_with_inflight_decode(eng):
    """While a long admission is PREFILLING, the in-flight row keeps
    decoding (prefill_interleaved counts chunks run alongside decode), and
    its output is untouched by the interleave (row keys are
    self-deterministic)."""
    solo = ContinuousDecodeLoop(eng, width=4, max_prompt=128, max_new=64)
    try:
        base = solo.submit(
            [7, 8, 9], n=1, max_new=48, temperature=0.6, top_p=0.9, seed=5
        ).result(timeout=120)
    finally:
        solo.stop()

    on = ContinuousDecodeLoop(
        eng, width=4, max_prompt=128, max_new=64, prefill_chunk_tokens=CHUNK
    )
    try:
        inflight = on.submit(
            [7, 8, 9], n=1, max_new=48, temperature=0.6, top_p=0.9, seed=5
        )
        long_fut = on.submit(
            list(LONG_PROMPT), n=1, max_new=8, temperature=0.0, top_p=None,
            seed=2,
        )
        got = inflight.result(timeout=120)
        long_res = long_fut.result(timeout=120)
        st = dict(on.stats)
    finally:
        on.stop()
    assert st["prefill_chunks"] >= 1
    assert st["prefill_interleaved"] >= 1, (
        "chunks should have run alongside the in-flight decode"
    )
    assert int(long_res.lengths[0]) > 0
    assert np.array_equal(got.tokens, base.tokens)
    assert np.array_equal(got.logprobs, base.logprobs)  # same programs: bitwise


def test_short_prompt_skips_chunking(eng):
    """prompt_len <= C: whole-prompt admission, zero chunk dispatches."""
    on = ContinuousDecodeLoop(
        eng, width=2, max_prompt=64, max_new=8, prefill_chunk_tokens=CHUNK
    )
    try:
        got = _run(on, prompt=[1, 2, 3, 4], n=1)
        st = dict(on.stats)
    finally:
        on.stop()
    assert st["prefill_chunks"] == 0
    assert int(got.lengths[0]) > 0


def test_prefix_cache_hit_skips_chunking_bitwise(paged_eng):
    """A prompt ingested via chunks lands in the prefix cache like any other;
    an identical follow-up admission skips PREFILLING entirely and reuses the
    stored run + first logits — bitwise-identical output, zero new chunks."""
    from conftest import shared_engine

    cached_eng = shared_engine(
        model="tiny", kv_layout="paged", kv_page_size=16, prefix_cache_size=4
    )
    on = ContinuousDecodeLoop(
        cached_eng, width=4, max_prompt=128, max_new=16,
        prefill_chunk_tokens=CHUNK,
    )
    try:
        first = _run(on)
        chunks_after_first = dict(on.stats)["prefill_chunks"]
        again = _run(on)
        st = dict(on.stats)
    finally:
        on.stop()
    assert chunks_after_first == (len(LONG_PROMPT) + CHUNK - 1) // CHUNK
    assert st["prefill_chunks"] == chunks_after_first  # hit: no new chunks
    assert np.array_equal(first.tokens, again.tokens)
    assert np.array_equal(first.logprobs, again.logprobs)  # bitwise reuse


# -- knob normalization ------------------------------------------------------

def test_chunk_tokens_normalization(eng):
    for given, want in ((0, 0), (-5, 0), (1, 32), (31, 32), (32, 32),
                        (48, 32), (64, 64), (100, 64)):
        loop = ContinuousDecodeLoop(
            eng, width=1, max_prompt=64, max_new=4, prefill_chunk_tokens=given
        )
        try:
            assert loop.prefill_chunk_tokens == want, (given, want)
        finally:
            loop.stop()


def test_memory_model_auto_chunk():
    from k_llms_tpu.backends.tpu import HbmMemoryModel
    from k_llms_tpu.models import get_config

    mm = HbmMemoryModel(get_config("tiny"), param_bytes=1 << 20)
    assert mm.prefill_chunk_tokens(4, 32) == 0  # tiny max_prompt: off
    c = mm.prefill_chunk_tokens(4, 1024)
    assert c >= 32 and (c & (c - 1)) == 0 and c <= 512


# -- fault domains -----------------------------------------------------------

def test_mid_chunk_hang_rebuilds_and_replays_bitwise(eng):
    """A chunk wedged past the watchdog budget (continuous.prefill=hang) is
    abandoned, the loop rebuilds, and the journaled admission replays from
    cursor 0 — the SAME chunk programs rerun on the same inputs, so the
    replayed output is bitwise-identical to an uninterrupted chunked run."""
    baseline = ContinuousDecodeLoop(
        eng, width=4, max_prompt=128, max_new=16, prefill_chunk_tokens=CHUNK
    )
    try:
        base = _run(baseline, seed=23)
    finally:
        baseline.stop()

    loop = ContinuousDecodeLoop(
        eng, width=4, max_prompt=128, max_new=16, prefill_chunk_tokens=CHUNK,
        budget_model=_step_budget(6.0), rebuild_fn=lambda: eng, max_rebuilds=3,
    )
    try:
        hangs = RECOVERY_EVENTS.snapshot().get("continuous.step_hangs", 0)
        with fp.failpoints(
            {"continuous.prefill": FailSpec(action="hang", times=1, delay=20.0)}
        ):
            got = _run(loop, seed=23)
        assert RECOVERY_EVENTS.snapshot()["continuous.step_hangs"] > hangs
        st = dict(loop.stats)
    finally:
        loop.stop()
    assert st["restarts"] >= 1
    assert st["last_recovery_reason"] == "hung_step"
    assert np.array_equal(got.tokens, base.tokens)
    assert np.array_equal(got.logprobs, base.logprobs)  # bitwise: same programs
    assert list(got.lengths) == list(base.lengths)


def test_prefilling_budget_abort_retires_row(eng):
    """A budget cancelled mid-PREFILLING retires the admission through the
    decode-abort fault domain (typed error, counter, slots freed) without
    wedging the loop."""
    budget = RequestBudget()
    before = FAILURE_EVENTS.snapshot().get("engine.decode_abort", 0)
    loop = ContinuousDecodeLoop(
        eng, width=4, max_prompt=128, max_new=16, prefill_chunk_tokens=CHUNK
    )
    try:
        # Stretch the first chunk so the cancel lands mid-prefill: the hang
        # spec sleeps inline in the chunk dispatch (no watchdog on a bare
        # loop), and the budget check runs at the next chunk boundary.
        with fp.failpoints(
            {"continuous.prefill": FailSpec(action="hang", times=1, delay=1.0)}
        ):
            fut = loop.submit(
                list(LONG_PROMPT), n=2, max_new=16, temperature=0.7,
                top_p=0.9, seed=11, budget=budget,
            )
            time.sleep(0.2)
            budget.cancel()
            with pytest.raises(RequestCancelledError):
                fut.result(timeout=60)
        assert FAILURE_EVENTS.snapshot().get("engine.decode_abort", 0) > before
        assert dict(loop.stats)["aborted"] >= 1
        # Slots and pages are free again: a follow-up request runs clean.
        ok = _run(loop, seed=31)
        assert int(ok.lengths[0]) > 0
    finally:
        loop.stop()
