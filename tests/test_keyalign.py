"""Key-based aligner: unit behavior + differential parity vs the reference."""

import random

import pytest

from reference_oracle import load_reference_keyalign, reference_available
from k_llms_tpu.keyalign import (
    CascadeConfig,
    recursive_align,
    select_best_keys,
    select_best_keys_with_fuzzy_fallback,
)
from k_llms_tpu.keyalign.align import _align_lists_by_key, _get_key_tuple
from k_llms_tpu.keyalign.selection import discover_scalar_paths, normalize_scalar


def test_normalize_scalar():
    assert normalize_scalar("  Hello   World ") == "hello world"
    assert normalize_scalar(3.5) == 3.5


def test_discover_scalar_paths():
    ex = {"products": [{"sku": "a", "meta": {"color": "red"}, "tags": ["x"]}]}
    assert discover_scalar_paths([ex]) == ["meta.color", "sku"]


def test_get_key_tuple_raw_values():
    obj = {"sku": "ABC", "meta": {"n": 2}}
    assert _get_key_tuple(obj, ("sku", "meta.n")) == ("ABC", 2)
    assert _get_key_tuple(obj, ("missing",)) is None
    assert _get_key_tuple({"sku": None}, ("sku",)) is None


def test_align_lists_by_key_basic():
    lists = [
        [{"sku": "a", "qty": 1}, {"sku": "b", "qty": 2}],
        [{"sku": "b", "qty": 2}, {"sku": "a", "qty": 1}, {"sku": "c", "qty": 3}],
    ]
    rows, idx = _align_lists_by_key(lists, ("sku",))
    # order follows the longest source (list 1): b, a, c
    assert [r[1]["sku"] if r[1] else None for r in rows] == ["b", "a", "c"]
    assert [r[0]["sku"] if r[0] else None for r in rows] == ["b", "a", None]
    assert idx[0] == [1, 0]


def test_select_best_keys_picks_stable_unique_key():
    extractions = [
        {"products": [{"sku": "a", "price": 1.0, "cat": "x"}, {"sku": "b", "price": 2.0, "cat": "x"}]},
        {"products": [{"sku": "b", "price": 2.0, "cat": "x"}, {"sku": "a", "price": 1.01, "cat": "x"}]},
    ]
    # With no uniqueness gate the union-size parsimony stage prefers the
    # constant "cat" key (reference behavior, verified by the parity tests);
    # gating constants out selects the real join key.
    result = select_best_keys(extractions)
    assert result.best_single.path == ("cat",)
    gated = select_best_keys(extractions, cascade_cfg=CascadeConfig(min_uniqueness=0.2))
    assert gated.best_single.path == ("sku",)


def test_fuzzy_preferred_on_jittery_numbers():
    # price differs slightly across extractions -> fuzzy (rounded) is more stable
    extractions = [
        {"products": [{"price": 1.291}, {"price": 2.502}]},
        {"products": [{"price": 1.293}, {"price": 2.498}]},
    ]
    comp = select_best_keys_with_fuzzy_fallback(extractions)
    assert comp.chosen == "fuzzy"


def test_recursive_align_swap_signature():
    values = [
        {"items": [{"sku": "a", "v": 1}, {"sku": "b", "v": 2}]},
        {"items": [{"sku": "b", "v": 2}, {"sku": "a", "v": 1}]},
    ]
    aligned, mappings = recursive_align(values, "levenshtein", 0.5)
    assert len(aligned) == 2
    # both sources see the same item order after alignment
    assert [d["sku"] for d in aligned[0]["items"]] == [d["sku"] for d in aligned[1]["items"]]
    assert mappings  # traceability paths present


# ---------------- differential parity vs the reference ----------------

pytestmark_ref = pytest.mark.skipif(
    not reference_available(), reason="reference tree not mounted"
)

SKUS = ["alpha", "beta", "gamma", "delta", "epsilon", "zeta"]
CATS = ["tools", "toys", "food"]


def _record(rng):
    return {
        "sku": rng.choice(SKUS),
        "name": rng.choice(SKUS) + " item",
        "price": round(rng.uniform(1, 50), rng.choice([2, 3])),
        "qty": rng.randint(1, 9),
        "meta": {"cat": rng.choice(CATS), "rank": rng.randint(1, 100)},
    }


def _extraction(rng, n_records):
    recs = []
    seen = set()
    for _ in range(n_records):
        r = _record(rng)
        if r["sku"] in seen:
            continue
        seen.add(r["sku"])
        recs.append(r)
    return {"products": recs}


def _perturbed_family(seed):
    rng = random.Random(seed)
    base = _extraction(rng, rng.randint(2, 5))
    out = [base]
    for _ in range(rng.randint(1, 3)):
        import copy

        e = copy.deepcopy(base)
        for rec in e["products"]:
            if rng.random() < 0.4:
                rec["price"] = round(rec["price"] + rng.uniform(-0.004, 0.004), 4)
            if rng.random() < 0.2:
                rec["qty"] += 1
            if rng.random() < 0.2:
                rec["name"] = rec["name"].upper()
        rng.shuffle(e["products"])
        if rng.random() < 0.3 and e["products"]:
            e["products"].pop()
        out.append(e)
    return out


def _metrics_key(m):
    return (tuple(m.path), m.score_tuple)


@pytestmark_ref
@pytest.mark.parametrize("seed", range(15))
def test_parity_select_best_keys(seed):
    ks, _, _ = load_reference_keyalign()
    extractions = _perturbed_family(seed)
    try:
        ref = ks.select_best_keys(extractions)
        ref_err = None
    except ValueError as e:
        ref, ref_err = None, str(e)
    try:
        ours = select_best_keys(extractions)
        our_err = None
    except ValueError as e:
        ours, our_err = None, str(e)
    assert (ref is None) == (ours is None)
    if ref is None:
        return
    assert _metrics_key(ref.best_single) == _metrics_key(ours.best_single)
    assert (ref.best_composite is None) == (ours.best_composite is None)
    if ref.best_composite is not None:
        assert _metrics_key(ref.best_composite) == _metrics_key(ours.best_composite)


@pytestmark_ref
@pytest.mark.parametrize("seed", range(15))
def test_parity_fuzzy_selection(seed):
    _, fz, _ = load_reference_keyalign()
    extractions = _perturbed_family(100 + seed)
    try:
        ref = fz.select_best_keys_with_fuzzy_fallback(extractions)
        ref_err = None
    except ValueError:
        ref, ref_err = None, True
    try:
        ours = select_best_keys_with_fuzzy_fallback(extractions)
        our_err = None
    except ValueError:
        ours, our_err = None, True
    assert (ref is None) == (ours is None)
    if ref is None:
        return
    assert ref.chosen == ours.chosen
    if ref.fuzzy_best is not None:
        assert ours.fuzzy_best is not None
        assert _metrics_key(ref.fuzzy_best) == _metrics_key(ours.fuzzy_best)


@pytestmark_ref
@pytest.mark.parametrize("seed", range(15))
def test_parity_recursive_align(seed):
    _, _, kb = load_reference_keyalign()
    rng = random.Random(500 + seed)
    values = []
    family = _perturbed_family(500 + seed)
    for e in family:
        values.append({"doc": {"items": e["products"], "status": rng.choice(CATS)}})
    import copy

    ref_aligned, ref_map = kb.recursive_align(copy.deepcopy(values), "levenshtein", 0.5)
    our_aligned, our_map = recursive_align(copy.deepcopy(values), "levenshtein", 0.5)
    assert list(ref_aligned) == list(our_aligned), f"seed={seed}"
    assert ref_map == our_map, f"seed={seed}"


UNICODE_SKUS = ["café-α", "naïve-β", "déjà-γ", "ångström-δ", "日本-ε", "jaźń-ζ"]


@pytestmark_ref
@pytest.mark.parametrize("seed", range(10))
def test_parity_recursive_align_unicode_keys(seed):
    """Key selection and alignment over unicode join keys (accents, Greek,
    CJK) must stay bit-compatible — the canonicalization/normalization path
    is exactly where ASCII-only fuzz would hide divergence."""
    _, _, kb = load_reference_keyalign()
    rng = random.Random(900 + seed)
    base = []
    for sku in rng.sample(UNICODE_SKUS, rng.randint(2, 5)):
        base.append({
            "sku": sku,
            "name": sku + " Ärtikel",
            "price": round(rng.uniform(1, 50), 2),
            "qty": rng.randint(1, 9),
        })
    values = []
    for _ in range(rng.randint(2, 4)):
        import copy

        e = copy.deepcopy(base)
        for rec in e:
            if rng.random() < 0.3:
                rec["price"] = round(rec["price"] + rng.uniform(-0.004, 0.004), 4)
            if rng.random() < 0.2:
                rec["name"] = rec["name"].upper()
        rng.shuffle(e)
        if rng.random() < 0.3 and len(e) > 1:
            e.pop()
        values.append({"doc": {"items": e}})
    import copy

    ref_aligned, ref_map = kb.recursive_align(copy.deepcopy(values), "levenshtein", 0.5)
    our_aligned, our_map = recursive_align(copy.deepcopy(values), "levenshtein", 0.5)
    assert list(ref_aligned) == list(our_aligned), f"seed={seed}"
    assert ref_map == our_map, f"seed={seed}"
