"""Prompt-lookup speculative decoding: acceptance math, draft proposal, and
loop-level equivalence with normal decode."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k_llms_tpu.ops.speculative import accept_drafts, propose_prompt_lookup

EOS = jnp.array([7, -1, -1, -1], jnp.int32)


# -- unit: draft proposal ----------------------------------------------------

def test_propose_finds_last_bigram_continuation():
    prompt = jnp.array([5, 6, 9, 5, 6, 11, 12, 13, 0, 0], jnp.int32)
    drafts = propose_prompt_lookup(
        prompt, jnp.int32(8), jnp.array([5]), jnp.array([6]), k=3
    )
    # LAST (5,6) is at positions 3,4 -> continuation 11,12,13.
    np.testing.assert_array_equal(np.asarray(drafts), [[11, 12, 13]])


def test_propose_falls_back_without_match():
    prompt = jnp.array([1, 2, 3, 4, 0, 0], jnp.int32)
    drafts = propose_prompt_lookup(
        prompt, jnp.int32(4), jnp.array([8]), jnp.array([9]), k=2
    )
    np.testing.assert_array_equal(np.asarray(drafts), [[9, 9]])  # repeat cur


def test_propose_clamps_at_prompt_end():
    prompt = jnp.array([1, 2, 3, 0], jnp.int32)
    drafts = propose_prompt_lookup(
        prompt, jnp.int32(3), jnp.array([1]), jnp.array([2]), k=3
    )
    # Match at (1,2); only token 3 follows inside the prompt; rest fall back.
    np.testing.assert_array_equal(np.asarray(drafts), [[3, 2, 2]])


# -- unit: acceptance --------------------------------------------------------

def test_accept_full_and_partial_runs():
    sampled = jnp.array([[10, 11, 12], [10, 99, 12]], jnp.int32)
    drafts = jnp.array([[10, 11], [10, 11]], jnp.int32)
    emit, counts, hit = accept_drafts(sampled, drafts, EOS, jnp.array([3, 3]))
    # Row 0: draws match both drafts -> all 3 emitted. Row 1: draw 1 != draft
    # -> draw 2 conditioned on wrong prefix, only draws 0..1 emitted.
    np.testing.assert_array_equal(np.asarray(counts), [3, 2])
    np.testing.assert_array_equal(np.asarray(emit[0]), [True, True, True])
    np.testing.assert_array_equal(np.asarray(emit[1]), [True, True, False])
    assert not np.asarray(hit).any()


def test_accept_stops_after_eos():
    sampled = jnp.array([[7, 11, 12]], jnp.int32)  # eos at position 0
    drafts = jnp.array([[11, 12]], jnp.int32)
    emit, counts, hit = accept_drafts(sampled, drafts, EOS, jnp.array([3]))
    np.testing.assert_array_equal(np.asarray(emit[0]), [True, False, False])
    np.testing.assert_array_equal(np.asarray(counts), [1])
    assert np.asarray(hit)[0]


def test_accept_respects_budget():
    sampled = jnp.array([[10, 11, 12]], jnp.int32)
    drafts = jnp.array([[10, 11]], jnp.int32)
    emit, counts, hit = accept_drafts(sampled, drafts, EOS, jnp.array([2]))
    np.testing.assert_array_equal(np.asarray(counts), [2])
    np.testing.assert_array_equal(np.asarray(emit[0]), [True, True, False])


def test_accept_zero_budget_emits_nothing():
    sampled = jnp.array([[10, 11]], jnp.int32)
    drafts = jnp.array([[10]], jnp.int32)
    emit, counts, _ = accept_drafts(sampled, drafts, EOS, jnp.array([0]))
    np.testing.assert_array_equal(np.asarray(counts), [0])


# -- loop: equivalence with normal decode ------------------------------------

@pytest.fixture(scope="module")
def engines():
    from conftest import shared_engine

    normal = shared_engine("tiny")
    spec = shared_engine("tiny", speculative="prompt_lookup", spec_lookahead=4)
    return normal, spec


PROMPT = [int(x) for x in jax.random.randint(jax.random.key(1), (40,), 5, 200)]


def test_greedy_spec_matches_normal_decode(engines):
    """Greedy chains are deterministic, so speculative output must equal the
    normal decode token-for-token (acceptance only changes how many tokens
    each forward confirms, never their values)."""
    normal, spec = engines
    r_n = normal.generate(PROMPT, n=3, max_new_tokens=12, temperature=0.0, seed=4)
    r_s = spec.generate(PROMPT, n=3, max_new_tokens=12, temperature=0.0, seed=4)
    np.testing.assert_array_equal(r_s.tokens, r_n.tokens)
    np.testing.assert_allclose(r_s.logprobs, r_n.logprobs, rtol=1e-4, atol=1e-4)
    np.testing.assert_array_equal(r_s.lengths, r_n.lengths)
    assert r_s.finish_reasons == r_n.finish_reasons


def test_greedy_spec_matches_with_repetitive_prompt(engines):
    """A highly repetitive prompt maximizes lookup hits (multi-token accepts)
    — output must still be exactly the greedy chain."""
    normal, spec = engines
    prompt = [11, 12, 13, 14] * 12
    r_n = normal.generate(prompt, n=2, max_new_tokens=10, temperature=0.0, seed=9)
    r_s = spec.generate(prompt, n=2, max_new_tokens=10, temperature=0.0, seed=9)
    np.testing.assert_array_equal(r_s.tokens, r_n.tokens)


def test_spec_sampling_outputs_valid(engines):
    """Sampled speculative decode: correct shapes, lengths consistent with
    buffers, pad only after the end, vocab-bounded tokens."""
    _, spec = engines
    r = spec.generate(PROMPT, n=4, max_new_tokens=8, temperature=0.9, seed=17)
    assert r.tokens.shape == (4, 8)
    cfg = spec.config
    for row, ln in zip(r.tokens, r.lengths):
        assert 1 <= ln <= 8
        assert (row[ln:] == cfg.pad_token_id).all()
        assert (row[:ln] < cfg.vocab_size).all()
    assert set(r.finish_reasons) <= {"stop", "length"}


def test_spec_respects_eos(engines):
    """Rows that emit eos finish with reason "stop" and stop growing — forced
    by declaring the greedy chain's own first token to be eos."""
    normal, spec = engines
    first = int(
        normal.generate(PROMPT, n=1, max_new_tokens=1, temperature=0.0, seed=3).tokens[0, 0]
    )
    r = spec.generate(PROMPT, n=2, max_new_tokens=8, temperature=0.0, seed=3,
                      eos_ids=[first])
    assert r.finish_reasons == ["stop", "stop"]
    np.testing.assert_array_equal(r.lengths, [1, 1])
    assert (r.tokens[:, 0] == first).all()
    assert (r.tokens[:, 1:] == spec.config.pad_token_id).all()


def _assert_spec_ran(spec):
    # the sentinel modes mark normal-loop fallbacks; absence = spec loop served
    assert "mode" not in spec.spec_stats, spec.spec_stats


def test_spec_composes_penalties(engines):
    """VERDICT r2 #4: frequency/presence penalties run UNDER speculation with
    normal-loop semantics — greedy chains must match token-for-token (the
    per-position penalty counts are closed-form over the draft prefix)."""
    normal, spec = engines
    kw = dict(
        n=2, max_new_tokens=12, temperature=0.0, seed=6,
        frequency_penalty=0.7, presence_penalty=0.3,
    )
    r_n = normal.generate(PROMPT, **kw)
    r_s = spec.generate(PROMPT, **kw)
    _assert_spec_ran(spec)
    np.testing.assert_array_equal(r_s.tokens, r_n.tokens)
    np.testing.assert_allclose(r_s.logprobs, r_n.logprobs, rtol=1e-4, atol=1e-4)
    assert r_s.finish_reasons == r_n.finish_reasons


def test_spec_composes_logit_bias(engines):
    normal, spec = engines
    bias = {int(PROMPT[0]): 4.0, int(PROMPT[1]): -6.0}
    kw = dict(n=2, max_new_tokens=10, temperature=0.0, seed=8, logit_bias=bias)
    r_n = normal.generate(PROMPT, **kw)
    r_s = spec.generate(PROMPT, **kw)
    _assert_spec_ran(spec)
    np.testing.assert_array_equal(r_s.tokens, r_n.tokens)


def test_spec_composes_top_logprobs(engines):
    """Per-position top-k alternatives captured in the verify loop must equal
    the normal loop's, position by position."""
    normal, spec = engines
    kw = dict(n=2, max_new_tokens=8, temperature=0.0, seed=4, top_logprobs=3)
    r_n = normal.generate(PROMPT, **kw)
    r_s = spec.generate(PROMPT, **kw)
    _assert_spec_ran(spec)
    np.testing.assert_array_equal(r_s.tokens, r_n.tokens)
    for i in range(2):
        ln = int(r_n.lengths[i])
        np.testing.assert_array_equal(
            r_s.top_tokens[i][:ln], r_n.top_tokens[i][:ln]
        )
        np.testing.assert_allclose(
            r_s.top_logprobs[i][:ln], r_n.top_logprobs[i][:ln], rtol=1e-4, atol=1e-4
        )


@pytest.mark.slow  # 9s e2e composition; spec decode and the JSON DFA each
@pytest.mark.duration_budget(45)  # have dedicated tier-1 coverage
def test_spec_composes_json_constraint(engines):
    """The grammar automaton advances across accepted drafts: greedy
    constrained output matches the normal constrained loop exactly, and every
    sample is valid JSON."""
    import json as _json

    normal, spec = engines
    eos = [normal.config.eos_token_id]
    kw = dict(
        n=2, max_new_tokens=24, temperature=0.0, seed=5,
        constraint="json", eos_ids=eos,
    )
    r_n = normal.generate(PROMPT, **kw)
    r_s = spec.generate(PROMPT, **kw)
    _assert_spec_ran(spec)
    np.testing.assert_array_equal(r_s.tokens, r_n.tokens)
    np.testing.assert_array_equal(r_s.lengths, r_n.lengths)
    # sampled constrained spec output is also structurally valid
    r = spec.generate(
        PROMPT, n=3, max_new_tokens=32, temperature=0.9, seed=123,
        constraint="json", eos_ids=eos,
    )
    _assert_spec_ran(spec)
    for row, ln, fin in zip(r.tokens, r.lengths, r.finish_reasons):
        if fin == "stop":
            text = bytes(t for t in row[:ln] if t < 256).decode("utf-8", "replace")
            _json.loads(text)


def test_spec_composes_stop_sequences(engines):
    """Device stop sequences run UNDER speculation: greedy output, lengths,
    and finish reasons match the normal loop's on-device halt — including
    stops that complete mid-draft-run."""
    normal, spec = engines
    # Find the greedy chain's 3rd token and stop on it: the stop triggers
    # mid-generation deterministically.
    chain = normal.generate(PROMPT, n=1, max_new_tokens=6, temperature=0.0, seed=4)
    stop_tok = int(chain.tokens[0, 2])
    kw = dict(
        n=2, max_new_tokens=12, temperature=0.0, seed=4,
        stop_sequences=[[stop_tok]],
    )
    r_n = normal.generate(PROMPT, **kw)
    r_s = spec.generate(PROMPT, **kw)
    _assert_spec_ran(spec)
    np.testing.assert_array_equal(r_s.tokens, r_n.tokens)
    np.testing.assert_array_equal(r_s.lengths, r_n.lengths)
    assert r_s.finish_reasons == r_n.finish_reasons == ["stop", "stop"]


def test_spec_stop_with_repetitive_prompt(engines):
    """A repetitive prompt maximizes multi-token accepts, so the stop must be
    caught inside an accepted draft run, not only at run boundaries."""
    normal, spec = engines
    prompt = [21, 22, 23, 24] * 12
    chain = normal.generate(prompt, n=1, max_new_tokens=8, temperature=0.0, seed=9)
    stop_pair = [int(chain.tokens[0, 3]), int(chain.tokens[0, 4])]
    kw = dict(n=2, max_new_tokens=10, temperature=0.0, seed=9,
              stop_sequences=[stop_pair])
    r_n = normal.generate(prompt, **kw)
    r_s = spec.generate(prompt, **kw)
    _assert_spec_ran(spec)
    np.testing.assert_array_equal(r_s.tokens, r_n.tokens)
    np.testing.assert_array_equal(r_s.lengths, r_n.lengths)


def test_backend_plumbs_speculative():
    """BackendConfig carries the knobs through to the engine (a silently
    dropped kwarg here once made the feature unreachable), and the public
    client path still serves — the spec loop runs on any topology now (the
    mesh gate is gone)."""
    from k_llms_tpu.backends.tpu import TpuBackend

    backend = TpuBackend(model="tiny", speculative="prompt_lookup", spec_lookahead=3)
    assert backend.engine.speculative == "prompt_lookup"
    assert backend.engine.spec_lookahead == 3
    from k_llms_tpu import KLLMs

    client = KLLMs(backend=backend, model="tiny")
    r = client.chat.completions.create(
        messages=[{"role": "user", "content": "hi"}], model="tiny", n=2, seed=3)
    assert len(r.choices) == 3

    # Per-launch spec stats propagate engine -> scheduler.stats() and the
    # fleet-level SPEC_EVENTS counters: drive a copy-shaped request (prompt
    # run of 'x' + logit_bias forcing its continuation) so drafts actually
    # get accepted, then read the aggregates back.
    from k_llms_tpu.utils.observability import SPEC_EVENTS

    events_before = SPEC_EVENTS.snapshot().get("spec.launches", 0)
    stats0 = backend.scheduler.stats
    client.chat.completions.create(
        messages=[{"role": "user", "content": "x" * 40}], model="tiny", n=1,
        temperature=0.0, seed=1, logit_bias={"120": 100.0}, max_tokens=24,
    )
    stats = backend.scheduler.stats
    assert stats["spec_launches"] > stats0["spec_launches"]
    assert stats["spec_drafted"] > stats0["spec_drafted"]
    assert stats["spec_accepted"] > stats0["spec_accepted"]
    assert stats["spec_tokens_per_iteration"] > 1.0
    assert SPEC_EVENTS.snapshot().get("spec.launches", 0) > events_before


def test_spec_loop_runs_through_engine_generate():
    from conftest import shared_engine

    # Private lookahead (=2) keys a fresh engine: the jit-cache assertions
    # below inspect engine state, which shared engines accumulate.
    eng = shared_engine("tiny", speculative="prompt_lookup", spec_lookahead=2)
    eng.generate([5, 6, 7, 8], n=2, max_new_tokens=4, temperature=0.7, seed=1)
    assert eng._spec_decode_cache and not eng._decode_cache


def test_propose_prefers_generated_text_match():
    prompt = jnp.array([5, 6, 30, 0, 0], jnp.int32)
    gen = jnp.array([[9, 5, 6, 40, 41, 5, 6, 0]], jnp.int32)
    drafts = propose_prompt_lookup(
        prompt, jnp.int32(3), jnp.array([5]), jnp.array([6]), k=2,
        gen=gen, gen_len=jnp.array([7]),
    )
    # Trailing bigram (5,6) at positions 5,6 is excluded; the match at 1,2
    # gives continuation 40,41 — preferred over the prompt's 30.
    np.testing.assert_array_equal(np.asarray(drafts), [[40, 41]])


def test_propose_gen_without_match_falls_back_to_prompt():
    prompt = jnp.array([5, 6, 30, 31, 0], jnp.int32)
    gen = jnp.array([[1, 2, 3, 5, 6, 0, 0, 0]], jnp.int32)  # only trailing bigram
    drafts = propose_prompt_lookup(
        prompt, jnp.int32(4), jnp.array([5]), jnp.array([6]), k=2,
        gen=gen, gen_len=jnp.array([5]),
    )
    np.testing.assert_array_equal(np.asarray(drafts), [[30, 31]])


def test_spec_stats_reports_acceptance():
    from conftest import shared_engine

    eng = shared_engine("tiny", speculative="prompt_lookup", spec_lookahead=4)
    r = eng.generate(PROMPT, n=2, max_new_tokens=10, temperature=0.0, seed=4)
    stats = eng.spec_stats
    assert stats["verify_iterations"] >= 1
    # Per-row rate: each verify a row enters emits at least one token for it;
    # accepts can only raise the rate.
    assert stats["tokens_per_iteration"] >= 0.99
    assert stats["tokens_per_iteration"] <= eng.spec_lookahead + 1

    # Zero-verify edge: every row stops on its prefill-sampled first token.
    first = int(r.tokens[0, 0])
    eng.generate(PROMPT, n=2, max_new_tokens=8, temperature=0.0, seed=4,
                 eos_ids=[first])
    assert eng.spec_stats["verify_iterations"] == 0
    assert eng.spec_stats["tokens_per_iteration"] is None


def test_copy_prompt_accepts_multi_token_drafts(engines):
    """The PAYOFF case (deterministic): a prompt ending in a long token run
    plus a logit_bias that forces the continuation to copy it. The
    prompt-lookup drafter proposes the run, greedy sampling matches it, and
    acceptance must climb well above one token per verify step — through the
    real draft/verify/accept machinery, not a mock."""
    _, spec = engines
    prompt = [50, 51, 52] + [120] * 40
    r = spec.generate(
        prompt, n=1, max_new_tokens=32, temperature=0.0, seed=0,
        logit_bias={120: 100.0},
    )
    assert (np.asarray(r.tokens) == 120).all()
    stats = spec.spec_stats
    assert stats["drafted"] > 0
    assert stats["accepted"] > 0
    # 32 tokens in ~ceil(32/(K+1)) verifies: 4+ tokens/iteration at K=4.
    assert stats["tokens_per_iteration"] > 2.0, stats


# -- mesh: spec decoding under TP/DP (VERDICT r3 #4) -------------------------

@pytest.fixture(scope="module")
def mesh_engines():
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device CPU mesh")
    from conftest import shared_engine

    normal = shared_engine("tiny", mesh_shape=(4, 2))
    spec = shared_engine(
        "tiny", mesh_shape=(4, 2),
        speculative="prompt_lookup", spec_lookahead=4,
    )
    return normal, spec


@pytest.mark.mesh
def test_mesh_greedy_spec_matches_mesh_normal(mesh_engines):
    """Greedy chains are deterministic: the meshed spec loop must reproduce
    the meshed normal loop token-for-token, and spec_stats must be LIVE (no
    fallback sentinel) now that the mesh gate is gone."""
    normal, spec = mesh_engines
    kw = dict(n=4, max_new_tokens=10, temperature=0.0, seed=3)
    r_n = normal.generate(PROMPT, **kw)
    r_s = spec.generate(PROMPT, **kw)
    assert "mode" not in spec.spec_stats, spec.spec_stats
    assert spec.spec_stats["verify_iterations"] >= 1
    np.testing.assert_array_equal(r_s.tokens, r_n.tokens)
    np.testing.assert_allclose(r_s.logprobs, r_n.logprobs, rtol=1e-4, atol=1e-4)
    assert r_s.finish_reasons == r_n.finish_reasons


@pytest.mark.mesh
def test_mesh_sampled_spec_matches_single_chip_spec(mesh_engines):
    """Sampling streams fold (request key, position, row), so the meshed spec
    loop must reproduce the single-chip spec loop draw-for-draw even at
    temperature > 0 — including when n doesn't divide the data axis (row
    padding must not perturb the first n rows' keys)."""
    from conftest import shared_engine

    _, spec = mesh_engines
    solo = shared_engine("tiny", speculative="prompt_lookup", spec_lookahead=4)
    kw = dict(n=3, max_new_tokens=8, temperature=0.9, seed=11)
    r_solo = solo.generate(PROMPT, **kw)
    r_mesh = spec.generate(PROMPT, **kw)
    np.testing.assert_array_equal(r_mesh.tokens, r_solo.tokens)
    np.testing.assert_allclose(r_mesh.logprobs, r_solo.logprobs, rtol=1e-4, atol=1e-4)


@pytest.mark.mesh
def test_mesh_spec_composes_features(mesh_engines):
    """Penalties + stop sequences + logit_bias under meshed speculation keep
    normal-loop semantics (greedy differential)."""
    normal, spec = mesh_engines
    kw = dict(
        n=4, max_new_tokens=10, temperature=0.0, seed=6,
        frequency_penalty=0.5, presence_penalty=0.2,
        logit_bias={9: 3.0},
        stop_sequences=[[13, 14]],
    )
    r_n = normal.generate(PROMPT, **kw)
    r_s = spec.generate(PROMPT, **kw)
    assert "mode" not in spec.spec_stats
    np.testing.assert_array_equal(r_s.tokens, r_n.tokens)
    assert r_s.finish_reasons == r_n.finish_reasons


@pytest.mark.mesh
def test_mesh_spec_sp_resident_matches_sp_decode(mesh_engines):
    """SP-resident (sequence-sharded prefix) prompts go through the REAL spec
    loop now: verify_step attends the ring-layout prefix via ring attention,
    so the spec engine must reproduce the non-spec sp_decode loop
    token-for-token at temperature 0 — and report live spec stats, not the
    old ``sp_decode_fallback`` sentinel."""
    from conftest import shared_engine

    plain = shared_engine(
        "tiny", mesh_shape=(4, 2), sp_prefill_min_tokens=48, sp_decode=True,
    )
    spec = shared_engine(
        "tiny", mesh_shape=(4, 2), sp_prefill_min_tokens=48, sp_decode=True,
        speculative="prompt_lookup", spec_lookahead=4,
    )
    long_prompt = PROMPT * 2  # 80 tokens >= 48: SP-resident
    kw = dict(n=4, max_new_tokens=6, temperature=0.0, seed=1)
    r_plain = plain.generate(long_prompt, **kw)
    r_spec = spec.generate(long_prompt, **kw)
    assert "mode" not in spec.spec_stats, spec.spec_stats
    assert spec.spec_stats["verify_iterations"] >= 1
    np.testing.assert_array_equal(r_spec.tokens, r_plain.tokens)
    np.testing.assert_allclose(
        r_spec.logprobs, r_plain.logprobs, rtol=1e-4, atol=1e-4
    )
    assert r_spec.finish_reasons == r_plain.finish_reasons


# -- coalesced batches: R-request spec loop (VERDICT r3 #5) ------------------

def test_coalesced_spec_matches_coalesced_normal_greedy(engines):
    """generate_many under speculation must reproduce the normal coalesced
    loop token-for-token at temperature 0 — including with DISTINCT prompts
    per request (each row drafts from its own request's prompt table)."""
    from k_llms_tpu.engine.engine import GenRequestSpec

    normal, spec = engines
    p2 = [int(x) for x in jax.random.randint(jax.random.key(9), (25,), 5, 200)]
    items = [
        GenRequestSpec(prompt_ids=PROMPT, n=2, seed=3),
        GenRequestSpec(prompt_ids=p2, n=3, seed=5),
        GenRequestSpec(prompt_ids=PROMPT[:17], n=1, seed=8),
    ]
    kw = dict(max_new_tokens=10, temperature=0.0)
    r_n = normal.generate_many(items, **kw)
    r_s = spec.generate_many(items, **kw)
    assert spec.spec_stats["coalesced_requests"] == 3
    for got, want in zip(r_s, r_n):
        np.testing.assert_array_equal(got.tokens, want.tokens)
        np.testing.assert_allclose(got.logprobs, want.logprobs, rtol=1e-4, atol=1e-4)
        assert got.finish_reasons == want.finish_reasons
        # Per-request stats are live values, not a fallback sentinel.
        assert "mode" not in got.spec_stats
        assert got.spec_stats["verify_iterations"] >= 1


def test_coalesced_spec_sampled_matches_solo_streams(engines):
    """Per-request sampling streams fold row-WITHIN-request, so a coalesced
    speculative batch must reproduce each request's SOLO speculative output
    draw-for-draw at temperature > 0."""
    from k_llms_tpu.engine.engine import GenRequestSpec

    _, spec = engines
    p2 = [int(x) for x in jax.random.randint(jax.random.key(12), (30,), 5, 200)]
    items = [
        GenRequestSpec(prompt_ids=PROMPT, n=2, seed=21),
        GenRequestSpec(prompt_ids=p2, n=2, seed=22),
    ]
    kw = dict(max_new_tokens=8, temperature=0.9)
    batched = spec.generate_many(items, **kw)
    for it, got in zip(items, batched):
        solo = spec.generate(
            it.prompt_ids, n=it.n, seed=it.seed,
            max_new_tokens=8, temperature=0.9,
        )
        np.testing.assert_array_equal(got.tokens, solo.tokens)


def test_coalesced_spec_accepts_drafts_on_prompt_copy(engines):
    """A prompt with a strongly repeated continuation gives draft acceptance
    > 1 token/iteration under coalescing — the burst workload the feature
    exists for (greedy decode on a repetitive prompt re-emits the pattern)."""
    from k_llms_tpu.engine.engine import GenRequestSpec

    _, spec = engines
    loop_prompt = [11, 12, 13, 14, 15] * 6  # strong bigram structure
    items = [
        GenRequestSpec(prompt_ids=loop_prompt, n=2, seed=1),
        GenRequestSpec(prompt_ids=loop_prompt, n=2, seed=2),
    ]
    spec.generate_many(items, max_new_tokens=12, temperature=0.0)
    stats = spec.spec_stats
    assert stats["coalesced_requests"] == 2
    assert stats["verify_iterations"] >= 1
    assert stats["tokens_per_iteration"] is not None


def test_coalesced_spec_composes_stops_and_bias(engines):
    """Stops + logit_bias under coalesced speculation keep normal-loop
    semantics (greedy differential)."""
    from k_llms_tpu.engine.engine import GenRequestSpec

    normal, spec = engines
    items = [
        GenRequestSpec(prompt_ids=PROMPT, n=2, seed=4),
        GenRequestSpec(prompt_ids=PROMPT[:22], n=2, seed=6),
    ]
    kw = dict(
        max_new_tokens=10, temperature=0.0,
        logit_bias={31: 4.0}, stop_sequences=[[31, 31]],
    )
    r_n = normal.generate_many(items, **kw)
    r_s = spec.generate_many(items, **kw)
    for got, want in zip(r_s, r_n):
        np.testing.assert_array_equal(got.tokens, want.tokens)
        assert got.finish_reasons == want.finish_reasons
