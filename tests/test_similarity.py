"""Generic similarity semantics (reference consensus_utils :797-917)."""

import pytest

from k_llms_tpu.consensus.settings import SIMILARITY_SCORE_LOWER_BOUND
from k_llms_tpu.consensus.similarity import SimilarityScorer, cosine_similarity


@pytest.fixture
def scorer():
    return SimilarityScorer(method="levenshtein")


def test_both_falsy_is_perfect(scorer):
    # "" / 0 / [] / False / None all count as agreement
    assert scorer.generic(None, None) == 1.0
    assert scorer.generic("", 0) == 1.0
    assert scorer.generic([], False) == 1.0


def test_single_none_is_floor(scorer):
    assert scorer.generic(None, "x") == SIMILARITY_SCORE_LOWER_BOUND
    assert scorer.generic(5, None) == SIMILARITY_SCORE_LOWER_BOUND


def test_numbers_one_percent_tolerance(scorer):
    assert scorer.generic(100, 100.5) == 1.0
    assert scorer.generic(100, 102) == SIMILARITY_SCORE_LOWER_BOUND
    assert scorer.generic(True, True) == 1.0
    assert scorer.generic(True, False) == SIMILARITY_SCORE_LOWER_BOUND


def test_dict_similarity_skips_reasoning_keys(scorer):
    d1 = {"a": "x", "reasoning___a": "completely different"}
    d2 = {"a": "x", "reasoning___a": "other"}
    assert scorer.generic(d1, d2) == 1.0


def test_dict_union_of_keys(scorer):
    d1 = {"a": "xx"}
    d2 = {"a": "xx", "b": "yy"}
    # key b: d1.get -> None vs "yy" => floor; mean of (1.0, floor)
    assert scorer.generic(d1, d2) == pytest.approx((1.0 + SIMILARITY_SCORE_LOWER_BOUND) / 2)


def test_list_positional_mean(scorer):
    assert scorer.generic(["ab", "cd"], ["ab", "cd"]) == 1.0
    assert scorer.generic(["ab"], ["ab", "cd"]) == pytest.approx(
        (1.0 + SIMILARITY_SCORE_LOWER_BOUND) / 2
    )


def test_mismatched_types_floor(scorer):
    assert scorer.generic("5", 5) == SIMILARITY_SCORE_LOWER_BOUND


def test_cosine_normalization():
    assert cosine_similarity([1.0, 0.0], [1.0, 0.0]) == pytest.approx(1.0)
    assert cosine_similarity([1.0, 0.0], [-1.0, 0.0]) == pytest.approx(SIMILARITY_SCORE_LOWER_BOUND)
    assert cosine_similarity([1.0, 0.0], [0.0, 1.0]) == pytest.approx(0.5)
    assert cosine_similarity([0.0, 0.0], [1.0, 0.0]) == SIMILARITY_SCORE_LOWER_BOUND
    with pytest.raises(ValueError):
        cosine_similarity([1.0], [1.0, 2.0])


def test_embeddings_gate_and_fallback():
    calls = []

    def embed(texts):
        calls.append(texts)
        return [[1.0, 0.0] for _ in texts]

    s = SimilarityScorer(method="embeddings", embed_fn=embed)
    # short strings: no embedding call, levenshtein fallback
    s.string("short", "short")
    assert calls == []
    long_a = "a" * 60
    long_b = "a" * 59 + "b"
    s.string(long_a, long_b)
    assert len(calls) == 2  # one embed call per string


def test_embedding_error_degrades_to_levenshtein():
    def embed(texts):
        raise RuntimeError("no device")

    s = SimilarityScorer(method="embeddings", embed_fn=embed)
    long_a = "x" * 60
    assert s.string(long_a, long_a) == 1.0  # levenshtein fallback


def test_similarity_cache_hit():
    count = 0

    class CountingScorer(SimilarityScorer):
        pass

    s = SimilarityScorer(method="levenshtein")
    r1 = s.string("hello world", "hello word")
    r2 = s.string("hello word", "hello world")  # symmetric key
    assert r1 == r2
