"""Vote-based consensus (reference consensus_utils :936-982)."""

from k_llms_tpu.consensus.settings import ConsensusSettings
from k_llms_tpu.consensus.voting import voting_consensus


def settings(**kw):
    return ConsensusSettings(**kw)


def test_string_majority():
    val, conf = voting_consensus(["yes", "yes", "no"], settings())
    assert val == "yes"
    assert conf == round(2 / 3, 5)


def test_sanitized_forms_vote_together_original_spelling_wins():
    val, conf = voting_consensus(["São Paulo", "sao paulo", "Rio"], settings())
    assert val == "São Paulo"  # first-seen original spelling
    assert conf == round(2 / 3, 5)


def test_none_excluded_from_candidates_but_counted_in_total():
    val, conf = voting_consensus(["a", None, None], settings())
    assert val == "a"
    assert conf == round(1 / 3, 5)


def test_none_as_candidate_allowed():
    val, conf = voting_consensus(["a", None, None], settings(allow_none_as_candidate=True))
    assert val is None
    assert conf == round(2 / 3, 5)


def test_booleans_none_is_false():
    val, conf = voting_consensus([True, None, False], settings())
    assert val is False
    assert conf == round(2 / 3, 5)


def test_all_none():
    val, conf = voting_consensus([None, None], settings(), parent_valid_frac=0.5)
    assert val is None
    assert conf == 0.5


def test_parent_valid_frac_scales():
    val, conf = voting_consensus(["x", "x"], settings(), parent_valid_frac=0.5)
    assert val == "x"
    assert conf == 0.5
