"""Environment sanity: the mesh suites must not silently evaporate.

Every multi-device test skips with a "needs the 8-device CPU mesh" guard; a
misconfigured runner (e.g. a caller-preset XLA_FLAGS without
--xla_force_host_platform_device_count) would skip them all and still report
green (VERDICT r2 weak #7). This test turns that silent degradation into a
loud failure; set KLLMS_ALLOW_NO_MESH=1 to acknowledge a deliberately
mesh-less run.
"""

import os

import jax
import pytest


def test_virtual_mesh_is_present():
    if os.environ.get("KLLMS_ALLOW_NO_MESH"):
        pytest.skip("mesh requirement explicitly waived via KLLMS_ALLOW_NO_MESH")
    assert len(jax.devices()) >= 8, (
        f"only {len(jax.devices())} JAX device(s) visible — the 8-device "
        "virtual CPU mesh is missing, so every mesh-marked suite would "
        "silently skip. tests/conftest.py appends "
        "--xla_force_host_platform_device_count=8 to XLA_FLAGS unless the "
        "caller already set a conflicting value; fix the environment or set "
        "KLLMS_ALLOW_NO_MESH=1 to run mesh-less deliberately."
    )


def test_platform_is_cpu():
    """Tests must run on the virtual CPU platform — the axon TPU relay hangs
    forever when unreachable, and test determinism assumes host execution."""
    assert jax.default_backend() == "cpu", jax.default_backend()
