"""Quickstart: the reference's README usage, running fully locally on the TPU
engine (zero OpenAI calls). Mirrors `/root/reference/README.md` "Usage" so a
k-LLMs user can see the one-line switch: `KLLMs()` -> `KLLMs(backend="tpu")`.

Run from the repo root (hermetic; uses the tiny random-init model so it works
anywhere — put a real checkpoint path in BackendConfig for production):

    python examples/quickstart.py
"""

import asyncio

from pydantic import BaseModel

from k_llms_tpu import AsyncKLLMs, KLLMs

# ---------------------------------------------------------------------------
# Basic usage — consensus via the `n` parameter (reference README "Basic
# Usage"; the remote OpenAI call becomes one batched on-device decode).
# ---------------------------------------------------------------------------
client = KLLMs(backend="tpu", model="tiny")

response = client.chat.completions.create(
    model="tiny",
    messages=[{"role": "user", "content": "What is 2+2?"}],
    n=3,  # 3 samples decoded as ONE batched XLA program, then consolidated
    seed=7,
)
print("consensus:", response.choices[0].message.content[:60])
print("originals:", [len(c.message.content or "") for c in response.choices[1:]])
print("likelihoods:", response.likelihoods)


# ---------------------------------------------------------------------------
# Structured outputs with parse() — grammar-constrained decoding guarantees
# every sample is valid for the schema (the reference delegates this to the
# OpenAI server; here a schema-compiled DFA masks logits on device).
# ---------------------------------------------------------------------------
class UserInfo(BaseModel):
    name: str
    age: int


result = client.chat.completions.parse(
    model="tiny",
    messages=[{"role": "user", "content": "John is 30 years old"}],
    response_format=UserInfo,
    n=3,
    seed=11,
    max_tokens=96,
)
consensus_user = result.choices[0].message.parsed  # consolidated UserInfo
original_users = [c.message.parsed for c in result.choices[1:]]
# Every sample is schema-valid JSON *as far as it got*: the DFA masks logits
# so invalid structure is impossible. The random-init tiny model may still
# run out of max_tokens before closing a string (finish_reason "length"),
# in which case .parsed degrades to None — with a real checkpoint, samples
# finish with "stop" and .parsed is always populated.
print("sample finish reasons:", [c.finish_reason for c in result.choices[1:]])
print("sample contents start with valid JSON:",
      [(c.message.content or "")[:9] for c in result.choices[1:]])
print("parsed consensus:", consensus_user)
print("parsed originals:", original_users)
print("field likelihoods:", result.likelihoods)


# ---------------------------------------------------------------------------
# Async usage — same engine underneath; concurrent requests coalesce into one
# batched decode through the scheduler instead of racing the device.
# ---------------------------------------------------------------------------
async def main():
    aclient = AsyncKLLMs(backend=client.backend)  # share the loaded engine
    out = await aclient.chat.completions.create(
        model="tiny",
        messages=[{"role": "user", "content": "Hello!"}],
        n=3,
        seed=3,
    )
    print("async consensus:", out.choices[0].message.content[:60])


asyncio.run(main())
