"""Headline benchmark (driver-run, real TPU).

Measures the BASELINE.md target: n=32 consensus p50 latency vs single-sample
p50 on a ~1B-param Llama-architecture model, end-to-end through the public
``KLLMs(backend="tpu")`` client (batched decode + on-device embeddings +
host-side consensus), plus decode tokens/sec/chip.

Prints ONE JSON line:
  metric = n32_consensus_p50_over_single_p50 (lower is better, target < 2.0)
  vs_baseline = 2.0 / value  (>1.0 means the target is beaten)
"""

import json
import statistics
import time

import jax

RUNS = 5
MAX_NEW = 64
N_CONSENSUS = 32


def main() -> None:
    from k_llms_tpu import KLLMs
    from k_llms_tpu.backends.tpu import TpuBackend

    model = "llama-1b-byte"
    # int8 weight-only quantization is the flagship serving config: ~1.4x decode
    # speedup on v5e (HBM-bandwidth-bound decode reads half the bytes).
    backend = TpuBackend(model=model, max_new_tokens=MAX_NEW, quantization="int8")
    client = KLLMs(backend=backend, model=model)

    messages = [
        {
            "role": "user",
            "content": (
                "Extract the invoice fields from this document: ACME Corp, "
                "invoice number INV-2024-00417, issued March 3rd, total due "
                "$4,310.55, payment terms net 30, contact billing@acme.example."
            ),
        }
    ]

    def run(n: int) -> float:
        t0 = time.perf_counter()
        client.chat.completions.create(
            messages=messages, model=model, n=n, temperature=0.8, top_p=0.95, seed=1234
        )
        return time.perf_counter() - t0

    # Warmup / compile both programs.
    run(1)
    run(N_CONSENSUS)

    single = [run(1) for _ in range(RUNS)]
    consensus = [run(N_CONSENSUS) for _ in range(RUNS)]
    p50_single = statistics.median(single)
    p50_consensus = statistics.median(consensus)
    ratio = p50_consensus / p50_single

    # Raw decode throughput (engine-level, excludes host consensus).
    tok = backend.tokenizer
    ids = tok.apply_chat_template(messages)
    backend.engine.generate(ids, n=N_CONSENSUS, max_new_tokens=MAX_NEW, seed=0)
    t0 = time.perf_counter()
    result = backend.engine.generate(ids, n=N_CONSENSUS, max_new_tokens=MAX_NEW, seed=7)
    decode_s = time.perf_counter() - t0
    tokens_generated = int(result.lengths.sum())
    tokens_per_sec_chip = tokens_generated / decode_s / max(1, len(jax.devices()))

    print(
        json.dumps(
            {
                "metric": "n32_consensus_p50_over_single_p50",
                "value": round(ratio, 4),
                "unit": "x",
                "vs_baseline": round(2.0 / ratio, 4),
                "detail": {
                    "model": model,
                    "quantization": "int8",
                    "device": str(jax.devices()[0]),
                    "p50_single_s": round(p50_single, 4),
                    "p50_n32_consensus_s": round(p50_consensus, 4),
                    "decode_tokens_per_sec_chip": round(tokens_per_sec_chip, 1),
                    "max_new_tokens": MAX_NEW,
                    "runs": RUNS,
                },
            }
        )
    )


if __name__ == "__main__":
    main()
