"""Headline benchmark (driver-run, real TPU).

Measures the BASELINE.md target on the FLAGSHIP configuration: Llama-3-8B
shape (synthetic int8 weights — no 8B checkpoint asset ships with this repo),
n=32 consensus p50 latency vs single-sample p50, end-to-end through the public
``KLLMs(backend="tpu")`` client (batched decode + on-device embeddings +
host-side consensus). Also reported, so the numbers are auditable rather than
self-referential:

- decode tokens/sec/chip plus the HBM bytes streamed per decode step and the
  implied bandwidth utilization (decode is HBM-bound; v5e peak is 819 GB/s);
- consensus QUALITY on the scripted noise model (field accuracy of consensus
  vs single sample, the reference's ~0.85 quality bar, README_TESTS.md:212);
- concurrent-request throughput: 5 concurrent clients vs serial (the
  reference's 5-worker baseline, README_TESTS.md:214) via the coalescing
  scheduler.

Prints ONE JSON line:
  metric = n32_consensus_p50_over_single_p50 (lower is better, target < 2.0)
  vs_baseline = 2.0 / value  (>1.0 means the BASELINE.md <2x target is beaten)
"""

import json
import statistics
import threading
import time

import jax

RUNS = 3
MAX_NEW = 64
N_CONSENSUS = 32
FLAGSHIP = "llama-3-8b"
V5E_PEAK_HBM_GBS = 819.0  # public v5e spec: 819 GB/s HBM bandwidth per chip

MESSAGES = [
    {
        "role": "user",
        "content": (
            "Extract the invoice fields from this document: ACME Corp, "
            "invoice number INV-2024-00417, issued March 3rd, total due "
            "$4,310.55, payment terms net 30, contact billing@acme.example."
        ),
    }
]


def _tree_bytes(tree) -> int:
    return sum(x.nbytes for x in jax.tree.leaves(tree))


def _decode_hbm_bytes_per_step(engine, n: int, prompt_len: int, max_new: int) -> int:
    """Bytes a decode step streams from HBM: every non-embedding weight once
    (the embedding table is only gathered for n rows), plus the FULL padded KV
    buckets — the XLA attention reads the whole prefix bucket and the whole
    generated-cache buffer every step, masked positions included."""
    from k_llms_tpu.engine.engine import _bucket

    params = engine.params
    weight_bytes = _tree_bytes(params) - params["embed"].nbytes
    cfg = engine.config
    kv_elem = 2 * 2  # k and v, bf16
    prefix_bucket = min(_bucket(prompt_len, minimum=32), cfg.max_seq_len)
    prefix_bytes = (
        cfg.num_layers * prefix_bucket * cfg.num_kv_heads * cfg.head_dim * kv_elem
    )
    gen_bytes = cfg.num_layers * n * max_new * cfg.num_kv_heads * cfg.head_dim * kv_elem
    return int(weight_bytes + prefix_bytes + gen_bytes)


def bench_flagship() -> "tuple[dict, object, object]":
    """Returns (metrics dict, backend, client) — the backend/client are reused
    by the concurrency section so the 8B engine initializes once."""
    from k_llms_tpu import KLLMs
    from k_llms_tpu.backends.tpu import TpuBackend

    # int8 weight-only quantization is the flagship serving config: decode is
    # HBM-bandwidth bound, int8 halves the streamed bytes, and 8B-class
    # weights (~8.6 GB with bf16 embeddings) fit one 16 GB v5e chip beside
    # the n=32 KV cache.
    backend = TpuBackend(model=FLAGSHIP, max_new_tokens=MAX_NEW, quantization="int8")
    client = KLLMs(backend=backend, model=FLAGSHIP)

    def run(n: int) -> float:
        t0 = time.perf_counter()
        client.chat.completions.create(
            messages=MESSAGES, model=FLAGSHIP, n=n, temperature=0.8, top_p=0.95, seed=1234
        )
        return time.perf_counter() - t0

    # Warmup / compile both programs.
    run(1)
    run(N_CONSENSUS)

    single = [run(1) for _ in range(RUNS)]
    consensus = [run(N_CONSENSUS) for _ in range(RUNS)]
    p50_single = statistics.median(single)
    p50_consensus = statistics.median(consensus)
    ratio = p50_consensus / p50_single

    # Engine-level decode throughput and HBM accounting. Prefill and fixed
    # dispatch overhead are removed by differencing two decode lengths.
    tok = backend.tokenizer
    ids = tok.apply_chat_template(MESSAGES, add_generation_prompt=True)

    def engine_time(max_new: int, seed: int) -> float:
        t0 = time.perf_counter()
        backend.engine.generate(
            ids, n=N_CONSENSUS, max_new_tokens=max_new, temperature=0.8, seed=seed
        )
        return time.perf_counter() - t0

    engine_time(8, seed=0)  # warm both decode-loop compiles
    engine_time(MAX_NEW, seed=0)
    # Median of several differenced pairs: a single host hiccup in one run
    # must not leak an absurd step time into the headline numbers.
    diffs = [
        engine_time(MAX_NEW, seed=7 + i) - engine_time(8, seed=7 + i)
        for i in range(3)
    ]
    step_s = statistics.median(diffs) / (MAX_NEW - 8)
    if step_s <= 0:
        raise RuntimeError(f"non-positive decode step time from diffs {diffs}")
    tokens_per_sec_chip = N_CONSENSUS / step_s / max(1, len(jax.devices()))

    prompt_len = len(ids)
    bytes_per_step = _decode_hbm_bytes_per_step(
        backend.engine, N_CONSENSUS, prompt_len, MAX_NEW
    )
    bandwidth_util = bytes_per_step / step_s / (V5E_PEAK_HBM_GBS * 1e9)

    return {
        "model": FLAGSHIP,
        "quantization": "int8",
        "device": str(jax.devices()[0]),
        "params_bytes": int(_tree_bytes(backend.engine.params)),
        "p50_single_s": round(p50_single, 4),
        "p50_n32_consensus_s": round(p50_consensus, 4),
        "ratio": round(ratio, 4),
        "decode_step_ms": round(step_s * 1000, 3),
        "decode_tokens_per_sec_chip": round(tokens_per_sec_chip, 1),
        "hbm_bytes_per_step": bytes_per_step,
        "hbm_bandwidth_util": round(bandwidth_util, 4),
        "prompt_tokens": prompt_len,
        "max_new_tokens": MAX_NEW,
        "runs": RUNS,
    }, backend, client


def bench_concurrency(backend, client) -> dict:
    """5 concurrent clients vs the same 5 requests serial, n=4 each — the
    coalescing scheduler should fuse the concurrent decodes."""
    N_REQ, N_PER = 5, 4
    prompts = [f"Summarize item {i}: " + MESSAGES[0]["content"] for i in range(N_REQ)]

    def one(i: int):
        return client.chat.completions.create(
            messages=[{"role": "user", "content": prompts[i]}],
            model=FLAGSHIP,
            n=N_PER,
            temperature=0.8,
            seed=500 + i,
        )

    # Warm every program shape a 5-request race can hit: the solo decode and
    # each power-of-two coalesced group size (opportunistic coalescing makes
    # the group composition timing-dependent; generate_many buckets R to
    # powers of two precisely so this warm set is exhaustive).
    from k_llms_tpu.engine.engine import GenRequestSpec

    one(0)
    tok = backend.tokenizer
    warm_ids = tok.apply_chat_template(
        [{"role": "user", "content": prompts[0]}], add_generation_prompt=True
    )
    for r in (2, 4, 8):
        backend.engine.generate_many(
            [GenRequestSpec(warm_ids, N_PER, i) for i in range(r)],
            max_new_tokens=backend.default_max_new_tokens,
            temperature=0.8,
            eos_ids=tok.stop_ids,
        )

    def timed_serial() -> float:
        t0 = time.perf_counter()
        for i in range(N_REQ):
            one(i)
        return time.perf_counter() - t0

    def timed_concurrent() -> float:
        threads = [threading.Thread(target=one, args=(i,)) for i in range(N_REQ)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return time.perf_counter() - t0

    # Two rounds each, best-of (first concurrent round can still catch a
    # straggler composition).
    serial_s = min(timed_serial() for _ in range(2))
    concurrent_s = min(timed_concurrent() for _ in range(2))

    return {
        "requests": N_REQ,
        "n_per_request": N_PER,
        "serial_s": round(serial_s, 4),
        "concurrent_s": round(concurrent_s, 4),
        "speedup": round(serial_s / concurrent_s, 3),
        "scheduler": {
            k: v for k, v in backend.scheduler.stats.items() if k in ("batches", "coalesced")
        },
    }


def main() -> None:
    flagship, backend, client = bench_flagship()
    concurrency = bench_concurrency(backend, client)

    # Host-side consensus quality on the scripted noise model (hermetic).
    from k_llms_tpu.utils.quality import consensus_quality_eval

    quality = consensus_quality_eval()

    ratio = flagship["ratio"]
    print(
        json.dumps(
            {
                "metric": "n32_consensus_p50_over_single_p50",
                "value": ratio,
                "unit": "x",
                "vs_baseline": round(2.0 / ratio, 4),
                "detail": {
                    "flagship": flagship,
                    "concurrency": concurrency,
                    "quality": quality,
                },
            }
        )
    )


if __name__ == "__main__":
    main()
