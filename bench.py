"""Headline benchmark (driver-run, real TPU).

Measures the BASELINE.md target on the FLAGSHIP configuration: Llama-3-8B
shape (synthetic int8 weights — no 8B checkpoint asset ships with this repo),
n=32 consensus p50 latency vs single-sample p50, end-to-end through the public
``KLLMs(backend="tpu")`` client (batched decode + on-device embeddings +
host-side consensus). Also reported, so the numbers are auditable rather than
self-referential:

- decode tokens/sec/chip plus the HBM bytes streamed per decode step and the
  implied bandwidth utilization (decode is HBM-bound; v5e peak is 819 GB/s);
- consensus QUALITY on the scripted noise model (field accuracy of consensus
  vs single sample, the reference's ~0.85 quality bar, README_TESTS.md:212);
- concurrent-request throughput: 5 concurrent clients vs serial (the
  reference's 5-worker baseline, README_TESTS.md:214) via the coalescing
  scheduler.

Prints ONE JSON line:
  metric = n32_consensus_p50_over_single_p50 (lower is better, target < 2.0)
  vs_baseline = 2.0 / value  (>1.0 means the BASELINE.md <2x target is beaten)
"""

import json
import statistics
import subprocess
import sys
import threading
import time

import jax

# Relay-outage hardening (VERDICT r2 #1): the axon TPU relay can die and make
# device init HANG (not error). Device availability is probed in a SUBPROCESS
# with a hard timeout, retried with backoff, and the in-process jax.devices()
# call only happens once a probe has succeeded. Mid-run UNAVAILABLE errors
# retry the whole flagship section. On final failure the one-line JSON is
# still printed, with an explicit "error" field, instead of a traceback.
PROBE_ATTEMPTS = 8
PROBE_TIMEOUT_S = 90
PROBE_BACKOFF_S = 45
RUN_RETRIES = 2

RUNS = 3
MAX_NEW = 64
N_CONSENSUS = 32
FLAGSHIP = "llama-3-8b"
V5E_PEAK_HBM_GBS = 819.0  # public v5e spec: 819 GB/s HBM bandwidth per chip

MESSAGES = [
    {
        "role": "user",
        "content": (
            "Extract the invoice fields from this document: ACME Corp, "
            "invoice number INV-2024-00417, issued March 3rd, total due "
            "$4,310.55, payment terms net 30, contact billing@acme.example."
        ),
    }
]


def _tree_bytes(tree) -> int:
    return sum(x.nbytes for x in jax.tree.leaves(tree))


def _decode_hbm_bytes_per_step(engine, n: int, prompt_len: int, max_new: int) -> int:
    """Bytes a decode step streams from HBM: every non-embedding weight once
    (the embedding table is only gathered for n rows), plus the FULL padded KV
    buckets — the XLA attention reads the whole prefix bucket and the whole
    generated-cache buffer every step, masked positions included."""
    from k_llms_tpu.engine.engine import _bucket

    params = engine.params
    weight_bytes = _tree_bytes(params) - params["embed"].nbytes
    cfg = engine.config
    kv_elem = 2 * 2  # k and v, bf16
    prefix_bucket = min(_bucket(prompt_len, minimum=32), cfg.max_seq_len)
    prefix_bytes = (
        cfg.num_layers * prefix_bucket * cfg.num_kv_heads * cfg.head_dim * kv_elem
    )
    gen_bytes = cfg.num_layers * n * max_new * cfg.num_kv_heads * cfg.head_dim * kv_elem
    return int(weight_bytes + prefix_bytes + gen_bytes)


def _device_probe_ok() -> bool:
    """True once `jax.devices()` completes in a sandboxed subprocess — the
    only safe way to detect a dead relay, which hangs instead of erroring."""
    try:
        proc = subprocess.run(
            [
                sys.executable,
                "-c",
                # Guard against JAX's silent CPU fallback: a refused (rather
                # than hung) relay would otherwise let the bench "pass" the
                # probe and time the 8B flagship on host CPU.
                "import jax; ds = jax.devices(); "
                "assert ds and all(d.platform != 'cpu' for d in ds), ds",
            ],
            timeout=PROBE_TIMEOUT_S,
            capture_output=True,
        )
        return proc.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def wait_for_device() -> None:
    """Bounded retry/backoff until the device relay answers; raises after the
    final attempt so main() can emit the structured-error JSON."""
    for attempt in range(1, PROBE_ATTEMPTS + 1):
        if _device_probe_ok():
            return
        print(
            f"# device probe {attempt}/{PROBE_ATTEMPTS} failed; retrying in {PROBE_BACKOFF_S}s",
            file=sys.stderr,
        )
        if attempt < PROBE_ATTEMPTS:
            time.sleep(PROBE_BACKOFF_S)
    raise RuntimeError(
        f"device unavailable after {PROBE_ATTEMPTS} probe attempts "
        f"({PROBE_TIMEOUT_S}s timeout each)"
    )


def bench_flagship() -> "tuple[dict, object, object]":
    """Returns (metrics dict, backend, client) — the backend/client are reused
    by the concurrency section so the 8B engine initializes once."""
    from k_llms_tpu import KLLMs
    from k_llms_tpu.backends.tpu import TpuBackend

    # int8 weight-only quantization is the flagship serving config: decode is
    # HBM-bandwidth bound, int8 halves the streamed bytes, and 8B-class
    # weights (~8.6 GB with bf16 embeddings) fit one 16 GB v5e chip beside
    # the n=32 KV cache.
    backend = TpuBackend(model=FLAGSHIP, max_new_tokens=MAX_NEW, quantization="int8")
    client = KLLMs(backend=backend, model=FLAGSHIP)

    def run(n: int) -> float:
        t0 = time.perf_counter()
        client.chat.completions.create(
            messages=MESSAGES, model=FLAGSHIP, n=n, temperature=0.8, top_p=0.95, seed=1234
        )
        return time.perf_counter() - t0

    # Warmup / compile both programs.
    run(1)
    run(N_CONSENSUS)

    single = [run(1) for _ in range(RUNS)]
    consensus = [run(N_CONSENSUS) for _ in range(RUNS)]
    p50_single = statistics.median(single)
    p50_consensus = statistics.median(consensus)
    ratio = p50_consensus / p50_single

    # Engine-level decode throughput and HBM accounting. Prefill and fixed
    # dispatch overhead are removed by differencing two decode lengths.
    tok = backend.tokenizer
    ids = tok.apply_chat_template(MESSAGES, add_generation_prompt=True)

    def engine_time(max_new: int, seed: int) -> float:
        t0 = time.perf_counter()
        backend.engine.generate(
            ids, n=N_CONSENSUS, max_new_tokens=max_new, temperature=0.8, seed=seed
        )
        return time.perf_counter() - t0

    engine_time(8, seed=0)  # warm both decode-loop compiles
    engine_time(MAX_NEW, seed=0)
    # Median of several differenced pairs: a single host hiccup in one run
    # must not leak an absurd step time into the headline numbers.
    diffs = [
        engine_time(MAX_NEW, seed=7 + i) - engine_time(8, seed=7 + i)
        for i in range(3)
    ]
    step_s = statistics.median(diffs) / (MAX_NEW - 8)
    if step_s <= 0:
        raise RuntimeError(f"non-positive decode step time from diffs {diffs}")
    tokens_per_sec_chip = N_CONSENSUS / step_s / max(1, len(jax.devices()))

    prompt_len = len(ids)
    bytes_per_step = _decode_hbm_bytes_per_step(
        backend.engine, N_CONSENSUS, prompt_len, MAX_NEW
    )
    bandwidth_util = bytes_per_step / step_s / (V5E_PEAK_HBM_GBS * 1e9)

    return {
        "model": FLAGSHIP,
        "quantization": "int8",
        "device": str(jax.devices()[0]),
        "params_bytes": int(_tree_bytes(backend.engine.params)),
        "p50_single_s": round(p50_single, 4),
        "p50_n32_consensus_s": round(p50_consensus, 4),
        "ratio": round(ratio, 4),
        "decode_step_ms": round(step_s * 1000, 3),
        "decode_tokens_per_sec_chip": round(tokens_per_sec_chip, 1),
        "hbm_bytes_per_step": bytes_per_step,
        "hbm_bandwidth_util": round(bandwidth_util, 4),
        "prompt_tokens": prompt_len,
        "max_new_tokens": MAX_NEW,
        "runs": RUNS,
    }, backend, client


def bench_concurrency(backend, client) -> dict:
    """5 concurrent clients vs the same 5 requests serial, n=4 each — the
    coalescing scheduler should fuse the concurrent decodes."""
    N_REQ, N_PER = 5, 4
    prompts = [f"Summarize item {i}: " + MESSAGES[0]["content"] for i in range(N_REQ)]

    def one(i: int):
        return client.chat.completions.create(
            messages=[{"role": "user", "content": prompts[i]}],
            model=FLAGSHIP,
            n=N_PER,
            temperature=0.8,
            seed=500 + i,
        )

    # Warm every program shape a 5-request race can hit: the solo decode and
    # each power-of-two coalesced group size (opportunistic coalescing makes
    # the group composition timing-dependent; generate_many buckets R to
    # powers of two precisely so this warm set is exhaustive).
    from k_llms_tpu.engine.engine import GenRequestSpec

    one(0)
    tok = backend.tokenizer
    warm_ids = tok.apply_chat_template(
        [{"role": "user", "content": prompts[0]}], add_generation_prompt=True
    )
    for r in (2, 4, 8):
        backend.engine.generate_many(
            [GenRequestSpec(warm_ids, N_PER, i) for i in range(r)],
            max_new_tokens=backend.default_max_new_tokens,
            temperature=0.8,
            eos_ids=tok.stop_ids,
        )

    def timed_serial() -> float:
        t0 = time.perf_counter()
        for i in range(N_REQ):
            one(i)
        return time.perf_counter() - t0

    def timed_concurrent() -> float:
        threads = [threading.Thread(target=one, args=(i,)) for i in range(N_REQ)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return time.perf_counter() - t0

    # Two rounds each, best-of (first concurrent round can still catch a
    # straggler composition).
    serial_s = min(timed_serial() for _ in range(2))
    concurrent_s = min(timed_concurrent() for _ in range(2))

    return {
        "requests": N_REQ,
        "n_per_request": N_PER,
        "serial_s": round(serial_s, 4),
        "concurrent_s": round(concurrent_s, 4),
        "speedup": round(serial_s / concurrent_s, 3),
        "scheduler": {
            k: v for k, v in backend.scheduler.stats.items() if k in ("batches", "coalesced")
        },
    }


def bench_speculative(backend) -> dict:
    """Prompt-copying extraction workload under prompt-lookup speculation —
    the canonical spec-decode win (the continuation copies spans of the
    prompt, so trailing-bigram drafts verify). The spec engine SHARES the
    flagship's initialized int8 params (no second 8B init); spec-off runs the
    plain flagship engine on the identical request."""
    from k_llms_tpu.engine.engine import LocalEngine

    eng_off = backend.engine
    eng_on = LocalEngine(
        eng_off.config, params=eng_off.params, mesh=eng_off.mesh,
        quantize="int8", speculative="prompt_lookup", spec_lookahead=4,
    )
    # Extraction shape: instruction head + a long literal field run the
    # answer must copy. Greedy + logit_bias pins the continuation to the run
    # so the measured acceptance is the workload's, not sampling noise.
    prompt = list(b"Copy the serial field exactly: serial=") + [120] * 96
    kw = dict(
        n=1, max_new_tokens=MAX_NEW, temperature=0.0, seed=3,
        logit_bias={120: 100.0},
    )

    def timed(eng, seed: int) -> float:
        t0 = time.perf_counter()
        eng.generate(prompt, **{**kw, "seed": seed})
        return time.perf_counter() - t0

    timed(eng_on, 0)  # compile
    timed(eng_off, 0)
    p50_on = statistics.median(timed(eng_on, 7 + i) for i in range(RUNS))
    p50_off = statistics.median(timed(eng_off, 7 + i) for i in range(RUNS))
    stats = dict(eng_on.spec_stats)
    return {
        "workload": "prompt-copy extraction (96-token literal run)",
        "prompt_tokens": len(prompt),
        "max_new_tokens": MAX_NEW,
        "spec_lookahead": 4,
        "tokens_per_iteration": stats.get("tokens_per_iteration"),
        "verify_iterations": stats.get("verify_iterations"),
        "drafted": stats.get("drafted"),
        "accepted": stats.get("accepted"),
        "p50_spec_on_s": round(p50_on, 4),
        "p50_spec_off_s": round(p50_off, 4),
        "speedup": round(p50_off / p50_on, 3),
        "runs": RUNS,
    }


def bench_prefix_cache(backend) -> dict:
    """Repeated growing-prompt requests through the prefix cache (the
    multi-turn / shared-system-prompt serving pattern): one miss, then
    suffix-only continuations, then exact-hit repeats. Decode work is
    identical on both engines, so the latency delta IS the prefill time
    saved. An sp_decode long-prompt variant runs when the mesh has a data
    axis to shard over."""
    from k_llms_tpu.engine.engine import LocalEngine

    eng_plain = backend.engine
    cfg = eng_plain.config
    eng_cache = LocalEngine(
        cfg, params=eng_plain.params, mesh=eng_plain.mesh, quantize="int8",
        prefix_cache_size=8, prefix_cache_min_reuse=16,
    )
    base = list(b"System: extract fields faithfully. Document: ")
    grow = [list(b" invoice total $4,310.55 net 30 terms, item %d." % i) for i in range(5)]
    chain = [base]
    for g in grow:
        chain.append(chain[-1] + g)
    requests = chain + [chain[-1]] * 2  # growing chain, then exact repeats
    kw = dict(n=1, max_new_tokens=8, temperature=0.0, seed=5)

    def run_all(eng) -> float:
        t0 = time.perf_counter()
        for p in requests:
            eng.generate(p, **kw)
        return time.perf_counter() - t0

    run_all(eng_cache)  # compile every shape (miss + continuation + hit)
    run_all(eng_plain)
    eng_cache._prefix_entries.clear()
    eng_cache.prefix_cache_stats = {"hits": 0, "partial_hits": 0, "misses": 0}
    p50_cached = statistics.median(
        # Cold cache each round so every pass pays exactly one miss.
        (eng_cache._prefix_entries.clear() or run_all(eng_cache))
        for _ in range(RUNS)
    )
    p50_plain = statistics.median(run_all(eng_plain) for _ in range(RUNS))
    stats = dict(eng_cache.prefix_cache_stats)

    result = {
        "workload": f"growing chain x{len(chain)} + 2 exact repeats",
        "prompt_tokens_final": len(chain[-1]),
        "p50_cached_s": round(p50_cached, 4),
        "p50_plain_s": round(p50_plain, 4),
        "prefill_saved_s": round(p50_plain - p50_cached, 4),
        "speedup": round(p50_plain / p50_cached, 3),
        "cache_stats_total": stats,
        "runs": RUNS,
    }

    mesh = eng_plain.mesh
    if mesh is not None and mesh.shape.get("data", 1) > 1:
        eng_sp = LocalEngine(
            cfg, params=eng_plain.params, mesh=mesh, quantize="int8",
            sp_prefill_min_tokens=256, sp_decode=True,
            prefix_cache_size=4, prefix_cache_min_reuse=64,
        )
        ring = mesh.shape["data"]
        long_prompt = (list(b"Summarize: ") + list(range(32, 96)) * 8)[: 512 // ring * ring]
        sp_kw = dict(n=1, max_new_tokens=8, temperature=0.0, seed=9)
        eng_sp.generate(long_prompt, **sp_kw)  # compile + miss
        t0 = time.perf_counter()
        for _ in range(RUNS):
            eng_sp.generate(long_prompt, **sp_kw)  # exact hits, ring decode
        result["sp_decode_long"] = {
            "prompt_tokens": len(long_prompt),
            "p50_exact_hit_s": round((time.perf_counter() - t0) / RUNS, 4),
            "cache_stats": dict(eng_sp.prefix_cache_stats),
        }
    else:
        result["sp_decode_long"] = {
            "skipped": "mesh data axis <= 1: no sequence axis to shard over"
        }
    return result


def bench_quality() -> dict:
    """Host-side consensus quality on the scripted noise model (hermetic —
    needs no device, so it runs first and survives a relay outage).

    ``default`` is the DEFAULT settings path (VERDICT r3 #3: alignment
    refinement + canonical spelling resolve ON by default — monotone in n and
    above the 0.85 bar at the headline n=32); ``reference_exact`` runs the
    bit-identical-to-reference escape hatch for contrast — it shows the high-n
    row-drop the default posture fixes. Both run n in {8,16,32} over 3
    distinct truth documents (VERDICT r2 #3)."""
    from k_llms_tpu.consensus.settings import ConsensusSettings
    from k_llms_tpu.utils.quality import consensus_quality_eval

    return {
        "default": consensus_quality_eval(n_values=(8, 16, 32), trials=12),
        "reference_exact": consensus_quality_eval(
            n_values=(8, 16, 32), trials=12,
            consensus_settings=ConsensusSettings(reference_exact=True),
        ),
    }


def bench_paged_kv() -> dict:
    """Paged-vs-dense KV capacity at equal HBM budget, plus a live page-pool
    run (hermetic — static accounting needs no device at all, the pool run
    uses the tiny model).

    Headline: how many decode rows fit in the flagship chip's post-params HBM
    under each layout for the n=32 shared-prompt extraction workload. The
    dense layout charges every row the full prompt+max_new KV; the paged
    layout charges each row its private generation reserve plus 1/n of the
    shared prompt pages (``HbmMemoryModel.paged_max_rows``), so width scales
    ~n x on the prompt-dominated shapes. Uses the real 8B int8 param
    footprint via ``jax.eval_shape`` (no weights materialize). The pool run
    decodes an actual n=32 fan-out through the paged continuous loop and
    reports the allocator's own stats — pages in use vs the dense-equivalent
    page count, shared pages, copy-on-write copies — with conservation
    checked by the loop's stats property."""
    import numpy as np

    from k_llms_tpu.backends.tpu import BackendConfig, HbmMemoryModel
    from k_llms_tpu.engine.paging import pages_for
    from k_llms_tpu.models import get_config
    from k_llms_tpu.models.quant import init_params_quantized

    cfg = get_config(FLAGSHIP)
    shapes = jax.eval_shape(
        lambda key: init_params_quantized(cfg, key, bits=8),
        jax.ShapeDtypeStruct((2,), np.uint32),
    )
    param_bytes = sum(
        int(np.prod(leaf.shape)) * np.dtype(leaf.dtype).itemsize
        for leaf in jax.tree.leaves(shapes)
    )
    ps = BackendConfig.model_fields["kv_page_size"].default
    mm = HbmMemoryModel(cfg, param_bytes=param_bytes, hbm_bytes=16 << 30)

    def shape_row(prompt_len: int) -> dict:
        dense = mm.max_rows(prompt_len + MAX_NEW)
        paged = mm.paged_max_rows(prompt_len, MAX_NEW, ps, fanout=N_CONSENSUS)
        return {
            "prompt_len": prompt_len,
            "max_new": MAX_NEW,
            "dense_max_rows": dense,
            "paged_max_rows": paged,
            "width_ratio_x": round(paged / max(1, dense), 2),
        }

    accounting = {
        "model": FLAGSHIP,
        "quantization": "int8",
        "param_bytes": param_bytes,
        "kv_bytes_per_token": mm.kv_bytes_per_token,
        "budget_bytes": mm.budget_bytes(),
        "page_size": ps,
        "fanout": N_CONSENSUS,
        # The repeated-extraction workload (one ~1.4k-token instruction
        # prompt, many documents) is the headline shared-prompt shape; the
        # 200-token flagship prompt is reported for contrast — short prompts
        # are reserve-dominated and amortize less.
        "extraction_1408": shape_row(1408),
        "flagship_200": shape_row(200),
    }

    # Live pool: n=32 greedy fan-out through the paged continuous loop.
    from k_llms_tpu.engine.continuous import ContinuousDecodeLoop
    from k_llms_tpu.engine.engine import LocalEngine
    from k_llms_tpu.models import get_config as _gc
    from k_llms_tpu.models.llama import init_params

    tiny = _gc("tiny")
    engine = LocalEngine(
        tiny,
        params=init_params(tiny, jax.random.PRNGKey(0)),
        use_mesh=False,
        kv_layout="paged",
        kv_page_size=8,
    )
    # 37 tokens = 4 full pages + a partial one, so every row's first
    # generated token lands in the shared partial page and the n-1 losers
    # copy-on-write — the bench exercises (and reports) the CoW path.
    prompt = [(i * 31) % 150 + 3 for i in range(37)]
    max_new = 8
    loop = ContinuousDecodeLoop(engine, width=32, max_prompt=64, max_new=max_new)
    try:
        t0 = time.perf_counter()
        loop.submit(
            prompt, n=32, max_new=max_new, temperature=0.0, top_p=None, seed=11
        ).result(timeout=600)
        elapsed = time.perf_counter() - t0
        snap = dict(loop.stats["pages"])  # runs PageAllocator.verify()
    finally:
        loop.stop()
    dense_equiv = 32 * pages_for(len(prompt) + max_new, 8)
    snap["dense_equivalent_pages"] = dense_equiv
    snap["peak_page_savings_x"] = round(dense_equiv / max(1, snap["peak_in_use"]), 2)
    return {
        "accounting": accounting,
        "pool_run": {
            "n": 32, "prompt_len": len(prompt), "max_new": max_new,
            "page_size": 8, "elapsed_s": round(elapsed, 2), **snap,
        },
    }


def bench_paged_attention() -> dict:
    """Fused paged decode attention vs the materializing step it replaced
    (hermetic, CPU-safe).

    Timed: one paged decode-attention step at n in {8, 32} on the tiny head
    geometry — ``paged_decode_attention_xla`` (the fused op: gather feeds the
    scores directly, this step's fresh column folded in without a pool
    round-trip) vs the PR 7 movement (gather to a dense copy, dense attention
    over the copy, then ``take_along_axis`` to re-extract the written column
    for the pool scatter). p50 over repeated jitted calls.

    Static: the per-step gather traffic BOTH XLA paths materialize — and the
    Pallas kernel's BlockSpec indirection reads in place instead — at the
    real int8 8B footprint, via ``jax.eval_shape`` only (no weights, no
    device): the repeated-extraction prompt shape (1408 tokens, shared by
    each request's fan-out) plus per-row gen slots, every layer, per decode
    step."""
    import functools

    import jax.numpy as jnp
    import numpy as np

    from k_llms_tpu.models import get_config
    from k_llms_tpu.models.llama import (
        _gqa_scores,
        _gqa_scores_shared,
        _gqa_values,
        _gqa_values_shared,
    )
    from k_llms_tpu.ops.attention import gather_kv_pages
    from k_llms_tpu.ops.paged_attention import paged_decode_attention_xla

    cfg = get_config("tiny")
    D, QH, KVH = cfg.head_dim, cfg.num_heads, cfg.num_kv_heads
    sm_scale = 1.0 / float(np.sqrt(D))
    ps, P, G = 8, 64, 32
    rng = np.random.default_rng(17)

    def materializing(
        q, pool_k, pool_v, prefix_idx, gen_idx, new_k, new_v, write_index,
        key_mask, prefix_mask,
    ):
        # The PR 7 step, operation for operation: gather both regions to a
        # dense copy, run the dense attention over the copy, re-extract the
        # written column from the copy for the pool scatter.
        pk, pv = gather_kv_pages(pool_k, pool_v, prefix_idx)
        gk, gv = gather_kv_pages(pool_k, pool_v, gen_idx)
        row_update = jax.vmap(
            lambda c, kk, off: jax.lax.dynamic_update_slice_in_dim(
                c, kk, off, axis=0
            )
        )
        gk = row_update(gk, new_k, write_index)
        gv = row_update(gv, new_v, write_index)
        neg = jnp.finfo(jnp.float32).min
        scores = jnp.where(
            key_mask[:, None, :, :], _gqa_scores(q, gk) * sm_scale, neg
        )
        p_scores = jnp.where(
            prefix_mask[:, None, :, :], _gqa_scores_shared(q, pk) * sm_scale, neg
        )
        w = jax.nn.softmax(jnp.concatenate([p_scores, scores], axis=-1), axis=-1)
        out = _gqa_values_shared(w[..., :P], pv) + _gqa_values(w[..., P:], gv)
        idx = write_index[:, None, None, None]
        return (
            out,
            jnp.take_along_axis(gk, idx, axis=1)[:, 0],
            jnp.take_along_axis(gv, idx, axis=1)[:, 0],
        )

    def timed_row(n: int) -> dict:
        B = n
        npages = P // ps + B * (G // ps) + 1
        flat = npages * ps
        pool_k = jnp.asarray(rng.standard_normal((flat, KVH, D)), jnp.float32)
        pool_v = jnp.asarray(rng.standard_normal((flat, KVH, D)), jnp.float32)
        # One request, n rows sharing its prefix (the consensus fan-out
        # shape): request-level [1, P] prefix table, per-row gen slots.
        prefix_idx = jnp.asarray(
            (np.arange(P) + ps)[None, :], jnp.int32
        )
        gen_pages = (P // ps + 1) + np.arange(B * (G // ps)).reshape(B, G // ps)
        gen_idx = jnp.asarray(
            (gen_pages[:, np.repeat(np.arange(G // ps), ps)] * ps
             + np.tile(np.arange(ps), G // ps)[None, :]),
            jnp.int32,
        )
        q = jnp.asarray(rng.standard_normal((B, 1, QH, D)), jnp.float32)
        new_k = jnp.asarray(rng.standard_normal((B, 1, KVH, D)), jnp.float32)
        new_v = jnp.asarray(rng.standard_normal((B, 1, KVH, D)), jnp.float32)
        glen, plen = G // 2, P - 3
        write_index = jnp.full((B,), glen, jnp.int32)
        key_mask = jnp.broadcast_to(jnp.arange(G) <= glen, (B, 1, G))
        prefix_mask = jnp.broadcast_to(jnp.arange(P) < plen, (B, 1, P))
        args = (
            q, pool_k, pool_v, prefix_idx, gen_idx, new_k, new_v,
            write_index, key_mask, prefix_mask,
        )
        fused = jax.jit(
            functools.partial(paged_decode_attention_xla, sm_scale=sm_scale)
        )
        mat = jax.jit(materializing)

        def p50(fn) -> float:
            jax.block_until_ready(fn(*args))  # compile
            samples = []
            for _ in range(30):
                t0 = time.perf_counter()
                jax.block_until_ready(fn(*args))
                samples.append(time.perf_counter() - t0)
            return statistics.median(samples)

        f, m = p50(fused), p50(mat)
        return {
            "n": n,
            "fused_xla_p50_us": round(f * 1e6, 1),
            "materializing_p50_us": round(m * 1e6, 1),
            "speedup_x": round(m / max(f, 1e-12), 2),
        }

    # Static gather accounting at the 8B int8 deployment shape: what the
    # take_along_axis gather materializes per decode step across all layers.
    from k_llms_tpu.backends.tpu import BackendConfig
    from k_llms_tpu.models.quant import init_params_quantized

    cfg8 = get_config(FLAGSHIP)
    shapes = jax.eval_shape(
        lambda key: init_params_quantized(cfg8, key, bits=8),
        jax.ShapeDtypeStruct((2,), np.uint32),
    )
    param_bytes = sum(
        int(np.prod(leaf.shape)) * np.dtype(leaf.dtype).itemsize
        for leaf in jax.tree.leaves(shapes)
    )
    ps8 = BackendConfig.model_fields["kv_page_size"].default
    prompt_len, gen_bucket = 1408, MAX_NEW
    pool_shape = jax.ShapeDtypeStruct(
        (64 * ps8, cfg8.num_kv_heads, cfg8.head_dim), cfg8.jax_dtype
    )

    def gather_bytes(n: int) -> int:
        outs = jax.eval_shape(
            gather_kv_pages, pool_shape, pool_shape,
            jax.ShapeDtypeStruct((1, prompt_len), np.int32),
        ) + jax.eval_shape(
            gather_kv_pages, pool_shape, pool_shape,
            jax.ShapeDtypeStruct((n, gen_bucket), np.int32),
        )
        per_layer = sum(
            int(np.prod(o.shape)) * np.dtype(o.dtype).itemsize for o in outs
        )
        return per_layer * cfg8.num_layers

    # Coalesced admitted-width accounting: the per-launch row cap
    # generate_many's scheduler hint derives from paged_max_rows (each row
    # charged its gen reserve plus a 1/n share of the shared prompt) vs the
    # dense-layout cap the coalesced path used before it went paged.
    from k_llms_tpu.backends.tpu import HbmMemoryModel

    mm = HbmMemoryModel(cfg8, param_bytes=param_bytes, hbm_bytes=16 << 30)
    dense_rows = mm.max_rows(prompt_len + gen_bucket)

    def width_row(n: int) -> dict:
        paged_rows = mm.paged_max_rows(prompt_len, gen_bucket, ps8, fanout=n)
        return {
            "fanout": n,
            "dense_max_rows": dense_rows,
            "paged_max_rows": paged_rows,
            "width_ratio_x": round(paged_rows / max(1, dense_rows), 2),
        }

    return {
        "timed_tiny": [timed_row(8), timed_row(32)],
        "accounting_8b": {
            "model": FLAGSHIP,
            "quantization": "int8",
            "param_bytes": param_bytes,
            "prompt_len": prompt_len,
            "gen_bucket": gen_bucket,
            "page_size": ps8,
            "gather_bytes_per_step_n8": gather_bytes(8),
            "gather_bytes_per_step_n32": gather_bytes(32),
            "coalesced_width_n8": width_row(8),
            "coalesced_width_n32": width_row(32),
            "note": (
                "bytes the XLA paths materialize per decode step (all "
                "layers, shared [1, P] prefix + per-row gen slots); the "
                "Pallas kernel reads pages in place through its BlockSpec "
                "index_map instead. coalesced_width_*: the paged-vs-dense "
                "per-launch row caps generate_many admits against"
            ),
        },
    }


def bench_host_consensus() -> dict:
    """Host-side consolidation latency at the headline n=32 (hermetic, no
    device): the consensus stage every request pays after decode. Runs cold
    (fresh similarity caches per request — the worst case) and warm (shared
    per-backend scorer, the production configuration)."""
    from k_llms_tpu.consensus.consolidation import consolidate_chat_completions
    from k_llms_tpu.consensus.similarity import SimilarityScorer
    from k_llms_tpu.types import ChatCompletion
    from k_llms_tpu.utils.quality import DEFAULT_TRUTH, make_noisy_samples

    samples = make_noisy_samples(DEFAULT_TRUTH, N_CONSENSUS, 0.15, 7)
    comp = ChatCompletion.model_validate(
        {
            "id": "c", "created": 0, "model": "m", "object": "chat.completion",
            "choices": [
                {
                    "finish_reason": "stop",
                    "index": i,
                    "message": {"role": "assistant", "content": s},
                }
                for i, s in enumerate(samples)
            ],
        }
    )
    shared = SimilarityScorer.levenshtein()
    consolidate_chat_completions(comp, shared)  # warm the shared scorer

    def timed(fresh: bool, reps: int = 15) -> float:
        t0 = time.perf_counter()
        for _ in range(reps):
            scorer = SimilarityScorer.levenshtein() if fresh else shared
            consolidate_chat_completions(comp, scorer)
        return (time.perf_counter() - t0) / reps * 1000.0

    return {
        "n": N_CONSENSUS,
        "cold_ms": round(timed(True), 2),
        "warm_ms": round(timed(False), 2),
    }


def bench_constrained() -> dict:
    """Grammar-constrained vs unconstrained n-way structured extraction
    (hermetic — tiny model on CPU-JAX, the same fused mask ops as chip).

    Headline: at n in {8, 32} every completed constrained sample parses and
    validates into the schema (parse-valid rate 1.0), so the
    retry-on-parse-failure loop an unconstrained deployment needs
    (``would_retry`` failed samples per request) disappears. Also reports the
    compile-cache amortization (one compile across every run), the per-step
    p50 cost of the fused mask+advance against the unmasked step, and the
    off-switch differential: ``constrained_decoding=False`` plus a
    ``response_format`` is byte-identical to no response_format at all."""
    import numpy as np
    from pydantic import BaseModel, Field

    from k_llms_tpu import KLLMs
    from k_llms_tpu.backends.base import ChatRequest
    from k_llms_tpu.backends.tpu import BackendConfig, TpuBackend
    from k_llms_tpu.engine.grammar import (
        clear_grammar_cache,
        grammar_cache_stats,
        grammar_for_schema,
    )
    from k_llms_tpu.utils.observability import GRAMMAR_EVENTS

    class Record(BaseModel):
        name: str = Field(max_length=12)
        count: int

    msgs = [{"role": "user", "content": "extract the record"}]
    clear_grammar_cache()
    out: dict = {"runs": []}
    for constrained in (True, False):
        backend = TpuBackend(
            model="tiny",
            config=BackendConfig(
                model="tiny", max_new_tokens=96,
                constrained_decoding=constrained,
            ),
        )
        client = KLLMs(backend=backend, model="tiny")
        for n in (8, 32):
            before = dict(GRAMMAR_EVENTS.snapshot())
            t0 = time.perf_counter()
            r = client.chat.completions.parse(
                messages=msgs, response_format=Record, model="tiny",
                n=n, seed=100 + n,
            )
            wall = time.perf_counter() - t0
            after = dict(GRAMMAR_EVENTS.snapshot())
            samples = r.choices[1:]
            completed = [c for c in samples if c.finish_reason == "stop"]
            valid = [c for c in completed if c.message.parsed is not None]
            out["runs"].append({
                "constrained": constrained,
                "n": n,
                "completed": len(completed),
                "parse_valid": len(valid),
                "parse_valid_rate": round(len(valid) / max(1, len(completed)), 4),
                # Each completed-but-unparseable sample is a retry an
                # unconstrained deployment would pay; the mask makes it 0.
                "would_retry": len(completed) - len(valid),
                "consensus_parsed": r.choices[0].message.parsed is not None,
                "masked_steps": after.get("grammar.masked_steps", 0)
                - before.get("grammar.masked_steps", 0),
                "wall_s": round(wall, 3),
            })
        client.close()
    out["grammar_cache"] = grammar_cache_stats()

    # Per-step overhead of the fused mask+advance, engine-level (n=8 rows,
    # per executed decode step — constrained rows finish early, so normalize
    # by steps actually run, not tokens emitted).
    backend = TpuBackend(model="tiny", config=BackendConfig(model="tiny"))
    eng, tok = backend.engine, backend.tokenizer
    vocab, vd = backend._grammar_vocab()
    g = grammar_for_schema(Record.model_json_schema(), vocab, vocab_digest=vd)
    ids = tok.apply_chat_template(msgs)

    def step_p50_us(constraint) -> float:
        eng.generate(ids, n=8, max_new_tokens=16, temperature=1.0, seed=0,
                     eos_ids=tok.stop_ids, constraint=constraint)  # compile
        per_step = []
        for rep in range(5):
            t0 = time.perf_counter()
            r = eng.generate(ids, n=8, max_new_tokens=64, temperature=1.0,
                             seed=1 + rep, eos_ids=tok.stop_ids,
                             constraint=constraint)
            steps = max(1, int(np.max(r.lengths)))
            per_step.append((time.perf_counter() - t0) / steps * 1e6)
        return round(statistics.median(per_step), 1)

    unmasked = step_p50_us(None)
    masked = step_p50_us(g)
    out["step_p50_us"] = {
        "unconstrained": unmasked,
        "constrained": masked,
        "overhead_x": round(masked / unmasked, 3) if unmasked else None,
    }

    # Off-switch differential: no mask attached => byte-identical output.
    def texts(cfg_kwargs, req_kwargs):
        b = TpuBackend(
            model="tiny",
            config=BackendConfig(model="tiny", max_new_tokens=24, **cfg_kwargs),
        )
        req = ChatRequest(messages=msgs, model="tiny", n=4, seed=41,
                          temperature=0.9, **req_kwargs)
        r = b.chat_completion(req)
        got = [c.message.content for c in r.choices[1:]]
        b.drain()
        return got

    out["off_switch_byte_identical"] = texts(
        {"constrained_decoding": False},
        {"response_format": {"type": "json_object"}},
    ) == texts({}, {})
    return out


def bench_consensus() -> dict:
    """Host vs device consolidation across n ∈ {8, 32, 128} (hermetic; on CI
    the "device" is CPU-JAX, same kernels as chip). Axes per n: cold (fresh
    scorer per request, empty caches) vs warm (shared scorer, production
    config), and device with the bucket/memo caches disabled — the cache's
    own contribution. Headline: warm device n=32 vs the r05 host baseline
    (15.74 ms), the ISSUE r08 3x target."""
    from k_llms_tpu.consensus.consolidation import consolidate_chat_completions
    from k_llms_tpu.consensus.device import DeviceSimilarityScorer, device_available
    from k_llms_tpu.consensus.similarity import SimilarityScorer
    from k_llms_tpu.types import ChatCompletion
    from k_llms_tpu.utils.quality import DEFAULT_TRUTH, make_noisy_samples

    def make_comp(n: int) -> ChatCompletion:
        samples = make_noisy_samples(DEFAULT_TRUTH, n, 0.15, 7)
        return ChatCompletion.model_validate(
            {
                "id": "c", "created": 0, "model": "m", "object": "chat.completion",
                "choices": [
                    {
                        "finish_reason": "stop",
                        "index": i,
                        "message": {"role": "assistant", "content": s},
                    }
                    for i, s in enumerate(samples)
                ],
            }
        )

    def timed(comp, factory, reps: int) -> float:
        t0 = time.perf_counter()
        for _ in range(reps):
            consolidate_chat_completions(comp, factory())
        return round((time.perf_counter() - t0) / reps * 1000.0, 2)

    def fresh_device(cache: bool):
        s = DeviceSimilarityScorer(method="levenshtein")
        s.cache_enabled = cache
        return s

    out: dict = {"device_available": device_available(), "grid": []}
    for n in (8, 32, 128):
        comp = make_comp(n)
        reps = 15 if n <= 32 else 5
        host_shared = SimilarityScorer.levenshtein()
        consolidate_chat_completions(comp, host_shared)  # warm the shared scorer
        row: dict = {
            "n": n,
            "host_cold_ms": timed(comp, SimilarityScorer.levenshtein, reps),
            "host_warm_ms": timed(comp, lambda: host_shared, reps),
        }
        if out["device_available"]:
            dev_shared = DeviceSimilarityScorer(method="levenshtein")
            consolidate_chat_completions(comp, dev_shared)  # jit + cache warm
            row["device_cold_ms"] = timed(comp, lambda: fresh_device(True), reps)
            row["device_nocache_ms"] = timed(comp, lambda: fresh_device(False), reps)
            row["device_warm_ms"] = timed(comp, lambda: dev_shared, reps)
            row["speedup_warm_x"] = round(row["host_warm_ms"] / row["device_warm_ms"], 2)
        out["grid"].append(row)
    r05_host_warm_n32 = 15.74  # BENCH_r05 detail.host_consensus.warm_ms
    for row in out["grid"]:
        if row["n"] == 32 and "device_warm_ms" in row:
            out["speedup_vs_r05_host_x"] = round(r05_host_warm_n32 / row["device_warm_ms"], 2)
    return out


def bench_serving() -> dict:
    """Hermetic serving workload (PR 6): a loopback HTTP server (stdlib
    runner, ServerThread) over the tiny CPU backend, driven with httpx —
    the serving stack end to end, no device required.

    Two headline numbers:

    - TTFT: p50 time-to-first-SSE-delta for stream=true vs the p50 full
      response latency of the same request non-streamed. Streaming's reason
      to exist is that the first token arrives a decode-step in, not a full
      consensus later.
    - Occupancy under staggered load: the same 6-request trickle (arrivals
      mid-decode of earlier requests) through (a) the continuous in-flight
      slot loop and (b) the coalescing scheduler. Occupancy = useful row-
      steps / (serving width W * sequential device steps): late arrivals can
      JOIN the continuous batch, so its device steps carry more live rows;
      the coalesced path decodes each straggler as its own launch.
    """
    import httpx

    from k_llms_tpu import KLLMs
    from k_llms_tpu.backends.tpu import TpuBackend
    from k_llms_tpu.serving import ServerThread, ServingApp

    # Stagger chosen well inside one request's decode time (tiny model on
    # CPU decodes ~0.1s at 48 tokens), so later arrivals genuinely land
    # mid-decode of earlier ones — the case the slot loop exists for.
    W, N_PER, MAX_TOK = 4, 2, 48
    N_REQ, STAGGER_S = 6, 0.01
    msgs = [{"role": "user", "content": "Stream me a short answer."}]

    def make_client(continuous: bool) -> KLLMs:
        backend = TpuBackend(
            model="tiny", max_new_tokens=MAX_TOK, batch_window=0.0,
            continuous_batching=continuous, continuous_width=W,
            continuous_max_prompt=128, continuous_max_new=64,
        )
        return KLLMs(backend=backend, model="tiny")

    out: dict = {
        "width": W, "requests": N_REQ, "n_per_request": N_PER,
        "max_tokens": MAX_TOK, "stagger_s": STAGGER_S,
    }

    # -- TTFT vs non-stream p50 (continuous backend, loopback socket) ------
    client = make_client(continuous=True)
    with ServerThread(ServingApp(client)) as srv:
        url = srv.base_url + "/v1/chat/completions"

        def body(seed: int, stream: bool) -> dict:
            return {
                "messages": msgs, "model": "tiny", "n": N_PER,
                "max_tokens": MAX_TOK, "temperature": 0.8, "seed": seed,
                "stream": stream,
            }

        httpx.post(url, json=body(0, False), timeout=600)  # warm compiles
        ttfts, fulls = [], []
        for i in range(5):
            t0 = time.perf_counter()
            with httpx.stream("POST", url, json=body(10 + i, True), timeout=600) as r:
                frames = r.iter_raw()
                next(frames, None)
                ttfts.append(time.perf_counter() - t0)
                for _ in frames:
                    pass
            t0 = time.perf_counter()
            httpx.post(url, json=body(10 + i, False), timeout=600)
            fulls.append(time.perf_counter() - t0)
        ttft_p50 = statistics.median(ttfts)
        full_p50 = statistics.median(fulls)
        out["ttft_stream_p50_s"] = round(ttft_p50, 4)
        out["nonstream_p50_s"] = round(full_p50, 4)
        out["ttft_speedup"] = round(full_p50 / ttft_p50, 2)

        # -- staggered occupancy: continuous --------------------------------
        loop = client.backend._continuous
        steps0, rows0 = loop.stats["steps"], loop.stats["row_steps"]

        def fire(seed: int) -> None:
            httpx.post(url, json=body(seed, False), timeout=600)

        threads = [
            threading.Thread(target=fire, args=(100 + i,)) for i in range(N_REQ)
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
            time.sleep(STAGGER_S)
        for t in threads:
            t.join()
        cont_makespan = time.perf_counter() - t0
        steps = loop.stats["steps"] - steps0
        row_steps = loop.stats["row_steps"] - rows0
        out["continuous"] = {
            "occupancy": round(row_steps / max(1, steps * W), 4),
            "device_steps": steps,
            "row_steps": row_steps,
            "joined_in_flight": loop.stats["joined_in_flight"],
            "makespan_s": round(cont_makespan, 4),
        }
    client.backend.close()

    # -- staggered occupancy: coalesced baseline ---------------------------
    client2 = make_client(continuous=False)
    engine2 = client2.backend.engine
    launches: list = []
    orig_many = engine2.generate_many

    def counted_many(specs, **kw):
        # One entry per LAUNCH (a coalesced group decodes together, so its
        # device steps are the longest member's, not the sum).
        results = orig_many(specs, **kw)
        lens = [
            int(x)
            for res in results
            if res is not None and getattr(res, "lengths", None) is not None
            for x in res.lengths
        ]
        if lens:
            launches.append((sum(lens), max(lens)))
        return results

    engine2.generate_many = counted_many
    with ServerThread(ServingApp(client2)) as srv2:
        url2 = srv2.base_url + "/v1/chat/completions"
        httpx.post(
            url2,
            json={"messages": msgs, "model": "tiny", "n": N_PER,
                  "max_tokens": MAX_TOK, "temperature": 0.8, "seed": 0},
            timeout=600,
        )  # warm
        launches.clear()

        def fire2(seed: int) -> None:
            httpx.post(
                url2,
                json={"messages": msgs, "model": "tiny", "n": N_PER,
                      "max_tokens": MAX_TOK, "temperature": 0.8, "seed": seed},
                timeout=600,
            )

        threads = [
            threading.Thread(target=fire2, args=(100 + i,)) for i in range(N_REQ)
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
            time.sleep(STAGGER_S)
        for t in threads:
            t.join()
        coal_makespan = time.perf_counter() - t0
    client2.backend.close()
    # Sequential device steps at serving width W: each launch runs
    # max(lengths) steps with its own (small) row count; useful row-steps are
    # the tokens actually produced.
    useful = sum(tokens for tokens, _ in launches)
    total_steps = sum(steps for _, steps in launches)
    out["coalesced"] = {
        "occupancy": round(useful / max(1, total_steps * W), 4),
        "launches": len(launches),
        "device_steps": total_steps,
        "makespan_s": round(coal_makespan, 4),
    }
    out["occupancy_gain"] = round(
        out["continuous"]["occupancy"] / max(1e-9, out["coalesced"]["occupancy"]), 3
    )
    return out


def bench_hedging() -> dict:
    """Tail-latency rescue via replica hedging (hermetic — FakeBackend
    members, no device): a 2-member replica set where one member is made slow
    through the keyed ``replica.dispatch`` sleep failpoint. Round-robin
    routing pins half the primaries onto the slow member (health routing
    would learn to avoid it and hide the effect), so with hedging OFF the
    p99 — and here even the p50 — carries the injected stall, while with
    hedging ON the duplicate dispatch on the healthy member rescues the tail
    at roughly the hedge delay."""
    from k_llms_tpu.backends.base import ChatRequest
    from k_llms_tpu.backends.fake import FakeBackend
    from k_llms_tpu.reliability import failpoints as fp
    from k_llms_tpu.reliability.failpoints import FailSpec
    from k_llms_tpu.reliability.replicas import ReplicaSet

    slow_s, hedge_delay_s, requests = 0.060, 0.015, 40

    def quantile(xs: list, q: float) -> float:
        ordered = sorted(xs)
        return ordered[min(len(ordered) - 1, int(q * (len(ordered) - 1)))]

    def run(hedge: bool) -> dict:
        rs = ReplicaSet(
            members=[FakeBackend(["hedged"]), FakeBackend(["hedged"])],
            model="fake",
            hedge=hedge,
            hedge_delay_s=hedge_delay_s,
            route_policy="round_robin",
        )
        request = ChatRequest(
            messages=[{"role": "user", "content": "bench"}], model="fake"
        )
        latencies = []
        with fp.failpoints(
            {"replica.dispatch": FailSpec(action="sleep", member="r1", delay=slow_s)}
        ):
            for _ in range(requests):
                t0 = time.perf_counter()
                rs.dispatch_chat_completion(request)
                latencies.append((time.perf_counter() - t0) * 1000.0)
        stats = rs.stats()
        rs._executor.shutdown(wait=False)
        return {
            "p50_ms": round(quantile(latencies, 0.50), 2),
            "p99_ms": round(quantile(latencies, 0.99), 2),
            "hedges_won": sum(s["hedges_won"] for s in stats.values()),
        }

    off, on = run(False), run(True)
    return {
        "requests": requests,
        "slow_member_stall_ms": slow_s * 1000.0,
        "hedge_delay_ms": hedge_delay_s * 1000.0,
        "hedging_off": off,
        "hedging_on": on,
        "p99_speedup_x": round(off["p99_ms"] / max(on["p99_ms"], 1e-6), 2),
    }


def bench_tenancy() -> dict:
    """Weighted-fair isolation under skewed offered load (ISSUE 16,
    hermetic — EngineScheduler directly, no device): two equal-weight
    tenants, one offering 10x the other's load, every item pre-queued
    behind a blocked worker so the dequeue ORDER is pure scheduler policy.
    The acceptance number: at the instant the light tenant's last item is
    served, the heavy tenant must have been served a near-equal share —
    equal weights mean equal goodput, regardless of the 10:1 backlog skew.
    A FIFO queue would score ~10:1 here (the light tenant starves behind
    the flood); WFQ alternates and scores ~1:1."""
    import threading

    from k_llms_tpu.engine.scheduler import EngineScheduler
    from k_llms_tpu.reliability.tenancy import TenancyConfig

    heavy_n, light_n = 1000, 100
    tenancy = TenancyConfig.from_options(
        tenants={"heavy": {"weight": 1.0}, "light": {"weight": 1.0}}
    )
    sched = EngineScheduler(
        name="bench-tenancy", batch_window=0.0, tenancy=tenancy
    )
    served = {"heavy": 0, "light": 0}
    heavy_at_light_done = [0]
    gate = threading.Event()
    blocker = sched.submit(gate.wait)
    while not (sched.stats["queued"] == 0 and blocker.running()):
        time.sleep(0.005)

    def make_fn(tenant: str, last_light: bool):
        def fn(payloads):
            served[tenant] += len(payloads)
            if last_light:
                heavy_at_light_done[0] = served["heavy"]
            return list(payloads)

        return fn

    futures = []
    # Heavy floods FIRST: with FIFO dequeue the light tenant would wait out
    # the full 10x backlog before its first item moves.
    for i in range(heavy_n):
        futures.append(sched.submit_batched(
            ("heavy", i), i, make_fn("heavy", False), weight=1, tenant="heavy"
        ))
    for i in range(light_n):
        futures.append(sched.submit_batched(
            ("light", i), i, make_fn("light", i == light_n - 1),
            weight=1, tenant="light",
        ))
    t0 = time.perf_counter()
    gate.set()
    for f in futures:
        f.result(timeout=120)
    drain_s = time.perf_counter() - t0
    blocker.result(timeout=10)
    health = sched.health()
    sched.shutdown()

    # Goodput split while BOTH tenants were backlogged: served counts at the
    # moment the light tenant finished. Equal weights -> ratio ~1.0.
    heavy_share = heavy_at_light_done[0]
    ratio = heavy_share / max(1, light_n)
    return {
        "offered": {"heavy": heavy_n, "light": light_n},
        "weights": {"heavy": 1.0, "light": 1.0},
        "heavy_served_at_light_done": heavy_share,
        "light_served": light_n,
        "goodput_ratio_heavy_over_light": round(ratio, 3),
        "within_10pct_of_weights": bool(abs(ratio - 1.0) <= 0.10),
        "drain_s": round(drain_s, 3),
        "served_per_tenant": {
            t: health["tenants"][t]["served"] for t in ("heavy", "light")
        },
    }


def bench_batch_lane() -> dict:
    """Durable offline batch lane (ISSUE 17, hermetic — FakeBackend with a
    fixed per-call service time, no device): (a) throughput — a 64-item
    durable batch job drained by the lane's bounded worker pool vs the same
    64 bodies executed foreground one at a time; the lane overlaps
    ``max_in_flight`` items so wall time divides by ~the pool width minus
    the per-item durable-commit fsyncs, while every output lands exactly
    once through the crash-safe store; (b)
    isolation — interactive p50/p99 client latency with the lane off vs
    grinding a second 64-item job; the pool is bounded, so foreground calls
    on the same client stay flat instead of queueing behind the backlog."""
    import shutil
    import tempfile

    from k_llms_tpu import KLLMs
    from k_llms_tpu.backends.fake import FakeBackend
    from k_llms_tpu.reliability.jobstore import JobStore
    from k_llms_tpu.serving.batch import BatchLane

    work_s, items, in_flight, interactive_n = 0.008, 64, 4, 40

    def quantile(xs: list, q: float) -> float:
        ordered = sorted(xs)
        return ordered[min(len(ordered) - 1, int(q * (len(ordered) - 1)))]

    client = KLLMs(backend=FakeBackend(), model="fake-model")
    real_create = client.chat.completions.create

    def timed_create(*args, **kwargs):
        time.sleep(work_s)  # fixed per-item service time: makes overlap visible
        return real_create(*args, **kwargs)

    client.chat.completions.create = timed_create

    def job_body(tag: str) -> bytes:
        return "\n".join(
            json.dumps({"custom_id": f"{tag}-{i}", "body": {
                "messages": [{"role": "user", "content": f"{tag} {i}"}],
                "n": 1, "seed": 1000 + i,
            }})
            for i in range(items)
        ).encode()

    def interactive() -> list:
        lats = []
        for i in range(interactive_n):
            t0 = time.perf_counter()
            client.chat.completions.create(
                messages=[{"role": "user", "content": f"interactive {i}"}],
                model="fake-model", n=1, seed=5000 + i,
            )
            lats.append((time.perf_counter() - t0) * 1000.0)
        return lats

    # (a) Foreground baseline: the same 64 bodies, strictly sequential.
    t0 = time.perf_counter()
    for i in range(items):
        client.chat.completions.create(
            messages=[{"role": "user", "content": f"foreground {i}"}],
            model="fake-model", n=1, seed=1000 + i,
        )
    foreground_s = time.perf_counter() - t0

    root = tempfile.mkdtemp(prefix="kllms-bench-batch-")
    lane = BatchLane(client, JobStore(root), max_in_flight=in_flight)
    try:
        t0 = time.perf_counter()
        wire = lane.submit(job_body("lane"), tenant="bench")
        assert lane.wait_idle(120.0), lane.health()
        lane_s = time.perf_counter() - t0
        final = lane.job_wire(wire["id"])
        records = [
            json.loads(l)
            for l in lane.output_bytes(wire["id"]).decode().splitlines()
        ]
        assert final["status"] == "completed", final
        assert len({r["id"] for r in records}) == items, "duplicate outputs"

        # (b) Interactive latency with the lane quiet, then grinding.
        lat_off = interactive()
        lane.submit(job_body("grind"), tenant="bench")
        lat_on = interactive()
        assert lane.wait_idle(120.0), lane.health()
        lane.drain(timeout=10.0)
    finally:
        lane.close()
        shutil.rmtree(root, ignore_errors=True)

    p99_off = quantile(lat_off, 0.99)
    p99_on = quantile(lat_on, 0.99)
    return {
        "items": items,
        "max_in_flight": in_flight,
        "service_time_ms": work_s * 1000.0,
        "foreground_s": round(foreground_s, 3),
        "lane_s": round(lane_s, 3),
        "lane_speedup_x": round(foreground_s / max(lane_s, 1e-6), 2),
        "outputs_exactly_once": len({r["id"] for r in records}) == items,
        "interactive": {
            "requests": interactive_n,
            "lane_off": {
                "p50_ms": round(quantile(lat_off, 0.50), 2),
                "p99_ms": round(p99_off, 2),
            },
            "lane_on": {
                "p50_ms": round(quantile(lat_on, 0.50), 2),
                "p99_ms": round(p99_on, 2),
            },
            "p99_ratio_on_over_off": round(p99_on / max(p99_off, 1e-6), 2),
        },
    }


def bench_chunked_prefill() -> dict:
    """Chunked prefill (ISSUE 18, hermetic — tiny model, dense continuous
    loop): the ISSUE's trickle-plus-whale workload. An in-flight row streams
    tokens continuously while one 1408-token admission lands; with chunking
    OFF the whole-prompt prefill runs between two decode steps, so the row's
    inter-token gap spikes by the full prefill and a short request submitted
    behind the whale waits just as long for its first token. With chunking ON
    (the HbmMemoryModel auto size for this shape) one chunk rides between
    decode steps: the max gap stays within a small multiple of the steady
    p50, the short request admits after at most one chunk, and the whale's
    own output tokens are byte-identical to the monolithic path (the
    differential tests/test_chunked_prefill.py pins). The whale's TTFT is
    the price paid, reported honestly."""
    import numpy as np

    from k_llms_tpu.backends.tpu import HbmMemoryModel
    from k_llms_tpu.engine.continuous import ContinuousDecodeLoop
    from k_llms_tpu.engine.engine import LocalEngine
    from k_llms_tpu.models import get_config
    from k_llms_tpu.models.llama import init_params
    from k_llms_tpu.utils.observability import LATENCY

    tiny = get_config("tiny")
    engine = LocalEngine(
        tiny, params=init_params(tiny, jax.random.PRNGKey(0)), use_mesh=False
    )
    width, max_prompt, max_new = 4, 2048, 256
    long_prompt = [(i * 17) % 150 + 3 for i in range(1408)]
    short_prompt = [(i * 13) % 150 + 3 for i in range(12)]
    auto_chunk = HbmMemoryModel(tiny, param_bytes=1 << 20).prefill_chunk_tokens(
        width, max_prompt
    )

    def quantile(xs: list, q: float) -> float:
        ordered = sorted(xs)
        return ordered[min(len(ordered) - 1, int(q * (len(ordered) - 1)))]

    def step_hist() -> "list[tuple[float, int]]":
        return list(
            LATENCY.snapshot().get("continuous.step", {}).get("buckets", [])
        )

    def hist_bound(before, after, q: float) -> "float | None":
        """Smallest bucket bound covering quantile q of the continuous.step
        observations made between the two snapshots (cumulative counts)."""
        delta = [
            (le, b - a)
            for (le, b), (_, a) in zip(after, before or [(0.0, 0)] * len(after))
        ]
        total = delta[-1][1] if delta else 0
        if total <= 0:
            return None
        need = max(1, int(q * total))
        for le, cum in delta:
            if cum >= need:
                return le
        return None

    def run(chunk_tokens: int) -> "tuple[dict, object]":
        loop = ContinuousDecodeLoop(
            engine, width=width, max_prompt=max_prompt, max_new=max_new,
            prefill_chunk_tokens=chunk_tokens,
        )
        try:
            # Warm every program (decode at the 2048 bucket, whole prefill,
            # chunk step): compile time must not masquerade as stall.
            loop.submit(
                list(long_prompt), n=1, max_new=4, temperature=0.0,
                top_p=None, seed=1,
            ).result(timeout=900)
            stamps: list = []
            h_start = step_hist()
            inflight = loop.submit(
                [5, 9, 23], n=1, max_new=max_new - 8, temperature=0.6,
                top_p=0.9, seed=7,
                token_sink=lambda s, t: stamps.append(time.perf_counter()),
            )
            while len(stamps) < 48:  # establish a steady decode cadence
                time.sleep(0.002)
            long_first: list = []
            h_mid = step_hist()
            t_long = time.perf_counter()
            long_fut = loop.submit(
                list(long_prompt), n=1, max_new=8, temperature=0.0,
                top_p=None, seed=3,
                token_sink=lambda s, t: (
                    long_first.append(time.perf_counter())
                    if not long_first else None
                ),
            )
            # The trickle request stuck behind the whale: its TTFT is the
            # headline admission-latency number.
            short_first: list = []
            t_short = time.perf_counter()
            short_fut = loop.submit(
                list(short_prompt), n=1, max_new=4, temperature=0.0,
                top_p=None, seed=5,
                token_sink=lambda s, t: (
                    short_first.append(time.perf_counter())
                    if not short_first else None
                ),
            )
            long_res = long_fut.result(timeout=900)
            h_end = step_hist()
            short_fut.result(timeout=900)
            inflight.result(timeout=900)
            chunks = dict(loop.stats)["prefill_chunks"]
        finally:
            loop.stop()
        # Skip the first few post-admission gaps: the row's own warm-in
        # (sink registration, first-step bookkeeping) is not steady cadence.
        gaps = list(zip(stamps[8:], stamps[9:]))
        steady = [b - a for a, b in gaps if b <= t_long]
        stall = [
            b - a for a, b in gaps if b > t_long and a < long_first[0]
        ]
        steady_p50 = quantile(steady, 0.5)
        max_stall = max(stall) if stall else None
        # The acceptance metric verbatim: the ``continuous.step`` histogram
        # (decode dispatch only — the interleaved chunk times into its own
        # ``continuous.prefill_chunk`` family), steady p50 bucket vs the max
        # bucket observed while the whale ingests.
        step_p50_le = hist_bound(h_start, h_mid, 0.5)
        step_max_le = hist_bound(h_mid, h_end, 1.0)
        return {
            "prefill_chunk_tokens": chunk_tokens,
            "prefill_chunks": chunks,
            "steady_step_p50_ms": round(steady_p50 * 1000.0, 3),
            "max_gap_during_admission_ms": (
                round(max_stall * 1000.0, 3) if max_stall is not None else None
            ),
            "stall_over_steady_p50_x": (
                round(max_stall / max(steady_p50, 1e-9), 2)
                if max_stall is not None else None
            ),
            "short_ttft_ms": round((short_first[0] - t_short) * 1000.0, 3),
            "long_ttft_ms": round((long_first[0] - t_long) * 1000.0, 3),
            "step_hist_steady_p50_le_ms": (
                round(step_p50_le * 1000.0, 1) if step_p50_le else None
            ),
            "step_hist_admission_max_le_ms": (
                round(step_max_le * 1000.0, 1) if step_max_le else None
            ),
            "step_max_within_3x_p50": (
                step_max_le <= 3.0 * step_p50_le
                if step_p50_le and step_max_le else None
            ),
        }, long_res.tokens

    off, off_tokens = run(0)
    on, on_tokens = run(auto_chunk)
    return {
        "model": "tiny",
        "layout": "dense",
        "width": width,
        "max_prompt": max_prompt,
        "long_prompt_tokens": len(long_prompt),
        "auto_chunk_tokens": auto_chunk,
        "off": off,
        "on": on,
        "long_output_identical": bool(np.array_equal(off_tokens, on_tokens)),
        "short_ttft_speedup_x": round(
            off["short_ttft_ms"] / max(on["short_ttft_ms"], 1e-6), 2
        ),
    }


def _emit(value, vs_baseline, detail: dict, error: "str | None" = None) -> None:
    line = {
        "metric": "n32_consensus_p50_over_single_p50",
        "value": value,
        "unit": "x",
        "vs_baseline": vs_baseline,
        "detail": detail,
    }
    if error is not None:
        line["error"] = error
    print(json.dumps(line))


def main() -> None:
    detail: dict = {}
    try:
        detail["quality"] = bench_quality()
    except Exception as exc:  # quality is hermetic; a failure here is a bug
        detail["quality"] = {"error": f"{type(exc).__name__}: {exc}"[:300]}
    try:
        detail["host_consensus"] = bench_host_consensus()
    except Exception as exc:
        detail["host_consensus"] = {"error": f"{type(exc).__name__}: {exc}"[:300]}
    try:
        detail["consensus"] = bench_consensus()
    except Exception as exc:  # hermetic like quality; a failure here is a bug
        detail["consensus"] = {"error": f"{type(exc).__name__}: {exc}"[:300]}
    try:
        detail["constrained"] = bench_constrained()
    except Exception as exc:  # hermetic like quality; a failure here is a bug
        detail["constrained"] = {"error": f"{type(exc).__name__}: {exc}"[:300]}
    try:
        detail["paged_kv"] = bench_paged_kv()
    except Exception as exc:  # hermetic like quality; a failure here is a bug
        detail["paged_kv"] = {"error": f"{type(exc).__name__}: {exc}"[:300]}
    try:
        detail["paged_attention"] = bench_paged_attention()
    except Exception as exc:  # hermetic like quality; a failure here is a bug
        detail["paged_attention"] = {"error": f"{type(exc).__name__}: {exc}"[:300]}
    try:
        detail["hedging"] = bench_hedging()
    except Exception as exc:  # hermetic like quality; a failure here is a bug
        detail["hedging"] = {"error": f"{type(exc).__name__}: {exc}"[:300]}
    try:
        detail["tenancy"] = bench_tenancy()
    except Exception as exc:  # hermetic like quality; a failure here is a bug
        detail["tenancy"] = {"error": f"{type(exc).__name__}: {exc}"[:300]}
    try:
        detail["batch_lane"] = bench_batch_lane()
    except Exception as exc:  # hermetic like quality; a failure here is a bug
        detail["batch_lane"] = {"error": f"{type(exc).__name__}: {exc}"[:300]}
    try:
        detail["chunked_prefill"] = bench_chunked_prefill()
    except Exception as exc:  # hermetic like quality; a failure here is a bug
        detail["chunked_prefill"] = {"error": f"{type(exc).__name__}: {exc}"[:300]}
    try:
        detail["serving"] = bench_serving()
    except Exception as exc:  # hermetic like quality; a failure here is a bug
        detail["serving"] = {"error": f"{type(exc).__name__}: {exc}"[:300]}

    last_error = None
    for attempt in range(1, RUN_RETRIES + 2):
        try:
            wait_for_device()
        except Exception as exc:
            # Probe exhaustion: report it only if no real run error was seen.
            last_error = last_error or f"{type(exc).__name__}: {exc}"[:500]
            break
        try:
            flagship, backend, client = bench_flagship()
            detail["flagship"] = flagship
            detail["concurrency"] = bench_concurrency(backend, client)
            try:
                detail["speculative"] = bench_speculative(backend)
            except Exception as exc:
                detail["speculative"] = {"error": f"{type(exc).__name__}: {exc}"[:300]}
            try:
                detail["prefix_cache"] = bench_prefix_cache(backend)
            except Exception as exc:
                detail["prefix_cache"] = {"error": f"{type(exc).__name__}: {exc}"[:300]}
            ratio = flagship["ratio"]
            _emit(ratio, round(2.0 / ratio, 4), detail)
            return
        except Exception as exc:
            last_error = f"{type(exc).__name__}: {exc}"[:500]
            print(
                f"# flagship attempt {attempt}/{RUN_RETRIES + 1} failed: {last_error}",
                file=sys.stderr,
            )
            if "UNAVAILABLE" not in last_error and "unavailable" not in last_error:
                break  # a genuine bug — retrying (and re-probing) would only mask it

    _emit(None, None, detail, error=last_error)
    sys.exit(1)


if __name__ == "__main__":
    main()
