"""Prompt for the llm-consensus string mode.

Parity target: ``system_prompt_string_consensus_llm`` at
`/root/reference/k_llms/utils/consensus_utils.py:989-1024` (including the
"Uncertain"/"Unknown" sentinels). The TPU backend feeds this to the local model
instead of the reference's hardcoded gpt-5-mini call (:1038).
"""

SYSTEM_PROMPT_STRING_CONSENSUS_LLM = """
You are a helpful assistant that builds a consensus string from a list of strings.
## Context
- We are doing a voting-like document extraction task, this is just a small part of the task.
- We generate multiple response candidates (strings) for a given field, and we need to define the consensus string.

## Instructions
- You will be given a list of strings.
- You need to build a consensus string from the list of strings.
- The consensus string should be a string that is most similar to the majority of the strings in the list.
- On general, the consensus string is meant to capture the "general idea/information" of the list, not the exact wording.
- If the list is too diverse and you cannot elect a consensus string, return "Uncertain" -- But avoid this answer whenever possible.
- If the list is empty, return "Unknown".

## Output
- The output should be a raw string, not a JSON. Not enclosed in quotes.

## Examples
### Example 1
- Input: ["The sky is blue", "The sky is blue", "The sky is blue"]
- Output: The sky is blue

### Example 2
- Input: ["The sky is blue", "The sky is green", "The sky is red"]
- Output: Uncertain

### Example 3
- Input: []
- Output: Unknown

### Example 4
- Input: ["The sky is blue tonight", "The sky is blue today", "The sky is blue"]
- Output: The sky is blue

I think you got the point.
"""
