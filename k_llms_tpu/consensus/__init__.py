"""Host-side k-way consensus engine.

Behavioral-parity reimplementation of the reference consensus stack
(`/root/reference/k_llms/utils/consensus_utils.py`, `majority_sorting.py`,
`consolidation.py`) restructured for a local TPU backend:

- one sync core engine (the reference's 750-line async mirror collapses into thin
  adapters — device work is launched once and is internally parallel);
- similarity is provided by a pluggable :class:`SimilarityScorer` instead of a
  hardwired OpenAI-embeddings callback, so the TPU backend can plug in on-device
  embeddings and a local llm-consensus model;
- the scalar hot loops (Levenshtein, Hungarian assignment) call into native C++
  (``k_llms_tpu.native``) with pure-Python fallbacks.
"""

from .settings import ConsensusSettings, SIMILARITY_SCORE_LOWER_BOUND
from .similarity import SimilarityScorer
from .voting import voting_consensus, sanitize_value
from .primitive import compute_similarity_scores, consensus_as_primitive
from .majority import sort_by_original_majority
from .alignment import lists_alignment
from .recursion import (
    consensus_dict,
    consensus_list,
    consensus_values,
    intermediary_consensus_cleanup,
    recursive_list_alignments,
)
from .consolidation import (
    consolidate_chat_completions,
    consolidate_parsed_chat_completions,
    async_consolidate_chat_completions,
    async_consolidate_parsed_chat_completions,
)
from .usage import consolidate_consensus_usage

__all__ = [
    "ConsensusSettings",
    "SIMILARITY_SCORE_LOWER_BOUND",
    "SimilarityScorer",
    "voting_consensus",
    "sanitize_value",
    "compute_similarity_scores",
    "consensus_as_primitive",
    "intermediary_consensus_cleanup",
    "sort_by_original_majority",
    "lists_alignment",
    "consensus_dict",
    "consensus_list",
    "consensus_values",
    "recursive_list_alignments",
    "consolidate_chat_completions",
    "consolidate_parsed_chat_completions",
    "async_consolidate_chat_completions",
    "async_consolidate_parsed_chat_completions",
    "consolidate_consensus_usage",
]
