"""First-party ASCII transliteration (unidecode-equivalent for the vote-key path).

The reference sanitizes vote keys with the ``unidecode`` wheel
(`/root/reference/k_llms/utils/consensus_utils.py:15`, applied at :925-933).
This module supplies the same behavior without the dependency:

* **Latin specials** — letters NFKD cannot decompose (ß, æ, ø, þ, ...), mapped
  exactly as unidecode maps them.
* **Cyrillic** — full Russian alphabet + common Ukrainian/Belarusian letters,
  using unidecode's ALA-LC-style mappings (ж→zh, х→kh, щ→shch, ю→iu, я→ia, ...).
* **Greek** — full alphabet incl. precomposed accents, unidecode's mappings
  (θ→th, ξ→x, φ→ph, χ→kh, ψ→ps, η→e, ...).
* **Everything else non-Latin** (CJK, kana, Arabic, Hebrew, Indic, ...) — a
  deterministic per-codepoint token ``u<hex>`` for alphanumeric characters.
  This *diverges* from unidecode (which romanizes, e.g. 北京 → "Bei Jing ") but
  preserves the property that matters for voting: distinct strings stay
  distinct, so "東京" and "北京" never collapse into one vote bucket.  The only
  observable difference vs the reference is that a romanized Latin spelling and
  its native-script spelling do not share a bucket (unidecode would sometimes
  merge them).

Tables are hand-derived from unidecode's documented mapping set and pinned by
the fixture vectors in ``tests/fixtures/unidecode_vectors.py``.
"""

from __future__ import annotations

import unicodedata

# Latin letters with no NFKD decomposition, mapped the way unidecode maps them.
_LATIN = {
    "ß": "ss",
    "ẞ": "SS",
    "æ": "ae",
    "Æ": "AE",
    "œ": "oe",
    "Œ": "OE",
    "ø": "o",
    "Ø": "O",
    "đ": "d",
    "Đ": "D",
    "ð": "d",
    "Ð": "D",
    "þ": "th",
    "Þ": "Th",
    "ł": "l",
    "Ł": "L",
    "ı": "i",
    "İ": "I",
}

# Cyrillic, unidecode (ALA-LC-like) romanization.  Lowercase entries; uppercase
# generated below with unidecode's title-style capitalization (Ж→"Zh").
_CYRILLIC_LOWER = {
    "а": "a",
    "б": "b",
    "в": "v",
    "г": "g",
    "д": "d",
    "е": "e",
    "ё": "io",
    "ж": "zh",
    "з": "z",
    "и": "i",
    "й": "i",
    "к": "k",
    "л": "l",
    "м": "m",
    "н": "n",
    "о": "o",
    "п": "p",
    "р": "r",
    "с": "s",
    "т": "t",
    "у": "u",
    "ф": "f",
    "х": "kh",
    "ц": "ts",
    "ч": "ch",
    "ш": "sh",
    "щ": "shch",
    "ъ": '"',
    "ы": "y",
    "ь": "'",
    "э": "e",
    "ю": "iu",
    "я": "ia",
    # Ukrainian / Belarusian
    "є": "ie",
    "і": "i",
    "ї": "i",
    "ґ": "g",
    "ў": "u",
}

_GREEK_LOWER = {
    "α": "a",
    "β": "b",
    "γ": "g",
    "δ": "d",
    "ε": "e",
    "ζ": "z",
    "η": "e",
    "θ": "th",
    "ι": "i",
    "κ": "k",
    "λ": "l",
    "μ": "m",
    "ν": "n",
    "ξ": "x",
    "ο": "o",
    "π": "p",
    "ρ": "r",
    "σ": "s",
    "ς": "s",
    "τ": "t",
    "υ": "u",
    "φ": "ph",
    "χ": "kh",
    "ψ": "ps",
    "ω": "o",
    # precomposed accents (also reachable via NFKD, but direct is exact)
    "ά": "a",
    "έ": "e",
    "ή": "e",
    "ί": "i",
    "ό": "o",
    "ύ": "u",
    "ώ": "o",
    "ϊ": "i",
    "ϋ": "u",
    "ΐ": "i",
    "ΰ": "u",
}


def _with_upper(lower: dict[str, str]) -> dict[str, str]:
    table = dict(lower)
    for ch, out in lower.items():
        up = ch.upper()
        if len(up) == 1 and up != ch and up not in table:
            # unidecode capitalizes the first romanized letter only (Щ → "Shch")
            table[up] = out[:1].upper() + out[1:] if out and out[0].isalpha() else out
    return table


_TABLE: dict[int, str] = {
    ord(k): v
    for k, v in {
        **_LATIN,
        **_with_upper(_CYRILLIC_LOWER),
        **_with_upper(_GREEK_LOWER),
    }.items()
}


def transliterate(text: str) -> str:
    """unidecode-equivalent ASCII transliteration.

    Pipeline: mapped-script table → NFKD decomposition → per-char sweep that
    keeps ASCII, drops combining marks, maps non-ASCII decimal digits to their
    ASCII digit (unidecode parity), and tokenizes any remaining alphanumeric
    codepoint as ``u<hex>`` so unmapped scripts stay distinct.
    """
    if text.isascii():
        return text
    text = text.translate(_TABLE)
    decomposed = unicodedata.normalize("NFKD", text)
    out: list[str] = []
    for ch in decomposed:
        cp = ord(ch)
        if cp < 128:
            out.append(ch)
        elif unicodedata.combining(ch):
            continue
        elif cp in _TABLE:
            # precomposed letters outside the table (e.g. ѝ, polytonic Greek)
            # NFKD-decompose to a mapped base letter + combining mark
            out.append(_TABLE[cp])
        elif (digit := unicodedata.decimal(ch, None)) is not None:
            out.append(str(digit))  # ٣ → 3, ३ → 3 (unidecode parity)
        elif ch.isalnum():
            out.append(f"u{cp:04x}")
        # other non-ASCII symbols (punctuation, emoji, ...) are dropped, as the
        # vote-key regex would strip them anyway
    return "".join(out)
