"""First-party ASCII transliteration (unidecode-equivalent for the vote-key path).

The reference sanitizes vote keys with the ``unidecode`` wheel
(`/root/reference/k_llms/utils/consensus_utils.py:15`, applied at :925-933).
This module supplies the same behavior without the dependency:

* **Latin specials** — letters NFKD cannot decompose (ß, æ, ø, þ, ...), mapped
  exactly as unidecode maps them.
* **Cyrillic** — full Russian alphabet + common Ukrainian/Belarusian letters,
  using unidecode's ALA-LC-style mappings (ж→zh, х→kh, щ→shch, ю→iu, я→ia, ...).
* **Greek** — full alphabet incl. precomposed accents, unidecode's mappings
  (θ→th, ξ→x, φ→ph, χ→kh, ψ→ps, η→e, ...).
* **Han ideographs** — unidecode-style pinyin for the high-frequency core
  (~1,700 codepoints incl. traditional variants, ``_cjk_data.HANZI``):
  北京 → "Bei Jing ", matching unidecode's capitalized-syllable-plus-space
  format exactly.
* **Kana** — full hiragana/katakana romaji tables (``_cjk_data.KANA``)
  matching unidecode's x030 block: こんにちは → "konnichiha", カード → "ka-do".
* **Hangul** — algorithmic jamo decomposition + Revised-Romanization letter
  values: 서울 → "seoul", 안녕 → "annyeong".
* **Remaining scripts / long-tail CJK** (Arabic, Hebrew, Indic, rare
  ideographs beyond the frequency table, ...) — a deterministic per-codepoint
  token ``u<hex>`` for alphanumeric characters.  This *diverges* from
  unidecode (which carries full Unihan tables) but preserves the property that
  matters for voting: distinct strings stay distinct, so two rare ideographs
  never collapse into one vote bucket.

Tables are hand-derived from unidecode's documented mapping set and pinned by
the fixture vectors in ``tests/fixtures/unidecode_vectors.py``.
"""

from __future__ import annotations

import unicodedata

from ._cjk_data import HANZI, KANA

# Latin letters with no NFKD decomposition, mapped the way unidecode maps them.
_LATIN = {
    "ß": "ss",
    "ẞ": "SS",
    "æ": "ae",
    "Æ": "AE",
    "œ": "oe",
    "Œ": "OE",
    "ø": "o",
    "Ø": "O",
    "đ": "d",
    "Đ": "D",
    "ð": "d",
    "Ð": "D",
    "þ": "th",
    "Þ": "Th",
    "ł": "l",
    "Ł": "L",
    "ı": "i",
    "İ": "I",
}

# Cyrillic, unidecode (ALA-LC-like) romanization.  Lowercase entries; uppercase
# generated below with unidecode's title-style capitalization (Ж→"Zh").
_CYRILLIC_LOWER = {
    "а": "a",
    "б": "b",
    "в": "v",
    "г": "g",
    "д": "d",
    "е": "e",
    "ё": "io",
    "ж": "zh",
    "з": "z",
    "и": "i",
    "й": "i",
    "к": "k",
    "л": "l",
    "м": "m",
    "н": "n",
    "о": "o",
    "п": "p",
    "р": "r",
    "с": "s",
    "т": "t",
    "у": "u",
    "ф": "f",
    "х": "kh",
    "ц": "ts",
    "ч": "ch",
    "ш": "sh",
    "щ": "shch",
    "ъ": '"',
    "ы": "y",
    "ь": "'",
    "э": "e",
    "ю": "iu",
    "я": "ia",
    # Ukrainian / Belarusian
    "є": "ie",
    "і": "i",
    "ї": "i",
    "ґ": "g",
    "ў": "u",
}

_GREEK_LOWER = {
    "α": "a",
    "β": "b",
    "γ": "g",
    "δ": "d",
    "ε": "e",
    "ζ": "z",
    "η": "e",
    "θ": "th",
    "ι": "i",
    "κ": "k",
    "λ": "l",
    "μ": "m",
    "ν": "n",
    "ξ": "x",
    "ο": "o",
    "π": "p",
    "ρ": "r",
    "σ": "s",
    "ς": "s",
    "τ": "t",
    "υ": "u",
    "φ": "ph",
    "χ": "kh",
    "ψ": "ps",
    "ω": "o",
    # precomposed accents (also reachable via NFKD, but direct is exact)
    "ά": "a",
    "έ": "e",
    "ή": "e",
    "ί": "i",
    "ό": "o",
    "ύ": "u",
    "ώ": "o",
    "ϊ": "i",
    "ϋ": "u",
    "ΐ": "i",
    "ΰ": "u",
}


def _with_upper(lower: dict[str, str]) -> dict[str, str]:
    table = dict(lower)
    for ch, out in lower.items():
        up = ch.upper()
        if len(up) == 1 and up != ch and up not in table:
            # unidecode capitalizes the first romanized letter only (Щ → "Shch")
            table[up] = out[:1].upper() + out[1:] if out and out[0].isalpha() else out
    return table


_TABLE: dict[int, str] = {
    ord(k): v
    for k, v in {
        **_LATIN,
        **_with_upper(_CYRILLIC_LOWER),
        **_with_upper(_GREEK_LOWER),
        **KANA,
        **HANZI,
    }.items()
}

# Hangul syllables (U+AC00..U+D7A3) decompose arithmetically into
# (initial, medial, final) jamo; romanize with Revised-Romanization letter
# values (서울 → "seoul").  Index order follows the Unicode syllable algorithm.
_HANGUL_BASE = 0xAC00
_HANGUL_LAST = 0xD7A3
_HANGUL_INITIALS = (
    "g", "kk", "n", "d", "tt", "r", "m", "b", "pp", "s", "ss", "", "j", "jj",
    "ch", "k", "t", "p", "h",
)
_HANGUL_MEDIALS = (
    "a", "ae", "ya", "yae", "eo", "e", "yeo", "ye", "o", "wa", "wae", "oe",
    "yo", "u", "wo", "we", "wi", "yu", "eu", "ui", "i",
)
_HANGUL_FINALS = (
    "", "g", "kk", "gs", "n", "nj", "nh", "d", "l", "lg", "lm", "lb", "ls",
    "lt", "lp", "lh", "m", "b", "bs", "s", "ss", "ng", "j", "ch", "k", "t",
    "p", "h",
)


def _hangul_romanize(cp: int) -> str:
    idx = cp - _HANGUL_BASE
    initial, rest = divmod(idx, 21 * 28)
    medial, final = divmod(rest, 28)
    return _HANGUL_INITIALS[initial] + _HANGUL_MEDIALS[medial] + _HANGUL_FINALS[final]


def transliterate(text: str) -> str:
    """unidecode-equivalent ASCII transliteration.

    Pipeline: mapped-script table (Latin specials, Cyrillic, Greek, kana,
    hanzi) → algorithmic Hangul romanization → NFKD decomposition → per-char
    sweep that keeps ASCII, drops combining marks, maps non-ASCII decimal
    digits to their ASCII digit (unidecode parity), and tokenizes any
    remaining alphanumeric codepoint as ``u<hex>`` so unmapped scripts stay
    distinct.  Hangul runs before NFKD because NFKD shatters syllables into
    conjoining jamo.

    NFD input (e.g. text from macOS filenames or some normalizing pipelines)
    arrives already shattered into conjoining jamo (U+1100–U+11FF), which the
    syllable-range romanizer cannot see; NFC composes those runs back into
    precomposed syllables first, so NFD '서울' romanizes to 'seoul' exactly
    like its NFC form (real unidecode romanizes the jamo block directly, so
    parity holds either way).
    """
    if text.isascii():
        return text
    if any(0x1100 <= ord(ch) <= 0x11FF for ch in text):
        text = unicodedata.normalize("NFC", text)
    text = text.translate(_TABLE)
    if any(_HANGUL_BASE <= ord(ch) <= _HANGUL_LAST for ch in text):
        text = "".join(
            _hangul_romanize(cp) if _HANGUL_BASE <= (cp := ord(ch)) <= _HANGUL_LAST else ch
            for ch in text
        )
    decomposed = unicodedata.normalize("NFKD", text)
    out: list[str] = []
    for ch in decomposed:
        cp = ord(ch)
        if cp < 128:
            out.append(ch)
        elif unicodedata.combining(ch):
            continue
        elif cp in _TABLE:
            # precomposed letters outside the table (e.g. ѝ, polytonic Greek)
            # NFKD-decompose to a mapped base letter + combining mark
            out.append(_TABLE[cp])
        elif (digit := unicodedata.decimal(ch, None)) is not None:
            out.append(str(digit))  # ٣ → 3, ३ → 3 (unidecode parity)
        elif ch.isalnum():
            out.append(f"u{cp:04x}")
        # other non-ASCII symbols (punctuation, emoji, ...) are dropped, as the
        # vote-key regex would strip them anyway
    return "".join(out)
