"""Primitive (non-enum) consensus: llm-consensus strings, hybrid numeric
clustering, and the similarity-medoid fallback.

Parity target: ``consensus_as_primitive`` at
`/root/reference/k_llms/utils/consensus_utils.py:1075-1237`:

- (a) llm-consensus string mode (:1090-1096): ask a model for a consensus string;
  confidence = mean similarity of candidates to it. The reference hardcodes an
  OpenAI ``gpt-5-mini`` call (:1026-1048); here the caller supplies
  ``llm_consensus_fn`` (the TPU backend routes it to the local model).
- (b) hybrid numeric (:1098-1219): sort, 1-D cluster with rel/abs eps,
  None-majority rules, tie-break by cross-cluster support including sign-less and
  power-of-10 closeness; representative = cluster mean.
- (c) similarity medoid (:1221-1237): full pairwise similarity matrix, pick the
  row-mean argmax; confidence = that mean.

Every threshold, rounding (5 decimals), and tie-break key is kept bit-compatible.
"""

from __future__ import annotations

import math
from typing import Any, Callable, List, Optional, Tuple

import numpy as np

from .settings import ConsensusSettings
from .similarity import SimilarityScorer

LlmConsensusFn = Callable[[List[str]], str]


def _weighted_numeric_consensus(
    xs: List[float], ws: List[float], total_weight: float, settings: ConsensusSettings
) -> Tuple[float, float]:
    """Weighted 1-D clustering: cluster mass = sum of member weights; the
    heaviest cluster wins and its weighted mean represents it."""
    pairs = sorted(zip(xs, ws))

    def _is_close(a: float, b: float) -> bool:
        denom = max(abs(a), abs(b), 1.0)
        return abs(b - a) <= max(settings.abs_eps, settings.rel_eps * denom)

    clusters: List[List[Tuple[float, float]]] = [[pairs[0]]]
    for prev, cur in zip(pairs, pairs[1:]):
        if _is_close(prev[0], cur[0]):
            clusters[-1].append(cur)
        else:
            clusters.append([cur])

    def mass(c):
        return sum(w for _, w in c)

    best = max(clusters, key=mass)
    m = mass(best)
    rep = sum(x * w for x, w in best) / m
    return rep, round(m / total_weight, 5)


def _weighted_medoid(
    values: List[Any], ws: List[float], scorer: SimilarityScorer, parent_valid_frac: float
) -> Tuple[Any, float]:
    """Medoid under weighted mean similarity (self excluded)."""
    n = len(values)
    sim = np.zeros((n, n))
    for i in range(n):
        for j in range(i + 1, n):
            sim[i, j] = sim[j, i] = scorer.generic(values[i], values[j])
    w = np.asarray(ws)
    weighted_rows = np.zeros(n)
    for i in range(n):
        others = np.arange(n) != i
        denom = w[others].sum()
        weighted_rows[i] = (sim[i, others] * w[others]).sum() / denom if denom else 0.0
    best_idx = int(np.argmax(weighted_rows))
    return values[best_idx], round(parent_valid_frac * float(weighted_rows[best_idx]), 5)


def consensus_as_primitive(
    values: list[Any],
    consensus_settings: ConsensusSettings,
    scorer: SimilarityScorer,
    parent_valid_frac: float = 1.0,
    llm_consensus_fn: Optional[LlmConsensusFn] = None,
    weights: Optional[List[float]] = None,
) -> Tuple[Any, float]:
    non_none_values = [v for v in values if v is not None]
    if len(non_none_values) == 0:
        return (None, parent_valid_frac)
    if len(non_none_values) == 1:
        return (non_none_values[0], parent_valid_frac * (len(non_none_values) / len(values)))

    first_val_type = type(non_none_values[0])

    # Strictly-additional likelihood-weighted mode: weighted clustering/medoid.
    # The weights-None path below stays bit-identical to the reference.
    if weights is not None:
        total_weight = sum(weights) or 1.0
        pairs = [
            (float(v), w)
            for v, w in zip(values, weights)
            if isinstance(v, (int, float)) and not isinstance(v, bool) and math.isfinite(float(v))
        ]
        if pairs and (
            isinstance(first_val_type(), (int, float))
            or all(isinstance(v, (int, float)) for v in non_none_values)
        ):
            return _weighted_numeric_consensus(
                [x for x, _ in pairs], [w for _, w in pairs], total_weight, consensus_settings
            )
        nn = [(v, w) for v, w in zip(values, weights) if v is not None]
        if len(nn) >= 2:
            return _weighted_medoid(
                [v for v, _ in nn], [w for _, w in nn], scorer, parent_valid_frac
            )
        # fall through to the unweighted path for degenerate cases

    # (a) llm-consensus string mode — only with embeddings similarity (:1090).
    if (
        first_val_type is str
        and consensus_settings.string_consensus_method == "llm-consensus"
        and consensus_settings.string_similarity_method == "embeddings"
    ):
        if llm_consensus_fn is None:
            raise ValueError(
                "string_consensus_method='llm-consensus' requires an llm_consensus_fn "
                "(the TPU backend provides one automatically)"
            )
        consensus_string = llm_consensus_fn(non_none_values)
        similarities = [scorer.generic(consensus_string, v) for v in non_none_values]
        confidence = float(np.nanmean(similarities))
        return consensus_string, confidence

    # (b) hybrid numeric consensus with None-aware confidence.
    # NB: `first_val_type()` constructs the type's default instance — for bool that
    # default is False, which IS an int instance, so all-bool inputs take this
    # branch and (xs being empty) return (None, parent_valid_frac), exactly like
    # the reference (:1099-1116).
    if isinstance(first_val_type(), (int, float)) or all(
        isinstance(v, (int, float)) for v in non_none_values
    ):
        total = len(values)
        none_count = sum(1 for v in values if v is None)
        frac_none = none_count / total if total else 0.0

        xs: list[float] = []
        for v in values:
            if isinstance(v, bool):
                continue
            if isinstance(v, (int, float)):
                try:
                    vf = float(v)
                    if math.isfinite(vf):
                        xs.append(vf)
                except Exception:
                    pass
        if not xs:
            return (None, parent_valid_frac)

        xs.sort()

        def _cluster_1d(xs_sorted: list[float]) -> list[list[float]]:
            if not xs_sorted:
                return []

            def _is_close(a: float, b: float) -> bool:
                denom = max(abs(a), abs(b), 1.0)
                rel_tol = consensus_settings.rel_eps * denom
                return abs(b - a) <= max(consensus_settings.abs_eps, rel_tol)

            clusters_local: list[list[float]] = []
            current = [xs_sorted[0]]
            for i in range(len(xs_sorted) - 1):
                a, b = xs_sorted[i], xs_sorted[i + 1]
                if _is_close(a, b):
                    current.append(b)
                else:
                    clusters_local.append(current)
                    current = [b]
            clusters_local.append(current)
            return clusters_local

        rel_eps = consensus_settings.rel_eps
        abs_eps = consensus_settings.abs_eps

        def _is_close_absrel(a: float, b: float) -> bool:
            denom = max(abs(a), abs(b), 1.0)
            return abs(a - b) <= max(abs_eps, rel_eps * denom)

        def _is_close_signless(a: float, b: float) -> bool:
            return _is_close_absrel(abs(a), abs(b))

        def _is_close_power10(a: float, b: float, k_range: tuple[int, int] = (-6, 6)) -> bool:
            if a == 0.0 or b == 0.0:
                return _is_close_absrel(a, b)
            for k in range(k_range[0], k_range[1] + 1):
                if _is_close_absrel(a, b * (10.0**k)):
                    return True
            return False

        clusters = _cluster_1d(xs)
        sizes_num = [len(c) for c in clusters]
        max_size_num = max((len(c) for c in clusters), default=0)
        sizes_all = sizes_num + ([none_count] if none_count > 0 else [])
        max_size_all = max(sizes_all) if sizes_all else 0

        if none_count > max_size_num:
            return (None, round(frac_none, 5))

        if max_size_all > total / 2:
            if none_count > 0 and none_count == max_size_all:
                return (None, round(none_count / total, 5))
            max_idx = int(np.argmax(sizes_num))
            rep = float(np.mean(clusters[max_idx]))
            return (rep, round(max_size_all / total, 5))

        if sizes_all.count(max_size_all) == 1:
            if none_count > 0 and none_count == max_size_all:
                return (None, round(none_count / total, 5))
            max_idx = int(np.argmax(sizes_num))
            rep = float(np.mean(clusters[max_idx]))
            return (rep, round(max_size_all / total, 5))

        # Tied largest clusters: break by cross-cluster "support" — a candidate
        # absorbs smaller clusters whose centers are close outright, sign-less
        # close, or close after a power-of-10 shift (common LLM numeric slips).
        candidate_indices = [i for i, c in enumerate(clusters) if len(c) == max_size_all]
        include_none_candidate = none_count > 0 and none_count == max_size_all
        centers = [float(np.median(c)) if c else float("nan") for c in clusters]
        spreads = [float(np.std(c)) if len(c) > 1 else 0.0 for c in clusters]
        supports: list[tuple[str, int, int]] = []
        for ci in candidate_indices:
            support = len(clusters[ci])
            c_center = centers[ci]
            for oi, other in enumerate(clusters):
                if oi == ci:
                    continue
                if len(other) < len(clusters[ci]):
                    o_center = centers[oi]
                    if (
                        _is_close_absrel(c_center, o_center)
                        or _is_close_signless(c_center, o_center)
                        or _is_close_power10(c_center, o_center)
                    ):
                        support += len(other)
            supports.append(("numeric", ci, support))
        if include_none_candidate:
            supports.append(("none", -1, none_count))
        supports.sort(
            key=lambda t: (
                -t[2],
                1 if t[0] != "numeric" else 0,
                spreads[t[1]] if t[1] >= 0 else float("inf"),
                -abs(centers[t[1]]) if t[1] >= 0 else 0.0,
            )
        )
        best_kind, best_idx, best_support = supports[0]
        if best_kind == "none":
            return (None, round(best_support / total, 5))
        rep = float(np.mean(clusters[best_idx]))
        return (rep, round(best_support / total, 5))

    # (c) similarity medoid (strings or other structures).
    n = len(values)
    if n == 0:
        return (None, 0.0)
    if n == 1:
        return (values[0], parent_valid_frac)
    sim_matrix = np.zeros((n, n), dtype=float)
    for i in range(n):
        for j in range(i + 1, n):
            sim = scorer.generic(values[i], values[j])
            sim_matrix[i, j] = sim_matrix[j, i] = sim
        sim_matrix[i, i] = np.nan
    avg_sims = np.nanmean(sim_matrix, axis=1)
    best_idx = int(np.argmax(avg_sims))
    best_value = values[best_idx]
    confidence = parent_valid_frac * float(avg_sims[best_idx])
    return (best_value, round(confidence, 5))


def compute_similarity_scores(values: list, scorer: SimilarityScorer) -> list:
    """Per-value mean similarity against all values (self included, at 1.0) —
    scores without electing a winner. Parity: ``compute_similarity_scores``,
    `/root/reference/k_llms/utils/consensus_utils.py:1243-1263`."""
    n = len(values)
    if n == 0:
        return []
    if n == 1:
        return [1.0]
    sim_matrix = np.zeros((n, n), dtype=float)
    for i in range(n):
        for j in range(i + 1, n):
            sim = scorer.generic(values[i], values[j])
            sim_matrix[i, j] = sim_matrix[j, i] = sim
        sim_matrix[i, i] = 1.0
    return [float(round(score, 5)) for score in sim_matrix.mean(axis=1)]
