"""Primitive (non-enum) consensus: llm-consensus strings, hybrid numeric
clustering, and the similarity-medoid fallback.

Behavioral spec: ``consensus_as_primitive`` at
`/root/reference/k_llms/utils/consensus_utils.py:1075-1237` — every threshold,
rounding (5 decimals), and tie-break key is kept bit-compatible and pinned by
the differential oracle. The implementation is vectorized: sorted values are
segmented into clusters with one boolean gap vector, and the tied-cluster
support tie-break evaluates all three closeness predicates (direct, sign-less,
power-of-10) as broadcast matrices over cluster centers rather than scanning
pairs. The llm-consensus string mode takes a caller-supplied
``llm_consensus_fn`` (the TPU backend routes it to the local model) instead of
the reference's hardcoded OpenAI ``gpt-5-mini`` call (:1026-1048).
"""

from __future__ import annotations

import math
from collections import Counter
from typing import Any, Callable, List, Optional, Tuple

import numpy as np

from .settings import ConsensusSettings
from .similarity import SimilarityScorer, freeze_key

LlmConsensusFn = Callable[[List[str]], str]


def _pairwise_matrix(values: List[Any], scorer: SimilarityScorer, diag: float) -> np.ndarray:
    """Symmetric generic-similarity matrix with a fixed diagonal."""
    n = len(values)
    sim = np.full((n, n), diag, dtype=float)
    for a in range(n):
        row = sim[a]
        for b in range(a + 1, n):
            row[b] = sim[b, a] = scorer.generic(values[a], values[b])
    return sim


def _close_matrix(a: np.ndarray, b: np.ndarray, rel_eps: float, abs_eps: float) -> np.ndarray:
    """Broadcast |a - b| <= max(abs_eps, rel_eps * max(|a|, |b|, 1))."""
    a = a[:, None]
    b = b[None, :]
    tol = np.maximum(abs_eps, rel_eps * np.maximum(np.maximum(np.abs(a), np.abs(b)), 1.0))
    return np.abs(a - b) <= tol


def _segment_sorted(xs: np.ndarray, rel_eps: float, abs_eps: float) -> List[np.ndarray]:
    """Chain-cluster a sorted 1-D array: a new segment starts wherever the gap
    to the previous value exceeds the mixed absolute/relative tolerance."""
    if xs.size == 0:
        return []
    left, right = xs[:-1], xs[1:]
    tol = np.maximum(abs_eps, rel_eps * np.maximum(np.maximum(np.abs(left), np.abs(right)), 1.0))
    breaks = np.flatnonzero(np.abs(right - left) > tol) + 1
    return np.split(xs, breaks)


def _finite_floats(values: List[Any]) -> np.ndarray:
    """The finite numeric payload of ``values`` (bools excluded), sorted."""
    out = []
    for v in values:
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            try:
                f = float(v)
            except Exception:
                continue
            if math.isfinite(f):
                out.append(f)
    return np.sort(np.asarray(out, dtype=float))


def _numeric_consensus(
    values: List[Any],
    settings: ConsensusSettings,
    parent_valid_frac: float,
    scorer: Optional[SimilarityScorer] = None,
) -> Tuple[Optional[float], float]:
    """Hybrid numeric consensus with None-aware confidence (spec :1098-1219).

    Pure in ``values``+tolerances except the empty-payload early return (the
    only branch that reads ``parent_valid_frac``), so results are memoized on
    the scorer's numeric cache with that branch stored as a sentinel."""
    cache = getattr(scorer, "_numeric_cache", None)
    key = None
    if cache is not None:
        frozen = freeze_key(values)
        if frozen is not None:
            key = (frozen, settings.rel_eps, settings.abs_eps)
            hit = cache.get(key)
            if hit is not None:
                if hit == "empty":
                    return None, parent_valid_frac
                return hit

    result = _numeric_consensus_uncached(values, settings, parent_valid_frac)
    if key is not None:
        xs_empty = result == (None, parent_valid_frac) and _finite_floats(values).size == 0
        cache.set(key, "empty" if xs_empty else result)
    return result


def _numeric_consensus_uncached(
    values: List[Any], settings: ConsensusSettings, parent_valid_frac: float
) -> Tuple[Optional[float], float]:
    total = len(values)
    none_count = sum(v is None for v in values)

    xs = _finite_floats(values)
    if xs.size == 0:
        return None, parent_valid_frac

    clusters = _segment_sorted(xs, settings.rel_eps, settings.abs_eps)
    sizes = np.array([c.size for c in clusters])
    biggest = int(sizes.max())
    top = max(biggest, none_count)

    if none_count > biggest:
        return None, round(none_count / total, 5)

    # A strict majority, or a unique largest block, decides outright.
    contenders = int((sizes == top).sum()) + (1 if none_count == top else 0)
    if top > total / 2 or contenders == 1:
        if none_count == top:
            return None, round(none_count / total, 5)
        winner = clusters[int(np.argmax(sizes))]
        return float(winner.mean()), round(top / total, 5)

    # Tied largest blocks: rank by cross-cluster support. A candidate absorbs
    # every strictly-smaller cluster whose center is close to its own directly,
    # after dropping signs, or after a power-of-10 shift (common LLM slips).
    centers = np.array([float(np.median(c)) for c in clusters])
    spreads = np.array([float(np.std(c)) if c.size > 1 else 0.0 for c in clusters])
    rel, ae = settings.rel_eps, settings.abs_eps

    near = _close_matrix(centers, centers, rel, ae)
    near |= _close_matrix(np.abs(centers), np.abs(centers), rel, ae)
    shifts = 10.0 ** np.arange(-6, 7)
    nz = centers != 0.0
    for s in shifts:
        shifted = _close_matrix(centers, centers * s, rel, ae)
        near |= shifted & nz[:, None] & nz[None, :]

    absorbable = sizes[None, :] < sizes[:, None]  # [cand, other]
    gained = np.where(near & absorbable, sizes[None, :], 0).sum(axis=1)

    board: List[Tuple[float, int, float, float, int]] = []
    for rank, ci in enumerate(np.flatnonzero(sizes == top)):
        ci = int(ci)
        board.append(
            (
                -(sizes[ci] + gained[ci]),  # total support, descending
                0,  # numeric candidates outrank the None candidate
                float(spreads[ci]),  # tighter cluster wins
                -abs(float(centers[ci])),  # then larger magnitude
                ci,
            )
        )
    if none_count == top:
        board.append((-float(none_count), 1, float("inf"), 0.0, -1))
    board.sort(key=lambda t: t[:4])
    support, _, _, _, idx = board[0]
    if idx < 0:
        return None, round(none_count / total, 5)
    return float(clusters[idx].mean()), round(-support / total, 5)


def _medoid_consensus(
    values: List[Any],
    scorer: SimilarityScorer,
    parent_valid_frac: float,
    canonical_spelling: bool = False,
) -> Tuple[Any, float]:
    """Similarity medoid (spec :1221-1237): the value with the highest mean
    similarity to the others wins; that mean (scaled) is the confidence.

    With ``canonical_spelling`` (default-on, see ConsensusSettings) ties at the
    max mean break toward the most frequent exact value among the tied
    candidates instead of np.argmax's first-index rule — normalized-identical
    case variants stop winning on position."""
    cache = getattr(scorer, "_medoid_cache", None)
    key = None
    if cache is not None:
        frozen = freeze_key(values)
        if frozen is not None:
            key = (frozen, bool(canonical_spelling))
            hit = cache.get(key)
            if hit is not None:
                best_idx, mean = hit
                return values[best_idx], round(parent_valid_frac * mean, 5)
    sim = _pairwise_matrix(values, scorer, diag=np.nan)
    mean_to_others = np.nanmean(sim, axis=1)
    best = int(np.argmax(mean_to_others))
    if canonical_spelling:
        tied = np.flatnonzero(mean_to_others >= mean_to_others[best] - 1e-12)
        if tied.size > 1:
            freq: Counter = Counter(repr(values[i]) for i in tied)
            top = max(freq[repr(values[i])] for i in tied)
            best = int(next(i for i in tied if freq[repr(values[i])] == top))
    if key is not None:
        cache.set(key, (best, float(mean_to_others[best])))
    return values[best], round(parent_valid_frac * float(mean_to_others[best]), 5)


def _looks_numeric(non_none: List[Any]) -> bool:
    """The spec's type gate (:1099): the first value's type default must be an
    int/float instance — for bool that default (False) IS an int, so all-bool
    input takes the numeric branch and returns (None, parent_valid_frac) —
    or every non-None value must be numeric."""
    head_default = type(non_none[0])()
    return isinstance(head_default, (int, float)) or all(
        isinstance(v, (int, float)) for v in non_none
    )


def _weighted_numeric_consensus(
    xs: List[float], ws: List[float], total_weight: float, settings: ConsensusSettings
) -> Tuple[float, float]:
    """Weighted 1-D clustering: cluster mass = sum of member weights; the
    heaviest cluster wins and its weighted mean represents it."""
    order = np.lexsort((ws, xs))
    x = np.asarray(xs, dtype=float)[order]
    w = np.asarray(ws, dtype=float)[order]
    tol = np.maximum(
        settings.abs_eps,
        settings.rel_eps * np.maximum(np.maximum(np.abs(x[:-1]), np.abs(x[1:])), 1.0),
    )
    breaks = np.flatnonzero(np.abs(x[1:] - x[:-1]) > tol) + 1
    seg_x = np.split(x, breaks)
    seg_w = np.split(w, breaks)
    masses = np.array([sw.sum() for sw in seg_w])
    best = int(np.argmax(masses))
    rep = float((seg_x[best] * seg_w[best]).sum() / masses[best])
    return rep, round(float(masses[best]) / total_weight, 5)


def _weighted_medoid(
    values: List[Any], ws: List[float], scorer: SimilarityScorer, parent_valid_frac: float
) -> Tuple[Any, float]:
    """Medoid under weighted mean similarity (self excluded)."""
    sim = _pairwise_matrix(values, scorer, diag=0.0)
    w = np.asarray(ws, dtype=float)
    denom = w.sum() - w  # per-row weight of the others
    weighted = (sim * w[None, :]).sum(axis=1)
    rows = np.divide(weighted, denom, out=np.zeros_like(weighted), where=denom != 0)
    best = int(np.argmax(rows))
    return values[best], round(parent_valid_frac * float(rows[best]), 5)


def consensus_as_primitive(
    values: list[Any],
    consensus_settings: ConsensusSettings,
    scorer: SimilarityScorer,
    parent_valid_frac: float = 1.0,
    llm_consensus_fn: Optional[LlmConsensusFn] = None,
    weights: Optional[List[float]] = None,
) -> Tuple[Any, float]:
    non_none = [v for v in values if v is not None]
    if not non_none:
        return None, parent_valid_frac
    if len(non_none) == 1:
        return non_none[0], parent_valid_frac * (1 / len(values))

    # Strictly-additional likelihood-weighted mode: weighted clustering/medoid.
    # The weights-None path below stays bit-identical to the reference.
    if weights is not None:
        total_weight = sum(weights) or 1.0
        pairs = [
            (float(v), w)
            for v, w in zip(values, weights)
            if isinstance(v, (int, float)) and not isinstance(v, bool) and math.isfinite(float(v))
        ]
        if pairs and _looks_numeric(non_none):
            return _weighted_numeric_consensus(
                [x for x, _ in pairs], [w for _, w in pairs], total_weight, consensus_settings
            )
        nn = [(v, w) for v, w in zip(values, weights) if v is not None]
        if len(nn) >= 2:
            return _weighted_medoid(
                [v for v, _ in nn], [w for _, w in nn], scorer, parent_valid_frac
            )
        # fall through to the unweighted path for degenerate cases

    # (a) llm-consensus string mode — only with embeddings similarity (:1090).
    if (
        type(non_none[0]) is str
        and consensus_settings.string_consensus_method == "llm-consensus"
        and consensus_settings.string_similarity_method == "embeddings"
    ):
        if llm_consensus_fn is None:
            raise ValueError(
                "string_consensus_method='llm-consensus' requires an llm_consensus_fn "
                "(the TPU backend provides one automatically)"
            )
        candidate = llm_consensus_fn(non_none)
        sims = [scorer.generic(candidate, v) for v in non_none]
        return candidate, float(np.nanmean(sims))

    # (b) hybrid numeric consensus with None-aware confidence.
    if _looks_numeric(non_none):
        return _numeric_consensus(values, consensus_settings, parent_valid_frac, scorer=scorer)

    # (c) similarity medoid (strings or other structures).
    return _medoid_consensus(
        values, scorer, parent_valid_frac, consensus_settings.effective_canonical_spelling
    )


def compute_similarity_scores(values: list, scorer: SimilarityScorer) -> list:
    """Per-value mean similarity against all values (self included, at 1.0) —
    scores without electing a winner. Spec: ``compute_similarity_scores``,
    `/root/reference/k_llms/utils/consensus_utils.py:1243-1263`."""
    if not values:
        return []
    if len(values) == 1:
        return [1.0]
    sim = _pairwise_matrix(values, scorer, diag=1.0)
    return [float(round(s, 5)) for s in sim.mean(axis=1)]
