"""Recursive alignment and the type-directed consensus dispatcher.

Parity targets in `/root/reference/k_llms/utils/consensus_utils.py`:
``exists_nested_lists`` :433-455, ``recursive_list_alignments`` :458-613 (walks
dicts per-key and lists per-position, returning aligned values plus key-mapping
paths back to original source positions), ``consensus_dict`` :1269-1306,
``consensus_list`` :1309-1352, and the dispatcher ``consensus_values`` :1376-1454
(str/bool with every value under 3 words => voting; dict => field recursion with
``parent_valid_frac`` scaled by the dict-typed fraction; list => element-wise
recursion; else primitive consensus).

Signature change vs the reference: similarity flows through a
:class:`SimilarityScorer` (and optional ``llm_consensus_fn``) rather than an
OpenAI-embeddings callback plus client.
"""

from __future__ import annotations

from copy import deepcopy
from typing import Any, Dict, List, Optional, Tuple, Union

from .alignment import lists_alignment
from .primitive import LlmConsensusFn, consensus_as_primitive
from .settings import SPECIAL_FIELD_PREFIXES, ConsensusSettings
from .similarity import SimilarityScorer
from .voting import voting_consensus


def exists_nested_lists(values: List[Any]) -> bool:
    """True if any value is a list, or a dict containing nested lists."""
    if not values:
        return False
    for v in values:
        if isinstance(v, list):
            return True
        elif isinstance(v, dict):
            if exists_nested_lists(list(v.values())):
                return True
    return False


def recursive_list_alignments(
    values: List[Any],
    scorer: SimilarityScorer,
    min_support_ratio: float,
    max_novelty_ratio: float = 0.25,
    current_path: str = "",
    reference_idx: Optional[int] = None,
) -> Tuple[List[Any], Dict[str, List[Optional[str]]]]:
    """Recursively align nested dicts/lists across the n samples.

    Returns the aligned values (same outer structure) and a mapping from each
    aligned path to, per sample, the original source path that landed there (or
    None where a sample contributed nothing).
    """
    if not values:
        return values, {}

    if all(v is None for v in values):
        return values, {current_path: [current_path for _ in values]}

    non_nulls = [v for v in values if v is not None]

    # Defensive copy: alignment mutates the nested structure in place.
    values = deepcopy(values)

    first_type = type(non_nulls[0])
    same_type = all(isinstance(x, first_type) for x in non_nulls)
    key_mappings: Dict[str, List[Optional[str]]] = {}

    if not same_type or first_type not in (dict, list):
        key_mappings[current_path] = [
            current_path if (v is not None or idx == reference_idx) else None
            for idx, v in enumerate(values)
        ]
        return values, key_mappings

    if first_type is dict:
        dicts_only = [(d if isinstance(d, dict) else {}) for d in values]

        all_keys = list(set(k for d in dicts_only for k in d.keys()))
        all_keys.sort()

        for key in all_keys:
            values_for_key = [d.get(key) for d in dicts_only]
            _current_path = f"{current_path}.{key}" if current_path else key
            aligned_values_for_key, sub_key_mapping = recursive_list_alignments(
                values_for_key,
                scorer,
                min_support_ratio,
                max_novelty_ratio=max_novelty_ratio,
                current_path=_current_path,
                reference_idx=reference_idx,
            )
            for _d, aligned_value in zip(dicts_only, aligned_values_for_key):
                _d[key] = aligned_value
            key_mappings.update(sub_key_mapping)

        values = [{k: _d.get(k) for k in all_keys} for _d in dicts_only]

    if first_type is list:
        lists_only = [(lst if isinstance(lst, list) else []) for lst in values]
        original_list_reference_indices: List[List[Optional[int]]] = [
            [None for _ in lst] for lst in lists_only
        ]

        if any(lst for lst in lists_only):
            aligned_lists_only, original_list_reference_indices = lists_alignment(
                lists_only,
                scorer.generic,
                min_support_ratio=min_support_ratio,
                max_novelty_ratio=max_novelty_ratio,
                reference_list_idx=reference_idx,
            )
            for l_idx, new_lst in enumerate(aligned_lists_only):
                values[l_idx] = new_lst
        else:
            for i in range(len(values)):
                values[i] = []

        if len(values) > 0:
            list_length = len(values[0])
            if list_length > 0:
                for i in range(list_length):
                    values_i = [lst[i] for lst in values]
                    values_i, sub_key_mapping = recursive_list_alignments(
                        values_i,
                        scorer,
                        min_support_ratio,
                        max_novelty_ratio=max_novelty_ratio,
                        current_path="",
                        reference_idx=reference_idx,
                    )
                    for l_idx, new_lst in enumerate(values_i):
                        values[l_idx][i] = new_lst

                    # Rewrite sub-paths through the original positions so the
                    # mapping points at where each value came from pre-alignment.
                    for key, sub_values in sub_key_mapping.items():
                        _key_path = f"{current_path}.{i}" if current_path else str(i)
                        _key_path = f"{_key_path}.{key}" if key else _key_path
                        current_values: List[Optional[str]] = []
                        for l_idx, v in enumerate(sub_values):
                            _original_position = original_list_reference_indices[l_idx][i]
                            if _original_position is None or v is None:
                                current_values.append(None)
                            else:
                                _original_value_path = (
                                    f"{current_path}.{_original_position}"
                                    if current_path
                                    else _original_position
                                )
                                _original_value_path = (
                                    f"{_original_value_path}.{v}" if v else _original_value_path
                                )
                                current_values.append(_original_value_path)
                        key_mappings[_key_path] = current_values
            elif current_path:  # don't support empty root paths
                key_mappings[current_path] = [current_path] * len(values)

    return values, key_mappings


def consensus_dict(
    dict_values: List[dict],
    consensus_settings: ConsensusSettings,
    scorer: SimilarityScorer,
    parent_valid_frac: float = 1.0,
    llm_consensus_fn: Optional[LlmConsensusFn] = None,
    weights: Optional[List[float]] = None,
) -> Tuple[dict, Dict[str, Any]]:
    """Field-by-field consensus. Returns (merged_dict, per-field confidences)."""
    seen: set = set()
    all_keys = [k for d in dict_values for k in d.keys() if k not in seen and not seen.add(k)]

    result: dict = {}
    confs: Dict[str, Any] = {}

    for key in all_keys:
        # reasoning___/source___ fields are skipped entirely (:1287-1294).
        if any(prefix in key for prefix in SPECIAL_FIELD_PREFIXES):
            continue
        sub_vals = [d.get(key, None) for d in dict_values]
        val, conf = consensus_values(
            sub_vals,
            consensus_settings,
            scorer,
            parent_valid_frac=parent_valid_frac,
            llm_consensus_fn=llm_consensus_fn,
            weights=weights,
        )
        result[key] = val
        confs[key] = conf

    return (result, confs)


def consensus_list(
    list_values: List[List[Any]],
    consensus_settings: ConsensusSettings,
    scorer: SimilarityScorer,
    parent_valid_frac: float = 1.0,
    llm_consensus_fn: Optional[LlmConsensusFn] = None,
    weights: Optional[List[float]] = None,
) -> Tuple[List[Any], List[Any]]:
    """Element-wise consensus across aligned lists (position i votes with position i)."""
    if not list_values:
        return ([], [])

    non_empty_list_values = [lst for lst in list_values if lst]
    if not non_empty_list_values:
        return ([], [])

    lengths = [len(lst) for lst in list_values]
    maximum_len = max(lengths)
    if maximum_len == 0:
        return ([], [])

    final_list = []
    confidences = []
    for i in range(maximum_len):
        items = [(model_list[i] if i < len(model_list) else None) for model_list in list_values]
        val_i, conf_i = consensus_values(
            items,
            consensus_settings,
            scorer,
            parent_valid_frac=parent_valid_frac,
            llm_consensus_fn=llm_consensus_fn,
            weights=weights,
        )
        final_list.append(val_i)
        confidences.append(conf_i)

    return final_list, confidences


def consensus_values(
    values: List[Any],
    consensus_settings: ConsensusSettings,
    scorer: SimilarityScorer,
    parent_valid_frac: float = 1.0,
    llm_consensus_fn: Optional[LlmConsensusFn] = None,
    weights: Optional[List[float]] = None,
) -> Tuple[Any, Union[float, List[Any], Dict[str, Any]]]:
    """Type-directed consensus dispatcher. Returns (value, confidence-structure)."""
    if not values:
        return (None, parent_valid_frac)

    non_none_values = [v for v in values if v is not None]
    if not non_none_values:
        return (None, 0.0)

    # Enum-like str/bool (every value under 3 words) => voting.
    if isinstance(non_none_values[0], (str, bool)):
        values_as_strings = [str(v).strip() for v in non_none_values]
        is_enum_like = all(len(v.split()) < 3 for v in values_as_strings)
        if is_enum_like:
            return voting_consensus(
                values, consensus_settings, parent_valid_frac=parent_valid_frac, weights=weights
            )

    if isinstance(non_none_values[0], dict):
        dicts_only = [v for v in values if isinstance(v, dict)]
        dict_weights = (
            [w for v, w in zip(values, weights) if isinstance(v, dict)] if weights else None
        )
        parent_valid_frac *= len(dicts_only) / len(values)
        return consensus_dict(
            dicts_only,
            consensus_settings,
            scorer,
            parent_valid_frac=parent_valid_frac,
            llm_consensus_fn=llm_consensus_fn,
            weights=dict_weights,
        )

    if isinstance(non_none_values[0], list):
        lists_only = [v for v in values if isinstance(v, list)]
        list_weights = (
            [w for v, w in zip(values, weights) if isinstance(v, list)] if weights else None
        )
        parent_valid_frac *= len(lists_only) / len(values)
        return consensus_list(
            lists_only,
            consensus_settings,
            scorer,
            parent_valid_frac=parent_valid_frac,
            llm_consensus_fn=llm_consensus_fn,
            weights=list_weights,
        )

    parent_valid_frac *= len(non_none_values) / len(values)
    nn_weights = (
        [w for v, w in zip(values, weights) if v is not None] if weights else None
    )
    return consensus_as_primitive(
        non_none_values,
        consensus_settings,
        scorer,
        parent_valid_frac=parent_valid_frac,
        llm_consensus_fn=llm_consensus_fn,
        weights=nn_weights,
    )


def intermediary_consensus_cleanup(obj):
    """Strip empty strings/dicts/lists recursively, collapsing emptied containers
    to None. Parity: ``intermediary_consensus_cleanup``,
    `/root/reference/k_llms/utils/consensus_utils.py:1355-1370`."""
    if isinstance(obj, dict):
        new_obj = {
            k: w for k, v in obj.items() if (w := intermediary_consensus_cleanup(v)) is not None
        }
        return new_obj if new_obj else None
    if isinstance(obj, (list, tuple)):
        new_obj = [w for v in obj if (w := intermediary_consensus_cleanup(v)) is not None]
        return new_obj if new_obj else None
    if isinstance(obj, str):
        stripped = obj.strip()
        return stripped if stripped else None
    return obj
