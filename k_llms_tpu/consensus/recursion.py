"""Recursive alignment and the type-directed consensus dispatcher.

Behavioral spec in `/root/reference/k_llms/utils/consensus_utils.py`:
``exists_nested_lists`` :433-455, ``recursive_list_alignments`` :458-613 (walk
dicts per-key and lists per-position, returning aligned values plus key-mapping
paths back to original source positions), ``consensus_dict`` :1269-1306,
``consensus_list`` :1309-1352, and the dispatcher ``consensus_values``
:1376-1454 (str/bool with every value under 3 words => voting; dict => field
recursion with ``parent_valid_frac`` scaled by the dict-typed fraction; list =>
element-wise recursion; else primitive consensus). Pinned by the differential
oracle; structured here as a dispatcher plus per-shape descent helpers.

Signature change vs the reference: similarity flows through a
:class:`SimilarityScorer` (and optional ``llm_consensus_fn``) rather than an
OpenAI-embeddings callback plus client.
"""

from __future__ import annotations

from copy import deepcopy
from typing import Any, Callable, Dict, List, Optional, Tuple, Union


def _copy_tree(v: Any) -> Any:
    """deepcopy fast path for parsed-JSON trees (dict/list/tuple/scalars).

    deepcopy's generic machinery (memo dict, reductor dispatch) measured ~2.6 ms
    per warm n=32 consolidation; parsed contents are almost always plain JSON,
    which this covers directly. Exotic nodes fall back to copy.deepcopy.
    """
    t = type(v)
    if t is dict:
        return {k: _copy_tree(x) for k, x in v.items()}
    if t is list:
        return [_copy_tree(x) for x in v]
    if t is tuple:
        return tuple(_copy_tree(x) for x in v)
    if v is None or t in (str, int, float, bool):
        return v
    return deepcopy(v)

from .alignment import lists_alignment
from .primitive import LlmConsensusFn, consensus_as_primitive
from .settings import SPECIAL_FIELD_PREFIXES, ConsensusSettings
from .similarity import SimilarityScorer
from .voting import voting_consensus

PathMap = Dict[str, List[Optional[str]]]


def exists_nested_lists(values: List[Any]) -> bool:
    """True if any value is a list, or a dict containing nested lists."""
    return any(
        isinstance(v, list)
        or (isinstance(v, dict) and exists_nested_lists(list(v.values())))
        for v in values
    )


def _aligned_path(prefix: str, pos: int, leaf: str) -> str:
    base = f"{prefix}.{pos}" if prefix else str(pos)
    return f"{base}.{leaf}" if leaf else base


def _source_path(prefix: str, pos: int, leaf: str):
    # Quirk kept for parity: with no prefix and no leaf the reference leaves the
    # position as a bare int, so root-level scalar lists map to ints not strings.
    base = f"{prefix}.{pos}" if prefix else pos
    return f"{base}.{leaf}" if leaf else base


def _descend_keys(
    values: List[Any],
    scorer: SimilarityScorer,
    min_support_ratio: float,
    max_novelty_ratio: float,
    prefix: str,
    reference_idx: Optional[int],
    refinement_rounds: int = 0,
) -> Tuple[List[Any], PathMap]:
    """Per-key recursion over dict samples (Nones become empty shells; every
    output dict carries the full key union, in sorted order)."""
    shells = [v if isinstance(v, dict) else {} for v in values]
    keys = sorted({k for d in shells for k in d})
    mappings: PathMap = {}
    for key in keys:
        column, sub = recursive_list_alignments(
            [d.get(key) for d in shells],
            scorer,
            min_support_ratio,
            max_novelty_ratio=max_novelty_ratio,
            current_path=f"{prefix}.{key}" if prefix else key,
            reference_idx=reference_idx,
            refinement_rounds=refinement_rounds,
        )
        for shell, aligned in zip(shells, column):
            shell[key] = aligned
        mappings.update(sub)
    return [{k: d.get(k) for k in keys} for d in shells], mappings


def _descend_positions(
    values: List[Any],
    scorer: SimilarityScorer,
    min_support_ratio: float,
    max_novelty_ratio: float,
    prefix: str,
    reference_idx: Optional[int],
    refinement_rounds: int = 0,
) -> Tuple[List[Any], PathMap]:
    """Structural alignment of list samples, then per-column recursion with the
    path map rewritten through each sample's pre-alignment positions."""
    rows = [v if isinstance(v, list) else [] for v in values]
    sources: List[List[Optional[int]]] = [[None] * len(r) for r in rows]
    if any(rows):
        rows, sources = lists_alignment(
            rows,
            scorer.generic,
            min_support_ratio=min_support_ratio,
            max_novelty_ratio=max_novelty_ratio,
            reference_list_idx=reference_idx,
            refinement_rounds=refinement_rounds,
        )
    else:
        rows = [[] for _ in rows]

    mappings: PathMap = {}
    width = len(rows[0]) if rows else 0
    for col in range(width):
        aligned_col, sub = recursive_list_alignments(
            [r[col] for r in rows],
            scorer,
            min_support_ratio,
            max_novelty_ratio=max_novelty_ratio,
            current_path="",
            reference_idx=reference_idx,
            refinement_rounds=refinement_rounds,
        )
        for r, v in zip(rows, aligned_col):
            r[col] = v
        for leaf, per_sample in sub.items():
            rewritten: List[Optional[str]] = []
            for r_idx, leaf_val in enumerate(per_sample):
                origin = sources[r_idx][col]
                if origin is None or leaf_val is None:
                    rewritten.append(None)
                else:
                    rewritten.append(_source_path(prefix, origin, leaf_val))
            mappings[_aligned_path(prefix, col, leaf)] = rewritten
    if width == 0 and prefix:  # empty root paths are not supported
        mappings[prefix] = [prefix] * len(values)
    return rows, mappings


def recursive_list_alignments(
    values: List[Any],
    scorer: SimilarityScorer,
    min_support_ratio: float,
    max_novelty_ratio: float = 0.25,
    current_path: str = "",
    reference_idx: Optional[int] = None,
    refinement_rounds: int = 0,
) -> Tuple[List[Any], PathMap]:
    """Recursively align nested dicts/lists across the n samples.

    Returns the aligned values (same outer structure) and a mapping from each
    aligned path to, per sample, the original source path that landed there (or
    None where a sample contributed nothing).
    """
    if not values:
        return values, {}
    if all(v is None for v in values):
        return values, {current_path: [current_path] * len(values)}

    values = _copy_tree(values)  # descent helpers mutate nested structure
    present = [v for v in values if v is not None]
    head = type(present[0])
    uniform = all(isinstance(v, head) for v in present)

    if uniform and head is dict:
        return _descend_keys(
            values, scorer, min_support_ratio, max_novelty_ratio, current_path,
            reference_idx, refinement_rounds,
        )
    if uniform and head is list:
        return _descend_positions(
            values, scorer, min_support_ratio, max_novelty_ratio, current_path,
            reference_idx, refinement_rounds,
        )

    # Scalars and mixed-type levels pass through untouched; a sample maps to the
    # path iff it contributed a value (the designated reference always does).
    return values, {
        current_path: [
            current_path if (v is not None or i == reference_idx) else None
            for i, v in enumerate(values)
        ]
    }


def _subset(
    values: List[Any], weights: Optional[List[float]], keep: Callable[[Any], bool]
) -> Tuple[List[Any], Optional[List[float]]]:
    kept = [v for v in values if keep(v)]
    kept_w = [w for v, w in zip(values, weights) if keep(v)] if weights else None
    return kept, kept_w


def consensus_values(
    values: List[Any],
    consensus_settings: ConsensusSettings,
    scorer: SimilarityScorer,
    parent_valid_frac: float = 1.0,
    llm_consensus_fn: Optional[LlmConsensusFn] = None,
    weights: Optional[List[float]] = None,
) -> Tuple[Any, Union[float, List[Any], Dict[str, Any]]]:
    """Type-directed consensus dispatcher. Returns (value, confidence-structure)."""
    if not values:
        return None, parent_valid_frac
    present = [v for v in values if v is not None]
    if not present:
        return None, 0.0

    # Enum-like str/bool (every value under 3 words) => voting.
    if isinstance(present[0], (str, bool)) and all(
        len(str(v).strip().split()) < 3 for v in present
    ):
        return voting_consensus(
            values,
            consensus_settings,
            parent_valid_frac=parent_valid_frac,
            weights=weights,
            scorer=scorer,
        )

    for shape, handler in ((dict, consensus_dict), (list, consensus_list)):
        if isinstance(present[0], shape):
            kept, kept_w = _subset(values, weights, lambda v: isinstance(v, shape))
            return handler(
                kept,
                consensus_settings,
                scorer,
                parent_valid_frac=parent_valid_frac * len(kept) / len(values),
                llm_consensus_fn=llm_consensus_fn,
                weights=kept_w,
            )

    kept_w = _subset(values, weights, lambda v: v is not None)[1]
    return consensus_as_primitive(
        present,
        consensus_settings,
        scorer,
        parent_valid_frac=parent_valid_frac * len(present) / len(values),
        llm_consensus_fn=llm_consensus_fn,
        weights=kept_w,
    )


def consensus_dict(
    dict_values: List[dict],
    consensus_settings: ConsensusSettings,
    scorer: SimilarityScorer,
    parent_valid_frac: float = 1.0,
    llm_consensus_fn: Optional[LlmConsensusFn] = None,
    weights: Optional[List[float]] = None,
) -> Tuple[dict, Dict[str, Any]]:
    """Field-by-field consensus. Returns (merged_dict, per-field confidences).

    Keys run in first-seen order across samples; reasoning___/source___ fields
    are skipped entirely (:1287-1294)."""
    merged: dict = {}
    confidences: Dict[str, Any] = {}
    for key in dict.fromkeys(k for d in dict_values for k in d):
        if any(marker in key for marker in SPECIAL_FIELD_PREFIXES):
            continue
        merged[key], confidences[key] = consensus_values(
            [d.get(key) for d in dict_values],
            consensus_settings,
            scorer,
            parent_valid_frac=parent_valid_frac,
            llm_consensus_fn=llm_consensus_fn,
            weights=weights,
        )
    return merged, confidences


def consensus_list(
    list_values: List[List[Any]],
    consensus_settings: ConsensusSettings,
    scorer: SimilarityScorer,
    parent_valid_frac: float = 1.0,
    llm_consensus_fn: Optional[LlmConsensusFn] = None,
    weights: Optional[List[float]] = None,
) -> Tuple[List[Any], List[Any]]:
    """Element-wise consensus across aligned lists (position i votes with
    position i; short lists contribute None)."""
    width = max((len(lst) for lst in list_values), default=0)
    out: List[Any] = []
    confidences: List[Any] = []
    for col in range(width):
        value, conf = consensus_values(
            [lst[col] if col < len(lst) else None for lst in list_values],
            consensus_settings,
            scorer,
            parent_valid_frac=parent_valid_frac,
            llm_consensus_fn=llm_consensus_fn,
            weights=weights,
        )
        out.append(value)
        confidences.append(conf)
    return out, confidences


def intermediary_consensus_cleanup(obj):
    """Strip empty strings/dicts/lists recursively, collapsing emptied
    containers to None. Spec: ``intermediary_consensus_cleanup``,
    `/root/reference/k_llms/utils/consensus_utils.py:1355-1370`."""
    if isinstance(obj, dict):
        kept = {
            k: v
            for k, v in ((k, intermediary_consensus_cleanup(v)) for k, v in obj.items())
            if v is not None
        }
        return kept or None
    if isinstance(obj, (list, tuple)):
        kept = [v for v in map(intermediary_consensus_cleanup, obj) if v is not None]
        return kept or None
    if isinstance(obj, str):
        return obj.strip() or None
    return obj
