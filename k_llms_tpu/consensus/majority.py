"""Pairwise-majority (Condorcet) column ordering for aligned lists.

Behavioral parity with `/root/reference/k_llms/utils/majority_sorting.py:8-112`:
after alignment, columns are reordered to follow the pairwise-majority order of
the elements' original positions. The acyclic part of the majority graph is
emitted by a heap-driven topological sort tie-broken on average original
position; any columns trapped in a Condorcet cycle are appended afterwards,
sorted by that same tie-break key. Cell-to-origin matching is by object
identity (``id``), so the aligner must carry original element objects through
(not copies); duplicate scalars resolve to their last original position, like
the reference's dict-comprehension lookup.

Implementation here is matrix-style (numpy over the tiny n_cols x n_cols win
table) rather than the reference's nested-list loops; the differential suite
(tests/test_reference_parity.py) pins the output equal.
"""

from __future__ import annotations

import heapq
from typing import Any, List, Optional, Tuple

import numpy as np


def _original_positions(
    aligned: List[List[Any]],
    originals: List[List[Any]],
) -> List[List[Optional[int]]]:
    """For every aligned cell, the element's index in its source row (or None)."""
    table: List[List[Optional[int]]] = []
    for row_aligned, row_original in zip(aligned, originals):
        by_identity = {id(obj): idx for idx, obj in enumerate(row_original)}
        table.append(
            [
                by_identity.get(id(cell)) if cell is not None else None
                for cell in row_aligned
            ]
        )
    # Rows beyond the originals (defensive; shapes normally match).
    while len(table) < len(aligned):
        table.append([None] * len(aligned[0]))
    return table


def _win_matrix(pos: List[List[Optional[int]]], n_cols: int) -> np.ndarray:
    """wins[i, j] = number of rows where both columns appear and i precedes j."""
    wins = np.zeros((n_cols, n_cols), dtype=np.int64)
    for row in pos:
        present = [(c, k) for c, k in enumerate(row) if k is not None]
        for ci, ki in present:
            for cj, kj in present:
                if ki < kj:
                    wins[ci, cj] += 1
    return wins


def _tie_break_key(pos: List[List[Optional[int]]], n_cols: int) -> List[float]:
    """Average original position per column; empty columns sort last."""
    sums = np.zeros(n_cols)
    counts = np.zeros(n_cols)
    for row in pos:
        for c, k in enumerate(row):
            if k is not None:
                sums[c] += k
                counts[c] += 1
    return [
        (sums[c] / counts[c]) if counts[c] else float("inf") for c in range(n_cols)
    ]


def _column_order(wins: np.ndarray, tie_key: List[float]) -> List[int]:
    """Kahn's algorithm over the strict-majority digraph, heap-ordered by the
    tie-break key; Condorcet-cycle leftovers appended by the same key."""
    n = wins.shape[0]
    beats = wins > wins.T  # i beats j strictly
    np.fill_diagonal(beats, False)
    indegree = beats.sum(axis=0).astype(int)

    heap: List[Tuple[float, int]] = [
        (tie_key[c], c) for c in range(n) if indegree[c] == 0
    ]
    heapq.heapify(heap)
    order: List[int] = []
    while heap:
        _, u = heapq.heappop(heap)
        order.append(u)
        for v in np.nonzero(beats[u])[0]:
            indegree[v] -= 1
            if indegree[v] == 0:
                heapq.heappush(heap, (tie_key[v], int(v)))

    if len(order) < n:
        emitted = set(order)
        order.extend(
            sorted((c for c in range(n) if c not in emitted), key=lambda c: tie_key[c])
        )
    return order


def sort_by_original_majority(
    aligned_list_of_lists: List[List[Any]],
    initial_list_of_lists: List[List[Any]],
) -> Tuple[List[List[Any]], List[List[Optional[int]]]]:
    """Reorder aligned columns by the pairwise-majority order of original
    indices. Returns (sorted_aligned_lists, sorted_original_indices)."""
    if not aligned_list_of_lists:
        return aligned_list_of_lists, [
            [None for _ in row] for row in aligned_list_of_lists
        ]

    n_cols = len(aligned_list_of_lists[0])
    pos = _original_positions(aligned_list_of_lists, initial_list_of_lists)
    order = _column_order(_win_matrix(pos, n_cols), _tie_break_key(pos, n_cols))

    sorted_lists = [[row[c] for c in order] for row in aligned_list_of_lists]
    sorted_positions = [[row[c] for c in order] for row in pos]
    return sorted_lists, sorted_positions
