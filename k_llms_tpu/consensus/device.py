"""On-device consensus: batched JAX kernels for the consolidation hot path.

The host consensus engine (alignment.py / voting.py / primitive.py) is pure
Python over one field pair or one vote column at a time — ~16 ms warm per n=32
request (BENCH_r05 ``host_consensus``), serialized behind the GIL. This module
ports the hot kernels to batched, jittable JAX so the per-request
similarity and voting work runs as a handful of chip dispatches:

- **Batched Levenshtein** (:func:`batched_levenshtein`): every unique string
  pair in a consolidation, scored in one padded ``[pairs, L]`` scan. The row-DP
  insertion chain (``new_row[i] = min(new_row[i-1]+1, ...)``) is solved as a
  min-plus prefix scan — ``cummin(d - idx) + idx`` — so each of the L scan
  steps is fully vectorized across pairs and row positions.
- **Batched cosine similarity** (:func:`batched_cosine`): every
  embedding-method pair of a consolidation scored in one padded ``[pairs, D]``
  device reduction instead of a per-pair host numpy loop, grouped by embedding
  dimensionality so jit compiles one shape per embed model.
- **Batched majority vote** (:func:`batched_votes`): all enum-like aligned
  columns of a consolidation tallied in one ``[fields, samples, candidates]``
  one-hot reduction, including the canonical-spelling election (spelling
  counts masked to the winning sanitized bucket).
- **Greedy assignment scan** (:func:`device_best_match_scores`): the
  ``_best_match_scores`` claim loop behind the alignment threshold as a
  ``lax.scan``, for chip deployments; the production host path keeps float64
  numpy here because f32 similarity re-derivation could flip threshold ties.

Equivalence architecture (pinned by tests/test_device_consensus.py): the
alignment/vote kernels compute only **integers** — edit distances, tallies,
winner indices. Every float those paths consume (similarities, confidences)
is derived host-side in float64 by the *same expressions* the host path uses
(``max(1e-8, 1 - dist/max_len)``, ``parent * count / total``), so device
results are bit-identical to host results, not merely within tolerance. The
one carve-out is the **batched cosine kernel** (:func:`batched_cosine`,
ISSUE 18) for the embeddings method: its dot/norms run in device f32 against
the host's float64, so its parity contract is tolerance-based (≤1e-5), with
the zero-norm floor and [lower_bound, 1] clip mirrored exactly.
Structure extraction and re-assembly stay on host: tree flatten → padded
device arrays → align/vote on device → unflatten.

:class:`DeviceSimilarityScorer` is the integration point: ``TpuBackend``
constructs it (``device_consensus`` config, default on) instead of the plain
``SimilarityScorer``. Its ``prepare()`` hook walks the parsed contents into
per-path string buckets, scores each bucket's unique pairs on device, and
publishes the results in a per-consolidation session consulted by ``string()``
before any TTL-cache lock. A persistent bucket-level cache (``pairs`` in
``cache_stats()``) lets warm repeats skip the device round-trip entirely.
Fallback to the host path is automatic and observable (CONSENSUS_EVENTS):
JAX/device unavailable, chip lock busy, unsupported payload shapes, any kernel
error, or the ``consensus.device=fallback:N`` failpoint.
"""

from __future__ import annotations

import functools
import logging
import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..analysis.lockcheck import make_lock, note_device_dispatch
from ..native import levenshtein_distance
from ..reliability import failpoints as _failpoints
from ..utils.observability import CONSENSUS_EVENTS
from .cache import TTLCache
from .settings import (
    SIMILARITY_SCORE_LOWER_BOUND,
    SPECIAL_FIELD_PREFIXES,
)
from .similarity import EMBEDDING_MIN_CHARS, SimilarityScorer, cosine_similarity
from .text import (
    hamming_similarity,
    jaccard_similarity,
    normalize_string,
    sanitize_value,
)
from .voting import vote_memo_key

logger = logging.getLogger(__name__)

#: Longest normalized string the Levenshtein kernel handles; longer pairs (and
#: anything else the encoder can't express) take the host native path.
LEV_MAX_LEN = 128
#: Pair-axis padding buckets: pow2 between these bounds, so jit compiles a
#: small, bounded set of shapes instead of one per workload size.
_PAIR_MIN_BUCKET = 64
_PAIR_CHUNK = 1024
#: Vote kernel fixed shape: up to 128 samples / 128 distinct spellings per
#: column, fields chunked by 8 — a single compiled shape for every workload.
VOTE_MAX_SAMPLES = 128
_VOTE_FIELD_CHUNK = 8
#: Refuse to device-score a bucket above this many pairs (payload-shape guard).
_MAX_BUCKET_PAIRS = 100_000


class DeviceConsensusUnavailable(RuntimeError):
    """JAX (or a device) is not importable/usable; callers fall back to host."""


_jax_state: Optional[Tuple[bool, Any]] = None
# Import-time module lock (created before any KLLMS_LOCKCHECK opt-in can take
# effect) guarding one lazy probe; leaf by design.
# kllms: ignore[lock-order] — import-time module lock, leaf by design
_jax_state_lock = threading.Lock()


def _require_jax():
    """Import jax once; raise :class:`DeviceConsensusUnavailable` if it (or a
    backend device) is missing. The verdict is memoized either way."""
    global _jax_state
    if _jax_state is None:
        with _jax_state_lock:
            if _jax_state is None:
                try:
                    import jax

                    jax.devices()
                    _jax_state = (True, jax)
                except Exception as e:  # pragma: no cover - env without jax
                    _jax_state = (False, f"{type(e).__name__}: {e}")
    ok, payload = _jax_state
    if not ok:
        raise DeviceConsensusUnavailable(payload)
    return payload


def device_available() -> bool:
    try:
        _require_jax()
        return True
    except DeviceConsensusUnavailable:
        return False


def _pow2_bucket(n: int, lo: int, hi: int) -> int:
    b = lo
    while b < n and b < hi:
        b *= 2
    return b


# ---------------------------------------------------------------------------
# Kernel 1: batched Levenshtein distance
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _lev_kernel(length: int):
    """Jitted row-DP Levenshtein over ``[P, length]`` code arrays.

    Scans the columns of ``b``; the carry is the DP row ``D[·][j]`` for all P
    pairs at once. The in-row insertion recurrence is the min-plus prefix scan
    ``cummin(d - idx) + idx``. Padding-safe: the result is read at column
    ``blen`` and row position ``alen``, which only depend on real characters.
    """
    jax = _require_jax()
    import jax.numpy as jnp
    from jax import lax

    L = length

    def kernel(a, alen, b, blen):
        P = a.shape[0]
        idx = jnp.arange(L + 1, dtype=jnp.int32)
        row0 = jnp.broadcast_to(idx, (P, L + 1))
        res0 = alen.astype(jnp.int32)

        def step(carry, inp):
            j, bj = inp
            row, res = carry
            sub = row[:, :-1] + (a != bj[:, None]).astype(jnp.int32)
            dele = row[:, 1:] + 1
            d = jnp.concatenate(
                [jnp.full((P, 1), j + 1, dtype=jnp.int32), jnp.minimum(sub, dele)],
                axis=1,
            )
            new_row = lax.cummin(d - idx[None, :], axis=1) + idx[None, :]
            got = jnp.take_along_axis(new_row, alen[:, None], axis=1)[:, 0]
            res = jnp.where(j + 1 == blen, got, res)
            return (new_row, res), None

        xs = (jnp.arange(L, dtype=jnp.int32), jnp.swapaxes(b, 0, 1))
        (_, res), _ = lax.scan(step, (row0, res0), xs)
        return res

    return jax.jit(kernel)


def _encode_ascii(strs: List[str], length: int) -> Tuple[np.ndarray, np.ndarray]:
    arr = np.zeros((len(strs), length), dtype=np.int32)
    lens = np.zeros(len(strs), dtype=np.int32)
    for i, s in enumerate(strs):
        raw = np.frombuffer(s.encode("ascii"), dtype=np.uint8)
        arr[i, : raw.size] = raw
        lens[i] = raw.size
    return arr, lens


def batched_levenshtein(pairs: List[Tuple[str, str]]) -> List[int]:
    """Exact Levenshtein distances for ASCII string pairs, batched on device.

    Strings must already be normalized (``normalize_string``) and no longer
    than :data:`LEV_MAX_LEN`. Pairs are grouped into power-of-two length
    buckets and chunked along the pair axis so jit compiles a bounded shape
    set. Returns plain Python ints, identical to the host native kernel.
    """
    results = [0] * len(pairs)
    buckets: Dict[int, List[int]] = {}
    for i, (a, b) in enumerate(pairs):
        L = _pow2_bucket(max(len(a), len(b), 1), 8, LEV_MAX_LEN)
        buckets.setdefault(L, []).append(i)
    for L, idxs in buckets.items():
        kern = _lev_kernel(L)
        for start in range(0, len(idxs), _PAIR_CHUNK):
            chunk = idxs[start : start + _PAIR_CHUNK]
            P = _pow2_bucket(len(chunk), _PAIR_MIN_BUCKET, _PAIR_CHUNK)
            a_s = [pairs[i][0] for i in chunk] + [""] * (P - len(chunk))
            b_s = [pairs[i][1] for i in chunk] + [""] * (P - len(chunk))
            a, alen = _encode_ascii(a_s, L)
            b, blen = _encode_ascii(b_s, L)
            out = np.asarray(kern(a, alen, b, blen))
            for j, i in enumerate(chunk):
                results[i] = int(out[j])
    return results


# ---------------------------------------------------------------------------
# Kernel 1b: batched cosine similarity over embedding pairs
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _cosine_kernel(dim: int):
    """Jitted raw cosine over ``[P, dim]`` embedding pairs. Returns
    ``(cos [P] f32, zero_norm [P] bool)``; the [-1,1] -> [0,1] normalization,
    the zero-norm floor, and the [lower_bound, 1] clip are derived HOST-side
    in float64 by the same expression the host path uses — so the special
    cases stay exact and only the dot/norm itself is f32-vs-f64 tolerance
    (the embeddings carve-out pinned in tests/test_device_consensus.py)."""
    jax = _require_jax()
    import jax.numpy as jnp

    def kernel(a, b):
        dot = jnp.sum(a * b, axis=-1)
        norm = jnp.sqrt(jnp.sum(a * a, axis=-1)) * jnp.sqrt(jnp.sum(b * b, axis=-1))
        return dot / jnp.where(norm == 0.0, 1.0, norm), norm == 0.0

    return jax.jit(kernel)


def batched_cosine(pairs: List[Tuple[Any, Any]]) -> List[float]:
    """Cosine similarities for embedding-vector pairs, batched on device.

    Pairs are grouped by embedding dimensionality (one compiled shape per
    embed model) and chunked along the pair axis with pow2 padding, like
    :func:`batched_levenshtein`. Mismatched shapes within a pair raise
    ``ValueError`` exactly like the host ``cosine_similarity``. Padding rows
    are all-zero (zero norm -> floored) and discarded.
    """
    results = [0.0] * len(pairs)
    by_dim: Dict[int, List[int]] = {}
    mats: Dict[int, Tuple[List[Any], List[Any]]] = {}
    for i, (e1, e2) in enumerate(pairs):
        a1 = np.asarray(e1, dtype=np.float32)
        a2 = np.asarray(e2, dtype=np.float32)
        if a1.shape != a2.shape:
            raise ValueError("Vectors must have the same shape for cosine similarity")
        by_dim.setdefault(a1.size, []).append(i)
        rows = mats.setdefault(a1.size, ([], []))
        rows[0].append(a1.reshape(-1))
        rows[1].append(a2.reshape(-1))
    for dim, idxs in by_dim.items():
        kern = _cosine_kernel(dim)
        rows_a, rows_b = mats[dim]
        for start in range(0, len(idxs), _PAIR_CHUNK):
            chunk = idxs[start : start + _PAIR_CHUNK]
            P = _pow2_bucket(len(chunk), _PAIR_MIN_BUCKET, _PAIR_CHUNK)
            a = np.zeros((P, dim), dtype=np.float32)
            b = np.zeros((P, dim), dtype=np.float32)
            for j in range(len(chunk)):
                a[j] = rows_a[start + j]
                b[j] = rows_b[start + j]
            cos, zero = kern(a, b)
            cos = np.asarray(cos, dtype=np.float64)
            zero = np.asarray(zero)
            for j, i in enumerate(chunk):
                if zero[j]:
                    results[i] = SIMILARITY_SCORE_LOWER_BOUND
                else:
                    results[i] = float(
                        np.clip(
                            0.5 * (cos[j] + 1.0), SIMILARITY_SCORE_LOWER_BOUND, 1.0
                        )
                    )
    return results


# ---------------------------------------------------------------------------
# Kernel 2: batched majority vote over aligned columns
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _vote_kernel():
    """Jitted two-level tally: sanitized-bucket counts pick the winner, then
    exact-spelling counts (masked to the winning bucket) pick the reported
    spelling. ``argmax`` first-hit ties equal first-insertion order, matching
    ``Counter.most_common(1)`` (heapq.nlargest is stable) and the host's
    first-occurrence spelling rule, because ids are assigned first-seen."""
    jax = _require_jax()
    import jax.numpy as jnp

    def kernel(codes, spell, spell_bucket):
        # codes/spell: [F, S] int32 ids (-1 = absent/padding);
        # spell_bucket: [F, U] int32 bucket of each spelling id (-1 = padding)
        U = spell_bucket.shape[1]
        cand = jnp.arange(U, dtype=jnp.int32)
        b_counts = (codes[:, None, :] == cand[None, :, None]).sum(axis=-1)
        winner = jnp.argmax(b_counts, axis=1).astype(jnp.int32)
        wcount = jnp.take_along_axis(b_counts, winner[:, None], axis=1)[:, 0]
        s_counts = (spell[:, None, :] == cand[None, :, None]).sum(axis=-1)
        eligible = spell_bucket == winner[:, None]
        masked = jnp.where(eligible, s_counts, -1)
        wspell = jnp.argmax(masked, axis=1).astype(jnp.int32)
        return winner, wcount, wspell

    return jax.jit(kernel)


class _VoteColumn:
    """Host-side encoding of one vote-eligible aligned column."""

    __slots__ = ("key", "codes", "spell", "bucket_of_spell", "spell_values", "valid", "is_bool", "canonical")

    def __init__(self, key, codes, spell, bucket_of_spell, spell_values, valid, is_bool, canonical):
        self.key = key
        self.codes = codes  # sanitized-bucket id per valid sample
        self.spell = spell  # spelling id per valid sample
        self.bucket_of_spell = bucket_of_spell  # spelling id -> bucket id
        self.spell_values = spell_values  # spelling id -> original value
        self.valid = valid  # the values that actually vote, in order
        self.is_bool = is_bool
        self.canonical = canonical  # effective_canonical_spelling at encode time


def _encode_vote_column(values: List[Any], consensus_settings) -> Optional[_VoteColumn]:
    """Encode a column for the vote kernel, or None when the host must do it.

    Mirrors ``voting_consensus`` exactly: booleans vote over ``v or False``
    with None as False; strings vote under ``sanitize_value`` with None a
    distinct candidate only when ``allow_none_as_candidate``. Columns mixing
    bools and strings (or exceeding the kernel shape) are not encoded.
    """
    key = vote_memo_key(values, consensus_settings)
    if key is None or not values or len(values) > VOTE_MAX_SAMPLES:
        return None
    non_none = [v for v in values if v is not None]
    if not non_none:
        return None
    is_bool = isinstance(non_none[0], bool)
    if is_bool:
        if not all(isinstance(v, bool) for v in non_none):
            return None
        valid: List[Any] = [v or False for v in values]
        proc: List[Any] = valid
    else:
        if not all(isinstance(v, str) for v in non_none):
            return None
        valid = list(values) if consensus_settings.allow_none_as_candidate else non_none
        proc = [sanitize_value(v) if v is not None else None for v in valid]

    bucket_ids: Dict[Any, int] = {}
    codes = []
    for p in proc:
        if p not in bucket_ids:
            bucket_ids[p] = len(bucket_ids)
        codes.append(bucket_ids[p])
    spell_ids: Dict[Any, int] = {}
    spell = []
    spell_values: List[Any] = []
    bucket_of_spell: List[int] = []
    for v, c in zip(valid, codes):
        if v not in spell_ids:
            spell_ids[v] = len(spell_ids)
            spell_values.append(v)
            bucket_of_spell.append(c)
        spell.append(spell_ids[v])
    if len(spell_values) > VOTE_MAX_SAMPLES:
        return None
    return _VoteColumn(
        key,
        codes,
        spell,
        bucket_of_spell,
        spell_values,
        valid,
        is_bool,
        bool(consensus_settings.effective_canonical_spelling),
    )


def batched_votes(columns: List[_VoteColumn]) -> List[Tuple[Any, int]]:
    """Run the vote kernel over encoded columns; returns (best_val, best_count)
    per column, field-chunked into the kernel's single compiled shape."""
    S = VOTE_MAX_SAMPLES
    kern = _vote_kernel()
    out: List[Tuple[Any, int]] = []
    for start in range(0, len(columns), _VOTE_FIELD_CHUNK):
        chunk = columns[start : start + _VOTE_FIELD_CHUNK]
        F = _VOTE_FIELD_CHUNK
        codes = np.full((F, S), -1, dtype=np.int32)
        spell = np.full((F, S), -1, dtype=np.int32)
        bucket = np.full((F, S), -1, dtype=np.int32)
        for f, col in enumerate(chunk):
            codes[f, : len(col.codes)] = col.codes
            spell[f, : len(col.spell)] = col.spell
            bucket[f, : len(col.bucket_of_spell)] = col.bucket_of_spell
        winner, wcount, wspell = (np.asarray(x) for x in kern(codes, spell, bucket))
        for f, col in enumerate(chunk):
            w, c, ws = int(winner[f]), int(wcount[f]), int(wspell[f])
            out.append((_decode_vote(col, w, c, ws), c))
    return out


def _decode_vote(col: _VoteColumn, winner: int, count: int, wspell: int):
    if col.is_bool or col.canonical:
        # Canonical-spelling election happened in the kernel (spelling counts
        # masked to the winning bucket; argmax = most common, first-seen on
        # ties). Booleans: spelling ids coincide with bucket ids, so this is
        # exactly the host branch's Counter winner.
        return col.spell_values[wspell]
    # Canonical spelling off: the host reports the winning bucket's first
    # occurrence (valid_values[processed.index(best_normalized)]).
    return next(v for v, c in zip(col.valid, col.codes) if c == winner)


# ---------------------------------------------------------------------------
# Kernel 3: greedy assignment scan (chip port of _best_match_scores)
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _greedy_match_kernel(n: int):
    """Jitted port of ``alignment._best_match_scores``: scan rows in order;
    each element claims its best still-unclaimed partner from a later list
    above the 0.5 base threshold; claims reset per source list (owner ids are
    contiguous and nondecreasing, so reset-on-owner-change is equivalent)."""
    jax = _require_jax()
    import jax.numpy as jnp
    from jax import lax

    def kernel(sim, owner):
        def step(carry, r):
            claimed, prev = carry
            src = owner[r]
            claimed = jnp.where(src != prev, jnp.zeros_like(claimed), claimed)
            pool = (owner > src) & jnp.logical_not(claimed)
            sims = jnp.where(pool, sim[r], -jnp.inf)
            p = jnp.argmax(sims)
            ok = sims[p] > 0.5
            claimed = claimed.at[p].set(claimed[p] | ok)
            return (claimed, src), jnp.where(ok, sims[p], jnp.nan)

        init = (jnp.zeros(n, dtype=bool), jnp.int32(-1))
        _, scores = lax.scan(step, init, jnp.arange(n, dtype=jnp.int32))
        return scores

    return jax.jit(kernel)


def device_best_match_scores(sim: np.ndarray, owner: np.ndarray) -> List[float]:
    """Greedy best-match score distribution, computed on device.

    Validated against the host scan in the differential suite; the production
    alignment path stays on host float64 (see module docstring) — this is the
    chip-deployment entry point for the assignment kernel.
    """
    n = sim.shape[0]
    if n == 0:
        return []
    N = _pow2_bucket(n, 8, 1 << 14)
    sim_p = np.full((N, N), -1.0, dtype=np.float32)
    sim_p[:n, :n] = sim
    owner_p = np.full(N, np.iinfo(np.int32).max, dtype=np.int32)
    owner_p[:n] = owner
    scores = np.asarray(_greedy_match_kernel(N)(sim_p, owner_p))[:n]
    return [float(s) for s in scores if not np.isnan(s)]


# ---------------------------------------------------------------------------
# Session + scorer integration
# ---------------------------------------------------------------------------


class DeviceConsensusSession:
    """Per-consolidation similarity table published by ``prepare()``: every
    unique in-bucket string pair, pre-scored (device batch, bucket cache, or
    host fallback) and consulted lock-free by ``string()``."""

    __slots__ = ("pair_sims", "hits", "misses")

    def __init__(self) -> None:
        self.pair_sims: Dict[Tuple[str, str], float] = {}
        self.hits = 0
        self.misses = 0


def _collect_string_buckets(contents: List[Any]) -> Dict[str, List[str]]:
    """Group scalar strings by structural path (list indices collapsed to
    ``*``, mirroring ``key_normalization``): alignment and consensus only ever
    compare strings within the same collapsed path."""
    buckets: Dict[str, List[str]] = {}

    def walk(node: Any, path: str) -> None:
        if isinstance(node, str):
            buckets.setdefault(path, []).append(node)
        elif isinstance(node, dict):
            for k, v in node.items():
                walk(v, f"{path}.{k}" if path else str(k))
        elif isinstance(node, (list, tuple)):
            child = f"{path}.*" if path else "*"
            for v in node:
                walk(v, child)

    for content in contents:
        walk(content, "")
    return buckets


class DeviceSimilarityScorer(SimilarityScorer):
    """SimilarityScorer whose consolidation hooks run the batched kernels.

    Construction raises :class:`DeviceConsensusUnavailable` when JAX is
    missing, so ``TpuBackend`` degrades to the plain host scorer at wiring
    time. At run time every consolidation independently falls back to host on
    the ``consensus.device`` failpoint, a busy chip lock, unsupported payload
    shapes, or any kernel error — recorded in CONSENSUS_EVENTS either way.
    """

    def __init__(self, *args: Any, **kwargs: Any) -> None:
        _require_jax()
        super().__init__(*args, **kwargs)
        # Persistent bucket-level pair cache: key = sorted unique strings of a
        # bucket, value = the scored pair map. Warm repeats skip the device.
        self._bucket_cache = TTLCache(maxsize=4096, ttl=300.0, name="pairs")
        # kllms: unguarded — threading.local: per-thread storage by design
        self._tls = threading.local()
        # Chip-busy gate: taken non-blocking, and held across the batched
        # similarity kernel dispatch on purpose — that hold IS the gate.
        self._device_lock = make_lock("consensus.device_chip", allow_dispatch=True)
        self.cache_enabled = True  # bench toggle (cache on/off axis)

    # -- consolidation hooks ----------------------------------------------
    def prepare(self, contents: List[Any]) -> None:
        self._tls.session = None
        spec = _failpoints.fire("consensus.device")
        if spec is not None and spec.action == "fallback":
            CONSENSUS_EVENTS.record("consensus.fallback_failpoint")
            self._fall_back_to_host(contents)
            return
        try:
            super().prepare(contents)  # embedding prefetch (one batched call)
            session = DeviceConsensusSession()
            self._build_pair_sims(contents, session)
            self._tls.session = session
            CONSENSUS_EVENTS.record("consensus.device_dispatch")
        except DeviceConsensusUnavailable:
            CONSENSUS_EVENTS.record("consensus.fallback_unavailable")
            self._fall_back_to_host(contents)
        except Exception:
            logger.exception("device consensus prepare failed; using host path")
            CONSENSUS_EVENTS.record("consensus.fallback_error")
            self._fall_back_to_host(contents)

    def _fall_back_to_host(self, contents: List[Any]) -> None:
        self._tls.session = None
        CONSENSUS_EVENTS.record("consensus.host_dispatch")
        try:
            super().prepare(contents)
        except Exception:  # prefetch is best-effort on the fallback path too
            logger.exception("host prepare failed during device fallback")

    def prepare_aligned(self, contents: List[Any], consensus_settings: Any) -> None:
        session = getattr(self._tls, "session", None)
        if session is None:
            return
        try:
            self._prefill_votes(list(contents), consensus_settings)
        except Exception:
            # Voting falls back lazily: any column missing from the memo is
            # simply computed by the host voting_consensus.
            logger.exception("device vote prefill failed; host voting takes over")
            CONSENSUS_EVENTS.record("consensus.fallback_error")

    # -- similarity lookup -------------------------------------------------
    def string(self, s1: str, s2: str) -> float:
        session = getattr(self._tls, "session", None)
        if session is not None:
            key = (s1, s2) if s1 <= s2 else (s2, s1)
            sim = session.pair_sims.get(key)
            if sim is not None:
                session.hits += 1
                return sim
            session.misses += 1
        return super().string(s1, s2)

    # -- device work -------------------------------------------------------
    def _build_pair_sims(self, contents: List[Any], session: DeviceConsensusSession) -> None:
        for values in _collect_string_buckets(contents).values():
            unique = list(dict.fromkeys(values))
            if len(unique) < 2:
                continue
            if len(unique) * (len(unique) - 1) // 2 > _MAX_BUCKET_PAIRS:
                continue  # unsupported payload shape: host scores lazily
            bucket_key = (self.method, tuple(sorted(unique)))
            if self.cache_enabled:
                cached = self._bucket_cache.get(bucket_key)
                if cached is not None:
                    session.pair_sims.update(cached)
                    CONSENSUS_EVENTS.record("consensus.cached_pairs", len(cached))
                    continue
            pair_map = self._score_bucket(unique)
            if self.cache_enabled:
                self._bucket_cache.set(bucket_key, pair_map)
            session.pair_sims.update(pair_map)

    def _score_bucket(self, unique: List[str]) -> Dict[Tuple[str, str], float]:
        """Score every unordered pair of a bucket, routing Levenshtein work to
        the device (float derivation bit-identical to the host) and embedding
        pairs to the batched cosine kernel (tolerance-equivalent; the one
        float-producing kernel)."""
        pair_map: Dict[Tuple[str, str], float] = {}
        lev_jobs: List[Tuple[Tuple[str, str], str, str, int]] = []
        cos_jobs: List[Tuple[Tuple[str, str], Any, Any]] = []
        host_pairs = 0
        for i, s1 in enumerate(unique):
            for s2 in unique[i + 1 :]:
                key = (s1, s2) if s1 <= s2 else (s2, s1)
                if key in pair_map:
                    continue
                if (
                    self.method == "embeddings"
                    and len(s1) > EMBEDDING_MIN_CHARS
                    and len(s2) > EMBEDDING_MIN_CHARS
                    and self.embed_fn is not None
                ):
                    try:
                        cos_jobs.append(
                            (key, self.get_embedding(s1), self.get_embedding(s2))
                        )
                        continue
                    except Exception as e:  # degrade to Levenshtein, like host
                        logger.error(
                            "Error getting embeddings for %r and %r", s1, s2,
                            exc_info=e,
                        )
                sim = self._score_host_only(s1, s2)
                if sim is not None:
                    pair_map[key] = sim
                    host_pairs += 1
                    continue
                n1, n2 = normalize_string(s1), normalize_string(s2)
                max_len = max(len(n1), len(n2))
                if max_len == 0:
                    pair_map[key] = 1.0
                elif max_len > LEV_MAX_LEN:
                    # payload shape the kernel doesn't cover: host native
                    dist = levenshtein_distance(n1, n2)
                    pair_map[key] = max(SIMILARITY_SCORE_LOWER_BOUND, 1 - (dist / max_len))
                    host_pairs += 1
                else:
                    lev_jobs.append((key, n1, n2, max_len))
        if lev_jobs:
            dists = self._lev_distances([(n1, n2) for _, n1, n2, _ in lev_jobs])
            for (key, _, _, max_len), dist in zip(lev_jobs, dists):
                pair_map[key] = max(SIMILARITY_SCORE_LOWER_BOUND, 1 - (dist / max_len))
        if cos_jobs:
            sims = self._cosine_sims([(e1, e2) for _, e1, e2 in cos_jobs])
            for (key, _, _), sim in zip(cos_jobs, sims):
                pair_map[key] = sim
        if host_pairs:
            CONSENSUS_EVENTS.record("consensus.host_pairs", host_pairs)
        return pair_map

    def _score_host_only(self, s1: str, s2: str) -> Optional[float]:
        """Methods the device doesn't kernelize, computed here so the bucket
        cache still memoizes them. Returns None for the Levenshtein route
        (embedding-eligible pairs are batched by the caller first)."""
        if self.method == "jaccard":
            return jaccard_similarity(s1, s2)
        if self.method == "hamming":
            return hamming_similarity(s1, s2)
        return None

    def _lev_distances(self, pairs: List[Tuple[str, str]]) -> List[int]:
        """Batched device Levenshtein; host native when the chip lock is busy
        (another thread mid-kernel) so consolidations never queue on it."""
        if self._device_lock.acquire(blocking=False):
            try:
                note_device_dispatch("consensus pair kernel")
                dists = batched_levenshtein(pairs)
                CONSENSUS_EVENTS.record("consensus.device_pairs", len(pairs))
                return dists
            finally:
                self._device_lock.release()
        CONSENSUS_EVENTS.record("consensus.device_busy")
        CONSENSUS_EVENTS.record("consensus.host_pairs", len(pairs))
        return [levenshtein_distance(a, b) for a, b in pairs]

    def _cosine_sims(self, pairs: List[Tuple[Any, Any]]) -> List[float]:
        """Batched device cosine; host float64 when the chip lock is busy —
        same gate discipline as :meth:`_lev_distances`."""
        if self._device_lock.acquire(blocking=False):
            try:
                note_device_dispatch("consensus cosine kernel")
                sims = batched_cosine(pairs)
                CONSENSUS_EVENTS.record("consensus.device_cosine", len(pairs))
                return sims
            finally:
                self._device_lock.release()
        CONSENSUS_EVENTS.record("consensus.device_busy")
        CONSENSUS_EVENTS.record("consensus.host_pairs", len(pairs))
        return [cosine_similarity(e1, e2) for e1, e2 in pairs]

    def _prefill_votes(self, contents: List[Any], consensus_settings: Any) -> None:
        """Batch-tally every vote-eligible aligned column into the vote memo,
        mirroring the consensus_values dispatch gates. Columns the encoder
        skips (mixed types, too wide) are computed lazily by the host."""
        columns: List[List[Any]] = []

        def walk(values: List[Any]) -> None:
            present = [v for v in values if v is not None]
            if not present:
                return
            if isinstance(present[0], (str, bool)) and all(
                len(str(v).strip().split()) < 3 for v in present
            ):
                columns.append(list(values))
                return
            if isinstance(present[0], dict):
                kept = [v for v in values if isinstance(v, dict)]
                for key in dict.fromkeys(k for d in kept for k in d):
                    if any(marker in key for marker in SPECIAL_FIELD_PREFIXES):
                        continue
                    walk([d.get(key) for d in kept])
                return
            if isinstance(present[0], list):
                kept = [v for v in values if isinstance(v, list)]
                width = max((len(lst) for lst in kept), default=0)
                for col in range(width):
                    walk([lst[col] if col < len(lst) else None for lst in kept])

        walk(contents)
        jobs: List[_VoteColumn] = []
        for column in columns:
            enc = _encode_vote_column(column, consensus_settings)
            if enc is None or self._vote_cache.get(enc.key) is not None:
                continue
            jobs.append(enc)
        if not jobs:
            return
        if not self._device_lock.acquire(blocking=False):
            CONSENSUS_EVENTS.record("consensus.device_busy")
            return
        try:
            note_device_dispatch("consensus vote kernel")
            results = batched_votes(jobs)
        finally:
            self._device_lock.release()
        for col, (best_val, best_count) in zip(jobs, results):
            if best_count > 0:
                self._vote_cache.set(col.key, (best_val, best_count))
        CONSENSUS_EVENTS.record("consensus.device_votes", len(jobs))

    # -- observability -----------------------------------------------------
    def cache_stats(self) -> dict:
        stats = super().cache_stats()
        stats["pairs"] = self._bucket_cache.stats()
        return stats
