"""Vote-based (enum-like) consensus.

Parity target: ``voting_consensus`` at
`/root/reference/k_llms/utils/consensus_utils.py:936-982`. Most-common non-null
value wins; booleans treat None as False; strings vote under their sanitized form
but the winner is reported in its original spelling (first occurrence). Confidence
is ``parent_valid_frac * count/total`` rounded to 5 decimals.
"""

from __future__ import annotations

from collections import Counter
from typing import Optional, Tuple, Union

from .settings import ConsensusSettings
from .text import sanitize_value

__all__ = ["voting_consensus", "sanitize_value", "vote_memo_key"]


def vote_memo_key(
    values: list,
    consensus_settings: ConsensusSettings,
) -> Optional[tuple]:
    """Memo key for an unweighted vote column, or None when not memo-safe.

    Only columns of str/bool/None are keyed: ``hash(True) == hash(1)``, so a
    bare value tuple would alias bool and numeric columns. The stored payload
    is ``(best_val, best_count)``; confidence is recomputed at lookup because
    ``parent_valid_frac`` varies by call site.
    """
    if not all(v is None or isinstance(v, (str, bool)) for v in values):
        return None
    return (
        tuple(values),
        bool(consensus_settings.allow_none_as_candidate),
        bool(consensus_settings.effective_canonical_spelling),
    )


def voting_consensus(
    values: list[Union[str, bool, None]],
    consensus_settings: ConsensusSettings,
    parent_valid_frac: float = 1.0,
    weights: Optional[list[float]] = None,
    scorer=None,
) -> Tuple[Optional[Union[str, bool]], float]:
    """``weights`` (strictly-additional extension): per-sample vote weights —
    the likelihood-weighted mode derives them from sequence logprobs. With
    weights None every sample votes 1.0, bit-identical to the reference.

    ``scorer`` (optional) supplies the vote memo table (and, on the device
    path, votes precomputed in one batched kernel call land in that same
    table keyed by :func:`vote_memo_key`)."""
    total_values = len(values)

    if not any(v is not None for v in values):
        return (None, parent_valid_frac)

    cache = getattr(scorer, "_vote_cache", None) if weights is None else None
    key = vote_memo_key(values, consensus_settings) if cache is not None else None
    if key is not None:
        hit = cache.get(key)
        if hit is not None:
            best_val, best_count = hit
            confidence = parent_valid_frac * (best_count / float(total_values))
            return (best_val, round(confidence, 5))

    if weights is None:
        w = [1.0] * total_values
        total_weight = float(total_values)
    else:
        w = list(weights)
        total_weight = sum(w) or 1.0

    first_non_none = next((v for v in values if v is not None), None)
    is_boolean = isinstance(first_non_none, bool)

    if is_boolean:
        # For booleans: treat None as False.
        processed_values = [v or False for v in values]
        tallies: Counter = Counter()
        for v, wi in zip(processed_values, w):
            tallies[v] += wi
        best_val, best_count = tallies.most_common(1)[0]
    else:
        if consensus_settings.allow_none_as_candidate:
            valid_values = values
            valid_weights = w
        else:
            valid_values = [v for v in values if v is not None]
            valid_weights = [wi for v, wi in zip(values, w) if v is not None]
        processed_values = [(sanitize_value(v) if v is not None else None) for v in valid_values]
        tallies = Counter()
        for v, wi in zip(processed_values, valid_weights):
            tallies[v] += wi
        best_normalized, best_count = tallies.most_common(1)[0]
        if consensus_settings.effective_canonical_spelling:
            # Default-on (reference_exact turns it off): report the bucket's
            # most common exact spelling (weighted; ties broken by first
            # occurrence).
            spelling: Counter = Counter()
            for v, pv, wi in zip(valid_values, processed_values, valid_weights):
                if pv == best_normalized:
                    spelling[v] += wi
            top = max(spelling.values())
            best_val = next(
                v
                for v, pv in zip(valid_values, processed_values)
                if pv == best_normalized and spelling[v] == top
            )
        else:
            # Report the winner in its original (first-seen) spelling.
            best_val = valid_values[processed_values.index(best_normalized)]

    if key is not None:
        cache.set(key, (best_val, best_count))

    confidence = parent_valid_frac * (best_count / total_weight)
    return (best_val, round(confidence, 5))
