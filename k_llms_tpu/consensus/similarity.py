"""Generic value similarity with a pluggable embedding provider.

Parity targets in `/root/reference/k_llms/utils/consensus_utils.py`:
``_cosine_similarity`` :626-649, ``string_similarity`` :797-824 (TTL-cached,
embeddings gated on both strings > 50 chars, Levenshtein fallback on any failure),
``numerical_similarity`` :827-841, ``dict_similarity`` :844-869 (skips
reasoning___/source___ keys), ``list_similarity`` :872-889 (positional mean),
``generic_similarity`` :892-917 (both-falsy => 1.0, single None => 1e-8 floor).

Design change vs the reference: instead of threading a raw
``sync_get_openai_embeddings_from_text`` callable through every function, similarity
state (method + embedding provider + caches) lives in one :class:`SimilarityScorer`.
The TPU backend plugs in on-device mean-pooled hidden-state embeddings; tests plug
in deterministic fakes. The reference's module-global TTL caches become per-scorer
(same 1024/300s policy, thread-safe).
"""

from __future__ import annotations

import functools
import logging
import math
import re
from typing import Any, Callable, List, Optional, Protocol

import numpy as np

from .cache import TTLCache
from .settings import (
    IGNORED_KEY_PATTERNS,
    SIMILARITY_SCORE_LOWER_BOUND,
    StringSimilarityMethod,
)
from .text import hamming_similarity, jaccard_similarity, levenshtein_similarity

logger = logging.getLogger(__name__)

EmbeddingFn = Callable[[List[str]], List[List[float]]]

NumericalPrimitive = (int, float)


@functools.lru_cache(maxsize=4096)
def _key_ignored(k: str) -> bool:
    """Memoized reasoning___/source___ key-skip check: dict similarity runs it
    per key per PAIR, which made re.match a measured hot spot at n=32."""
    return any(re.match(p, k) for p in IGNORED_KEY_PATTERNS)


class _Unfreezable(Exception):
    """Value cannot be turned into a hashable memo key (exotic type / too big)."""


def _freeze(v: Any, counter: List[int]) -> Any:
    """Hashable structural snapshot of a JSON-ish value, for memo keys.

    Bools are type-tagged because ``hash(True) == hash(1)`` would otherwise
    alias bool and int keys. Leaf budget (``counter``) bounds key-build cost so
    pathological payloads skip the memo instead of paying O(tree) per lookup.
    """
    counter[0] -= 1
    if counter[0] < 0:
        raise _Unfreezable
    if isinstance(v, bool):
        return ("b", v)
    if v is None or isinstance(v, (str, int, float)):
        return v
    if isinstance(v, dict):
        try:
            items = sorted(v.items())
        except TypeError as e:  # non-sortable keys
            raise _Unfreezable from e
        return ("d", tuple((k, _freeze(val, counter)) for k, val in items))
    if isinstance(v, (list, tuple)):
        return ("l", tuple(_freeze(x, counter) for x in v))
    raise _Unfreezable


def freeze_key(v: Any, budget: int = 256) -> Optional[Any]:
    """Public memo-key helper: hashable snapshot of ``v`` or None if unsuitable."""
    try:
        return _freeze(v, [budget])
    except _Unfreezable:
        return None


def collect_strings(value: Any, acc: Optional[List[str]] = None) -> List[str]:
    """All string leaves in a parsed-content tree (for embedding prefetch)."""
    if acc is None:
        acc = []
    if isinstance(value, str):
        acc.append(value)
    elif isinstance(value, dict):
        for v in value.values():
            collect_strings(v, acc)
    elif isinstance(value, (list, tuple)):
        for v in value:
            collect_strings(v, acc)
    return acc

# Embeddings are only worth the trip for long strings (reference :813).
EMBEDDING_MIN_CHARS = 50


def cosine_similarity(vec1: List[float], vec2: List[float]) -> float:
    """Cosine similarity normalized from [-1,1] to [0,1] and floored at 1e-8."""
    arr1 = np.asarray(vec1, dtype=np.float64)
    arr2 = np.asarray(vec2, dtype=np.float64)
    if arr1.shape != arr2.shape:
        raise ValueError("Vectors must have the same shape for cosine similarity")
    norm1 = np.linalg.norm(arr1)
    norm2 = np.linalg.norm(arr2)
    if norm1 == 0 or norm2 == 0:
        return SIMILARITY_SCORE_LOWER_BOUND
    similarity = float(np.dot(arr1, arr2) / (norm1 * norm2))
    similarity = 0.5 * (similarity + 1.0)
    return float(np.clip(similarity, SIMILARITY_SCORE_LOWER_BOUND, 1.0))


def numerical_similarity(val1: Any, val2: Any) -> float:
    """Booleans exact; numbers within 1% relative tolerance; else equality."""
    if isinstance(val1, bool) and isinstance(val2, bool):
        return 1.0 if val1 == val2 else SIMILARITY_SCORE_LOWER_BOUND
    if (
        isinstance(val1, NumericalPrimitive)
        and isinstance(val2, NumericalPrimitive)
        and math.isclose(val1, val2, rel_tol=0.01)
    ):
        return 1.0
    return 1.0 if val1 == val2 else SIMILARITY_SCORE_LOWER_BOUND


class EmbeddingProvider(Protocol):
    def __call__(self, texts: List[str]) -> List[List[float]]: ...


class SimilarityScorer:
    """Stateful similarity engine: method dispatch + embedding provider + caches."""

    def __init__(
        self,
        method: StringSimilarityMethod = "embeddings",
        embed_fn: Optional[EmbeddingFn] = None,
        cache_maxsize: int = 1024,
        cache_ttl: float = 300.0,
    ):
        self.method = method
        self.embed_fn = embed_fn
        self._sim_cache = TTLCache(maxsize=cache_maxsize, ttl=cache_ttl, name="similarity")
        self._emb_cache = TTLCache(maxsize=cache_maxsize, ttl=cache_ttl, name="embeddings")
        # Host-path memo tables (ISSUE 8 satellite): repeated identical field
        # values within (and across) consolidations hit these instead of
        # recomputing votes / medoid scans / numeric consensus / container sims.
        self._vote_cache = TTLCache(maxsize=4096, ttl=cache_ttl, name="vote")
        self._medoid_cache = TTLCache(maxsize=4096, ttl=cache_ttl, name="medoid")
        self._numeric_cache = TTLCache(maxsize=4096, ttl=cache_ttl, name="numeric")
        # Whole-alignment memo (lists_alignment): frozen input lists ->
        # source-index table; aligned output is rebuilt from the caller's own
        # objects, so hits preserve the uncached path's aliasing exactly.
        self._align_cache = TTLCache(maxsize=2048, ttl=cache_ttl, name="align")

    # -- consolidation hooks ----------------------------------------------
    def prepare(self, contents: List[Any]) -> None:
        """Pre-alignment hook, called once per consolidation with the parsed
        contents. Host path: batch-prefetch embeddings. The device scorer
        overrides this to additionally build its batched pair-similarity
        session on the chip."""
        self.prefetch_embeddings(collect_strings(contents))

    def prepare_aligned(self, contents: List[Any], consensus_settings: Any) -> None:
        """Post-alignment hook: the device scorer batch-computes majority
        votes for the aligned columns here. Host path: no-op."""

    def cache_stats(self) -> dict:
        """Per-cache counters, keyed by cache name (see TTLCache.stats())."""
        caches = (
            self._sim_cache,
            self._emb_cache,
            self._vote_cache,
            self._medoid_cache,
            self._numeric_cache,
            self._align_cache,
        )
        return {c.name: c.stats() for c in caches}

    # -- embeddings -------------------------------------------------------
    def prefetch_embeddings(self, texts: List[str]) -> None:
        """Batch-embed the long strings that similarity will need and warm the
        cache — turns the engine's lazy per-pair, batch-1 device calls into ONE
        batched forward (big win for n=32 consensus latency)."""
        if self.embed_fn is None or self.method != "embeddings":
            return
        missing, seen = [], set()
        for t in texts:
            if (
                isinstance(t, str)
                and len(t) > EMBEDDING_MIN_CHARS
                and t not in seen
                and self._emb_cache.get(t) is None
            ):
                missing.append(t)
                seen.add(t)
        if not missing:
            return
        try:
            for t, e in zip(missing, self.embed_fn(missing)):
                self._emb_cache.set(t, e)
        except Exception as e:  # lazy path will retry / degrade per pair
            logger.error("embedding prefetch failed", exc_info=e)

    def get_embedding(self, s: str) -> List[float]:
        cached = self._emb_cache.get(s)
        if cached is not None:
            return cached
        if self.embed_fn is None:
            raise RuntimeError("No embedding provider configured")
        result = self.embed_fn([s])[0]
        self._emb_cache.set(s, result)
        return result

    # -- strings ----------------------------------------------------------
    def string(self, s1: str, s2: str) -> float:
        key = (min(s1, s2), max(s1, s2), self.method)
        cached = self._sim_cache.get(key)
        if cached is not None:
            return cached
        result: Optional[float] = None
        if self.method == "jaccard":
            result = jaccard_similarity(s1, s2)
        elif self.method == "hamming":
            result = hamming_similarity(s1, s2)
        elif (
            self.method == "embeddings"
            and len(s1) > EMBEDDING_MIN_CHARS
            and len(s2) > EMBEDDING_MIN_CHARS
            and self.embed_fn is not None
        ):
            try:
                result = cosine_similarity(self.get_embedding(s1), self.get_embedding(s2))
            except Exception as e:  # degrade identically to the reference (:816-817)
                logger.error("Error getting embeddings for %r and %r", s1, s2, exc_info=e)
        if result is None:
            result = levenshtein_similarity(s1, s2)
        self._sim_cache.set(key, result)
        return result

    # -- containers -------------------------------------------------------
    def dict(self, d1: dict, d2: dict) -> float:
        # Sorted union: a raw set iterates in hash order, which varies with
        # PYTHONHASHSEED across processes — the float sum below then rounds
        # differently run to run and downstream threshold/medoid decisions
        # flip (the reference has this instability; determinism wins here).
        all_keys = sorted(set(d1.keys()) | set(d2.keys()))
        all_keys = [k for k in all_keys if not _key_ignored(k)]
        if not all_keys:
            return 1.0
        total = 0.0
        for k in all_keys:
            total += self.generic(d1.get(k), d2.get(k))
        return total / len(all_keys)

    def list(self, l1, l2) -> float:
        max_len = max(len(l1), len(l2))
        if max_len == 0:
            return 1.0
        total = 0.0
        for i in range(max_len):
            v1 = l1[i] if i < len(l1) else None
            v2 = l2[i] if i < len(l2) else None
            total += self.generic(v1, v2)
        return total / max_len

    # -- dispatcher -------------------------------------------------------
    def generic(self, v1: Any, v2: Any) -> float:
        # Both falsy ("" / 0 / [] / False / None) => perfect agreement.
        if not bool(v1) and not bool(v2):
            return 1.0
        if v1 is None or v2 is None:
            return SIMILARITY_SCORE_LOWER_BOUND
        if isinstance(v1, str) and isinstance(v2, str):
            return self.string(v1, v2)
        elif isinstance(v1, NumericalPrimitive) and isinstance(v2, NumericalPrimitive):
            return numerical_similarity(v1, v2)
        elif isinstance(v1, dict) and isinstance(v2, dict):
            key = self._container_pair_key(v1, v2)
            if key is not None:
                cached = self._sim_cache.get(key)
                if cached is not None:
                    return cached
            result = self.dict(v1, v2)
            if key is not None:
                self._sim_cache.set(key, result)
            return result
        elif isinstance(v1, (list, tuple)) and isinstance(v2, (list, tuple)):
            key = self._container_pair_key(v1, v2)
            if key is not None:
                cached = self._sim_cache.get(key)
                if cached is not None:
                    return cached
            result = self.list(v1, v2)
            if key is not None:
                self._sim_cache.set(key, result)
            return result
        else:
            return SIMILARITY_SCORE_LOWER_BOUND

    def _container_pair_key(self, v1: Any, v2: Any):
        """Symmetric memo key for a container pair, or None when not cacheable.

        generic() is symmetric in its arguments (every branch is), so the key
        orders the two frozen halves by hash for a canonical form.
        """
        f1 = freeze_key(v1)
        if f1 is None:
            return None
        f2 = freeze_key(v2)
        if f2 is None:
            return None
        if hash(f2) < hash(f1):
            f1, f2 = f2, f1
        return ("pair", self.method, f1, f2)

    # Convenience constructor used by tests and the alignment internals.
    @classmethod
    def levenshtein(cls) -> "SimilarityScorer":
        return cls(method="levenshtein")
