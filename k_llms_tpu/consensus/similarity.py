"""Generic value similarity with a pluggable embedding provider.

Parity targets in `/root/reference/k_llms/utils/consensus_utils.py`:
``_cosine_similarity`` :626-649, ``string_similarity`` :797-824 (TTL-cached,
embeddings gated on both strings > 50 chars, Levenshtein fallback on any failure),
``numerical_similarity`` :827-841, ``dict_similarity`` :844-869 (skips
reasoning___/source___ keys), ``list_similarity`` :872-889 (positional mean),
``generic_similarity`` :892-917 (both-falsy => 1.0, single None => 1e-8 floor).

Design change vs the reference: instead of threading a raw
``sync_get_openai_embeddings_from_text`` callable through every function, similarity
state (method + embedding provider + caches) lives in one :class:`SimilarityScorer`.
The TPU backend plugs in on-device mean-pooled hidden-state embeddings; tests plug
in deterministic fakes. The reference's module-global TTL caches become per-scorer
(same 1024/300s policy, thread-safe).
"""

from __future__ import annotations

import functools
import logging
import math
import re
from typing import Any, Callable, List, Optional, Protocol

import numpy as np

from .cache import TTLCache
from .settings import (
    IGNORED_KEY_PATTERNS,
    SIMILARITY_SCORE_LOWER_BOUND,
    StringSimilarityMethod,
)
from .text import hamming_similarity, jaccard_similarity, levenshtein_similarity

logger = logging.getLogger(__name__)

EmbeddingFn = Callable[[List[str]], List[List[float]]]

NumericalPrimitive = (int, float)


@functools.lru_cache(maxsize=4096)
def _key_ignored(k: str) -> bool:
    """Memoized reasoning___/source___ key-skip check: dict similarity runs it
    per key per PAIR, which made re.match a measured hot spot at n=32."""
    return any(re.match(p, k) for p in IGNORED_KEY_PATTERNS)

# Embeddings are only worth the trip for long strings (reference :813).
EMBEDDING_MIN_CHARS = 50


def cosine_similarity(vec1: List[float], vec2: List[float]) -> float:
    """Cosine similarity normalized from [-1,1] to [0,1] and floored at 1e-8."""
    arr1 = np.asarray(vec1, dtype=np.float64)
    arr2 = np.asarray(vec2, dtype=np.float64)
    if arr1.shape != arr2.shape:
        raise ValueError("Vectors must have the same shape for cosine similarity")
    norm1 = np.linalg.norm(arr1)
    norm2 = np.linalg.norm(arr2)
    if norm1 == 0 or norm2 == 0:
        return SIMILARITY_SCORE_LOWER_BOUND
    similarity = float(np.dot(arr1, arr2) / (norm1 * norm2))
    similarity = 0.5 * (similarity + 1.0)
    return float(np.clip(similarity, SIMILARITY_SCORE_LOWER_BOUND, 1.0))


def numerical_similarity(val1: Any, val2: Any) -> float:
    """Booleans exact; numbers within 1% relative tolerance; else equality."""
    if isinstance(val1, bool) and isinstance(val2, bool):
        return 1.0 if val1 == val2 else SIMILARITY_SCORE_LOWER_BOUND
    if (
        isinstance(val1, NumericalPrimitive)
        and isinstance(val2, NumericalPrimitive)
        and math.isclose(val1, val2, rel_tol=0.01)
    ):
        return 1.0
    return 1.0 if val1 == val2 else SIMILARITY_SCORE_LOWER_BOUND


class EmbeddingProvider(Protocol):
    def __call__(self, texts: List[str]) -> List[List[float]]: ...


class SimilarityScorer:
    """Stateful similarity engine: method dispatch + embedding provider + caches."""

    def __init__(
        self,
        method: StringSimilarityMethod = "embeddings",
        embed_fn: Optional[EmbeddingFn] = None,
        cache_maxsize: int = 1024,
        cache_ttl: float = 300.0,
    ):
        self.method = method
        self.embed_fn = embed_fn
        self._sim_cache = TTLCache(maxsize=cache_maxsize, ttl=cache_ttl)
        self._emb_cache = TTLCache(maxsize=cache_maxsize, ttl=cache_ttl)

    # -- embeddings -------------------------------------------------------
    def prefetch_embeddings(self, texts: List[str]) -> None:
        """Batch-embed the long strings that similarity will need and warm the
        cache — turns the engine's lazy per-pair, batch-1 device calls into ONE
        batched forward (big win for n=32 consensus latency)."""
        if self.embed_fn is None or self.method != "embeddings":
            return
        missing, seen = [], set()
        for t in texts:
            if (
                isinstance(t, str)
                and len(t) > EMBEDDING_MIN_CHARS
                and t not in seen
                and self._emb_cache.get(t) is None
            ):
                missing.append(t)
                seen.add(t)
        if not missing:
            return
        try:
            for t, e in zip(missing, self.embed_fn(missing)):
                self._emb_cache.set(t, e)
        except Exception as e:  # lazy path will retry / degrade per pair
            logger.error("embedding prefetch failed", exc_info=e)

    def get_embedding(self, s: str) -> List[float]:
        cached = self._emb_cache.get(s)
        if cached is not None:
            return cached
        if self.embed_fn is None:
            raise RuntimeError("No embedding provider configured")
        result = self.embed_fn([s])[0]
        self._emb_cache.set(s, result)
        return result

    # -- strings ----------------------------------------------------------
    def string(self, s1: str, s2: str) -> float:
        key = (min(s1, s2), max(s1, s2), self.method)
        cached = self._sim_cache.get(key)
        if cached is not None:
            return cached
        result: Optional[float] = None
        if self.method == "jaccard":
            result = jaccard_similarity(s1, s2)
        elif self.method == "hamming":
            result = hamming_similarity(s1, s2)
        elif (
            self.method == "embeddings"
            and len(s1) > EMBEDDING_MIN_CHARS
            and len(s2) > EMBEDDING_MIN_CHARS
            and self.embed_fn is not None
        ):
            try:
                result = cosine_similarity(self.get_embedding(s1), self.get_embedding(s2))
            except Exception as e:  # degrade identically to the reference (:816-817)
                logger.error("Error getting embeddings for %r and %r", s1, s2, exc_info=e)
        if result is None:
            result = levenshtein_similarity(s1, s2)
        self._sim_cache.set(key, result)
        return result

    # -- containers -------------------------------------------------------
    def dict(self, d1: dict, d2: dict) -> float:
        # Sorted union: a raw set iterates in hash order, which varies with
        # PYTHONHASHSEED across processes — the float sum below then rounds
        # differently run to run and downstream threshold/medoid decisions
        # flip (the reference has this instability; determinism wins here).
        all_keys = sorted(set(d1.keys()) | set(d2.keys()))
        all_keys = [k for k in all_keys if not _key_ignored(k)]
        if not all_keys:
            return 1.0
        total = 0.0
        for k in all_keys:
            total += self.generic(d1.get(k), d2.get(k))
        return total / len(all_keys)

    def list(self, l1, l2) -> float:
        max_len = max(len(l1), len(l2))
        if max_len == 0:
            return 1.0
        total = 0.0
        for i in range(max_len):
            v1 = l1[i] if i < len(l1) else None
            v2 = l2[i] if i < len(l2) else None
            total += self.generic(v1, v2)
        return total / max_len

    # -- dispatcher -------------------------------------------------------
    def generic(self, v1: Any, v2: Any) -> float:
        # Both falsy ("" / 0 / [] / False / None) => perfect agreement.
        if not bool(v1) and not bool(v2):
            return 1.0
        if v1 is None or v2 is None:
            return SIMILARITY_SCORE_LOWER_BOUND
        if isinstance(v1, str) and isinstance(v2, str):
            return self.string(v1, v2)
        elif isinstance(v1, NumericalPrimitive) and isinstance(v2, NumericalPrimitive):
            return numerical_similarity(v1, v2)
        elif isinstance(v1, dict) and isinstance(v2, dict):
            return self.dict(v1, v2)
        elif isinstance(v1, (list, tuple)) and isinstance(v2, (list, tuple)):
            return self.list(v1, v2)
        else:
            return SIMILARITY_SCORE_LOWER_BOUND

    # Convenience constructor used by tests and the alignment internals.
    @classmethod
    def levenshtein(cls) -> "SimilarityScorer":
        return cls(method="levenshtein")
